"""Worker for the multi-process CLI test: runs the real
``tpu_als.cli train`` entry under a 2-process gloo deployment (CPU
devices forced before first JAX use — the axon plugin ignores the
JAX_PLATFORMS env var, so this must be a config knob in a wrapper)."""

import os
import sys

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")

from tpu_als.cli import main

if __name__ == "__main__":
    main(["train", "--data", "synthetic:120x50x3000", "--rank", "4",
          "--max-iter", "3", "--reg-param", "0.01", "--seed", "0",
          "--devices", "0", "--output", os.environ["MH_OUT"]])
    print("cli worker done", flush=True)
