"""Worker for the multi-process CLI test: runs the real
``tpu_als.cli train`` entry under a 2-process gloo deployment (CPU
devices forced before first JAX use — the axon plugin ignores the
JAX_PLATFORMS env var, so this must be a config knob in a wrapper)."""

import os
import sys

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")

if __name__ == "__main__":
    # REAL two-process rendezvous for every mode.  The cli mode's
    # cmd_train calls init_distributed itself, but the fit* modes drive
    # ALS.fit directly — without this they would silently run as two
    # INDEPENDENT single-process fits (jax.process_count() == 1), and the
    # parent's comparisons would still pass because the single- and
    # multi-process math agree: exactly the failure mode that hid this
    # for a round.  The assertion pins the rendezvous.
    from tpu_als.parallel.multihost import init_distributed

    _, _pcount = init_distributed()
    assert _pcount == 2, f"expected a 2-process rendezvous, got {_pcount}"
    if os.environ.get("MH_MODE") == "fit_ckpt_sharded":
        # shard-per-process checkpointing: no cross-host factor gather on
        # the checkpoint path; a resume from the sharded directory must
        # reproduce the uninterrupted run
        import numpy as np

        from tpu_als import ALS
        from tpu_als.io.movielens import synthetic_movielens
        from tpu_als.parallel.mesh import make_mesh

        frame = synthetic_movielens(80, 30, 1500, seed=2)
        ckdir = os.environ["MH_OUT"] + ".ckpt"
        ALS(rank=3, maxIter=2, regParam=0.02, seed=0, mesh=make_mesh(),
            checkpointDir=ckdir, checkpointInterval=2,
            checkpointSharded=True).fit(frame)
        ckpt = os.path.join(ckdir, "als_checkpoint")
        import json

        with open(os.path.join(ckpt, "manifest.json")) as f:
            assert json.load(f)["sharded"] is True
        resumed = ALS(rank=3, maxIter=4, regParam=0.02, seed=0,
                      mesh=make_mesh(), resumeFrom=ckpt).fit(frame)
        straight = ALS(rank=3, maxIter=4, regParam=0.02, seed=0,
                       mesh=make_mesh()).fit(frame)
        if jax.process_index() == 0:
            np.savez(os.environ["MH_OUT"] + ".ckpt.npz",
                     Ur=resumed._U, Vr=resumed._V,
                     Us=straight._U, Vs=straight._V)
        print("sharded ckpt worker done", flush=True)
    elif os.environ.get("MH_MODE") == "fit_ckpt":
        # multi-process checkpoint -> resume == uninterrupted run
        import numpy as np

        from tpu_als import ALS
        from tpu_als.io.movielens import synthetic_movielens
        from tpu_als.parallel.mesh import make_mesh

        frame = synthetic_movielens(80, 30, 1500, seed=2)
        ckdir = os.environ["MH_OUT"] + ".ckpt"
        ALS(rank=3, maxIter=2, regParam=0.02, seed=0, mesh=make_mesh(),
            checkpointDir=ckdir, checkpointInterval=2).fit(frame)
        resumed = ALS(rank=3, maxIter=4, regParam=0.02, seed=0,
                      mesh=make_mesh(),
                      resumeFrom=os.path.join(ckdir, "als_checkpoint"),
                      ).fit(frame)
        straight = ALS(rank=3, maxIter=4, regParam=0.02, seed=0,
                       mesh=make_mesh()).fit(frame)
        if jax.process_index() == 0:
            np.savez(os.environ["MH_OUT"] + ".ckpt.npz",
                     Ur=resumed._U, Vr=resumed._V,
                     Us=straight._U, Vs=straight._V)
        print("ckpt worker done", flush=True)
    elif os.environ.get("MH_MODE") == "cli_perhost":
        # the CLI per-host surface end-to-end: each process writes its
        # own csv split, the SAME command with --per-host-data and a
        # {proc} placeholder loads them, trains, and process 0 saves
        import numpy as np

        from tpu_als.cli import main
        from tpu_als.io.movielens import synthetic_movielens

        pid = jax.process_index()
        full = synthetic_movielens(90, 35, 2000, seed=4)
        sel = np.arange(len(full)) % 2 == pid
        base = os.environ["MH_OUT"]
        np.savetxt(
            base + f".part{pid}.csv",
            np.column_stack([
                np.asarray(full["user"])[sel],
                np.asarray(full["item"])[sel],
                np.asarray(full["rating"])[sel],
                np.zeros(int(sel.sum()), np.int64),
            ]),
            delimiter=",", header="userId,movieId,rating,timestamp",
            comments="", fmt=["%d", "%d", "%.6f", "%d"])
        main(["train", "--data", "csv:" + base + ".part{proc}.csv",
              "--per-host-data", "--devices", "0", "--rank", "4",
              "--max-iter", "3", "--reg-param", "0.02", "--seed", "0",
              "--output", base + ".model"])
        print("cli perhost worker done", flush=True)
    elif os.environ.get("MH_MODE") == "cli_stream":
        # the config-3 CLI one-liner: ONE shared string-id csv, each
        # process streams only its byte range (--per-host-data with a
        # stream: spec needs no {proc} file splits), ids agreed
        # collectively, process 0 saves the model + label sidecar
        from tpu_als.cli import main

        base = os.environ["MH_OUT"]
        main(["train", "--data", "stream:" + os.environ["MH_CSV"],
              "--per-host-data", "--devices", "0", "--rank", "4",
              "--max-iter", "3", "--reg-param", "0.02", "--seed", "0",
              "--output", base + ".model"])
        print("cli stream worker done", flush=True)
    elif os.environ.get("MH_MODE") == "gate_diverge":
        # processes deliberately disagree on a fit knob: the config gate
        # (fit's FIRST collective) must turn what would be a distributed
        # hang into a ValueError on EVERY process
        from tpu_als import ALS
        from tpu_als.io.movielens import synthetic_movielens
        from tpu_als.parallel.mesh import make_mesh

        pid = jax.process_index()
        frame = synthetic_movielens(60, 30, 800, seed=3)
        try:
            ALS(rank=3, maxIter=2, seed=0, mesh=make_mesh(),
                fitCallbackInterval=1 + pid,  # the divergence
                fitCallback=lambda it, U, V: None).fit(frame)
        except ValueError as e:
            assert "disagree" in str(e), e
            print("gate worker caught divergence", flush=True)
        else:
            raise AssertionError("divergent fit config was not rejected")
    elif os.environ.get("MH_MODE") == "nan_ratings":
        # ONE host's data contains a nan rating: the collective finite
        # check must raise on EVERY host (a one-sided abort would
        # strand the peer in the next collective — code-review r4)
        import numpy as np

        from tpu_als import ALS
        from tpu_als.io.movielens import synthetic_movielens
        from tpu_als.parallel.mesh import make_mesh

        pid = jax.process_index()
        frame = synthetic_movielens(60, 30, 800, seed=3)
        if pid == 1:
            r = np.asarray(frame["rating"]).copy()
            r[5] = np.nan
            from tpu_als.utils.frame import ColumnarFrame

            frame = ColumnarFrame({"user": np.asarray(frame["user"]),
                                   "item": np.asarray(frame["item"]),
                                   "rating": r})
        try:
            ALS(rank=3, maxIter=2, seed=0, mesh=make_mesh()).fit(frame)
        except ValueError as e:
            assert "non-finite" in str(e), e
            print("nan worker caught bad ratings", flush=True)
        else:
            raise AssertionError("nan ratings were not rejected")
    elif os.environ.get("MH_MODE") == "gate_diverge_strategy":
        # divergence in gatherStrategy specifically: the knob that decides
        # WHICH collectives the compiled step issues (ring pairs ppermute
        # against all_gather = hang).  No callback/checkpoint knobs set,
        # so only the strategy/cg fields of the gate can catch it
        # (advisor r3, medium).
        from tpu_als import ALS
        from tpu_als.io.movielens import synthetic_movielens
        from tpu_als.parallel.mesh import make_mesh

        pid = jax.process_index()
        frame = synthetic_movielens(60, 30, 800, seed=3)
        try:
            ALS(rank=3, maxIter=2, seed=0, mesh=make_mesh(),
                gatherStrategy="ring" if pid else "all_gather",
                ).fit(frame)
        except ValueError as e:
            assert "gatherStrategy" in str(e), e
            print("gate worker caught divergence", flush=True)
        else:
            raise AssertionError("divergent gatherStrategy not rejected")
    elif os.environ.get("MH_MODE") == "fit_perhost":
        # per-host disjoint files: each process writes + loads ONLY its
        # half of the dataset (row parity split), fits with
        # dataMode='per_host', and the factors must match the
        # single-process fit of the full data.  fitCallback runs too —
        # multi-process callbacks gather collectively, observe on proc 0.
        import numpy as np

        from tpu_als import ALS
        from tpu_als.io.movielens import (
            load_movielens_csv,
            synthetic_movielens,
        )
        from tpu_als.parallel.mesh import make_mesh

        pid = jax.process_index()
        full = synthetic_movielens(100, 40, 2500, seed=1)
        sel = np.arange(len(full)) % 2 == pid
        part_path = os.environ["MH_OUT"] + f".part{pid}.csv"
        np.savetxt(
            part_path,
            np.column_stack([
                np.asarray(full["user"])[sel],
                np.asarray(full["item"])[sel],
                np.asarray(full["rating"])[sel],
                np.zeros(int(sel.sum()), np.int64),
            ]),
            delimiter=",", header="userId,movieId,rating,timestamp",
            comments="", fmt=["%d", "%d", "%.6f", "%d"])
        mine = load_movielens_csv(part_path)
        seen = []
        model = ALS(rank=4, maxIter=3, regParam=0.02, seed=0,
                    mesh=make_mesh(), dataMode="per_host",
                    fitCallback=lambda it, U, V: seen.append(it)).fit(mine)
        if pid == 0:
            assert seen == [1, 2, 3], seen  # gathered + invoked every iter
            np.savez(os.environ["MH_OUT"] + ".fit.npz",
                     U=model._U, V=model._V,
                     uids=model._user_map.ids, iids=model._item_map.ids)
        else:
            assert seen == [], seen  # peers gather but never observe
        print("perhost worker done", flush=True)
    elif os.environ.get("MH_MODE", "").startswith("fit"):
        # multi-process ALS.fit: every host fits the same replicated frame
        import numpy as np

        from tpu_als import ALS
        from tpu_als.io.movielens import synthetic_movielens
        from tpu_als.parallel.mesh import make_mesh

        strategy = {"fit": "all_gather", "fit_ring": "ring",
                    "fit_a2a": "all_to_all"}[os.environ["MH_MODE"]]
        if strategy == "all_to_all":
            # banded-sparse layout: each user rates a private 4-item
            # block, so the exchange plan is NON-degenerate at D=4
            # (a dense frame would silently fall back to all_gather and
            # test nothing)
            from tpu_als.utils.frame import ColumnarFrame

            uu = np.repeat(np.arange(32), 4)
            ii = (np.arange(128) * 2) % 256
            rr = (1.0 + (np.arange(128) % 4)).astype(np.float32)
            frame = ColumnarFrame({"user": uu, "item": ii, "rating": rr})
        else:
            frame = synthetic_movielens(100, 40, 2500, seed=1)
        model = ALS(rank=4, maxIter=3, regParam=0.02, seed=0,
                    mesh=make_mesh(), gatherStrategy=strategy).fit(frame)
        if jax.process_index() == 0:
            np.savez(os.environ["MH_OUT"] + ".fit.npz",
                     U=model._U, V=model._V,
                     uids=model._user_map.ids, iids=model._item_map.ids)
        print("fit worker done", flush=True)
    else:
        from tpu_als.cli import main

        main(["train", "--data", "synthetic:120x50x3000", "--rank", "4",
              "--max-iter", "3", "--reg-param", "0.01", "--seed", "0",
              "--devices", "0", "--output", os.environ["MH_OUT"]])
        print("cli worker done", flush=True)
