"""tpu_als/analysis (docs/analysis.md): the tracer-safety linter, the
jax-free obs-vocabulary engine behind scripts/check_obs_schema.py, and
the jaxpr contract registry.

The load-bearing pins, straight from the subsystem's contract:

- every rule in the catalog has a fixture (tests/fixtures_analysis/)
  that fires it and a negative that stays silent, and each bad fixture
  makes the CLI exit nonzero;
- the AST lint stage is jax-free — proven by poisoning ``jax`` the way
  test_regress.py poisons the bench gate — and finishes under 10 s on
  the full default roots;
- the merged tree lints clean against the checked-in baseline, and the
  baseline stays policy-EMPTY (findings get fixed or suppressed with a
  reason, never banked);
- the four jaxpr pins are resolvable by name from
  ``analysis.contracts`` and re-verify with unchanged verdicts;
- the defects this linter surfaced on the pre-PR tree stay fixed
  (DEFAULT_JITTER threading, the attribution twin mirror, the
  serve-bench pacing epoch, the check_obs_schema jax-free claim).
"""

import glob
import importlib.util
import inspect
import os
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "fixtures_analysis")
LINT = os.path.join(REPO, "tpu_als", "analysis", "lint.py")
SHIM = os.path.join(REPO, "scripts", "check_obs_schema.py")


def _load_standalone(name, path):
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# loaded by file path, never through the package: the same jax-free
# doorway the smoke scripts use
lint = _load_standalone("_tal_lint_under_test", LINT)


def _fixture(name):
    return os.path.join(FIXTURES, name)


def _poisoned_env(tmp_path):
    poison = tmp_path / "poison"
    poison.mkdir()
    (poison / "jax.py").write_text(
        'raise ImportError("jax must not be imported by the lint '
        'stage")\n')
    return {**os.environ, "PYTHONPATH": str(poison)}


# -- the fixture corpus: one positive + one negative per rule --------------

RULE_CASES = [
    ("bad_parse_error.py", "parse-error"),
    ("bad_tracer_branch.py", "tracer-branch"),
    ("bad_host_side_effect.py", "host-side-effect"),
    ("bad_wallclock_rng.py", "wallclock-rng"),
    ("bad_use_after_donation.py", "use-after-donation"),
    ("bad_dtype_drift.py", "dtype-drift"),
    ("bad_numpy_on_traced.py", "numpy-on-traced"),
    ("bad_unregistered_name.py", "unregistered-name"),
    ("bad_bare_jit.py", "bare-jit"),
    ("bad_magic_jitter.py", "magic-jitter"),
    ("bad_jaxfree_import.py", "jaxfree-import"),
    ("bad_timer_brackets_span.py", "timer-brackets-span"),
    ("bad_suppression.py", "bad-suppression"),
]


def test_corpus_covers_the_whole_catalog():
    """Adding a rule without a fixture (or retiring one and leaving its
    fixture behind) fails here, keeping the corpus authoritative."""
    assert {rule for _, rule in RULE_CASES} == set(lint.RULES)
    on_disk = {os.path.basename(p)
               for p in glob.glob(_fixture("bad_*.py"))}
    assert on_disk == {fname for fname, _ in RULE_CASES}


@pytest.mark.parametrize("fname,rule", RULE_CASES)
def test_bad_fixture_fires_its_rule(fname, rule):
    findings, nfiles = lint.lint_paths([_fixture(fname)])
    assert nfiles == 1
    assert any(f.rule == rule for f in findings), \
        [(f.rule, f.msg) for f in findings]


@pytest.mark.parametrize("fname,rule", RULE_CASES)
def test_bad_fixture_exits_nonzero(fname, rule):
    p = subprocess.run(
        [sys.executable, LINT, "--paths", _fixture(fname),
         "--baseline", "none"],
        capture_output=True, text=True, cwd=REPO)
    assert p.returncode == 1, p.stdout + p.stderr
    assert rule in p.stderr


@pytest.mark.parametrize("fname", sorted(
    os.path.basename(p) for p in glob.glob(
        os.path.join(FIXTURES, "ok_*.py"))))
def test_ok_fixture_is_finding_free(fname):
    findings, nfiles = lint.lint_paths([_fixture(fname)])
    assert nfiles == 1
    assert not findings, [(f.rule, f.line, f.msg) for f in findings]


def test_suppression_without_reason_does_not_suppress():
    """A reasonless 'tal: disable' is itself a finding AND the finding
    it aimed at survives — silence is never free."""
    findings, _ = lint.lint_paths([_fixture("bad_suppression.py")])
    rules = [f.rule for f in findings]
    assert "bad-suppression" in rules and "bare-jit" in rules


def test_suppression_with_reason_suppresses():
    findings, _ = lint.lint_paths([_fixture("ok_suppression.py")])
    assert not findings, [(f.rule, f.msg) for f in findings]


# -- baseline round-trip ---------------------------------------------------

def test_baseline_round_trip(tmp_path):
    """write-baseline -> exit 0 against it -> remove it -> exit 1; a
    fixed finding left in the baseline is reported stale, not fatal."""
    bad = _fixture("bad_magic_jitter.py")
    baseline = tmp_path / "baseline.txt"
    run = lambda *extra: subprocess.run(
        [sys.executable, LINT, "--paths", bad,
         "--baseline", str(baseline), *extra],
        capture_output=True, text=True, cwd=REPO)

    p = run("--write-baseline")
    assert p.returncode == 0 and baseline.exists(), p.stderr
    entries = [ln for ln in baseline.read_text().splitlines()
               if ln and not ln.startswith("#")]
    assert len(entries) == 1 and " :: magic-jitter :: " in entries[0]

    p = run()
    assert p.returncode == 0, p.stdout + p.stderr
    assert "1 baselined" in p.stdout

    # the baselined finding no longer exists -> stale note on stderr,
    # still exit 0 (notes nag, they don't block)
    p = subprocess.run(
        [sys.executable, LINT, "--paths", _fixture("ok_magic_jitter.py"),
         "--baseline", str(baseline)],
        capture_output=True, text=True, cwd=REPO)
    assert p.returncode == 0
    assert "stale baseline entry" in p.stderr

    baseline.unlink()
    p = run()
    assert p.returncode == 1
    assert "magic-jitter" in p.stderr


def test_checked_in_baseline_is_empty():
    """The policy in the file's own header: findings get fixed or
    suppressed at the site with a reason, never banked."""
    with open(os.path.join(REPO, "lint_baseline.txt")) as f:
        entries = [ln for ln in f.read().splitlines()
                   if ln.strip() and not ln.startswith("#")]
    assert entries == []


# -- the repo tree: clean, fast, and jax-free ------------------------------

def test_repo_tree_lints_clean_under_10s():
    t0 = time.monotonic()
    p = subprocess.run([sys.executable, LINT], capture_output=True,
                       text=True, cwd=REPO)
    dt = time.monotonic() - t0
    assert p.returncode == 0, p.stdout + p.stderr
    assert "tpu_als lint: OK" in p.stdout
    assert dt < 10.0, f"lint took {dt:.1f}s — the CI-gate budget is 10s"


def test_lint_stage_is_jax_free(tmp_path):
    """The AST stage must run on hosts with no accelerator stack at all
    (the test_regress.py poisoning discipline)."""
    p = subprocess.run([sys.executable, LINT], capture_output=True,
                       text=True, cwd=REPO, env=_poisoned_env(tmp_path))
    assert p.returncode == 0, p.stdout + p.stderr
    assert "tpu_als lint: OK" in p.stdout


def test_obs_schema_shim_is_jax_free(tmp_path):
    """The pre-PR script claimed 'deliberately jax-free' while importing
    tpu_als.obs.schema through the package root (which imports jax) —
    the linter's jaxfree-import rule caught it; the shim now loads the
    engine standalone by file path.  This is fix #1 of the findings the
    linter surfaced on its own tree."""
    p = subprocess.run([sys.executable, SHIM], capture_output=True,
                       text=True, cwd=REPO, env=_poisoned_env(tmp_path))
    assert p.returncode == 0, p.stdout + p.stderr
    assert "check_obs_schema: OK" in p.stdout


# -- the contract registry -------------------------------------------------

def test_contracts_resolvable_by_name():
    from tpu_als.analysis import contracts

    assert set(contracts.names()) == {
        "ne_audit", "fused_solve_audit", "guardrails_disarmed",
        "tracing_disarmed", "plan_cache_off", "comm_audit",
        "ring_substrate", "live_delta_index", "serve_comm_audit",
        "elastic_disarmed", "floor_audit"}
    for name in contracts.names():
        c = contracts.get(name)
        assert c.name == name
        assert "tests/" in c.provenance      # every pin names its owner
    with pytest.raises(KeyError, match="no contract named"):
        contracts.get("bogus")


def test_contracts_verify_with_unchanged_verdicts():
    """The acceptance pin: all four byte-level invariants still hold
    when re-verified through the registry (conftest supplies the
    8-device CPU backend comm_audit needs)."""
    from tpu_als.analysis import contracts

    results = contracts.verify_all()
    assert [r.name for r in results] == list(contracts.names())
    assert all(r.ok for r in results), \
        [(r.name, r.detail) for r in results if not r.ok]


def test_verify_all_only_subset():
    from tpu_als.analysis import contracts

    results = contracts.verify_all(only=["guardrails_disarmed"])
    assert [r.name for r in results] == ["guardrails_disarmed"]
    assert results[0].ok, results[0].detail


def test_cli_lint_contract_by_name(capsys):
    from tpu_als.cli import main as cli_main

    rc = cli_main(["lint", "--paths", _fixture("ok_magic_jitter.py"),
                   "--baseline", "none", "--contract", "ne_audit"])
    out = capsys.readouterr()
    assert rc == 0, out.err
    assert "contract ne_audit: OK" in out.out
    assert "tpu_als lint --contracts: OK (1 verified)" in out.out

    rc = cli_main(["lint", "--paths", _fixture("ok_magic_jitter.py"),
                   "--baseline", "none", "--contract", "bogus"])
    out = capsys.readouterr()
    assert rc == 1
    assert "contract bogus: UNKNOWN" in out.err


def test_cli_module_doorway_propagates_exit_code():
    """`python -m tpu_als.cli` must exit with lint's return code — the
    smoke scripts' `|| fail=1` gating is dead weight otherwise.  (cli's
    __main__ shim used to drop main()'s return value on the floor.)"""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    bad = subprocess.run(
        [sys.executable, "-m", "tpu_als.cli", "lint", "--paths",
         _fixture("bad_bare_jit.py"), "--baseline", "none"],
        capture_output=True, text=True, cwd=REPO, env=env)
    assert bad.returncode == 1, bad.stdout + bad.stderr
    ok = subprocess.run(
        [sys.executable, "-m", "tpu_als.cli", "lint", "--paths",
         _fixture("ok_bare_jit.py"), "--baseline", "none"],
        capture_output=True, text=True, cwd=REPO, env=env)
    assert ok.returncode == 0, ok.stdout + ok.stderr


# -- the defects the linter surfaced stay fixed ----------------------------

def test_default_jitter_is_the_one_knob():
    """Fix #2 (magic-jitter, 14 sites): every solver entry point and
    AlsConfig share ops.solve.DEFAULT_JITTER — a retuned default
    propagates everywhere instead of stranding 1e-6 copies."""
    from tpu_als.core import foldin
    from tpu_als.core.als import AlsConfig
    from tpu_als.ops import solve
    from tpu_als.ops.pallas_gather_ne import gather_solve

    D = solve.DEFAULT_JITTER
    for fn in (solve.solve_spd, solve.solve_spd_checked, solve.solve_cg,
               solve.solve_cg_matfree, solve.solve_nnls,
               foldin.fold_in, foldin._fold_in_jit, gather_solve):
        assert inspect.signature(fn).parameters["jitter"].default == D, \
            getattr(fn, "__name__", fn)
    assert AlsConfig().jitter == D


def test_attribution_twin_mirrors_default_jitter():
    """Fix #2b: the attribution twin picks the prebuilt solver exactly
    when cfg.jitter matches the production default — by comparing
    against DEFAULT_JITTER, not a second 1e-6 literal that could drift
    from the real default silently."""
    from tpu_als.ops import solve
    from tpu_als.perf import attribution

    src = inspect.getsource(attribution)
    assert "DEFAULT_JITTER" in src
    assert "1e-6" not in src
    # and the linter agrees: no magic-jitter findings anywhere in the
    # subsystems the sweep fixed
    for rel in ("tpu_als/ops", "tpu_als/core", "tpu_als/perf"):
        findings, _ = lint.lint_paths([os.path.join(REPO, rel)])
        assert not [f for f in findings if f.rule == "magic-jitter"], rel
    assert solve.DEFAULT_JITTER == 1e-6


def test_serve_bench_pacing_epoch_inside_span():
    """Fix #3 (timer-brackets-span): the serve-bench drive loop's pacing
    epoch starts inside the obs.span, so the span-enter JSONL write can
    never make request 0 late against its own schedule."""
    findings, _ = lint.lint_paths([os.path.join(REPO, "tpu_als",
                                                "cli.py")])
    assert not [f for f in findings if f.rule == "timer-brackets-span"]


def test_stage_timer_suppression_is_reasoned():
    """The flip side of fix #3: obs/trace.py's stage() clock DOES
    bracket the span — deliberately, because the attribution coverage
    bound attributes all armed-path time to stages — and carries an
    in-source suppression with a reason rather than a baseline entry."""
    trace_py = os.path.join(REPO, "tpu_als", "obs", "trace.py")
    with open(trace_py) as f:
        src = f.read()
    assert "tal: disable=timer-brackets-span --" in src
    findings, _ = lint.lint_paths([trace_py])
    assert not findings, [(f.rule, f.line) for f in findings]
