"""Pallas fused GEMM+top-k kernel vs the XLA reference path.

Runs in interpreter mode on the CPU test mesh (the kernel compiles for real
on TPU; interpret mode executes the identical kernel logic — the Pallas
analog of the reference stack testing distributed code under ``local[N]``).
"""

import numpy as np
import jax.numpy as jnp
import pytest

from tpu_als.ops.pallas_topk import topk_scores_pallas
from tpu_als.ops.topk import chunked_topk_scores, topk_scores


def _rand_problem(rng, n, ni, r, dead_frac=0.1):
    U = jnp.asarray(rng.normal(size=(n, r)).astype(np.float32))
    V = jnp.asarray(rng.normal(size=(ni, r)).astype(np.float32))
    valid = jnp.asarray(rng.random(ni) > dead_frac)
    return U, V, valid


@pytest.mark.parametrize("n,ni,r,k", [
    (37, 200, 16, 5),      # everything unaligned, single item tile
    (300, 1234, 48, 10),   # multiple user and item tiles
    (64, 700, 130, 3),     # rank above one lane tile
])
def test_matches_xla_path(rng, n, ni, r, k):
    U, V, valid = _rand_problem(rng, n, ni, r)
    s0, i0 = chunked_topk_scores(U, V, valid, k, item_chunk=256)
    s1, i1 = topk_scores_pallas(U, V, valid, k, interpret=True)
    np.testing.assert_allclose(np.asarray(s0), np.asarray(s1), atol=1e-4)
    assert (np.asarray(i0) == np.asarray(i1)).all()


def test_sorted_descending_and_valid_only(rng):
    U, V, valid = _rand_problem(rng, 50, 500, 8, dead_frac=0.5)
    s, i = topk_scores_pallas(U, V, valid, 7, interpret=True)
    s = np.asarray(s)
    i = np.asarray(i)
    assert (np.diff(s, axis=1) <= 1e-6).all()
    assert np.asarray(valid)[i].all()  # never recommends invalid items


def test_k_larger_than_lane_tile_rejected(rng):
    U, V, valid = _rand_problem(rng, 8, 300, 8)
    with pytest.raises(ValueError):
        topk_scores_pallas(U, V, valid, 129, interpret=True)


def test_dispatcher_xla_on_cpu(rng):
    # on the CPU test backend 'auto' must route to the XLA scan
    U, V, valid = _rand_problem(rng, 20, 100, 8)
    s0, i0 = topk_scores(U, V, valid, 5, backend="auto")
    s1, i1 = chunked_topk_scores(U, V, valid, 5)
    np.testing.assert_allclose(np.asarray(s0), np.asarray(s1))
    assert (np.asarray(i0) == np.asarray(i1)).all()
