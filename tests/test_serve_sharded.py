"""Sharded serving (parallel/serve.py): both strategies must agree with
the single-device chunked top-k — the serving analog of the trainer's
sharded == single-device equivalence tests (SURVEY.md §4.4)."""

import numpy as np
import pytest

import jax.numpy as jnp

from tpu_als.ops.topk import chunked_topk_scores
from tpu_als.parallel.mesh import make_mesh
from tpu_als.parallel.serve import topk_sharded


def _factors(rng, nu, ni, r):
    # continuous values: score ties (which strategies may break
    # differently) have probability ~0
    U = rng.normal(size=(nu, r)).astype(np.float32)
    V = rng.normal(size=(ni, r)).astype(np.float32)
    return U, V


def _reference(U, V, valid, k):
    return chunked_topk_scores(jnp.asarray(U), jnp.asarray(V),
                               jnp.asarray(valid), k=k)


@pytest.mark.parametrize("strategy", ["all_gather", "ring"])
def test_matches_single_device(rng, strategy):
    U, V = _factors(rng, 41, 97, 8)  # neither divisible by 8 devices
    valid = np.ones(97, bool)
    k = 10
    ref_s, ref_i = _reference(U, V, valid, k)
    s, ix = topk_sharded(U, V, k, make_mesh(8), strategy=strategy)
    np.testing.assert_allclose(s, np.asarray(ref_s), rtol=1e-5, atol=1e-6)
    np.testing.assert_array_equal(ix, np.asarray(ref_i))


@pytest.mark.parametrize("strategy", ["all_gather", "ring"])
def test_k_larger_than_shard(rng, strategy):
    # 8 devices x 2 items/shard: k=5 exceeds every shard's local k
    U, V = _factors(rng, 12, 16, 4)
    k = 5
    ref_s, ref_i = _reference(U, V, np.ones(16, bool), k)
    s, ix = topk_sharded(U, V, k, make_mesh(8), strategy=strategy)
    np.testing.assert_allclose(s, np.asarray(ref_s), rtol=1e-5, atol=1e-6)
    np.testing.assert_array_equal(ix, np.asarray(ref_i))


@pytest.mark.parametrize("strategy", ["all_gather", "ring"])
def test_item_valid_mask(rng, strategy):
    U, V = _factors(rng, 9, 40, 4)
    valid = rng.random(40) < 0.5
    k = 3
    ref_s, ref_i = _reference(U, V, valid, k)
    s, ix = topk_sharded(U, V, k, make_mesh(8), strategy=strategy,
                         item_valid=valid)
    np.testing.assert_allclose(s, np.asarray(ref_s), rtol=1e-5, atol=1e-6)
    # every selected index must be a valid item
    assert valid[ix].all()


def test_k_capped_at_catalog(rng):
    U, V = _factors(rng, 5, 6, 4)
    s, ix = topk_sharded(U, V, 50, make_mesh(8))
    assert s.shape == (5, 6) and ix.shape == (5, 6)
    # every real item appears exactly once per row
    assert np.array_equal(np.sort(ix, axis=1),
                          np.broadcast_to(np.arange(6), (5, 6)))


def test_strategies_agree_on_duplicate_scores(rng):
    """Adversarial ties: the module docstring promises SCORES are always
    identical across strategies even though tied INDICES may differ
    (merge order is shard-rotation order).  Pin both halves: scores
    bitwise equal, and every returned index earns its claimed score."""
    base = rng.normal(size=(7, 6)).astype(np.float32)
    V = base[rng.integers(0, 7, 96)]     # whole catalog = repeated rows
    U = rng.normal(size=(11, 6)).astype(np.float32)
    k = 12                               # deep enough to span tie groups
    s_ag, i_ag = topk_sharded(U, V, k, make_mesh(8),
                              strategy="all_gather")
    s_ring, i_ring = topk_sharded(U, V, k, make_mesh(8), strategy="ring")
    np.testing.assert_array_equal(s_ag, s_ring)
    full = U.astype(np.float64) @ V.astype(np.float64).T
    for ix, s in ((i_ag, s_ag), (i_ring, s_ring)):
        np.testing.assert_allclose(
            np.take_along_axis(full, ix.astype(np.int64), axis=1), s,
            rtol=1e-5, atol=1e-5)


def test_unknown_strategy_rejected(rng):
    U, V = _factors(rng, 4, 4, 2)
    with pytest.raises(ValueError, match="unknown serving strategy"):
        topk_sharded(U, V, 2, make_mesh(8), strategy="broadcast")


def test_recommend_arrays_mesh_equivalence(rng):
    """ALSModel.recommend_arrays(mesh=...) == the single-device path."""
    from tests.conftest import make_ratings
    from tpu_als import ALS, ColumnarFrame

    u, i, r, _, _ = make_ratings(rng, 30, 20, 4, density=0.5)
    frame = ColumnarFrame({"user": u, "item": i, "rating": r})
    model = ALS(rank=4, maxIter=3, regParam=0.005, seed=0).fit(frame)
    ids0, rec0, sc0 = model.recommend_arrays(5)
    for strategy in ("all_gather", "ring"):
        ids1, rec1, sc1 = model.recommend_arrays(
            5, mesh=make_mesh(8), gatherStrategy=strategy)
        np.testing.assert_array_equal(ids0, ids1)
        np.testing.assert_allclose(sc0, sc1, rtol=1e-5, atol=1e-6)
        np.testing.assert_array_equal(rec0, rec1)
