"""Gather-fused NE build (ops.pallas_gather_ne) vs the unfused
``normal_eq_*(V[cols], …)`` reference, interpret mode on CPU (the same
kernel compiles on TPU — interpret-mode parity is the portability
contract for every Pallas kernel in this repo).

The numerics contract under test (kernel module docstring): for widths
that fit ONE width chunk (w8 <= 256 — every real bucket, entity_widths
only emits %8==0 widths) the fused build is **bitwise equal** at f32 to
the reference — same weights, same dot_general contraction, same
ridge/YtY tail expressions.  Widths spanning several chunks accumulate
chunk-by-chunk: ``count`` stays bitwise, ``A``/``b`` match to
accumulation-order rounding only, asserted tight."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tpu_als.core.als import AlsConfig, resolve_solve_path, train
from tpu_als.core.ratings import build_csr_buckets
from tpu_als.ops.pallas_gather_ne import (
    _tiles,
    gather_normal_eq_explicit,
    gather_normal_eq_implicit,
)
from tpu_als.ops.solve import compute_yty, normal_eq_explicit, \
    normal_eq_implicit


def _problem(rng, n, w, r, N=200, implicit=False, dtype=jnp.float32):
    V = (rng.normal(size=(N, r)).astype(np.float32) / np.sqrt(r))
    cols = rng.integers(0, N, (n, w)).astype(np.int32)
    vals = rng.normal(size=(n, w)).astype(np.float32)
    if implicit:
        vals = np.abs(vals) * 3
        vals[rng.random((n, w)) < 0.2] *= -1  # zero/negative confidence
    mask = (rng.random((n, w)) < 0.8).astype(np.float32)
    vals = vals * mask
    return (jnp.asarray(V).astype(dtype), jnp.asarray(cols),
            jnp.asarray(vals).astype(dtype), jnp.asarray(mask).astype(dtype))


def _single_chunk(w):
    """True when the kernel covers the (8-padded) width in one chunk —
    the bitwise regime."""
    w8 = -(-w // 8) * 8
    _, wc, w_pad = _tiles(128, w8)
    return w_pad // wc == 1


def _assert_matches(got, ref, w):
    A, b, c = (np.asarray(x) for x in got)
    Ar, br, cr = (np.asarray(x) for x in ref)
    np.testing.assert_array_equal(c, cr)
    if _single_chunk(w):
        np.testing.assert_array_equal(A, Ar)
        np.testing.assert_array_equal(b, br)
    else:
        # multi-chunk accumulation reorders both reductions — rounding
        # only (observed ~1e-05 abs at unit-scale factors)
        np.testing.assert_allclose(A, Ar, atol=1e-4, rtol=5e-3)
        np.testing.assert_allclose(b, br, atol=1e-4, rtol=5e-3)


SHAPES = [
    (5, 8, 4),       # tiny everything
    (37, 24, 10),    # non-pow2 batch, w multiple of 8
    (33, 100, 128),  # the benchmark rank; w not a multiple of 8
    (64, 512, 32),   # multiple width chunks -> allclose regime for b
]


@pytest.mark.parametrize("n,w,r", SHAPES)
def test_explicit_matches_reference(rng, n, w, r):
    V, cols, vals, mask = _problem(rng, n, w, r)
    got = gather_normal_eq_explicit(V, cols, vals, mask, 0.05,
                                    interpret=True)
    ref = normal_eq_explicit(V[cols], vals, mask, 0.05)
    _assert_matches(got, ref, w)


@pytest.mark.parametrize("n,w,r", SHAPES)
def test_implicit_matches_reference(rng, n, w, r):
    V, cols, vals, mask = _problem(rng, n, w, r, implicit=True)
    YtY = compute_yty(V.astype(jnp.float32))
    got = gather_normal_eq_implicit(V, cols, vals, mask, 0.1, 4.0, YtY,
                                    interpret=True)
    ref = normal_eq_implicit(V[cols], vals, mask, 0.1, 4.0, YtY)
    _assert_matches(got, ref, w)


def test_empty_and_all_padding_rows(rng):
    # rows whose mask is entirely zero (empty users / all-padding bucket
    # rows pointing at col 0): A must be exactly the ridge-free zero +
    # tail, identical to the reference in every slot
    n, w, r = 16, 24, 8
    V, cols, vals, mask = _problem(rng, n, w, r)
    mask = mask.at[3].set(0.0).at[11].set(0.0)
    vals = vals * mask
    cols = cols.at[11].set(0)  # the builder's padding convention
    got = gather_normal_eq_explicit(V, cols, vals, mask, 0.05,
                                    interpret=True)
    ref = normal_eq_explicit(V[cols], vals, mask, 0.05)
    _assert_matches(got, ref, w)
    assert np.asarray(got[2])[3] == 0 and np.asarray(got[2])[11] == 0


def test_duplicate_columns_in_a_row(rng):
    # one entity rating the same opposite row several times in a window
    # (also the padding convention): each occurrence's DMA lands in its
    # own Vg slot, so duplicates contribute exactly like the gather
    n, w, r = 12, 16, 8
    V, cols, vals, mask = _problem(rng, n, w, r, N=5)  # tiny N -> dupes
    assert any(len(set(row)) < w for row in np.asarray(cols))
    got = gather_normal_eq_explicit(V, cols, vals, mask, 0.05,
                                    interpret=True)
    ref = normal_eq_explicit(V[cols], vals, mask, 0.05)
    _assert_matches(got, ref, w)


def test_bfloat16_compute_dtype(rng):
    # the bf16 casting rule: table gathered in bf16, contraction
    # accumulates f32 — both paths promote identically, so bitwise holds
    n, w, r = 24, 32, 16
    V, cols, vals, mask = _problem(rng, n, w, r, dtype=jnp.bfloat16)
    got = gather_normal_eq_explicit(V, cols, vals, mask, 0.05,
                                    interpret=True)
    ref = normal_eq_explicit(V[cols], vals, mask, 0.05)
    _assert_matches(got, ref, w)
    YtY = compute_yty(V.astype(jnp.float32))
    goti = gather_normal_eq_implicit(V, cols, vals, mask, 0.1, 4.0, YtY,
                                     interpret=True)
    refi = normal_eq_implicit(V[cols], vals, mask, 0.1, 4.0, YtY)
    _assert_matches(goti, refi, w)


def test_degree_skewed_buckets_match(rng):
    # real bucket layouts from the builder on a power-law degree
    # distribution: every (width, rows) bucket the planner emits must be
    # bitwise (entity_widths only emits single-chunk widths here)
    nU, nI = 120, 90
    deg = np.minimum((rng.pareto(1.2, nU) * 4 + 1).astype(int), nI)
    u = np.repeat(np.arange(nU), deg)
    i = np.concatenate([rng.choice(nI, d, replace=False) for d in deg])
    vals = rng.normal(size=len(u)).astype(np.float32)
    csr = build_csr_buckets(u, i, vals, nU, min_width=8)
    V = jnp.asarray(rng.normal(size=(nI, 16)).astype(np.float32) / 4.0)
    for bkt in csr.device_buckets():
        c = jnp.asarray(bkt.cols)
        v = jnp.asarray(bkt.vals)
        m = jnp.asarray(bkt.mask)
        got = gather_normal_eq_explicit(V, c, v, m, 0.05, interpret=True)
        ref = normal_eq_explicit(V[c], v, m, 0.05)
        _assert_matches(got, ref, c.shape[1])


@pytest.mark.parametrize("implicit", [False, True])
def test_train_gather_fused_bitwise_equals_auto(rng, implicit):
    # end to end: solve_backend='gather_fused' (interpret mode off-TPU)
    # must reproduce the einsum path's factors BITWISE after several
    # iterations — same normal equations in, same solver out
    nU, nI, nnz = 40, 30, 500
    u = rng.integers(0, nU, nnz)
    i = rng.integers(0, nI, nnz)
    r = np.abs(rng.normal(size=nnz)).astype(np.float32) + 0.1
    ucsr = build_csr_buckets(u, i, r, nU, min_width=8)
    icsr = build_csr_buckets(i, u, r, nI, min_width=8)
    kw = dict(rank=16, max_iter=3, reg_param=0.1, seed=3,
              implicit_prefs=implicit, alpha=4.0)
    Ua, Va = train(ucsr, icsr, AlsConfig(**kw))
    Ug, Vg = train(ucsr, icsr, AlsConfig(solve_backend="gather_fused",
                                         **kw))
    np.testing.assert_array_equal(np.asarray(Ua), np.asarray(Ug))
    np.testing.assert_array_equal(np.asarray(Va), np.asarray(Vg))


def test_resolve_path_forced_gather_fused():
    info = resolve_solve_path(
        AlsConfig(rank=16, solve_backend="gather_fused"), 16)
    assert info["resolved_solve_path"].startswith("gatherfused+")
    # off-TPU the auto walk must NOT pick the kernel (probe gates on TPU)
    if not info["on_tpu"]:
        auto = resolve_solve_path(AlsConfig(rank=16), 16)
        assert auto["resolved_solve_path"].startswith("einsum+")
        assert auto["gather_ne_probe"] is False
