"""tpu_als.obs — registry semantics, exposition, run dirs, the observe CLI.

Covers the observability contracts end to end on the forced 8-device CPU
mesh (conftest): fixed-bucket histograms, schema validation at call time
AND statically (scripts/check_obs_schema.py), Prometheus text exposition,
finalize/run-dir lifecycle, the instrumented train/serve/ingest/checkpoint
paths, and the `tpu_als observe summarize|tail` surface.  The deeper
comm-model-vs-jaxpr cross-check lives in tests/test_comm_audit.py; here we
verify the emitted gauge matches the audited estimator value for every
strategy.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import jax

from tpu_als import ALS, obs
from tpu_als.cli import main as cli_main
from tpu_als.obs import report, schema
from tpu_als.obs.metrics import BUCKET_BOUNDS, MetricsRegistry, _Hist
from tpu_als.parallel.mesh import make_mesh
from tpu_als.utils import observe
from tpu_als.utils.observe import IterationLogger

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHECKER = os.path.join(REPO, "scripts", "check_obs_schema.py")


@pytest.fixture(autouse=True)
def _fresh_registry():
    """Each test gets a clean default registry (the instrumented modules
    resolve it at call time through the tpu_als.obs delegators)."""
    obs.reset()
    yield
    obs.reset()


def _read_events(run_dir):
    with open(os.path.join(run_dir, "events.jsonl")) as f:
        return [json.loads(line) for line in f if line.strip()]


def _parse_prom(text):
    """name{labels} -> float for every sample line (comments skipped)."""
    samples = {}
    for line in text.strip().splitlines():
        if not line or line.startswith("#"):
            continue
        key, val = line.rsplit(" ", 1)
        samples[key] = float(val)
    return samples


# -- histogram buckets -----------------------------------------------------

def test_bucket_grid_is_fixed_log_scale():
    assert len(BUCKET_BOUNDS) == 49
    assert BUCKET_BOUNDS[0] == pytest.approx(1e-6)
    assert BUCKET_BOUNDS[-1] == pytest.approx(1e6)
    assert all(b < c for b, c in zip(BUCKET_BOUNDS, BUCKET_BOUNDS[1:]))
    # 4 buckets per decade, anchored at 1.0
    assert BUCKET_BOUNDS[24] == 1.0
    assert BUCKET_BOUNDS[28] / BUCKET_BOUNDS[24] == pytest.approx(10.0)


def test_hist_bucket_placement():
    h = _Hist()
    h.observe(1.0)          # exact bound: le semantics put it AT the bound
    assert h.counts[24] == 1
    h = _Hist()
    h.observe(2.0)          # (10^0.25, 10^0.5]
    assert h.counts[26] == 1
    h = _Hist()
    h.observe(5e7)          # beyond the last bound: overflow bucket
    assert h.counts[-1] == 1
    assert h.quantile(0.5) == 5e7   # overflow reports the observed max


def test_hist_state_and_quantiles():
    h = _Hist()
    for v in (0.01, 0.02, 0.04, 10.0):
        h.observe(v)
    st = h.state()
    assert st["count"] == 4
    assert st["sum"] == pytest.approx(10.07)
    assert st["min"] == 0.01 and st["max"] == 10.0
    # quantile returns the bucket's upper bound: an upper estimate
    assert st["p50"] >= 0.02
    assert st["p95"] == pytest.approx(10.0)   # 10.0 is a grid bound
    empty = _Hist()
    assert empty.state()["count"] == 0 and empty.state()["p50"] is None


def test_hist_quantile_bucket_edges():
    import math

    # empty: NaN for EVERY q — including q=0, where target is 0 and a
    # naive `acc >= target` would report the first grid bound
    empty = _Hist()
    for q in (0.0, 0.5, 1.0):
        assert math.isnan(empty.quantile(q))
    # single sample ON a grid bound (le semantics put it AT the bound):
    # every quantile reports exactly that bound, never the next one up
    h = _Hist()
    h.observe(1.0)                       # == BUCKET_BOUNDS[24]
    for q in (0.0, 0.5, 0.99, 1.0):
        assert h.quantile(q) == 1.0
    # exact-boundary observation deeper in the grid behaves the same
    h = _Hist()
    h.observe(BUCKET_BOUNDS[30])
    assert h.quantile(0.5) == BUCKET_BOUNDS[30]
    # q=0 with only a LATE bucket populated: the empty prefix must not
    # satisfy the target — the answer is the min's bucket, not bound[0]
    h = _Hist()
    h.observe(10.0)
    assert h.quantile(0.0) == 10.0 != BUCKET_BOUNDS[0]
    # overflow-only series: the observed max at every quantile
    h = _Hist()
    h.observe(5e7)
    assert h.quantile(0.0) == h.quantile(1.0) == 5e7


def test_registry_quantile_accessors_edge_cases():
    import math

    reg = MetricsRegistry()
    # a never-observed series reads NaN / 0, never raises
    assert math.isnan(reg.histogram_quantile("serve.request_seconds", 0.5))
    assert reg.histogram_count("serve.request_seconds") == 0
    assert reg.counter_value("serve.requests") == 0
    # single observation at an exact bound round-trips through the
    # label-keyed accessor
    reg.histogram("serve.request_seconds", 1.0, strategy="ring")
    assert reg.histogram_quantile("serve.request_seconds", 0.5,
                                  strategy="ring") == 1.0
    assert reg.histogram_count("serve.request_seconds",
                               strategy="ring") == 1
    # label mismatch is a distinct (empty) series
    assert math.isnan(reg.histogram_quantile("serve.request_seconds", 0.5))


# -- schema validation at call time ----------------------------------------

def test_undeclared_or_miskinded_names_raise():
    reg = MetricsRegistry()
    with pytest.raises(KeyError):
        reg.counter("made.up.metric")
    with pytest.raises(TypeError):
        reg.counter("serve.request_seconds")   # declared as a histogram
    with pytest.raises(TypeError):
        reg.histogram("serve.requests", 1.0)   # declared as a counter
    with pytest.raises(KeyError):
        reg.emit("made_up_event", x=1)
    with pytest.raises(ValueError):
        reg.emit("warning", what="half")       # missing required `reason`


# -- spans -----------------------------------------------------------------

def test_span_paths_nest_and_carry_labels():
    reg = MetricsRegistry()
    with reg.span("outer"):
        with reg.span("inner", strategy="ring"):
            pass
    spans = [e for e in reg._events if e["type"] == "span"]
    assert [e["path"] for e in spans] == ["outer/inner", "outer"]
    assert spans[0]["name"] == "inner" and spans[0]["strategy"] == "ring"
    assert all(e["seconds"] >= 0 for e in spans)


# -- Prometheus exposition -------------------------------------------------

def test_prometheus_exposition_contract():
    reg = MetricsRegistry()
    reg.counter("serve.requests", 3)
    reg.gauge("train.comm_bytes_per_iter", 4096, strategy="ring")
    for v in (0.001, 0.002, 0.5, 2e7):
        reg.histogram("serve.request_seconds", v, strategy="all_gather")
    text = reg.prometheus_text()
    samples = _parse_prom(text)
    assert samples["tpu_als_serve_requests_total"] == 3
    assert samples[
        'tpu_als_train_comm_bytes_per_iter{strategy="ring"}'] == 4096
    assert "# TYPE tpu_als_serve_request_seconds histogram" in text
    buckets = [(k, v) for k, v in samples.items()
               if k.startswith("tpu_als_serve_request_seconds_bucket")]
    # cumulative over the fixed grid: 49 bounds + the +Inf bucket
    assert len(buckets) == 50
    counts = [v for _, v in buckets]
    assert counts == sorted(counts)
    inf_key = [k for k, _ in buckets if 'le="+Inf"' in k]
    assert inf_key and samples[inf_key[0]] == 4   # overflow obs included
    assert samples[
        'tpu_als_serve_request_seconds_count{strategy="all_gather"}'] == 4
    assert samples[
        'tpu_als_serve_request_seconds_sum{strategy="all_gather"}'] == \
        pytest.approx(0.503 + 2e7)


# -- run-dir lifecycle -----------------------------------------------------

def test_finalize_roundtrip_and_idempotence(tmp_path):
    run = str(tmp_path / "obs")
    reg = MetricsRegistry()
    reg.configure(run, config={"cmd": "test"}, argv=["train", "--x"])
    assert reg.active()
    reg.counter("ingest.rows", 5)
    reg.gauge("train.comm_bytes_per_iter", 1234, strategy="ring")
    with reg.span("train.fit"):
        pass
    assert reg.finalize() == run
    events = _read_events(run)
    assert [e["type"] for e in events] == ["metric", "span", "snapshot"]
    snap = events[-1]
    assert snap["counters"]["ingest.rows"] == 5
    assert snap["gauges"][
        'train.comm_bytes_per_iter{strategy="ring"}'] == 1234
    with open(os.path.join(run, "run_manifest.json")) as f:
        manifest = json.load(f)
    assert manifest["argv"] == ["train", "--x"]
    assert manifest["config"] == {"cmd": "test"}
    assert manifest["finished_at"] >= manifest["started_at"]
    assert manifest["device_count"] == 8
    samples = _parse_prom(
        open(os.path.join(run, "metrics.prom")).read())
    assert samples["tpu_als_ingest_rows_total"] == 5
    # idempotent: a second finalize appends only what happened since
    n1 = len(events)
    reg.counter("ingest.rows", 2)      # counters don't emit events
    reg.finalize()
    events = _read_events(run)
    assert len(events) == n1 + 1       # exactly the second snapshot
    assert events[-1]["counters"]["ingest.rows"] == 7
    reg.deconfigure()
    assert not reg.active()
    assert reg.finalize() is None      # detached: nothing written


# -- summarize -------------------------------------------------------------

def test_summarize_events_aggregates():
    reg = MetricsRegistry()
    with reg.span("train.fit"):
        with reg.span("train.iteration"):
            pass
        with reg.span("train.iteration"):
            pass
    reg.emit("iteration", iteration=1, seconds=0.5, total_seconds=0.5,
             probe_rmse=0.9)
    reg.gauge("train.comm_bytes_per_iter", 777, strategy="all_gather")
    reg.emit("warning", what="trace_skipped", reason="already active")
    s = report.summarize_events(reg._events)
    it_path = "train.fit/train.iteration"
    assert s["phases"][it_path]["count"] == 2
    assert s["phases"]["train.fit"]["count"] == 1
    assert s["phases"][it_path]["mean_seconds"] == pytest.approx(
        s["phases"][it_path]["total_seconds"] / 2)
    assert s["iterations"][0]["probe_rmse"] == 0.9
    assert s["gauges"][
        'train.comm_bytes_per_iter{strategy="all_gather"}'] == 777
    assert s["warnings"][0]["what"] == "trace_skipped"
    text = report.render_summary(s)
    assert "phases:" in text and it_path in text
    assert "probe_rmse" in text
    assert "warning: trace_skipped" in text


# -- instrumented paths ----------------------------------------------------

def test_checkpoint_events_and_metrics(tmp_path, rng):
    from tpu_als.io.checkpoint import load_factors, save_factors

    run = str(tmp_path / "obs")
    obs.configure(run)
    path = str(tmp_path / "ckpt")
    U = rng.normal(size=(6, 3)).astype(np.float32)
    V = rng.normal(size=(5, 3)).astype(np.float32)
    save_factors(path, np.arange(6), U, np.arange(5), V, iteration=2)
    load_factors(path)
    obs.finalize()
    events = _read_events(run)
    saves = [e for e in events if e["type"] == "checkpoint_save"]
    loads = [e for e in events if e["type"] == "checkpoint_load"]
    assert len(saves) == 1 and len(loads) == 1
    assert saves[0]["bytes"] > 0 and saves[0]["iteration"] == 2
    snap = events[-1]
    assert snap["counters"]["checkpoint.save_bytes"] == saves[0]["bytes"]
    assert snap["counters"]["checkpoint.load_bytes"] == loads[0]["bytes"]
    assert snap["histograms"]["checkpoint.save_seconds"]["count"] == 1
    assert snap["histograms"]["checkpoint.load_seconds"]["count"] == 1


def test_ingest_counters_match_file(tmp_path):
    from tpu_als.io.stream import stream_ingest

    p = tmp_path / "ratings.csv"
    p.write_text("u1,i1,3.0\nu2,i2,4.0\nu1,i2,5.0\n")
    u, i, r, ulab, ilab = stream_ingest(str(p))
    assert len(u) == 3
    snap = obs.snapshot()
    assert snap["counters"]["ingest.rows"] == 3
    assert snap["counters"]["ingest.bytes"] == os.path.getsize(p)
    evs = [e for e in obs.default_registry()._events
           if e["type"] == "ingest"]
    assert len(evs) == 1 and evs[0]["rows"] == 3


def test_estimator_gauge_matches_comm_model():
    """The train.comm_bytes_per_iter gauge must equal the estimator's
    audited comm model for every strategy (the model itself is checked
    against traced jaxprs in tests/test_comm_audit.py).  Sparse random
    layout so all_to_all does not degenerate into its fallback."""
    gen = np.random.default_rng(11)
    nU = nI = 256
    u = np.repeat(np.arange(nU), 4)
    i = np.concatenate([gen.choice(nI, 4, replace=False)
                        for _ in range(nU)])
    r = gen.normal(size=len(u)).astype(np.float32)
    frame = {"user": u, "item": i, "rating": r}
    mesh = make_mesh(8)
    for strategy in ("all_gather", "ring", "all_to_all"):
        obs.reset()
        als = ALS(rank=4, maxIter=1, regParam=0.05, seed=0, mesh=mesh,
                  gatherStrategy=strategy)
        als.fit(frame)
        assert als.lastFitStrategy == strategy, \
            "layout degenerated; the strategy under test never ran"
        key = f'train.comm_bytes_per_iter{{strategy="{strategy}"}}'
        gauges = obs.snapshot()["gauges"]
        assert key in gauges, gauges
        assert gauges[key] == als.lastFitCommBytes > 0


def test_serve_histogram_and_overhead():
    from tpu_als.parallel.serve import topk_sharded

    gen = np.random.default_rng(3)
    U = gen.normal(size=(64, 8)).astype(np.float32)
    V = gen.normal(size=(256, 8)).astype(np.float32)
    mesh = make_mesh(8)
    topk_sharded(U, V, 10, mesh)            # warmup / compile
    n, times = 5, []
    for _ in range(n):
        t0 = time.perf_counter()
        topk_sharded(U, V, 10, mesh)
        times.append(time.perf_counter() - t0)
    snap = obs.snapshot()
    h = snap["histograms"]['serve.request_seconds{strategy="all_gather"}']
    assert h["count"] == n + 1
    assert snap["counters"]["serve.requests"] == n + 1
    assert snap["counters"]["serve.rows"] == 64 * (n + 1)
    # the exposition of the live registry parses as Prometheus text
    samples = _parse_prom(obs.prometheus_text())
    assert samples[
        'tpu_als_serve_request_seconds_count{strategy="all_gather"}'] \
        == n + 1
    assert samples["tpu_als_serve_requests_total"] == n + 1
    # instrumentation overhead: the per-request registry writes (what
    # topk_sharded adds per call) must be <5% of the request itself
    reps = 200
    t0 = time.perf_counter()
    for _ in range(reps):
        obs.histogram("serve.request_seconds", 1e-3, strategy="all_gather")
        obs.counter("serve.requests")
        obs.counter("serve.rows", 64)
    per_request_cost = (time.perf_counter() - t0) / reps
    assert per_request_cost < 0.05 * min(times), \
        (per_request_cost, min(times))


# -- IterationLogger / trace hardening -------------------------------------

def test_iteration_logger_context_manager(tmp_path):
    path = tmp_path / "log.jsonl"
    U = np.ones((4, 2), dtype=np.float32)
    V = np.ones((3, 2), dtype=np.float32)
    with IterationLogger(stream=None, path=str(path)) as logger:
        logger(1, U, V)
        logger(2, U, V)
        assert logger._file is not None
    assert logger._closed and logger._file is None
    recs = [json.loads(line) for line in open(path)]
    assert [r["iteration"] for r in recs] == [1, 2]
    # total_seconds is cumulative wall clock: monotone, >= the delta
    assert recs[1]["total_seconds"] >= recs[0]["total_seconds"]
    assert recs[1]["total_seconds"] >= recs[1]["seconds"]


def test_iteration_logger_lazy_open(tmp_path):
    path = tmp_path / "never.jsonl"
    with IterationLogger(stream=None, path=str(path)):
        pass                       # no records -> no file
    assert not path.exists()


def test_trace_degrades_to_noop_when_profiler_fails(tmp_path, monkeypatch):
    def boom(logdir):
        raise RuntimeError("profiler plugin missing")

    monkeypatch.setattr(jax.profiler, "start_trace", boom)
    ran = []
    with observe.trace(str(tmp_path / "t")):
        ran.append(True)           # body still runs, nothing raises
    assert ran
    warns = [e for e in obs.default_registry()._events
             if e["type"] == "warning"]
    assert any(e["what"] == "trace_unavailable" for e in warns)
    assert observe._trace_active is False


def test_trace_nested_request_skipped(tmp_path, monkeypatch):
    monkeypatch.setattr(jax.profiler, "start_trace", lambda d: None)
    monkeypatch.setattr(jax.profiler, "stop_trace", lambda: None)
    with observe.trace(str(tmp_path / "outer")):
        with observe.trace(str(tmp_path / "inner")):
            pass
    warns = [e for e in obs.default_registry()._events
             if e["type"] == "warning"]
    assert any(e["what"] == "trace_skipped" for e in warns)
    assert observe._trace_active is False


# -- static schema checker -------------------------------------------------

def test_check_obs_schema_repo_is_clean():
    p = subprocess.run([sys.executable, CHECKER],
                       capture_output=True, text=True)
    assert p.returncode == 0, p.stderr + p.stdout
    assert "OK" in p.stdout


def test_check_obs_schema_catches_violations(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(
        'obs.counter("made.up.metric")\n'
        'obs.histogram("serve.requests", 1.0)\n'
        'obs.emit("made_up_event", x=1)\n'
        'obs.counter(variable_name)\n'
        'ev = {"ts": 0.0, "type": "rogue_inline_event"}\n')
    p = subprocess.run([sys.executable, CHECKER, "--paths", str(bad)],
                       capture_output=True, text=True)
    assert p.returncode == 1
    assert "made.up.metric" in p.stderr
    assert "declared as a counter" in p.stderr
    assert "made_up_event" in p.stderr
    assert "non-literal name" in p.stderr
    assert "rogue_inline_event" in p.stderr


def test_check_obs_schema_catches_accessor_and_assertion_drift(tmp_path):
    """The read-side extension: typo'd accessor names and undeclared
    Assertion(metric=/event=/den=) literals are violations; a dynamic
    accessor read (the scenario evaluator) is NOT."""
    bad = tmp_path / "bad.py"
    bad.write_text(
        'q = reg.histogram_quantile("no.such.hist", 0.5)\n'
        'c = reg.counter_value("serve.request_seconds")\n'
        'ok = reg.histogram_quantile(a.metric, a.q)\n'
        'x = Assertion("n", "quantile", metric="not.declared", q=0.5)\n'
        'y = Assertion("n", "event", event="not_an_event")\n'
        'z = Assertion("n", "ratio", num="serving.shed",\n'
        '              den=("serving.requests", "bogus.counter"))\n'
        'w = Assertion("n", "fact", fact="anything_goes")\n')
    p = subprocess.run([sys.executable, CHECKER, "--paths", str(bad)],
                       capture_output=True, text=True)
    assert p.returncode == 1
    assert "no.such.hist" in p.stderr
    # kind mismatch through the accessor alias
    assert "used as a counter (counter_value)" in p.stderr
    assert "not.declared" in p.stderr
    assert "not_an_event" in p.stderr
    assert "bogus.counter" in p.stderr
    # the dynamic read and the fact-kind assertion are clean
    assert "a.metric" not in p.stderr
    assert "anything_goes" not in p.stderr
    assert p.stderr.count(str(bad.name)) == 5


def test_check_obs_schema_catches_fault_point_drift(tmp_path):
    """The fault-vocabulary extension: typo'd faults.check/armed/hits
    literals and unparseable scenario fault_spec strings are violations;
    a declared point and a well-formed spec are not."""
    bad = tmp_path / "bad.py"
    bad.write_text(
        'mode = faults.check("solve.grim")\n'
        'ok = faults.armed("solve.gram")\n'
        'n = faults.hits(point_var)\n'
        'spec = ScenarioSpec(fault_spec="ingest.record=corrupt@every=5")\n'
        'bad = ScenarioSpec(fault_spec="no.such.point=raise")\n'
        'ugly = ScenarioSpec(fault_spec="solve.gram-corrupt")\n')
    p = subprocess.run([sys.executable, CHECKER, "--paths", str(bad)],
                       capture_output=True, text=True)
    assert p.returncode == 1
    assert "solve.grim" in p.stderr
    assert "non-literal point" in p.stderr
    assert "no.such.point" in p.stderr
    assert "solve.gram-corrupt" in p.stderr
    # the declared point (line 2) and well-formed spec (line 4) are clean
    assert "4 violation(s)" in p.stderr
    assert f"{bad.name}:2" not in p.stderr
    assert f"{bad.name}:4" not in p.stderr


# -- bench.py probe events -------------------------------------------------

def test_bench_retry_events_are_schema_valid(monkeypatch):
    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.remove(REPO)

    class _Failed:
        returncode = 1
        stdout = ""
        stderr = "RuntimeError: tunnel down\n"

    monkeypatch.setattr(bench.subprocess, "run",
                        lambda *a, **k: _Failed())
    ok, err, events = bench.tpu_ready(attempts=2, wait_s=0,
                                      probe_timeout_s=5)
    assert not ok and "tunnel down" in err
    # per-attempt retry records, then the terminal exhaustion verdict
    assert [e["attempt"] for e in events[:-1]] == [1, 2]
    assert events[-1]["type"] == "bench_probe_exhausted"
    for ev in events:
        schema.check_event(ev["type"], {
            k: v for k, v in ev.items() if k not in ("ts", "type")})


# -- the observe CLI end to end (ISSUE acceptance) -------------------------

def test_cli_train_then_observe_summarize(tmp_path, capsys):
    out = str(tmp_path / "model")
    cli_main(["train", "--data", "synthetic:200x80x3000", "--rank", "4",
              "--max-iter", "2", "--devices", "4",
              "--gather-strategy", "ring", "--output", out])
    capsys.readouterr()                      # drop training chatter
    obs_dir = os.path.join(out, "obs")
    for name in ("events.jsonl", "metrics.prom", "run_manifest.json"):
        assert os.path.exists(os.path.join(obs_dir, name)), name

    cli_main(["observe", "summarize", out])
    text = capsys.readouterr().out
    assert "phases:" in text and "cli.train" in text
    assert "train.fit" in text and "data.load" in text
    assert "iterations:" in text and "probe_rmse" in text
    assert 'train.comm_bytes_per_iter{strategy="ring"}' in text
    assert "MB/device/iter" in text

    cli_main(["observe", "summarize", out, "--json"])
    j = json.loads(capsys.readouterr().out)
    assert j["phases"]["cli.train"]["count"] == 1
    assert len(j["iterations"]) == 2
    assert all(np.isfinite(ev["probe_rmse"]) for ev in j["iterations"])
    assert j["manifest"]["config"]["cmd"] == "train"

    cli_main(["observe", "tail", out, "-n", "5"])
    lines = [ln for ln in capsys.readouterr().out.splitlines()
             if ln.strip()]
    assert len(lines) == 5
    assert json.loads(lines[-1])["type"] == "snapshot"

    # the model save itself must be intact next to the run dir
    assert os.path.exists(os.path.join(out, "manifest.json"))
    # the exposition file parses
    samples = _parse_prom(
        open(os.path.join(obs_dir, "metrics.prom")).read())
    assert 'tpu_als_train_comm_bytes_per_iter{strategy="ring"}' in samples


def test_observe_tail_event_filter(tmp_path, capsys):
    run = str(tmp_path / "obs")
    reg = MetricsRegistry()
    reg.configure(run)
    for i in range(5):
        reg.emit("warning", what=f"w{i}", reason="x")
        with reg.span("noise"):
            pass
    reg.finalize()
    # filtered BEFORE the tail slice: the last 3 warnings, not whatever
    # warnings happen to sit in the last 3 raw lines
    lines = report.cmd_tail(run, n=3, event="warning").splitlines()
    assert [json.loads(ln)["what"] for ln in lines] == ["w2", "w3", "w4"]
    assert all(json.loads(ln)["type"] == "warning" for ln in lines)
    # the CLI surface
    cli_main(["observe", "tail", run, "-n", "2", "--event", "span"])
    out = [ln for ln in capsys.readouterr().out.splitlines() if ln.strip()]
    assert len(out) == 2
    assert all(json.loads(ln)["type"] == "span" for ln in out)
    # a type with no occurrences filters to empty output, not an error
    assert report.cmd_tail(run, n=5, event="flight_record") == ""


def test_observe_summarize_missing_dir_errors(tmp_path):
    with pytest.raises(SystemExit):
        cli_main(["observe", "summarize", str(tmp_path / "nope")])
