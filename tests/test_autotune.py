"""Self-tuning kernels (tpu_als.perf.autotune + the planner's
kernel_config component, docs/roofline.md): the measure -> plan ->
re-plan loop.

The load-bearing pins:

- NEVER SLOWER: the defaults are trial 0 and the winner is the strict
  measured minimum with ties going to the earlier trial, so the tuned
  config can never lose its own A/B.
- DETERMINISM: same seed + same timer => same trial list => same
  winning config.
- ZERO TUNING WARM: a banked, non-invalidated kernel_config resolves as
  a pure cache read — ``plan_cache_hit`` present, ``tune_trial`` absent.
- OFF IS FREE: with ``TPU_ALS_AUTOTUNE`` unset the training step's
  traced jaxpr is byte-identical to the disarmed planner, even with a
  non-default config banked (the ne_audit/plan_cache_off discipline);
  with it set, the banked config actually changes the trace.
- NEVER OVERRIDE: an interpret-sourced verdict never replaces a banked
  on-chip (device) measurement, even under ``force``.
- FLOOR AUDIT: the committed CPU A/B bank must keep measured-vs-modeled
  inside its band — doctored banks turn the contract red.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tpu_als import obs, plan
from tpu_als.analysis import contracts
from tpu_als.core.als import AlsConfig, init_factors, make_step
from tpu_als.core.ratings import build_csr_buckets
from tpu_als.ops.pallas_gather_ne import (TileBudgetError, _tiles_solve,
                                          gather_fused_solve_explicit)
from tpu_als.perf import autotune
from tpu_als.plan import cache as plan_cache
from tpu_als.plan.cache import ENV_VAR
from tpu_als.utils import platform

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_plan_state(monkeypatch, tmp_path):
    monkeypatch.setenv(ENV_VAR, str(tmp_path / "plan"))
    monkeypatch.delenv(plan.AUTOTUNE_ENV, raising=False)
    platform.clear_probe_caches()
    obs.reset()
    yield
    platform.clear_probe_caches()
    obs.reset()


def _events(etype):
    return [e for e in obs.default_registry()._events if e["type"] == etype]


def _fake_timer(score, interpret=True):
    """Deterministic injectable timer: ``score(config) -> seconds``."""
    def timer(config):
        return float(score(config))
    timer.interpret = bool(interpret)
    return timer


# -- search space and enumeration ------------------------------------------

def test_enumerate_default_first_and_deterministic():
    trials = autotune.enumerate_configs()
    assert trials[0] == autotune.DEFAULT_CONFIG
    # 1 default + one-at-a-time alternatives: 2+3+2+2+1
    assert len(trials) == 11
    assert trials == autotune.enumerate_configs()
    # every trial differs from the default in at most one knob
    for t in trials[1:]:
        diffs = [k for k in t if t[k] != autotune.DEFAULT_CONFIG[k]]
        assert len(diffs) == 1


def test_enumerate_restricted_space_and_unknown_knob():
    # the default (8) is inside the space, so the base keeps it and the
    # alternative is the only extra trial
    trials = autotune.enumerate_configs({"depth": (2, 8)})
    assert [t["depth"] for t in trials] == [8, 2]
    # the default is NOT in the space: the base snaps to the space's
    # first value so trial 0 stays a member of the searched space
    trials = autotune.enumerate_configs({"depth": (2, 4)})
    assert [t["depth"] for t in trials] == [2, 4]
    assert autotune.enumerate_configs({}) == [autotune.DEFAULT_CONFIG]
    with pytest.raises(ValueError, match="unknown autotune knob"):
        autotune.enumerate_configs({"tile_rows": (8,)})


def test_feasible_respects_panel_divisibility_and_budget():
    assert autotune.feasible(autotune.DEFAULT_CONFIG, 128)
    bad_panel = dict(autotune.DEFAULT_CONFIG, panel=48)
    assert not autotune.feasible(bad_panel, 128)    # 128 % 48 != 0
    starved = dict(autotune.DEFAULT_CONFIG, vmem_budget=1 << 12)
    assert not autotune.feasible(starved, 512)


# -- the satellite: _tiles_solve typed error + edge shapes -----------------

def test_tiles_solve_default_pins_unchanged():
    # the hand-picked historical behavior IS the untuned fallback —
    # these exact triples are what the tuned-off path must keep
    assert _tiles_solve(128, 256) == (16, 256, 256)
    assert _tiles_solve(128, 64) == (32, 64, 64)
    assert _tiles_solve(128, 8, panel=8, vmem_budget=1 << 16) == (16, 8, 8)


def test_tiles_solve_rank256_panel32_edge():
    # rank 256 / panel 32 at the default budget sits exactly ON the
    # 8-row knee: cap = 2^17 // (32*256) = 16 -> tn clamps to 8, no raise
    tn, wc, w_pad = _tiles_solve(256, 32, panel=32)
    assert tn == 8 and wc == 32


def test_tiles_solve_below_knee_is_typed_error():
    with pytest.raises(TileBudgetError, match="panel-efficiency knee"):
        _tiles_solve(1024, 8, vmem_budget=1 << 15)
    # the message names the fix: the minimal sufficient budget
    with pytest.raises(TileBudgetError, match=str(8 * 32 * 1024)):
        _tiles_solve(1024, 8, vmem_budget=1 << 15)
    # TileBudgetError is a ValueError: existing callers' except clauses
    # keep working
    assert issubclass(TileBudgetError, ValueError)


# -- tune(): determinism, never-slower, budget, events ---------------------

def test_tune_same_seed_same_config():
    score = lambda c: 1.0 + 0.1 * c["panel"] / (1 + c["depth"])
    a = autotune.tune(rank=128, timer=_fake_timer(score))
    b = autotune.tune(rank=128, timer=_fake_timer(score))
    assert a["config"] == b["config"]
    assert [t["config"] for t in a["trials"]] \
        == [t["config"] for t in b["trials"]]


def test_tune_default_wins_ties_and_is_never_slower():
    flat = autotune.tune(rank=128, timer=_fake_timer(lambda c: 1.0))
    assert flat["config"] == autotune.DEFAULT_CONFIG   # tie -> trial 0
    score = lambda c: 0.5 if c["depth"] == 2 else 1.0
    tuned = autotune.tune(rank=128, timer=_fake_timer(score))
    assert tuned["config"]["depth"] == 2
    assert tuned["measured_seconds"] <= tuned["default_seconds"]
    assert flat["measured_seconds"] <= flat["default_seconds"]


def test_tune_emits_trial_events_and_skips_infeasible():
    autotune.tune(rank=128, timer=_fake_timer(lambda c: 1.0),
                  space={"panel": (16, 48)})      # 48 infeasible at 128
    ev = _events("tune_trial")
    assert len(ev) == 1 and ev[0]["config"]["panel"] == 16


def test_tune_budget_keeps_default_trial():
    slow = _fake_timer(lambda c: 1.0)
    out = autotune.tune(rank=128, timer=slow, budget_s=0.0)
    # budget exhausts after trial 0 — the defaults still got timed
    assert len(out["trials"]) == 1
    assert out["config"] == autotune.DEFAULT_CONFIG


def test_tune_source_follows_timer_interpret_flag():
    assert autotune.tune(rank=128, timer=_fake_timer(lambda c: 1.0)
                         )["source"] == "interpret"
    assert autotune.tune(rank=128,
                         timer=_fake_timer(lambda c: 1.0, interpret=False)
                         )["source"] == "device"


def test_drift_band():
    assert not autotune.drifted(10.0, 15.0, band=2.0)
    assert autotune.drifted(10.0, 25.0, band=2.0)
    assert autotune.drifted(10.0, 4.0, band=2.0)
    assert not autotune.drifted(None, 5.0)
    assert not autotune.drifted(5.0, None)


# -- tuned-vs-untuned kernel equivalence -----------------------------------

def _instance(rank=16, n=24, w=16, seed=3):
    rng = np.random.default_rng(seed)
    N = 96
    V = jnp.asarray(rng.normal(size=(N, rank)).astype(np.float32))
    cols = jnp.asarray(rng.integers(0, N, size=(n, w)).astype(np.int32))
    vals = jnp.asarray(rng.normal(size=(n, w)).astype(np.float32))
    mask = jnp.asarray((rng.random((n, w)) < 0.8).astype(np.float32))
    return V, cols, vals, mask


def test_depth_and_max_wc_are_bitwise_neutral():
    V, cols, vals, mask = _instance()
    ref = gather_fused_solve_explicit(V, cols, vals, mask, 0.1,
                                      interpret=True)
    for kw in ({"depth": 2}, {"depth": 4}, {"max_wc": 128},
               {"max_wc": 512}):
        out = gather_fused_solve_explicit(V, cols, vals, mask, 0.1,
                                          interpret=True, **kw)
        assert jnp.array_equal(ref, out), kw


def test_panel_and_budget_change_stays_allclose():
    V, cols, vals, mask = _instance()
    ref = gather_fused_solve_explicit(V, cols, vals, mask, 0.1,
                                      interpret=True)
    for kw in ({"panel": 8}, {"panel": 32}, {"vmem_budget": 1 << 16},
               {"vmem_budget": 1 << 19}):
        out = gather_fused_solve_explicit(V, cols, vals, mask, 0.1,
                                          interpret=True, **kw)
        assert jnp.allclose(ref, out, atol=1e-3, rtol=1e-2), kw


# -- planner integration: bank, warm read, invalidate, never-override ------

def _bank(score=lambda c: 0.5 if c["panel"] == 32 else 1.0,
          interpret=True, **kw):
    return plan.resolve_kernel_config(
        rank=4, tune=True, timer=_fake_timer(score, interpret), **kw)


def test_cold_tune_banks_then_warm_reads_with_zero_tuning():
    cfg = _bank()
    assert cfg["panel"] == 32
    assert _events("plan_tuned") and _events("tune_trial")
    obs.reset()
    again = plan.resolve_kernel_config(rank=4)
    assert again == cfg
    hits = [e for e in _events("plan_cache_hit")
            if e["component"] == "kernel_config"]
    assert hits and not _events("tune_trial")     # ZERO tuning warm
    src = [e["source"] for e in _events("plan_resolved")
           if e["component"] == "kernel_config"]
    assert src == ["cache"]


def test_untuned_miss_returns_none_without_autotune_env(monkeypatch):
    assert plan.resolve_kernel_config(rank=4) is None
    assert not _events("tune_trial")
    monkeypatch.setenv(plan.AUTOTUNE_ENV, "1")
    assert plan.autotune_enabled()
    cfg = plan.resolve_kernel_config(
        rank=4, timer=_fake_timer(lambda c: 1.0))
    assert cfg == autotune.DEFAULT_CONFIG        # auto-tune-on-miss


def test_invalidate_triggers_retune_on_next_armed_resolve():
    _bank()
    assert plan.invalidate_kernel_config(rank=4, reason="drift")
    assert plan.resolve_kernel_config(rank=4) is None   # stale: not trusted
    obs.reset()
    cfg = _bank(score=lambda c: 0.5 if c["depth"] == 2 else 1.0)
    assert cfg["depth"] == 2 and _events("tune_trial")
    key = plan.plan_key(rank=4, dtype="float32")
    prov = plan_cache.load_entry(key)["components"]["kernel_config"][
        "provenance"]
    assert not prov.get("invalidated")
    assert not plan.invalidate_kernel_config(rank=99)   # absent -> False


def test_interpret_never_overrides_device_bank():
    dev = _bank(interpret=False)
    key = plan.plan_key(rank=4, dtype="float32")
    assert plan_cache.load_entry(key)["components"]["kernel_config"][
        "provenance"]["source"] == "device"
    obs.reset()
    got = _bank(score=lambda c: 0.1 if c["depth"] == 2 else 1.0,
                interpret=True, force=True)
    assert got == dev                            # fresh verdict discarded
    prov = plan_cache.load_entry(key)["components"]["kernel_config"][
        "provenance"]
    assert prov["source"] == "device"
    assert any("never-override" in e.get("reason", "")
               for e in _events("warning"))


def test_execution_plan_carries_kernel_config():
    plan.resolve_kernel_config(rank=16, tune=True,
                               timer=_fake_timer(lambda c: 1.0))
    ep = plan.resolve_execution_plan(rank=16, compute_dtype="float32",
                                     solve_backend="auto", cg_iters=0)
    assert ep.kernel_config == autotune.DEFAULT_CONFIG
    assert "kernel_config" in ep.summary()


# -- OFF IS FREE: the jaxpr pin --------------------------------------------

def _trace_step(rank=4):
    jax.clear_caches()      # the pjit trace cache would otherwise hand
    # back the previous env's jaxpr for identical statics
    gen = np.random.default_rng(0)
    nU, nI, nnz = 60, 40, 800
    u = gen.integers(0, nU, nnz)
    i = gen.integers(0, nI, nnz)
    r = gen.uniform(0.5, 5.0, nnz).astype(np.float32)
    ucsr = build_csr_buckets(u, i, r, nU, min_width=4, chunk_elems=1 << 12)
    icsr = build_csr_buckets(i, u, r, nI, min_width=4, chunk_elems=1 << 12)
    cfg = AlsConfig(rank=rank, max_iter=2,
                    solve_backend="gather_fused_solve")
    ub = jax.device_put(ucsr.device_buckets())
    ib = jax.device_put(icsr.device_buckets())
    ku, kv = jax.random.split(jax.random.PRNGKey(cfg.seed))
    U0 = init_factors(ku, nU, cfg.rank)
    V0 = init_factors(kv, nI, cfg.rank)
    step = make_step(ub, ib, nU, nI, cfg,
                     ucsr.chunk_elems, icsr.chunk_elems)
    return str(jax.make_jaxpr(step)(U0, V0))


def test_autotune_off_jaxpr_byte_identical_and_on_diverges(monkeypatch,
                                                           tmp_path):
    # bank a config that differs from the defaults in a trace-visible
    # knob (panel changes the kernel tiling)
    _bank(score=lambda c: 0.5 if c["panel"] == 32 else 1.0)

    monkeypatch.setenv(ENV_VAR, "off")
    disarmed = _trace_step()

    monkeypatch.setenv(ENV_VAR, str(tmp_path / "plan"))
    monkeypatch.delenv(plan.AUTOTUNE_ENV, raising=False)
    armed_off = _trace_step()
    assert armed_off == disarmed     # the ne_audit-style byte pin

    monkeypatch.setenv(plan.AUTOTUNE_ENV, "1")
    armed_on = _trace_step()
    assert armed_on != disarmed      # the banked config reached the trace


# -- the floor_audit contract ----------------------------------------------

def _consistent_bank(tmp_path, **overrides):
    config = dict(autotune.DEFAULT_CONFIG)
    shape = {"rank": 128, "n": 256, "w": 64, "k": 3, "seed": 0}
    model_s = autotune.model_seconds(config, 128, 256, 64)
    tuned = model_s * 10.0
    default = tuned * 1.25
    doc = {"metric": "autotune_fused_solve_speedup_cpu",
           "value": default / tuned, "unit": "x",
           "kernel": "gather_solve", "source": "interpret",
           "config": config, "default_seconds": default,
           "tuned_seconds": tuned, "model_seconds": model_s,
           "tune_seconds": 1.0, "shape": shape,
           "banked_at": "2026-08-07T00:00:00+00:00"}
    doc.update(overrides)
    (tmp_path / contracts.FLOOR_AUDIT_BANK).write_text(json.dumps(doc))
    return doc


def _floor_verdict(monkeypatch, tmp_path):
    monkeypatch.setenv(contracts.FLOOR_AUDIT_ROOT_ENV, str(tmp_path))
    return contracts._REGISTRY["floor_audit"].verify()


def test_floor_audit_registered_and_green_on_consistent_bank(
        monkeypatch, tmp_path):
    assert "floor_audit" in contracts._REGISTRY
    _consistent_bank(tmp_path)
    res = _floor_verdict(monkeypatch, tmp_path)
    assert res.ok, res.detail
    assert "inside its band" in res.detail


def test_floor_audit_red_on_doctored_banks(monkeypatch, tmp_path):
    good = _consistent_bank(tmp_path)
    # (a) tuned slower than default: never-slower rule broken
    _consistent_bank(tmp_path,
                     tuned_seconds=good["default_seconds"] * 1.2,
                     value=1.0 / 1.2)
    assert not _floor_verdict(monkeypatch, tmp_path).ok
    # (b) interpret timing at/below the HBM floor: physically impossible
    _consistent_bank(tmp_path, tuned_seconds=good["model_seconds"] * 0.5,
                     value=good["default_seconds"]
                     / (good["model_seconds"] * 0.5))
    assert not _floor_verdict(monkeypatch, tmp_path).ok
    # (c) banked model_seconds drifted from the closed form
    _consistent_bank(tmp_path, model_seconds=good["model_seconds"] * 3)
    assert not _floor_verdict(monkeypatch, tmp_path).ok
    # (d) speedup value inconsistent with its own timings
    _consistent_bank(tmp_path, value=good["value"] * 2)
    assert not _floor_verdict(monkeypatch, tmp_path).ok


def test_floor_audit_green_on_shipped_tree(monkeypatch):
    monkeypatch.delenv(contracts.FLOOR_AUDIT_ROOT_ENV, raising=False)
    assert os.path.exists(os.path.join(REPO, contracts.FLOOR_AUDIT_BANK)), \
        "the committed CPU A/B bank is missing"
    res = contracts._REGISTRY["floor_audit"].verify()
    assert res.ok, res.detail


# -- the CLI surface -------------------------------------------------------

@pytest.mark.slow
def test_cli_plan_tune_cold_warm_and_show(tmp_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               TPU_ALS_PLAN_CACHE=str(tmp_path / "plan"))
    env.pop(plan.AUTOTUNE_ENV, None)
    base = [sys.executable, "-m", "tpu_als.cli", "plan", "tune",
            "--rank", "8", "--n", "16", "--w", "8", "--reps", "1",
            "--space", "{}"]
    cold = json.loads(subprocess.run(
        base + ["--bank-out", str(tmp_path / "bank.json")],
        capture_output=True, text=True, env=env, cwd=REPO,
        check=True).stdout.splitlines()[0])
    assert cold["config"] == autotune.DEFAULT_CONFIG
    assert cold["provenance"]["trials"] == 1
    assert cold["provenance"]["source"] == "interpret"
    bank = json.loads((tmp_path / "bank.json").read_text())
    assert bank["metric"] == "autotune_fused_solve_speedup_cpu"
    assert bank["value"] >= 1.0          # never slower, tie allowed

    warm = json.loads(subprocess.run(
        base, capture_output=True, text=True, env=env, cwd=REPO,
        check=True).stdout.splitlines()[0])
    assert warm["config"] == cold["config"]
    assert warm["resolve_seconds"] < cold["resolve_seconds"]

    show = json.loads(subprocess.run(
        [sys.executable, "-m", "tpu_als.cli", "plan", "show"],
        capture_output=True, text=True, env=env, cwd=REPO,
        check=True).stdout)
    comp = show["entries"][0]["components"]["kernel_config"]
    mvm = comp["model_vs_measured"]
    assert mvm["tuned_config"] == cold["config"]
    assert mvm["measured_s"] > 0 and mvm["prediction_s"] > 0
    assert mvm["ratio"] == pytest.approx(
        mvm["measured_s"] / mvm["prediction_s"])
