"""Sharded serving fabric (PR 17): the mesh-resident int8 index, the
in-kernel merge-ring serve path, the engine's backend dispatch, and the
traffic-derived bucket ladder.

Equality discipline: corpora are built from INTEGER-valued factors drawn
from a tiny row pool, so every f32 dot product is exact regardless of
contraction order and rows collide constantly — score ties are the
common case, not the measure-zero one.  Bitwise equality (scores AND
ids) against the single-device ``chunked_topk_scores`` is then a real
statement about tie ORDER across shard counts, backends, and delta
publishes.  All on the 8-device forced-host CPU backend; the merge-ring
kernel runs in interpret mode (identical kernel logic to the TPU
compile — see tests/test_pallas_topk.py).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tpu_als.ops.topk import chunked_topk_scores
from tpu_als.parallel.mesh import make_mesh
from tpu_als.parallel.serve import topk_sharded
from tpu_als.resilience import faults
from tpu_als.serving.engine import ServingEngine
from tpu_als.serving.index import (
    Int8CandidateIndex,
    ShardedInt8Index,
    build_index,
    build_sharded_index,
)


def _tie_corpus(rng, nu, ni, r, pool=7):
    """Integer factors from a ``pool``-row palette: exact f32 arithmetic
    and duplicate catalog rows everywhere."""
    base = rng.integers(-3, 4, size=(pool, r)).astype(np.float32)
    V = base[rng.integers(0, pool, ni)]
    U = rng.integers(-3, 4, size=(nu, r)).astype(np.float32)
    return U, V


def _reference(U, V, valid, k):
    s, i = chunked_topk_scores(jnp.asarray(U), jnp.asarray(V),
                               jnp.asarray(valid), k=k)
    return np.asarray(s), np.asarray(i)


# ---------------------------------------------------------------------------
# 1. in-kernel merge ring through topk_sharded


# Tier-1 keeps one non-pow2 count (3) and the full mesh width (8); the
# interior odd counts ride the slow tier (interpret-mode pallas is
# seconds per shard count on the 1-core CI box).
@pytest.mark.parametrize("n_shards", [
    3, pytest.param(5, marks=pytest.mark.slow),
    pytest.param(7, marks=pytest.mark.slow), 8])
def test_merge_ring_bitwise_on_ties_any_shard_count(rng, n_shards):
    # non-pow2 ring sizes included: the rotation schedule must not
    # assume a power-of-two neighborhood
    U, V = _tie_corpus(rng, 23, 90, 16)
    valid = rng.random(90) < 0.85
    ref_s, ref_i = _reference(U, V, valid, 6)
    s, ix = topk_sharded(U, V, 6, make_mesh(n_shards),
                         strategy="merge_ring", item_valid=valid)
    assert np.array_equal(np.asarray(s), ref_s)
    assert np.array_equal(np.asarray(ix), ref_i)


def test_merge_ring_all_invalid_shard(rng):
    # one shard contributes nothing: its candidate set is all sentinel
    # and must never displace a real candidate during the rotation
    U, V = _tie_corpus(rng, 11, 64, 8)
    valid = np.ones(64, bool)
    valid[16:24] = False           # shard 2 of 8 entirely masked
    ref_s, ref_i = _reference(U, V, valid, 5)
    s, ix = topk_sharded(U, V, 5, make_mesh(8), strategy="merge_ring",
                         item_valid=valid)
    assert np.array_equal(np.asarray(s), ref_s)
    assert np.array_equal(np.asarray(ix), ref_i)
    assert not np.isin(np.asarray(ix), np.arange(16, 24)).any()


def test_merge_ring_k_exceeds_shard(rng):
    # 8 shards x 2 rows: every shard's local k is smaller than the
    # requested k, so the answer only exists after the full rotation
    U, V = _tie_corpus(rng, 9, 16, 8)
    ref_s, ref_i = _reference(U, V, np.ones(16, bool), 5)
    s, ix = topk_sharded(U, V, 5, make_mesh(8), strategy="merge_ring")
    assert np.array_equal(np.asarray(s), ref_s)
    assert np.array_equal(np.asarray(ix), ref_i)


def test_serve_comm_audit_contract_is_registered():
    from tpu_als.analysis import contracts

    assert "serve_comm_audit" in contracts.names()
    res = contracts.verify("serve_comm_audit")
    assert res.ok, res
    assert "no XLA collectives" in res.detail


# ---------------------------------------------------------------------------
# 2. mesh-sharded int8 index


@pytest.fixture(scope="module")
def mesh8():
    return make_mesh(8)


def test_sharded_index_bitwise_vs_single_device(rng, mesh8):
    # distinct-score corpus: ids must match the single-device index
    # exactly, not merely point at equal scores
    Ni, r, k = 700, 32, 10
    V = rng.normal(size=(Ni, r)).astype(np.float32)
    U = rng.normal(size=(33, r)).astype(np.float32)
    valid = rng.random(Ni) < 0.9
    ref = build_index(V, item_valid=valid, shortlist_k=Ni)
    sh = build_sharded_index(V, mesh8, item_valid=valid, shortlist_k=Ni)
    assert isinstance(sh, ShardedInt8Index)
    assert isinstance(ref, Int8CandidateIndex)
    s0, i0 = ref.topk(jnp.asarray(U), k)
    s1, i1 = sh.topk(jnp.asarray(U), k)
    assert np.array_equal(np.asarray(s0), np.asarray(s1))
    assert np.array_equal(np.asarray(i0), np.asarray(i1))


def test_sharded_index_tie_scores_and_ids_verifiable(rng, mesh8):
    # ragged Ni (700 over 8 shards): scores bitwise vs chunked; each
    # returned id re-verified independently (ties make id equality
    # against a different tiebreak order meaningless)
    Ni, k = 700, 10
    U, V = _tie_corpus(rng, 21, Ni, 32, pool=11)
    valid = rng.random(Ni) < 0.9
    ref_s, _ = _reference(U, V, valid, k)
    sh = build_sharded_index(V, mesh8, item_valid=valid, shortlist_k=Ni)
    s, i = sh.topk(jnp.asarray(U), k)
    s, i = np.asarray(s), np.asarray(i)
    assert np.array_equal(s, ref_s)
    sc = U @ V.T
    hit = s > -3.0e38
    assert valid[i[hit]].all()
    assert np.array_equal(sc[np.nonzero(hit)[0], i[hit]], s[hit])


def test_sharded_index_delta_then_compact_bitwise(rng, mesh8):
    Ni, r, k = 700, 32, 10
    U, V = _tie_corpus(rng, 17, Ni, r, pool=11)
    valid = rng.random(Ni) < 0.9
    sh = build_sharded_index(V, mesh8, item_valid=valid, shortlist_k=Ni)
    touch = rng.choice(Ni, size=29, replace=False)
    app = np.arange(Ni, Ni + 4)    # appends, under capacity
    rows = np.concatenate([touch, app])
    newV = _tie_corpus(rng, 1, rows.size, r, pool=11)[1]
    newvalid = rng.random(rows.size) < 0.8
    d = sh.with_updates(rows, newV, newvalid, seq=1)
    assert isinstance(d, ShardedInt8Index)
    assert d.delta_count == rows.size and d.n_items == Ni + 4
    V2 = np.concatenate([V, np.zeros((4, r), np.float32)])
    valid2 = np.concatenate([valid, np.zeros(4, bool)])
    V2[rows], valid2[rows] = newV, newvalid
    ref_s, _ = _reference(U, V2, valid2, k)
    ds, _ = d.topk(jnp.asarray(U), k, shortlist_k=Ni + 4)
    assert np.array_equal(np.asarray(ds), ref_s)
    c = d.compact(seq=2)
    assert isinstance(c, ShardedInt8Index) and c.delta_count == 0
    cs, _ = c.topk(jnp.asarray(U), k, shortlist_k=Ni + 4)
    assert np.array_equal(np.asarray(cs), ref_s)


def test_sharded_index_retag_shares_device_arrays(rng, mesh8):
    _, V = _tie_corpus(rng, 1, 96, 8)
    sh = build_sharded_index(V, mesh8)
    t = sh.retag(5)
    assert isinstance(t, ShardedInt8Index) and t.seq == 5
    assert t.V is sh.V and t.Vq is sh.Vq and t.ni_loc == sh.ni_loc


def test_sharded_index_growth_past_capacity_rebuilds(rng, mesh8):
    _, V = _tie_corpus(rng, 1, 100, 8)
    sh = build_sharded_index(V, mesh8)
    big = np.arange(sh.n_items, sh.capacity + 13)
    g = sh.with_updates(big, _tie_corpus(rng, 1, big.size, 8)[1], seq=3)
    assert isinstance(g, ShardedInt8Index)
    assert g.n_items == sh.capacity + 13 and g.delta_count == 0
    assert g.capacity >= g.n_items
    with pytest.raises(ValueError, match="append gap"):
        sh.with_updates(np.asarray([sh.capacity + 2]),
                        np.zeros((1, 8), np.float32))


def test_sharded_index_all_invalid_and_sparse_valid(rng, mesh8):
    U, V = _tie_corpus(rng, 9, 200, 8)
    none, _ = build_sharded_index(
        V, mesh8, item_valid=np.zeros(200, bool),
        shortlist_k=200).topk(jnp.asarray(U), 5)
    assert np.all(np.asarray(none) <= -3.0e38)
    few = np.zeros(200, bool)
    few[[3, 101, 199]] = True      # k > valid count
    fs, _ = build_sharded_index(
        V, mesh8, item_valid=few, shortlist_k=200).topk(jnp.asarray(U), 5)
    ref_s, _ = _reference(U, V, few, 5)
    assert np.array_equal(np.asarray(fs), ref_s)


def test_sharded_index_residency(rng, mesh8):
    # the catalog is never committed whole to one device: every base
    # array spans all 8 shards with an ni_loc-row slice on each
    _, V = _tie_corpus(rng, 1, 700, 16)
    sh = build_sharded_index(V, mesh8)
    for arr in (sh.V, sh.Vq, sh.sv, sh.valid):
        assert len(arr.sharding.device_set) == 8
        assert arr.addressable_shards[0].data.shape[0] == sh.ni_loc


# ---------------------------------------------------------------------------
# 3. engine backend dispatch


def _drain(eng, payloads, **kw):
    tickets = [eng.submit(p, **kw) for p in payloads]
    while True:
        b = eng.batcher.next_batch(timeout=0.01)
        if b is None:
            break
        eng.serve_batch(b)
    return [t.result(timeout=10) for t in tickets]


@pytest.mark.parametrize("backend_kw", [
    {},
    dict(serve_backend="sharded"),
    dict(serve_backend="merge_ring"),
    dict(serve_backend="auto"),
], ids=["local", "sharded", "merge_ring", "auto"])
def test_engine_backends_bitwise(rng, mesh8, backend_kw):
    Nu, Ni, r, k = 40, 700, 32, 10
    U, V = _tie_corpus(rng, Nu, Ni, r, pool=11)
    valid = rng.random(Ni) < 0.9
    ref_s, ref_i = _reference(U, V, valid, k)
    kw = dict(mesh=mesh8, **backend_kw) if backend_kw else {}
    eng = ServingEngine(k=k, shortlist_k=Ni, buckets=(8, 32), **kw)
    eng.publish(U, V, item_valid=valid)
    eng.warmup()
    for u, (s, ix) in zip(range(20), _drain(eng, list(range(20)))):
        assert np.array_equal(ix, ref_i[u])
        assert np.array_equal(s, ref_s[u])
    # fold-in payload equal to a published row answers identically
    (s, ix), = _drain(eng, [U[7].copy()])
    assert np.array_equal(ix, ref_i[7])
    # per-request k trim slices the shared response buffer
    (s, ix), = _drain(eng, [3], k=4)
    assert s.shape == (4,) and np.array_equal(ix, ref_i[3, :4])


def test_engine_backend_validation(mesh8):
    with pytest.raises(ValueError, match="serve_backend"):
        ServingEngine(serve_backend="bogus")
    with pytest.raises(ValueError, match="mesh"):
        ServingEngine(serve_backend="sharded")   # mesh-less


def test_engine_backend_event_mesh_only(rng, mesh8):
    """``serving_backend`` fires once per MESH-backed engine with the
    resolved backend and shard count; mesh-less engines are local by
    construction and emit nothing (docs/observability.md)."""
    from tpu_als import obs

    U, V = _tie_corpus(rng, 8, 96, 16)
    reg = obs.reset()
    try:
        for eng in (ServingEngine(k=5, shortlist_k=96, buckets=(8,)),
                    ServingEngine(k=5, shortlist_k=96, buckets=(8,),
                                  mesh=mesh8, serve_backend="sharded")):
            eng.publish(U, V)
        ev = [e for e in reg._events if e["type"] == "serving_backend"]
        assert [(e["backend"], e["n_shards"]) for e in ev] == \
            [("sharded", 8)]
    finally:
        obs.reset()


@pytest.mark.parametrize("backend", ["sharded", "merge_ring"])
def test_engine_publish_update_modes_on_mesh(rng, mesh8, backend):
    Nu, Ni, r, k = 30, 700, 32, 10
    U, V = _tie_corpus(rng, Nu, Ni, r, pool=11)
    valid = rng.random(Ni) < 0.9
    eng = ServingEngine(k=k, shortlist_k=Ni, buckets=(8,),
                        mesh=mesh8, serve_backend=backend)
    eng.publish(U, V, item_valid=valid)
    _, mode = eng.publish_update(U, V, item_valid=valid)
    assert mode == "retag"
    V2 = V.copy()
    V2[[5, 600]] = _tie_corpus(rng, 1, 2, r, pool=11)[1]
    _, mode = eng.publish_update(U, V2, touched_items=[5, 600],
                                 item_valid=valid)
    assert mode == "delta"
    ref_s, ref_i = _reference(U, V2, valid, k)
    (s, ix), = _drain(eng, [11])
    assert np.array_equal(ix, ref_i[11]) and np.array_equal(s, ref_s[11])


@pytest.mark.parametrize("backend", ["sharded", "merge_ring"])
def test_engine_torn_publish_serves_fresh_catalog(rng, mesh8, backend):
    # a corrupt publish must never leave a stale shard answering: the
    # fabric handle is dropped and the exact path answers against the
    # FRESH host catalog
    Nu, Ni, r, k = 30, 700, 32, 10
    U, V = _tie_corpus(rng, Nu, Ni, r, pool=11)
    valid = rng.random(Ni) < 0.9
    V2 = V.copy()
    V2[[5, 600]] = _tie_corpus(rng, 1, 2, r, pool=11)[1]
    eng = ServingEngine(k=k, shortlist_k=Ni, buckets=(8,),
                        mesh=mesh8, serve_backend=backend)
    eng.publish(U, V, item_valid=valid)
    faults.install("serving.publish=corrupt")
    try:
        eng.publish(U, V2, item_valid=valid)
    finally:
        faults.clear()
    ref_s, ref_i = _reference(U, V2, valid, k)
    (s, ix), = _drain(eng, [11])
    assert np.array_equal(ix, ref_i[11]) and np.array_equal(s, ref_s[11])


def test_engine_score_fault_falls_back_exact(rng, mesh8):
    Nu, Ni, r, k = 20, 700, 32, 10
    U, V = _tie_corpus(rng, Nu, Ni, r, pool=11)
    valid = rng.random(Ni) < 0.9
    ref_s, ref_i = _reference(U, V, valid, k)
    eng = ServingEngine(k=k, shortlist_k=Ni, buckets=(8,),
                        mesh=mesh8, serve_backend="merge_ring")
    eng.publish(U, V, item_valid=valid)
    faults.install("serving.score=corrupt@every=1")
    try:
        (s, ix), = _drain(eng, [2])
    finally:
        faults.clear()
    assert np.array_equal(ix, ref_i[2]) and np.array_equal(s, ref_s[2])


def test_engine_pin_dropped_on_shape_changing_publish(rng):
    # distinct scores here: the truncated shortlist makes no tie-order
    # promise, and this test is about the pin lifecycle, not ties
    Nu, Ni, r, k = 20, 300, 16, 5
    U = rng.normal(size=(Nu, r)).astype(np.float32)
    V = rng.normal(size=(Ni, r)).astype(np.float32)
    eng = ServingEngine(k=k, shortlist_k=64, buckets=(8,))
    eng.publish(U, V)
    eng.warmup()
    assert (8, "int8") in eng._pinned and (8, "exact") in eng._pinned
    Vbig = np.concatenate(
        [V, rng.normal(size=(200, r)).astype(np.float32)])
    eng.publish(U, Vbig)               # shapes changed, pins now stale
    ref_s, ref_i = _reference(U, Vbig, np.ones(500, bool), k)
    (s, ix), = _drain(eng, [4])
    assert np.array_equal(ix, ref_i[4])
    assert (8, "int8") not in eng._pinned   # dropped, jit served


# ---------------------------------------------------------------------------
# 4. traffic-derived bucket ladder


def test_observed_ladder_is_pow2_quantiles():
    from tpu_als.plan import resolve_serving_buckets
    from tpu_als.plan.planner import _ladder_from_observed

    sizes = [3, 3, 4, 7, 9, 20, 20, 21, 40, 120]
    lad = resolve_serving_buckets(observed=sizes)
    assert lad == _ladder_from_observed(sizes)
    assert all(b & (b - 1) == 0 for b in lad)    # pow2 rungs
    assert lad[-1] == 128                        # covers the max
    assert lad == tuple(sorted(set(lad)))


def test_observed_ladder_empty_falls_back():
    from tpu_als.plan import resolve_serving_buckets
    from tpu_als.serving.batcher import DEFAULT_BUCKETS

    assert resolve_serving_buckets(observed=[]) == tuple(DEFAULT_BUCKETS)


def test_observed_ladder_banks_and_recalls(tmp_path, monkeypatch):
    from tpu_als import plan

    monkeypatch.setenv("TPU_ALS_PLAN_CACHE", str(tmp_path))
    plan.clear()
    try:
        lad = plan.resolve_serving_buckets(rank=16,
                                           observed=[3, 5, 60, 200])
        assert lad == (64, 256) or lad[-1] == 256
        # a later default resolution inherits the banked measured mix
        assert plan.resolve_serving_buckets(rank=16) == lad
    finally:
        plan.clear()
