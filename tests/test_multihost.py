"""Multi-host helpers on the single-process CPU mesh: the no-op init
contract and the shard-ownership math every host uses to block only its
local ratings."""

import numpy as np
import pytest

from tpu_als.parallel.data import partition_balanced
from tpu_als.parallel.mesh import make_mesh
from tpu_als.parallel.multihost import (
    init_distributed,
    local_positions,
    local_rating_mask,
)


def test_init_single_process_noop():
    idx, count = init_distributed()
    assert idx == 0
    assert count == 1


def test_local_positions_cover_whole_single_host_mesh():
    mesh = make_mesh(8)
    assert local_positions(mesh) == list(range(8))


def test_local_rating_mask_partitions_exactly():
    rng = np.random.default_rng(0)
    n_entities, nnz, D = 40, 500, 8
    rows = rng.integers(0, n_entities, nnz)
    part = partition_balanced(np.bincount(rows, minlength=n_entities), D)
    mesh = make_mesh(D)
    mask = local_rating_mask(part, rows, mesh)
    # single process owns every position -> mask is all-True
    assert mask.all()

    # two simulated processes (positions 0-3 and 4-7) through the real
    # function: every rating must land on exactly one process, and the
    # claimed ratings must be exactly those whose owner is in-range
    mask_a = local_rating_mask(part, rows, positions=range(0, 4))
    mask_b = local_rating_mask(part, rows, positions=range(4, 8))
    assert (mask_a ^ mask_b).all()
    np.testing.assert_array_equal(
        mask_a, np.isin(part.owner[rows], np.arange(0, 4)))


def test_positions_build_equals_slice_of_full_build(rng):
    # each host building only its shards (positions=) must produce
    # bit-identical arrays to slicing the full build — the agreement
    # contract that makes make_array_from_process_local_data assembly safe
    from tpu_als.parallel.data import shard_csr

    nU, nI, nnz, D = 60, 40, 900, 8
    u = rng.integers(0, nU, nnz)
    i = rng.integers(0, nI, nnz)
    r = rng.normal(size=nnz).astype(np.float32)
    ucounts = np.bincount(u, minlength=nU)
    upart = partition_balanced(ucounts, D)
    ipart = partition_balanced(np.bincount(i, minlength=nI), D)

    full = shard_csr(upart, ipart, u, i, r, min_width=4)
    for positions in ([0, 1, 2, 3], [4, 5, 6, 7], [2, 5]):
        msk = local_rating_mask(upart, u, positions=positions)
        part_build = shard_csr(upart, ipart, u[msk], i[msk], r[msk],
                               min_width=4, positions=positions,
                               row_counts=ucounts)
        assert len(part_build.buckets) == len(full.buckets)
        for bl, bf in zip(part_build.buckets, full.buckets):
            np.testing.assert_array_equal(bl.rows, bf.rows[positions])
            np.testing.assert_array_equal(bl.cols, bf.cols[positions])
            np.testing.assert_array_equal(bl.vals, bf.vals[positions])
            np.testing.assert_array_equal(bl.mask, bf.mask[positions])


def test_positions_without_counts_rejected(rng):
    from tpu_als.parallel.data import shard_csr

    u = rng.integers(0, 10, 50)
    i = rng.integers(0, 8, 50)
    r = np.ones(50, np.float32)
    upart = partition_balanced(np.bincount(u, minlength=10), 2)
    ipart = partition_balanced(np.bincount(i, minlength=8), 2)
    import pytest

    with pytest.raises(ValueError, match="row_counts"):
        shard_csr(upart, ipart, u, i, r, positions=[0])


def _spawn_two_procs(worker, env_extra, timeout=300):
    """Spawn two rendezvousing worker processes; return their outputs.
    Kills survivors on failure (a crashed peer leaves the other blocked
    in distributed init forever)."""
    import os
    import socket
    import subprocess
    import sys

    with socket.socket() as s:  # free port for the coordinator
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env.update(JAX_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
                   JAX_NUM_PROCESSES="2", JAX_PROCESS_ID=str(pid),
                   **env_extra)
        procs.append(subprocess.Popen(
            [sys.executable, worker], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    outs = []
    try:
        for p in procs:
            text, _ = p.communicate(timeout=timeout)
            outs.append(text)
            assert p.returncode == 0, text[-2000:]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return outs


@pytest.mark.slow
def test_two_process_sharded_step_matches_single_process(tmp_path):
    """REAL multi-process run: 2 spawned processes x 2 CPU devices, gloo
    collectives over a 4-device global mesh, per-host blocking — the
    result must match the same step on one process with all shards."""
    import os
    import socket
    import subprocess
    import sys

    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpu_als.core.als import AlsConfig, init_factors
    from tpu_als.parallel.data import shard_csr
    from tpu_als.parallel.mesh import AXIS
    from tpu_als.parallel.trainer import make_sharded_step

    worker = os.path.join(os.path.dirname(__file__), "_multihost_worker.py")
    out = str(tmp_path / "mh")
    _spawn_two_procs(worker, {"MH_OUT": out})

    # single-process reference: same data, all 4 shards on 4 local devices
    rng = np.random.default_rng(7)
    nU, nI, nnz, D = 50, 30, 600, 4
    u = rng.integers(0, nU, nnz)
    i = rng.integers(0, nI, nnz)
    r = np.abs(rng.normal(size=nnz)).astype(np.float32) + 0.1
    upart = partition_balanced(np.bincount(u, minlength=nU), D)
    ipart = partition_balanced(np.bincount(i, minlength=nI), D)
    ush = shard_csr(upart, ipart, u, i, r, min_width=4)
    ish = shard_csr(ipart, upart, i, u, r, min_width=4)
    mesh = make_mesh(D)
    leading = NamedSharding(mesh, P(AXIS))
    ub = jax.device_put(ush.device_buckets(), leading)
    ib = jax.device_put(ish.device_buckets(), leading)
    cfg = AlsConfig(rank=6, max_iter=2, reg_param=0.05, implicit_prefs=True,
                    alpha=3.0, seed=0)
    key = jax.random.PRNGKey(cfg.seed)
    ku, kv = jax.random.split(key)
    U0 = np.zeros((upart.padded_rows, cfg.rank), np.float32)
    U0[upart.slot] = np.asarray(init_factors(ku, nU, cfg.rank))
    V0 = np.zeros((ipart.padded_rows, cfg.rank), np.float32)
    V0[ipart.slot] = np.asarray(init_factors(kv, nI, cfg.rank))
    step = make_sharded_step(mesh, ush, ish, cfg)
    U1 = jax.device_put(jnp.asarray(U0), leading)
    V1 = jax.device_put(jnp.asarray(V0), leading)
    for _ in range(cfg.max_iter):
        U1, V1 = step(U1, V1, ub, ib)
    U1, V1 = np.asarray(U1), np.asarray(V1)

    rps_u, rps_i = upart.rows_per_shard, ipart.rows_per_shard
    seen = set()
    for pid in range(2):
        dat = np.load(f"{out}.{pid}.npz")
        for kname in dat.files:
            side, pos = kname[0], int(kname[1:])
            seen.add((side, pos))
            ref = (U1[pos * rps_u:(pos + 1) * rps_u] if side == "U"
                   else V1[pos * rps_i:(pos + 1) * rps_i])
            np.testing.assert_allclose(dat[kname], ref, rtol=2e-5,
                                       atol=2e-5, err_msg=kname)
    assert seen == {(s, p) for s in "UV" for p in range(4)}


@pytest.mark.slow
def test_two_process_cli_train(tmp_path):
    """The CLI's multi-process branch end-to-end: two spawned processes
    run the same `train` command; process 0 evaluates and saves a model
    the parent can load and serve."""
    import os
    import socket
    import subprocess
    import sys

    worker = os.path.join(os.path.dirname(__file__),
                          "_multihost_cli_worker.py")
    out_dir = str(tmp_path / "model")
    outs = _spawn_two_procs(worker, {"MH_OUT": out_dir})
    import json as _json

    rmse_lines = [ln for text in outs for ln in text.splitlines()
                  if ln.startswith("{") and "holdout_rmse" in ln]
    assert len(rmse_lines) == 1, outs  # only process 0 reports
    rmse = _json.loads(rmse_lines[0])["holdout_rmse"]
    assert 0.0 < rmse < 1.6, rmse  # synthetic stars std ~1.0

    from tpu_als import ALSModel
    from tpu_als.io.movielens import synthetic_movielens

    model = ALSModel.load(out_dir)
    frame = synthetic_movielens(120, 50, 3000, seed=0)
    preds = model.transform(frame)["prediction"]
    assert np.isfinite(preds).all() and len(preds) > 0



@pytest.mark.parametrize("strategy", ["all_gather", "ring",
                                      "all_to_all"])
@pytest.mark.slow
def test_two_process_estimator_fit_matches_single_process(tmp_path,
                                                          strategy):
    """Multi-process ALS.fit == single-process mesh fit, exactly the same
    partitions/init/layout — the Estimator-level multi-host contract,
    for both the all_gather and the ring (ppermute streaming) strategy."""
    import os
    import socket
    import subprocess
    import sys

    worker = os.path.join(os.path.dirname(__file__),
                          "_multihost_cli_worker.py")
    out = str(tmp_path / "fitout")
    _spawn_two_procs(worker, {
        "MH_OUT": out,
        "MH_MODE": {"all_gather": "fit", "ring": "fit_ring",
                    "all_to_all": "fit_a2a"}[strategy]})

    from tpu_als import ALS
    from tpu_als.io.movielens import synthetic_movielens
    from tpu_als.parallel.mesh import make_mesh

    if strategy == "all_to_all":
        from tpu_als.parallel.a2a import build_a2a
        from tpu_als.utils.frame import ColumnarFrame

        uu = np.repeat(np.arange(32), 4)
        ii = (np.arange(128) * 2) % 256
        rr = (1.0 + (np.arange(128) % 4)).astype(np.float32)
        frame = ColumnarFrame({"user": uu, "item": ii, "rating": rr})
        # the layout must actually exercise a2a: a degenerate plan would
        # silently fall back to all_gather and this test would be vacuous
        from tpu_als.core.ratings import remap_ids

        ud, _ = remap_ids(uu)
        id_, _ = remap_ids(ii)
        up = partition_balanced(np.bincount(ud), 4)
        ip = partition_balanced(np.bincount(id_), 4)
        assert not build_a2a(up, ip, ud, id_, rr,
                             on_degenerate="stub").degenerate
    else:
        frame = synthetic_movielens(100, 40, 2500, seed=1)
    ref = ALS(rank=4, maxIter=3, regParam=0.02, seed=0,
              mesh=make_mesh(4), gatherStrategy=strategy).fit(frame)
    dat = np.load(out + ".fit.npz")
    np.testing.assert_array_equal(dat["uids"], ref._user_map.ids)
    np.testing.assert_array_equal(dat["iids"], ref._item_map.ids)
    # cross-process collectives reorder reductions; 3 iterations compound
    # to ~1e-4 worst-case on f32
    np.testing.assert_allclose(dat["U"], ref._U, rtol=5e-4, atol=5e-4)
    np.testing.assert_allclose(dat["V"], ref._V, rtol=5e-4, atol=5e-4)


@pytest.mark.slow
def test_two_process_per_host_files_fit_matches_replicated(tmp_path):
    """dataMode='per_host': each worker writes and loads a DISJOINT csv
    (row-parity halves of one dataset), fit agrees the entity space via
    global_id_union and redistributes — the factors must match the
    single-process fit of the full data (VERDICT r2 weak #6).  The worker
    also asserts fitCallback fired on process 0 (and only there)."""
    import os

    worker = os.path.join(os.path.dirname(__file__),
                          "_multihost_cli_worker.py")
    out = str(tmp_path / "ph")
    _spawn_two_procs(worker, {"MH_OUT": out, "MH_MODE": "fit_perhost"})

    from tpu_als import ALS
    from tpu_als.io.movielens import synthetic_movielens

    full = synthetic_movielens(100, 40, 2500, seed=1)
    ref = ALS(rank=4, maxIter=3, regParam=0.02, seed=0,
              mesh=make_mesh(4)).fit(full)
    dat = np.load(out + ".fit.npz")
    np.testing.assert_array_equal(dat["uids"], ref._user_map.ids)
    np.testing.assert_array_equal(dat["iids"], ref._item_map.ids)
    # triple order differs after the redistribution; reductions reorder
    np.testing.assert_allclose(dat["U"], ref._U, rtol=5e-4, atol=5e-4)
    np.testing.assert_allclose(dat["V"], ref._V, rtol=5e-4, atol=5e-4)


@pytest.mark.slow
def test_two_process_cli_per_host_data(tmp_path):
    """`cli train --per-host-data --data csv:...part-{proc}.csv`: each
    process loads only its split; process 0 reports holdout RMSE and
    saves a model the parent can serve."""
    import json as _json
    import os

    worker = os.path.join(os.path.dirname(__file__),
                          "_multihost_cli_worker.py")
    out = str(tmp_path / "clip")
    outs = _spawn_two_procs(worker, {"MH_OUT": out,
                                     "MH_MODE": "cli_perhost"})
    rmse_lines = [ln for text in outs for ln in text.splitlines()
                  if ln.startswith("{") and "holdout_rmse" in ln]
    assert len(rmse_lines) == 1, outs  # process 0 only
    assert 0.0 < _json.loads(rmse_lines[0])["holdout_rmse"] < 2.0

    from tpu_als import ALSModel
    from tpu_als.io.movielens import synthetic_movielens

    model = ALSModel.load(out + ".model")
    frame = synthetic_movielens(90, 35, 2000, seed=4)
    preds = model.transform(frame)["prediction"]
    assert np.isfinite(preds).any() and len(preds) > 0


@pytest.mark.slow
def test_two_process_divergent_config_fails_fast(tmp_path):
    """A fit knob that differs across processes (here fitCallbackInterval)
    must raise the config-gate ValueError on every process instead of
    deadlocking inside a one-sided collective gather."""
    import os

    worker = os.path.join(os.path.dirname(__file__),
                          "_multihost_cli_worker.py")
    outs = _spawn_two_procs(worker, {"MH_OUT": str(tmp_path / "g"),
                                     "MH_MODE": "gate_diverge"},
                            timeout=180)
    for o in outs:
        assert "gate worker caught divergence" in o, o[-1500:]


@pytest.mark.slow
def test_two_process_divergent_gather_strategy_fails_fast(tmp_path):
    """gatherStrategy is the knob that picks WHICH collectives the step
    compiles (ring=ppermute vs all_gather) — a cross-process divergence
    with no observer knobs set must still hit the gate (advisor r3)."""
    import os

    worker = os.path.join(os.path.dirname(__file__),
                          "_multihost_cli_worker.py")
    outs = _spawn_two_procs(worker, {"MH_OUT": str(tmp_path / "gs"),
                                     "MH_MODE": "gate_diverge_strategy"},
                            timeout=180)
    for o in outs:
        assert "gate worker caught divergence" in o, o[-1500:]


@pytest.mark.slow
def test_two_process_nan_ratings_raise_on_every_host(tmp_path):
    """nan ratings on ONE host: the collective finite check must raise
    on BOTH processes instead of stranding the clean host in the next
    collective (code-review r4)."""
    import os

    worker = os.path.join(os.path.dirname(__file__),
                          "_multihost_cli_worker.py")
    outs = _spawn_two_procs(worker, {"MH_OUT": str(tmp_path / "nn"),
                                     "MH_MODE": "nan_ratings"},
                            timeout=180)
    for o in outs:
        assert "nan worker caught bad ratings" in o, o[-1500:]


def test_duplicated_split_detection_is_pairwise():
    from tpu_als.parallel.multihost import _split_signatures_duplicated

    # all distinct -> fine
    assert not _split_signatures_duplicated([[10, 1], [10, 2], [12, 3]])
    # ALL equal (the P=2 duplicated-load case) -> rejected
    assert _split_signatures_duplicated([[10, 1], [10, 1]])
    # P>2: only TWO of the rows collide — must still be rejected
    # (the old all-equal check passed this, advisor r3)
    assert _split_signatures_duplicated([[10, 1], [10, 1], [12, 3]])
    # several empty splits share the empty digest legitimately
    assert not _split_signatures_duplicated([[0, 5], [0, 5], [10, 1]])


def test_ring_local_slice_matches_full_grid(rng):
    from tpu_als.parallel.comm import shard_csr_grid

    nU, nI, nnz, D = 40, 30, 500, 8
    u = rng.integers(0, nU, nnz)
    i = rng.integers(0, nI, nnz)
    r = rng.normal(size=nnz).astype(np.float32)
    upart = partition_balanced(np.bincount(u, minlength=nU), D)
    ipart = partition_balanced(np.bincount(i, minlength=nI), D)
    full = shard_csr_grid(upart, ipart, u, i, r, min_width=4)
    loc = full.local_slice([2, 5, 7])
    assert loc.positions == (2, 5, 7)
    for bl, bf in zip(loc.buckets, full.buckets):
        np.testing.assert_array_equal(bl.rows, bf.rows[[2, 5, 7]])
        np.testing.assert_array_equal(bl.cols, bf.cols[[2, 5, 7]])


def test_ring_grid_positions_build_matches_slice(rng):
    # building only local owner rows (positions=) must equal slicing the
    # full grid — the multi-host shape-agreement contract for ring
    from tpu_als.parallel.comm import shard_csr_grid

    nU, nI, nnz, D = 40, 30, 500, 8
    u = rng.integers(0, nU, nnz)
    i = rng.integers(0, nI, nnz)
    r = rng.normal(size=nnz).astype(np.float32)
    upart = partition_balanced(np.bincount(u, minlength=nU), D)
    ipart = partition_balanced(np.bincount(i, minlength=nI), D)
    full = shard_csr_grid(upart, ipart, u, i, r, min_width=4)
    for pos in ([0, 1, 2, 3], [6, 7]):
        loc = shard_csr_grid(upart, ipart, u, i, r, min_width=4,
                             positions=pos)
        ref = full.local_slice(pos)
        assert loc.positions == tuple(pos)
        for bl, bf in zip(loc.buckets, ref.buckets):
            np.testing.assert_array_equal(bl.rows, bf.rows)
            np.testing.assert_array_equal(bl.cols, bf.cols)
            np.testing.assert_array_equal(bl.vals, bf.vals)
            np.testing.assert_array_equal(bl.mask, bf.mask)


def test_sharded_checkpoint_roundtrip(rng, tmp_path):
    """save_checkpoint_sharded + load_factors: per-position shard files
    must reassemble to exactly the entity-space factors a gather would
    produce, through the standard load path (same return contract as the
    replicated format)."""
    from tpu_als.core.als import AlsConfig
    from tpu_als.core.ratings import IdMap
    from tpu_als.io.checkpoint import load_factors
    from tpu_als.parallel.data import shard_csr
    from tpu_als.parallel.multihost import save_checkpoint_sharded
    from tpu_als.parallel.trainer import train_sharded

    nU, nI, nnz, D = 50, 30, 600, 8
    u = rng.integers(0, nU, nnz)
    i = rng.integers(0, nI, nnz)
    r = np.abs(rng.normal(size=nnz)).astype(np.float32) + 0.1
    upart = partition_balanced(np.bincount(u, minlength=nU), D)
    ipart = partition_balanced(np.bincount(i, minlength=nI), D)
    mesh = make_mesh(D)
    cfg = AlsConfig(rank=5, max_iter=2, reg_param=0.05, seed=0)
    Us, Vs = train_sharded(
        mesh, upart, ipart,
        shard_csr(upart, ipart, u, i, r, min_width=4),
        shard_csr(ipart, upart, i, u, r, min_width=4), cfg)

    user_map = IdMap(ids=np.arange(nU))
    item_map = IdMap(ids=np.arange(nI))
    path = str(tmp_path / "ck")
    save_checkpoint_sharded(path, Us, Vs, upart, ipart, user_map,
                            item_map, mesh, params={"regParam": 0.05},
                            iteration=2)
    manifest, uids, U, iids, V = load_factors(path)
    assert manifest["sharded"] and manifest["iteration"] == 2
    np.testing.assert_array_equal(uids, user_map.ids)
    np.testing.assert_allclose(U, np.asarray(Us)[upart.slot], rtol=0,
                               atol=0)
    np.testing.assert_allclose(V, np.asarray(Vs)[ipart.slot], rtol=0,
                               atol=0)
    # overwrite path: a second save must swap atomically, old removed
    # (this save also carries the serving-column params the model-load
    # check below needs)
    save_checkpoint_sharded(path, Us, Vs, upart, ipart, user_map,
                            item_map, mesh,
                            params={"userCol": "user", "itemCol": "item",
                                    "predictionCol": "prediction",
                                    "coldStartStrategy": "nan"},
                            iteration=3)
    manifest2, _, U2, _, _ = load_factors(path)
    assert manifest2["iteration"] == 3
    np.testing.assert_array_equal(U2, U)

    # a sharded checkpoint directory IS a loadable model (one format
    # serves resume and persistence, SURVEY §5.4)
    from tpu_als.api.estimator import ALSModel

    model = ALSModel.load(path)
    preds = model.transform({"user": u[:50], "item": i[:50]})["prediction"]
    # exact wiring check, not just finiteness: each prediction must be
    # the dot of the right user/item factor rows
    want = (np.asarray(Us)[upart.slot][u[:50]]
            * np.asarray(Vs)[ipart.slot][i[:50]]).sum(axis=1)
    np.testing.assert_allclose(np.asarray(preds), want, rtol=1e-5,
                               atol=1e-6)

    # crash window of atomic_install (old renamed aside, new not yet
    # installed): the sharded format must honor the same .old fallback
    # contract as the replicated one
    import os

    os.rename(path, path + ".old")
    manifest3, _, U3, _, _ = load_factors(path)
    assert manifest3["sharded"] and manifest3["iteration"] == 3
    np.testing.assert_array_equal(U3, U)


@pytest.mark.parametrize("mode", ["fit_ckpt", "fit_ckpt_sharded"])
@pytest.mark.slow
def test_two_process_checkpoint_resume(tmp_path, mode):
    """Multi-process fit writes checkpoints and a resumed run reproduces
    the uninterrupted one — for both formats: replicated (collective
    gather, process-0 write) and sharded (each process writes its own
    factor shards, NO cross-host factor bytes)."""
    import os

    worker = os.path.join(os.path.dirname(__file__),
                          "_multihost_cli_worker.py")
    out = str(tmp_path / "ck")
    _spawn_two_procs(worker, {"MH_OUT": out, "MH_MODE": mode})
    dat = np.load(out + ".ckpt.npz")
    np.testing.assert_allclose(dat["Ur"], dat["Us"], rtol=5e-4, atol=5e-4)
    np.testing.assert_allclose(dat["Vr"], dat["Vs"], rtol=5e-4, atol=5e-4)


@pytest.mark.slow
def test_two_process_sharded_serving_matches_single(tmp_path):
    """REAL multi-process serving: topk_sharded's all_gather AND ring
    collectives across two spawned gloo processes == the single-device
    chunked top-k (parallel/serve.py multi-process contract: global
    arrays back, shards read per host)."""
    import os

    import jax.numpy as jnp

    from tpu_als.ops.topk import chunked_topk_scores

    out = str(tmp_path / "serve")
    worker = os.path.join(os.path.dirname(__file__),
                          "_multihost_worker.py")
    _spawn_two_procs(worker, {"MH_OUT": out, "MH_MODE": "serve"})

    rng = np.random.default_rng(11)
    U = rng.normal(size=(24, 8)).astype(np.float32)
    V = rng.normal(size=(36, 8)).astype(np.float32)
    ref_s, ref_i = chunked_topk_scores(
        jnp.asarray(U), jnp.asarray(V), jnp.ones(36, bool), k=6)
    ref_s, ref_i = np.asarray(ref_s), np.asarray(ref_i)

    for strategy in ("all_gather", "ring"):
        got_s = np.full((24, 6), np.nan, np.float32)
        got_i = np.full((24, 6), -1, np.int64)
        for pid in range(2):
            z = np.load(f"{out}.{pid}.npz")
            for key in z.files:
                tag, strat, row0 = key.split("_")[0], key[2:].rsplit(
                    "_", 1)[0], int(key.rsplit("_", 1)[1])
                if strat != strategy:
                    continue
                block = z[key]
                if tag == "s":
                    got_s[row0:row0 + len(block)] = block
                else:
                    got_i[row0:row0 + len(block)] = block
        assert not np.isnan(got_s).any(), f"{strategy}: missing rows"
        np.testing.assert_allclose(got_s, ref_s, rtol=1e-5, atol=1e-6)
        np.testing.assert_array_equal(got_i, ref_i)


@pytest.mark.slow
def test_two_process_streaming_string_ingest_matches_single(tmp_path):
    """The whole config-3 flow across REAL processes: byte-range
    streaming ingest of a STRING-id csv per host, global_vocab_union to
    agree the entity space, train_multihost over gloo — the factors must
    equal a single-process fit of the whole file (SURVEY.md §6 row 3)."""
    import os

    from tpu_als.core.als import AlsConfig
    from tpu_als.parallel.multihost import train_multihost

    rng = np.random.default_rng(5)
    nU, nI, nnz = 40, 25, 500
    uu = rng.integers(0, nU, nnz)
    ii = rng.integers(0, nI, nnz)
    # half-star ratings: exact in float32, so the worker's strtof and
    # the reference's python-float path cannot differ by an ulp
    rr = (rng.integers(1, 10, nnz) / 2.0).astype(np.float32)
    lines = [f"user_{uu[k]:03d},B{ii[k]:04d},{rr[k]}" for k in range(nnz)]
    csv = tmp_path / "pod.csv"
    csv.write_text("\n".join(lines) + "\n")

    worker = os.path.join(os.path.dirname(__file__),
                          "_multihost_worker.py")
    out = str(tmp_path / "sv")
    _spawn_two_procs(worker, {"MH_OUT": out, "MH_MODE": "stream_vocab",
                              "MH_CSV": str(csv)})

    # single-process reference: trivial whole-file parse, same
    # (lexicographic) global id space, same trainer on a 4-device mesh
    g_ul = np.unique(np.array([f"user_{k:03d}" for k in uu], dtype="S"))
    g_il = np.unique(np.array([f"B{k:04d}" for k in ii], dtype="S"))
    u = np.searchsorted(g_ul, np.array(
        [f"user_{k:03d}" for k in uu], dtype="S"))
    i = np.searchsorted(g_il, np.array(
        [f"B{k:04d}" for k in ii], dtype="S"))
    cfg = AlsConfig(rank=4, max_iter=2, reg_param=0.05,
                    implicit_prefs=True, alpha=3.0, seed=0)
    U, V, upart, ipart = train_multihost(
        u, i, rr, len(g_ul), len(g_il), cfg, mesh=make_mesh(4),
        min_width=4)
    U, V = np.asarray(U), np.asarray(V)

    rps_u, rps_i = upart.rows_per_shard, ipart.rows_per_shard
    seen, rows_total = set(), 0
    for pid in range(2):
        dat = np.load(f"{out}.{pid}.npz")
        # both processes computed the identical global vocabularies
        np.testing.assert_array_equal(
            dat["g_ul"], g_ul.astype("S16"))
        np.testing.assert_array_equal(
            dat["g_il"], g_il.astype("S16"))
        rows_total += int(dat["rows"][0])
        for kname in dat.files:
            if kname[0] not in "UV" or not kname[1:].isdigit():
                continue
            side, pos = kname[0], int(kname[1:])
            seen.add((side, pos))
            ref = (U[pos * rps_u:(pos + 1) * rps_u] if side == "U"
                   else V[pos * rps_i:(pos + 1) * rps_i])
            np.testing.assert_allclose(dat[kname], ref, rtol=2e-5,
                                       atol=2e-5, err_msg=kname)
    assert rows_total == nnz  # every line landed on exactly one host
    assert seen == {(s, p) for s in "UV" for p in range(4)}


@pytest.mark.slow
def test_two_process_cli_stream_shared_file(tmp_path):
    """`cli train --per-host-data --data stream:one_shared.csv`: the
    config-3 one-liner — byte-range split of a single string-id file,
    collective vocab agreement, model + stream_labels sidecar saved."""
    import os

    rng = np.random.default_rng(9)
    nnz = 3000
    uu = rng.integers(0, 50, nnz)
    ii = rng.integers(0, 30, nnz)
    rr = (rng.integers(1, 10, nnz) / 2.0)
    csv = tmp_path / "shared.csv"
    with open(csv, "w") as f:
        f.write("user_id,parent_asin,rating,timestamp\n")
        for k in range(nnz):
            f.write(f"rev_{uu[k]:03d},B{ii[k]:04d},{rr[k]},160{k % 10}\n")

    worker = os.path.join(os.path.dirname(__file__),
                          "_multihost_cli_worker.py")
    out = str(tmp_path / "cls")
    outs = _spawn_two_procs(worker, {"MH_OUT": out,
                                     "MH_MODE": "cli_stream",
                                     "MH_CSV": str(csv)})
    assert any("cli stream worker done" in t for t in outs), outs

    from tpu_als import ALSModel

    model = ALSModel.load(out + ".model")
    side = np.load(out + ".model/stream_labels.npz")
    assert len(side["users"]) == 50 and len(side["items"]) == 30
    # dense ids in the model line up with the sorted label space
    assert sorted(side["users"].tolist()) == side["users"].tolist()
    preds = model.transform({
        "user": np.arange(10), "item": np.arange(10),
        "rating": np.ones(10, np.float32)})["prediction"]
    assert np.isfinite(np.asarray(preds, dtype=np.float64)).any()
