"""Multi-host helpers on the single-process CPU mesh: the no-op init
contract and the shard-ownership math every host uses to block only its
local ratings."""

import numpy as np

from tpu_als.parallel.data import partition_balanced
from tpu_als.parallel.mesh import make_mesh
from tpu_als.parallel.multihost import (
    init_distributed,
    local_positions,
    local_rating_mask,
)


def test_init_single_process_noop():
    idx, count = init_distributed()
    assert idx == 0
    assert count == 1


def test_local_positions_cover_whole_single_host_mesh():
    mesh = make_mesh(8)
    assert local_positions(mesh) == list(range(8))


def test_local_rating_mask_partitions_exactly():
    rng = np.random.default_rng(0)
    n_entities, nnz, D = 40, 500, 8
    rows = rng.integers(0, n_entities, nnz)
    part = partition_balanced(np.bincount(rows, minlength=n_entities), D)
    mesh = make_mesh(D)
    mask = local_rating_mask(part, rows, mesh)
    # single process owns every position -> mask is all-True
    assert mask.all()

    # two simulated processes (positions 0-3 and 4-7) through the real
    # function: every rating must land on exactly one process, and the
    # claimed ratings must be exactly those whose owner is in-range
    mask_a = local_rating_mask(part, rows, positions=range(0, 4))
    mask_b = local_rating_mask(part, rows, positions=range(4, 8))
    assert (mask_a ^ mask_b).all()
    np.testing.assert_array_equal(
        mask_a, np.isin(part.owner[rows], np.arange(0, 4)))
