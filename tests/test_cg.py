"""Warm-started conjugate-gradient solve (inexact ALS) — ops.solve.solve_cg.

The CG path replaces the exact per-row factorization (the measured 80% of
the on-chip iteration) with a few batched matvecs; these tests pin:

- convergence of the solver itself toward the exact solution;
- the cold-entity semantic (count 0 → factors exactly 0, even from a
  nonzero warm start);
- end-to-end inexact ALS: same held-out quality as exact ALS on the
  synthetic low-rank protocol (SURVEY.md §4.1), single-device and
  sharded, and via the Estimator's ``cgIters`` knob.
"""

import numpy as np
import pytest

from tpu_als.core.als import AlsConfig, predict, train
from tpu_als.core.ratings import build_csr_buckets
from tpu_als.ops.solve import solve_cg, solve_spd

from conftest import make_ratings


def _spd_batch(rng, n=64, r=16):
    M = rng.normal(size=(n, r, r)).astype(np.float32) / np.sqrt(r)
    A = M @ np.swapaxes(M, 1, 2) + 0.5 * np.eye(r, dtype=np.float32)
    b = rng.normal(size=(n, r)).astype(np.float32)
    return A, b


def test_cg_converges_to_exact(rng):
    import jax.numpy as jnp

    A, b = _spd_batch(rng)
    count = np.ones(len(b), np.float32)
    exact = np.asarray(solve_spd(jnp.asarray(A), jnp.asarray(b),
                                 jnp.asarray(count)))
    errs = []
    for iters in (2, 8, 32):
        x = np.asarray(solve_cg(jnp.asarray(A), jnp.asarray(b),
                                jnp.asarray(count), iters=iters))
        errs.append(np.abs(x - exact).max())
    assert errs[2] < 1e-3          # essentially exact at r iters
    assert errs[0] > errs[2]       # monotone improvement with iters


def test_cg_warm_start_accelerates(rng):
    import jax.numpy as jnp

    A, b = _spd_batch(rng)
    count = np.ones(len(b), np.float32)
    exact = np.asarray(solve_spd(jnp.asarray(A), jnp.asarray(b),
                                 jnp.asarray(count)))
    # warm start near the solution: 2 steps must beat 2 cold steps
    x0 = exact + 0.01 * rng.normal(size=exact.shape).astype(np.float32)
    warm = np.asarray(solve_cg(jnp.asarray(A), jnp.asarray(b),
                               jnp.asarray(count), x0=jnp.asarray(x0),
                               iters=2))
    cold = np.asarray(solve_cg(jnp.asarray(A), jnp.asarray(b),
                               jnp.asarray(count), iters=2))
    assert np.abs(warm - exact).max() < np.abs(cold - exact).max()


def test_cg_empty_rows_zero_from_nonzero_warm_start(rng):
    import jax.numpy as jnp

    A, b = _spd_batch(rng, n=8)
    count = np.zeros(8, np.float32)          # all rows empty
    b[:] = 0.0
    x0 = rng.normal(size=b.shape).astype(np.float32)
    x = np.asarray(solve_cg(jnp.asarray(A), jnp.asarray(b),
                            jnp.asarray(count), x0=jnp.asarray(x0),
                            iters=1))
    np.testing.assert_allclose(x, 0.0, atol=1e-6)


def _rmse(U, V, u, i, r):
    import jax.numpy as jnp

    ones = jnp.ones(len(u), bool)
    pred = np.asarray(predict(U, V, jnp.asarray(u), jnp.asarray(i),
                              ones, ones))
    return float(np.sqrt(np.mean((pred - r) ** 2)))


@pytest.mark.parametrize("implicit", [False, True])
def test_inexact_als_matches_exact_quality(rng, implicit):
    u, i, r, Ustar, Vstar = make_ratings(rng, 80, 50, rank=4, density=0.3,
                                         noise=0.05)
    if implicit:
        r = np.abs(r) * 4 + 0.1
    kw = dict(rank=4, max_iter=10, reg_param=0.01,
              implicit_prefs=implicit, alpha=8.0, seed=0)
    ucsr = build_csr_buckets(u, i, r, 80)
    icsr = build_csr_buckets(i, u, r, 50)
    Ue, Ve = train(ucsr, icsr, AlsConfig(**kw))
    Uc, Vc = train(ucsr, icsr, AlsConfig(**kw, cg_iters=3))
    if implicit:
        # trajectories differ pointwise at few CG steps (inexact ALS);
        # what must match is the thing being minimized — the HKV
        # objective (confidence-weighted preference loss + weighted-λ
        # ridge, dense form over all pairs)
        def objective(U, V):
            U, V = np.asarray(U), np.asarray(V)
            S = U @ V.T
            obj = (S ** 2).sum()                  # c=1, p=0 everywhere
            c = 1 + kw["alpha"] * np.abs(r)
            s = S[u, i]
            obj += (c * (1 - s) ** 2 - s ** 2).sum()   # observed upgrade
            nu = np.bincount(u, weights=r > 0, minlength=U.shape[0])
            ni = np.bincount(i, weights=r > 0, minlength=V.shape[0])
            obj += kw["reg_param"] * ((nu[:, None] * U ** 2).sum()
                                      + (ni[:, None] * V ** 2).sum())
            return obj

        assert objective(Uc, Vc) < objective(Ue, Ve) * 1.02
    else:
        rmse_e = _rmse(Ue, Ve, u, i, r)
        rmse_c = _rmse(Uc, Vc, u, i, r)
        # inexact ALS must land at the same quality level as exact
        assert rmse_c < rmse_e * 1.05 + 5e-3


@pytest.mark.parametrize("implicit", [False, True])
def test_matfree_unit_matches_dense_operator(rng, implicit):
    """solve_cg_matfree on raw padded-CSR chunks vs solve_cg on the
    normal-equation tensor built from the SAME data — identical Krylov
    trajectory (same operator, preconditioner, warm start, iterations),
    at an odd width ≫ rank with ragged masks."""
    import jax.numpy as jnp

    from tpu_als.ops.solve import (
        normal_eq_explicit, normal_eq_implicit, solve_cg_matfree)

    n, w, r = 40, 48, 8
    Vg = rng.normal(size=(n, w, r)).astype(np.float32) / np.sqrt(r)
    lens = rng.integers(0, w + 1, n)
    lens[:3] = 0                                     # some empty rows
    mask = (np.arange(w)[None, :] < lens[:, None]).astype(np.float32)
    vals = (rng.uniform(0.5, 5.0, (n, w)).astype(np.float32) * mask)
    x0 = rng.normal(size=(n, r)).astype(np.float32)
    reg, alpha = 0.03, 6.0
    YtY = None
    if implicit:
        M = rng.normal(size=(64, r)).astype(np.float32)
        YtY = jnp.asarray(M.T @ M / 64)

    if implicit:
        A, b, count = normal_eq_implicit(
            jnp.asarray(Vg), jnp.asarray(vals), jnp.asarray(mask), reg,
            alpha, YtY)
    else:
        A, b, count = normal_eq_explicit(
            jnp.asarray(Vg), jnp.asarray(vals), jnp.asarray(mask), reg)
    dense = np.asarray(solve_cg(A, b, count, x0=jnp.asarray(x0), iters=4))
    mf = np.asarray(solve_cg_matfree(
        jnp.asarray(Vg), jnp.asarray(vals), jnp.asarray(mask), reg,
        implicit=implicit, alpha=alpha, YtY=YtY, x0=jnp.asarray(x0),
        iters=4))
    np.testing.assert_allclose(mf, dense, rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(mf[:3], 0.0, atol=1e-6)  # empty rows


@pytest.mark.parametrize("implicit", [False, True])
def test_matfree_equals_dense_cg(rng, implicit):
    """The matrix-free half-step applies the SAME operator the dense path
    builds — at equal iterations and warm starts the two Krylov
    trajectories coincide (to fp reordering), so whole trainings must
    agree pointwise."""
    u, i, r, _, _ = make_ratings(rng, 70, 40, rank=4, density=0.3,
                                 noise=0.05)
    if implicit:
        r = np.abs(r) * 4 + 0.1
    kw = dict(rank=6, max_iter=6, reg_param=0.01,
              implicit_prefs=implicit, alpha=8.0, seed=0, cg_iters=3)
    ucsr = build_csr_buckets(u, i, r, 70)
    icsr = build_csr_buckets(i, u, r, 40)
    Um, Vm = train(ucsr, icsr, AlsConfig(**kw, cg_mode="matfree"))
    Ud, Vd = train(ucsr, icsr, AlsConfig(**kw, cg_mode="dense"))
    np.testing.assert_allclose(np.asarray(Um), np.asarray(Ud),
                               rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(np.asarray(Vm), np.asarray(Vd),
                               rtol=2e-3, atol=2e-3)


def test_matfree_bf16_quality_tracks_f32(rng):
    """The sweep's bf16+cg entry runs matfree with a bfloat16 Vg: only
    the big gathered tensor narrows — every Krylov intermediate stays
    f32 — so training quality must track the f32 run closely."""
    u, i, r, _, _ = make_ratings(rng, 80, 50, rank=4, density=0.3,
                                 noise=0.05)
    kw = dict(rank=4, max_iter=8, reg_param=0.01, seed=0, cg_iters=2)
    ucsr = build_csr_buckets(u, i, r, 80)
    icsr = build_csr_buckets(i, u, r, 50)
    Uf, Vf = train(ucsr, icsr, AlsConfig(**kw, compute_dtype="float32"))
    Ub, Vb = train(ucsr, icsr, AlsConfig(**kw, compute_dtype="bfloat16"))
    rmse_f = _rmse(Uf, Vf, u, i, r)
    rmse_b = _rmse(Ub, Vb, u, i, r)
    assert rmse_b < rmse_f * 1.1 + 1e-2, (rmse_f, rmse_b)


def test_inexact_als_sharded_matches_single_device(rng):
    import jax

    from tpu_als.parallel.data import partition_balanced, shard_csr
    from tpu_als.parallel.mesh import make_mesh
    from tpu_als.parallel.trainer import train_sharded

    u, i, r, _, _ = make_ratings(np.random.default_rng(4), 60, 45,
                                 rank=3, density=0.4)
    cfg = AlsConfig(rank=4, max_iter=4, reg_param=0.05, seed=9, cg_iters=3)
    ucsr = build_csr_buckets(u, i, r, 60, min_width=4)
    icsr = build_csr_buckets(i, u, r, 45, min_width=4)
    U1, V1 = train(ucsr, icsr, cfg)

    D = 8
    upart = partition_balanced(np.bincount(u, minlength=60), D)
    ipart = partition_balanced(np.bincount(i, minlength=45), D)
    Us, Vs = train_sharded(
        make_mesh(D), upart, ipart,
        shard_csr(upart, ipart, u, i, r, min_width=4),
        shard_csr(ipart, upart, i, u, r, min_width=4), cfg)
    # same math, different reduction orders/warm-start row layouts
    np.testing.assert_allclose(np.asarray(Us)[upart.slot], np.asarray(U1),
                               rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(np.asarray(Vs)[ipart.slot], np.asarray(V1),
                               rtol=2e-3, atol=2e-3)


def test_cg_knobs_persist_and_gate_resume(rng, tmp_path):
    """cgIters/cgMode travel with estimator saves, and a resume that
    switches solver (inexact -> exact) is rejected — the trajectory the
    checkpoint froze cannot be reproduced by a different solver."""
    import os

    from tpu_als.api.estimator import ALS
    from tpu_als.utils.frame import ColumnarFrame

    u, i, r, _, _ = make_ratings(rng, 50, 30, rank=3, density=0.4)
    frame = ColumnarFrame({"user": u, "item": i, "rating": r})

    est_dir = str(tmp_path / "est")
    ALS(rank=3, maxIter=4, cgIters=2, cgMode="dense").save(est_dir)
    got = ALS.load(est_dir)
    assert got.cgIters == 2 and got.cgMode == "dense"

    ck = str(tmp_path / "ck")
    ALS(rank=3, maxIter=2, cgIters=2, checkpointDir=ck,
        checkpointInterval=2, seed=0).fit(frame)
    with pytest.raises(ValueError, match="cgIters"):
        ALS(rank=3, maxIter=4, cgIters=0, seed=0,
            resumeFrom=os.path.join(ck, "als_checkpoint")).fit(frame)


def test_estimator_cg_knob(rng):
    from tpu_als.api.estimator import ALS
    from tpu_als.utils.frame import ColumnarFrame

    u, i, r, _, _ = make_ratings(rng, 50, 30, rank=3, density=0.4,
                                 noise=0.05)
    frame = ColumnarFrame({"user": u, "item": i, "rating": r})
    exact = ALS(rank=3, maxIter=8, regParam=0.01, seed=1).fit(frame)
    inexact = ALS(rank=3, maxIter=8, regParam=0.01, seed=1,
                  cgIters=3).fit(frame)
    pe = np.asarray(exact.transform(frame)["prediction"])
    pc = np.asarray(inexact.transform(frame)["prediction"])
    rmse_e = float(np.sqrt(np.mean((pe - r) ** 2)))
    rmse_c = float(np.sqrt(np.mean((pc - r) ** 2)))
    assert rmse_c < rmse_e * 1.05 + 5e-3
