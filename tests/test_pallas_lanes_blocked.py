"""Blocked out-of-core lanes Cholesky (ranks > 128 — the rank-256
config-3 solve path, VERDICT r3 #4) vs dense references, in interpret
mode on the CPU test mesh; the same kernel compiles for real on TPU and
is A/B-timed against pallas_solve by scripts/rank256_proxy.py."""

import numpy as np
import jax.numpy as jnp
import pytest

from tpu_als.ops.pallas_lanes_blocked import (
    LANES,
    chol_lanes_blocked,
    spd_solve_lanes_blocked,
    supported_rank,
)

pytestmark = pytest.mark.slow


def _spd_problem(rng, N, r):
    M = rng.normal(size=(N, r, r)).astype(np.float32) / np.sqrt(r)
    A = M @ M.transpose(0, 2, 1) + 0.5 * np.eye(r, dtype=np.float32)
    b = rng.normal(size=(N, r)).astype(np.float32)
    return jnp.asarray(A), jnp.asarray(b)


@pytest.mark.parametrize("N,r", [
    (6, 256),          # two 128-blocks, one lane group (batch-padded)
    (5, 200),          # rank pads 200 -> 256, identity-padded tail
    (4, 384),          # three blocks: exercises the m<k streamed loops
])
def test_factor_matches_numpy_cholesky(rng, N, r):
    A, _ = _spd_problem(rng, N, r)
    L = np.asarray(chol_lanes_blocked(A, interpret=True))
    Lref = np.linalg.cholesky(np.asarray(A, np.float64))
    denom = np.abs(Lref).max()
    assert np.abs(L - Lref).max() / denom < 1e-4
    # strictly lower-triangular output (upper blocks zeroed)
    assert np.triu(L, 1).max() == 0.0


# the two-lane-group case lives here (solve covers the factor too), so
# multi-group is exercised once instead of in both parametrizations —
# interpret-mode minutes are the suite's scarce resource.  (5, 200)
# stays: the identity-padded 200->256 tail must flow through the
# substitutions end-to-end, not only through the factor.
@pytest.mark.parametrize("N,r", [(LANES + 2, 256), (5, 200)])
def test_solve_matches_dense(rng, N, r):
    A, b = _spd_problem(rng, N, r)
    x = np.asarray(spd_solve_lanes_blocked(A, b, interpret=True))
    ref = np.linalg.solve(np.asarray(A, np.float64),
                          np.asarray(b, np.float64)[..., None])[..., 0]
    denom = max(1.0, np.abs(ref).max())
    assert np.abs(x - ref).max() / denom < 1e-3


def test_mxu_fused_outer_agrees(rng):
    # the MXU trailing-update variant (rank-k dot_general over the
    # streamed panels) must reproduce the VPU sweep's factorization at
    # a multi-block rank, and selection must stay conservative off-TPU
    from tpu_als.ops.pallas_lanes_blocked import selected_mxu

    A, b = _spd_problem(rng, 4, 256)
    x_vpu = np.asarray(spd_solve_lanes_blocked(A, b, mxu=False,
                                               interpret=True))
    x_mxu = np.asarray(spd_solve_lanes_blocked(A, b, mxu=True,
                                               interpret=True))
    ref = np.linalg.solve(np.asarray(A, np.float64),
                          np.asarray(b, np.float64)[..., None])[..., 0]
    denom = max(1.0, np.abs(ref).max())
    assert np.abs(x_mxu - ref).max() / denom < 1e-3
    np.testing.assert_allclose(x_mxu, x_vpu, atol=1e-3, rtol=1e-2)
    assert selected_mxu(256) is False  # no probe has validated it here


def test_panel_width_agrees(rng):
    # panel=4 must reproduce the default panel=8 math (same blocked
    # factorization, different streaming granularity)
    A, _ = _spd_problem(rng, 4, 256)
    L8 = np.asarray(chol_lanes_blocked(A, interpret=True))
    L4 = np.asarray(chol_lanes_blocked(A, panel=4, interpret=True))
    np.testing.assert_allclose(L4, L8, rtol=1e-5, atol=1e-6)


def test_bad_panel_rejected(rng):
    A, _ = _spd_problem(rng, 4, 256)
    with pytest.raises(ValueError, match="must divide"):
        chol_lanes_blocked(A, panel=7, interpret=True)


def test_supported_rank_partition():
    # the flat lanes kernel owns <= 128; blocked owns everything above —
    # together they cover every rank with no overlap
    from tpu_als.ops.pallas_lanes import supported_rank as flat_ok

    for r in (8, 64, 128, 129, 200, 256, 384, 512):
        assert supported_rank(r) != flat_ok(r), r


def test_solve_spd_dispatch_accepts_lanes_blocked(rng):
    # forced-backend path exists; off-TPU the kernel itself cannot run,
    # so only the backend-name validation is checked here (the real
    # dispatch is exercised on chip by rank256_proxy)
    from tpu_als.ops.solve import solve_spd

    A, b = _spd_problem(rng, 4, 16)
    with pytest.raises(ValueError, match="unknown solve backend"):
        solve_spd(A, b, jnp.ones(4), backend="nope")


def test_cold_rows_solve_to_zero(rng):
    # solve_spd contract at rank 256: count == 0 rows -> x == 0 exactly
    # (A replaced by I, b stays 0) — through the blocked kernel's
    # factor+substitution path in interpret mode
    N, r = 4, 256
    A, _ = _spd_problem(rng, N, r)
    b = jnp.zeros((N, r), jnp.float32)
    eye = jnp.eye(r, dtype=jnp.float32)
    Ar = jnp.where(jnp.zeros((N, 1, 1)) > 0, A, eye) + 1e-6 * eye
    x = np.asarray(spd_solve_lanes_blocked(Ar, b, interpret=True))
    assert np.abs(x).max() == 0.0
