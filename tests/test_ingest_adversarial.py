"""Adversarial ingest robustness (SURVEY.md §2.A1; VERDICT r3 #8): the
native fastcsv parser against a pure-Python oracle on hostile inputs —
agreement byte-for-byte where the input is legal, a CLEAN error where it
is not (never a silently zero-filled or nan row entering training) — plus
hostile layouts through the native bucketizer vs the numpy blocking path.
"""

import numpy as np
import pytest

from tpu_als.io.fastcsv import load_ratings_csv


def _oracle(text, delim=",", skip_header=1):
    """Python-int/float parse — exact for full-int64 ids (the numpy
    float64 fallback is NOT, above 2^53)."""
    rows = []
    for k, ln in enumerate(text.split("\n")):
        if k < skip_header:
            continue
        ln = ln.rstrip("\r").rstrip(" ")
        if not ln:
            continue
        u, i, r, t = ln.split(delim)
        rows.append((int(u), int(i), float(r), int(t)))
    u = np.array([r[0] for r in rows], np.int64)
    i = np.array([r[1] for r in rows], np.int64)
    r_ = np.array([r[2] for r in rows], np.float32)
    t = np.array([r[3] for r in rows], np.int64)
    return u, i, r_, t


def _check_agreement(tmp_path, text, delim=",", skip_header=1):
    p = tmp_path / "ratings.csv"
    p.write_bytes(text.encode())
    got = load_ratings_csv(str(p), delim=delim, skip_header=skip_header)
    want = _oracle(text, delim, skip_header)
    for g, w, name in zip(got, want, ("user", "item", "rating", "ts")):
        np.testing.assert_array_equal(g, w, err_msg=name)
    return got


HEADER = "userId,movieId,rating,timestamp\n"


def test_crlf_line_endings(tmp_path):
    text = HEADER.replace("\n", "\r\n") + \
        "1,10,3.5,100\r\n2,20,4.0,200\r\n3,30,0.5,300\r\n"
    u, i, r, t = _check_agreement(tmp_path, text)
    assert len(u) == 3 and r[1] == np.float32(4.0)


def test_missing_final_newline(tmp_path):
    text = HEADER + "1,10,3.5,100\n2,20,4.0,200"
    u, _, _, t = _check_agreement(tmp_path, text)
    assert len(u) == 2 and t[-1] == 200


def test_scientific_notation_and_negative_ratings(tmp_path):
    text = HEADER + "1,10,4.5e-1,100\n2,20,-1.25E2,200\n3,30,.5,300\n"
    _, _, r, _ = _check_agreement(tmp_path, text)
    np.testing.assert_array_equal(
        r, np.array([0.45, -125.0, 0.5], np.float32))


def test_full_int64_ids_exact(tmp_path):
    # ids above 2^53: the numpy float64 fallback rounds these; the
    # native parser must carry them exactly
    big = (1 << 53) + 1
    text = HEADER + f"{big},10,3.0,100\n{big + 2},{big + 4},4.0,{big}\n"
    u, i, _, t = _check_agreement(tmp_path, text)
    assert u[0] == big and u[1] == big + 2
    assert i[1] == big + 4 and t[1] == big
    # and the float64 path would NOT have preserved them
    assert int(np.float64(big)) != big


def test_blank_lines_skipped(tmp_path):
    text = HEADER + "1,10,3.5,100\n\n2,20,4.0,200\n\r\n\n3,30,1.0,300\n\n"
    u, _, _, _ = _check_agreement(tmp_path, text)
    assert len(u) == 3


def test_trailing_spaces_tolerated(tmp_path):
    text = HEADER + "1,10,3.5,100  \n2,20,4.0,200\n"
    u, _, _, _ = _check_agreement(tmp_path, text)
    assert len(u) == 2


def test_tab_delimited_u_data_with_crlf(tmp_path):
    text = "1\t10\t3\t100\r\n2\t20\t4\t200\r\n"
    p = tmp_path / "u.data"
    p.write_bytes(text.encode())
    from tpu_als.io.fastcsv import load_u_data

    u, i, r, t = load_u_data(str(p))
    np.testing.assert_array_equal(u, [1, 2])
    np.testing.assert_array_equal(r, np.array([3, 4], np.float32))


def test_quoted_fields_raise_cleanly(tmp_path):
    p = tmp_path / "q.csv"
    p.write_text(HEADER + '"1","10","3.5","100"\n')
    with pytest.raises(ValueError, match="malformed ratings line"):
        load_ratings_csv(str(p))


def test_truncated_line_raises_cleanly(tmp_path):
    p = tmp_path / "t.csv"
    p.write_text(HEADER + "1,10,3.5,100\n2,20\n3,30,1.0,300\n")
    with pytest.raises(ValueError, match="malformed ratings line"):
        load_ratings_csv(str(p))


def test_extra_columns_raise_cleanly(tmp_path):
    p = tmp_path / "x.csv"
    p.write_text(HEADER + "1,10,3.5,100,999\n")
    with pytest.raises(ValueError, match="malformed ratings line"):
        load_ratings_csv(str(p))


def test_non_numeric_field_raises_cleanly(tmp_path):
    p = tmp_path / "n.csv"
    p.write_text(HEADER + "1,ten,3.5,100\n")
    with pytest.raises(ValueError, match="malformed ratings line"):
        load_ratings_csv(str(p))


def test_wrong_delimiter_raises_cleanly(tmp_path):
    p = tmp_path / "d.csv"
    p.write_text(HEADER + "1;10;3.5;100\n")
    with pytest.raises(ValueError, match="malformed ratings line"):
        load_ratings_csv(str(p))


def test_nan_inf_ratings_raise_cleanly(tmp_path):
    # strtof accepts 'nan'/'inf' spellings — the parser must not let a
    # non-finite rating poison the factor accumulation (code-review r4)
    for bad in ("nan", "inf", "-inf", "1e40"):
        p = tmp_path / "f.csv"
        p.write_text(HEADER + f"1,10,{bad},100\n")
        with pytest.raises(ValueError, match="malformed ratings line"):
            load_ratings_csv(str(p))


def test_int64_overflow_raises_cleanly(tmp_path):
    # an id beyond int64 would clamp to INT64_MAX and merge distinct
    # entities — must be a clean error, not silent corruption
    p = tmp_path / "o.csv"
    p.write_text(HEADER + "99999999999999999999999,10,3.5,100\n")
    with pytest.raises(ValueError, match="malformed ratings line"):
        load_ratings_csv(str(p))
    # float underflow in the rating is LEGAL (errno ERANGE from strtof
    # must not leak into the timestamp's overflow check)
    p.write_text(HEADER + "1,10,1e-50,100\n")
    u, _, r, _ = load_ratings_csv(str(p))
    assert len(u) == 1 and abs(float(r[0])) < 1e-30


def test_empty_file_and_header_only(tmp_path):
    p = tmp_path / "e.csv"
    p.write_text("")
    u, i, r, t = load_ratings_csv(str(p))
    assert len(u) == len(i) == len(r) == len(t) == 0
    p.write_text(HEADER)
    u, _, _, _ = load_ratings_csv(str(p))
    assert len(u) == 0


def test_page_multiple_sized_file(tmp_path):
    # exactly PAGESIZE bytes with no trailing newline: the heap-copy
    # path must engage (an mmap would end at the page boundary mid-field)
    import mmap as _mmap

    row = "7,8,1.5,9\n"
    n_pad = _mmap.PAGESIZE - len(HEADER) - len(row) + 1
    assert n_pad > 0
    filler_count = n_pad // len(row)
    rem = n_pad - filler_count * len(row)
    text = (HEADER + row * filler_count
            + "1" * rem + ",2,3.5,4\n")[:-1]  # strip final newline
    text = text + "9" * (_mmap.PAGESIZE - len(text))
    assert len(text) == _mmap.PAGESIZE
    p = tmp_path / "page.csv"
    p.write_bytes(text.encode())
    got = load_ratings_csv(str(p))
    want = _oracle(text)
    for g, w in zip(got, want):
        np.testing.assert_array_equal(g, w)


def test_malformed_content_does_not_fall_back_to_numpy(tmp_path):
    # io.movielens falls back to genfromtxt on OSError (build problems);
    # malformed CONTENT must propagate as ValueError instead — the
    # fallback would silently parse quoted rows as nan
    from tpu_als.io.movielens import load_movielens_csv

    p = tmp_path / "bad.csv"
    p.write_text(HEADER + '"1","10","3.5","100"\n')
    with pytest.raises(ValueError, match="malformed ratings line"):
        load_movielens_csv(str(p))


# ---- hostile layouts through the native bucketizer ------------------


def test_bucketizer_single_mega_row(rng):
    # one entity holds EVERY rating (the pathological power-law tail):
    # native and numpy blocking must agree bit-for-bit
    from tpu_als.core.ratings import build_csr_buckets
    from tpu_als.io import fastbucket

    if not fastbucket.available():
        pytest.skip("native bucketizer unavailable")
    nnz = 4096
    rows = np.zeros(nnz, np.int64)
    cols = rng.integers(0, 50, nnz)
    vals = rng.normal(size=nnz).astype(np.float32)
    a = build_csr_buckets(rows, cols, vals, 3, native=False)
    b = build_csr_buckets(rows, cols, vals, 3, native=True)
    assert a.nnz == b.nnz
    for ba, bb in zip(a.buckets, b.buckets):
        np.testing.assert_array_equal(ba.rows, bb.rows)
        np.testing.assert_array_equal(ba.cols, bb.cols)
        np.testing.assert_array_equal(ba.vals, bb.vals)
        np.testing.assert_array_equal(ba.mask, bb.mask)


def test_bucketizer_boundary_ids(rng):
    # ids exactly at num_rows-1 and 0, many empty entities between:
    # native == numpy, and only the two rated entities appear
    from tpu_als.core.ratings import build_csr_buckets
    from tpu_als.io import fastbucket

    if not fastbucket.available():
        pytest.skip("native bucketizer unavailable")
    num_rows = 1000
    rows = np.array([0, num_rows - 1, 0, num_rows - 1], np.int64)
    cols = np.array([1, 2, 3, 4], np.int64)
    vals = np.ones(4, np.float32)
    a = build_csr_buckets(rows, cols, vals, num_rows, native=False)
    b = build_csr_buckets(rows, cols, vals, num_rows, native=True)
    for x, y in zip(a.buckets, b.buckets):
        np.testing.assert_array_equal(x.rows, y.rows)
        np.testing.assert_array_equal(x.cols, y.cols)
    flat_rows = np.concatenate([bk.rows for bk in b.buckets])
    assert set(flat_rows[flat_rows < num_rows]) == {0, num_rows - 1}
