"""The NE-build byte models (perf.roofline: einsum_ne_build_bytes /
fused_ne_kernel_bytes — the CLI's roofline stages) validated against the
bytes the TRACED BUILDS actually move, counted from their jaxprs
(perf.ne_audit) — the test_comm_audit.py pattern applied to HBM traffic.

Three discrete, unfusable facts are pinned exactly:
- the einsum path's jaxpr materializes ``Vg = V[cols]`` (a gather writing
  n·w·r·db bytes — the tensor the fused kernel is built to delete),
- the gather-fused path's jaxpr contains NO HBM gather at all,
- the fused kernel's embedded CostEstimate equals fused_ne_kernel_bytes
  at the kernel's padded shapes,
plus the headline acceptance bound: the modeled NE-build bytes drop >=40%
at the BASELINE.md row-2 config when ne_path flips to gather_fused."""

import numpy as np
import pytest

import jax.numpy as jnp

from tpu_als.ops.pallas_gather_ne import (
    _tiles,
    gather_normal_eq_explicit,
    gather_normal_eq_implicit,
)
from tpu_als.ops.solve import normal_eq_explicit, normal_eq_implicit
from tpu_als.perf.ne_audit import gather_out_bytes, pallas_cost_bytes
from tpu_als.perf.roofline import (
    einsum_ne_build_bytes,
    fused_ne_kernel_bytes,
    headline_roofline,
)


def _problem(n=48, w=40, r=24, N=300, dtype=jnp.float32):
    rng = np.random.default_rng(7)
    V = jnp.asarray(rng.normal(size=(N, r)).astype(np.float32)).astype(dtype)
    cols = jnp.asarray(rng.integers(0, N, size=(n, w)).astype(np.int32))
    vals = jnp.asarray(rng.normal(size=(n, w)).astype(np.float32)).astype(
        dtype)
    mask = jnp.asarray((rng.random((n, w)) < 0.8).astype(np.float32)).astype(
        dtype)
    return V, cols, vals, mask


def _padded_shapes(n, w, r, dtype):
    """The kernel's own padding arithmetic (gather_gram), re-derived."""
    r_pad = max(128, -(-r // 128) * 128)
    tn, wc, w_pad = _tiles(r_pad, -(-w // 8) * 8)
    n_pad = -(-n // tn) * tn
    return n_pad, w_pad, r_pad, jnp.dtype(dtype).itemsize


@pytest.mark.parametrize("implicit", [False, True])
def test_einsum_path_materializes_vg(implicit):
    V, cols, vals, mask = _problem()
    n, w = cols.shape
    r = V.shape[1]
    if implicit:
        YtY = jnp.eye(r, dtype=jnp.float32)
        fn = lambda V, c, v, m: normal_eq_implicit(
            V[c], v, m, 0.1, 4.0, YtY)
    else:
        fn = lambda V, c, v, m: normal_eq_explicit(V[c], v, m, 0.1)
    total, count = gather_out_bytes(fn, V, cols, vals, mask)
    # exactly ONE gather, writing exactly the [n, w, r] intermediate —
    # the model's Vg-materialization term at unpadded shapes
    assert count == 1
    assert total == n * w * r * 4


@pytest.mark.parametrize("implicit", [False, True])
def test_fused_path_never_gathers(implicit):
    V, cols, vals, mask = _problem()
    r = V.shape[1]
    if implicit:
        YtY = jnp.eye(r, dtype=jnp.float32)
        fn = lambda V, c, v, m: gather_normal_eq_implicit(
            V, c, v, m, 0.1, 4.0, YtY, interpret=True)
    else:
        fn = lambda V, c, v, m: gather_normal_eq_explicit(
            V, c, v, m, 0.1, interpret=True)
    total, count = gather_out_bytes(fn, V, cols, vals, mask)
    assert (total, count) == (0, 0), (
        "the fused path traced an HBM gather — Vg is being materialized")


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize("implicit", [False, True])
def test_fused_kernel_cost_estimate_pins_roofline_model(implicit, dtype):
    V, cols, vals, mask = _problem(dtype=dtype)
    n, w = cols.shape
    r = V.shape[1]
    if implicit:
        YtY = jnp.eye(r, dtype=jnp.float32)
        fn = lambda V, c, v, m: gather_normal_eq_implicit(
            V, c, v, m, 0.1, 4.0, YtY, interpret=True)
    else:
        fn = lambda V, c, v, m: gather_normal_eq_explicit(
            V, c, v, m, 0.1, interpret=True)
    total, count = pallas_cost_bytes(fn, V, cols, vals, mask)
    n_pad, w_pad, r_pad, db = _padded_shapes(n, w, r, dtype)
    assert count == 1
    assert total == fused_ne_kernel_bytes(n_pad * w_pad, n_pad, r_pad, db), (
        total, (n_pad, w_pad, r_pad, db))


def test_headline_fused_reduction_at_least_40pct():
    """The acceptance bound: at the headline config the modeled NE-build
    bytes (the stages the kernel replaces) drop >=40% — via the SAME
    roofline the CLI renders, both through the stage tables and through
    the closed forms the stages are built from."""
    ein = headline_roofline(ne_path="einsum")
    fus = headline_roofline(ne_path="gather_fused")
    ein_ne = sum(s["bytes"] for s in ein["stages"]
                 if s["name"] in ("gather_stream", "normal_eq"))
    fus_ne = sum(s["bytes"] for s in fus["stages"]
                 if s["name"] == "gather_fused_ne")
    assert ein_ne and fus_ne
    reduction = 1.0 - fus_ne / ein_ne
    assert reduction >= 0.40, (ein_ne, fus_ne, reduction)
    # the stage tables are the closed forms the kernel/audit pin (each
    # stage int()s its float sum separately, hence the ±2 slack)
    c = ein["config"]
    P = 2.0 * c["padding_waste"] * c["nnz"]
    n = float(c["n_users"] + c["n_items"])
    assert abs(ein_ne - einsum_ne_build_bytes(P, n, c["rank"], 4)) <= 2
    assert abs(fus_ne - fused_ne_kernel_bytes(P, n, c["rank"], 4)) <= 2
    # the fused floor must actually be lower end to end, too
    assert (fus["hbm_floor_s_per_iter"] < ein["hbm_floor_s_per_iter"])
