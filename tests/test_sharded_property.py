"""Randomized sharded==single-device equivalence sweep: seeded random
shapes, ranks, device counts, strategies, and solver families — the edge
shapes a fixed-parameter test never reaches (tiny buckets, heavy skew,
more devices than busy entities, odd ranks).  Deterministic per seed, so
a failure reproduces."""

import numpy as np
import pytest

import jax

from tpu_als.core.als import AlsConfig, train
from tpu_als.core.ratings import build_csr_buckets
from tpu_als.parallel.data import partition_balanced, shard_csr
from tpu_als.parallel.mesh import make_mesh
from tpu_als.parallel.trainer import (
    make_ring_step,
    stacked_counts,
    train_sharded,
)

pytestmark = pytest.mark.slow


def _random_case(rng):
    nU = int(rng.integers(9, 80))
    nI = int(rng.integers(9, 60))
    nnz = int(rng.integers(4 * max(nU, nI), 12 * max(nU, nI)))
    # zipf-ish skew so some entities are huge and many are empty
    u = (rng.zipf(1.3, nnz) % nU).astype(np.int64)
    i = rng.integers(0, nI, nnz)
    implicit = bool(rng.integers(0, 2))
    r = (np.abs(rng.normal(size=nnz)) * 3 + 0.1 if implicit
         else rng.normal(size=nnz)).astype(np.float32)
    rank = int(rng.choice([2, 3, 5, 8]))
    cg = int(rng.choice([0, 2]))
    n_dev = int(rng.choice([2, 4, 8]))
    cfg = AlsConfig(rank=rank, max_iter=2, reg_param=0.03,
                    implicit_prefs=implicit, alpha=4.0, seed=0,
                    cg_iters=cg)
    return nU, nI, u, i, r, cfg, n_dev


@pytest.mark.parametrize("case_seed", [101, 202, 303, 404])
def test_random_case_sharded_equals_single(case_seed):
    from tpu_als.parallel.comm import shard_csr_grid

    rng = np.random.default_rng(case_seed)
    nU, nI, u, i, r, cfg, n_dev = _random_case(rng)
    ucsr = build_csr_buckets(u, i, r, nU, min_width=4)
    icsr = build_csr_buckets(i, u, r, nI, min_width=4)
    U1, V1 = train(ucsr, icsr, cfg)

    mesh = make_mesh(n_dev)
    upart = partition_balanced(np.bincount(u, minlength=nU), n_dev)
    ipart = partition_balanced(np.bincount(i, minlength=nI), n_dev)
    ush = shard_csr(upart, ipart, u, i, r, min_width=4)
    ish = shard_csr(ipart, upart, i, u, r, min_width=4)
    ugrid = shard_csr_grid(upart, ipart, u, i, r, min_width=4)
    igrid = shard_csr_grid(ipart, upart, i, u, r, min_width=4)
    rc = (stacked_counts(upart, u, r, positive_only=cfg.implicit_prefs),
          stacked_counts(ipart, i, r, positive_only=cfg.implicit_prefs))
    # every random case runs the base gather AND both overlapped
    # schedules — a shape that breaks the ragged gather blocks or the
    # ring prefetch shows up here, not on a pod
    runs = [("all_gather", ush, ish, {}),
            ("all_gather_chunked", ush, ish,
             {"gather_blocks": int(rng.integers(1, 6))}),
            ("ring_overlap", ugrid, igrid, {"ring_counts": rc})]
    for strategy, us_, is_, kw in runs:
        Us, Vs = train_sharded(mesh, upart, ipart, us_, is_, cfg,
                               strategy=strategy, **kw)
        np.testing.assert_allclose(
            np.asarray(Us)[upart.slot], np.asarray(U1),
            rtol=5e-3, atol=5e-3,
            err_msg=f"case {case_seed} [{strategy}]: {nU}x{nI} "
                    f"r{cfg.rank} D{n_dev} implicit={cfg.implicit_prefs} "
                    f"cg={cfg.cg_iters}")
        np.testing.assert_allclose(
            np.asarray(Vs)[ipart.slot], np.asarray(V1),
            rtol=5e-3, atol=5e-3, err_msg=f"case {case_seed} [{strategy}]")


def test_single_device_mesh_all_strategies(rng):
    """mesh of ONE device: every gather strategy must degrade gracefully
    (degenerate collectives) and agree with the plain single-device
    trainer — the 'one chip but mesh-structured code' deployment."""
    from tpu_als.parallel.comm import shard_csr_grid

    nU, nI, nnz = 30, 20, 400
    u = rng.integers(0, nU, nnz)
    i = rng.integers(0, nI, nnz)
    r = (np.abs(rng.normal(size=nnz)) + 0.1).astype(np.float32)
    cfg = AlsConfig(rank=3, max_iter=2, reg_param=0.05,
                    implicit_prefs=True, alpha=3.0, seed=0)
    ucsr = build_csr_buckets(u, i, r, nU, min_width=4)
    icsr = build_csr_buckets(i, u, r, nI, min_width=4)
    U1, V1 = train(ucsr, icsr, cfg)

    mesh = make_mesh(1)
    upart = partition_balanced(np.bincount(u, minlength=nU), 1)
    ipart = partition_balanced(np.bincount(i, minlength=nI), 1)
    ush = shard_csr(upart, ipart, u, i, r, min_width=4)
    ish = shard_csr(ipart, upart, i, u, r, min_width=4)
    Ua, Va = train_sharded(mesh, upart, ipart, ush, ish, cfg)
    np.testing.assert_allclose(np.asarray(Ua)[upart.slot], np.asarray(U1),
                               rtol=2e-3, atol=2e-3)

    ugrid = shard_csr_grid(upart, ipart, u, i, r, min_width=4)
    igrid = shard_csr_grid(ipart, upart, i, u, r, min_width=4)
    rc = (stacked_counts(upart, u, r, positive_only=True),
          stacked_counts(ipart, i, r, positive_only=True))
    Ur, Vr = train_sharded(mesh, upart, ipart, ugrid, igrid, cfg,
                           strategy="ring", ring_counts=rc)
    np.testing.assert_allclose(np.asarray(Ur)[upart.slot], np.asarray(U1),
                               rtol=2e-3, atol=2e-3)

    Uo, _ = train_sharded(mesh, upart, ipart, ugrid, igrid, cfg,
                          strategy="ring_overlap", ring_counts=rc)
    np.testing.assert_allclose(np.asarray(Uo)[upart.slot], np.asarray(U1),
                               rtol=2e-3, atol=2e-3)

    # D=1 makes every gather block a full-shard slice of one shard — the
    # chunked path must still partition it exactly
    Uc, _ = train_sharded(mesh, upart, ipart, ush, ish, cfg,
                          strategy="all_gather_chunked", gather_blocks=3)
    np.testing.assert_allclose(np.asarray(Uc)[upart.slot], np.asarray(U1),
                               rtol=2e-3, atol=2e-3)
