"""Stage attribution (tpu_als/perf/attribution.py + obs/trace.py +
``observe attribution``).

The contracts under test, in acceptance order:

- the decomposed fence-timed twin computes the SAME iteration as the
  production fused step (bitwise factors),
- disarmed (the default), the attribution machinery leaves the
  production step's jaxpr byte-for-byte unchanged and records nothing
  — the "<2% overhead when disabled" bound pinned structurally,
- armed, ``core.als.train`` swaps in the twin and per-stage seconds
  land in ``train.stage_seconds{stage=...}`` histograms,
- ``measure_attributed`` coverage (sum of stages / wall) clears the
  acceptance bound, and the report joins measured seconds against the
  roofline floor by stage name.
"""

import json

import numpy as np
import pytest

import jax

from tpu_als import obs
from tpu_als.cli import main as cli_main
from tpu_als.core.als import AlsConfig, init_factors, make_step, train
from tpu_als.core.ratings import build_csr_buckets
from tpu_als.obs import trace
from tpu_als.perf import attribution
from tpu_als.perf.attribution import AttributionUnsupported
from tpu_als.perf.roofline import roofline


@pytest.fixture(autouse=True)
def _fresh_state():
    obs.reset()
    trace.disable_stage_attribution()
    yield
    obs.reset()
    trace.disable_stage_attribution()


def _problem(nU=300, nI=200, nnz=5000, seed=0):
    gen = np.random.default_rng(seed)
    u = gen.integers(0, nU, nnz)
    i = gen.integers(0, nI, nnz)
    r = gen.uniform(0.5, 5.0, nnz).astype(np.float32)
    ucsr = build_csr_buckets(u, i, r, nU, min_width=4, chunk_elems=1 << 12)
    icsr = build_csr_buckets(i, u, r, nI, min_width=4, chunk_elems=1 << 12)
    return ucsr, icsr


def _factors(cfg, nU, nI):
    ku, kv = jax.random.split(jax.random.PRNGKey(cfg.seed))
    return init_factors(ku, nU, cfg.rank), init_factors(kv, nI, cfg.rank)


# -- the twin computes the production iteration ----------------------------

@pytest.mark.parametrize("implicit", [True, False])
def test_attributed_step_matches_production_bitwise(implicit):
    ucsr, icsr = _problem()
    cfg = AlsConfig(rank=8, implicit_prefs=implicit)
    nU, nI = ucsr.num_rows, icsr.num_rows
    ub = jax.device_put(ucsr.device_buckets())
    ib = jax.device_put(icsr.device_buckets())
    step = make_step(ub, ib, nU, nI, cfg,
                     ucsr.chunk_elems, icsr.chunk_elems)
    # the production step DONATES its factor buffers; regenerate the
    # (deterministic) initial factors for each run
    Uf, Vf = step(*step(*_factors(cfg, nU, nI)))
    with trace.stage_attribution():
        astep = attribution.make_attributed_step(
            ub, ib, nU, nI, cfg, ucsr.chunk_elems, icsr.chunk_elems)
        Ua, Va = astep(*astep(*_factors(cfg, nU, nI)))
    assert np.array_equal(np.asarray(Ua), np.asarray(Uf))
    assert np.array_equal(np.asarray(Va), np.asarray(Vf))


def test_unsupported_paths_raise_typed():
    ucsr, icsr = _problem(nU=40, nI=30, nnz=400)
    ub = jax.device_put(ucsr.device_buckets())
    ib = jax.device_put(icsr.device_buckets())
    with pytest.raises(AttributionUnsupported):
        attribution.make_attributed_step(
            ub, ib, ucsr.num_rows, icsr.num_rows,
            AlsConfig(rank=4, cg_iters=3),
            ucsr.chunk_elems, icsr.chunk_elems)


# -- disarmed: the production path is untouched ----------------------------

def test_disarmed_leaves_production_step_jaxpr_unchanged():
    """The '<2% overhead when disabled' acceptance, pinned structurally:
    arming state must not leak into the production step's traced graph
    (the only disarmed cost is one armed-check boolean in train())."""
    ucsr, icsr = _problem(nU=60, nI=40, nnz=800)
    cfg = AlsConfig(rank=4, max_iter=2)
    nU, nI = ucsr.num_rows, icsr.num_rows
    ub = jax.device_put(ucsr.device_buckets())
    ib = jax.device_put(icsr.device_buckets())
    step = make_step(ub, ib, nU, nI, cfg,
                     ucsr.chunk_elems, icsr.chunk_elems)
    U0, V0 = _factors(cfg, nU, nI)
    disarmed = str(jax.make_jaxpr(step)(U0, V0))
    with trace.stage_attribution():
        armed = str(jax.make_jaxpr(step)(U0, V0))
    assert disarmed == armed
    # disarmed train() takes the production step verbatim...
    U1, V1 = train(ucsr, icsr, cfg)
    U2, V2 = step(*step(U0, V0))
    assert np.array_equal(np.asarray(U1), np.asarray(U2))
    assert np.array_equal(np.asarray(V1), np.asarray(V2))
    # ...and records no stage histograms at all
    assert not any(k.startswith("train.stage_seconds")
                   for k in obs.snapshot()["histograms"])


def test_env_flag_arms_attribution(monkeypatch):
    monkeypatch.delenv(trace._ENV_FLAG, raising=False)
    assert not trace.stage_attribution_armed()
    monkeypatch.setenv(trace._ENV_FLAG, "1")
    assert trace.stage_attribution_armed()
    monkeypatch.setenv(trace._ENV_FLAG, "0")
    assert not trace.stage_attribution_armed()


# -- armed: train() swaps in the twin and records stages -------------------

def test_armed_train_records_stage_seconds_and_matches():
    ucsr, icsr = _problem(nU=60, nI=40, nnz=800)
    cfg = AlsConfig(rank=4, max_iter=2, implicit_prefs=True)
    U_plain, V_plain = train(ucsr, icsr, cfg)
    obs.reset()
    with trace.stage_attribution():
        U_att, V_att = train(ucsr, icsr, cfg)
    assert np.array_equal(np.asarray(U_att), np.asarray(U_plain))
    assert np.array_equal(np.asarray(V_att), np.asarray(V_plain))
    hists = {k: v for k, v in obs.snapshot()["histograms"].items()
             if k.startswith("train.stage_seconds")}
    stages = {k.split('stage="')[1].rstrip('"}') for k in hists}
    # solve + scatter appear on every path; yty on the implicit path;
    # the NE stage name depends on the resolved backend
    assert {"solve", "scatter", "yty", "gather_stream"} <= stages
    assert stages & {"normal_eq", "gather_fused_ne"}
    # 2 iterations x (item half + user half) solves at least once each
    assert all(v["count"] >= 2 for v in hists.values())


# -- measurement + the gap-table join --------------------------------------

def test_measure_attributed_coverage():
    ucsr, icsr = _problem(nU=500, nI=300, nnz=20000)
    cfg = AlsConfig(rank=16, implicit_prefs=True)
    m = attribution.measure_attributed(ucsr, icsr, cfg, iters=2, warmup=1)
    assert m["wall_s_per_iter"] > 0 and m["stage_seconds"]
    assert m["sum_stage_s_per_iter"] == pytest.approx(
        sum(m["stage_seconds"].values()))
    # the acceptance bound: stage seconds sum within 10% of the wall
    # iteration time (fences can only lose time, never double-count)
    assert 0.9 <= m["coverage"] <= 1.01, m
    assert m["unattributed_s_per_iter"] == pytest.approx(
        m["wall_s_per_iter"] - m["sum_stage_s_per_iter"])
    assert m["fused_s_per_iter"] > 0


def test_attribution_report_joins_by_stage_name():
    measured = {
        "stage_seconds": {"solve": 0.004, "mystery": 0.001},
        "wall_s_per_iter": 0.01, "sum_stage_s_per_iter": 0.005,
        "coverage": 0.5, "unattributed_s_per_iter": 0.005,
        "resolved_solve_path": "einsum", "iters": 2, "warmup": 1,
        "fused_s_per_iter": 0.002,
    }
    rl = roofline(1000, 500, 20000, 8, dtype="float32", implicit=True,
                  padding_waste=0.2)
    rep = attribution.attribution_report(measured, rl)
    rows = {r["stage"]: r for r in rep["rows"]}
    # measured+modeled: gap and % both populated
    solve = rows["solve"]
    assert solve["gap_x"] == pytest.approx(0.004 / solve["floor_s"])
    assert solve["pct_of_iter"] == pytest.approx(40.0)
    # modeled-only (never measured on this run): measured side is None
    assert rows["gather_stream"]["measured_s"] is None
    assert rows["gather_stream"]["gap_x"] is None
    assert rows["gather_stream"]["floor_s"] > 0
    # measured-only (the model has no such stage): floor side is None
    assert rows["mystery"]["floor_s"] is None
    assert rows["mystery"]["pct_of_iter"] == pytest.approx(10.0)
    assert rep["attribution_overhead_x"] == pytest.approx(5.0)
    text = attribution.render_attribution(rep)
    assert "gap x" in text and "mystery" in text
    assert "production fused step" in text
    # None cells render as '-', not as a crash or a fake zero
    assert " -" in text


# -- the CLI surface (ISSUE acceptance) ------------------------------------

def test_cli_observe_attribution(tmp_path, capsys):
    rep = cli_main(["observe", "attribution",
                    "--data", "synthetic:500x300x20000", "--rank", "16",
                    "--iters", "2", "--warmup", "1", "--json",
                    "--obs-dir", str(tmp_path / "obs")])
    out = json.loads(capsys.readouterr().out)
    assert out["coverage"] >= 0.9          # sum within 10% of the wall
    stages = {r["stage"] for r in out["rows"]}
    assert {"solve", "scatter", "gather_stream"} <= stages
    measured = [r for r in out["rows"] if r["measured_s"] is not None]
    assert measured and all(r["pct_of_iter"] is not None for r in measured)
    assert rep["coverage"] == out["coverage"]
    # the run dir carries the attribution event + stage histograms
    events = [json.loads(ln) for ln in
              open(tmp_path / "obs" / "events.jsonl") if ln.strip()]
    attr = [e for e in events if e["type"] == "attribution"]
    assert len(attr) == 1 and attr[0]["coverage"] == out["coverage"]
    snap = [e for e in events if e["type"] == "snapshot"][-1]
    assert any(k.startswith("train.stage_seconds")
               for k in snap["histograms"])
    # human rendering: the gap table header and footer lines
    cli_main(["observe", "attribution",
              "--data", "synthetic:120x80x1500", "--rank", "4",
              "--iters", "1", "--warmup", "1"])
    text = capsys.readouterr().out
    assert "ALS stage attribution" in text
    assert "gap x" in text and "roofline floor" in text
