"""Chaos matrix for the resilience subsystem (SURVEY.md §5.3 parity).

Every named fault point is exercised with at least one injected failure,
asserting either retry-to-success or a clean typed error — never a raw
traceback from numpy/jax internals.  Fast single-shot cases run in
tier 1; the exhaustive point × mode matrix is ``slow``.
"""

import os
import time

import numpy as np
import pytest

from tpu_als.resilience import faults
from tpu_als.resilience.faults import FaultSpecError, InjectedFault
from tpu_als.resilience.retry import (
    AttemptTimeout,
    RetryExhausted,
    RetryPolicy,
    retry_call,
)


@pytest.fixture(autouse=True)
def _disarm():
    """Every test starts and ends with the harness disarmed — a leaked
    spec would fault unrelated tests in the same process."""
    faults.clear()
    yield
    faults.clear()


def _fast():
    """No-sleep retry policy for chaos cases."""
    return RetryPolicy(max_attempts=3, base_delay=0.0, jitter=0.0)


# ---------------------------------------------------------------------------
# spec grammar


def test_parse_minimal_rule_defaults_to_once():
    rules = faults.parse_spec("checkpoint.write=raise")
    rule = rules["checkpoint.write"]
    assert rule.mode == "raise" and rule.sched == "nth" and rule.k == 1


def test_parse_full_grammar():
    rules = faults.parse_spec(
        "checkpoint.write=raise@nth=3;"
        "ingest.read_chunk=corrupt@first=2;"
        "comm.ring_step=hang:0.5@every=4;"
        "serve.gather=raise@prob=0.25,seed=7;"
        "multihost.init=raise@once")
    assert rules["checkpoint.write"].k == 3
    assert rules["ingest.read_chunk"].sched == "first"
    assert rules["comm.ring_step"].hang_seconds == 0.5
    assert rules["serve.gather"].prob == 0.25
    assert rules["multihost.init"].k == 1


@pytest.mark.parametrize("bad", [
    "nonsense",                      # not POINT=MODE
    "no.such.point=raise",           # unknown point
    "checkpoint.write=explode",      # unknown mode
    "checkpoint.write=hang:abc",     # non-numeric hang
    "checkpoint.write=hang:-1",      # negative hang
    "checkpoint.write=raise@nth=0",  # K < 1
    "checkpoint.write=raise@nth=x",  # non-integer K
    "checkpoint.write=raise@sometimes",            # unknown sched
    "checkpoint.write=raise@prob=2.0",             # P out of range
    "checkpoint.write=raise@prob=0.5,sneed=3",     # bad seed key
    "checkpoint.write=raise;checkpoint.write=corrupt",  # duplicate
    " ; ;",                          # empty
])
def test_parse_rejects_malformed_specs(bad):
    with pytest.raises(FaultSpecError):
        faults.parse_spec(bad)


def test_install_from_env_arms_and_unset_disarms():
    faults.install_from_env({faults.ENV_VAR: "serve.gather=raise"})
    assert faults.active() and faults.armed("serve.gather")
    faults.install_from_env({})
    assert not faults.active()


def test_schedules_fire_deterministically():
    faults.install("checkpoint.write=raise@nth=2")
    assert faults.check("checkpoint.write") is None
    with pytest.raises(InjectedFault):
        faults.check("checkpoint.write")
    assert faults.check("checkpoint.write") is None
    assert faults.hits("checkpoint.write") == (3, 1)

    faults.install("checkpoint.write=corrupt@first=2")
    assert [faults.check("checkpoint.write") for _ in range(4)] == \
        ["corrupt", "corrupt", None, None]

    faults.install("checkpoint.write=corrupt@every=2")
    assert [faults.check("checkpoint.write") for _ in range(4)] == \
        [None, "corrupt", None, "corrupt"]


def test_prob_schedule_replays_exactly():
    def pattern():
        faults.install("serve.gather=corrupt@prob=0.5,seed=11")
        return [faults.check("serve.gather") for _ in range(32)]

    first = pattern()
    assert first == pattern()          # pure function of (spec, hit)
    assert "corrupt" in first and None in first


def test_disarmed_check_is_none_and_cheap():
    assert not faults.active()
    assert faults.check("comm.ring_step") is None
    assert not faults.armed("comm.ring_step")
    assert faults.hits("comm.ring_step") == (0, 0)


def test_injected_fault_is_transient_ioerror():
    faults.install("multihost.init=raise")
    with pytest.raises(IOError) as ei:
        faults.check("multihost.init")
    assert ei.value.point == "multihost.init" and ei.value.hit == 1


def test_hang_mode_stalls_then_continues():
    faults.install("serve.gather=hang:0.05")
    t0 = time.monotonic()
    assert faults.check("serve.gather") is None
    assert time.monotonic() - t0 >= 0.04
    assert faults.hits("serve.gather") == (1, 1)


# ---------------------------------------------------------------------------
# retry policies


def test_backoff_schedule_without_jitter_is_exact():
    p = RetryPolicy(base_delay=0.1, factor=2.0, max_delay=0.5, jitter=0.0)
    assert [p.delay(k) for k in range(4)] == [0.1, 0.2, 0.4, 0.5]


def test_jitter_is_deterministic_per_seed():
    a = RetryPolicy(base_delay=1.0, jitter=0.25, seed=3)
    b = RetryPolicy(base_delay=1.0, jitter=0.25, seed=3)
    da, db = [a.delay(0) for _ in range(5)], [b.delay(0) for _ in range(5)]
    assert da == db
    assert all(0.75 <= d <= 1.25 for d in da)


def test_deterministic_jitter_is_drawcount_independent():
    """Deterministic mode: the jitter for attempt k is a pure function
    of (seed, k), so two same-seed policies agree byte for byte even
    after one has already drawn — the replay property traced runs
    need.  The stateful default walks its stream instead."""
    a = RetryPolicy(base_delay=1.0, jitter=0.25, seed=3,
                    deterministic=True)
    b = RetryPolicy(base_delay=1.0, jitter=0.25, seed=3,
                    deterministic=True)
    for _ in range(7):
        a.delay(0)   # burn draws on a only
    assert ([a.delay(k) for k in range(5)]
            == [b.delay(k) for k in range(5)])
    c = RetryPolicy(base_delay=1.0, jitter=0.25, seed=3,
                    deterministic=False)
    assert len({c.delay(0) for _ in range(5)}) > 1


def test_deterministic_jitter_resolves_from_trace_env(monkeypatch):
    monkeypatch.delenv("TPU_ALS_TRACE", raising=False)
    assert RetryPolicy().deterministic is False
    monkeypatch.setenv("TPU_ALS_TRACE", "1")
    assert RetryPolicy().deterministic is True
    # an explicit argument beats the env resolution
    assert RetryPolicy(deterministic=False).deterministic is False


def test_retry_succeeds_after_transient_failures():
    calls, infos = [], []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("blip")
        return "ok"

    slept = []
    policy = RetryPolicy(max_attempts=4, base_delay=0.01, jitter=0.0,
                         sleep=slept.append)
    assert retry_call(flaky, policy=policy, what="t",
                      on_attempt=infos.append) == "ok"
    assert len(calls) == 3 and len(slept) == 2
    assert [i["attempt"] for i in infos] == [1, 2]
    assert infos[0]["what"] == "t" and "OSError: blip" in infos[0]["reason"]


def test_retry_exhausted_carries_last_error():
    def always():
        raise OSError("down")

    with pytest.raises(RetryExhausted) as ei:
        retry_call(always, policy=_fast(), what="t")
    assert ei.value.attempts == 3
    assert isinstance(ei.value.last, OSError)
    assert ei.value.__cause__ is ei.value.last


def test_non_retryable_error_propagates_immediately():
    calls = []

    def fatal():
        calls.append(1)
        raise ValueError("a fact about the data")

    with pytest.raises(ValueError):
        retry_call(fatal, policy=_fast())
    assert len(calls) == 1


def test_per_attempt_timeout_counts_as_failure():
    policy = RetryPolicy(max_attempts=2, base_delay=0.0, jitter=0.0,
                         timeout=0.05)
    with pytest.raises(RetryExhausted) as ei:
        retry_call(time.sleep, 5.0, policy=policy, what="hung")
    assert isinstance(ei.value.last, AttemptTimeout)


def test_retry_emits_obs_events():
    from tpu_als import obs

    reg = obs.reset()
    with pytest.raises(RetryExhausted):
        retry_call(lambda: (_ for _ in ()).throw(OSError("x")),
                   policy=RetryPolicy(max_attempts=2, base_delay=0.0,
                                      jitter=0.0), what="t")
    kinds = [e["type"] for e in reg._events]
    assert kinds.count("retry_attempt") == 2
    assert kinds.count("retry_exhausted") == 1


# ---------------------------------------------------------------------------
# fault point: checkpoint.write / checkpoint.rename


def _save(path, rng, iteration=1, **kw):
    from tpu_als.io.checkpoint import save_factors

    ids = np.arange(10)
    F = rng.normal(size=(10, 3)).astype(np.float32)
    save_factors(path, ids, F, ids, F, params={}, iteration=iteration,
                 **kw)
    return F


def test_checkpoint_write_transient_error_is_retried(rng, tmp_path):
    from tpu_als.io.checkpoint import load_factors

    path = str(tmp_path / "ck")
    faults.install("checkpoint.write=raise@nth=1")
    F = _save(path, rng, retry_policy=_fast())
    reached, fired = faults.hits("checkpoint.write")
    assert fired == 1 and reached >= 2      # failed once, then succeeded
    manifest, _, U, _, _ = load_factors(path)
    np.testing.assert_array_equal(U, F)


def test_checkpoint_write_corruption_detected_and_quarantined(
        rng, tmp_path):
    from tpu_als.io.checkpoint import CheckpointCorrupt, load_factors

    path = str(tmp_path / "ck")
    faults.install("checkpoint.write=corrupt@nth=1")
    _save(path, rng)                # torn npz slips past the writer
    faults.clear()
    with pytest.raises(CheckpointCorrupt) as ei:
        load_factors(path, retry_policy=_fast())
    assert "digest mismatch" in ei.value.reason
    # forensics copy moved aside, primary gone
    qdir = tmp_path / ".corrupt"
    assert qdir.is_dir() and list(qdir.iterdir())
    assert not os.path.exists(path)


def test_checkpoint_rename_crash_window_leaves_old_loadable(
        rng, tmp_path):
    from tpu_als.io.checkpoint import load_factors

    path = str(tmp_path / "ck")
    F1 = _save(path, rng, iteration=1)
    faults.install("checkpoint.rename=raise@nth=1")
    with pytest.raises(RetryExhausted):
        # max_attempts=1: the crash lands mid-swap and stays there
        _save(path, rng, iteration=2,
              retry_policy=RetryPolicy(max_attempts=1))
    faults.clear()
    # primary gone, .old holds the complete previous generation
    assert not os.path.exists(os.path.join(path, "manifest.json"))
    manifest, _, U, _, _ = load_factors(path)
    assert manifest["iteration"] == 1
    np.testing.assert_array_equal(U, F1)


def test_checkpoint_rename_retry_completes_the_swap(rng, tmp_path):
    from tpu_als.io.checkpoint import load_factors

    path = str(tmp_path / "ck")
    _save(path, rng, iteration=1)
    faults.install("checkpoint.rename=raise@nth=1")
    _save(path, rng, iteration=2, retry_policy=_fast())
    faults.clear()
    manifest, *_ = load_factors(path)
    assert manifest["iteration"] == 2


def test_discover_resume_picks_newest_valid_generation(rng, tmp_path):
    from tpu_als.io.checkpoint import discover_resume

    ck = str(tmp_path / "als_checkpoint")
    _save(ck, rng, iteration=5)
    assert discover_resume(str(tmp_path)) == ck
    # also accepts the checkpoint dir itself
    assert discover_resume(ck) == ck


def test_discover_resume_quarantines_corrupt_generation(rng, tmp_path):
    from tpu_als.io.checkpoint import discover_resume

    ck = str(tmp_path / "als_checkpoint")
    _save(ck, rng, iteration=5)
    with open(os.path.join(ck, "user_factors.npz"), "ab") as f:
        f.write(b"bitrot")          # digest mismatch
    assert discover_resume(str(tmp_path)) is None
    assert (tmp_path / ".corrupt").is_dir()


def test_discover_resume_empty_dir_is_none(tmp_path):
    from tpu_als.io.checkpoint import discover_resume

    assert discover_resume(str(tmp_path)) is None


# ---------------------------------------------------------------------------
# fault point: ingest.read_chunk


def _ratings_csv(tmp_path, rows=200):
    lines = [f"u{k % 17},i{k % 11},{(k % 5) + 1.0}" for k in range(rows)]
    p = tmp_path / "ratings.csv"
    p.write_text("\n".join(lines) + "\n")
    return str(p)


def test_ingest_chunk_read_retried_to_identical_result(tmp_path):
    from tpu_als.io.stream import stream_ingest

    path = _ratings_csv(tmp_path)
    want = stream_ingest(path, chunk_bytes=256)
    faults.install("ingest.read_chunk=raise@nth=2")
    got = stream_ingest(path, chunk_bytes=256, retry_policy=_fast())
    reached, fired = faults.hits("ingest.read_chunk")
    assert fired == 1 and reached > fired
    for a, b in zip(want, got):
        np.testing.assert_array_equal(a, b)


def test_ingest_chunk_corruption_is_a_typed_parse_error(tmp_path):
    from tpu_als.io.stream import stream_ingest

    path = _ratings_csv(tmp_path)
    faults.install("ingest.read_chunk=corrupt@nth=1")
    with pytest.raises(ValueError, match="malformed"):
        stream_ingest(path, chunk_bytes=256, retry_policy=_fast())


def test_ingest_chunk_retry_exhaustion_surfaces(tmp_path):
    from tpu_als.io.stream import stream_ingest

    path = _ratings_csv(tmp_path)
    faults.install("ingest.read_chunk=raise@first=5")
    with pytest.raises(RetryExhausted):
        stream_ingest(path, chunk_bytes=256, retry_policy=_fast())


# ---------------------------------------------------------------------------
# fault point: multihost.init


def test_multihost_init_retries_rendezvous():
    from tpu_als.parallel.multihost import init_distributed

    faults.install("multihost.init=raise@first=2")
    pid, pcount = init_distributed(retry_policy=_fast())
    assert (pid, pcount) == (0, 1)
    assert faults.hits("multihost.init") == (3, 2)


def test_multihost_init_exhaustion_raises():
    from tpu_als.parallel.multihost import init_distributed

    faults.install("multihost.init=raise@first=99")
    with pytest.raises(RetryExhausted):
        init_distributed(retry_policy=RetryPolicy(max_attempts=2,
                                                  base_delay=0.0,
                                                  jitter=0.0))


# ---------------------------------------------------------------------------
# fault point: comm.ring_step


def _ring_step_inputs(rng, armed_spec=None):
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpu_als.core.als import AlsConfig
    from tpu_als.parallel.comm import shard_csr_grid
    from tpu_als.parallel.data import partition_balanced
    from tpu_als.parallel.mesh import AXIS, make_mesh
    from tpu_als.parallel.trainer import make_ring_step, stacked_counts

    D, rank = 8, 4
    u = rng.integers(0, 24, 300)
    i = rng.integers(0, 16, 300)
    r = np.abs(rng.normal(size=300)).astype(np.float32) + 0.1
    upart = partition_balanced(np.bincount(u, minlength=24), D)
    ipart = partition_balanced(np.bincount(i, minlength=16), D)
    cfg = AlsConfig(rank=rank, max_iter=1, reg_param=0.1, seed=0)
    ugrid = shard_csr_grid(upart, ipart, u, i, r, min_width=4)
    igrid = shard_csr_grid(ipart, upart, i, u, r, min_width=4)
    mesh = make_mesh(D)
    leading = NamedSharding(mesh, P(AXIS))
    U = jax.device_put(
        jnp.ones((upart.padded_rows, rank), jnp.float32), leading)
    V = jax.device_put(
        jnp.ones((ipart.padded_rows, rank), jnp.float32), leading)
    ub = jax.device_put(ugrid.device_buckets(), leading)
    ib = jax.device_put(igrid.device_buckets(), leading)
    uc = jax.device_put(
        jnp.asarray(stacked_counts(upart, u, r)), leading)
    ic = jax.device_put(
        jnp.asarray(stacked_counts(ipart, i, r)), leading)
    if armed_spec:
        faults.install(armed_spec)
    step = make_ring_step(mesh, ugrid, igrid, cfg)
    return step, (U, V, ub, ib, uc, ic)


def test_ring_step_disarmed_returns_raw_jitted(rng):
    step, args = _ring_step_inputs(rng)
    # the disarmed builder must hand back the jitted callable itself —
    # that is the "traced jaxprs unchanged" guarantee test_comm_audit
    # relies on (a wrapper would hide .lower from the audit)
    assert hasattr(step, "lower")
    U, V = step(*args)
    assert np.isfinite(np.asarray(U)).all()


def test_ring_step_injected_failure_raises_typed(rng):
    step, args = _ring_step_inputs(rng, "comm.ring_step=raise@nth=1")
    assert not hasattr(step, "lower")   # chaos wrapper installed
    with pytest.raises(InjectedFault):
        step(*args)


def test_ring_step_corruption_detected_as_factors_corrupt(rng):
    from tpu_als.parallel.trainer import FactorsCorrupt

    step, args = _ring_step_inputs(rng, "comm.ring_step=corrupt@nth=2")
    U, V = step(*args)                  # hit 1: clean
    assert np.isfinite(np.asarray(U)).all()
    with pytest.raises(FactorsCorrupt):
        step(U, V, *args[2:])           # hit 2: poisoned reduction


# ---------------------------------------------------------------------------
# fault point: serve.gather (degraded-mode serving)


def _serve_setup(rng):
    from tpu_als.parallel import serve
    from tpu_als.parallel.mesh import make_mesh

    serve.reset_last_good()
    U = rng.normal(size=(12, 4)).astype(np.float32)
    V = rng.normal(size=(20, 4)).astype(np.float32)
    return serve, U, V, make_mesh(8)


@pytest.mark.parametrize("mode", ["raise", "corrupt"])
def test_serve_degrades_to_last_good_catalog(rng, mode):
    from tpu_als import obs

    serve, U, V, mesh = _serve_setup(rng)
    reg = obs.reset()
    s0, i0 = serve.topk_sharded(U, V, 5, mesh)     # primes _last_good
    faults.install(f"serve.gather={mode}@nth=1")
    s1, i1, info = serve.topk_sharded(U, V, 5, mesh, return_info=True)
    assert info["degraded"] and info["reason"]
    np.testing.assert_allclose(s1, s0, atol=1e-5)  # same catalog served
    assert reg.snapshot()["counters"]["serve.degraded"] == 1
    assert "serve_degraded" in [e["type"] for e in reg._events]


def test_serve_without_cache_raises_shard_lost(rng):
    serve, U, V, mesh = _serve_setup(rng)
    faults.install("serve.gather=raise@nth=1")
    with pytest.raises(serve.ServeShardLost):
        serve.topk_sharded(U, V, 5, mesh)


def test_serve_recovers_after_fault_clears(rng):
    serve, U, V, mesh = _serve_setup(rng)
    s0, _ = serve.topk_sharded(U, V, 5, mesh)
    faults.install("serve.gather=raise@nth=1")
    _, _, info = serve.topk_sharded(U, V, 5, mesh, return_info=True)
    assert info["degraded"]
    s2, _, info2 = serve.topk_sharded(U, V, 5, mesh, return_info=True)
    assert not info2["degraded"]
    np.testing.assert_array_equal(s2, s0)


def test_last_good_cache_keyed_by_mesh(rng):
    """Two meshes in one process (a pod host serving two slices) must
    never answer from each other's cached catalog: priming mesh A leaves
    mesh B with nothing to degrade onto."""
    import jax

    serve, U, V, _ = _serve_setup(rng)
    from tpu_als.parallel.mesh import make_mesh

    mesh_a = make_mesh(devices=jax.devices()[:4])
    mesh_b = make_mesh(devices=jax.devices()[4:8])
    s0, _ = serve.topk_sharded(U, V, 5, mesh_a)    # primes A only
    faults.install("serve.gather=raise@first=2")
    with pytest.raises(serve.ServeShardLost):      # B has no last-good
        serve.topk_sharded(U, V, 5, mesh_b)
    s1, _, info = serve.topk_sharded(U, V, 5, mesh_a, return_info=True)
    assert info["degraded"]                        # A degrades onto A's
    np.testing.assert_allclose(s1, s0, atol=1e-5)


def test_last_good_cache_bounded_per_mesh(rng):
    """The degraded cache holds ONE entry per mesh — the newest
    successful serve, whatever strategy produced it — and that entry
    backs any strategy's failover (a catalog of generation g is correct
    for every strategy; the answer is already flagged degraded).
    Per-strategy entries only multiplied full-catalog retention."""
    serve, U, V, mesh = _serve_setup(rng)
    serve.topk_sharded(U, V, 5, mesh, strategy="all_gather")
    serve.topk_sharded(U, V, 5, mesh, strategy="ring")
    with serve._last_good_lock:
        assert len(serve._last_good) == 1       # bounded: one per mesh
        (Vg, validg), = serve._last_good.values()
    assert Vg.shape == V.shape
    faults.install("serve.gather=raise@nth=1")
    s, _, info = serve.topk_sharded(U, V, 5, mesh, strategy="all_gather",
                                    return_info=True)
    assert info["degraded"]                     # ring's newest catalog
    assert s.shape == (U.shape[0], 5)           # backs any failover


# ---------------------------------------------------------------------------
# fault points: serving.publish / serving.score live with the engine
# tests in tests/test_serving.py (the serving subsystem owns them)


# ---------------------------------------------------------------------------
# bench.py rides the same retry implementation


def test_bench_tpu_ready_failure_events(monkeypatch):
    import subprocess as sp

    import bench

    def failing_run(cmd, timeout=None, capture_output=None, text=None):
        raise sp.TimeoutExpired(cmd, timeout)

    monkeypatch.setattr(bench.subprocess, "run", failing_run)
    ok, err, events = bench.tpu_ready(attempts=2, wait_s=0.01,
                                      probe_timeout_s=1)
    assert not ok and "hung" in err
    assert [e["attempt"] for e in events[:-1]] == [1, 2]
    for e in events[:-1]:
        assert e["type"] == "bench_retry" and e["attempts"] == 2
        assert "hung" in e["reason"] and "ts" in e
        assert "TimeoutError" not in e["reason"]   # raw reason contract
    # exhaustion ends the trail with an explicit terminal verdict
    last = events[-1]
    assert last["type"] == "bench_probe_exhausted"
    assert last["attempts"] == 2 and "hung" in last["reason"]


# ---------------------------------------------------------------------------
# preemption primitives (the end-to-end kill-and-resume lives in
# tests/test_resume.py)


def test_preemption_guard_records_signal():
    import signal

    from tpu_als.resilience import preempt

    assert preempt.installed() is None and not preempt.enabled()
    with preempt.PreemptionGuard() as g:
        assert preempt.installed() is g and preempt.enabled()
        assert not preempt.pending(1)
        signal.raise_signal(signal.SIGTERM)
        assert g.triggered() and g.signum == signal.SIGTERM
        assert preempt.pending(2)
    assert preempt.installed() is None


def test_preempt_env_knob_fires_at_exact_iteration(monkeypatch):
    from tpu_als.resilience import preempt

    monkeypatch.setenv(preempt.ENV_PREEMPT_AT, "3")
    assert preempt.enabled()
    assert not preempt.pending(2)
    assert preempt.pending(3)


@pytest.mark.parametrize("bad", ["three", "0", "-2", "2.5"])
def test_preempt_at_malformed_is_typed_error(monkeypatch, bad):
    """A deterministic-preemption knob that silently fails to fire is
    the worst chaos tooling: the malformed value is a typed error at
    arm time (guard entry) AND at every poll, never a no-op."""
    from tpu_als.resilience import preempt

    monkeypatch.setenv(preempt.ENV_PREEMPT_AT, bad)
    with pytest.raises(preempt.PreemptAtError):
        preempt.preempt_at()
    with pytest.raises(preempt.PreemptAtError):
        with preempt.PreemptionGuard():
            pass
    assert preempt.installed() is None   # arm-time failure leaks nothing
    with pytest.raises(preempt.PreemptAtError):
        preempt.pending(1)
    assert isinstance(preempt.PreemptAtError("x"), ValueError)


def test_preempt_at_unset_empty_and_valid(monkeypatch):
    from tpu_als.resilience import preempt

    monkeypatch.delenv(preempt.ENV_PREEMPT_AT, raising=False)
    assert preempt.preempt_at() is None
    monkeypatch.setenv(preempt.ENV_PREEMPT_AT, "")
    assert preempt.preempt_at() is None
    monkeypatch.setenv(preempt.ENV_PREEMPT_AT, "4")
    assert preempt.preempt_at() == 4


def test_preempted_is_systemexit_with_distinct_code():
    from tpu_als.resilience import preempt

    p = preempt.Preempted(7, "/tmp/ck")
    assert isinstance(p, SystemExit) and p.code == preempt.EXIT_PREEMPTED
    assert "/tmp/ck" in str(p) and p.iteration == 7


def test_estimator_preempts_at_iteration_boundary(rng, tmp_path,
                                                  monkeypatch):
    import tpu_als
    from tests.conftest import make_ratings
    from tpu_als.io.checkpoint import load_factors
    from tpu_als.resilience import preempt

    u, i, r, _, _ = make_ratings(rng, num_users=40, num_items=25, rank=3)
    frame = {"user": u, "item": i, "rating": r}
    monkeypatch.setenv(preempt.ENV_PREEMPT_AT, "3")
    als = tpu_als.ALS(rank=3, maxIter=8, regParam=0.01, seed=1,
                      checkpointDir=str(tmp_path), checkpointInterval=100)
    with pytest.raises(preempt.Preempted) as ei:
        als.fit(frame)
    assert ei.value.iteration == 3
    manifest, *_ = load_factors(str(tmp_path / "als_checkpoint"))
    assert manifest["iteration"] == 3


# ---------------------------------------------------------------------------
# elastic mesh training: the detect -> classify -> reschedule primitives
# (the end-to-end loss -> reform -> bitwise resume lives in the
# device-loss scenario, tests/test_scenarios.py)


@pytest.fixture
def _no_lost():
    from tpu_als.resilience import elastic

    elastic.clear_lost()
    yield elastic
    elastic.clear_lost()


def test_lost_registry_roundtrip(_no_lost):
    elastic = _no_lost
    assert elastic.lost_devices() == frozenset()
    elastic.mark_lost(2, 5)
    assert elastic.lost_devices() == frozenset({2, 5})
    elastic.clear_lost()
    assert elastic.lost_devices() == frozenset()


def test_victim_index_validates():
    from tpu_als.resilience import elastic

    assert elastic._victim_index(4, environ={}) == 3
    assert elastic._victim_index(
        4, environ={elastic.ENV_LOST_DEVICE: "1"}) == 1
    with pytest.raises(ValueError, match="not an integer"):
        elastic._victim_index(4, environ={elastic.ENV_LOST_DEVICE: "x"})
    with pytest.raises(ValueError, match="out of range"):
        elastic._victim_index(4, environ={elastic.ENV_LOST_DEVICE: "4"})


def test_classify_reports_only_dead_peers(_no_lost):
    import jax

    elastic = _no_lost
    devices = jax.devices()[:4]
    policy = RetryPolicy(max_attempts=2, base_delay=0.0, jitter=0.0)
    assert elastic.classify(devices, policy=policy) == ()
    elastic.mark_lost(devices[2].id)
    assert elastic.classify(devices, policy=policy) == (
        int(devices[2].id),)


def test_surviving_devices_preserve_mesh_order(_no_lost):
    from tpu_als.parallel.mesh import make_mesh

    elastic = _no_lost
    mesh = make_mesh(4)
    flat = list(mesh.devices.flat)
    elastic.mark_lost(flat[1].id)
    survivors = elastic.surviving_devices(mesh)
    assert [int(d.id) for d in survivors] == [
        int(d.id) for d in (flat[0], flat[2], flat[3])]


def _probe_fast(max_attempts=2):
    return RetryPolicy(max_attempts=max_attempts, base_delay=0.0,
                       jitter=0.0, sleep=lambda s: None,
                       retry_on=(OSError, TimeoutError))


def test_wrap_step_transient_failure_retried_in_place(_no_lost):
    from tpu_als.parallel.mesh import make_mesh
    from tpu_als.resilience import elastic

    mesh = make_mesh(2)
    calls = []

    def step(U, V):
        calls.append(1)
        if len(calls) < 2:
            raise OSError("ICI hiccup")   # every peer probes healthy
        return U, V

    wrapped = elastic.wrap_step(step, mesh, policy=_probe_fast())
    assert wrapped(1, 2) == (1, 2)
    assert len(calls) == 2


def test_wrap_step_dead_peer_raises_device_lost(_no_lost):
    from tpu_als.parallel.mesh import make_mesh
    from tpu_als.resilience import elastic
    from tpu_als.resilience.elastic import DeviceLost

    mesh = make_mesh(4)
    faults.install("mesh.device_lost=corrupt@once")
    wrapped = elastic.wrap_step(lambda U, V: (U, V), mesh,
                                policy=_probe_fast())
    with pytest.raises(DeviceLost) as ei:
        wrapped(0, 0)
    assert ei.value.lost == (int(mesh.devices.flat[-1].id),)
    assert ei.value.surviving == 3
    assert isinstance(ei.value.__cause__, elastic.ProbeFailed)


def test_elastic_vocabulary_pins_hold():
    """The recovery-trail names are a cross-process contract (the
    device-loss scenario counts them in events.jsonl): the explicit
    vocab pin must hold — declared AND emitted/consulted."""
    import importlib.util

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "_tal_vocab_elastic_test",
        os.path.join(repo, "tpu_als", "analysis", "vocab.py"))
    vocab = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(vocab)
    assert vocab.check_elastic_vocabulary(repo) == []


def test_wrap_step_transient_budget_exhausts(_no_lost):
    from tpu_als.parallel.mesh import make_mesh
    from tpu_als.resilience import elastic

    mesh = make_mesh(2)

    def step(U, V):
        raise OSError("persistent but no peer is dead")

    wrapped = elastic.wrap_step(step, mesh, policy=_probe_fast(),
                                max_transient=2)
    with pytest.raises(OSError, match="persistent"):
        wrapped(0, 0)


# ---------------------------------------------------------------------------
# the full point × mode matrix (slow tier): every fault point fires under
# both raise and corrupt and ends in a retry/recovery or a typed error


_MATRIX_TYPED = {
    "checkpoint.write": ("CheckpointCorrupt",),
    "checkpoint.rename": ("RetryExhausted",),
    "ingest.read_chunk": ("ValueError", "RetryExhausted"),
    "multihost.init": ("RetryExhausted",),
    "comm.ring_step": ("InjectedFault", "FactorsCorrupt"),
    "serve.gather": ("ServeShardLost",),
}


@pytest.mark.slow
@pytest.mark.parametrize("mode", ["raise", "corrupt"])
@pytest.mark.parametrize("point", faults.FAULT_POINTS)
def test_chaos_matrix(point, mode, rng, tmp_path):
    """Arm one (point, mode) pair, drive the owning subsystem, and
    assert the outcome is recovery or a typed error from the resilience
    vocabulary — never an untyped crash."""
    from tpu_als.io.checkpoint import CheckpointCorrupt, load_factors
    from tpu_als.io.stream import stream_ingest
    from tpu_als.parallel import serve
    from tpu_als.parallel.mesh import make_mesh
    from tpu_als.parallel.multihost import init_distributed
    from tpu_als.parallel.trainer import FactorsCorrupt

    typed = (InjectedFault, RetryExhausted, CheckpointCorrupt,
             FactorsCorrupt, serve.ServeShardLost, ValueError)
    spec = f"{point}={mode}@first=99"   # fire on EVERY hit
    one_shot = RetryPolicy(max_attempts=1)

    try:
        if point in ("checkpoint.write", "checkpoint.rename"):
            faults.install(spec)
            path = str(tmp_path / "ck")
            _save(path, rng, retry_policy=one_shot)
            faults.clear()
            load_factors(path, retry_policy=one_shot)
        elif point == "ingest.read_chunk":
            path = _ratings_csv(tmp_path)
            faults.install(spec)
            stream_ingest(path, chunk_bytes=256, retry_policy=one_shot)
        elif point == "multihost.init":
            faults.install(spec)
            init_distributed(retry_policy=one_shot)
        elif point == "comm.ring_step":
            step, args = _ring_step_inputs(rng, spec)
            step(*args)
        elif point in ("serving.publish", "serving.score"):
            # raise -> InjectedFault out of publish/serve_batch;
            # corrupt -> stale-index detection + exact-path fallback
            # (the request is still answered — recovery, not an error)
            from tpu_als.serving import ServingEngine

            eng = ServingEngine(k=3, buckets=(8,), max_wait_s=0.0)
            faults.install(spec)
            eng.publish(rng.normal(size=(6, 3)).astype(np.float32),
                        rng.normal(size=(12, 3)).astype(np.float32))
            t = eng.submit(0)
            eng.serve_batch(eng.batcher.next_batch(timeout=1.0))
            t.result(timeout=1.0)
        else:  # serve.gather
            serve.reset_last_good()
            U = rng.normal(size=(8, 3)).astype(np.float32)
            V = rng.normal(size=(12, 3)).astype(np.float32)
            faults.install(spec)
            serve.topk_sharded(U, V, 4, make_mesh(8))
    except typed:
        pass                      # a clean, typed failure is a pass
    reached, fired = (faults.hits(point) if faults.active()
                      else (1, 1))  # cleared above ⇒ already asserted
    assert fired >= 1, f"{point}={mode} never fired"
