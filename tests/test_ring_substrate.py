"""The shared double-buffer ring substrate (ops.ring_buffer) and the
fused-comm ring kernel (``solve_backend='gather_fused_ring'``) built on it.

Two families of pins:

1. **Substrate extraction** — routing ``pallas_gather_ne`` and
   ``pallas_topk`` through :func:`ring_buffer.pump` /
   :func:`ring_buffer.grid_pump` emits a byte-identical jaxpr (modulo
   source locations) to the pre-extraction hand-rolled loops, and no
   private ``make_async_copy`` call sites survive outside the substrate
   module.  Owned here; re-verifiable via
   ``contracts.verify('ring_substrate')``.

2. **Fused-comm ring kernel** — the in-kernel ``make_async_remote_copy``
   rotation under ``shard_map`` matches the single-device fused solve on
   the concatenated global column space: degenerate ring (n_shards=1,
   bitwise), full 8-device ring, non-power-of-two submesh rings, ragged
   row/width tiles, and a 3-iteration end-to-end ``train_sharded`` run
   against the single-device reference (both feedback modes).

All on the 8-device forced-host CPU backend in interpret mode — schedule
and numerics are fully exercised; the hardware-only race-control arms
(ack backpressure, pass barrier) are compile-gated and documented in the
kernel docstring.
"""

import functools

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from tpu_als.ops.pallas_gather_ne import (
    gather_fused_ring_explicit,
    gather_fused_ring_implicit,
    gather_fused_solve_explicit,
    gather_fused_solve_implicit,
)

from conftest import make_ratings

RANK = 128  # one real lane tile: exercises the exact hardware layout


# -- 1. the substrate extraction pin ---------------------------------------

def test_ring_substrate_contract():
    """Substrate pump == frozen pre-extraction twin, byte-for-byte after
    source-location normalization, for gather_gram, gather_solve AND
    topk_scores_pallas; no async-DMA call sites outside ops/ring_buffer."""
    from tpu_als.analysis import contracts

    res = contracts.verify("ring_substrate")
    assert res.ok, res.detail
    assert "no async-DMA call sites outside ops/ring_buffer.py" in res.detail


def test_substrate_is_the_only_dma_descriptor_owner():
    """Standalone restatement of the source scan (fails with the offender
    list even if the jaxpr half of the contract breaks first)."""
    import re
    from pathlib import Path

    import tpu_als

    root = Path(tpu_als.__file__).resolve().parent
    call = re.compile(r"make_async(?:_remote)?_copy\s*\(")
    offenders = sorted(
        str(p.relative_to(root))
        for p in root.rglob("*.py")
        if p.name != "ring_buffer.py" and call.search(p.read_text())
    )
    assert not offenders, offenders


# -- 2. the fused-comm ring kernel -----------------------------------------

def _ring_problem(rng, S, per, n, w, r=RANK):
    """Per-device ring buckets: cols[d, s, n, w] are LOCAL ids into the
    shard held at ring step s (the wrapper's pre-rotation maps step to
    source shard), plus the concatenated global-column reference inputs."""
    Vfull = (rng.normal(size=(S * per, r)) / np.sqrt(r)).astype(np.float32)
    cols = rng.integers(0, per, size=(S, S, n, w)).astype(np.int32)
    vals = rng.normal(size=(S, S, n, w)).astype(np.float32)
    mask = (rng.random(size=(S, S, n, w)) < 0.7).astype(np.float32)
    return Vfull, cols, vals, mask


def _global_ref(d, per, S, cols, vals, mask):
    gcols = np.concatenate([cols[d, s] + s * per for s in range(S)], axis=1)
    gvals = np.concatenate([vals[d, s] for s in range(S)], axis=1)
    gmask = np.concatenate([mask[d, s] for s in range(S)], axis=1)
    return gcols, gvals, gmask


def test_nshards1_ring_is_bitwise_gather_fused_solve(rng):
    """The degenerate ring (S=1, no rotation, no remote DMA) IS the PR 14
    fused-solve kernel — bitwise, not approximately: same tiling, same
    accumulation order, the ring arms compile out entirely."""
    per, n, w = 64, 48, 24
    V = (rng.normal(size=(per, RANK)) / np.sqrt(RANK)).astype(np.float32)
    cols = rng.integers(0, per, size=(1, n, w)).astype(np.int32)
    vals = rng.normal(size=(1, n, w)).astype(np.float32)
    mask = (rng.random(size=(1, n, w)) < 0.8).astype(np.float32)

    x_ring = gather_fused_ring_explicit(
        jnp.asarray(V), jnp.asarray(cols), jnp.asarray(vals),
        jnp.asarray(mask), 0.05, interpret=True)
    x_ref = gather_fused_solve_explicit(
        jnp.asarray(V), jnp.asarray(cols[0]), jnp.asarray(vals[0]),
        jnp.asarray(mask[0]), 0.05, interpret=True)
    assert np.array_equal(np.asarray(x_ring), np.asarray(x_ref))


@pytest.mark.parametrize("S", [8, 5, 3])
def test_ring_matches_global_fused_solve_explicit(rng, S):
    """Ring under shard_map == single-device fused solve on concatenated
    global columns, per device.  S=5 and S=3 are the non-power-of-two
    rings: the schedule is (S-1) rotations of a logical ring, nothing in
    it assumes S is a power of two — this is where that's pinned."""
    AXIS = "d"
    mesh = Mesh(np.array(jax.devices()[:S]), (AXIS,))
    per, n, w = 40, 56, 16
    Vfull, cols, vals, mask = _ring_problem(rng, S, per, n, w)

    @jax.jit
    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(P(AXIS), P(AXIS), P(AXIS), P(AXIS)),
                       out_specs=P(AXIS), check_rep=False)
    def run(V_shard, c, v, m):
        return gather_fused_ring_explicit(
            V_shard, c[0], v[0], m[0], 0.05, axis_name=AXIS,
            interpret=True)[None]

    x = np.asarray(run(jnp.asarray(Vfull), jnp.asarray(cols),
                       jnp.asarray(vals), jnp.asarray(mask)))
    for d in range(S):
        gcols, gvals, gmask = _global_ref(d, per, S, cols, vals, mask)
        xr = np.asarray(gather_fused_solve_explicit(
            jnp.asarray(Vfull), jnp.asarray(gcols), jnp.asarray(gvals),
            jnp.asarray(gmask), 0.05, interpret=True))
        np.testing.assert_allclose(x[d], xr, atol=2e-5, rtol=1e-5)


def test_ring_matches_global_fused_solve_implicit(rng):
    """Implicit mode: the YtY base term is replicated (psum'd outside the
    kernel), only (conf-1)-weighted corrections ride the ring."""
    AXIS = "d"
    S = 8
    mesh = Mesh(np.array(jax.devices()), (AXIS,))
    per, n, w = 40, 56, 16
    Vfull, cols, vals, mask = _ring_problem(rng, S, per, n, w)
    vals = np.abs(vals) * 4 + 0.1
    YtY = (Vfull.T @ Vfull).astype(np.float32)

    @jax.jit
    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(P(AXIS), P(AXIS), P(AXIS), P(AXIS), P()),
                       out_specs=P(AXIS), check_rep=False)
    def run(V_shard, c, v, m, yty):
        return gather_fused_ring_implicit(
            V_shard, c[0], v[0], m[0], 0.05, 40.0, yty, axis_name=AXIS,
            interpret=True)[None]

    x = np.asarray(run(jnp.asarray(Vfull), jnp.asarray(cols),
                       jnp.asarray(vals), jnp.asarray(mask),
                       jnp.asarray(YtY)))
    for d in range(S):
        gcols, gvals, gmask = _global_ref(d, per, S, cols, vals, mask)
        xr = np.asarray(gather_fused_solve_implicit(
            jnp.asarray(Vfull), jnp.asarray(gcols), jnp.asarray(gvals),
            jnp.asarray(gmask), 0.05, 40.0, jnp.asarray(YtY),
            interpret=True))
        # ring accumulates shard Grams in rotation order, the reference
        # in concatenation order — fp association noise only
        np.testing.assert_allclose(x[d], xr, atol=1e-4, rtol=1e-4)


def test_ring_ragged_rows_and_width(rng):
    """Rows not a multiple of the row tile and width not a multiple of
    the lane chunk: the padding rows/columns must not contaminate the
    gathered tiles of LATER ring steps (a padded row gathers shard row 0
    via clamped ids but carries zero weight)."""
    AXIS = "d"
    S = 4
    mesh = Mesh(np.array(jax.devices()[:S]), (AXIS,))
    per, n, w = 24, 13, 5  # n, w both ragged vs any power-of-two tiling
    Vfull, cols, vals, mask = _ring_problem(rng, S, per, n, w)

    @jax.jit
    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(P(AXIS), P(AXIS), P(AXIS), P(AXIS)),
                       out_specs=P(AXIS), check_rep=False)
    def run(V_shard, c, v, m):
        return gather_fused_ring_explicit(
            V_shard, c[0], v[0], m[0], 0.05, axis_name=AXIS,
            interpret=True)[None]

    x = np.asarray(run(jnp.asarray(Vfull), jnp.asarray(cols),
                       jnp.asarray(vals), jnp.asarray(mask)))
    for d in range(S):
        gcols, gvals, gmask = _global_ref(d, per, S, cols, vals, mask)
        xr = np.asarray(gather_fused_solve_explicit(
            jnp.asarray(Vfull), jnp.asarray(gcols), jnp.asarray(gvals),
            jnp.asarray(gmask), 0.05, interpret=True))
        np.testing.assert_allclose(x[d], xr, atol=2e-5, rtol=1e-5)


# -- 3. end-to-end: train_sharded with the fused ring ----------------------

@pytest.mark.parametrize("implicit", [False, True])
def test_fused_ring_train_matches_single_device(implicit):
    """3 iterations of strategy='ring' + solve_backend='gather_fused_ring'
    == the single-device reference, both feedback modes.  The whole
    wiring stack is on the line here: resolve_solve_path, make_ring_step's
    fused dispatch, ring_fused_half_step's bucket loop + scatter, the
    kernel, and the psum(YtY) path for implicit."""
    from tpu_als.core.als import AlsConfig, train
    from tpu_als.core.ratings import build_csr_buckets
    from tpu_als.parallel.comm import shard_csr_grid
    from tpu_als.parallel.data import partition_balanced
    from tpu_als.parallel.mesh import make_mesh
    from tpu_als.parallel.trainer import stacked_counts, train_sharded

    gen = np.random.default_rng(2)
    u, i, r, _, _ = make_ratings(gen, 60, 45, rank=3, density=0.4)
    if implicit:
        r = np.abs(r) * 4 + 0.1
    cfg = AlsConfig(rank=4, max_iter=3, reg_param=0.05,
                    implicit_prefs=implicit, alpha=6.0, seed=9,
                    solve_backend="gather_fused_ring")
    n_dev = 8
    mesh = make_mesh(n_dev)
    upart = partition_balanced(np.bincount(u, minlength=60), n_dev)
    ipart = partition_balanced(np.bincount(i, minlength=45), n_dev)
    ush = shard_csr_grid(upart, ipart, u, i, r, min_width=4)
    ish = shard_csr_grid(ipart, upart, i, u, r, min_width=4)
    counts = (stacked_counts(upart, u, r, positive_only=implicit),
              stacked_counts(ipart, i, r, positive_only=implicit))
    U, V = train_sharded(mesh, upart, ipart, ush, ish, cfg,
                         strategy="ring", ring_counts=counts)
    Ur, Vr = np.asarray(U)[upart.slot], np.asarray(V)[ipart.slot]

    cfg1 = AlsConfig(rank=4, max_iter=3, reg_param=0.05,
                     implicit_prefs=implicit, alpha=6.0, seed=9)
    ub = build_csr_buckets(u, i, r, 60, min_width=4)
    ib = build_csr_buckets(i, u, r, 45, min_width=4)
    U1, V1 = train(ub, ib, cfg1)
    np.testing.assert_allclose(Ur, np.asarray(U1), rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(Vr, np.asarray(V1), rtol=2e-3, atol=2e-3)
