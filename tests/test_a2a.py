"""Ragged all_to_all gather strategy — must reproduce the all_gather result
(and hence the single-device result) to fp tolerance on the 8-device mesh,
while moving only the factor rows each device's rating shard references.
"""

import numpy as np
import pytest

from tpu_als.core.als import AlsConfig
from tpu_als.parallel.a2a import build_a2a
from tpu_als.parallel.data import partition_balanced, shard_csr
from tpu_als.parallel.mesh import make_mesh
from tpu_als.parallel.trainer import train_sharded

from conftest import make_ratings


def _run(cfg, strategy, u, i, r, num_users, num_items, n_dev=8):
    mesh = make_mesh(n_dev)
    upart = partition_balanced(np.bincount(u, minlength=num_users), n_dev)
    ipart = partition_balanced(np.bincount(i, minlength=num_items), n_dev)
    if strategy == "all_to_all":
        ush = build_a2a(upart, ipart, u, i, r, min_width=4)
        ish = build_a2a(ipart, upart, i, u, r, min_width=4)
    else:
        ush = shard_csr(upart, ipart, u, i, r, min_width=4)
        ish = shard_csr(ipart, upart, i, u, r, min_width=4)
    U, V = train_sharded(mesh, upart, ipart, ush, ish, cfg,
                         strategy=strategy)
    return np.asarray(U)[upart.slot], np.asarray(V)[ipart.slot]


@pytest.mark.parametrize("implicit", [False, True])
def test_a2a_equals_all_gather(rng, implicit):
    u, i, r, _, _ = make_ratings(np.random.default_rng(3), 60, 45,
                                 rank=3, density=0.4)
    if implicit:
        r = np.abs(r) * 4 + 0.1
    cfg = AlsConfig(rank=4, max_iter=4, reg_param=0.05,
                    implicit_prefs=implicit, alpha=6.0, seed=9)
    Ug, Vg = _run(cfg, "all_gather", u, i, r, 60, 45)
    Ua, Va = _run(cfg, "all_to_all", u, i, r, 60, 45)
    np.testing.assert_allclose(Ua, Ug, rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(Va, Vg, rtol=2e-3, atol=2e-3)


def test_a2a_nonnegative(rng):
    u, i, r, _, _ = make_ratings(np.random.default_rng(5), 40, 30,
                                 rank=3, density=0.4)
    r = np.abs(r) + 0.1
    cfg = AlsConfig(rank=3, max_iter=3, reg_param=0.05, nonnegative=True,
                    seed=1)
    Ug, _ = _run(cfg, "all_gather", u, i, r, 40, 30)
    Ua, _ = _run(cfg, "all_to_all", u, i, r, 40, 30)
    assert Ua.min() >= -1e-5
    np.testing.assert_allclose(Ua, Ug, rtol=5e-3, atol=5e-3)


def test_request_budget_bounds_traffic(rng):
    """Clustered interactions → request lists (and hence bytes exchanged)
    far below a full gather: R ≪ rows_per_shard · D."""
    nU = nI = 64
    D = 8
    # block-diagonal interactions: user block b only rates item block b
    u = np.repeat(np.arange(nU), 8)
    i = (np.tile(np.arange(8), nU) + (u // 8) * 8) % nI
    r = np.ones(len(u), np.float32)
    upart = partition_balanced(np.bincount(u, minlength=nU), D)
    ipart = partition_balanced(np.bincount(i, minlength=nI), D)
    plan = build_a2a(upart, ipart, u, i, r, min_width=4)
    # each user needs 8 items; spread over D sources that's ≤ 8 rows/src,
    # padded to the sublane multiple
    assert plan.request_budget <= 16
    # exchanged rows per device (D*R) ≪ full gather (D * rows_per_shard)
    assert D * plan.request_budget < D * ipart.rows_per_shard * D


def test_a2a_wins_on_sparse_large_catalog(rng):
    """The strategy's raison d'être, demonstrated (VERDICT r2 weak #4):
    when each rating block touches few rows of a large opposite catalog
    (the Ulysses regime, SURVEY.md §5.7 / the OutBlock analogy §2.B4), the
    exchange must (a) build non-degenerate with no fallback warning,
    (b) move strictly fewer bytes than all_gather — here asserted at ≤ half
    — and (c) still reproduce the all_gather factors."""
    import warnings

    local_rng = np.random.default_rng(11)
    D = 8
    nU, nI = 64 * D, 64 * D          # big catalogs...
    nnz = 2 * nU                     # ...sparsely touched: 2 ratings/user
    u = local_rng.integers(0, nU, nnz)
    i = local_rng.integers(0, nI, nnz)
    r = np.abs(local_rng.normal(size=nnz)).astype(np.float32) + 0.1
    upart = partition_balanced(np.bincount(u, minlength=nU), D)
    ipart = partition_balanced(np.bincount(i, minlength=nI), D)
    with warnings.catch_warnings():
        warnings.simplefilter("error")   # (a) no degeneracy warning
        ua = build_a2a(upart, ipart, u, i, r, min_width=4)
        ia = build_a2a(ipart, upart, i, u, r, min_width=4)
    assert not ua.degenerate and not ia.degenerate
    # (b) bytes: each device receives D·R opposite rows vs the full
    # opposite table (padded_rows ≈ D·rows_per_shard) under all_gather;
    # both half-steps must win by at least 2x on this layout
    assert D * ua.request_budget <= ipart.padded_rows // 2
    assert D * ia.request_budget <= upart.padded_rows // 2
    # (c) equivalence at this exact layout
    cfg = AlsConfig(rank=4, max_iter=3, reg_param=0.05, seed=3)
    mesh = make_mesh(D)
    Ug, Vg = train_sharded(
        mesh, upart, ipart,
        shard_csr(upart, ipart, u, i, r, min_width=4),
        shard_csr(ipart, upart, i, u, r, min_width=4), cfg)
    Ua, Va = train_sharded(mesh, upart, ipart, ua, ia, cfg,
                           strategy="all_to_all")
    np.testing.assert_allclose(np.asarray(Ua), np.asarray(Ug),
                               rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(np.asarray(Va), np.asarray(Vg),
                               rtol=2e-3, atol=2e-3)


def test_send_idx_round_trip(rng):
    """The compact col ids must address exactly the rows the plan ships:
    reconstruct each rating's gathered factor row through send_idx and
    compare with direct indexing."""
    u, i, r, _, _ = make_ratings(np.random.default_rng(7), 30, 20,
                                 rank=3, density=0.5)
    D = 4
    upart = partition_balanced(np.bincount(u, minlength=30), D)
    ipart = partition_balanced(np.bincount(i, minlength=20), D)
    plan = build_a2a(upart, ipart, u, i, r, min_width=4)
    R = plan.request_budget
    # fake item factors: value = item slot id, so row identity is checkable
    V_slots = np.arange(ipart.padded_rows, dtype=np.float32)
    V_by_shard = V_slots.reshape(D, ipart.rows_per_shard)
    # simulate the exchange: recv table on device d = rows requested by d
    for d in range(D):
        recv = np.zeros(D * R, np.float32)
        for s in range(D):
            recv[s * R:(s + 1) * R] = V_by_shard[s][plan.send_idx[s, d]]
        for b in plan.buckets:
            rows, cols, mask = b.rows[d], b.cols[d], b.mask[d]
            valid = mask > 0
            got = recv[cols[valid]]
            # expected: the slot id of the item each rating references
            want_rows = rows[:, None].repeat(cols.shape[1], 1)[valid]
            # recover original (user local row, item slot) pairs
            sel = upart.owner[u] == d
            pairs = {}
            for uu, ii in zip(upart.local[u[sel]], ipart.slot[i[sel]]):
                pairs.setdefault(int(uu), []).append(float(ii))
            for rr, g in zip(want_rows, got):
                assert g in pairs[int(rr)]


def test_skewed_budget_detected_and_bounded(rng):
    """One dense source inflates the uniform budget R for all D² pairs
    (VERDICT r1 weak #6): the plan must report the degeneration so total
    bytes never silently exceed all_gather's."""
    import warnings

    nU = nI = 64
    D = 8
    # one power user rates EVERY item: its shard must request every row of
    # every item shard (R_true = rows/shard), so the plan is degenerate no
    # matter how partition_balanced places entities — everyone else rates
    # a single item, making this genuinely one-hot skew
    u = np.concatenate([np.zeros(nI, np.int64), np.arange(1, nU)])
    i = np.concatenate([np.arange(nI), np.arange(1, nU) % 8])
    r = np.ones(len(u), np.float32)
    upart = partition_balanced(np.bincount(u, minlength=nU), D)
    ipart = partition_balanced(np.bincount(i, minlength=nI), D)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        plan = build_a2a(upart, ipart, u, i, r, min_width=4)
    assert plan.degenerate  # must fire unconditionally on this layout
    assert any("all_gather" in str(x.message) for x in w)
    # bytes bound: exchanged rows >= all_gather is exactly what the
    # flag reports — callers (the Estimator) must fall back
    assert D * plan.request_budget >= D * ipart.rows_per_shard
    assert plan.padding_ratio >= 1.0
    # 'stub' mode must detect BEFORE allocating the [D, D, R] exchange
    # tables (terabyte-class at the scale where the fallback matters)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        stub = build_a2a(upart, ipart, u, i, r, min_width=4,
                         on_degenerate="stub")
    assert stub.degenerate
    assert stub.send_idx.size == 0 and stub.buckets == []


def test_estimator_falls_back_on_degenerate_plan(rng):
    """gatherStrategy='all_to_all' with a clustered-skew layout must train
    via all_gather instead of shipping an exchange that moves more bytes
    than a full gather."""
    import jax

    from tpu_als.api.estimator import ALS
    from tpu_als.parallel.mesh import make_mesh

    # tiny problem where every user rates most items -> R ~ full shard
    nU, nI = 16, 16
    uu, ii = np.meshgrid(np.arange(nU), np.arange(nI), indexing="ij")
    u, i = uu.ravel(), ii.ravel()
    r = rng.normal(size=len(u)).astype(np.float32)
    frame = {"user": u.astype(np.int64), "item": i.astype(np.int64),
             "rating": r}
    mesh = make_mesh(8)
    m_ag = ALS(rank=3, maxIter=3, seed=0, mesh=mesh).fit(frame)
    import warnings

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        m_a2a = ALS(rank=3, maxIter=3, seed=0, mesh=mesh,
                    gatherStrategy="all_to_all").fit(frame)
    assert any("all_gather" in str(x.message) for x in w)
    np.testing.assert_allclose(
        np.asarray(m_a2a.transform(frame)["prediction"]),
        np.asarray(m_ag.transform(frame)["prediction"]),
        rtol=2e-3, atol=2e-3)


def test_a2a_positions_build_matches_slice(rng):
    # multi-host contract: building only local source rows (positions=)
    # must equal slicing a full build
    nU = nI = 64
    D = 8
    u = np.repeat(np.arange(nU), 8)
    i = (np.tile(np.arange(8), nU) + (u // 8) * 8) % nI
    r = np.ones(len(u), np.float32)
    upart = partition_balanced(np.bincount(u, minlength=nU), D)
    ipart = partition_balanced(np.bincount(i, minlength=nI), D)
    full = build_a2a(upart, ipart, u, i, r, min_width=4)
    for pos in ([0, 1, 2, 3], [5, 7]):
        loc = build_a2a(upart, ipart, u, i, r, min_width=4, positions=pos)
        ref = full.local_slice(pos)
        assert loc.positions == tuple(pos)
        assert loc.request_budget == full.request_budget
        np.testing.assert_array_equal(loc.send_idx, ref.send_idx)
        for bl, bf in zip(loc.buckets, ref.buckets):
            np.testing.assert_array_equal(bl.rows, bf.rows)
            np.testing.assert_array_equal(bl.cols, bf.cols)
            np.testing.assert_array_equal(bl.vals, bf.vals)
            np.testing.assert_array_equal(bl.mask, bf.mask)
