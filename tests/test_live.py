"""Live pipeline tests (tpu_als/live/ + the incremental index).

Three layers:

1. the DELTA-INDEX contract — ``with_updates``/``compact`` top-k is
   bitwise-equal to a full ``build_index`` rebuild of the same catalog
   (property matrix: touched-rows-only, append-only, mixed, second-
   generation merges, compaction, invalid rows, duplicate scores),
2. the engine's incremental publish modes
   (retag/delta/compact/full/none) and the live-path warmup,
3. the :class:`LiveUpdater` loop — admission + shed, quarantine,
   freshness measurement, SLO-breach flight dumps — plus the planner
   cadence and the bounded fold-in stats ring.
"""

import json
import time

import numpy as np
import pytest

import jax.numpy as jnp

from tpu_als import obs, plan
from tpu_als.api.estimator import ALSModel
from tpu_als.core.ratings import IdMap
from tpu_als.live import LiveUpdater
from tpu_als.live.updater import LIVE_SPAN_KEYS
from tpu_als.obs.trace import FlightRecorder
from tpu_als.ops.topk import topk_validity
from tpu_als.serving import Overloaded, ServingEngine, build_index
from tpu_als.stream.microbatch import FoldInServer


@pytest.fixture(autouse=True)
def _fresh():
    reg = obs.reset()
    yield reg


# ---------------------------------------------------------------------------
# 1. the delta-index bitwise contract


def _assert_same_topk(idx, ref, U, k):
    """Scores bitwise-equal; indices equal wherever scores are unique
    (ties may legitimately resolve differently across kernels, but the
    tied index must still earn its score)."""
    s, ix = np.asarray(idx.topk(U, k)[0]), np.asarray(idx.topk(U, k)[1])
    rs, rix = np.asarray(ref.topk(U, k)[0]), np.asarray(ref.topk(U, k)[1])
    np.testing.assert_array_equal(s, rs)
    for row in range(s.shape[0]):
        real = topk_validity(s[row])
        if len(np.unique(s[row][real])) == real.sum():
            np.testing.assert_array_equal(ix[row][real], rix[row][real])


def _queries(rng, n, r):
    return jnp.asarray(rng.normal(size=(n, r)).astype(np.float32))


@pytest.mark.parametrize("Ni,r,sk", [(64, 4, 16), (200, 8, 64),
                                     (33, 3, 8)])
def test_delta_touched_rows_only_matches_rebuild(rng, Ni, r, sk):
    V = rng.normal(size=(Ni, r)).astype(np.float32)
    idx = build_index(V, shortlist_k=sk, seq=1)
    rows = np.sort(rng.choice(Ni, size=max(1, Ni // 8), replace=False))
    V2 = V.copy()
    V2[rows] = rng.normal(size=(len(rows), r)).astype(np.float32)
    upd = idx.with_updates(rows.astype(np.int64), V2[rows], seq=2)
    assert upd.delta_count == len(rows)
    assert idx.delta_count == 0          # the source index is untouched
    ref = build_index(V2, shortlist_k=sk, seq=2)
    _assert_same_topk(upd, ref, _queries(rng, 9, r), 5)


def test_delta_append_only_new_rows_matches_rebuild(rng):
    Ni, r = 80, 6
    V = rng.normal(size=(Ni, r)).astype(np.float32)
    idx = build_index(V, shortlist_k=24, seq=1)
    V2 = np.concatenate(
        [V, rng.normal(size=(7, r)).astype(np.float32)])
    rows = np.arange(Ni, Ni + 7, dtype=np.int64)
    upd = idx.with_updates(rows, V2[rows], seq=2)
    assert upd.n_items == Ni + 7 and upd.n_base == Ni
    ref = build_index(V2, shortlist_k=24, seq=2)
    _assert_same_topk(upd, ref, _queries(rng, 6, r), 5)


def test_delta_mixed_and_second_generation_merge(rng):
    """Touched + appended in one update, then a SECOND update touching
    an overlapping set — the merged segment must still be newest-wins
    bitwise-equal to a rebuild."""
    Ni, r = 100, 5
    V = rng.normal(size=(Ni, r)).astype(np.float32)
    idx = build_index(V, shortlist_k=32, seq=1)
    V2 = np.concatenate(
        [V, rng.normal(size=(4, r)).astype(np.float32)])
    rows1 = np.array([3, 50, 99, 100, 101, 102, 103], dtype=np.int64)
    V2[rows1[:3]] = rng.normal(size=(3, r)).astype(np.float32)
    g1 = idx.with_updates(rows1, V2[rows1], seq=2)
    V3 = V2.copy()
    rows2 = np.array([3, 7, 101], dtype=np.int64)   # overlaps gen 1
    V3[rows2] = rng.normal(size=(3, r)).astype(np.float32)
    g2 = g1.with_updates(rows2, V3[rows2], seq=3)
    assert g2.delta_count == len(set(rows1) | set(rows2))
    ref = build_index(V3, shortlist_k=32, seq=3)
    _assert_same_topk(g2, ref, _queries(rng, 8, r), 5)


def test_compact_is_bitwise_identical_to_rebuild(rng):
    """Compaction folds the segment back WITHOUT re-quantizing: the
    compacted base arrays must be byte-identical to a from-scratch
    rebuild of the same catalog (per-row quantization is row-local)."""
    Ni, r = 90, 4
    V = rng.normal(size=(Ni, r)).astype(np.float32)
    idx = build_index(V, shortlist_k=16, seq=1)
    V2 = np.concatenate(
        [V, rng.normal(size=(5, r)).astype(np.float32)])
    rows = np.array([0, 17, 44, 89, 90, 91, 92, 93, 94], dtype=np.int64)
    V2[rows[:4]] = rng.normal(size=(4, r)).astype(np.float32)
    comp = idx.with_updates(rows, V2[rows], seq=2).compact(seq=3)
    assert comp.delta_count == 0 and comp.n_items == Ni + 5
    ref = build_index(V2, shortlist_k=16, seq=3)
    np.testing.assert_array_equal(np.asarray(comp.Vq),
                                  np.asarray(ref.Vq))
    np.testing.assert_array_equal(np.asarray(comp.sv),
                                  np.asarray(ref.sv))
    np.testing.assert_array_equal(np.asarray(comp.valid),
                                  np.asarray(ref.valid))
    np.testing.assert_array_equal(np.asarray(comp.V),
                                  np.asarray(ref.V))
    _assert_same_topk(comp, ref, _queries(rng, 7, r), 5)


def test_delta_invalid_rows_never_surface(rng):
    """Rows updated with valid_rows=False (retired items) must never
    appear in the top-k — matching a rebuild with the same mask."""
    Ni, r, k = 40, 4, 5
    V = rng.normal(size=(Ni, r)).astype(np.float32)
    idx = build_index(V, shortlist_k=Ni, seq=1)
    rows = np.arange(0, 10, dtype=np.int64)
    mask2 = np.ones(Ni, dtype=bool)
    mask2[rows] = False
    upd = idx.with_updates(rows, V[rows],
                           valid_rows=np.zeros(10, bool), seq=2)
    ref = build_index(V, item_valid=mask2, shortlist_k=Ni, seq=2)
    U = _queries(rng, 6, r)
    _assert_same_topk(upd, ref, U, k)
    _, ix = upd.topk(U, k)
    assert not np.isin(np.asarray(ix), rows).any()


def test_delta_duplicate_scores_stay_bitwise_equal(rng):
    """Adversarial ties: identical rows live in both the base and the
    delta segment — scores must still be bitwise-equal to a rebuild."""
    Ni, r = 48, 4
    V = rng.normal(size=(Ni, r)).astype(np.float32)
    V[24:] = V[:24]                      # every score duplicated
    idx = build_index(V, shortlist_k=Ni, seq=1)
    rows = np.arange(12, 36, dtype=np.int64)
    upd = idx.with_updates(rows, V[rows], seq=2)   # same values -> ties
    ref = build_index(V, shortlist_k=Ni, seq=2)
    _assert_same_topk(upd, ref, _queries(rng, 10, r), 6)


def test_delta_append_gap_raises(rng):
    V = rng.normal(size=(30, 4)).astype(np.float32)
    idx = build_index(V, shortlist_k=8, seq=1)
    with pytest.raises(ValueError, match="append gap"):
        idx.with_updates(np.array([33], dtype=np.int64),
                         rng.normal(size=(1, 4)).astype(np.float32))


def test_delta_input_duplicates_newest_wins(rng):
    V = rng.normal(size=(30, 4)).astype(np.float32)
    idx = build_index(V, shortlist_k=8, seq=1)
    old = rng.normal(size=(1, 4)).astype(np.float32)
    new = rng.normal(size=(1, 4)).astype(np.float32)
    upd = idx.with_updates(np.array([5, 5], dtype=np.int64),
                           np.concatenate([old, new]), seq=2)
    assert upd.delta_count == 1
    V2 = V.copy()
    V2[5] = new[0]
    ref = build_index(V2, shortlist_k=8, seq=2)
    _assert_same_topk(upd, ref, _queries(rng, 4, 4), 5)


def test_retag_shares_arrays_and_quantizes_nothing(rng):
    V = rng.normal(size=(30, 4)).astype(np.float32)
    idx = build_index(V, shortlist_k=8, seq=1)
    tagged = idx.retag(7)
    assert tagged.seq == 7 and idx.seq == 1
    assert tagged.Vq is idx.Vq and tagged.sv is idx.sv


def test_nbytes_quantized_counts_the_delta(rng):
    V = rng.normal(size=(30, 4)).astype(np.float32)
    idx = build_index(V, shortlist_k=8, seq=1)
    upd = idx.with_updates(np.arange(6, dtype=np.int64),
                           V[:6], seq=2)
    assert upd.nbytes_quantized() > idx.nbytes_quantized()


def test_live_delta_index_contract_is_registered():
    from tpu_als.analysis import contracts

    assert "live_delta_index" in contracts.names()
    res = contracts.verify("live_delta_index")
    assert res.ok, res


# ---------------------------------------------------------------------------
# 2. engine incremental publish


def _published_engine(rng, n=24, Ni=300, r=6, k=5):
    eng = ServingEngine(k=k, buckets=(8,), shortlist_k=32,
                        max_wait_s=0.0)
    U = rng.normal(size=(n, r)).astype(np.float32)
    V = rng.normal(size=(Ni, r)).astype(np.float32)
    eng.publish(U, V)
    return eng, U, V


def test_publish_update_retag_delta_compact_modes(rng, _fresh):
    eng, U, V = _published_engine(rng)
    seq0 = eng.published_seq
    # user-only fold-in: nothing in the catalog changed -> retag
    seq, mode = eng.publish_update(U * 1.01, V)
    assert (seq, mode) == (seq0 + 1, "retag")
    # touched items -> delta segment, O(touched) re-quantization
    V2 = V.copy()
    V2[:8] = rng.normal(size=(8, V.shape[1])).astype(np.float32)
    seq, mode = eng.publish_update(U, V2, touched_items=np.arange(8))
    assert mode == "delta"
    assert eng.published_index.delta_count == 8
    # crossing the planner cadence's threshold folds the segment back
    cad = plan.resolve_live_cadence()
    n_big = int(max(cad["compact_min_rows"],
                    cad["compact_delta_frac"] * 300)) + 8
    V3 = V2.copy()
    V3[:n_big] = rng.normal(size=(n_big, V.shape[1])).astype(np.float32)
    seq, mode = eng.publish_update(U, V3,
                                   touched_items=np.arange(n_big))
    assert mode == "compact"
    assert eng.published_index.delta_count == 0
    # every mode priced in the publish histogram, trail carries modes
    pubs = [e for e in _fresh._events if e["type"] == "serving_publish"]
    assert [e["mode"] for e in pubs[-3:]] == ["retag", "delta",
                                              "compact"]
    priced = sum(
        _fresh.histogram_count("serving.publish_seconds", mode=m)
        for m in ("full", "retag", "delta", "compact", "none"))
    assert priced >= 4


def test_publish_update_delta_serves_bitwise_vs_rebuild(rng):
    eng, U, V = _published_engine(rng)
    V2 = V.copy()
    V2[5:15] = rng.normal(size=(10, V.shape[1])).astype(np.float32)
    eng.publish_update(U, V2, touched_items=np.arange(5, 15))
    idx = eng.published_index
    assert idx.delta_count == 10
    ref = build_index(V2, shortlist_k=idx.shortlist_k, seq=idx.seq)
    _assert_same_topk(idx, ref, _queries(rng, 8, V.shape[1]), eng.k)


def test_publish_update_malformed_update_falls_back_full(rng, _fresh):
    eng, U, V = _published_engine(rng)
    # a touched row beyond the catalog with the gap never filled is a
    # caller bug: the engine must refuse the delta and rebuild
    seq, mode = eng.publish_update(
        U, V, touched_items=np.array([V.shape[0] + 3]))
    assert mode == "full"
    warn = [e for e in _fresh._events if e["type"] == "warning"
            and e.get("what") == "serving.publish_update"]
    assert warn and "outside the catalog" in warn[-1]["reason"]


def test_publish_update_without_usable_index_is_full(rng):
    eng = ServingEngine(k=5, buckets=(8,), shortlist_k=32,
                        max_wait_s=0.0)
    U = rng.normal(size=(10, 4)).astype(np.float32)
    V = rng.normal(size=(60, 4)).astype(np.float32)
    eng.publish(U, V, quantize=False)       # serving exact: no index
    seq, mode = eng.publish_update(U, V)
    assert mode == "full"
    assert eng.published_index.seq == seq


def test_publish_update_tiny_catalog_is_none(rng):
    eng = ServingEngine(k=5, buckets=(8,), shortlist_k=32,
                        max_wait_s=0.0)
    U = rng.normal(size=(4, 3)).astype(np.float32)
    V = rng.normal(size=(3, 3)).astype(np.float32)
    eng.publish(U, V)
    seq, mode = eng.publish_update(U, V)
    assert mode == "none" and eng.published_index is None


def test_warmup_live_precompiles_without_touching_the_index(rng):
    eng, U, V = _published_engine(rng, Ni=80)
    idx = eng.published_index
    eng.warmup_live(max_delta_rows=4)
    assert eng.published_index is idx       # warmup publishes nothing
    # the delta path it warmed serves correctly afterwards
    V2 = V.copy()
    V2[:3] = rng.normal(size=(3, V.shape[1])).astype(np.float32)
    eng.publish_update(U, V2, touched_items=np.arange(3))
    ref = build_index(V2, shortlist_k=idx.shortlist_k,
                      seq=eng.published_seq)
    _assert_same_topk(eng.published_index, ref,
                      _queries(rng, 4, V.shape[1]), eng.k)


# ---------------------------------------------------------------------------
# 3. the LiveUpdater loop


def _live_stack(rng, users=24, items=20, r=4, k=5, **updater_kw):
    U = rng.normal(size=(users, r)).astype(np.float32)
    V = rng.normal(size=(items, r)).astype(np.float32)
    model = ALSModel(
        r, IdMap(ids=np.arange(users)), IdMap(ids=np.arange(items)),
        U, V, {"userCol": "u", "itemCol": "i", "ratingCol": "rt",
               "regParam": 0.05, "implicitPrefs": False,
               "alpha": 1.0, "nonnegative": False})
    eng = ServingEngine(k=k, buckets=(8,), shortlist_k=16,
                        max_wait_s=0.0)
    eng.publish(U, V)
    srv = FoldInServer(model)
    upd = LiveUpdater(eng, srv, max_batch=8, max_wait_ms=5.0,
                      **updater_kw)
    return upd, eng, srv, model


def _drain(upd, timeout=10.0):
    deadline = time.perf_counter() + timeout
    while upd.queue_depth and time.perf_counter() < deadline:
        time.sleep(0.01)
    time.sleep(0.05)


def test_updater_folds_publishes_and_measures_freshness(rng, _fresh):
    upd, eng, srv, model = _live_stack(rng, fold_items=True)
    with upd:
        for j in range(12):
            upd.submit(j % 24, j % 20, 3.0)
        upd.submit(3, 777, 4.5)             # a NEW catalog item
        _drain(upd)
    assert _fresh.histogram_count("live.freshness_seconds") == 13
    ups = [e for e in _fresh._events if e["type"] == "live_update"]
    assert ups and all(e["mode"] in ("retag", "delta", "compact")
                       for e in ups)
    assert sum(e["events"] for e in ups) == 13
    assert eng.published_index.n_items == 21    # the append is servable
    # both fold directions count their ratings (user side sees all 13;
    # the item side sees them again)
    assert _fresh.counter_value("foldin.ratings") >= 13


def test_updater_quarantines_poison_before_the_factors(rng, _fresh):
    upd, eng, srv, model = _live_stack(rng)
    U_before = np.asarray(model._U).copy()
    with upd:
        upd.submit(0, 0, float("nan"))
        upd.submit(1, 1, float("inf"))
        upd.submit(2, 2, 1e9)               # out of range
        _drain(upd)
    assert _fresh.counter_value("ingest.quarantined_rows") == 3
    q = [e for e in _fresh._events if e["type"] == "ingest_quarantined"]
    assert q and q[0]["path"] == "live"
    assert sum(e["rows"] for e in q) == 3
    # an all-poison batch folds nothing: the factors are untouched
    np.testing.assert_array_equal(np.asarray(model._U), U_before)
    assert _fresh.counter_value("foldin.ratings") == 0


def test_updater_sheds_at_capacity_with_typed_overload(rng, _fresh):
    upd, *_ = _live_stack(rng, max_queue=2)
    # not started: the queue cannot drain, so capacity is deterministic
    upd.submit(0, 0, 1.0)
    upd.submit(1, 1, 1.0)
    with pytest.raises(Overloaded):
        upd.submit(2, 2, 1.0)
    assert _fresh.counter_value("live.shed") == 1


def test_updater_submit_after_stop_raises(rng):
    upd, *_ = _live_stack(rng)
    upd.start()
    upd.stop()
    with pytest.raises(RuntimeError, match="stopped"):
        upd.submit(0, 0, 1.0)


def test_updater_slo_breach_emits_and_dumps_flight_ring(rng, _fresh):
    upd, *_ = _live_stack(rng, fold_items=True, slo_s=1e-9)
    with upd:
        upd.submit(0, 0, 3.0)
        upd.submit(1, 3, 2.0)
        _drain(upd)
    breaches = [e for e in _fresh._events
                if e["type"] == "live_freshness_breach"]
    assert breaches
    assert breaches[0]["freshness_seconds"] > breaches[0]["slo_s"]
    dumps = [e for e in _fresh._events if e["type"] == "flight_record"
             and e.get("trigger") == "freshness_breach"]
    assert dumps
    for d in dumps:
        assert set(d["spans"]) == set(LIVE_SPAN_KEYS)
        assert d["spans"]["foldin"] is not None


def _wait_for(pred, timeout=10.0):
    deadline = time.perf_counter() + timeout
    while not pred() and time.perf_counter() < deadline:
        time.sleep(0.01)
    assert pred(), "condition not reached before timeout"


def test_updater_loop_survives_processing_errors(rng, _fresh):
    """Queue drain is NOT processing completion, so each step waits on
    the obs trail itself before mutating the fold path."""
    upd, eng, srv, model = _live_stack(rng)

    def _warns():
        return [e for e in _fresh._events if e["type"] == "warning"
                and e.get("what") == "live.update"]

    with upd:
        upd.submit(0, 0, 3.0)
        _wait_for(lambda: _fresh.histogram_count(
            "live.freshness_seconds") == 1)
        srv.model = None                    # sabotage the fold path
        upd.submit(1, 1, 3.0)
        _wait_for(lambda: len(_warns()) >= 1)
        srv.model = model                   # and the loop still serves
        upd.submit(2, 2, 3.0)
        _wait_for(lambda: _fresh.histogram_count(
            "live.freshness_seconds") == 2)
    assert _warns()
    assert _fresh.histogram_count("live.freshness_seconds") == 2


def test_foldin_stats_ring_is_bounded(rng):
    upd, eng, srv, model = _live_stack(rng)
    srv.stats = type(srv.stats)(maxlen=3)
    for j in range(6):
        srv.update({"u": np.array([j % 24]), "i": np.array([j % 20]),
                    "rt": np.array([3.0], dtype=np.float32)})
    assert len(srv.stats) == 3
    srv2 = FoldInServer(model, stats_window=5)
    assert srv2.stats.maxlen == 5


def test_resolve_live_cadence_defaults_and_overrides():
    cad = plan.resolve_live_cadence()
    assert set(cad) == set(plan.DEFAULT_LIVE_CADENCE)
    assert cad["max_batch"] >= 1 and cad["max_wait_ms"] > 0
    merged = plan.resolve_live_cadence(requested={"max_batch": 7})
    assert merged["max_batch"] == 7
    assert merged["compact_min_rows"] == cad["compact_min_rows"]


def test_flight_recorder_custom_span_keys():
    fr = FlightRecorder(4, span_keys=("alpha", "beta"))
    fr.record("ok", {"alpha": 0.5}, note=1)
    fr.dump("test_trigger")
    recs = [e for e in obs.default_registry()._events
            if e["type"] == "flight_record"]
    assert recs and set(recs[0]["spans"]) == {"alpha", "beta"}
    assert recs[0]["spans"]["beta"] is None


# ---------------------------------------------------------------------------
# serve-bench --update-qps (the live SLO report)


def test_serve_bench_cli_live_mode_reports_freshness(tmp_path, capsys):
    from tpu_als.cli import main

    bank = tmp_path / "BENCH_live_test.json"
    main(["serve-bench", "--users", "64", "--items", "48",
          "--rank", "4", "--k", "5", "--shortlist-k", "16",
          "--qps", "30", "--duration", "0.4", "--slo-ms", "5000",
          "--buckets", "8",
          "--update-qps", "50", "--update-items",
          "--update-poison-frac", "0.1",
          "--update-max-batch", "8", "--update-max-wait-ms", "10",
          "--freshness-slo-ms", "30000",
          "--bench-json", str(bank)])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["metric"] == "live_freshness_p99_ms"
    assert out["value"] > 0 and out["slo_met"] is True
    assert out["live"]["events_scored"] > 0
    assert out["live"]["quarantined_rows"] >= 1
    assert set(out["live"]["publish_modes"]) <= {"retag", "delta",
                                                 "compact"}
    assert out["live"]["publish_delta_ms"] > 0
    assert out["serve"]["p99_ms"] > 0
    banked = json.loads(bank.read_text())
    assert banked["banked_at"].endswith("+00:00")
    assert banked["metric"] == "live_freshness_p99_ms"
