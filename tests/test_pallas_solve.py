"""Pallas batched SPD solver vs scipy/XLA reference (interpret mode on the
CPU test mesh; the same kernel compiles for real on TPU)."""

import numpy as np
import jax.numpy as jnp
import pytest

from tpu_als.ops.pallas_solve import spd_solve_pallas
from tpu_als.ops.solve import solve_spd


def _spd_problem(rng, N, r, scale=1.0):
    M = rng.normal(size=(N, r, r)).astype(np.float32) * scale
    A = M @ M.transpose(0, 2, 1) + 0.5 * np.eye(r, dtype=np.float32)
    b = rng.normal(size=(N, r)).astype(np.float32)
    return jnp.asarray(A), jnp.asarray(b)


@pytest.mark.parametrize("N,r", [
    (5, 4),       # rank below one panel, tiny batch
    (37, 10),     # the ALS default rank
    (100, 32),    # exactly one panel
    (33, 128),    # the benchmark rank, batch not tile-aligned
    (20, 130),    # rank above a lane tile and not panel-aligned
])
def test_matches_dense_solve(rng, N, r):
    A, b = _spd_problem(rng, N, r)
    x = np.asarray(spd_solve_pallas(A, b, interpret=True))
    ref = np.stack([np.linalg.solve(np.asarray(A)[k], np.asarray(b)[k])
                    for k in range(N)])
    denom = max(1.0, np.abs(ref).max())
    assert np.abs(x - ref).max() / denom < 5e-3


def test_matches_solve_spd_contract(rng):
    # same prep as solve_spd: empty rows (count=0) -> identity A, zero b
    N, r = 24, 16
    A, b = _spd_problem(rng, N, r)
    count = np.ones(N, np.float32)
    count[::5] = 0.0
    b = jnp.asarray(np.where(count[:, None] > 0, np.asarray(b), 0.0))
    x_ref = solve_spd(A, b, jnp.asarray(count), backend="xla")
    eye = jnp.eye(r)
    Ap = jnp.where((count <= 0)[:, None, None], eye, A) + 1e-6 * eye
    x_pal = spd_solve_pallas(Ap, b, interpret=True)
    np.testing.assert_allclose(np.asarray(x_pal), np.asarray(x_ref),
                               atol=2e-4, rtol=2e-3)
    assert (np.asarray(x_pal)[::5] == 0).all()


def test_ill_conditioned_stays_finite(rng):
    # weighted-lambda ridge keeps ALS systems SPD but spread in scale
    N, r = 16, 64
    A, b = _spd_problem(rng, N, r, scale=30.0)
    x = np.asarray(spd_solve_pallas(A, b, interpret=True))
    assert np.isfinite(x).all()


class TestAvailableProbe:
    """The available() probe must validate real factorization arithmetic:
    a kernel producing finite-but-wrong output has to fail it, and one
    producing correct output has to pass (VERDICT r1 weak #4)."""

    def _probe(self, monkeypatch, fake_kernel):
        from tpu_als.ops import pallas_solve
        from tpu_als.utils import platform

        monkeypatch.setattr(platform, "on_tpu", lambda: True)
        monkeypatch.setattr(pallas_solve, "_AVAILABLE", {})
        monkeypatch.setattr(pallas_solve, "spd_solve_pallas", fake_kernel)
        return pallas_solve.available(32)

    def test_rejects_wrong_but_finite_kernel(self, monkeypatch):
        # returns b unchanged: finite, right shape, wrong values — the
        # exact failure mode an identity-matrix-only probe cannot see
        assert self._probe(
            monkeypatch, lambda A, b, panel=32, interpret=False: b) is False

    def test_rejects_crashing_kernel(self, monkeypatch):
        def boom(A, b, panel=32, interpret=False):
            raise RuntimeError("mosaic compile failure")

        assert self._probe(monkeypatch, boom) is False

    def test_accepts_correct_kernel(self, monkeypatch):
        assert self._probe(
            monkeypatch,
            lambda A, b, panel=32, interpret=False: jnp.linalg.solve(
                A, b[..., None])[..., 0],
        ) is True
