"""Test harness: force an 8-device CPU mesh before JAX initializes.

This is the direct analog of the reference stack's
``local-cluster[2,1,1024]`` test masters (SURVEY.md §4): multi-device
semantics exercised in one process, no real TPU pod required.  Must run
before any ``import jax`` in the test session.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

# Hermetic execution planner: without this, every resolve_solve_path call
# in the suite would read/write the developer's real autotune cache
# (~/.cache/tpu_als/plan) and test outcomes would depend on what previous
# runs banked there.  One throwaway dir per session keeps the suite
# cold-start deterministic; tests that need their own cache (or the
# disarmed mode) monkeypatch TPU_ALS_PLAN_CACHE on top.
if "TPU_ALS_PLAN_CACHE" not in os.environ:
    import tempfile

    os.environ["TPU_ALS_PLAN_CACHE"] = os.path.join(
        tempfile.mkdtemp(prefix="tpu_als_plan_test_"), "plan")

import jax  # noqa: E402

# The axon TPU plugin in this environment ignores JAX_PLATFORMS=cpu from the
# environment; the config knob does work and must be set before first use.
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)


def make_ratings(rng, num_users=60, num_items=40, rank=4, density=0.3, noise=0.0):
    """Synthetic low-rank ground truth — the reference test protocol
    (ALSSuite.genFactors/testALS, SURVEY.md §4.1)."""
    Ustar = rng.normal(0, 1.0 / np.sqrt(rank), (num_users, rank)).astype(np.float32)
    Vstar = rng.normal(0, 1.0 / np.sqrt(rank), (num_items, rank)).astype(np.float32)
    full = Ustar @ Vstar.T
    mask = rng.random((num_users, num_items)) < density
    # guarantee every user/item has at least one rating
    mask[np.arange(num_users), rng.integers(0, num_items, num_users)] = True
    mask[rng.integers(0, num_users, num_items), np.arange(num_items)] = True
    u, i = np.nonzero(mask)
    r = full[u, i] + noise * rng.normal(size=len(u)).astype(np.float32)
    return u.astype(np.int64), i.astype(np.int64), r.astype(np.float32), Ustar, Vstar


@pytest.fixture(autouse=True, scope="module")
def _bound_live_executables_per_module(request):
    """Drop jax's compiled-program caches after compile-heavy modules.

    The CPU harness compiles thousands of tiny executables in ONE
    process across 35+ modules; jaxlib's CPU JIT segfaults inside
    ``backend_compile_and_load`` once too many live executables
    accumulate — reproducibly at the same compile in two full-suite
    runs (test_stream_io's first fold-in jit, test ~380 of 408), while
    every subset of the suite passes.  Clearing after every module that
    ran a ``slow``-marked test (the interpret-mode Pallas, spawned-
    process, and e2e modules are where the executables pile up) keeps
    the live count at fast-tier levels — which ran the whole history of
    this repo without ever hitting the limit — while the fast tier
    itself (``-m "not slow"``) pays no recompiles at all.  TPU/bench
    runs never load this conftest and are unaffected.
    """
    yield
    mod = request.node
    for item in request.session.items:
        if (item.getparent(pytest.Module) is mod
                and item.get_closest_marker("slow") is not None):
            jax.clear_caches()
            return
