"""Blocking/bucketing unit tests — SURVEY.md §4 mapping item 3.

The reference suite round-trips LocalIndexEncoder and the in-block
compression (ALSSuite); here the analogous invariants are: CSR
blockify/unblockify round-trip, padding invariants, and id-remap round-trip.
"""

import numpy as np

from tpu_als.core.ratings import build_csr_buckets, remap_ids


def coo_from_buckets(csr):
    rows, cols, vals = [], [], []
    for b in csr.buckets:
        r, c = np.nonzero(b.mask)
        rows.append(b.rows[r])
        cols.append(b.cols[r, c])
        vals.append(b.vals[r, c])
    return (
        np.concatenate(rows),
        np.concatenate(cols),
        np.concatenate(vals),
    )


def test_roundtrip(rng):
    n_rows, n_cols, nnz = 50, 30, 400
    row = rng.integers(0, n_rows, nnz)
    col = rng.integers(0, n_cols, nnz)
    val = rng.normal(size=nnz).astype(np.float32)
    csr = build_csr_buckets(row, col, val, n_rows, min_width=4)
    assert csr.nnz == nnz
    r2, c2, v2 = coo_from_buckets(csr)
    assert len(r2) == nnz
    order_a = np.lexsort((v2, c2, r2))
    order_b = np.lexsort((val, col, row))
    np.testing.assert_array_equal(r2[order_a], row[order_b])
    np.testing.assert_array_equal(c2[order_a], col[order_b])
    np.testing.assert_allclose(v2[order_a], val[order_b])


def test_bucket_invariants(rng):
    row = rng.integers(0, 100, 1000)
    col = rng.integers(0, 60, 1000)
    val = np.ones(1000, dtype=np.float32)
    csr = build_csr_buckets(row, col, val, 100, min_width=8)
    widths = [b.width for b in csr.buckets]
    assert widths == sorted(widths)
    for b in csr.buckets:
        # width is a power of two >= min_width
        assert b.width >= 8 and (b.width & (b.width - 1)) == 0
        # per-row entry counts fit the width and exceed half of it (or min)
        per_row = b.mask.sum(axis=1)
        real = b.rows < csr.num_rows
        assert np.all(per_row[real] <= b.width)
        if b.width > 8:
            assert np.all(per_row[real] > b.width // 2)
        # padding rows are fully masked out and scatter out-of-bounds
        assert np.all(per_row[~real] == 0)
        assert np.all(b.rows[~real] == csr.num_rows)
    # counts match
    np.testing.assert_array_equal(csr.counts, np.bincount(row, minlength=100))


def test_rows_with_zero_ratings_absent(rng):
    row = np.array([0, 0, 2, 5])
    col = np.array([1, 2, 0, 3])
    val = np.ones(4, dtype=np.float32)
    csr = build_csr_buckets(row, col, val, 7, min_width=2)
    present = np.concatenate([b.rows[b.rows < 7] for b in csr.buckets])
    assert set(present.tolist()) == {0, 2, 5}
    assert csr.counts[1] == 0 and csr.counts[6] == 0


def test_remap_roundtrip(rng):
    raw = rng.choice(np.array([7, 42, 1000000007, -3, 8]), size=200)
    dense, idmap = remap_ids(raw)
    assert dense.min() >= 0 and dense.max() < len(idmap)
    np.testing.assert_array_equal(idmap.to_original(dense), raw)
    np.testing.assert_array_equal(idmap.to_dense(raw), dense)
    # unseen ids map to missing
    assert idmap.to_dense(np.array([999]))[0] == -1


def test_duplicate_entries_kept(rng):
    row = np.array([1, 1, 1])
    col = np.array([2, 2, 3])
    val = np.array([1.0, 2.0, 3.0], dtype=np.float32)
    csr = build_csr_buckets(row, col, val, 3, min_width=2)
    r2, c2, v2 = coo_from_buckets(csr)
    assert sorted(v2.tolist()) == [1.0, 2.0, 3.0]


def test_large_bucket_chunking_pads_instead_of_collapsing(rng):
    # odd row count larger than the scan chunk: the builder must pad rows up
    # to a chunk multiple, not shrink the chunk (a gcd fallback to 1 would
    # serialize the hot loop)
    from tpu_als.core.ratings import scan_chunk, trainer_chunk

    nnz_rows = 101  # odd
    row = np.repeat(np.arange(nnz_rows), 3)
    col = rng.integers(0, 10, len(row))
    val = np.ones(len(row), dtype=np.float32)
    csr = build_csr_buckets(row, col, val, nnz_rows, min_width=4,
                            chunk_elems=4 * 10)  # chunk cap: 10 -> pow2 8
    b = csr.buckets[0]
    chunk = scan_chunk(b.rows.shape[0], b.width, csr.chunk_elems)
    assert chunk == 8
    assert b.rows.shape[0] == 104  # padded to a chunk multiple, not 1-chunks
    assert trainer_chunk(b.rows.shape[0], b.width, 4, csr.chunk_elems) == 8


def test_trainer_chunk_caps_rank_dominated_memory():
    from tpu_als.core.ratings import trainer_chunk

    # w=8, rank=128: builder chunk is 65536 rows, but A is chunk*r*r —
    # the trainer must halve until chunk*r*max(w,r) fits the budget
    c = trainer_chunk(131072, 8, 128, 1 << 19, mem_elems=1 << 28)
    assert c * 128 * 128 <= 1 << 28
    assert c >= 1 and 131072 % c == 0
    # rank smaller than width: memory never forces a halving below the
    # builder chunk (the ~nb/16 scan cap, not the rank, decides)
    assert trainer_chunk(1024, 512, 16, 1 << 19) == 64


def test_native_bucketizer_bit_identical(rng):
    from tpu_als.io import fastbucket

    if not fastbucket.available():
        import pytest

        pytest.skip("g++ unavailable")
    # power-law degrees + rows with zero ratings + duplicates
    n_rows, n_cols, nnz = 500, 90, 6000
    rows = (rng.zipf(1.4, nnz) % n_rows).astype(np.int64)
    cols = rng.integers(0, n_cols, nnz)
    vals = rng.random(nnz).astype(np.float32)
    a = build_csr_buckets(rows, cols, vals, n_rows, native=False)
    b = build_csr_buckets(rows, cols, vals, n_rows, native=True)
    assert a.nnz == b.nnz and (a.counts == b.counts).all()
    assert len(a.buckets) == len(b.buckets)
    for x, y in zip(a.buckets, b.buckets):
        np.testing.assert_array_equal(x.rows, y.rows)
        np.testing.assert_array_equal(x.cols, y.cols)
        np.testing.assert_array_equal(x.vals, y.vals)
        np.testing.assert_array_equal(x.mask, y.mask)


def test_native_bucketizer_non_pow2_min_width(rng):
    # regression: non-power-of-two min_width once crashed the native path
    from tpu_als.io import fastbucket

    if not fastbucket.available():
        import pytest

        pytest.skip("g++ unavailable")
    rows = rng.integers(0, 40, 300)
    cols = rng.integers(0, 25, 300)
    vals = rng.random(300).astype(np.float32)
    a = build_csr_buckets(rows, cols, vals, 40, min_width=6, native=False)
    b = build_csr_buckets(rows, cols, vals, 40, min_width=6, native=True)
    for x, y in zip(a.buckets, b.buckets):
        np.testing.assert_array_equal(x.rows, y.rows)
        np.testing.assert_array_equal(x.cols, y.cols)
        np.testing.assert_array_equal(x.vals, y.vals)
        np.testing.assert_array_equal(x.mask, y.mask)


def test_native_counts_bounds_checked(rng):
    from tpu_als.io import fastbucket

    if not fastbucket.available():
        import pytest

        pytest.skip("g++ unavailable")
    import pytest

    with pytest.raises(ValueError, match="row indices"):
        fastbucket.counts(np.array([0, 5, -1]), 10)
    with pytest.raises(ValueError, match="row indices"):
        fastbucket.counts(np.array([0, 10]), 10)


def test_width_growth_ladder(rng):
    """growth=1.5 adds the 0.75*2^k rungs that are sublane multiples and
    never shrinks a row below its rating count."""
    from tpu_als.core.ratings import entity_widths

    counts = np.arange(1, 400)
    w2 = entity_widths(counts, 8)
    w15 = entity_widths(counts, 8, growth=1.5)
    assert (w15 >= counts).all()
    assert (w15 <= w2).all()
    assert (w15 % 8 == 0).all()
    # the new rungs actually appear and help: count=20 -> 24 not 32
    assert entity_widths([20], 8, growth=1.5)[0] == 24
    assert entity_widths([40], 8, growth=1.5)[0] == 48
    # but 12 is not a sublane multiple, so count=10 stays at 16
    assert entity_widths([10], 8, growth=1.5)[0] == 16


def test_width_growth_end_to_end(rng):
    """Blocking with growth=1.5 reduces padded nnz and trains to the same
    factors (bucketization must not change the math)."""
    from conftest import make_ratings
    from tpu_als.core.als import AlsConfig, train

    u, i, r, _, _ = make_ratings(np.random.default_rng(6), 80, 50,
                                 rank=3, density=0.5)
    a = build_csr_buckets(u, i, r, 80, min_width=8)
    b = build_csr_buckets(u, i, r, 80, min_width=8, width_growth=1.5)
    assert b.padded_nnz <= a.padded_nnz
    ia = build_csr_buckets(i, u, r, 50, min_width=8)
    ib = build_csr_buckets(i, u, r, 50, min_width=8, width_growth=1.5)
    cfg = AlsConfig(rank=4, max_iter=3, reg_param=0.05, seed=0)
    Ua, Va = train(a, ia, cfg)
    Ub, Vb = train(b, ib, cfg)
    np.testing.assert_allclose(np.asarray(Ub), np.asarray(Ua),
                               rtol=2e-3, atol=2e-3)


def test_width_growth_native_matches_numpy(rng):
    from tpu_als.io import fastbucket

    if not fastbucket.available():
        import pytest
        pytest.skip("native bucketizer unavailable")
    rows = rng.integers(0, 60, 800).astype(np.int64)
    cols = rng.integers(0, 40, 800).astype(np.int64)
    vals = rng.normal(size=800).astype(np.float32)
    a = build_csr_buckets(rows, cols, vals, 60, native=False,
                          width_growth=1.5)
    b = build_csr_buckets(rows, cols, vals, 60, native=True,
                          width_growth=1.5)
    assert [x.width for x in a.buckets] == [x.width for x in b.buckets]
    for x, y in zip(a.buckets, b.buckets):
        np.testing.assert_array_equal(x.rows, y.rows)
        np.testing.assert_array_equal(x.cols, y.cols)
        np.testing.assert_array_equal(x.vals, y.vals)
