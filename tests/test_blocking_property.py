"""Property-based fuzzing of blocking / CSR round-trips at adversarial
degree distributions (VERDICT r4 #9; SURVEY.md §7 hard-part 1).

The invariants under test, for ANY degree distribution:

1. lossless: reassembling (row, col, val) triples from the padded
   buckets recovers exactly the input multiset — padding slots carry
   mask 0 and harm nothing;
2. bounded waste: padded_nnz <= 2x nnz + bucket-count x chunk floors
   (power-of-two bucketing's contract);
3. zero-degree entities: never appear as bucket rows, factors solve to
   exactly 0 and stay finite, and sharded == single-device training
   still holds;
4. degenerate skew (one mega-user owning >50% of nnz, all-singleton
   tails, empty shards after partitioning) breaks neither the balance
   partitioner nor the trainer equivalence.

Deterministic "fuzz": a seeded battery of adversarial generators, so a
failure reproduces by case name.
"""

import numpy as np
import pytest

from tpu_als.core.ratings import build_csr_buckets
from tpu_als.parallel.data import partition_balanced, shard_csr


def _roundtrip_triples(csr):
    """Reassemble (row, col, val) triples from the padded buckets."""
    rows, cols, vals = [], [], []
    for b in csr.buckets:
        m = b.mask.astype(bool)
        valid = b.rows < csr.num_rows
        m = m & valid[:, None]
        rr = np.repeat(b.rows, b.width).reshape(b.mask.shape)
        rows.append(rr[m])
        cols.append(b.cols[m])
        vals.append(b.vals[m])
    return (np.concatenate(rows), np.concatenate(cols),
            np.concatenate(vals))


def _sorted_triples(u, i, r):
    order = np.lexsort((r, i, u))
    return u[order], i[order], r[order]


# name -> (num_rows, generator(rng) -> (row_idx, col_idx, vals))
def _mega_user(rng):
    # one user owns 60% of nnz; the rest spread over a power-law tail
    n_mega = 1200
    tail_u = rng.integers(1, 200, 800)
    u = np.concatenate([np.zeros(n_mega, np.int64), tail_u])
    i = rng.integers(0, 150, len(u))
    return u, i, rng.uniform(0.5, 5, len(u)).astype(np.float32)


def _half_zero_degree(rng):
    # only even users rate anything: every odd user is a cold row
    u = rng.integers(0, 100, 1500) * 2
    i = rng.integers(0, 80, 1500)
    return u, i, rng.uniform(0.5, 5, 1500).astype(np.float32)


def _all_singletons(rng):
    # every user has exactly one rating: min_width padding dominates
    u = np.arange(180, dtype=np.int64)
    i = rng.integers(0, 60, 180)
    return u, i, rng.uniform(0.5, 5, 180).astype(np.float32)


def _pow2_boundaries(rng):
    # degrees sitting exactly at and one past every pow2 boundary
    rows, cols = [], []
    uid = 0
    for deg in (1, 2, 3, 4, 5, 8, 9, 16, 17, 32, 33):
        rows.append(np.full(deg, uid, np.int64))
        cols.append(rng.integers(0, 64, deg))
        uid += 1
    u = np.concatenate(rows)
    return u, np.concatenate(cols), \
        rng.uniform(0.5, 5, len(u)).astype(np.float32)


def _duplicate_pairs(rng):
    # the same (user, item) pair rated repeatedly (legal: multiset)
    u = rng.integers(0, 40, 900)
    i = rng.integers(0, 30, 900)
    sel = rng.integers(0, 900, 300)
    u = np.concatenate([u, u[sel]])
    i = np.concatenate([i, i[sel]])
    return u, i, rng.uniform(0.5, 5, len(u)).astype(np.float32)


CASES = {
    "mega_user": (202, _mega_user),
    "half_zero_degree": (200, _half_zero_degree),
    "all_singletons": (190, _all_singletons),
    "pow2_boundaries": (40, _pow2_boundaries),
    "duplicate_pairs": (40, _duplicate_pairs),
}


@pytest.mark.parametrize("case", sorted(CASES))
@pytest.mark.parametrize("seed", [0, 7])
def test_bucket_roundtrip_is_lossless(case, seed):
    num_rows, gen = CASES[case]
    u, i, r = gen(np.random.default_rng(seed))
    csr = build_csr_buckets(u, i, r, num_rows, min_width=4)
    gu, gi, gr = _roundtrip_triples(csr)
    np.testing.assert_array_equal(
        np.stack(_sorted_triples(gu, gi, gr)),
        np.stack(_sorted_triples(u.astype(np.int64),
                                 i.astype(np.int64), r)))
    assert csr.nnz == len(u)
    # counts match the true degree histogram (zero rows included)
    np.testing.assert_array_equal(
        csr.counts, np.bincount(u, minlength=num_rows))


@pytest.mark.parametrize("case", sorted(CASES))
def test_padding_waste_is_bounded(case):
    num_rows, gen = CASES[case]
    u, i, r = gen(np.random.default_rng(1))
    csr = build_csr_buckets(u, i, r, num_rows, min_width=4)
    # pow2 bucketing's per-row contract: width <= max(2*degree,
    # min_width), so total padded slots are bounded by their sum
    assert csr.padded_nnz <= \
        2 * csr.nnz + 4 * int((csr.counts > 0).sum())


@pytest.mark.parametrize("case", sorted(CASES))
def test_zero_degree_rows_never_appear(case):
    num_rows, gen = CASES[case]
    u, i, r = gen(np.random.default_rng(2))
    csr = build_csr_buckets(u, i, r, num_rows, min_width=4)
    present = np.unique(np.concatenate(
        [b.rows[b.rows < csr.num_rows] for b in csr.buckets]))
    assert set(present.tolist()) == set(np.unique(u).tolist())


@pytest.mark.slow
@pytest.mark.parametrize("case", sorted(CASES))
def test_training_equivalence_and_cold_rows(case, rng):
    """Sharded (8-device) == single-device training on every adversarial
    distribution, and zero-degree factors are exactly 0."""
    import jax.numpy as jnp

    from tpu_als.core.als import AlsConfig, init_factors, train
    from tpu_als.parallel.mesh import make_mesh
    from tpu_als.parallel.trainer import train_sharded

    num_rows, gen = CASES[case]
    u, i, r = gen(np.random.default_rng(3))
    nI = int(i.max()) + 1
    cfg = AlsConfig(rank=4, max_iter=2, reg_param=0.05,
                    implicit_prefs=True, alpha=2.0, seed=0)
    ucsr = build_csr_buckets(u, i, r, num_rows, min_width=4)
    icsr = build_csr_buckets(i, u, r, nI, min_width=4)
    U0, V0 = train(ucsr, icsr, cfg)
    U0, V0 = np.asarray(U0), np.asarray(V0)

    cold = np.setdiff1d(np.arange(num_rows), u)
    assert np.isfinite(U0).all()
    if len(cold):
        np.testing.assert_array_equal(U0[cold], 0.0)

    D = 8
    upart = partition_balanced(np.bincount(u, minlength=num_rows), D)
    ipart = partition_balanced(np.bincount(i, minlength=nI), D)
    ush = shard_csr(upart, ipart, u, i, r, min_width=4)
    ish = shard_csr(ipart, upart, i, u, r, min_width=4)
    U1, V1 = train_sharded(make_mesh(D), upart, ipart, ush, ish, cfg)
    np.testing.assert_allclose(np.asarray(U1)[upart.slot], U0,
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(V1)[ipart.slot], V0,
                               rtol=2e-5, atol=2e-5)
