"""Streaming fold-in driver + MovieLens IO tests."""

import numpy as np
import pytest

from tpu_als import ALS, ColumnarFrame
from tpu_als.io.movielens import (
    load_movielens_100k,
    load_movielens_csv,
    synthetic_movielens,
)
from tpu_als.stream.microbatch import FoldInServer

from conftest import make_ratings


def _fitted(rng):
    u, i, r, _, _ = make_ratings(rng, 50, 40, rank=3, density=0.4)
    frame = ColumnarFrame({"user": u, "item": i, "rating": r})
    return ALS(rank=3, maxIter=6, regParam=0.05, seed=0).fit(frame), frame


def test_foldin_server_improves_new_user(rng):
    model, frame = _fitted(rng)
    V = model._V
    # a brand-new user whose tastes follow item-factor direction 0
    pref = V[:, 0]
    top_items = np.argsort(-pref)[:8]
    item_ids = model._item_map.to_original(top_items)
    batch = ColumnarFrame({
        "user": np.full(8, 777_777),
        "item": item_ids,
        "rating": np.full(8, 5.0, dtype=np.float32),
    })
    srv = FoldInServer(model)
    touched = srv.update(batch)
    assert touched.tolist() == [777_777]
    # the new user now exists and predicts high on their liked items
    preds = model.transform(batch)["prediction"]
    assert np.isfinite(preds).all()
    other_items = model._item_map.to_original(np.argsort(pref)[:8])
    low = model.transform(ColumnarFrame({
        "user": np.full(8, 777_777), "item": other_items,
        "rating": np.zeros(8, dtype=np.float32)}))["prediction"]
    assert preds.mean() > low.mean()


def test_foldin_server_existing_user_history_merge(rng):
    model, frame = _fitted(rng)
    uid = int(model._user_map.ids[0])
    before = model._U[0].copy()
    batch = ColumnarFrame({
        "user": np.array([uid]),
        "item": np.array([int(model._item_map.ids[0])]),
        "rating": np.array([5.0], dtype=np.float32),
    })
    srv = FoldInServer(model)
    srv.update(batch)
    after = model._U[model._user_map.to_dense(np.array([uid]))[0]]
    assert not np.allclose(before, after)
    assert len(srv.stats) == 1
    assert np.isfinite(srv.p50_latency())


def test_foldin_server_prewarm_matches_serving_shapes(rng):
    # prewarm compiles the same jit entries update() later hits: after
    # prewarming the grid, a batch whose padded shape is in the grid adds
    # no new cache entry (its latency is serve-only)
    model, frame = _fitted(rng)
    srv = FoldInServer(model)
    srv.prewarm(rows=(4,), widths=(8,))
    from tpu_als.core import foldin as foldin_mod

    sizes0 = foldin_mod._fold_in_jit._cache_size()
    batch = ColumnarFrame({
        "user": np.array([1, 1, 1, 1, 1, 2, 3]),
        "item": model._item_map.to_original(
            np.array([0, 1, 2, 3, 4, 5, 6])),
        "rating": np.full(7, 4.0, np.float32),
    })
    srv.update(batch)  # 3 touched users -> rows pad to 4; max count 5 ->
    # width pads to 8: exactly the prewarmed (4, 8) entry
    assert foldin_mod._fold_in_jit._cache_size() == sizes0


def test_foldin_server_unknown_items_ignored(rng):
    model, _ = _fitted(rng)
    srv = FoldInServer(model)
    batch = ColumnarFrame({
        "user": np.array([1, 2]),
        "item": np.array([10**9, 10**9 + 1]),  # never trained
        "rating": np.array([5.0, 5.0], dtype=np.float32),
    })
    touched = srv.update(batch)
    assert len(touched) == 0


def test_foldin_server_new_item(rng):
    """Symmetric item fold-in: a brand-new item rated 5.0 by a cohort of
    users must (a) become transformable with finite scores, (b) score
    higher for its raters than an anti-cohort, and (c) be visible to
    SUBSEQUENT user fold-ins (the server's cached V refreshes)."""
    model, frame = _fitted(rng)
    U = model._U
    pref = U[:, 1]
    raters = model._user_map.to_original(np.argsort(-pref)[:8])
    anti = model._user_map.to_original(np.argsort(pref)[:8])
    new_item = 888_888
    batch = ColumnarFrame({
        "user": raters,
        "item": np.full(8, new_item),
        "rating": np.full(8, 5.0, dtype=np.float32),
    })
    srv = FoldInServer(model)
    touched = srv.update_items(batch)
    assert touched.tolist() == [new_item]
    hi = model.transform(ColumnarFrame({
        "user": raters, "item": np.full(8, new_item),
        "rating": np.zeros(8, np.float32)}))["prediction"]
    lo = model.transform(ColumnarFrame({
        "user": anti, "item": np.full(8, new_item),
        "rating": np.zeros(8, np.float32)}))["prediction"]
    assert np.isfinite(hi).all() and hi.mean() > lo.mean()
    # a user folded in AFTER the item sees it (cache refreshed): a new
    # user who rates ONLY the new item gets a factor along its direction
    ubatch = ColumnarFrame({
        "user": np.array([999_999]),
        "item": np.array([new_item]),
        "rating": np.array([5.0], np.float32),
    })
    assert srv.update(ubatch).tolist() == [999_999]
    p = model.transform(ColumnarFrame({
        "user": np.array([999_999]), "item": np.array([new_item]),
        "rating": np.zeros(1, np.float32)}))["prediction"]
    assert np.isfinite(p).all() and p[0] > 0


def test_foldin_item_matches_item_half_step(rng):
    """update_items == the item half-step restricted to the touched item
    (same math oracle the user fold-in tests pin)."""
    import jax.numpy as jnp

    from tpu_als.core.foldin import fold_in

    model, frame = _fitted(rng)
    iid = int(model._item_map.ids[3])
    dense_i = 3
    # exact expected factor: regress the item's (training) ratings on U
    u = np.asarray(frame["user"])
    i = np.asarray(frame["item"])
    r = np.asarray(frame["rating"])
    sel = i == iid
    ud = model._user_map.to_dense(u[sel])
    w = len(ud)
    cols = np.zeros((1, w), np.int32); cols[0] = ud
    vals = np.zeros((1, w), np.float32); vals[0] = r[sel]
    mask = np.ones((1, w), np.float32)
    want = np.asarray(fold_in(
        jnp.asarray(model._U), jnp.asarray(cols), jnp.asarray(vals),
        jnp.asarray(mask), 0.05))[0]

    srv = FoldInServer(model)
    srv.update_items(ColumnarFrame({
        "user": u[sel], "item": i[sel], "rating": r[sel]}))
    got = model._V[dense_i]
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_foldin_item_unknown_users_ignored(rng):
    model, _ = _fitted(rng)
    srv = FoldInServer(model)
    touched = srv.update_items(ColumnarFrame({
        "user": np.array([10**9, 10**9 + 1]),  # never trained
        "item": np.array([5, 5]),
        "rating": np.array([5.0, 5.0], np.float32),
    }))
    assert len(touched) == 0


def test_synthetic_movielens_shape_and_determinism():
    f1 = synthetic_movielens(200, 100, 5000, seed=3)
    f2 = synthetic_movielens(200, 100, 5000, seed=3)
    assert len(f1) == 5000
    np.testing.assert_array_equal(f1["user"], f2["user"])
    np.testing.assert_array_equal(f1["rating"], f2["rating"])
    assert f1["rating"].min() >= 0.5 and f1["rating"].max() <= 5.0
    # half-star grid
    assert np.all((f1["rating"] * 2) == np.round(f1["rating"] * 2))
    assert f1["user"].max() < 200 and f1["item"].max() < 100


def test_movielens_loaders(tmp_path):
    # u.data format
    udata = tmp_path / "u.data"
    udata.write_text("1\t10\t5\t100\n2\t20\t3\t200\n")
    f = load_movielens_100k(str(tmp_path))
    assert f["user"].tolist() == [1, 2]
    assert f["rating"].tolist() == [5.0, 3.0]
    # ratings.csv format
    csv = tmp_path / "ratings.csv"
    csv.write_text("userId,movieId,rating,timestamp\n1,10,4.5,99\n3,11,2.0,98\n")
    f2 = load_movielens_csv(str(csv))
    assert f2["user"].tolist() == [1, 3]
    assert f2["rating"].tolist() == [4.5, 2.0]
    # trainable end-to-end
    model = ALS(rank=2, maxIter=2).fit(f)
    assert model.rank == 2


def test_movielens_dat_loader(tmp_path):
    from tpu_als.io.movielens import load_movielens_dat

    # ml-1m/ml-10m format: '::' separated, no header, half-star ratings
    dat = tmp_path / "ratings.dat"
    dat.write_text("1::1193::5::978300760\n2::661::3.5::978302109\n\n")
    f = load_movielens_dat(str(tmp_path))  # directory form resolves
    assert f["user"].tolist() == [1, 2]
    assert f["item"].tolist() == [1193, 661]
    assert f["rating"].tolist() == [5.0, 3.5]
    assert f["timestamp"].tolist() == [978300760, 978302109]
    assert f["user"].dtype == np.int64 and f["rating"].dtype == np.float32

    bad = tmp_path / "bad.dat"
    bad.write_text("1::2::3\n")  # missing timestamp field
    with pytest.raises(ValueError, match="malformed ratings line"):
        load_movielens_dat(str(bad))
    bad.write_text("1::2::xx::9\n")  # non-numeric rating
    with pytest.raises(ValueError, match="malformed ratings line"):
        load_movielens_dat(str(bad))


def test_fastcsv_native_parser(tmp_path):
    import time

    from tpu_als.io.fastcsv import load_ratings_csv, load_u_data

    rng = np.random.default_rng(0)
    n = 200_000
    u = rng.integers(1, 10000, n)
    i = rng.integers(1, 5000, n)
    r = np.round(rng.uniform(0.5, 5.0, n) * 2) / 2
    t = rng.integers(10**9, 2 * 10**9, n)
    csv = tmp_path / "ratings.csv"
    with open(csv, "w") as f:
        f.write("userId,movieId,rating,timestamp\n")
        for k in range(n):
            f.write(f"{u[k]},{i[k]},{r[k]},{t[k]}\n")

    t0 = time.perf_counter()
    pu, pi, pr, pt = load_ratings_csv(str(csv))
    dt = time.perf_counter() - t0
    np.testing.assert_array_equal(pu, u)
    np.testing.assert_array_equal(pi, i)
    np.testing.assert_allclose(pr, r.astype(np.float32), rtol=1e-6)
    np.testing.assert_array_equal(pt, t)
    assert dt < 5.0  # 200k rows well under 5s

    tsv = tmp_path / "u.data"
    with open(tsv, "w") as f:
        for k in range(100):
            f.write(f"{u[k]}\t{i[k]}\t{int(r[k])}\t{t[k]}\n")
    pu2, _, pr2, _ = load_u_data(str(tsv))
    assert len(pu2) == 100
    np.testing.assert_array_equal(pu2, u[:100])


def test_fastcsv_no_trailing_newline(tmp_path):
    from tpu_als.io.fastcsv import load_ratings_csv

    csv = tmp_path / "r.csv"
    csv.write_text("userId,movieId,rating,timestamp\n1,2,3.5,100\n7,8,1.0,200")
    pu, pi, pr, pt = load_ratings_csv(str(csv))
    assert pu.tolist() == [1, 7]
    assert pr.tolist() == [3.5, 1.0]
    assert pt.tolist() == [100, 200]


def test_synthetic_return_factors():
    frame, Us, Vs = synthetic_movielens(50, 30, 500, seed=3,
                                        return_factors=True)
    assert Us.shape == (50, 16) and Vs.shape == (30, 16)
    # same seed without factors -> identical frame
    frame2 = synthetic_movielens(50, 30, 500, seed=3)
    assert np.array_equal(frame["rating"], frame2["rating"])
