"""Whole-iteration fused gather→Gram→solve kernel
(ops.pallas_gather_ne.gather_solve) vs the unfused ``normal_eq_*`` +
``solve_spd`` pipeline it collapses, interpret mode on CPU (the same
kernel compiles on TPU — interpret-mode parity is the portability
contract for every Pallas kernel in this repo).

Honesty note on the tolerance regime: the NE semantics upstream of the
solve are the BITWISE ones pinned in tests/test_pallas_gather_ne.py
(same weights, same dot_general contraction, same ridge/YtY tail
expressions), but the fused path then factorizes with its own in-VMEM
Cholesky panels (ops.pallas_solve's factorize/substitute) while the
reference runs the XLA lowering — a different elimination order.  The
solve output therefore matches to factorization rounding only, asserted
tight (~1e-5 abs at unit-scale, ridge-regularized systems), and the
3-iteration training comparison compounds that per-iteration rounding —
it is allclose, NOT bitwise, by construction.  The byte-level claims
(no HBM gather, CostEstimate == fused_solve_kernel_bytes, bytes below
the NE-build + A/b handoff) are pinned by the ``fused_solve_audit``
contract in analysis/contracts.py."""

import numpy as np
import pytest

import jax.numpy as jnp

from tpu_als.core.als import AlsConfig, resolve_solve_path, train
from tpu_als.core.ratings import build_csr_buckets
from tpu_als.ops.pallas_gather_ne import (
    gather_fused_solve_explicit,
    gather_fused_solve_implicit,
)
from tpu_als.ops.solve import (
    compute_yty,
    normal_eq_explicit,
    normal_eq_implicit,
    solve_spd,
)


def _problem(rng, n, w, r, N=200, implicit=False, dtype=jnp.float32):
    V = (rng.normal(size=(N, r)).astype(np.float32) / np.sqrt(r))
    cols = rng.integers(0, N, (n, w)).astype(np.int32)
    vals = rng.normal(size=(n, w)).astype(np.float32)
    if implicit:
        vals = np.abs(vals) * 3
        vals[rng.random((n, w)) < 0.2] *= -1  # zero/negative confidence
    mask = (rng.random((n, w)) < 0.8).astype(np.float32)
    vals = vals * mask
    return (jnp.asarray(V).astype(dtype), jnp.asarray(cols),
            jnp.asarray(vals).astype(dtype), jnp.asarray(mask).astype(dtype))


def _ref_explicit(V, cols, vals, mask, reg):
    A, b, cnt = normal_eq_explicit(V[cols], vals, mask, reg)
    return solve_spd(A.astype(jnp.float32), b.astype(jnp.float32), cnt,
                     backend="xla")


def _ref_implicit(V, cols, vals, mask, reg, alpha, YtY):
    A, b, cnt = normal_eq_implicit(V[cols], vals, mask, reg, alpha, YtY)
    return solve_spd(A.astype(jnp.float32), b.astype(jnp.float32), cnt,
                     backend="xla")


def _assert_solutions_match(got, ref):
    # factorization-rounding regime (module docstring): the two paths
    # solve the SAME normal equations with different elimination orders
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=5e-5, rtol=5e-4)


SHAPES = [
    (5, 8, 4),       # tiny everything
    (37, 24, 10),    # non-pow2 batch, w multiple of 8
    (33, 100, 128),  # the benchmark rank; w not a multiple of 8
    (64, 512, 32),   # multiple width chunks (accumulated in-kernel)
]


@pytest.mark.parametrize("n,w,r", SHAPES)
def test_explicit_matches_reference(rng, n, w, r):
    V, cols, vals, mask = _problem(rng, n, w, r)
    got = gather_fused_solve_explicit(V, cols, vals, mask, 0.05,
                                      interpret=True)
    _assert_solutions_match(got, _ref_explicit(V, cols, vals, mask, 0.05))


@pytest.mark.parametrize("n,w,r", SHAPES)
def test_implicit_matches_reference(rng, n, w, r):
    V, cols, vals, mask = _problem(rng, n, w, r, implicit=True)
    YtY = compute_yty(V.astype(jnp.float32))
    got = gather_fused_solve_implicit(V, cols, vals, mask, 0.1, 4.0, YtY,
                                      interpret=True)
    _assert_solutions_match(
        got, _ref_implicit(V, cols, vals, mask, 0.1, 4.0, YtY))


def test_rank_deficient_rows(rng):
    # w < r: every row's gathered Gram has rank <= w, so the system is
    # SPD only through the weighted-lambda ridge — the regime where a
    # Cholesky disagreement (dropped ridge, wrong diagonal mask) blows
    # up instead of rounding
    n, w, r = 16, 8, 24
    V, cols, vals, mask = _problem(rng, n, w, r)
    got = gather_fused_solve_explicit(V, cols, vals, mask, 0.05,
                                      interpret=True)
    ref = _ref_explicit(V, cols, vals, mask, 0.05)
    _assert_solutions_match(got, ref)
    assert np.isfinite(np.asarray(got)).all()


def test_empty_and_all_padding_rows(rng):
    # rows whose mask is entirely zero (empty users / all-padding bucket
    # rows pointing at col 0): the in-kernel empty-row guard must return
    # EXACT zeros, matching solve_spd's count guard
    n, w, r = 16, 24, 8
    V, cols, vals, mask = _problem(rng, n, w, r)
    mask = mask.at[3].set(0.0).at[11].set(0.0)
    vals = vals * mask
    cols = cols.at[11].set(0)  # the builder's padding convention
    got = gather_fused_solve_explicit(V, cols, vals, mask, 0.05,
                                      interpret=True)
    ref = _ref_explicit(V, cols, vals, mask, 0.05)
    _assert_solutions_match(got, ref)
    g = np.asarray(got)
    assert (g[3] == 0).all() and (g[11] == 0).all()


def test_duplicate_columns_in_a_row(rng):
    # one entity rating the same opposite row several times in a window
    # (also the padding convention): each occurrence's DMA lands in its
    # own Vg slot, so duplicates contribute exactly like the gather
    n, w, r = 12, 16, 8
    V, cols, vals, mask = _problem(rng, n, w, r, N=5)  # tiny N -> dupes
    assert any(len(set(row)) < w for row in np.asarray(cols))
    got = gather_fused_solve_explicit(V, cols, vals, mask, 0.05,
                                      interpret=True)
    _assert_solutions_match(got, _ref_explicit(V, cols, vals, mask, 0.05))


def test_bfloat16_table_upcast_gate(rng):
    # the bf16-before-gather A/B's numerics leg: the table streams in
    # bf16 (halving the dominant HBM bytes) but the Gram accumulates f32
    # and the in-kernel Cholesky runs f32 — the PR 8 upcast-solve gate's
    # discipline.  Both paths promote identically upstream of the solve,
    # so only factorization rounding remains.
    n, w, r = 24, 32, 16
    V, cols, vals, mask = _problem(rng, n, w, r, dtype=jnp.bfloat16)
    got = gather_fused_solve_explicit(V, cols, vals, mask, 0.05,
                                      interpret=True)
    _assert_solutions_match(got, _ref_explicit(V, cols, vals, mask, 0.05))
    YtY = compute_yty(V.astype(jnp.float32))
    goti = gather_fused_solve_implicit(V, cols, vals, mask, 0.1, 4.0, YtY,
                                       interpret=True)
    _assert_solutions_match(
        goti, _ref_implicit(V, cols, vals, mask, 0.1, 4.0, YtY))


# the implicit variant is the headline configuration and rides tier-1;
# the explicit twin costs another full train() compile (~20s of budget)
# and exercises no additional kernel path, so it runs in the slow tier
@pytest.mark.parametrize("implicit", [
    pytest.param(False, marks=pytest.mark.slow), True])
def test_train_gather_fused_solve_close_to_auto(rng, implicit):
    # end to end: solve_backend='gather_fused_solve' (interpret mode
    # off-TPU) over 3 iterations vs the einsum+XLA path.  NOT bitwise —
    # the fused path's own Cholesky rounds differently each iteration
    # (module docstring) — but the compounded drift at these shapes
    # stays in the 1e-4 band.
    nU, nI, nnz = 40, 30, 500
    u = rng.integers(0, nU, nnz)
    i = rng.integers(0, nI, nnz)
    r = np.abs(rng.normal(size=nnz)).astype(np.float32) + 0.1
    ucsr = build_csr_buckets(u, i, r, nU, min_width=8)
    icsr = build_csr_buckets(i, u, r, nI, min_width=8)
    kw = dict(rank=16, max_iter=3, reg_param=0.1, seed=3,
              implicit_prefs=implicit, alpha=4.0)
    Ua, Va = train(ucsr, icsr, AlsConfig(**kw))
    Uf, Vf = train(ucsr, icsr,
                   AlsConfig(solve_backend="gather_fused_solve", **kw))
    np.testing.assert_allclose(np.asarray(Ua), np.asarray(Uf),
                               atol=5e-4, rtol=5e-3)
    np.testing.assert_allclose(np.asarray(Va), np.asarray(Vf),
                               atol=5e-4, rtol=5e-3)


def test_resolve_path_forced_gather_fused_solve():
    info = resolve_solve_path(
        AlsConfig(rank=16, solve_backend="gather_fused_solve"), 16)
    assert info["resolved_solve_path"] == "gatherfused_solve"
    # off-TPU the auto walk must NOT pick the kernel (probe gates on TPU)
    if not info["on_tpu"]:
        auto = resolve_solve_path(AlsConfig(rank=16), 16)
        assert auto["resolved_solve_path"].startswith("einsum+")
        assert auto["gather_solve_probe"] is False
