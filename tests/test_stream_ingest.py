"""Config-3 streaming data plane: chunked string-id ingest correctness.

The protocol under test (io/stream.py): host byte-ranges with
straddling-line ownership, chunk re-stitching, native interning, and
cross-host vocabulary merge — every rating lands exactly once with a
globally consistent id, for ANY host count and chunk size (SURVEY.md §6
row 3; VERDICT r4 next-round #4).
"""

import numpy as np
import pytest

from tpu_als.io.stream import (
    host_byte_range,
    ingest_per_host,
    merge_vocabularies,
    split_claim,
    stream_ingest,
    validate_split_claims,
)


def _reference_rows(text, require_cols=3, skip_header=0):
    rows = []
    for k, line in enumerate(text.split("\n")):
        line = line.rstrip("\r")
        if k < skip_header or not line.strip():
            continue
        parts = line.split(",")
        assert len(parts) == require_cols
        rows.append((parts[0], parts[1], float(parts[2])))
    return rows


def _make_file(tmp_path, n=3000, seed=0, header=False, cols=3):
    rng = np.random.default_rng(seed)
    users = [f"u{chr(97 + k % 7)}_{k % 211}" for k in range(n)]
    items = [f"B{k % 83:07d}" for k in range(n)]
    rng.shuffle(users)
    lines = []
    if header:
        lines.append("user_id,parent_asin,rating,timestamp"[:None])
    for k in range(n):
        tail = ",1609459200" if cols == 4 else ""
        lines.append(f"{users[k]},{items[k]},{(k % 9) / 2 + 0.5}{tail}")
    path = tmp_path / "ratings.csv"
    path.write_text("\n".join(lines) + "\n")
    return str(path), "\n".join(lines) + "\n"


def _assemble(splits, user_labels, item_labels):
    rows = []
    for u, i, r in splits:
        for k in range(len(u)):
            rows.append((user_labels[u[k]].decode(),
                         item_labels[i[k]].decode(),
                         float(np.float32(r[k]))))
    return rows


@pytest.mark.parametrize("num_hosts", [1, 2, 3, 5, 8])
def test_every_rating_lands_exactly_once(tmp_path, num_hosts):
    path, text = _make_file(tmp_path, n=1200)
    ref = _reference_rows(text)
    splits, ul, il = ingest_per_host(path, num_hosts,
                                     chunk_bytes=257)
    got = _assemble(splits, ul, il)
    assert got == [(u, i, float(np.float32(r))) for u, i, r in ref]


def test_tiny_chunks_stitch_lines(tmp_path):
    # chunk smaller than a line: every line crosses >=1 chunk boundary
    path, text = _make_file(tmp_path, n=200)
    ref = _reference_rows(text)
    splits, ul, il = ingest_per_host(path, 3, chunk_bytes=7)
    assert _assemble(splits, ul, il) == [
        (u, i, float(np.float32(r))) for u, i, r in ref]


def test_amazon_schema_four_cols_and_header(tmp_path):
    path, text = _make_file(tmp_path, n=400, header=True, cols=4)
    ref = _reference_rows(text, require_cols=4, skip_header=1)
    splits, ul, il = ingest_per_host(path, 4, require_cols=4,
                                     skip_header=1, chunk_bytes=101)
    assert _assemble(splits, ul, il) == [
        (u, i, float(np.float32(r))) for u, i, r in ref]


def test_more_hosts_than_bytes(tmp_path):
    path = tmp_path / "tiny.csv"
    path.write_text("a,b,1.0\n")
    splits, ul, il = ingest_per_host(str(path), 64)
    got = _assemble(splits, ul, il)
    assert got == [("a", "b", 1.0)]


def test_more_hosts_than_bytes_with_header(tmp_path):
    # degenerate split: the LAST host owns (0, size); the header skip
    # must follow byte-0 ownership, not host index 0
    path = tmp_path / "tiny_hdr.csv"
    path.write_text("user,item,rating\na,b,1.0\n")
    splits, ul, il = ingest_per_host(str(path), 64, skip_header=1)
    assert _assemble(splits, ul, il) == [("a", "b", 1.0)]


def test_crlf_and_missing_final_newline(tmp_path):
    path = tmp_path / "crlf.csv"
    path.write_bytes(b"ux,iy,2.5\r\nuz,iw,3.0")
    for hosts in (1, 2, 3):
        splits, ul, il = ingest_per_host(str(path), hosts)
        assert _assemble(splits, ul, il) == [("ux", "iy", 2.5),
                                             ("uz", "iw", 3.0)]


def test_unicode_ids_roundtrip(tmp_path):
    path = tmp_path / "uni.csv"
    path.write_text("amélie,書籍B01,4.5\namélie,ítem-2,1.0\n",
                    encoding="utf-8")
    from tpu_als.io.stream import decode_labels

    (u, i, r, ul, il) = stream_ingest(str(path))
    assert decode_labels(ul) == ["amélie"]
    assert decode_labels(il) == ["書籍B01", "ítem-2"]
    assert u.tolist() == [0, 0] and i.tolist() == [0, 1]


@pytest.mark.parametrize("bad", [
    '"quoted",item,3.0',          # quoted id
    "user,,3.0",                  # empty item id
    ",item,3.0",                  # empty user id
    "user,item,notafloat",        # unparseable rating
    "user,item,nan",              # non-finite rating
    "user,item,3.0,extra",        # too many columns (require_cols=3)
    "user,item",                  # too few columns
])
def test_malformed_lines_raise(tmp_path, bad):
    path = tmp_path / "bad.csv"
    path.write_text(f"ok_user,ok_item,2.0\n{bad}\n")
    with pytest.raises(ValueError, match="malformed ratings line"):
        stream_ingest(str(path))


def test_too_few_columns_for_amazon_schema(tmp_path):
    path = tmp_path / "bad4.csv"
    path.write_text("u,i,3.0\n")
    with pytest.raises(ValueError, match="malformed ratings line"):
        stream_ingest(str(path), require_cols=4)


def test_empty_file(tmp_path):
    path = tmp_path / "empty.csv"
    path.write_text("")
    u, i, r, ul, il = stream_ingest(str(path))
    assert len(u) == len(i) == len(r) == len(ul) == len(il) == 0


def test_host_byte_range_partitions_exactly():
    for size in (0, 1, 99, 100, 101):
        for hosts in (1, 2, 3, 7):
            ranges = [host_byte_range(size, k, hosts)
                      for k in range(hosts)]
            assert ranges[0][0] == 0 and ranges[-1][1] == size
            for (a, b), (c, d) in zip(ranges, ranges[1:]):
                assert b == c


def test_merge_vocabularies_lexicographic_and_remap():
    labels, remaps = merge_vocabularies(
        [["a", "bb"], ["bb", "c", "a"], [], ["d"]])
    assert labels.tolist() == [b"a", b"bb", b"c", b"d"]
    assert remaps[0].tolist() == [0, 1]
    assert remaps[1].tolist() == [1, 2, 0]
    assert remaps[2].tolist() == []
    assert remaps[3].tolist() == [3]


def _fuzz_case(rng, tmp_path, case):
    """One randomized ingest scenario: random row count, id lengths
    (including ids that make single LINES longer than chunk_bytes),
    random header, random (hosts, chunk_bytes)."""
    n = int(rng.integers(1, 400))
    header = bool(rng.integers(0, 2))
    long_ids = bool(rng.integers(0, 2))
    lines = []
    if header:
        lines.append("user_id,item_id,rating")
    for k in range(n):
        ulen = int(rng.integers(1, 120 if long_ids else 12))
        u = "u" + "x" * ulen + str(int(rng.integers(0, 37)))
        i = f"i{int(rng.integers(0, 53))}"
        lines.append(f"{u},{i},{(k % 9) / 2 + 0.5}")
    text = "\n".join(lines) + ("" if rng.integers(0, 2) else "\n")
    path = tmp_path / f"fuzz_{case}.csv"
    path.write_text(text)
    hosts = int(rng.integers(1, 9))
    chunk = int(rng.choice([3, 17, 64, 257, 4096]))
    return str(path), text, header, hosts, chunk


@pytest.mark.parametrize("case", range(12))
def test_fuzz_exactly_once(tmp_path, case):
    """Property sweep (VERDICT r4 next-round #4): for ANY (file size,
    host count, chunk_bytes, line length vs chunk_bytes, header
    placement), every rating lands exactly once, in file order, with
    globally consistent ids."""
    rng = np.random.default_rng(1000 + case)
    path, text, header, hosts, chunk = _fuzz_case(rng, tmp_path, case)
    ref = _reference_rows(text, skip_header=1 if header else 0)
    splits, ul, il = ingest_per_host(path, hosts, chunk_bytes=chunk,
                                     skip_header=1 if header else 0)
    got = _assemble(splits, ul, il)
    assert got == [(u, i, float(np.float32(r))) for u, i, r in ref], (
        f"case {case}: hosts={hosts} chunk={chunk} header={header} "
        f"rows={len(ref)}")


def test_split_claims_agree_and_strip():
    # a correct H-host launch: one claim per range, same H everywhere
    vocab = np.unique(np.array(
        [b"alice", b"bob"] + [split_claim(h, 3) for h in range(3)]))
    clean, hosts = validate_split_claims(vocab)
    assert hosts == 3
    assert clean.tolist() == [b"alice", b"bob"]


def test_split_claims_detect_host_count_mismatch():
    # host 1 launched with a stale --num-hosts=2 while hosts {0,2} think
    # H=3: the union carries both claims and must refuse
    vocab = np.unique(np.array(
        [b"alice", split_claim(0, 3), split_claim(1, 2),
         split_claim(2, 3)]))
    with pytest.raises(ValueError, match="disagree on num_hosts"):
        validate_split_claims(vocab)


def test_split_claims_detect_missing_range():
    vocab = np.unique(np.array(
        [b"alice", split_claim(0, 3), split_claim(2, 3)]))
    with pytest.raises(ValueError, match=r"\[1\] of 3"):
        validate_split_claims(vocab)


def test_split_claims_required():
    with pytest.raises(ValueError, match="no split claims"):
        validate_split_claims(np.array([b"alice", b"bob"]))


def test_split_claim_rejects_bad_index():
    with pytest.raises(ValueError, match="not in"):
        split_claim(3, 3)


def test_split_claims_sort_before_real_labels():
    # the \x01 prefix must sort claims to the FRONT of the union so
    # stripping them never reorders the real (remap-bearing) labels
    vocab = np.unique(np.array([b"0user", b"zz", split_claim(0, 1)]))
    clean, _ = validate_split_claims(vocab)
    assert vocab[0].startswith(b"\x01")
    assert clean.tolist() == [b"0user", b"zz"]


def test_streamed_ids_feed_string_indexer_model(tmp_path):
    from tpu_als import ColumnarFrame
    from tpu_als.api.pipeline import StringIndexerModel
    from tpu_als.io.stream import decode_labels

    path, text = _make_file(tmp_path, n=300)
    splits, ul, il = ingest_per_host(path, 2, chunk_bytes=64)
    m = StringIndexerModel.from_labels(decode_labels(ul),
                                       inputCol="user_id",
                                       outputCol="user")
    # the model's transform must agree with the streaming dense ids
    ref = _reference_rows(text)
    frame = ColumnarFrame(
        {"user_id": np.array([u for u, _, _ in ref], dtype=object)})
    out = m.transform(frame)
    merged_u = np.concatenate([s[0] for s in splits])
    np.testing.assert_array_equal(
        np.asarray(out["user"], dtype=np.int64), merged_u)


def test_per_host_splits_train_like_the_whole_file(tmp_path, rng):
    """End-to-end config-3 plumbing: streamed per-host splits with
    globally-merged ids produce the same fit as the whole file parsed at
    once (single-process dataMode='per_host' degenerates to one split —
    the equivalence pin is on ids and ratings, trained to convergence)."""
    from tpu_als import ALS, ColumnarFrame

    n = 600
    path, text = _make_file(tmp_path, n=n, seed=3)
    splits, ul, il = ingest_per_host(path, 3, chunk_bytes=128)
    u = np.concatenate([s[0] for s in splits])
    i = np.concatenate([s[1] for s in splits])
    r = np.concatenate([s[2] for s in splits])
    ref = _reference_rows(text)
    # dense ids must cover [0, n_labels) with no gaps
    assert set(u.tolist()) == set(range(len(ul)))
    assert set(i.tolist()) == set(range(len(il)))
    als = ALS(rank=4, maxIter=3, regParam=0.05, seed=0,
              dataMode="per_host")
    m1 = als.fit(ColumnarFrame({"user": u, "item": i, "rating": r}))
    # same data, parsed trivially
    lab_u = {s.decode(): k for k, s in enumerate(ul.tolist())}
    lab_i = {s.decode(): k for k, s in enumerate(il.tolist())}
    u2 = np.array([lab_u[a] for a, _, _ in ref], dtype=np.int64)
    i2 = np.array([lab_i[b] for _, b, _ in ref], dtype=np.int64)
    r2 = np.array([c for _, _, c in ref], dtype=np.float32)
    m2 = ALS(rank=4, maxIter=3, regParam=0.05, seed=0).fit(
        ColumnarFrame({"user": u2, "item": i2, "rating": r2}))
    np.testing.assert_allclose(m1._U, m2._U, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(m1._V, m2._V, rtol=1e-5, atol=1e-6)
