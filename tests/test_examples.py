"""The examples/ scripts must stay runnable — they are the front door a
reference user walks through first."""

import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_example(name, extra_env=None, timeout=500):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    if extra_env:
        env.update(extra_env)
    return subprocess.run(
        [sys.executable, "-c",
         "import jax; jax.config.update('jax_platforms', 'cpu'); "
         "import runpy, sys; sys.argv=['x']; "
         f"runpy.run_path('examples/{name}', run_name='__main__')"],
        cwd=ROOT, env=env, capture_output=True, text=True,
        timeout=timeout)


@pytest.mark.parametrize("name", ["01_movielens_basic.py",
                                  "02_pipeline_string_ids.py",
                                  "03_distributed_and_streaming.py",
                                  "04_multihost_pod_walkthrough.py"])
def test_example_compiles(name):
    import py_compile

    py_compile.compile(os.path.join(ROOT, "examples", name), doraise=True)


@pytest.mark.slow
def test_basic_example_runs_end_to_end():
    p = _run_example("01_movielens_basic.py")
    assert p.returncode == 0, p.stderr[-2000:]
    assert "held-out RMSE" in p.stdout and "top-10" in p.stdout


@pytest.mark.slow
def test_pipeline_example_runs_end_to_end():
    p = _run_example("02_pipeline_string_ids.py")
    assert p.returncode == 0, p.stderr[-2000:]
    assert "grid RMSE" in p.stdout and "top-5" in p.stdout


@pytest.mark.slow
def test_distributed_example_runs_on_forced_mesh():
    p = _run_example(
        "03_distributed_and_streaming.py",
        {"XLA_FLAGS": "--xla_force_host_platform_device_count=8"})
    assert p.returncode == 0, p.stderr[-2000:]
    assert "mesh: 8" in p.stdout
    assert "ring strategy" in p.stdout and "no refit" in p.stdout


@pytest.mark.slow
def test_multihost_pod_walkthrough_runs_end_to_end():
    """examples/04: two spawned gloo processes, per-host streaming
    ingest, vocab-union, cross-process training."""
    p = _run_example("04_multihost_pod_walkthrough.py", timeout=540)
    assert p.returncode == 0, (p.stdout[-1000:], p.stderr[-2000:])
    assert "global space: 600 users x 200 items" in p.stdout
    assert "both hosts done" in p.stdout
