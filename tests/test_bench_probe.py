"""bench.py's probe budget + sweep-fallback banking (round-5 failure:
6x120s of hung backend probes burned the capture window and banked
``value: null`` into BENCH_r05.json while a same-round sweep measurement
sat on disk).  The budget caps total probe wall-clock; on exhaustion the
capture banks the strongest builder-measured value with explicit
``source: "sweep_fallback"`` provenance instead of a null."""

import argparse
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench


def _hang_forever(monkeypatch, calls):
    """Make the probe subprocess look hung: every run raises
    TimeoutExpired (instantly — the tests cap wall-clock via the
    budget/waits, not via real 120s timeouts)."""

    def fake_run(cmd, timeout=None, **kw):
        calls.append(timeout)
        raise subprocess.TimeoutExpired(cmd, timeout)

    monkeypatch.setattr(bench.subprocess, "run", fake_run)


def _args(mode="headline"):
    return argparse.Namespace(mode=mode, rank=128, small=False)


def test_probe_budget_caps_total_wallclock(monkeypatch):
    calls = []
    _hang_forever(monkeypatch, calls)
    t0 = time.monotonic()
    ok, err, events = bench.tpu_ready(attempts=6, wait_s=5,
                                      probe_timeout_s=120, budget_s=0.3)
    elapsed = time.monotonic() - t0
    assert not ok
    assert "budget" in err
    # the 6x(120+5)s envelope never ran: the first inter-attempt sleep
    # was clipped to the remaining budget and the next attempt stopped
    assert elapsed < 5.0, elapsed
    assert len(calls) < 6
    # exhaustion ends with the terminal bench_probe_exhausted verdict,
    # after the real attempts' bench_retry records
    assert events and "budget" in events[-1]["reason"]
    assert events[-1]["type"] == "bench_probe_exhausted"


def test_probe_budget_zero_keeps_full_retry_envelope(monkeypatch):
    calls = []
    _hang_forever(monkeypatch, calls)
    ok, err, events = bench.tpu_ready(attempts=3, wait_s=0,
                                      probe_timeout_s=120, budget_s=0)
    assert not ok
    assert "budget" not in err       # exhausted attempts, not budget
    assert len(calls) == 3
    # one bench_retry per attempt + the terminal verdict
    assert len(events) == 4
    assert [e["type"] for e in events[:3]] == ["bench_retry"] * 3
    term = events[-1]
    assert term["type"] == "bench_probe_exhausted"
    assert term["attempts"] == 3
    assert term["reason"] == err
    assert term["elapsed_seconds"] >= 0


def _bank(d, name, payload):
    os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, name + ".out"), "w") as f:
        f.write(json.dumps(payload) + "\n")


def test_hung_probe_banks_sweep_fallback_not_null(monkeypatch, tmp_path):
    """The acceptance case: probe exhausts its budget, a same-round
    sweep measurement exists on disk -> the emitted JSON carries THAT
    value with sweep_fallback provenance, never value: null."""
    calls = []
    _hang_forever(monkeypatch, calls)
    monkeypatch.chdir(tmp_path)
    _bank("sweep_logs", "headline_f32",
          {"value": 0.845, "unit": "iters/sec", "vs_baseline": 50.7,
           "banked_at": "2026-08-01T08:32:00+00:00"})
    ok, err, events = bench.tpu_ready(attempts=6, wait_s=1,
                                      probe_timeout_s=120, budget_s=0.2)
    assert not ok
    out = bench.error_json(_args(), "als_iters_per_sec_rank128_ml25m"
                           "_implicit", "iters/sec", err,
                           probe_events=events)
    assert out["value"] == 0.845
    assert out["source"] == "sweep_fallback"
    assert out["vs_baseline"] == 50.7
    assert out["error"] == err          # the failure stays on record
    lb = out["last_builder_measured"]
    assert lb["source_log"].endswith("headline_f32.out")
    assert lb["banked_at"] == "2026-08-01T08:32:00+00:00"
    assert out["probe_events"]


def test_no_evidence_still_banks_null(monkeypatch, tmp_path):
    monkeypatch.chdir(tmp_path)          # empty sweep_logs
    monkeypatch.setattr(bench, "_BUILDER_MEASURED", {})
    out = bench.error_json(_args(), "m", "iters/sec", "probe dead")
    assert out["value"] is None
    assert "source" not in out


def test_unit_mismatch_blocks_fallback(monkeypatch, tmp_path):
    # a fallback from a differently-united record would be a silent
    # unit swap — the value must stay null
    monkeypatch.chdir(tmp_path)
    monkeypatch.setattr(bench, "_BUILDER_MEASURED", {})
    _bank("sweep_logs", "headline_f32", {"value": 11.2, "unit": "s/iter"})
    out = bench.error_json(_args(), "m", "iters/sec", "probe dead")
    assert out["value"] is None
    assert "source" not in out
