"""End-to-end training quality tests — the reference protocol (SURVEY.md §4):
synthetic low-rank ground truth, train, assert held-out RMSE below threshold
(the analog of ALSSuite.testALS's targetRMSE assertions).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from tpu_als.core.als import AlsConfig, predict, train
from tpu_als.core.ratings import build_csr_buckets

from conftest import make_ratings


def split(rng, u, i, r, frac=0.2):
    test = rng.random(len(u)) < frac
    return (u[~test], i[~test], r[~test]), (u[test], i[test], r[test])


def fit(u, i, r, num_users, num_items, cfg):
    user_csr = build_csr_buckets(u, i, r, num_users, min_width=4, chunk_elems=1 << 12)
    item_csr = build_csr_buckets(i, u, r, num_items, min_width=4, chunk_elems=1 << 12)
    return train(user_csr, item_csr, cfg)


def rmse(U, V, u, i, r, num_users, num_items):
    p = predict(
        U, V, jnp.array(u), jnp.array(i),
        jnp.ones(len(u), bool), jnp.ones(len(i), bool),
    )
    return float(jnp.sqrt(jnp.nanmean((p - jnp.array(r)) ** 2)))


def test_explicit_recovers_low_rank(rng):
    u, i, r, _, _ = make_ratings(rng, 80, 60, rank=3, density=0.4, noise=0.01)
    (tu, ti, tr), (eu, ei, er) = split(rng, u, i, r)
    cfg = AlsConfig(rank=3, max_iter=12, reg_param=0.01, seed=1)
    U, V = fit(tu, ti, tr, 80, 60, cfg)
    err = rmse(U, V, eu, ei, er, 80, 60)
    scale = float(np.std(r))
    assert err < 0.15 * scale + 0.05, f"held-out rmse {err} vs scale {scale}"


def test_more_iterations_reduce_train_rmse(rng):
    u, i, r, _, _ = make_ratings(rng, 60, 40, rank=4, density=0.5, noise=0.0)
    errs = []
    for iters in (1, 4, 10):
        cfg = AlsConfig(rank=4, max_iter=iters, reg_param=0.005, seed=3)
        U, V = fit(u, i, r, 60, 40, cfg)
        errs.append(rmse(U, V, u, i, r, 60, 40))
    assert errs[2] < errs[1] < errs[0]
    assert errs[2] < 0.05


def test_implicit_ranks_positives_above_negatives(rng):
    # implicit protocol: observed entries get confidence, preference 1;
    # model scores for observed pairs should exceed unobserved ones on average
    num_users, num_items = 50, 40
    u, i, r, Ustar, Vstar = make_ratings(rng, num_users, num_items, rank=3, density=0.3)
    r_impl = np.abs(r) * 5 + 0.1  # positive interaction strengths
    cfg = AlsConfig(rank=8, max_iter=10, reg_param=0.01, implicit_prefs=True,
                    alpha=10.0, seed=5)
    U, V = fit(u, i, r_impl, num_users, num_items, cfg)
    scores = np.asarray(U @ jnp.transpose(V))
    obs = np.zeros((num_users, num_items), bool)
    obs[u, i] = True
    assert scores[obs].mean() > scores[~obs].mean() + 0.1
    # predictions live in the preference range [~0, ~1]
    assert scores[obs].mean() < 1.5


def test_nonnegative_factors(rng):
    u, i, r, _, _ = make_ratings(rng, 40, 30, rank=3, density=0.4)
    r = np.abs(r) + 0.1
    cfg = AlsConfig(rank=3, max_iter=8, reg_param=0.05, nonnegative=True, seed=2)
    U, V = fit(u, i, r, 40, 30, cfg)
    assert float(jnp.min(U)) >= -1e-5
    assert float(jnp.min(V)) >= -1e-5
    err = rmse(U, V, u, i, r, 40, 30)
    assert err < 0.5


def test_seed_determinism(rng):
    u, i, r, _, _ = make_ratings(rng, 30, 20, rank=2, density=0.5)
    cfg = AlsConfig(rank=2, max_iter=3, seed=7)
    U1, V1 = fit(u, i, r, 30, 20, cfg)
    U2, V2 = fit(u, i, r, 30, 20, cfg)
    np.testing.assert_array_equal(np.asarray(U1), np.asarray(U2))
    np.testing.assert_array_equal(np.asarray(V1), np.asarray(V2))


def test_predict_cold_start_nan(rng):
    u, i, r, _, _ = make_ratings(rng, 20, 15, rank=2, density=0.5)
    cfg = AlsConfig(rank=2, max_iter=2, seed=0)
    U, V = fit(u, i, r, 20, 15, cfg)
    u_valid = jnp.ones(3, bool)
    p = predict(U, V, jnp.array([0, 1, -1]), jnp.array([0, 99, 2]),
                u_valid, jnp.array([True, True, True]))
    p = np.asarray(p)
    assert np.isfinite(p[0])
    assert np.isnan(p[1])  # item idx out of range -> NaN, even if mask says ok
    assert np.isnan(p[2])  # negative id -> NaN


def test_bfloat16_compute_converges(rng):
    # compute_dtype='bfloat16' moves the gather + normal-equation einsums
    # to bf16 (f32 accumulate); the solves stay f32, so held-out quality
    # must stay within a small factor of the f32 run
    u, i, r, _, _ = make_ratings(rng, 80, 60, rank=3, density=0.4,
                                 noise=0.01)
    (tu, ti, tr), (eu, ei, er) = split(rng, u, i, r)
    cfg32 = AlsConfig(rank=3, max_iter=12, reg_param=0.01, seed=1)
    cfg16 = AlsConfig(rank=3, max_iter=12, reg_param=0.01, seed=1,
                      compute_dtype="bfloat16")
    U32, V32 = fit(tu, ti, tr, 80, 60, cfg32)
    U16, V16 = fit(tu, ti, tr, 80, 60, cfg16)
    e32 = rmse(U32, V32, eu, ei, er, 80, 60)
    e16 = rmse(U16, V16, eu, ei, er, 80, 60)
    assert e16 < 1.5 * e32 + 0.02, (e16, e32)


def test_resolve_path_agrees_with_dispatch(rng):
    # resolve_solve_path's attribution (what benchmarks record) must name
    # the same backend solve_spd's 'auto' dispatch will take
    from tpu_als.core.als import resolve_solve_path
    from tpu_als.ops.solve import auto_solve_backend

    cfg = AlsConfig(rank=16, solve_backend="auto")
    info = resolve_solve_path(cfg, 16)
    expect = {
        "lanes": "einsum+pallas_lanes",
        "pallas": "einsum+pallas_cholesky",
        "xla": "einsum+xla_cholesky",
    }[auto_solve_backend(16)]
    assert info["resolved_solve_path"] == expect
    # nonnegative always resolves to the NNLS path regardless of probes
    assert resolve_solve_path(
        AlsConfig(rank=16, nonnegative=True), 16
    )["resolved_solve_path"] == "einsum+nnls"


@pytest.mark.slow
def test_reg_grid_shares_one_compiled_step(rng):
    """regParam is a traced scalar stripped from the step's static cache
    key: a tuning grid over regParam at fixed rank/data must reuse ONE
    compiled executable (the CrossValidator recompile tax), while still
    applying each reg value numerically."""
    import jax.numpy as jnp

    from tpu_als.core import als
    from tpu_als.core.als import AlsConfig, init_factors, make_step
    from tpu_als.core.ratings import build_csr_buckets

    nU, nI, nnz = 30, 20, 300
    u = rng.integers(0, nU, nnz)
    i = rng.integers(0, nI, nnz)
    r = rng.normal(size=nnz).astype(np.float32)
    ucsr = build_csr_buckets(u, i, r, nU, min_width=4)
    icsr = build_csr_buckets(i, u, r, nI, min_width=4)
    import jax

    ub = jax.device_put(ucsr.device_buckets())
    ib = jax.device_put(icsr.device_buckets())
    key = jax.random.PRNGKey(0)
    ku, kv = jax.random.split(key)
    U0 = init_factors(ku, nU, 4)
    V0 = init_factors(kv, nI, 4)

    cfg_a = AlsConfig(rank=4, reg_param=0.05, seed=0)
    step_a = make_step(ub, ib, nU, nI, cfg_a,
                       ucsr.chunk_elems, icsr.chunk_elems)
    Ua, Va = step_a(jnp.array(U0), jnp.array(V0))
    size_after_first = als._step_jit._cache_size()

    cfg_b = AlsConfig(rank=4, reg_param=5.0, seed=0)
    step_b = make_step(ub, ib, nU, nI, cfg_b,
                       ucsr.chunk_elems, icsr.chunk_elems)
    Ub, Vb = step_b(jnp.array(U0), jnp.array(V0))
    assert als._step_jit._cache_size() == size_after_first, \
        "a reg-only config change must not add a jit cache entry"
    # ...and the traced reg is actually applied: heavy ridge shrinks
    assert float(jnp.abs(Ub).sum()) < float(jnp.abs(Ua).sum())

    # oracle: the dynamic-reg step equals the direct half-step math at
    # the same reg (local_half_step with the static default)
    V_direct = als.local_half_step(
        jnp.array(U0), ib, nI, cfg_b, chunk_elems=icsr.chunk_elems,
        prev=jnp.array(V0))
    U_direct = als.local_half_step(
        V_direct, ub, nU, cfg_b, chunk_elems=ucsr.chunk_elems,
        prev=jnp.array(U0))
    np.testing.assert_allclose(np.asarray(Vb), np.asarray(V_direct),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(Ub), np.asarray(U_direct),
                               rtol=1e-5, atol=1e-6)


def test_alpha_grid_shares_one_compiled_step(rng):
    """alpha (implicit confidence) is traced like regParam: an
    alpha-only config change adds no jit cache entry and still changes
    the numerics."""
    import jax
    import jax.numpy as jnp

    from tpu_als.core import als
    from tpu_als.core.als import AlsConfig, init_factors, make_step
    from tpu_als.core.ratings import build_csr_buckets

    nU, nI, nnz = 30, 20, 300
    u = rng.integers(0, nU, nnz)
    i = rng.integers(0, nI, nnz)
    r = (np.abs(rng.normal(size=nnz)) + 0.1).astype(np.float32)
    ucsr = build_csr_buckets(u, i, r, nU, min_width=4)
    icsr = build_csr_buckets(i, u, r, nI, min_width=4)
    ub = jax.device_put(ucsr.device_buckets())
    ib = jax.device_put(icsr.device_buckets())
    key = jax.random.PRNGKey(0)
    ku, kv = jax.random.split(key)
    U0 = init_factors(ku, nU, 4)
    V0 = init_factors(kv, nI, 4)

    cfg_a = AlsConfig(rank=4, implicit_prefs=True, alpha=1.0, seed=0)
    Ua, _ = make_step(ub, ib, nU, nI, cfg_a, ucsr.chunk_elems,
                      icsr.chunk_elems)(jnp.array(U0), jnp.array(V0))
    size_after = als._step_jit._cache_size()
    cfg_b = AlsConfig(rank=4, implicit_prefs=True, alpha=40.0, seed=0)
    Ub, _ = make_step(ub, ib, nU, nI, cfg_b, ucsr.chunk_elems,
                      icsr.chunk_elems)(jnp.array(U0), jnp.array(V0))
    assert als._step_jit._cache_size() == size_after
    assert not np.allclose(np.asarray(Ua), np.asarray(Ub))
    # oracle: equals the direct half-step math at alpha=40
    YtY_u = als.compute_yty(jnp.array(U0))
    V_direct = als.local_half_step(
        jnp.array(U0), ib, nI, cfg_b, YtY_u,
        chunk_elems=icsr.chunk_elems, prev=jnp.array(V0))
    YtY_v = als.compute_yty(V_direct)
    U_direct = als.local_half_step(
        V_direct, ub, nU, cfg_b, YtY_v,
        chunk_elems=ucsr.chunk_elems, prev=jnp.array(U0))
    np.testing.assert_allclose(np.asarray(Ub), np.asarray(U_direct),
                               rtol=1e-5, atol=1e-6)


def test_training_is_deterministic_per_seed(rng):
    """Same seed -> bit-identical factors; different seed -> different.
    ALS here is a deterministic fixed-point iteration (reproducibility
    claim behind checkpoint-resume equivalence)."""
    from tpu_als import ALS, ColumnarFrame

    u = rng.integers(0, 40, 600)
    i = rng.integers(0, 25, 600)
    r = rng.normal(size=600).astype(np.float32)
    frame = ColumnarFrame({"user": u, "item": i, "rating": r})
    m1 = ALS(rank=4, maxIter=4, regParam=0.02, seed=7).fit(frame)
    m2 = ALS(rank=4, maxIter=4, regParam=0.02, seed=7).fit(frame)
    np.testing.assert_array_equal(m1._U, m2._U)
    np.testing.assert_array_equal(m1._V, m2._V)
    m3 = ALS(rank=4, maxIter=4, regParam=0.02, seed=8).fit(frame)
    assert not np.array_equal(m1._U, m3._U)
