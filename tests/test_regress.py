"""The bench regression gate (tpu_als/obs/regress.py + ``observe
regress`` + scripts/bench_gate.sh).

The gate is the reader the result banks never had: BENCH_r05.json sat
in the repo carrying ``value: null`` for three PRs because nothing
consumed it.  These tests pin the typed exit codes on synthetic series
(regression -> 1, latest null -> 2, provenance -> 3) AND that the
committed artifacts at the repo root gate clean (exit 0) — the same
invariant scripts/bench_gate.sh enforces in the smoke gates.

Pure stdlib under test: no jax import in this module's code paths.
"""

import json
import os
import subprocess

import pytest

from tpu_als.cli import main as cli_main
from tpu_als.obs import regress

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _write(d, name, doc):
    p = os.path.join(str(d), name)
    with open(p, "w") as f:
        json.dump(doc, f)
    return p


def _round(n, value, unit="iters/sec", **extra):
    return {"n": n, "cmd": "bench", "rc": 0, "tail": "",
            "parsed": {"metric": "m", "value": value, "unit": unit,
                       **extra}}


# -- the committed artifacts (the acceptance bar) --------------------------

def test_committed_banks_gate_clean():
    result = regress.check(REPO)
    assert result["exit_code"] == regress.EXIT_OK
    # the gate actually read the committed history, not an empty glob
    assert "BENCH_r05.json" in result["checked"]
    assert "BENCH_serve_cpu.json" in result["checked"]
    assert "BENCH" in result["series"]
    # the round-5 sweep-fallback recovery is reported, not silent
    assert any("sweep fallback" in f["message"]
               for f in result["findings"])
    # historical nulls surface as warnings, never errors
    assert all(f["severity"] != "error" for f in result["findings"])


def test_bench_gate_script_passes_exit_code_through(tmp_path):
    p = subprocess.run(
        ["bash", os.path.join(REPO, "scripts", "bench_gate.sh")],
        capture_output=True, text=True)
    assert p.returncode == 0, p.stdout + p.stderr
    assert "verdict: OK" in p.stdout
    # and a failing root propagates its typed code through the script
    _write(tmp_path, "BENCH_broken.json",
           {"metric": "m", "value": None, "unit": "ms",
            "banked_at": "2026-08-01T00:00:00+00:00"})
    p = subprocess.run(
        ["bash", os.path.join(REPO, "scripts", "bench_gate.sh"),
         str(tmp_path)],
        capture_output=True, text=True)
    assert p.returncode == regress.EXIT_NULL_BANK, p.stdout + p.stderr


# -- synthetic series: the typed failure modes -----------------------------

def test_regression_beyond_noise_band_exits_1(tmp_path):
    _write(tmp_path, "BENCH_r01.json", _round(1, 1.00))
    _write(tmp_path, "BENCH_r02.json", _round(2, 0.98))   # within noise
    result = regress.check(str(tmp_path))
    assert result["exit_code"] == regress.EXIT_OK
    _write(tmp_path, "BENCH_r03.json", _round(3, 0.80))   # -20% throughput
    result = regress.check(str(tmp_path))
    assert result["exit_code"] == regress.EXIT_REGRESSION
    msg = [f for f in result["findings"] if f["severity"] == "error"]
    assert len(msg) == 1 and "noise band" in msg[0]["message"]
    # a wider band absorbs it
    assert regress.check(str(tmp_path), noise=0.30)["exit_code"] == 0


def test_unit_direction_lower_better(tmp_path):
    # ms series: the LARGER latest value is the regression
    _write(tmp_path, "BENCH_r01.json", _round(1, 30.0, unit="ms"))
    _write(tmp_path, "BENCH_r02.json", _round(2, 45.0, unit="ms"))
    assert regress.check(str(tmp_path))["exit_code"] == \
        regress.EXIT_REGRESSION
    # improving latency is not a regression
    _write(tmp_path, "BENCH_r02.json", _round(2, 20.0, unit="ms"))
    assert regress.check(str(tmp_path))["exit_code"] == regress.EXIT_OK


def test_latest_null_exits_2_historical_null_warns(tmp_path):
    _write(tmp_path, "BENCH_r01.json", _round(1, 1.0))
    _write(tmp_path, "BENCH_r02.json", _round(2, None))
    result = regress.check(str(tmp_path))
    assert result["exit_code"] == regress.EXIT_NULL_BANK
    # a later measured round demotes the null to a historical warning
    _write(tmp_path, "BENCH_r03.json", _round(3, 1.02))
    result = regress.check(str(tmp_path))
    assert result["exit_code"] == regress.EXIT_OK
    assert any("[historical]" in f["message"] for f in result["findings"])
    # --strict upgrades the historical null back to an error
    assert regress.check(str(tmp_path), strict=True)["exit_code"] == \
        regress.EXIT_NULL_BANK


def test_null_round_with_sweep_fallback_counts_as_measured(tmp_path):
    _write(tmp_path, "BENCH_r01.json", _round(1, 1.0))
    doc = _round(2, None)
    doc["parsed"]["last_builder_measured"] = {"value": 0.99,
                                              "unit": "iters/sec"}
    _write(tmp_path, "BENCH_r02.json", doc)
    result = regress.check(str(tmp_path))
    assert result["exit_code"] == regress.EXIT_OK
    assert any("sweep fallback" in f["message"]
               for f in result["findings"])


def test_direct_bank_provenance_exits_3(tmp_path):
    bank = {"metric": "serve_e2e_p99_ms", "value": 31.6, "unit": "ms"}
    _write(tmp_path, "BENCH_serve.json", bank)        # no banked_at
    assert regress.check(str(tmp_path))["exit_code"] == \
        regress.EXIT_PROVENANCE
    bank["banked_at"] = "2026-08-05T11:14:02"         # tz-naive
    _write(tmp_path, "BENCH_serve.json", bank)
    assert regress.check(str(tmp_path))["exit_code"] == \
        regress.EXIT_PROVENANCE
    bank["banked_at"] = "2026-08-05T11:14:02+00:00"
    _write(tmp_path, "BENCH_serve.json", bank)
    assert regress.check(str(tmp_path))["exit_code"] == regress.EXIT_OK


def test_multichip_latest_failure_exits_1(tmp_path):
    _write(tmp_path, "MULTICHIP_r01.json",
           {"n_devices": 4, "rc": 0, "ok": True, "skipped": False})
    _write(tmp_path, "MULTICHIP_r02.json",
           {"n_devices": 4, "rc": 124, "ok": False, "skipped": False})
    assert regress.check(str(tmp_path))["exit_code"] == \
        regress.EXIT_REGRESSION
    # skipped rounds never judge the series
    _write(tmp_path, "MULTICHIP_r03.json",
           {"n_devices": 4, "rc": 0, "ok": False, "skipped": True})
    _write(tmp_path, "MULTICHIP_r02.json",
           {"n_devices": 4, "rc": 0, "ok": True, "skipped": False})
    assert regress.check(str(tmp_path))["exit_code"] == regress.EXIT_OK


def test_unreadable_and_unknown_shapes(tmp_path):
    with open(os.path.join(str(tmp_path), "BENCH_r01.json"), "w") as f:
        f.write("{not json")
    result = regress.check(str(tmp_path))
    assert result["exit_code"] == regress.EXIT_NULL_BANK
    assert "unreadable" in result["findings"][0]["message"]
    _write(tmp_path, "BENCH_weird.json", {"something": "else"})
    result = regress.check(str(tmp_path), files=[
        os.path.join(str(tmp_path), "BENCH_weird.json")])
    assert result["exit_code"] == regress.EXIT_OK
    assert "unrecognized" in result["findings"][0]["message"]


def test_render_carries_verdict(tmp_path):
    _write(tmp_path, "BENCH_r01.json", _round(1, 1.0))
    _write(tmp_path, "BENCH_r02.json", _round(2, 0.5))
    text = regress.render(regress.check(str(tmp_path)))
    assert "verdict: REGRESSION (exit 1)" in text
    text = regress.render(regress.check(str(tmp_path), noise=2.0))
    assert "verdict: OK (exit 0)" in text


# -- the CLI surface -------------------------------------------------------

def test_cli_observe_regress_exit_codes(tmp_path, capsys):
    _write(tmp_path, "BENCH_r01.json", _round(1, 1.0))
    _write(tmp_path, "BENCH_r02.json", _round(2, 0.5))
    with pytest.raises(SystemExit) as e:
        cli_main(["observe", "regress", str(tmp_path)])
    assert e.value.code == regress.EXIT_REGRESSION
    capsys.readouterr()
    # clean root returns (no SystemExit) and prints the OK verdict
    cli_main(["observe", "regress", str(tmp_path), "--noise", "2.0"])
    assert "verdict: OK" in capsys.readouterr().out
    # --json emits the machine-readable result
    cli_main(["observe", "regress", str(tmp_path), "--noise", "2.0",
              "--json"])
    j = json.loads(capsys.readouterr().out)
    assert j["exit_code"] == 0 and j["noise"] == 2.0


def test_bench_gate_is_jax_free(tmp_path):
    """The gate must run on hosts with no accelerator stack at all —
    bench_gate.sh loads regress.py standalone (the full CLI surface,
    which imports the package and thus jax, is the convenience path)."""
    poison = tmp_path / "poison"
    poison.mkdir()
    (poison / "jax.py").write_text(
        'raise ImportError("jax must not be imported by the bench gate")\n')
    p = subprocess.run(
        ["bash", os.path.join(REPO, "scripts", "bench_gate.sh")],
        capture_output=True, text=True,
        env={**os.environ, "PYTHONPATH": str(poison)})
    assert p.returncode == 0, p.stdout + p.stderr
    assert "verdict: OK" in p.stdout


# -- the trend-aware gate (--trend) ----------------------------------------


_MASKED_SLIDE = [10.0, 9.2, 8.6, 8.0, 9.2]
# latest 9.2 vs best prior 10.0 is -8%: INSIDE the 10% band, so the
# plain latest-vs-best gate passes — but the least-squares fit over all
# five rounds loses ~11% of its starting value: the masking case the
# trend gate exists for


def _series(d, vals, unit="iters/sec", name="BENCH"):
    for n, v in enumerate(vals, 1):
        _write(d, f"{name}_r{n:02d}.json", _round(n, v, unit=unit))


def test_trend_catches_masked_regression(tmp_path):
    _series(tmp_path, _MASKED_SLIDE)
    assert regress.check(str(tmp_path))["exit_code"] == regress.EXIT_OK
    result = regress.check(str(tmp_path), trend=True)
    assert result["exit_code"] == regress.EXIT_REGRESSION
    assert any("trend" in f["message"] and "falling" in f["message"]
               for f in result["findings"])


def test_trend_clean_on_stable_series(tmp_path):
    _series(tmp_path, [10.0, 10.2, 9.9, 10.1, 10.0])
    assert regress.check(str(tmp_path), trend=True)["exit_code"] == \
        regress.EXIT_OK


def test_trend_direction_aware(tmp_path):
    # an IMPROVING series drifts steeply but in the better direction
    _series(tmp_path, [8.0, 8.6, 9.2, 10.0])
    assert regress.check(str(tmp_path), trend=True)["exit_code"] == \
        regress.EXIT_OK
    # lower-better unit: the same RISING values are now a regression
    _series(tmp_path, [8.0, 8.6, 9.2, 10.0], unit="ms",
            name="BENCH_lat")
    result = regress.check(str(tmp_path), trend=True)
    assert result["exit_code"] == regress.EXIT_REGRESSION
    assert any("rising" in f["message"] for f in result["findings"])


def test_trend_needs_three_points(tmp_path):
    # a 2-point slide is latest-vs-best territory; the trend fit stays
    # quiet (this also keeps the committed 2-point BENCH_r history
    # trend-clean at the repo root)
    _series(tmp_path, [10.0, 8.9])
    result = regress.check(str(tmp_path), trend=True)
    assert not any("trend" in f["message"] for f in result["findings"])


def test_trend_window_bounds_the_fit(tmp_path):
    # ancient history outside the window must not drag the fit: the
    # last 3 rounds are flat, the slide is 5 rounds old (the plain
    # latest-vs-best finding fires either way — judge the TREND
    # findings specifically)
    _series(tmp_path, [14.0, 12.0, 10.0, 10.0, 10.0, 10.0])

    def trend_findings(window):
        result = regress.check(str(tmp_path), trend=True,
                               trend_window=window)
        return [f for f in result["findings"] if "trend" in f["message"]]

    assert not trend_findings(3)
    assert trend_findings(6)


def test_committed_banks_gate_clean_with_trend():
    # scripts/bench_gate.sh now runs with trend ON by default — the
    # committed history must hold under the stronger gate
    result = regress.check(REPO, trend=True)
    assert result["exit_code"] == regress.EXIT_OK
    assert result["trend"] is True


def test_trend_cli_flag(tmp_path, capsys):
    _series(tmp_path, _MASKED_SLIDE)
    cli_main(["observe", "regress", str(tmp_path)])
    capsys.readouterr()
    with pytest.raises(SystemExit) as e:
        cli_main(["observe", "regress", str(tmp_path), "--trend"])
    assert e.value.code == regress.EXIT_REGRESSION
    out = capsys.readouterr().out
    assert "trend window 5" in out


def test_bench_gate_script_no_trend_flag(tmp_path):
    # the script gates with trend by default; --no-trend restores the
    # plain latest-vs-best behaviour
    _series(tmp_path, _MASKED_SLIDE)
    gate = os.path.join(REPO, "scripts", "bench_gate.sh")
    p = subprocess.run(["bash", gate, str(tmp_path)],
                       capture_output=True, text=True)
    assert p.returncode == regress.EXIT_REGRESSION, p.stdout + p.stderr
    p = subprocess.run(["bash", gate, str(tmp_path), "--no-trend"],
                       capture_output=True, text=True)
    assert p.returncode == 0, p.stdout + p.stderr
