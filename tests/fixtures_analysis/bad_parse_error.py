"""Fixture: TAL000 — the file does not parse."""
def broken(:
    return
