"""Fixture: TAL001 — Python branch on a traced value in a jitted fn."""
import jax
import jax.numpy as jnp


@jax.jit
def relu_or_neg(x):
    y = jnp.sum(x)
    if y > 0:
        return y
    return -y
