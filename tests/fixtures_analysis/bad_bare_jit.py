"""Fixture: TAL008 — jit built inside a plain function recompiles."""
import jax


def scorer(x):
    f = jax.jit(lambda y: y * 2.0)
    return f(x)
