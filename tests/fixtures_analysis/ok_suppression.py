"""Fixture negative: a real finding suppressed with a reason."""
import jax


def scorer(x):
    # tal: disable=bare-jit -- fixture: the per-call jit IS the point
    f = jax.jit(lambda y: y * 2.0)
    return f(x)
