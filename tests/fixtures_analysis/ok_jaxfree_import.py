"""Fixture negative: deliberately jax-free, and actually stdlib-only."""
import json


def probe():
    return json.dumps({"ok": True})
