"""Fixture: TAL003 — wall clock / host RNG baked in at trace time."""
import random
import time

import jax
import jax.numpy as jnp


@jax.jit
def stamped(x):
    t = time.time()
    return jnp.sum(x) + t + random.random()
