"""Fixture: TAL012 — suppressions without a reason / of unknown rules."""
import jax


def scorer(x):
    f = jax.jit(lambda y: y * 2.0)  # tal: disable=bare-jit
    return f(x)


def other(x):
    # tal: disable=not-a-rule -- the rule name does not exist
    return x
