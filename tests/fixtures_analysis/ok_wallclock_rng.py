"""Fixture negative: clock outside the trace, jax.random inside."""
import time

import jax
import jax.numpy as jnp


@jax.jit
def noised(x, key):
    return jnp.sum(x) + jax.random.normal(key, ())


def timed(x, key):
    t0 = time.perf_counter()
    y = noised(x, key)
    return y, time.perf_counter() - t0
