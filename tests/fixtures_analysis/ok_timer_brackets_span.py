"""Fixture negative: the clock starts inside the span body."""
import time

from tpu_als import obs


def timed(work):
    with obs.span("fixture.work"):
        t0 = time.perf_counter()
        work()
        return time.perf_counter() - t0
