"""Fixture: TAL005 — unconditional bf16 downcast, no dtype gate."""
import jax
import jax.numpy as jnp


@jax.jit
def shrink(x):
    return x.astype(jnp.bfloat16) * 2.0
