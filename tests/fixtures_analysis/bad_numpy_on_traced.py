"""Fixture: TAL006 — numpy consuming a traced array."""
import numpy as np

import jax
import jax.numpy as jnp


@jax.jit
def bad_norm(x):
    y = jnp.sum(x * x)
    return np.sqrt(y)
