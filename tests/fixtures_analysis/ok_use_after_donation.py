"""Fixture negative: rebinding the donated names (the als.py loop)."""
import jax


def _step_impl(U, V):
    return U + 1.0, V + 1.0


step = jax.jit(_step_impl, donate_argnums=(0, 1))


def drive(U, V):
    last_good = (U, V)
    U, V = step(U, V)
    return U.sum() + V.sum(), last_good
