"""Fixture negative: data branch via jnp.where, static-shape branch ok."""
import jax
import jax.numpy as jnp


@jax.jit
def relu_or_neg(x):
    y = jnp.sum(x)
    if x.shape[0] > 4:
        y = y / x.shape[0]
    return jnp.where(y > 0, y, -y)
