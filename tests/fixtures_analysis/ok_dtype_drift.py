"""Fixture negative: downcast gated on (and restoring) the input dtype."""
import jax
import jax.numpy as jnp


@jax.jit
def shrink(x):
    orig = x.dtype
    y = x.astype(jnp.bfloat16) * 2.0
    return y.astype(orig)
