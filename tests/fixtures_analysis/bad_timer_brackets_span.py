"""Fixture: TAL011 — the clock brackets the span enter/exit emission."""
import time

from tpu_als import obs


def timed(work):
    t0 = time.perf_counter()
    with obs.span("fixture.work"):
        work()
    return time.perf_counter() - t0
