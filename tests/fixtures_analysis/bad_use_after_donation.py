"""Fixture: TAL004 — reading a buffer after donating it."""
import jax


def _step_impl(U, V):
    return U + 1.0, V + 1.0


step = jax.jit(_step_impl, donate_argnums=(0, 1))


def drive(U, V):
    U2, V2 = step(U, V)
    return U.sum() + U2.sum()
