"""Fixture negative: module-level jit and a build-once factory."""
import jax


def _double(y):
    return y * 2.0


scorer = jax.jit(_double)


def make_scorer(scale):
    return jax.jit(lambda y: y * scale)
