"""Fixture negative: the jitter default is threaded, not hardcoded."""
import jax.numpy as jnp

from tpu_als.ops.solve import DEFAULT_JITTER


def regularize(A, jitter=DEFAULT_JITTER):
    return A + jitter * jnp.eye(A.shape[-1])
