"""Fixture: TAL007 — metric literal not declared in the obs schema."""
from tpu_als import obs


def report(n):
    obs.counter("fixture.not_registered", n)
