"""Fixture: TAL010.  Deliberately jax-free — except it isn't."""
import jax


def probe():
    return jax.__name__
