"""Fixture: TAL002 — host print inside a jitted fn fires at trace only."""
import jax
import jax.numpy as jnp


@jax.jit
def noisy_sum(x):
    y = jnp.sum(x)
    print("partial:", y)
    return y
