"""Fixture negative: jnp on traced values; numpy only on host constants."""
import numpy as np

import jax
import jax.numpy as jnp

SCALE = np.float32(2.0)


@jax.jit
def good_norm(x):
    y = jnp.sum(x * x)
    return jnp.sqrt(y) * SCALE
