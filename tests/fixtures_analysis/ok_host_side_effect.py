"""Fixture negative: jax.debug.print is the sanctioned escape hatch."""
import jax
import jax.numpy as jnp


@jax.jit
def noisy_sum(x):
    y = jnp.sum(x)
    jax.debug.print("partial: {}", y)
    return y
