"""Fixture negative: a declared counter, used with its declared kind."""
from tpu_als import obs


def report(n):
    obs.counter("serve.requests", n)
