"""Fixture: TAL009 — hardcoded 1e-6 jitter literal."""
import jax.numpy as jnp


def regularize(A, jitter=1e-6):
    return A + jitter * jnp.eye(A.shape[-1])
