"""tpu_als.perf.roofline — the analytical bytes/FLOPs model (ISSUE 2).

The load-bearing check: the roofline's collective stage priced from
built partitions/containers must EQUAL trainer.comm_bytes_per_iter,
which tests/test_comm_audit.py pins to the traced jaxpr — so the
roofline's comm bytes are transitively traced-checked here without
re-tracing a step.
"""

import json
import subprocess
import sys

import numpy as np
import pytest

from tpu_als.parallel.data import partition_balanced, shard_csr
from tpu_als.parallel.trainer import comm_bytes_per_iter
from tpu_als.perf.roofline import (
    HEADLINE,
    HEADLINE_MEASURED_S_PER_ITER,
    headline_roofline,
    modeled_padding_waste,
    render,
    roofline,
)

D = 8


def _parts_and_containers(rng):
    nU, nI, nnz = 60, 40, 900
    u = rng.integers(0, nU, nnz)
    i = rng.integers(0, nI, nnz)
    r = np.abs(rng.normal(size=nnz)).astype(np.float32) + 0.1
    upart = partition_balanced(np.bincount(u, minlength=nU), D)
    ipart = partition_balanced(np.bincount(i, minlength=nI), D)
    ush = shard_csr(upart, ipart, u, i, r, min_width=4, chunk_elems=512)
    ish = shard_csr(ipart, upart, i, u, r, min_width=4, chunk_elems=512)
    return (nU, nI, nnz), upart, ipart, ush, ish


@pytest.mark.parametrize("strategy", ["all_gather", "all_gather_chunked"])
def test_collective_stage_equals_comm_model(rng, strategy):
    (nU, nI, nnz), upart, ipart, ush, ish = _parts_and_containers(rng)
    rank = 8
    rep = roofline(nU, nI, nnz, rank, implicit=True, devices=D,
                   strategy=strategy, user_part=upart, item_part=ipart,
                   user_container=ush, item_container=ish)
    model = comm_bytes_per_iter(strategy, upart, ipart, rank,
                                user_container=ush, item_container=ish,
                                implicit=True)
    assert rep["comm_bytes_per_iter"] == model
    coll = [s for s in rep["stages"] if s["name"] == "collective"]
    assert len(coll) == 1 and coll[0]["bytes"] == model


def test_closed_form_fallback_matches_balanced_exact(rng):
    """Without containers the roofline falls back to a closed form with
    rows_per_shard = ceil(n/D); on a shape where partition_balanced is
    exactly balanced at 1 tile, fallback == exact."""
    nU = nI = 64
    u = np.repeat(np.arange(nU), 2)
    i = (u * 7 + 3) % nI
    vals = np.ones(len(u), np.float32)
    upart = partition_balanced(np.bincount(u, minlength=nU), D)
    ipart = partition_balanced(np.bincount(i, minlength=nI), D)
    rank = 16
    for strategy in ("all_gather", "ring", "ring_overlap",
                     "all_gather_chunked"):
        exact = comm_bytes_per_iter(strategy, upart, ipart, rank,
                                    implicit=True)
        rep = roofline(nU, nI, len(u), rank, implicit=True, devices=D,
                       strategy=strategy)
        assert rep["comm_bytes_per_iter"] == exact, strategy


def test_headline_floor_sane():
    rep = headline_roofline()
    # the measured point must sit ABOVE the floor (a floor above the
    # measurement means the byte accounting is wrong), and within an
    # order of magnitude (the documented gap is ~6.6x — VPU Cholesky)
    assert rep["measured_s_per_iter"] == HEADLINE_MEASURED_S_PER_ITER
    assert rep["hbm_floor_s_per_iter"] < rep["measured_s_per_iter"]
    assert 1.0 < rep["measured_over_hbm_floor"] < 20.0
    assert rep["roofline_floor_s_per_iter"] >= rep["hbm_floor_s_per_iter"]
    # every stage is priced: no zero-byte on-chip stages at rank 128
    assert all(s["bytes"] > 0 for s in rep["stages"])
    # render() must format without error and show the floor + measured
    text = render(rep)
    assert "HBM floor" in text and "measured" in text


def test_restream_scales_gather_stream():
    base = roofline(**HEADLINE)
    tiled = roofline(**dict(HEADLINE, devices=8), strategy="ring_overlap",
                     tiles_user=3, tiles_item=3)
    gs = {s["name"]: s["bytes"] for s in base["stages"]}
    gt = {s["name"]: s["bytes"] for s in tiled["stages"]}
    # tiling re-streams the gathered factors ~3x (the 12*P rating stream
    # is not re-read, so strictly less than 3x)
    assert 2.0 < gt["gather_stream"] * 8 / gs["gather_stream"] < 3.0


def _powerlaw_degrees(rng, n, cap, scale=6):
    deg = np.minimum((rng.pareto(1.1, n) * scale + 1).astype(np.int64), cap)
    deg[rng.random(n) < 0.1] = 0  # leave some entities unrated
    return deg


@pytest.mark.parametrize("growth", [2.0, 1.5])
@pytest.mark.parametrize("chunk_elems", [512, 1 << 19])
def test_modeled_padding_waste_matches_built_buckets(rng, growth,
                                                     chunk_elems):
    """The derived waste (what the roofline now uses instead of the
    hardcoded 1.514) must EQUAL padded_nnz/nnz of an actual
    build_csr_buckets run — same width assignment, same row padding —
    on skewed power-law degrees, across chunk budgets and width ladders."""
    from tpu_als.core.ratings import build_csr_buckets

    nU, nI = 150, 80
    deg = _powerlaw_degrees(rng, nU, nI)
    u = np.repeat(np.arange(nU), deg)
    i = rng.integers(0, nI, len(u))
    vals = np.ones(len(u), np.float32)
    csr = build_csr_buckets(u, i, vals, nU, min_width=8,
                            chunk_elems=chunk_elems, width_growth=growth)
    modeled = modeled_padding_waste(np.bincount(u, minlength=nU),
                                    min_width=8, chunk_elems=chunk_elems,
                                    growth=growth)
    assert modeled == pytest.approx(csr.padded_nnz / csr.nnz, rel=0, abs=0)


def test_width_growth_15_tighter_than_pow2(rng):
    """The growth=1.5 ladder (AlsConfig's unmeasured knob): every width
    still covers its count, stays a sublane multiple (the fused kernel
    and sharded stackers rely on %8==0), never exceeds the pow2 width,
    and cuts the MODELED padding waste on power-law degrees — the claim
    the sweep's headline_wg15 ablation step measures on hardware."""
    from tpu_als.core.ratings import entity_widths

    counts = _powerlaw_degrees(rng, 5000, 4096, scale=12)
    rated = counts[counts > 0]
    w20 = entity_widths(rated, 8, growth=2.0)
    w15 = entity_widths(rated, 8, growth=1.5)
    assert (w15 >= rated).all()
    assert (w15 % 8 == 0).all()
    assert (w15 <= w20).all()
    waste20 = modeled_padding_waste(counts, min_width=8, growth=2.0)
    waste15 = modeled_padding_waste(counts, min_width=8, growth=1.5)
    assert waste15 < waste20, (waste15, waste20)


def test_roofline_padding_waste_provenance(rng):
    cu = _powerlaw_degrees(rng, 200, 100)
    ci = _powerlaw_degrees(rng, 100, 200)
    nnz = int(cu.sum())
    derived = roofline(200, 100, nnz, 16, user_counts=cu, item_counts=ci)
    assert derived["config"]["padding_waste_source"] == "derived"
    expect = (modeled_padding_waste(cu) + modeled_padding_waste(ci)) / 2
    assert derived["config"]["padding_waste"] == pytest.approx(expect)
    explicit = roofline(200, 100, nnz, 16, padding_waste=1.514)
    assert explicit["config"]["padding_waste_source"] == "explicit"
    assert explicit["config"]["padding_waste"] == 1.514
    default = roofline(200, 100, nnz, 16)
    assert default["config"]["padding_waste_source"] == "default"
    assert default["config"]["padding_waste"] == 1.0
    # the derived-vs-explicit knob changes ONLY byte totals, not stages
    assert [s["name"] for s in derived["stages"]] == \
        [s["name"] for s in explicit["stages"]]


def test_cli_roofline_json():
    out = subprocess.run(
        [sys.executable, "-m", "tpu_als.cli", "observe", "roofline",
         "--json"],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    rep = json.loads(out.stdout)
    assert rep["config"]["rank"] == HEADLINE["rank"]
    assert rep["measured_s_per_iter"] == HEADLINE_MEASURED_S_PER_ITER
