"""Worker for the REAL multi-process test (tests/test_multihost.py).

Each of the two spawned processes owns 2 CPU devices of a 4-device global
mesh and starts with a DISJOINT half of the rating triples (as if each
read its own input split).  ``train_multihost`` then redistributes,
blocks per-host (shard_csr positions=), assembles global arrays, and runs
the sharded trainer with cross-process gloo collectives.  The worker
saves its local factor rows for the parent to compare against a
single-process run over the full data.

Env contract (set by the parent): JAX_COORDINATOR_ADDRESS,
JAX_NUM_PROCESSES, JAX_PROCESS_ID (exercises init_distributed's env-var
path), MH_OUT (output .npz path prefix).
"""

import os
import sys

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")

import numpy as np

from tpu_als.core.als import AlsConfig
from tpu_als.parallel.mesh import make_mesh
from tpu_als.parallel.multihost import init_distributed, train_multihost


def main():
    pid, pcount = init_distributed()  # env-var path
    assert pcount == 2, pcount
    assert jax.device_count() == 4
    mesh = make_mesh()

    # identical seeded synthetic on both hosts; each KEEPS only its half
    # (interleaved split, as if reading separate input files)
    rng = np.random.default_rng(7)
    nU, nI, nnz = 50, 30, 600
    u = rng.integers(0, nU, nnz)
    i = rng.integers(0, nI, nnz)
    r = np.abs(rng.normal(size=nnz)).astype(np.float32) + 0.1
    mine = np.arange(nnz) % 2 == pid
    cfg = AlsConfig(rank=6, max_iter=2, reg_param=0.05, implicit_prefs=True,
                    alpha=3.0, seed=0)
    U, V, upart, ipart = train_multihost(
        u[mine], i[mine], r[mine], nU, nI, cfg, mesh=mesh, min_width=4)

    out = {}
    for name, arr, rps in (("U", U, upart.rows_per_shard),
                           ("V", V, ipart.rows_per_shard)):
        for s in arr.addressable_shards:
            pos = s.index[0].start // rps if s.index[0].start else 0
            out[f"{name}{pos}"] = np.asarray(s.data)
    np.savez(os.environ["MH_OUT"] + f".{pid}.npz", **out)
    print(f"worker {pid} ok", flush=True)


if __name__ == "__main__":
    main()
