"""Worker for the REAL multi-process test (tests/test_multihost.py).

Each of the two spawned processes owns 2 CPU devices of a 4-device global
mesh and starts with a DISJOINT half of the rating triples (as if each
read its own input split).  ``train_multihost`` then redistributes,
blocks per-host (shard_csr positions=), assembles global arrays, and runs
the sharded trainer with cross-process gloo collectives.  The worker
saves its local factor rows for the parent to compare against a
single-process run over the full data.

Env contract (set by the parent): JAX_COORDINATOR_ADDRESS,
JAX_NUM_PROCESSES, JAX_PROCESS_ID (exercises init_distributed's env-var
path), MH_OUT (output .npz path prefix).
"""

import os
import sys

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")

import numpy as np

from tpu_als.core.als import AlsConfig
from tpu_als.parallel.mesh import make_mesh
from tpu_als.parallel.multihost import init_distributed, train_multihost


def main():
    pid, pcount = init_distributed()  # env-var path
    assert pcount == 2, pcount
    assert jax.device_count() == 4
    mesh = make_mesh()

    # identical seeded synthetic on both hosts; each KEEPS only its half
    # (interleaved split, as if reading separate input files)
    rng = np.random.default_rng(7)
    nU, nI, nnz = 50, 30, 600
    u = rng.integers(0, nU, nnz)
    i = rng.integers(0, nI, nnz)
    r = np.abs(rng.normal(size=nnz)).astype(np.float32) + 0.1
    mine = np.arange(nnz) % 2 == pid
    cfg = AlsConfig(rank=6, max_iter=2, reg_param=0.05, implicit_prefs=True,
                    alpha=3.0, seed=0)
    U, V, upart, ipart = train_multihost(
        u[mine], i[mine], r[mine], nU, nI, cfg, mesh=mesh, min_width=4)

    out = {}
    for name, arr, rps in (("U", U, upart.rows_per_shard),
                           ("V", V, ipart.rows_per_shard)):
        for s in arr.addressable_shards:
            pos = s.index[0].start // rps if s.index[0].start else 0
            out[f"{name}{pos}"] = np.asarray(s.data)
    np.savez(os.environ["MH_OUT"] + f".{pid}.npz", **out)
    print(f"worker {pid} ok", flush=True)


def main_serve():
    """Sharded SERVING across processes (parallel/serve.py): both
    strategies' cross-process collectives (all_gather / ppermute ring)
    over the 2-process x 2-device gloo mesh.  Each process saves its
    addressable output shards; the parent stitches and compares to the
    single-device reference."""
    pid, pcount = init_distributed()
    assert pcount == 2, pcount
    mesh = make_mesh()

    from tpu_als.parallel.serve import topk_sharded

    # divisible by the 4-device mesh so output shards map cleanly
    rng = np.random.default_rng(11)
    U = rng.normal(size=(24, 8)).astype(np.float32)
    V = rng.normal(size=(36, 8)).astype(np.float32)
    out = {}
    for strategy in ("all_gather", "ring"):
        s, ix = topk_sharded(U, V, 6, mesh, strategy=strategy)
        for arr, tag in ((s, "s"), (ix, "i")):
            for sh in arr.addressable_shards:
                row0 = sh.index[0].start or 0
                out[f"{tag}_{strategy}_{row0}"] = np.asarray(sh.data)
    np.savez(os.environ["MH_OUT"] + f".{pid}.npz", **out)
    print(f"serve worker {pid} ok", flush=True)


def main_stream_vocab():
    """The full config-3 flow across REAL processes: each host streams
    its byte range of a shared STRING-id csv (io/stream.py), the
    vocabularies are agreed with global_vocab_union, and the remapped
    per-host triples train through train_multihost — no host ever parses
    the other's rows."""
    pid, pcount = init_distributed()
    assert pcount == 2, pcount
    mesh = make_mesh()

    from tpu_als.io.stream import stream_ingest
    from tpu_als.parallel.multihost import global_vocab_union

    u_loc, i_loc, r, ul, il = stream_ingest(
        os.environ["MH_CSV"], pid, pcount, chunk_bytes=97)
    g_ul = global_vocab_union(ul)
    g_il = global_vocab_union(il)
    # lexicographic global space -> remap is one searchsorted per side
    u = np.searchsorted(g_ul, ul)[u_loc]
    i = np.searchsorted(g_il, il)[i_loc]
    cfg = AlsConfig(rank=4, max_iter=2, reg_param=0.05,
                    implicit_prefs=True, alpha=3.0, seed=0)
    U, V, upart, ipart = train_multihost(
        u, i, r, len(g_ul), len(g_il), cfg, mesh=mesh, min_width=4)
    out = {"g_ul": g_ul.astype("S16"), "g_il": g_il.astype("S16"),
           "rows": np.array([len(u_loc)])}
    for name, arr, rps in (("U", U, upart.rows_per_shard),
                           ("V", V, ipart.rows_per_shard)):
        for s in arr.addressable_shards:
            pos = s.index[0].start // rps if s.index[0].start else 0
            out[f"{name}{pos}"] = np.asarray(s.data)
    np.savez(os.environ["MH_OUT"] + f".{pid}.npz", **out)
    print(f"stream-vocab worker {pid} ok", flush=True)


if __name__ == "__main__":
    if os.environ.get("MH_MODE") == "serve":
        main_serve()
    elif os.environ.get("MH_MODE") == "stream_vocab":
        main_stream_vocab()
    else:
        main()
