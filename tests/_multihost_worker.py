"""Worker for the REAL multi-process test (tests/test_multihost.py).

Each of the two spawned processes owns 2 CPU devices of a 4-device global
mesh, blocks ONLY its local ratings (multihost.local_rating_mask +
data.shard_csr positions=), assembles global arrays with
``jax.make_array_from_process_local_data``, runs one sharded ALS step over
the global mesh (cross-process collectives via gloo), and saves its local
factor rows for the parent to compare against a single-process run.

Env contract (set by the parent): JAX_COORDINATOR_ADDRESS,
JAX_NUM_PROCESSES, JAX_PROCESS_ID (exercises init_distributed's env-var
path), MH_OUT (output .npz path prefix).
"""

import os
import sys

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")

import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from tpu_als.core.als import AlsConfig, init_factors
from tpu_als.parallel.data import partition_balanced, shard_csr
from tpu_als.parallel.mesh import AXIS, make_mesh
from tpu_als.parallel.multihost import (
    init_distributed,
    local_positions,
    local_rating_mask,
)
from tpu_als.parallel.trainer import make_sharded_step


def main():
    pid, pcount = init_distributed()  # env-var path
    assert pcount == 2, pcount
    D = jax.device_count()
    assert D == 4, D
    mesh = make_mesh()  # global mesh over all 4 devices
    positions = local_positions(mesh)
    assert len(positions) == 2, positions

    # identical synthetic data on both hosts (seeded) — only the LOCAL
    # subset is fed to the blocking builders below
    rng = np.random.default_rng(7)
    nU, nI, nnz = 50, 30, 600
    u = rng.integers(0, nU, nnz)
    i = rng.integers(0, nI, nnz)
    r = np.abs(rng.normal(size=nnz)).astype(np.float32) + 0.1
    ucounts = np.bincount(u, minlength=nU)
    icounts = np.bincount(i, minlength=nI)
    upart = partition_balanced(ucounts, D)
    ipart = partition_balanced(icounts, D)

    umask = local_rating_mask(upart, u, positions=positions)
    imask = local_rating_mask(ipart, i, positions=positions)
    ush = shard_csr(upart, ipart, u[umask], i[umask], r[umask], min_width=4,
                    positions=positions, row_counts=ucounts)
    ish = shard_csr(ipart, upart, i[imask], u[imask], r[imask], min_width=4,
                    positions=positions, row_counts=icounts)

    leading = NamedSharding(mesh, P(AXIS))

    def assemble(local):
        return jax.make_array_from_process_local_data(leading, local)

    ub = jax.tree.map(assemble, ush.device_buckets())
    ib = jax.tree.map(assemble, ish.device_buckets())

    cfg = AlsConfig(rank=6, max_iter=1, reg_param=0.05, implicit_prefs=True,
                    alpha=3.0, seed=0)
    key = jax.random.PRNGKey(cfg.seed)
    ku, kv = jax.random.split(key)
    # slot-space factors: full init on every host (cheap), local rows fed
    # to the global array
    U0 = np.zeros((upart.padded_rows, cfg.rank), np.float32)
    U0[upart.slot] = np.asarray(init_factors(ku, nU, cfg.rank))
    V0 = np.zeros((ipart.padded_rows, cfg.rank), np.float32)
    V0[ipart.slot] = np.asarray(init_factors(kv, nI, cfg.rank))
    rps_u, rps_i = upart.rows_per_shard, ipart.rows_per_shard
    U_loc = np.concatenate([U0[p * rps_u:(p + 1) * rps_u] for p in positions])
    V_loc = np.concatenate([V0[p * rps_i:(p + 1) * rps_i] for p in positions])
    U = jax.make_array_from_process_local_data(leading, U_loc)
    V = jax.make_array_from_process_local_data(leading, V_loc)

    step = make_sharded_step(mesh, ush, ish, cfg)
    U1, V1 = step(U, V, ub, ib)

    out = {}
    for name, arr, rps in (("U", U1, rps_u), ("V", V1, rps_i)):
        for s in arr.addressable_shards:
            pos = s.index[0].start // rps if s.index[0].start else 0
            out[f"{name}{pos}"] = np.asarray(s.data)
    np.savez(os.environ["MH_OUT"] + f".{pid}.npz", **out)
    print(f"worker {pid} ok", flush=True)


if __name__ == "__main__":
    main()
