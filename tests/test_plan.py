"""Execution planner (tpu_als.plan, docs/planner.md): the persistent
autotune cache, the seed-and-walk resolve discipline, and every dispatch
site that consults it.

The load-bearing pins, straight from the subsystem's contract:

- EQUIVALENCE: warm cache, cold cache, and planner-off must resolve the
  exact same plan at every dispatch site — the cache supplies probe
  outcomes, never a different answer.
- ZERO PROBES WARM: a separate process resolving the same plan key must
  perform no probe executions, asserted from the obs event trail
  (``plan_cache_hit`` present, ``plan_probe`` absent).
- NEVER TRUST CORRUPTION: a corrupt or schema-mismatched entry is typed
  (``PlanCacheCorrupt``), quarantined to ``.corrupt/``, and reprobed —
  never crashed on, never silently steering a plan.
- OFF IS FREE: ``TPU_ALS_PLAN_CACHE=off`` leaves the training step's
  traced jaxpr byte-identical (the ne_audit/attribution discipline).
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tpu_als import ALS, obs, plan
from tpu_als.core.als import AlsConfig, init_factors, make_step
from tpu_als.core.als import resolve_solve_path
from tpu_als.core.ratings import build_csr_buckets
from tpu_als.plan import cache as plan_cache
from tpu_als.plan.cache import ENV_VAR, PlanCacheCorrupt
from tpu_als.serving.batcher import DEFAULT_BUCKETS
from tpu_als.utils import platform

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_plan_state(monkeypatch, tmp_path):
    """Each test gets its own cache dir, an empty probe registry, and a
    clean obs registry — planner state is exactly what the test builds."""
    monkeypatch.setenv(ENV_VAR, str(tmp_path / "plan"))
    platform.clear_probe_caches()
    obs.reset()
    yield
    platform.clear_probe_caches()
    obs.reset()


def _events(etype):
    return [e for e in obs.default_registry()._events if e["type"] == etype]


def _problem(nU=60, nI=40, nnz=800, seed=0):
    gen = np.random.default_rng(seed)
    u = gen.integers(0, nU, nnz)
    i = gen.integers(0, nI, nnz)
    r = gen.uniform(0.5, 5.0, nnz).astype(np.float32)
    ucsr = build_csr_buckets(u, i, r, nU, min_width=4, chunk_elems=1 << 12)
    icsr = build_csr_buckets(i, u, r, nI, min_width=4, chunk_elems=1 << 12)
    return ucsr, icsr


# -- cache layer (stdlib-only): mode, roundtrip, validation, quarantine ----

def test_mode_and_off_values(monkeypatch):
    for v in ("off", "OFF", "0", "none", "disabled", " Off "):
        monkeypatch.setenv(ENV_VAR, v)
        assert plan_cache.mode() == "off"
        assert plan_cache.cache_dir() is None
        assert not plan.armed()
        with pytest.raises(RuntimeError, match="disarmed"):
            plan_cache.entry_path({"rank": 4})
    monkeypatch.setenv(ENV_VAR, "/tmp/somewhere")
    assert plan_cache.mode() == "/tmp/somewhere"
    assert plan.armed()


def test_key_digest_stable_and_shape_class():
    k1 = {"rank": 4, "dtype": "float32"}
    assert plan_cache.key_digest(k1) == plan_cache.key_digest(dict(k1))
    assert plan_cache.key_digest(k1) != plan_cache.key_digest(
        {"rank": 8, "dtype": "float32"})
    assert plan.shape_class() == "generic"
    # log2 bucketing: near sizes share a class, order-of-magnitude don't
    a = plan.shape_class(n_users=1000, n_items=500, nnz=10_000)
    b = plan.shape_class(n_users=1023, n_items=400, nnz=12_000)
    c = plan.shape_class(n_users=100_000, n_items=500, nnz=10_000)
    assert a == b != c
    assert plan.shape_class(n_users=1000) == "u2^9.i?.nnz?"


def _entry_for(key, resolved="xla"):
    return {
        "schema_version": plan_cache.SCHEMA_VERSION,
        "plan_key": key,
        "probes": {"pallas_topk": {"(8, 5)": True}},
        "components": {"topk:k=5": {
            "resolved": resolved,
            "provenance": {"banked_at": "2026-08-05T00:00:00+00:00"},
        }},
    }


def test_store_load_roundtrip_atomic(tmp_path):
    key = plan.plan_key(rank=8, dtype="float32")
    path = plan_cache.store_entry(key, _entry_for(key))
    assert os.path.basename(path).startswith("plan_")
    doc = plan_cache.load_entry(key)
    assert doc["components"]["topk:k=5"]["resolved"] == "xla"
    # no temp litter from the atomic-rename discipline
    leftovers = [n for n in os.listdir(os.path.dirname(path)) if ".tmp." in n]
    assert leftovers == []
    # absent key reads as None, not an error
    assert plan_cache.load_entry(plan.plan_key(rank=99, dtype="float32")) \
        is None


@pytest.mark.parametrize("mutate,match", [
    (lambda d: d.update(schema_version=999), "schema_version"),
    (lambda d: d.update(plan_key={"rank": -1}), "plan_key mismatch"),
    (lambda d: d.update(probes={"pallas_topk": {"k": "yes"}}),
     "not {key: bool}"),
    (lambda d: d["components"]["topk:k=5"].pop("resolved"),
     "no resolved plan"),
    (lambda d: d["components"]["topk:k=5"].update(provenance={}),
     "banked_at"),
])
def test_schema_violations_are_typed(mutate, match):
    key = plan.plan_key(rank=8, dtype="float32")
    path = plan_cache.store_entry(key, _entry_for(key))
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    mutate(doc)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f)
    with pytest.raises(PlanCacheCorrupt, match=match) as ei:
        plan_cache.load_entry(key)
    assert ei.value.path == path


def test_unparseable_json_is_typed_and_quarantine_keeps_evidence():
    key = plan.plan_key(rank=8, dtype="float32")
    path = plan_cache.store_entry(key, _entry_for(key))
    with open(path, "w", encoding="utf-8") as f:
        f.write("{ this is not json")
    with pytest.raises(PlanCacheCorrupt, match="unreadable JSON"):
        plan_cache.load_entry(key)
    dest = plan_cache.quarantine(path, "unreadable JSON")
    assert not os.path.exists(path)          # moved, not copied
    assert os.path.exists(dest)
    with open(dest + ".reason", encoding="utf-8") as f:
        assert "unreadable" in f.read()
    assert plan_cache.quarantine(path, "again") is None   # already gone


def test_list_entries_renders_corrupt_without_raising(tmp_path):
    key = plan.plan_key(rank=8, dtype="float32")
    plan_cache.store_entry(key, _entry_for(key))
    bad = os.path.join(plan_cache.cache_dir(), "plan_deadbeef00.json")
    with open(bad, "w", encoding="utf-8") as f:
        f.write("garbage")
    entries = plan_cache.list_entries()
    kinds = sorted(type(doc).__name__ for _, doc in entries)
    assert kinds == ["PlanCacheCorrupt", "dict"]
    assert plan_cache.clear() == 2           # both files removed
    assert plan_cache.list_entries() == []


# -- planner resolve discipline: cold banks, warm seeds, corrupt reprobes --

def test_cold_resolve_banks_with_provenance_and_emits_trail():
    out = plan.resolve_topk(rank=8, k=5, walk=lambda: "xla")
    assert out == "xla"
    miss = _events("plan_cache_miss")
    assert len(miss) == 1 and miss[0]["reason"] == "absent"
    probes = _events("plan_probe")
    assert any(e["kernel"] == "walk:topk:k=5" for e in probes)
    res = _events("plan_resolved")
    assert len(res) == 1 and res[0]["source"] == "probe"
    entry = plan_cache.load_entry(plan.plan_key(rank=8, dtype="float32"))
    comp = entry["components"]["topk:k=5"]
    assert comp["resolved"] == "xla"
    prov = comp["provenance"]
    assert prov["banked_at"] and prov["walk_seconds"] >= 0
    assert prov["model"]["proposal"] in ("pallas", "xla")


def test_warm_resolve_hits_and_runs_zero_probes():
    plan.resolve_topk(rank=8, k=5, walk=lambda: "xla")
    platform.clear_probe_caches()            # simulate a fresh process
    obs.reset()
    out = plan.resolve_topk(rank=8, k=5, walk=lambda: "xla")
    assert out == "xla"
    assert len(_events("plan_cache_hit")) == 1
    assert _events("plan_probe") == []       # the warm-start contract
    res = _events("plan_resolved")
    assert len(res) == 1 and res[0]["source"] == "cache"
    assert _events("plan_cache_miss") == []


def test_new_component_on_existing_entry_is_component_absent():
    plan.resolve_topk(rank=8, k=5, walk=lambda: "xla")
    obs.reset()
    plan.resolve_topk(rank=8, k=64, walk=lambda: "xla")
    miss = _events("plan_cache_miss")
    assert len(miss) == 1 and miss[0]["reason"] == "component_absent"
    entry = plan_cache.load_entry(plan.plan_key(rank=8, dtype="float32"))
    assert set(entry["components"]) == {"topk:k=5", "topk:k=64"}


def test_corrupt_entry_is_quarantined_and_reprobed_never_crashed_on():
    """The satellite's negative test: garbage in the cache file must not
    crash the resolve OR steer the plan — quarantine, miss with
    reason='corrupt', rewalk, rebank."""
    first = plan.resolve_topk(rank=8, k=5, walk=lambda: "xla")
    key = plan.plan_key(rank=8, dtype="float32")
    path = plan_cache.entry_path(key)
    with open(path, "w", encoding="utf-8") as f:
        f.write("{ this is not json")
    obs.reset()
    again = plan.resolve_topk(rank=8, k=5, walk=lambda: "xla")
    assert again == first == "xla"
    miss = _events("plan_cache_miss")
    assert len(miss) == 1 and miss[0]["reason"] == "corrupt"
    warn = _events("warning")
    assert any("quarantined" in e.get("reason", "") for e in warn)
    qdir = os.path.join(os.path.dirname(path), ".corrupt")
    assert any(n.endswith(".reason") for n in os.listdir(qdir))
    # and the entry was re-banked valid
    assert plan_cache.load_entry(key)["components"]["topk:k=5"][
        "resolved"] == "xla"


def test_schema_mismatch_entry_also_quarantines_and_reprobes():
    plan.resolve_topk(rank=8, k=5, walk=lambda: "xla")
    path = plan_cache.entry_path(plan.plan_key(rank=8, dtype="float32"))
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    doc["schema_version"] = 999              # written by a different build
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f)
    obs.reset()
    assert plan.resolve_topk(rank=8, k=5, walk=lambda: "xla") == "xla"
    assert _events("plan_cache_miss")[0]["reason"] == "corrupt"
    assert not os.path.exists(path) or \
        plan_cache.load_entry(plan.plan_key(rank=8, dtype="float32"))


def test_disarmed_resolvers_return_none_or_defaults(monkeypatch):
    monkeypatch.setenv(ENV_VAR, "off")
    assert plan.resolve_training(rank=8, compute_dtype="float32",
                                 label="x", walk=lambda: {"a": 1}) is None
    assert plan.resolve_topk(rank=8, k=5, walk=lambda: "xla") is None
    assert plan.resolve_serving_buckets() == tuple(DEFAULT_BUCKETS)
    assert _events("plan_cache_hit") == _events("plan_cache_miss") == []


# -- equivalence at every dispatch site ------------------------------------

@pytest.mark.parametrize("cfg,rank", [
    (AlsConfig(rank=8), 8),
    (AlsConfig(rank=8, cg_iters=3, cg_mode="matfree"), 8),
    (AlsConfig(rank=8, nonnegative=True), 8),
    (AlsConfig(rank=160, compute_dtype="bfloat16"), 160),
])
def test_resolve_solve_path_equivalence(monkeypatch, tmp_path, cfg, rank):
    """Warm == cold == off, per config: the planner supplies probe
    outcomes, never a different answer."""
    monkeypatch.setenv(ENV_VAR, "off")
    off = resolve_solve_path(cfg, rank)
    monkeypatch.setenv(ENV_VAR, str(tmp_path / "equiv"))
    cold = resolve_solve_path(cfg, rank)
    platform.clear_probe_caches()
    obs.reset()
    warm = resolve_solve_path(cfg, rank)
    assert off == cold == warm
    assert len(_events("plan_cache_hit")) == 1    # the warm one hit
    assert _events("plan_probe") == []


def test_topk_scores_auto_matches_planner_off(monkeypatch, tmp_path, rng):
    U = jnp.array(rng.normal(size=(6, 8)).astype(np.float32))
    V = jnp.array(rng.normal(size=(30, 8)).astype(np.float32))
    valid = jnp.ones((30,), dtype=bool)
    from tpu_als.ops.topk import auto_topk_backend, topk_scores

    assert auto_topk_backend(8, 5) == "xla"       # CPU: never pallas
    armed = topk_scores(U, V, valid, 5)
    assert len(_events("plan_resolved")) == 1     # went through the planner
    monkeypatch.setenv(ENV_VAR, "off")
    off = topk_scores(U, V, valid, 5)
    for a, b in zip(jax.tree_util.tree_leaves(armed),
                    jax.tree_util.tree_leaves(off)):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_topk_auto_under_trace_skips_planner(rng):
    """A traced call must not touch the planner's disk I/O — it walks the
    in-process caches exactly as before."""
    U = jnp.array(rng.normal(size=(6, 8)).astype(np.float32))
    V = jnp.array(rng.normal(size=(30, 8)).astype(np.float32))
    valid = jnp.ones((30,), dtype=bool)
    from tpu_als.ops.topk import topk_scores

    jax.jit(lambda u, v: topk_scores(u, v, valid, 5))(U, V)
    assert _events("plan_resolved") == []
    assert plan_cache.list_entries() == []


def test_gather_strategy_explicit_passthrough_and_model_auto():
    assert plan.resolve_gather_strategy(
        requested="ring", n_users=100, n_items=50, rank=8,
        n_devices=4) == "ring"
    assert plan_cache.list_entries() == []        # passthrough banks nothing
    choice = plan.resolve_gather_strategy(
        requested="auto", n_users=50_000, n_items=4_000, rank=64,
        n_devices=4)
    assert choice in plan.GATHER_CANDIDATES
    model = plan.gather_model(n_users=50_000, n_items=4_000, rank=64,
                              n_devices=4)
    # the verdict is ALWAYS the deterministic model's (multi-host safety)
    assert choice == model["proposal"]
    # the bank carries provenance for plan show
    key = plan.plan_key(
        rank=64, dtype="float32",
        shape_class=plan.shape_class(n_users=50_000, n_items=4_000),
        mesh_shape=(4,))
    entry = plan_cache.load_entry(key)
    assert entry["components"]["gather:D=4"]["resolved"] == choice


def test_gather_auto_identical_with_and_without_cache(monkeypatch):
    kw = dict(requested="auto", n_users=10_000, n_items=2_000, rank=32,
              n_devices=8, implicit=True)
    armed = plan.resolve_gather_strategy(**kw)
    rearmed = plan.resolve_gather_strategy(**kw)     # warm path
    monkeypatch.setenv(ENV_VAR, "off")
    off = plan.resolve_gather_strategy(**kw)
    assert armed == rearmed == off


def test_gather_auto_rejected_in_multiprocess_gate():
    from tpu_als.api.fitting import check_multiprocess_gate

    est = ALS(gatherStrategy="auto")
    with pytest.raises(ValueError, match="auto"):
        check_multiprocess_gate(est)


def test_serving_buckets_default_banked_and_requested():
    assert plan.resolve_serving_buckets(requested=[4, 16]) == (4, 16)
    assert plan.resolve_serving_buckets() == tuple(DEFAULT_BUCKETS)
    # the bucket ladder is configuration-like: a banked ladder WINS
    key = plan.plan_key(rank=0, dtype="float32")
    path = plan_cache.entry_path(key)
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    doc["components"]["serving_buckets"]["resolved"] = [4, 16, 64]
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f)
    assert plan.resolve_serving_buckets() == (4, 16, 64)


def test_serving_engine_default_buckets_come_from_planner():
    from tpu_als.serving.engine import ServingEngine

    eng = ServingEngine(k=5)
    assert tuple(eng.batcher.buckets) == tuple(DEFAULT_BUCKETS)
    assert tuple(ServingEngine(k=5, buckets=(8, 32)).batcher.buckets) \
        == (8, 32)


# -- off is free: the traced training step is byte-identical ---------------

def test_planner_off_training_step_jaxpr_byte_identical(monkeypatch,
                                                        tmp_path):
    """The ne_audit-style pin: arming the planner may change WHERE probe
    verdicts come from, never the traced graph of the step itself."""
    ucsr, icsr = _problem()
    cfg = AlsConfig(rank=4, max_iter=2)
    nU, nI = ucsr.num_rows, icsr.num_rows
    ub = jax.device_put(ucsr.device_buckets())
    ib = jax.device_put(icsr.device_buckets())
    ku, kv = jax.random.split(jax.random.PRNGKey(cfg.seed))
    U0 = init_factors(ku, nU, cfg.rank)
    V0 = init_factors(kv, nI, cfg.rank)

    monkeypatch.setenv(ENV_VAR, "off")
    step = make_step(ub, ib, nU, nI, cfg,
                     ucsr.chunk_elems, icsr.chunk_elems)
    disarmed = str(jax.make_jaxpr(step)(U0, V0))

    monkeypatch.setenv(ENV_VAR, str(tmp_path / "armed"))
    step = make_step(ub, ib, nU, nI, cfg,
                     ucsr.chunk_elems, icsr.chunk_elems)
    armed = str(jax.make_jaxpr(step)(U0, V0))
    assert disarmed == armed


# -- probe registry (satellite: five module caches, one registry) ----------

def test_probe_registry_enumerable_and_clearable_in_place():
    c = platform.probe_cache("t_reg")
    assert platform.probe_cache("t_reg") is c
    c["k"] = True
    c.meta["k"] = {"seconds": 0.1, "transient": False}
    assert "t_reg" in platform.probe_caches()
    platform.clear_probe_caches("t_reg")
    assert platform.probe_cache("t_reg") is c    # identity preserved
    assert not c and not c.meta


def test_all_pallas_modules_share_the_registry():
    from tpu_als.ops import (pallas_gather_ne, pallas_lanes,
                             pallas_lanes_blocked, pallas_solve,
                             pallas_topk)

    for mod in (pallas_gather_ne, pallas_lanes,
                pallas_lanes_blocked, pallas_solve, pallas_topk):
        cache = mod._AVAILABLE
        assert isinstance(cache, platform.ProbeCache)
        assert platform.probe_cache(cache.name) is cache
    assert platform.probe_cache("pallas_gather_ne_speed") \
        is pallas_gather_ne._FASTER
    assert platform.probe_cache("pallas_gather_solve") \
        is pallas_gather_ne._SOLVE_AVAILABLE
    assert platform.probe_cache("pallas_gather_solve_speed") \
        is pallas_gather_ne._SOLVE_FASTER


def test_probe_kernel_contract_unchanged_for_plain_dicts():
    d = {}
    assert platform.probe_kernel(d, "k", lambda: True) is False  # off-TPU
    assert d == {"k": False}                 # cached; no meta attribute


def test_probe_kernel_notes_provenance_on_registered_caches():
    c = platform.probe_cache("t_pk")
    assert platform.probe_kernel(c, ("r", 8), lambda: True) is False
    assert c.meta[("r", 8)] == {"seconds": None, "transient": False}


def test_snapshot_excludes_transient_and_seed_in_process_wins():
    c = platform.probe_cache("t_snap")
    c[("a", 1)] = True
    c.meta[("a", 1)] = {"seconds": 0.5, "transient": False}
    c["flaky"] = False
    c.meta["flaky"] = {"seconds": 1.0, "transient": True}
    snap = platform.snapshot_probes()
    assert snap["t_snap"] == {repr(("a", 1)): True}   # flaky excluded
    assert platform.probe_timings()["t_snap"] == {repr(("a", 1)): 0.5,
                                                  "'flaky'": 1.0}
    platform.clear_probe_caches("t_snap")
    c["flaky"] = True                        # this process's own verdict
    n = platform.seed_probes({"t_snap": {repr(("a", 1)): True,
                                         "'flaky'": False,
                                         "<unparseable": True}})
    assert n == 1                            # flaky kept, junk skipped
    assert c[("a", 1)] is True and c["flaky"] is True
    assert c.meta[("a", 1)]["seeded"]


# -- probe budget suggestion (bench.py consumes this jax-free) -------------

def test_suggested_probe_budget_ladder(monkeypatch, tmp_path):
    monkeypatch.setenv(ENV_VAR, "off")
    assert plan_cache.suggested_probe_budget(600) == (600.0, "planner off")
    monkeypatch.setenv(ENV_VAR, str(tmp_path / "b"))
    b, why = plan_cache.suggested_probe_budget(600)
    assert b == 600.0 and "no warm" in why
    plan.resolve_topk(rank=4, k=3, walk=lambda: "xla")   # bank one entry
    b, why = plan_cache.suggested_probe_budget(600)
    assert b == 120.0 and "warm plan entr" in why
    assert plan_cache.suggested_probe_budget(100)[0] == 100.0  # capped
    # an entry banked under another jax version is not warm
    path = plan_cache.entry_path(plan.plan_key(rank=4, dtype="float32"))
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    doc["plan_key"]["jax_version"] = "0.0.0"
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f)
    assert plan_cache.suggested_probe_budget(600)[0] == 600.0


def test_bench_resolves_probe_budget_from_the_cache(monkeypatch, tmp_path):
    sys.path.insert(0, REPO)
    import bench

    monkeypatch.setenv(ENV_VAR, str(tmp_path / "bb"))
    b, why = bench.resolve_probe_budget(None)
    assert b == bench.DEFAULT_PROBE_BUDGET_S and "no warm" in why
    assert bench.resolve_probe_budget(45) == (45.0, "explicit --probe-budget")
    plan.resolve_topk(rank=4, k=3, walk=lambda: "xla")
    b, why = bench.resolve_probe_budget(None)
    assert b == 120.0


# -- whole-plan assembly + CLI verbs ---------------------------------------

def test_resolve_execution_plan_and_summary():
    ep = plan.resolve_execution_plan(rank=8, k=5, n_users=20_000,
                                     n_items=2_000, n_devices=4)
    assert ep.solve["resolved_solve_path"]
    assert ep.topk_backend == "xla"
    assert ep.gather_strategy in plan.GATHER_CANDIDATES
    assert ep.serving_buckets == tuple(DEFAULT_BUCKETS)
    s = ep.summary()
    assert s["resolved_solve_path"] == ep.solve["resolved_solve_path"]
    assert s["probe_budget_s"] > 0
    # off: same plan, no planner involvement
    os.environ[ENV_VAR] = "off"
    try:
        ep_off = plan.resolve_execution_plan(rank=8, k=5, n_users=20_000,
                                             n_items=2_000, n_devices=4)
    finally:
        del os.environ[ENV_VAR]
    assert ep_off.solve == ep.solve
    assert ep_off.topk_backend == ep.topk_backend
    assert ep_off.gather_strategy == ep.gather_strategy
    assert ep_off.serving_buckets == ep.serving_buckets


def test_cli_plan_warm_show_clear(capsys):
    from tpu_als.cli import main as cli_main

    cli_main(["plan", "warm", "--rank", "8", "--k", "5"])
    warm = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert warm["topk_backend"] == "xla"
    assert warm["serving_buckets"] == list(DEFAULT_BUCKETS)
    assert warm["mode"] != "off"

    bad = os.path.join(plan_cache.cache_dir(), "plan_deadbeef00.json")
    with open(bad, "w", encoding="utf-8") as f:
        f.write("garbage")
    cli_main(["plan", "show"])
    show = json.loads(capsys.readouterr().out)
    assert show["mode"] == plan_cache.cache_dir()
    good = [e for e in show["entries"] if "components" in e]
    corrupt = [e for e in show["entries"] if "corrupt" in e]
    assert good and corrupt                   # both rendered, nothing raised
    assert all("banked_at" in c for e in good
               for c in e["components"].values())

    cli_main(["plan", "clear"])
    cleared = json.loads(capsys.readouterr().out)
    assert cleared["cleared_entries"] == 2
    assert plan_cache.list_entries() == []


# -- the cross-process warm-start pin --------------------------------------

def test_cross_process_warm_start_zero_probe_executions(tmp_path):
    """Process 1 resolves cold and banks; process 2 on the same plan key
    must resolve with ZERO probe executions — pinned from the obs event
    trail: plan_cache_hit present, plan_probe absent."""
    plandir = str(tmp_path / "xproc")
    env = {**os.environ, ENV_VAR: plandir, "JAX_PLATFORMS": "cpu"}
    trails = []
    for run in ("cold", "warm"):
        obs_dir = str(tmp_path / f"obs_{run}")
        p = subprocess.run(
            [sys.executable, "-m", "tpu_als.cli", "plan", "warm",
             "--rank", "8", "--k", "5", "--obs-dir", obs_dir],
            cwd=REPO, env=env, capture_output=True, text=True, timeout=300)
        assert p.returncode == 0, p.stderr
        with open(os.path.join(obs_dir, "events.jsonl"),
                  encoding="utf-8") as f:
            trails.append([json.loads(ln) for ln in f if ln.strip()])

    cold, warm = trails

    def of(trail, etype):
        return [e for e in trail if e["type"] == etype]

    assert of(cold, "plan_cache_miss") and of(cold, "plan_probe")
    assert all(e["source"] == "probe" for e in of(cold, "plan_resolved"))

    assert of(warm, "plan_cache_hit")
    assert of(warm, "plan_probe") == []       # zero probe executions
    assert of(warm, "plan_cache_miss") == []
    resolved = of(warm, "plan_resolved")
    assert resolved and all(e["source"] == "cache" for e in resolved)
    # and the two processes resolved the SAME plan
    cold_plans = {e["component"]: e["resolved"]
                  for e in of(cold, "plan_resolved")}
    warm_plans = {e["component"]: e["resolved"]
                  for e in of(warm, "plan_resolved")}
    assert cold_plans == warm_plans
