"""Ring (ppermute) gather strategy — must reproduce the all_gather result
(and hence the single-device result) to fp tolerance on the 8-device mesh.
"""

import numpy as np
import pytest

from tpu_als.core.als import AlsConfig
from tpu_als.parallel.comm import shard_csr_grid
from tpu_als.parallel.data import partition_balanced, shard_csr
from tpu_als.parallel.mesh import make_mesh
from tpu_als.parallel.trainer import stacked_counts, train_sharded

from conftest import make_ratings


def _run(cfg, strategy, u, i, r, num_users, num_items, n_dev=8):
    mesh = make_mesh(n_dev)
    upart = partition_balanced(np.bincount(u, minlength=num_users), n_dev)
    ipart = partition_balanced(np.bincount(i, minlength=num_items), n_dev)
    if strategy == "ring":
        ush = shard_csr_grid(upart, ipart, u, i, r, min_width=4)
        ish = shard_csr_grid(ipart, upart, i, u, r, min_width=4)
        pos = cfg.implicit_prefs
        counts = (stacked_counts(upart, u, r, positive_only=pos),
                  stacked_counts(ipart, i, r, positive_only=pos))
        U, V = train_sharded(mesh, upart, ipart, ush, ish, cfg,
                             strategy="ring", ring_counts=counts)
    else:
        ush = shard_csr(upart, ipart, u, i, r, min_width=4)
        ish = shard_csr(ipart, upart, i, u, r, min_width=4)
        U, V = train_sharded(mesh, upart, ipart, ush, ish, cfg)
    return np.asarray(U)[upart.slot], np.asarray(V)[ipart.slot]


@pytest.mark.parametrize("implicit", [False, True])
def test_ring_equals_all_gather(rng, implicit):
    u, i, r, _, _ = make_ratings(np.random.default_rng(2), 60, 45,
                                 rank=3, density=0.4)
    if implicit:
        r = np.abs(r) * 4 + 0.1
    cfg = AlsConfig(rank=4, max_iter=4, reg_param=0.05,
                    implicit_prefs=implicit, alpha=6.0, seed=9)
    Ug, Vg = _run(cfg, "all_gather", u, i, r, 60, 45)
    Ur, Vr = _run(cfg, "ring", u, i, r, 60, 45)
    np.testing.assert_allclose(Ur, Ug, rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(Vr, Vg, rtol=2e-3, atol=2e-3)


def test_ring_nonnegative(rng):
    u, i, r, _, _ = make_ratings(np.random.default_rng(5), 40, 30,
                                 rank=3, density=0.4)
    r = np.abs(r) + 0.1
    cfg = AlsConfig(rank=3, max_iter=3, reg_param=0.05, nonnegative=True,
                    seed=1)
    Ug, _ = _run(cfg, "all_gather", u, i, r, 40, 30)
    Ur, _ = _run(cfg, "ring", u, i, r, 40, 30)
    assert Ur.min() >= -1e-5
    np.testing.assert_allclose(Ur, Ug, rtol=5e-3, atol=5e-3)
