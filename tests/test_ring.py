"""Ring (ppermute) gather strategy — must reproduce the all_gather result
(and hence the single-device result) to fp tolerance on the 8-device mesh.
"""

import numpy as np
import pytest

from tpu_als.core.als import AlsConfig
from tpu_als.parallel.comm import shard_csr_grid
from tpu_als.parallel.data import partition_balanced, shard_csr
from tpu_als.parallel.mesh import make_mesh
from tpu_als.parallel.trainer import stacked_counts, train_sharded

from conftest import make_ratings


def _run(cfg, strategy, u, i, r, num_users, num_items, n_dev=8):
    mesh = make_mesh(n_dev)
    upart = partition_balanced(np.bincount(u, minlength=num_users), n_dev)
    ipart = partition_balanced(np.bincount(i, minlength=num_items), n_dev)
    if strategy == "ring":
        ush = shard_csr_grid(upart, ipart, u, i, r, min_width=4)
        ish = shard_csr_grid(ipart, upart, i, u, r, min_width=4)
        pos = cfg.implicit_prefs
        counts = (stacked_counts(upart, u, r, positive_only=pos),
                  stacked_counts(ipart, i, r, positive_only=pos))
        U, V = train_sharded(mesh, upart, ipart, ush, ish, cfg,
                             strategy="ring", ring_counts=counts)
    else:
        ush = shard_csr(upart, ipart, u, i, r, min_width=4)
        ish = shard_csr(ipart, upart, i, u, r, min_width=4)
        U, V = train_sharded(mesh, upart, ipart, ush, ish, cfg)
    return np.asarray(U)[upart.slot], np.asarray(V)[ipart.slot]


@pytest.mark.parametrize("implicit", [False, True])
@pytest.mark.slow
def test_ring_equals_all_gather(rng, implicit):
    u, i, r, _, _ = make_ratings(np.random.default_rng(2), 60, 45,
                                 rank=3, density=0.4)
    if implicit:
        r = np.abs(r) * 4 + 0.1
    cfg = AlsConfig(rank=4, max_iter=4, reg_param=0.05,
                    implicit_prefs=implicit, alpha=6.0, seed=9)
    Ug, Vg = _run(cfg, "all_gather", u, i, r, 60, 45)
    Ur, Vr = _run(cfg, "ring", u, i, r, 60, 45)
    np.testing.assert_allclose(Ur, Ug, rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(Vr, Vg, rtol=2e-3, atol=2e-3)


@pytest.mark.slow
def test_ring_nonnegative(rng):
    u, i, r, _, _ = make_ratings(np.random.default_rng(5), 40, 30,
                                 rank=3, density=0.4)
    r = np.abs(r) + 0.1
    cfg = AlsConfig(rank=3, max_iter=3, reg_param=0.05, nonnegative=True,
                    seed=1)
    Ug, _ = _run(cfg, "all_gather", u, i, r, 40, 30)
    Ur, _ = _run(cfg, "ring", u, i, r, 40, 30)
    assert Ur.min() >= -1e-5
    np.testing.assert_allclose(Ur, Ug, rtol=5e-3, atol=5e-3)


@pytest.mark.slow
def test_ring_multi_tile_equals_all_gather(rng):
    # tiny chunk_elems forces several row tiles per bucket — exercises the
    # fori_loop ring-pass-per-tile path (VERDICT r1 weak #1 restructure)
    u, i, r, _, _ = make_ratings(np.random.default_rng(7), 64, 48,
                                 rank=3, density=0.5)
    cfg = AlsConfig(rank=4, max_iter=3, reg_param=0.05, seed=3)
    n_dev = 8
    mesh = make_mesh(n_dev)
    upart = partition_balanced(np.bincount(u, minlength=64), n_dev)
    ipart = partition_balanced(np.bincount(i, minlength=48), n_dev)
    ush = shard_csr_grid(upart, ipart, u, i, r, min_width=4, chunk_elems=16)
    ish = shard_csr_grid(ipart, upart, i, u, r, min_width=4, chunk_elems=16)
    # prove the tiny budget actually produced multi-tile buckets
    from tpu_als.core.ratings import trainer_chunk
    assert any(b.rows.shape[1] // trainer_chunk(
        b.rows.shape[1], b.width, cfg.rank, 16) > 1 for b in ush.buckets)
    counts = (stacked_counts(upart, u, r), stacked_counts(ipart, i, r))
    Ur, Vr = train_sharded(mesh, upart, ipart, ush, ish, cfg,
                           strategy="ring", ring_counts=counts)
    ug = shard_csr(upart, ipart, u, i, r, min_width=4)
    ig = shard_csr(ipart, upart, i, u, r, min_width=4)
    Ug, Vg = train_sharded(mesh, upart, ipart, ug, ig, cfg)
    np.testing.assert_allclose(np.asarray(Ur)[upart.slot],
                               np.asarray(Ug)[upart.slot],
                               rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(np.asarray(Vr)[ipart.slot],
                               np.asarray(Vg)[ipart.slot],
                               rtol=2e-3, atol=2e-3)


def test_ring_accumulator_bound_at_target_scale():
    # the documented peak-HBM model: tile·r·max(w,r) <= 2^28 elements
    # (1 GiB f32) regardless of how many rows the shard solves — the
    # rank-256 / 1M-rows-per-shard regime of BASELINE config 3 must NOT
    # materialize a [num_rows, r, r] accumulator (~262 GB)
    from tpu_als.core.ratings import trainer_chunk

    r = 256
    for nb in (1 << 14, 1 << 17, 1 << 20):
        for w in (8, 64, 512):
            tile = trainer_chunk(nb, w, r, 1 << 19)
            assert tile * r * max(w, r) <= 1 << 28
            assert nb % tile == 0
    # and the tile count grows with nb (i.e. the tile itself is bounded):
    # a 64x bigger bucket may not grow the tile past the chunk_elems cap
    t_small = trainer_chunk(1 << 14, 64, r, 1 << 19)
    t_big = trainer_chunk(1 << 20, 64, r, 1 << 19)
    assert t_big <= max(t_small, (1 << 19) // 64)
