"""Numerical-health guardrails (docs/resilience.md): mode arming,
divergence sentinels, bounded rollback-and-retry, the disarmed
byte-identity pin, and the poisoned-input quarantine.

The disarmed pin is the load-bearing test: guardrails may not perturb
the production training step's traced graph — the ne_audit/attribution
discipline — so `--guardrails off` costs one mode check per train()
call and nothing on device.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tpu_als import ALS, ColumnarFrame, obs
from tpu_als.core.als import AlsConfig, init_factors, make_step, train
from tpu_als.core.ratings import (
    RATING_ABS_MAX,
    build_csr_buckets,
    invalid_rating_mask,
)
from tpu_als.io.stream import stream_ingest
from tpu_als.resilience import faults, guardrails
from tpu_als.resilience.guardrails import Monitor, TrainDiverged
from tpu_als.resilience.retry import RetryPolicy


@pytest.fixture(autouse=True)
def _fresh_state(monkeypatch):
    monkeypatch.delenv(guardrails.ENV_VAR, raising=False)
    guardrails.clear_mode()
    faults.clear()
    obs.reset()
    yield
    guardrails.clear_mode()
    faults.clear()
    obs.reset()


def _events(etype):
    return [e for e in obs.default_registry()._events if e["type"] == etype]


def _problem(nU=60, nI=40, nnz=800, seed=0):
    gen = np.random.default_rng(seed)
    u = gen.integers(0, nU, nnz)
    i = gen.integers(0, nI, nnz)
    r = gen.uniform(0.5, 5.0, nnz).astype(np.float32)
    ucsr = build_csr_buckets(u, i, r, nU, min_width=4, chunk_elems=1 << 12)
    icsr = build_csr_buckets(i, u, r, nI, min_width=4, chunk_elems=1 << 12)
    return ucsr, icsr


def _factors(cfg, nU, nI):
    ku, kv = jax.random.split(jax.random.PRNGKey(cfg.seed))
    return init_factors(ku, nU, cfg.rank), init_factors(kv, nI, cfg.rank)


# -- mode arming ------------------------------------------------------------

def test_mode_resolution(monkeypatch):
    assert guardrails.guardrails_mode() == "off"
    assert not guardrails.armed()
    monkeypatch.setenv(guardrails.ENV_VAR, "warn")
    assert guardrails.guardrails_mode() == "warn"
    # an explicit set_mode wins over the env
    guardrails.set_mode("recover")
    assert guardrails.guardrails_mode() == "recover"
    guardrails.clear_mode()
    assert guardrails.guardrails_mode() == "warn"


def test_garbage_modes_raise(monkeypatch):
    with pytest.raises(ValueError, match="unknown guardrails mode"):
        guardrails.set_mode("loud")
    monkeypatch.setenv(guardrails.ENV_VAR, "recove")
    with pytest.raises(ValueError, match=guardrails.ENV_VAR):
        guardrails.guardrails_mode()


def test_scoped_restores_on_exit():
    with guardrails.scoped("warn"):
        assert guardrails.guardrails_mode() == "warn"
        with guardrails.scoped("recover"):
            assert guardrails.guardrails_mode() == "recover"
        assert guardrails.guardrails_mode() == "warn"
    assert guardrails.guardrails_mode() == "off"


# -- sentinels --------------------------------------------------------------

def test_health_stats_values(rng):
    U = rng.normal(size=(7, 4)).astype(np.float32)
    V = rng.normal(size=(5, 4)).astype(np.float32)
    s = np.asarray(guardrails.health_stats(jnp.array(U), jnp.array(V)))
    assert s[0] == 1.0
    np.testing.assert_allclose(
        s[1], np.sqrt((U * U).sum(1).max()), rtol=1e-5)
    np.testing.assert_allclose(
        s[2], np.sqrt((V * V).sum(1).max()), rtol=1e-5)
    np.testing.assert_allclose(
        s[3], np.sqrt((U * U).sum() + (V * V).sum()), rtol=1e-5)
    U[3, 1] = np.nan
    s = np.asarray(guardrails.health_stats(jnp.array(U), jnp.array(V)))
    assert s[0] == 0.0


def test_judge_trips_each_sentinel(rng):
    cfg = AlsConfig(rank=4)
    mon = Monitor(cfg, "warn")
    U = jnp.array(rng.normal(size=(6, 4)).astype(np.float32))
    V = jnp.array(rng.normal(size=(5, 4)).astype(np.float32))
    assert mon.judge(1, U, V) is None          # healthy baseline
    assert mon.judge(2, U * jnp.nan, V) == "nonfinite"
    assert mon.judge(3, U.at[0].set(1e5), V) == "norm_band"
    # trend: large global-norm jump but every row inside the band
    assert mon.judge(4, U * 300.0, V * 300.0) == "trend"
    evs = _events("guardrail_tripped")
    assert [e["sentinel"] for e in evs] == ["nonfinite", "norm_band",
                                            "trend"]
    assert all(e["mode"] == "warn" for e in evs)


def test_judge_trend_baseline_only_advances_when_healthy(rng):
    cfg = AlsConfig(rank=4)
    mon = Monitor(cfg, "warn")
    U = jnp.array(rng.normal(size=(6, 4)).astype(np.float32))
    V = jnp.array(rng.normal(size=(5, 4)).astype(np.float32))
    assert mon.judge(1, U, V) is None
    base = mon._prev_fro
    assert mon.judge(2, U * jnp.nan, V) == "nonfinite"
    assert mon._prev_fro == base               # tripped -> baseline frozen
    assert mon.judge(3, U * 2.0, V * 2.0) is None
    assert mon._prev_fro > base


# -- rollback ---------------------------------------------------------------

def test_rollback_perturbs_snapshot_and_bumps_reg(rng):
    cfg = AlsConfig(rank=4, seed=3, reg_param=0.1)
    mon = Monitor(cfg, "recover")
    U = jnp.array(rng.normal(size=(6, 4)).astype(np.float32))
    V = jnp.array(rng.normal(size=(5, 4)).astype(np.float32))
    mon.keep_last_good(U, V)
    U2, V2, scale = mon.rollback(2, "nonfinite")
    assert scale == guardrails.REG_BUMP_FACTOR
    # perturbed, but still within PERTURB_SCALE noise of the snapshot
    assert not np.array_equal(np.asarray(U2), np.asarray(U))
    np.testing.assert_allclose(np.asarray(U2), np.asarray(U), atol=1e-2)
    np.testing.assert_allclose(np.asarray(V2), np.asarray(V), atol=1e-2)
    assert obs.counter_value("train.rollbacks") == 1
    ev = _events("train_rollback")[0]
    assert ev["attempt"] == 1 and ev["sentinel"] == "nonfinite"
    np.testing.assert_allclose(ev["reg_param"],
                               0.1 * guardrails.REG_BUMP_FACTOR)


def test_rollback_is_deterministic(rng):
    U = jnp.array(rng.normal(size=(6, 4)).astype(np.float32))
    V = jnp.array(rng.normal(size=(5, 4)).astype(np.float32))
    outs = []
    for _ in range(2):
        mon = Monitor(AlsConfig(rank=4, seed=3), "recover")
        mon.keep_last_good(U, V)
        U2, _, _ = mon.rollback(2, "trend")
        outs.append(np.asarray(U2))
    np.testing.assert_array_equal(outs[0], outs[1])


def test_rollback_budget_exhaustion_raises_typed(rng):
    mon = Monitor(AlsConfig(rank=4), "recover",
                  policy=RetryPolicy(max_attempts=1, base_delay=0.0,
                                     jitter=0.0))
    U = jnp.array(rng.normal(size=(6, 4)).astype(np.float32))
    V = jnp.array(rng.normal(size=(5, 4)).astype(np.float32))
    mon.keep_last_good(U, V)
    mon.rollback(2, "nonfinite")
    with pytest.raises(TrainDiverged) as ei:
        mon.rollback(2, "nonfinite")
    assert ei.value.rollbacks == 1 and ei.value.sentinel == "nonfinite"


def test_rollback_without_snapshot_raises(rng):
    mon = Monitor(AlsConfig(rank=4), "recover")
    with pytest.raises(TrainDiverged):
        mon.rollback(1, "nonfinite")


def test_retry_does_not_overwrite_snapshot(rng):
    mon = Monitor(AlsConfig(rank=4), "recover")
    U = jnp.array(rng.normal(size=(6, 4)).astype(np.float32))
    V = jnp.array(rng.normal(size=(5, 4)).astype(np.float32))
    mon.keep_last_good(U, V)
    mon.keep_last_good(U * jnp.nan, V, retry=True)
    assert np.all(np.isfinite(np.asarray(mon._snap[0])))


# -- disarmed: the production path is untouched -----------------------------

def test_disarmed_step_jaxpr_is_byte_identical():
    """Arming state must not leak into the production step's traced
    graph: the sentinels are a separate jitted reduction consulted at
    the host-side iteration boundary, never woven into _step_jit."""
    ucsr, icsr = _problem()
    cfg = AlsConfig(rank=4, max_iter=2)
    nU, nI = ucsr.num_rows, icsr.num_rows
    ub = jax.device_put(ucsr.device_buckets())
    ib = jax.device_put(icsr.device_buckets())
    step = make_step(ub, ib, nU, nI, cfg,
                     ucsr.chunk_elems, icsr.chunk_elems)
    U0, V0 = _factors(cfg, nU, nI)
    disarmed = str(jax.make_jaxpr(step)(U0, V0))
    with guardrails.scoped("recover"):
        armed = str(jax.make_jaxpr(step)(U0, V0))
    assert disarmed == armed


def test_warn_mode_factors_bitwise_match_disarmed():
    ucsr, icsr = _problem()
    cfg = AlsConfig(rank=4, max_iter=3)
    U_off, V_off = train(ucsr, icsr, cfg)
    with guardrails.scoped("warn"):
        U_w, V_w = train(ucsr, icsr, cfg)
    assert np.array_equal(np.asarray(U_off), np.asarray(U_w))
    assert np.array_equal(np.asarray(V_off), np.asarray(V_w))
    assert not _events("guardrail_tripped")    # healthy fit: no noise


# -- end-to-end recovery from an injected mid-train NaN ---------------------

def test_recover_mode_rolls_back_injected_nan():
    ucsr, icsr = _problem(nU=80, nI=60, nnz=1500)
    cfg = AlsConfig(rank=4, max_iter=4, reg_param=0.1)
    faults.install("solve.gram=corrupt@nth=2")
    with guardrails.scoped("recover"):
        U, V = train(ucsr, icsr, cfg)
    assert np.all(np.isfinite(np.asarray(U)))
    assert np.all(np.isfinite(np.asarray(V)))
    assert obs.counter_value("train.rollbacks") == 1
    assert [e["sentinel"] for e in _events("guardrail_tripped")] \
        == ["nonfinite"]
    assert _events("train_rollback")[0]["iteration"] == 2


def test_warn_mode_emits_but_never_rolls_back():
    ucsr, icsr = _problem()
    cfg = AlsConfig(rank=4, max_iter=3)
    faults.install("solve.gram=corrupt@nth=2")
    with guardrails.scoped("warn"):
        train(ucsr, icsr, cfg)
    assert _events("guardrail_tripped")
    assert obs.counter_value("train.rollbacks") == 0
    assert not _events("train_rollback")


def test_recover_mode_raises_train_diverged_when_budget_spent():
    # the fault fires on EVERY iteration: each retry re-trips until the
    # rollback budget is gone, then the typed error surfaces
    ucsr, icsr = _problem()
    cfg = AlsConfig(rank=4, max_iter=4)
    faults.install("solve.gram=corrupt@every=1")
    with guardrails.scoped("recover"):
        with pytest.raises(TrainDiverged):
            train(ucsr, icsr, cfg)


# -- poisoned-input quarantine ----------------------------------------------

def test_invalid_rating_mask():
    r = np.array([1.0, np.nan, np.inf, -np.inf, RATING_ABS_MAX,
                  RATING_ABS_MAX * 2, -RATING_ABS_MAX * 2],
                 dtype=np.float32)
    np.testing.assert_array_equal(
        invalid_rating_mask(r),
        [False, True, True, True, False, True, True])


def test_stream_quarantine_catches_every_bad_class(tmp_path):
    lines = ["u0,i0,1.0", "u1,i1,2.0", "badline", "u2,i2,nan",
             "u3,i3,1e40", "u4,i4,1e9", "u5,i5,3.0"]
    p = tmp_path / "r.csv"
    p.write_text("\n".join(lines) + "\n")
    u, i, r, ul, il = stream_ingest(str(p), quarantine=True)
    # exactly the clean rows survive, in order (labels may retain an
    # interned entry for a post-parse-scrubbed row; the ROWS are gone)
    np.testing.assert_allclose(r, [1.0, 2.0, 3.0])
    assert [ul[k].decode() for k in u] == ["u0", "u1", "u5"]
    assert [il[k].decode() for k in i] == ["i0", "i1", "i5"]
    assert obs.counter_value("ingest.quarantined_rows") == 4
    ev = _events("ingest_quarantined")[0]
    # the strict native parser rejects 'nan'/'1e40' as malformed text;
    # the huge-but-finite 1e9 parses and is scrubbed post-parse
    assert ev["rows"] == 4
    assert ev["reasons"]["malformed"] == 3
    assert ev["reasons"]["out_of_range"] == 1
    sink = (p.parent / "r.csv.quarantine" / "host0.bad").read_text()
    for bad in ("badline", "u2,i2,nan", "u3,i3,1e40"):
        assert bad in sink


def test_stream_without_quarantine_still_raises(tmp_path):
    p = tmp_path / "r.csv"
    p.write_text("u0,i0,1.0\nbadline\n")
    with pytest.raises(ValueError):
        stream_ingest(str(p))
    p.write_text("u0,i0,1.0\nu1,i1,2.0\n")
    u, i, r, ul, il = stream_ingest(str(p))
    assert obs.counter_value("ingest.quarantined_rows") == 0
    assert not _events("ingest_quarantined")


def test_estimator_armed_scrubs_poisoned_ratings(rng):
    n = 200
    u = rng.integers(0, 30, n)
    i = rng.integers(0, 20, n)
    r = rng.uniform(1.0, 5.0, n).astype(np.float32)
    r[7] = np.nan
    r[13] = 1e9
    frame = ColumnarFrame({"user": u, "item": i, "rating": r})
    als = ALS(rank=4, maxIter=2, guardrails="warn")
    model = als.fit(frame)
    uf = np.stack([np.asarray(f) for f in model.userFactors["features"]])
    assert np.all(np.isfinite(uf))
    assert obs.counter_value("ingest.quarantined_rows") == 2
    ev = _events("ingest_quarantined")[0]
    assert ev["path"] == "<api>" and ev["rows"] == 2
    assert ev["reasons"]["nonfinite"] == 1
    assert ev["reasons"]["out_of_range"] == 1


def test_estimator_disarmed_rejects_poisoned_ratings(rng):
    n = 50
    u = rng.integers(0, 10, n)
    i = rng.integers(0, 8, n)
    r = rng.uniform(1.0, 5.0, n).astype(np.float32)
    r[3] = np.nan
    frame = ColumnarFrame({"user": u, "item": i, "rating": r})
    with pytest.raises(ValueError, match="non-finite"):
        ALS(rank=4, maxIter=2).fit(frame)


def test_estimator_rejects_unknown_guardrails_mode():
    with pytest.raises(ValueError, match="unknown guardrails mode"):
        ALS(guardrails="loud")
