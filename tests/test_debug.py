"""Numerical-safety tooling tests (SURVEY.md §5.2)."""

import numpy as np
import jax.numpy as jnp
import pytest
from jax.experimental import checkify

from tpu_als.utils.debug import (
    assert_all_finite, checked_predict, debug_mode)


def test_checked_predict_ok(rng):
    U = jnp.asarray(rng.normal(size=(10, 4)).astype(np.float32))
    V = jnp.asarray(rng.normal(size=(8, 4)).astype(np.float32))
    out = checked_predict(U, V, np.array([0, 9]), np.array([7, 3]))
    expect = (np.asarray(U)[[0, 9]] * np.asarray(V)[[7, 3]]).sum(1)
    np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-5)


def test_checked_predict_catches_out_of_range(rng):
    U = jnp.asarray(rng.normal(size=(10, 4)).astype(np.float32))
    V = jnp.asarray(rng.normal(size=(8, 4)).astype(np.float32))
    with pytest.raises(checkify.JaxRuntimeError, match="user index"):
        checked_predict(U, V, np.array([10]), np.array([0]))
    with pytest.raises(checkify.JaxRuntimeError, match="negative item"):
        checked_predict(U, V, np.array([0]), np.array([-1]))


def test_debug_mode_raises_on_nan():
    with pytest.raises(FloatingPointError):
        with debug_mode():
            jnp.log(jnp.zeros(3) - 1.0).block_until_ready()


def test_debug_mode_restores_config():
    import jax

    before = jax.config.jax_debug_nans
    with debug_mode():
        pass
    assert jax.config.jax_debug_nans == before


def test_assert_all_finite():
    ok = np.ones((3, 2), np.float32)
    assert_all_finite(1, ok, ok)
    bad = ok.copy()
    bad[1, 1] = np.nan
    with pytest.raises(FloatingPointError, match="iteration 7"):
        assert_all_finite(7, ok, bad)
