"""Online serving subsystem tests (tpu_als/serving/).

Three layers: the int8 candidate index's bitwise-equality contract
against the exact kernel (property sweep over shapes, validity masks,
and adversarial duplicate-score inputs), the micro-batching admission
queue (bucketing, shedding, deadlines), and the engine loop
(publish/swap, stale-index fallback, fault points, the serve-bench
CLI).
"""

import json
import time

import numpy as np
import pytest

import jax.numpy as jnp

from tpu_als import obs
from tpu_als.ops.topk import NEG_INF, chunked_topk_scores, topk_validity
from tpu_als.resilience import faults
from tpu_als.resilience.faults import InjectedFault
from tpu_als.serving import (
    DeadlineExceeded,
    Int8CandidateIndex,
    MicroBatcher,
    NoModelPublished,
    Overloaded,
    ServingEngine,
    bucket_for,
)


@pytest.fixture(autouse=True)
def _fresh():
    """Disarmed faults + a fresh metrics registry per test (counters
    are asserted exactly)."""
    faults.clear()
    reg = obs.reset()
    yield reg
    faults.clear()


def _exact(U, V, valid, k):
    s, ix = chunked_topk_scores(jnp.asarray(U), jnp.asarray(V),
                                jnp.asarray(valid), k)
    return np.asarray(s), np.asarray(ix)


def _assert_matches_exact(s, ix, ref_s, ref_ix):
    """The index contract: scores bitwise equal; indices equal on rows
    whose scores are unique (ties may legitimately resolve differently);
    on tied rows every returned index must still earn its score."""
    s, ix = np.asarray(s), np.asarray(ix)
    np.testing.assert_array_equal(s, ref_s)
    for row in range(s.shape[0]):
        real = topk_validity(s[row])
        if len(np.unique(s[row][real])) == real.sum():
            np.testing.assert_array_equal(ix[row][real],
                                          ref_ix[row][real])


# ---------------------------------------------------------------------------
# int8 index + exact rescore == exact kernel (the acceptance property)


@pytest.mark.parametrize("n,Ni,r,k,sk,seed", [
    (1, 50, 4, 5, 20, 0),
    (13, 257, 24, 10, 40, 1),
    (33, 1000, 64, 10, 64, 2),
    (8, 96, 8, 8, 96, 3),       # shortlist == catalog: unconditional
    (5, 7, 3, 7, 7, 4),         # k == catalog size
])
def test_int8_rescore_matches_exact_random(n, Ni, r, k, sk, seed):
    rng = np.random.default_rng(seed)
    U = rng.normal(size=(n, r)).astype(np.float32)
    V = rng.normal(size=(Ni, r)).astype(np.float32)
    valid = np.ones(Ni, bool)
    idx = Int8CandidateIndex(V, valid, shortlist_k=sk)
    s, ix = idx.topk(U, k)
    _assert_matches_exact(s, ix, *_exact(U, V, valid, k))


@pytest.mark.parametrize("seed", range(4))
def test_int8_rescore_matches_exact_duplicate_scores(seed):
    # adversarial ties: the catalog is a few distinct rows repeated, so
    # exact scores collide in whole groups; duplicates quantize
    # identically, so the shortlist keeps enough of each group and the
    # returned SCORES (with multiplicity) must still match bitwise
    rng = np.random.default_rng(100 + seed)
    base = rng.normal(size=(6, 8)).astype(np.float32)
    V = base[rng.integers(0, 6, 120)]
    U = np.concatenate([rng.normal(size=(5, 8)), base[:3]]).astype(
        np.float32)
    valid = np.ones(120, bool)
    idx = Int8CandidateIndex(V, valid, shortlist_k=60)
    k = 12
    s, ix = idx.topk(U, k)
    ref_s, ref_ix = _exact(U, V, valid, k)
    np.testing.assert_array_equal(np.asarray(s), ref_s)
    # tied indices may differ, but each must earn its claimed score
    full = U.astype(np.float64) @ V.astype(np.float64).T
    np.testing.assert_allclose(
        np.take_along_axis(full, np.asarray(ix), axis=1), ref_s,
        rtol=1e-5, atol=1e-5)


def test_int8_rescore_sparse_validity(rng):
    U = rng.normal(size=(9, 16)).astype(np.float32)
    V = rng.normal(size=(200, 16)).astype(np.float32)
    valid = rng.random(200) < 0.3
    idx = Int8CandidateIndex(V, valid, shortlist_k=48)
    s, ix = idx.topk(U, 8)
    _assert_matches_exact(s, ix, *_exact(U, V, valid, 8))
    assert valid[np.asarray(ix)[topk_validity(np.asarray(s))]].all()


def test_int8_fewer_valid_than_k_leaves_sentinels(rng):
    U = rng.normal(size=(4, 8)).astype(np.float32)
    V = rng.normal(size=(50, 8)).astype(np.float32)
    valid = np.zeros(50, bool)
    valid[[7, 21, 40]] = True
    idx = Int8CandidateIndex(V, valid, shortlist_k=10)
    s, ix = idx.topk(U, 5)
    ref_s, _ = _exact(U, V, valid, 5)
    s = np.asarray(s)
    np.testing.assert_array_equal(s, ref_s)        # incl. the sentinels
    mask = topk_validity(s)
    np.testing.assert_array_equal(mask, np.tile([True] * 3 + [False] * 2,
                                                (4, 1)))
    assert np.isin(np.asarray(ix)[mask], [7, 21, 40]).all()


def test_int8_all_invalid_catalog(rng):
    U = rng.normal(size=(3, 4)).astype(np.float32)
    V = rng.normal(size=(20, 4)).astype(np.float32)
    idx = Int8CandidateIndex(V, np.zeros(20, bool), shortlist_k=8)
    s, _ = idx.topk(U, 4)
    assert not topk_validity(np.asarray(s)).any()
    np.testing.assert_array_equal(np.asarray(s),
                                  np.full((3, 4), NEG_INF, np.float32))


def test_int8_index_guards():
    with pytest.raises(ValueError, match="empty catalog"):
        Int8CandidateIndex(np.zeros((0, 4), np.float32))
    idx = Int8CandidateIndex(np.ones((10, 4), np.float32), shortlist_k=4)
    with pytest.raises(ValueError, match="exceeds shortlist_k"):
        idx.topk(np.ones((2, 4), np.float32), 6)
    # shortlist is capped by the catalog
    assert Int8CandidateIndex(np.ones((5, 4), np.float32),
                              shortlist_k=64).shortlist_k == 5


# ---------------------------------------------------------------------------
# admission queue


def test_bucket_for():
    assert bucket_for(1, (8, 32, 128)) == 8
    assert bucket_for(8, (8, 32, 128)) == 8
    assert bucket_for(9, (8, 32, 128)) == 32
    assert bucket_for(128, (8, 32, 128)) == 128
    with pytest.raises(ValueError, match="largest bucket"):
        bucket_for(129, (8, 32, 128))


def test_batcher_coalesces_and_stamps(_fresh):
    b = MicroBatcher(buckets=(4, 8), max_wait_s=0.01)
    tickets = [b.submit(i) for i in range(3)]
    batch = b.next_batch(timeout=1.0)
    assert [t.payload for t in batch] == [0, 1, 2]
    assert all(t.t_dequeue is not None for t in batch)
    assert b.depth() == 0
    assert _fresh.histogram_count("serving.enqueue_seconds") == 3
    assert tickets[0] is batch[0]


def test_batcher_caps_dequeue_at_largest_bucket():
    b = MicroBatcher(buckets=(2, 4), max_wait_s=0.0)
    for i in range(6):
        b.submit(i)
    assert len(b.next_batch(timeout=1.0)) == 4
    assert len(b.next_batch(timeout=1.0)) == 2


def test_batcher_sheds_when_full(_fresh):
    b = MicroBatcher(buckets=(8,), max_queue=2, max_wait_s=0.0)
    b.submit(0)
    b.submit(1)
    with pytest.raises(Overloaded):
        b.submit(2)
    assert _fresh.snapshot()["counters"]["serving.shed"] == 1


def test_batcher_timeout_returns_none():
    b = MicroBatcher(max_wait_s=0.0)
    assert b.next_batch(timeout=0.01) is None


def test_batcher_close_drains_then_stops():
    b = MicroBatcher(buckets=(8,), max_wait_s=0.0)
    b.submit(0)
    b.close()
    assert len(b.next_batch(timeout=0.1)) == 1
    assert b.next_batch(timeout=0.1) is None
    with pytest.raises(RuntimeError, match="closed"):
        b.submit(1)


def test_batcher_rejects_bad_buckets():
    with pytest.raises(ValueError, match="sorted and unique"):
        MicroBatcher(buckets=(32, 8))


# ---------------------------------------------------------------------------
# engine


def _engine(rng, n=40, Ni=300, r=8, k=5, quantize=True, **kw):
    eng = ServingEngine(k=k, buckets=(8, 32), shortlist_k=32,
                        max_wait_s=0.0, **kw)
    U = rng.normal(size=(n, r)).astype(np.float32)
    V = rng.normal(size=(Ni, r)).astype(np.float32)
    eng.publish(U, V, quantize=quantize)
    return eng, U, V


def _drain_one(eng):
    """Pump one batch through the engine synchronously (no thread)."""
    batch = eng.batcher.next_batch(timeout=1.0)
    assert batch is not None
    eng.serve_batch(batch)
    return batch


@pytest.mark.parametrize("quantize", [True, False])
def test_engine_roundtrip_ids_and_foldin_rows(rng, quantize):
    eng, U, V = _engine(rng, quantize=quantize)
    valid = np.ones(V.shape[0], bool)
    t_id = eng.submit(7)
    t_row = eng.submit(U[3] * 0.5)       # a fold-in vector payload
    _drain_one(eng)
    queries = np.stack([U[7], U[3] * 0.5])
    ref_s, ref_ix = _exact(queries, V, valid, eng.k)
    for j, t in enumerate([t_id, t_row]):
        s, ix = t.result(timeout=1.0)
        np.testing.assert_allclose(s, ref_s[j], rtol=1e-5, atol=1e-6)
        np.testing.assert_array_equal(ix, ref_ix[j])


def test_engine_threaded_recommend(rng, _fresh):
    eng, U, V = _engine(rng)
    with eng:
        s, ix = eng.recommend(11, timeout=5.0)
    assert s.shape == (5,) and ix.shape == (5,)
    ref_s, _ = _exact(U[11:12], V, np.ones(V.shape[0], bool), 5)
    np.testing.assert_allclose(s, ref_s[0], rtol=1e-5, atol=1e-6)
    snap = _fresh.snapshot()
    assert snap["counters"]["serving.requests"] == 1
    assert snap["histograms"]["serving.e2e_seconds"]["count"] == 1
    assert snap["histograms"]['serving.score_seconds{path="int8"}'][
        "count"] == 1


def test_engine_per_request_k_trims(rng):
    eng, _, _ = _engine(rng, k=8)
    t = eng.submit(0, k=3)
    _drain_one(eng)
    s, ix = t.result(timeout=1.0)
    assert s.shape == (3,) and ix.shape == (3,)


def test_engine_submit_guards(rng):
    eng = ServingEngine(k=5)
    with pytest.raises(NoModelPublished):
        eng.submit(0)
    eng.publish(np.ones((4, 6), np.float32), np.ones((9, 6), np.float32))
    with pytest.raises(ValueError, match="outside the published table"):
        eng.submit(4)
    with pytest.raises(ValueError, match="payload shape"):
        eng.submit(np.ones(5, np.float32))
    with pytest.raises(ValueError, match="per-request k"):
        eng.submit(0, k=6)


def test_engine_deadline_expires_in_queue(rng, _fresh):
    eng, _, _ = _engine(rng)
    t = eng.submit(0, deadline_s=0.0)
    time.sleep(0.01)
    _drain_one(eng)
    with pytest.raises(DeadlineExceeded):
        t.result(timeout=1.0)
    assert _fresh.snapshot()["counters"]["serving.expired"] == 1


def test_engine_publish_swaps_atomically(rng, _fresh):
    eng, U, V = _engine(rng)
    t1 = eng.submit(0)
    _drain_one(eng)
    V2 = V * -1.0                        # same shape: no recompile path
    assert eng.publish(U, V2) == 2
    t2 = eng.submit(0)
    _drain_one(eng)
    s1, _ = t1.result(timeout=1.0)
    s2, _ = t2.result(timeout=1.0)
    ref2, _ = _exact(U[:1], V2, np.ones(V.shape[0], bool), eng.k)
    np.testing.assert_allclose(s2, ref2[0], rtol=1e-5, atol=1e-6)
    assert not np.allclose(s1, s2)
    snap = _fresh.snapshot()
    assert snap["counters"]["serving.publishes"] == 2
    seqs = [e["seq"] for e in _fresh._events
            if e["type"] == "serving_publish"]
    assert seqs == [1, 2]


def test_engine_stale_index_falls_back_to_exact(rng, _fresh):
    eng, U, V = _engine(rng, quantize=True)
    V2 = rng.normal(size=V.shape).astype(np.float32)
    eng.publish(U, V2, quantize=False)   # index carried but stale
    t = eng.submit(2)
    _drain_one(eng)
    s, ix = t.result(timeout=1.0)
    # served the NEW catalog on the exact path, not the stale index
    ref_s, ref_ix = _exact(U[2:3], V2, np.ones(V.shape[0], bool), eng.k)
    np.testing.assert_allclose(s, ref_s[0], rtol=1e-5, atol=1e-6)
    snap = _fresh.snapshot()
    assert snap["counters"]["serving.fallback_exact"] == 1
    assert snap["histograms"]['serving.score_seconds{path="exact"}'][
        "count"] == 1


def test_engine_publish_corrupt_fault_first_publish_goes_indexless(
        rng, _fresh):
    """A torn FIRST publish has no prior generation to carry: the
    publish goes out with ``index=None`` (never an in-place mutation of
    a live index), requests take the exact path directly — no stale
    index exists, so nothing counts as a fallback."""
    faults.install("serving.publish=corrupt@nth=1")
    eng, U, V = _engine(rng, quantize=True)
    assert eng.published_index is None
    t = eng.submit(1)
    _drain_one(eng)
    s, _ = t.result(timeout=1.0)
    ref_s, _ = _exact(U[1:2], V, np.ones(V.shape[0], bool), eng.k)
    np.testing.assert_allclose(s, ref_s[0], rtol=1e-5, atol=1e-6)
    assert "serving.fallback_exact" not in _fresh.snapshot()["counters"]
    pub = [e for e in _fresh._events if e["type"] == "serving_publish"]
    assert pub and pub[-1]["quantized"] is False


def test_engine_publish_corrupt_fault_carries_stale_index(rng, _fresh):
    """A torn publish AFTER a healthy one carries the previous
    generation's index untouched — stale by seq, detected on the score
    path, counted as an exact fallback.  The prior generation's index
    object itself must stay intact (the old in-place ``seq = -1``
    corruption poisoned it for any still-serving reader)."""
    eng, U, V = _engine(rng, quantize=True)
    first = eng.published_index
    first_seq = first.seq
    faults.install("serving.publish=corrupt@nth=1")
    eng.publish(U, V, quantize=True)
    assert eng.published_index is first          # carried, not rebuilt
    assert first.seq == first_seq                # and NOT mutated
    t = eng.submit(1)
    _drain_one(eng)
    s, _ = t.result(timeout=1.0)
    ref_s, _ = _exact(U[1:2], V, np.ones(V.shape[0], bool), eng.k)
    np.testing.assert_allclose(s, ref_s[0], rtol=1e-5, atol=1e-6)
    assert _fresh.snapshot()["counters"]["serving.fallback_exact"] == 1


def test_engine_score_corrupt_fault_forces_exact(rng, _fresh):
    eng, U, V = _engine(rng, quantize=True)
    faults.install("serving.score=corrupt@nth=1")
    t = eng.submit(1)
    _drain_one(eng)
    t.result(timeout=1.0)
    assert _fresh.snapshot()["counters"]["serving.fallback_exact"] == 1


def test_engine_score_raise_fault_fails_waiting_callers(rng):
    eng, _, _ = _engine(rng)
    faults.install("serving.score=raise@nth=1")
    with eng:
        t = eng.submit(0)
        with pytest.raises(InjectedFault):
            t.result(timeout=5.0)
        # the loop survives the fault: the next request is served
        s, _ = eng.recommend(1, timeout=5.0)
    assert s.shape == (5,)


def test_engine_warmup_records_no_latency_samples(rng, _fresh):
    eng, _, _ = _engine(rng)
    eng.warmup()
    snap = _fresh.snapshot()
    assert "serving.score_seconds" not in str(snap["histograms"])
    assert snap["histograms"].get("serving.e2e_seconds") is None


def test_engine_small_catalog_skips_index(rng):
    eng = ServingEngine(k=10, buckets=(8,), max_wait_s=0.0)
    eng.publish(rng.normal(size=(4, 3)).astype(np.float32),
                rng.normal(size=(6, 3)).astype(np.float32))
    # catalog (6) < k (10): exact path, sentinel-padded like the kernel
    t = eng.submit(0)
    _drain_one(eng)
    s, _ = t.result(timeout=1.0)
    assert topk_validity(s).sum() == 6


# ---------------------------------------------------------------------------
# serve-bench CLI (the SLO report the acceptance criteria name)


def test_serve_bench_cli_reports_from_histograms(tmp_path, capsys):
    from tpu_als.cli import main

    bank = tmp_path / "BENCH_serve_test.json"
    main(["serve-bench", "--users", "300", "--items", "800",
          "--rank", "8", "--k", "5", "--shortlist-k", "32",
          "--qps", "400", "--duration", "0.25", "--slo-ms", "5000",
          "--foldin-frac", "0.2", "--buckets", "8,32",
          "--bench-json", str(bank)])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["metric"] == "serve_e2e_p99_ms"
    assert out["value"] > 0 and out["p50_ms"] > 0
    assert out["scored"] > 0
    assert out["slo_met"] is True        # 5s SLO on a toy config
    assert 0.0 <= out["shed_rate"] <= 1.0
    banked = json.loads(bank.read_text())
    assert banked["banked_by"] == "tpu_als serve-bench"
    assert banked["banked_at"].endswith("+00:00")
    assert banked["value"] == out["value"]


def test_serve_bench_cli_exact_path(capsys):
    from tpu_als.cli import main

    main(["serve-bench", "--users", "100", "--items", "200",
          "--rank", "4", "--qps", "300", "--duration", "0.1",
          "--slo-ms", "5000", "--exact", "--buckets", "8"])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["config"]["path"] == "exact"
    assert out["scored"] > 0


# ---------------------------------------------------------------------------
# flight recorder (per-request span breakdowns dumped on SLO breach)


def test_flight_recorder_ring_and_watermark(_fresh):
    from tpu_als.obs.trace import SPAN_KEYS, FlightRecorder

    fr = FlightRecorder(capacity=4)
    for i in range(6):
        fr.record("ok", {"score": 0.001 * (i + 1)}, e2e_seconds=0.01)
    assert len(fr) == 4                       # bounded ring
    assert fr.dump("slo_breach") == 4
    evs = [e for e in _fresh._events if e["type"] == "flight_record"]
    # capacity evicted seqs 1-2; unknown span keys are dropped, the
    # record always carries the full SPAN_KEYS vocabulary
    assert [e["seq"] for e in evs] == [3, 4, 5, 6]
    assert all(set(e["spans"]) == set(SPAN_KEYS) for e in evs)
    assert all(e["trigger"] == "slo_breach" for e in evs)
    # monotonic watermark: a repeat trigger re-emits nothing
    assert fr.dump("slo_breach") == 0
    fr.record("ok", {"score": 1.0})
    assert fr.dump("shed") == 1               # only the new record
    evs = [e for e in _fresh._events if e["type"] == "flight_record"]
    assert len(evs) == 5 and evs[-1]["trigger"] == "shed"


def test_engine_slo_breach_dumps_span_breakdowns(rng, _fresh):
    """The acceptance shape: a forced breach (microsecond SLO) leaves
    the last N per-request traces in the obs trail, each with the full
    admission/queue_wait/score/respond breakdown."""
    eng, _, _ = _engine(rng, slo_s=1e-7)
    n = 10
    with eng:
        for j in range(n):
            eng.recommend(j, timeout=5.0)
    evs = [e for e in _fresh._events if e["type"] == "flight_record"]
    assert len(evs) >= 8
    for e in evs:
        assert e["trigger"] == "slo_breach" and e["status"] == "ok"
        for k in ("admission", "queue_wait", "score", "respond"):
            assert e["spans"][k] is not None and e["spans"][k] >= 0
        # rescore is fused into the int8 top-k kernel: recorded None
        assert e["spans"]["rescore"] is None
        assert e["e2e_seconds"] > 0 and e["path"] == "int8"
    # and the spans roughly compose the e2e they explain
    spans = evs[-1]["spans"]
    parts = sum(v for v in spans.values() if v is not None)
    assert parts <= evs[-1]["e2e_seconds"] * 1.5


def test_engine_loose_slo_dumps_nothing(rng, _fresh):
    eng, _, _ = _engine(rng, slo_s=60.0)
    with eng:
        eng.recommend(0, timeout=5.0)
    assert not [e for e in _fresh._events if e["type"] == "flight_record"]
    # recording is still always-on: the trace sits in the ring, undumped
    assert len(eng.flight) == 1


def test_engine_shed_dumps_flight_record(rng, _fresh):
    eng, _, _ = _engine(rng, max_queue=2)
    with pytest.raises(Overloaded):
        for _ in range(50):                   # engine loop not running
            eng.submit(0)
    evs = [e for e in _fresh._events if e["type"] == "flight_record"]
    assert len(evs) == 1
    assert evs[0]["status"] == "shed" and evs[0]["trigger"] == "shed"
    assert evs[0]["spans"]["admission"] is not None
    assert evs[0]["spans"]["score"] is None   # never reached the scorer


def test_engine_expired_ticket_flight_record(rng, _fresh):
    eng, _, _ = _engine(rng, slo_s=1e-7)
    t_dead = eng.submit(0, deadline_s=0.0)
    t_ok = eng.submit(1)
    time.sleep(0.01)
    _drain_one(eng)
    with pytest.raises(DeadlineExceeded):
        t_dead.result(timeout=1.0)
    t_ok.result(timeout=1.0)
    evs = [e for e in _fresh._events if e["type"] == "flight_record"]
    statuses = {e["status"] for e in evs}
    assert statuses == {"expired", "ok"}
    exp = next(e for e in evs if e["status"] == "expired")
    assert exp["spans"]["queue_wait"] is not None
    assert exp["spans"]["score"] is None


def test_serve_bench_forced_breach_emits_flight_records(capsys):
    """ISSUE acceptance: serve-bench under a forced SLO breach reports
    flight_record events covering at least the last 8 requests."""
    from tpu_als.cli import main

    main(["serve-bench", "--users", "100", "--items", "300",
          "--rank", "4", "--qps", "300", "--duration", "0.1",
          "--slo-ms", "0.000001", "--buckets", "8"])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["slo_met"] is False
    assert out["scored"] >= 8
    assert out["flight_records"] >= min(out["scored"], 8)
