"""Two-tower retrieval tests: training improves retrieval, ALS warm start
helps at few epochs (the config-5 claim)."""

import numpy as np

from tpu_als.models.two_tower import (
    TwoTowerConfig,
    recall_at_k,
    train_two_tower,
)

from conftest import make_ratings
import pytest


def _interactions(rng, nU=60, nI=40):
    u, i, r, Ustar, Vstar = make_ratings(rng, nU, nI, rank=4, density=0.2)
    pos = r > np.quantile(r, 0.5)  # top-half ratings are "interactions"
    return u[pos], i[pos], Ustar, Vstar


@pytest.mark.slow
def test_training_beats_random_init_recall(rng):
    u, i, _, _ = _interactions(rng)
    cfg = TwoTowerConfig(embed_dim=8, hidden=(16,), out_dim=8, epochs=0,
                         seed=0)
    params0 = train_two_tower(u, i, 60, 40, cfg)  # untrained
    r0 = recall_at_k(params0, u, i, k=5)
    cfg2 = TwoTowerConfig(embed_dim=8, hidden=(16,), out_dim=8, epochs=60,
                          batch_size=256, learning_rate=3e-3, seed=0)
    params = train_two_tower(u, i, 60, 40, cfg2)
    r1 = recall_at_k(params, u, i, k=5)
    assert r1 > r0 + 0.1, (r0, r1)


def test_als_warm_start(rng):
    u, i, Ustar, Vstar = _interactions(rng)
    # warm start from the planted factors (stand-in for fitted ALS factors)
    cfg = TwoTowerConfig(embed_dim=4, hidden=(), out_dim=4, epochs=0, seed=1)
    warm = train_two_tower(u, i, 60, 40, cfg,
                           als_user_factors=Ustar, als_item_factors=Vstar)
    cold = train_two_tower(u, i, 60, 40, cfg)
    r_warm = recall_at_k(warm, u, i, k=10)
    r_cold = recall_at_k(cold, u, i, k=10)
    assert r_warm > r_cold, (r_warm, r_cold)


@pytest.mark.slow
def test_popularity_correction_changes_loss_and_stays_finite(rng):
    # one dominant item: the logQ correction must shift the logits (loss
    # differs from the uncorrected run) and training must stay finite
    import jax.numpy as jnp

    from tpu_als.models.two_tower import in_batch_softmax_loss, init_params
    import jax

    nU, nI, n = 30, 10, 200
    u = rng.integers(0, nU, n)
    i = np.where(rng.random(n) < 0.7, 0, rng.integers(1, nI, n))  # item 0 hot
    counts = np.bincount(i, minlength=nI).astype(np.float64)
    log_q = jnp.asarray(
        np.log((counts + 1) / (counts.sum() + nI)), jnp.float32)
    params = init_params(jax.random.PRNGKey(0), nU, nI,
                         TwoTowerConfig(embed_dim=4, hidden=(), out_dim=4))
    ub, ib = jnp.asarray(u[:64]), jnp.asarray(i[:64])
    w = jnp.ones(64)
    l_plain = in_batch_softmax_loss(params, ub, ib, w, 0.1)
    l_corr = in_batch_softmax_loss(params, ub, ib, w, 0.1, log_q)
    assert np.isfinite(float(l_plain)) and np.isfinite(float(l_corr))
    assert abs(float(l_plain) - float(l_corr)) > 1e-4

    cfg = TwoTowerConfig(embed_dim=4, hidden=(), out_dim=4, epochs=2,
                         batch_size=64, popularity_correction=True, seed=0)
    p = train_two_tower(u, i, nU, nI, cfg)
    assert np.isfinite(np.asarray(p["item_embed"])).all()


def test_filtered_recall_excludes_train_items(rng):
    # user 0's strongest item (0) is a *train* interaction; held-out item 1
    # is second-best.  Unfiltered top-1 is occupied by the train item
    # (recall 0); the filtered protocol removes it (recall 1).
    import jax

    from tpu_als.models.two_tower import init_params

    nU, nI = 3, 5
    Uf = np.zeros((nU, 4), np.float32)
    Vf = np.zeros((nI, 4), np.float32)
    Uf[0, 0] = 1.0
    Vf[0, 0] = 10.0   # train item, top score for user 0
    Vf[1, 0] = 5.0    # held-out item, second
    Vf[2:, 1] = 1.0
    cfg = TwoTowerConfig(embed_dim=4, hidden=(), out_dim=4, epochs=0)
    params = init_params(jax.random.PRNGKey(0), nU, nI, cfg,
                         als_user_factors=Uf, als_item_factors=Vf)
    params["user_embed"] = jax.numpy.asarray(Uf)
    params["item_embed"] = jax.numpy.asarray(Vf)
    eval_u, eval_i = np.array([0]), np.array([1])
    train_u, train_i = np.array([0]), np.array([0])
    r_plain = recall_at_k(params, eval_u, eval_i, k=1)
    r_filt = recall_at_k(params, eval_u, eval_i, k=1,
                         exclude=(train_u, train_i), user_batch=2)
    assert r_plain == 0.0 and r_filt == 1.0, (r_plain, r_filt)


@pytest.mark.slow
def test_filtered_recall_matches_plain_when_no_overlap(rng):
    u, i, _, _ = _interactions(rng)
    cfg = TwoTowerConfig(embed_dim=8, hidden=(16,), out_dim=8, epochs=2,
                         batch_size=256, seed=3)
    params = train_two_tower(u, i, 60, 40, cfg)
    # exclusion lists for users outside the eval set change nothing
    other_u = np.full(5, 59)
    other_i = np.arange(5)
    eval_u, eval_i = u[u != 59], i[u != 59]
    r_plain = recall_at_k(params, eval_u, eval_i, k=5)
    r_filt = recall_at_k(params, eval_u, eval_i, k=5,
                         exclude=(other_u, other_i), user_batch=16)
    assert r_plain == r_filt, (r_plain, r_filt)


def test_weights_and_callback(rng):
    """`weights` gate the per-row softmax loss (a zero-weight pair adds
    no positive gradient, though its item still serves as an in-batch
    negative for other rows) and `callback` observes every epoch."""
    u, i, _, _ = _interactions(rng)
    cfg = TwoTowerConfig(embed_dim=8, hidden=(16,), out_dim=8, epochs=3,
                         batch_size=256, seed=3)
    seen = []
    params = train_two_tower(
        u, i, 60, 40, cfg,
        callback=lambda ep, loss, p: seen.append((ep, loss)))
    assert [ep for ep, _ in seen] == [1, 2, 3]
    assert all(np.isfinite(l) for _, l in seen)

    # training with HALF the pairs zero-weighted must differ from
    # uniform weights (the gate is live), and still train finitely
    w = np.ones(len(u), np.float32)
    w[::2] = 0.0
    pw = train_two_tower(u, i, 60, 40, cfg, weights=w)
    assert not np.allclose(np.asarray(pw["user_embed"]),
                           np.asarray(params["user_embed"]))
    assert np.isfinite(np.asarray(pw["user_embed"])).all()


def test_serving_bias_steers_topk_toward_biased_items(rng):
    """An item_bias large on one item must pull it into every top-k (and a
    zero bias must change nothing) — the serving-time popularity-prior
    mechanism, exercised through both the biased and ban machinery."""
    from tpu_als.models.two_tower import serving_bias

    u, i, _, _ = _interactions(rng)
    cfg = TwoTowerConfig(embed_dim=8, hidden=(16,), out_dim=8, epochs=2,
                         batch_size=256, seed=3)
    params = train_two_tower(u, i, 60, 40, cfg)
    plain = recall_at_k(params, u, i, k=5)
    zero = recall_at_k(params, u, i, k=5, item_bias=np.zeros(40, np.float32))
    assert plain == zero
    # a huge bias on item 7 forces it into every user's top-k: recall
    # becomes exactly the share of eval pairs whose item is 7 plus
    # whatever still ranks in the remaining 4 slots >= pairs-with-7 share
    bias = np.zeros(40, np.float32)
    bias[7] = 1e4
    boosted = recall_at_k(params, u, i, k=1, item_bias=bias)
    assert boosted == float((i == 7).mean())
    # the real helper: temperature-scaled log q, finite, and strictly
    # higher for the hottest item than for a zero-count one
    counts = np.bincount(i, minlength=40)
    sb = serving_bias(counts, cfg.temperature)
    assert np.isfinite(sb).all()
    hot = int(np.argmax(counts))
    cold_ = int(np.argmin(counts))
    assert counts[hot] > counts[cold_]
    assert sb[hot] > sb[cold_]


def test_from_fitted_als_model(rng):
    from tpu_als import ALS, ColumnarFrame

    u, i, r, _, _ = make_ratings(rng, 40, 30, rank=3, density=0.4)
    model = ALS(rank=4, maxIter=5, seed=0).fit(
        ColumnarFrame({"user": u, "item": i, "rating": r}))
    u_dense = model._user_map.to_dense(u)
    i_dense = model._item_map.to_dense(i)
    cfg = TwoTowerConfig(embed_dim=4, hidden=(8,), out_dim=4, epochs=3,
                         batch_size=128, seed=2)
    params = train_two_tower(u_dense, i_dense, 40, 30, cfg,
                             als_user_factors=model._U,
                             als_item_factors=model._V)
    rec = recall_at_k(params, u_dense, i_dense, k=10)
    assert 0.0 <= rec <= 1.0


@pytest.mark.slow
def test_two_tower_save_load_roundtrip(rng, tmp_path):
    """Config-5 model persistence: save -> load reproduces the exact
    serving behavior (representations and retrieval top-k)."""
    import numpy as np

    from tpu_als.models.two_tower import (
        TwoTowerConfig,
        load_two_tower,
        recall_at_k,
        save_two_tower,
        train_two_tower,
        user_repr,
        item_repr,
    )

    nU, nI = 60, 30
    u = rng.integers(0, nU, 800)
    i = rng.integers(0, nI, 800)
    cfg = TwoTowerConfig(embed_dim=8, hidden=(16,), out_dim=8, epochs=2,
                         batch_size=256, seed=0)
    params = train_two_tower(u, i, nU, nI, cfg)
    path = str(tmp_path / "tt")
    save_two_tower(path, params, cfg, nU, nI)
    p2, cfg2, nU2, nI2 = load_two_tower(path)
    assert (nU2, nI2) == (nU, nI) and cfg2 == cfg
    np.testing.assert_array_equal(
        np.asarray(user_repr(params, np.arange(nU))),
        np.asarray(user_repr(p2, np.arange(nU))))
    np.testing.assert_array_equal(
        np.asarray(item_repr(params, np.arange(nI))),
        np.asarray(item_repr(p2, np.arange(nI))))
    r1 = recall_at_k(params, u[:100], i[:100], k=5)
    r2 = recall_at_k(p2, u[:100], i[:100], k=5)
    assert r1 == r2


def test_embed_lr_scale_freezes_and_slows_tables(rng):
    import numpy as np

    u, i, Ustar, Vstar = _interactions(rng)
    base = dict(embed_dim=4, hidden=(16,), out_dim=4, epochs=3,
                batch_size=256, seed=0)
    frozen = train_two_tower(
        u, i, 60, 40, TwoTowerConfig(**base, embed_lr_scale=0.0),
        als_user_factors=Ustar, als_item_factors=Vstar)
    # frozen: tables still exactly the warm start, towers trained
    np.testing.assert_array_equal(
        np.asarray(frozen["user_embed"])[:, :4], Ustar)
    assert np.abs(np.asarray(frozen["user_tower"][0]["w"])).sum() > 0
    slow = train_two_tower(
        u, i, 60, 40, TwoTowerConfig(**base, embed_lr_scale=0.1),
        als_user_factors=Ustar, als_item_factors=Vstar)
    full = train_two_tower(
        u, i, 60, 40, TwoTowerConfig(**base),
        als_user_factors=Ustar, als_item_factors=Vstar)
    drift = lambda p: float(  # noqa: E731
        np.abs(np.asarray(p["user_embed"])[:, :4] - Ustar).mean())
    assert 0 < drift(slow) < drift(full)
