"""Fused normal-eq + solve kernel vs the unfused einsum + Cholesky path
(interpret mode on the CPU test mesh; the same kernel compiles on TPU)."""

import numpy as np
import jax.numpy as jnp
import pytest

from tpu_als.ops.pallas_fused import fused_normal_solve
from tpu_als.ops.solve import (
    compute_yty,
    normal_eq_explicit,
    normal_eq_implicit,
    solve_spd,
)


def _problem(rng, N, w, r, n_opp=200, implicit=False):
    V = rng.normal(size=(n_opp, r)).astype(np.float32) / np.sqrt(r)
    cols = rng.integers(0, n_opp, (N, w))
    vals = rng.normal(size=(N, w)).astype(np.float32)
    if implicit:
        vals = np.abs(vals) * 3
        # sprinkle zero-confidence and negative entries
        vals[rng.random((N, w)) < 0.2] *= -1
    mask = (rng.random((N, w)) < 0.8).astype(np.float32)
    vals = vals * mask
    Vg = V[cols] * 1.0  # gathered factors
    return jnp.asarray(V), jnp.asarray(Vg), jnp.asarray(vals), jnp.asarray(mask)


@pytest.mark.parametrize("N,w,r", [
    (5, 8, 4),       # tiny everything
    (37, 24, 10),    # ALS default rank, non-pow2 batch, w multiple of 8
    (64, 512, 32),   # multiple width chunks
    (33, 128, 128),  # the benchmark rank
])
def test_explicit_matches_unfused(rng, N, w, r):
    V, Vg, vals, mask = _problem(rng, N, w, r)
    reg = 0.05
    A, b, count = normal_eq_explicit(Vg, vals, mask, reg)
    ref = solve_spd(A, b, count, backend="xla")
    x = fused_normal_solve(Vg, vals, mask, reg=reg, interpret=True)
    np.testing.assert_allclose(np.asarray(x), np.asarray(ref),
                               atol=5e-4, rtol=5e-3)


def test_implicit_matches_unfused(rng):
    N, w, r = 48, 64, 16
    V, Vg, vals, mask = _problem(rng, N, w, r, implicit=True)
    reg, alpha = 0.1, 4.0
    YtY = compute_yty(V)
    A, b, count = normal_eq_implicit(Vg, vals, mask, reg, alpha, YtY)
    ref = solve_spd(A, b, count, backend="xla")
    x = fused_normal_solve(Vg, vals, mask, YtY, reg=reg, implicit=True,
                           alpha=alpha, interpret=True)
    np.testing.assert_allclose(np.asarray(x), np.asarray(ref),
                               atol=5e-4, rtol=5e-3)


def test_empty_rows_solve_to_zero(rng):
    N, w, r = 16, 16, 8
    V, Vg, vals, mask = _problem(rng, N, w, r)
    mask = np.asarray(mask).copy()
    mask[::4] = 0.0  # whole rows empty
    vals = np.asarray(vals) * mask
    x = fused_normal_solve(jnp.asarray(np.asarray(Vg)),
                           jnp.asarray(vals), jnp.asarray(mask),
                           reg=0.05, interpret=True)
    x = np.asarray(x)
    assert np.isfinite(x).all()
    assert np.abs(x[::4]).max() == 0.0
    assert np.abs(x[1::4]).max() > 0.0


def test_training_with_fused_backend_matches(rng):
    """End-to-end: cfg.solve_backend='fused' (interpret off-TPU is not
    available, so drive the kernel in interpret mode through one half-step
    equivalent) — here we check the config plumbing rejects nothing and the
    auto path stays unfused off-TPU."""
    from conftest import make_ratings
    from tpu_als.core.als import AlsConfig, train
    from tpu_als.core.ratings import build_csr_buckets

    u, i, r, _, _ = make_ratings(np.random.default_rng(1), 30, 20,
                                 rank=3, density=0.4)
    ucsr = build_csr_buckets(u, i, r, 30, min_width=4)
    icsr = build_csr_buckets(i, u, r, 20, min_width=4)
    cfg = AlsConfig(rank=4, max_iter=2, reg_param=0.05, seed=0,
                    solve_backend="auto")
    U, V = train(ucsr, icsr, cfg)  # off-TPU auto → unfused, must be green
    assert np.isfinite(np.asarray(U)).all()
