"""Multi-tenant control-plane tests (tpu_als/tenancy/).

Five layers:

1. the REGISTRY contract — spec validation (name slug, weight,
   guardrail mode), duplicate/unknown-tenant typing, register → first
   publish, remove → lifecycle teardown, shape-class report,
2. the SCHEDULER policy — stride fair-share (weighted goodput under
   contention, min-vtime floor for joiners), typed per-tenant
   :class:`TenantOverloaded`, per-batch fault isolation,
3. the LABEL vocabulary — serving.*/live.* series carry tenant=<name>,
   unregistered label keys raise at write time, the static
   check_tenant_vocabulary / call-site rule catch the same drift
   offline,
4. seq-space NAMESPACING — one tenant's publishes never advance a
   neighbor's sequence, and same-shaped tenants share one plan entry,
5. the tenant-isolation scenario is registered with the fault-matrix
   assertions the smoke gate runs.
"""

import importlib.util
import os

import numpy as np
import pytest

from tpu_als import obs, plan
from tpu_als.tenancy import (DuplicateTenant, FairShareScheduler,
                             MultiTenantEngine, TenancyError, Tenant,
                             TenantOverloaded, TenantRegistry,
                             TenantSpec, UnknownTenant)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh():
    reg = obs.reset()
    yield reg


def _factors(rng, users=32, items=48, rank=8):
    return (rng.normal(size=(users, rank)).astype(np.float32),
            rng.normal(size=(items, rank)).astype(np.float32))


# ---------------------------------------------------------------------------
# 1. registry


def test_spec_validates_name_weight_mode():
    with pytest.raises(ValueError, match="must match"):
        TenantSpec(name="Bad Name!")
    with pytest.raises(ValueError, match="must match"):
        TenantSpec(name="")
    with pytest.raises(ValueError, match="weight"):
        TenantSpec(name="a", weight=0)
    with pytest.raises(ValueError, match="guardrail_mode"):
        TenantSpec(name="a", guardrail_mode="yolo")
    assert TenantSpec(name="team-a_01").weight == 1.0


def test_register_publishes_and_emits(_fresh):
    rng = np.random.default_rng(0)
    U, V = _factors(rng)
    reg = TenantRegistry()
    t = reg.register(TenantSpec(name="a"), U, V)
    assert t.engine.published_seq == 1
    assert t.engine.tenant == "a"
    assert "a" in reg and len(reg) == 1
    evs = [e for e in _fresh._events
           if e.get("type") == "tenant_registered"]
    assert evs and evs[0]["tenant"] == "a"
    assert evs[0]["shape_class"] == t.shape_class


def test_duplicate_and_unknown_are_typed():
    rng = np.random.default_rng(0)
    U, V = _factors(rng)
    reg = TenantRegistry()
    reg.register(TenantSpec(name="a"), U, V)
    with pytest.raises(DuplicateTenant):
        reg.register(TenantSpec(name="a"), U, V)
    with pytest.raises(UnknownTenant) as ei:
        reg.get("ghost")
    assert ei.value.available == ("a",)
    assert isinstance(ei.value, TenancyError)


def test_remove_tears_down_and_emits(_fresh):
    rng = np.random.default_rng(0)
    U, V = _factors(rng)
    reg = TenantRegistry()
    reg.register(TenantSpec(name="a"), U, V)
    reg.remove("a")
    assert len(reg) == 0
    with pytest.raises(UnknownTenant):
        reg.remove("a")
    assert any(e.get("type") == "tenant_removed"
               for e in _fresh._events)


def test_register_is_publish_before_visible(monkeypatch):
    """The churn invariant (PR 18): a tenant is never observable in the
    registry before its engine's first publish completes, and a failed
    publish leaves no zombie — the engine is stopped and the name is
    immediately reusable."""
    from tpu_als.serving.engine import ServingEngine

    rng = np.random.default_rng(0)
    U, V = _factors(rng)
    reg = TenantRegistry()

    seen = {}
    real_publish = ServingEngine.publish

    def spying_publish(self, *a, **kw):
        seen["visible_during_publish"] = "a" in reg
        return real_publish(self, *a, **kw)

    monkeypatch.setattr(ServingEngine, "publish", spying_publish)
    reg.register(TenantSpec(name="a"), U, V)
    assert seen["visible_during_publish"] is False

    stopped = {}
    real_stop = ServingEngine.stop

    def failing_publish(self, *a, **kw):
        raise RuntimeError("boom: torn first publish")

    def spying_stop(self, *a, **kw):
        stopped["called"] = True
        return real_stop(self, *a, **kw)

    monkeypatch.setattr(ServingEngine, "publish", failing_publish)
    monkeypatch.setattr(ServingEngine, "stop", spying_stop)
    with pytest.raises(RuntimeError, match="torn first publish"):
        reg.register(TenantSpec(name="b"), U, V)
    assert "b" not in reg
    assert stopped.get("called") is True

    monkeypatch.setattr(ServingEngine, "publish", real_publish)
    monkeypatch.setattr(ServingEngine, "stop", real_stop)
    assert reg.register(TenantSpec(name="b"), U, V).name == "b"


def test_tenant_churn_snapshots_always_servable():
    """Register/remove churn on one name while a reader thread takes
    registry snapshots: every tenant a snapshot ever exposes has a
    published generation (``published_seq >= 1``), so the scheduler can
    never pick up a tenant mid-construction."""
    import threading

    rng = np.random.default_rng(0)
    U, V = _factors(rng, users=8, items=8, rank=4)
    reg = TenantRegistry()
    reg.register(TenantSpec(name="stable"), U, V)
    bad, stop = [], threading.Event()

    def reader():
        while not stop.is_set():
            for t in reg.tenants():
                if t.engine.published_seq < 1:
                    bad.append(t.name)

    r = threading.Thread(target=reader)
    r.start()
    try:
        for _ in range(25):
            reg.register(TenantSpec(name="churn"), U, V)
            reg.remove("churn")
    finally:
        stop.set()
        r.join()
    assert not bad, f"snapshot exposed unpublished tenants: {bad}"
    assert reg.names() == ("stable",)


def test_same_shape_tenants_share_plan_entry():
    rng = np.random.default_rng(0)
    reg = TenantRegistry()
    U, V = _factors(rng)
    reg.register(TenantSpec(name="a"), U, V)
    reg.register(TenantSpec(name="b"), *_factors(rng))
    U2, V2 = _factors(rng, users=4096, items=8192)
    reg.register(TenantSpec(name="big"), U2, V2)
    classes = reg.shape_classes()
    shared = [v for v in classes.values() if set(v) >= {"a", "b"}]
    assert shared, classes
    assert reg.get("a").engine.batcher.buckets \
        == reg.get("b").engine.batcher.buckets
    # and the planner resolution is tenant-blind: same inputs, same plan
    p1 = plan.resolve_tenant_plan(rank=8, n_users=32, n_items=48)
    p2 = plan.resolve_tenant_plan(rank=8, n_users=32, n_items=48)
    assert p1 == p2


def test_attach_live_is_tenant_labeled_and_single():
    rng = np.random.default_rng(0)
    U, V = _factors(rng)
    reg = TenantRegistry()
    reg.register(TenantSpec(name="a", fold_items=True,
                            freshness_slo_s=2.0), U, V)

    class _FakeFoldin:
        pass

    upd = reg.attach_live("a", _FakeFoldin())
    assert upd.tenant == "a"
    assert upd.fold_items is True
    assert upd.slo_s == 2.0
    with pytest.raises(TenancyError, match="already has"):
        reg.attach_live("a", _FakeFoldin())


# ---------------------------------------------------------------------------
# 2. scheduler policy


def _mk_tenant(name, weight=1.0, depth=1):
    class _B:
        def __init__(self, d):
            self._d = d

        def depth(self):
            return self._d

    class _E:
        def __init__(self, d):
            self.batcher = _B(d)

    return Tenant(spec=TenantSpec(name=name, weight=weight),
                  engine=_E(depth))


def test_stride_pick_prefers_min_vtime_then_name():
    s = FairShareScheduler()
    a, b = _mk_tenant("a"), _mk_tenant("b")
    a.vtime, b.vtime = 5.0, 3.0
    assert s.pick([a, b]).name == "b"
    b.vtime = 5.0
    assert s.pick([a, b]).name == "a"       # deterministic tie-break


def test_stride_charge_is_weighted(_fresh):
    s = FairShareScheduler()
    heavy, light = _mk_tenant("heavy", weight=2.0), _mk_tenant("light")
    s.charge(heavy, 8)
    s.charge(light, 8)
    assert heavy.vtime == 4.0 and light.vtime == 8.0
    assert heavy.served_rows == light.served_rows == 8
    assert _fresh.counter_value("tenancy.served_rows",
                                tenant="heavy") == 8


def test_joiner_floored_to_virtual_clock():
    s = FairShareScheduler()
    old = _mk_tenant("old")
    for _ in range(10):
        s.charge(s.pick([old]), 10)
    assert old.vtime == 100.0
    new = _mk_tenant("new")
    picked = s.pick([old, new])
    # the newcomer is floored to the global virtual clock (old's vtime
    # at its LAST pick) — it competes from now, not from a 100-row
    # catch-up monopoly
    assert new.vtime == 90.0
    assert picked.name == "new"
    # ...while a tenant that stayed in the rotation keeps its earned
    # deficit: the weighted shares are never clipped by the floor
    s.charge(picked, 10)
    assert s.pick([old, new]).name == "new"
    assert new.vtime == 100.0


def test_weighted_fair_share_under_contention():
    rng = np.random.default_rng(1)
    eng = MultiTenantEngine()
    eng.add_tenant(TenantSpec(name="heavy", weight=3.0, k=5),
                   *_factors(rng))
    eng.add_tenant(TenantSpec(name="light", weight=1.0, k=5),
                   *_factors(rng))
    eng.warmup()
    with eng:
        tickets = []
        for j in range(60):
            tickets.append(eng.submit("heavy", j % 32))
            tickets.append(eng.submit("light", j % 32))
        for t in tickets:
            t.result(timeout=30.0)
    h = eng.tenant("heavy")
    li = eng.tenant("light")
    assert h.served_rows == li.served_rows == 60
    # equal rows at 3x weight -> one third the virtual time charged
    assert h.vtime == pytest.approx(li.vtime / 3.0)


def test_tenant_overloaded_is_typed_and_isolated():
    rng = np.random.default_rng(2)
    eng = MultiTenantEngine()
    eng.add_tenant(TenantSpec(name="small", k=5, max_queue=2),
                   *_factors(rng))
    eng.add_tenant(TenantSpec(name="roomy", k=5), *_factors(rng))
    eng.warmup()
    # engine NOT started: small's queue fills and stays full
    with pytest.raises(TenantOverloaded) as ei:
        for _ in range(10):
            eng.submit("small", 0)
    assert ei.value.tenant == "small"
    from tpu_als.serving import Overloaded
    assert isinstance(ei.value, Overloaded)   # old handlers still catch
    # the neighbor's budget is untouched
    t = eng.submit("roomy", 0)
    assert obs.counter_value("serving.shed", tenant="small") == 1
    assert obs.counter_value("serving.shed", tenant="roomy") == 0
    with eng:                                  # drain what was admitted
        t.result(timeout=10.0)


def test_batch_fault_isolated_to_one_tenant(_fresh):
    rng = np.random.default_rng(3)
    eng = MultiTenantEngine()
    eng.add_tenant(TenantSpec(name="sick", k=5), *_factors(rng))
    eng.add_tenant(TenantSpec(name="well", k=5), *_factors(rng))
    eng.warmup()
    from tpu_als.resilience import faults
    with eng:
        faults.install("serving.score=raise@once")
        try:
            bad = eng.submit("sick", 0)
            with pytest.raises(faults.InjectedFault):
                bad.result(timeout=10.0)
        finally:
            faults.clear()
        s, ix = eng.recommend("well", 0, timeout=10.0)
        assert np.isfinite(np.asarray(s)).all()
        # the sick tenant recovers on its next batch too
        s2, _ = eng.recommend("sick", 1, timeout=10.0)
        assert np.isfinite(np.asarray(s2)).all()
    assert _fresh.counter_value("tenancy.batch_errors",
                                tenant="sick") == 1
    assert _fresh.counter_value("tenancy.batch_errors",
                                tenant="well") == 0


# ---------------------------------------------------------------------------
# 3. label vocabulary, runtime + static


def test_serving_metrics_carry_tenant_label(_fresh):
    rng = np.random.default_rng(4)
    eng = MultiTenantEngine()
    eng.add_tenant(TenantSpec(name="a", k=5), *_factors(rng))
    eng.warmup()
    with eng:
        eng.recommend("a", 0, timeout=10.0)
    assert _fresh.counter_value("serving.requests", tenant="a") == 1
    assert _fresh.histogram_count("serving.e2e_seconds", tenant="a") == 1
    # the UNLABELED series is a different series: single-tenant engines
    # keep writing it, per-tenant reads never see their neighbors
    assert _fresh.counter_value("serving.requests") == 0


def test_unregistered_label_key_raises():
    with pytest.raises(ValueError, match="does not declare"):
        obs.counter("ingest.rows", 1, tenant="a")
    with pytest.raises(ValueError, match="does not declare"):
        obs.histogram("train.stage_seconds", 0.1, tenant="a",
                      stage="solve")
    # declared keys still work
    obs.histogram("train.stage_seconds", 0.1, stage="solve")
    obs.histogram("serving.publish_seconds", 0.1, mode="full",
                  tenant="a")


def _load_vocab():
    spec = importlib.util.spec_from_file_location(
        "_tal_vocab_test", os.path.join(REPO, "tpu_als", "analysis",
                                        "vocab.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_tenant_vocabulary_pins_hold():
    vocab = _load_vocab()
    assert vocab.check_tenant_vocabulary(REPO) == []
    # the pin actually bites: a schema missing the mode key fails it
    schema, _ = vocab.load_registries(REPO)
    assert "mode" in schema.LABELS["serving.publish_seconds"]
    assert "tenant" in schema.LABELS["serving.publish_seconds"]
    for name in schema.METRICS:
        if name.startswith(("serving.", "live.")):
            assert name in schema.TENANT_LABELED, name


def test_callsite_rule_flags_unregistered_tenant_label(tmp_path):
    vocab = _load_vocab()
    bad = tmp_path / "bad_site.py"
    bad.write_text(
        "from tpu_als import obs\n"
        "obs.counter('ingest.rows', 5, tenant='a')\n"
        "obs.histogram('serving.e2e_seconds', 0.1, tenant='a')\n")
    errs = vocab.check_file(str(bad), repo=REPO)
    assert len(errs) == 1
    lineno, msg = errs[0]
    assert lineno == 2 and "tenant=" in msg and "ingest.rows" in msg


# ---------------------------------------------------------------------------
# 4. seq-space namespacing


def test_publish_seq_spaces_are_namespaced(_fresh):
    rng = np.random.default_rng(5)
    eng = MultiTenantEngine()
    Ua, Va = _factors(rng)
    Ub, Vb = _factors(rng)
    eng.add_tenant(TenantSpec(name="a", k=5), Ua, Va)
    eng.add_tenant(TenantSpec(name="b", k=5), Ub, Vb)
    assert eng.published_seq("a") == eng.published_seq("b") == 1
    eng.publish("a", Ua, Va)
    eng.publish("a", Ua, Va)
    assert eng.published_seq("a") == 3
    assert eng.published_seq("b") == 1      # untouched by the neighbor
    seq, mode = eng.publish_update("b", Ub, Vb)
    assert (seq, eng.published_seq("a")) == (2, 3)
    pubs = [e for e in _fresh._events
            if e.get("type") == "serving_publish"]
    assert {e.get("tenant") for e in pubs} == {"a", "b"}
    eng.stop()


# ---------------------------------------------------------------------------
# 5. scenario registration


def test_tenant_isolation_scenario_registered():
    from tpu_als.scenario import get_scenario

    s = get_scenario("tenant-isolation")
    assert [p.name for p in s.phases] == [
        "solo-baseline", "multi-tenant-start", "fault-storm",
        "tenant-churn", "judge"]
    checks = {a.check for a in s.assertions}
    assert {"b_topk_bitwise", "b_p99_under_slo", "b_zero_shed",
            "a_spike_shed", "a_quarantine_attributed",
            "sentinel_tripped", "rolled_back"} <= checks
    # the storm arms its faults IN PHASE, scoped to tenant A — a
    # spec-level fault_spec would poison the solo baseline too
    assert s.fault_spec is None
