"""Pipeline / StringIndexer / IndexToString — the reference's
`pyspark.ml` composition layer (SURVEY.md §1 L2; canonical upstream
`python/pyspark/ml/pipeline.py`, `python/pyspark/ml/feature.py`).

The flagship test is the canonical recommender pipeline shape:
StringIndexer(user) → StringIndexer(item) → ALS on raw string ids.
"""

import numpy as np
import pytest

from tpu_als import (
    ALS,
    ColumnarFrame,
    CrossValidator,
    IndexToString,
    ParamGridBuilder,
    Pipeline,
    PipelineModel,
    RegressionEvaluator,
    StringIndexer,
    StringIndexerModel,
)


def _string_ratings(rng, n_users=30, n_items=20, rank=4, density=0.5):
    from tests.conftest import make_ratings

    u, i, r, _, _ = make_ratings(rng, n_users, n_items, rank, density)
    return ColumnarFrame({
        "userName": np.array([f"user_{k}" for k in u], dtype=object),
        "itemName": np.array([f"item_{k}" for k in i], dtype=object),
        "rating": r,
    })


# -- StringIndexer ---------------------------------------------------------

def test_indexer_frequency_desc_order():
    df = ColumnarFrame({"c": np.array(["b", "a", "b", "c", "b", "a"])})
    m = StringIndexer(inputCol="c", outputCol="ci").fit(df)
    assert m.labels == ["b", "a", "c"]  # freq 3, 2, 1
    out = m.transform(df)
    np.testing.assert_array_equal(out["ci"], [0, 1, 0, 2, 0, 1])
    assert out["ci"].dtype == np.int64


def test_indexer_tie_breaks_alphabetically():
    df = ColumnarFrame({"c": np.array(["z", "a", "z", "a"])})
    m = StringIndexer(inputCol="c", outputCol="ci").fit(df)
    assert m.labels == ["a", "z"]


@pytest.mark.parametrize("order,expected", [
    ("frequencyAsc", ["c", "a", "b"]),
    ("alphabetAsc", ["a", "b", "c"]),
    ("alphabetDesc", ["c", "b", "a"]),
])
def test_indexer_order_types(order, expected):
    df = ColumnarFrame({"c": np.array(["b", "a", "b", "c", "b", "a"])})
    m = StringIndexer(inputCol="c", outputCol="ci",
                      stringOrderType=order).fit(df)
    assert m.labels == expected


def test_indexer_handle_invalid_error():
    train = ColumnarFrame({"c": np.array(["a", "b"])})
    test = ColumnarFrame({"c": np.array(["a", "zzz"])})
    m = StringIndexer(inputCol="c", outputCol="ci").fit(train)
    with pytest.raises(ValueError, match="unseen.*zzz"):
        m.transform(test)


def test_indexer_handle_invalid_skip_and_keep():
    train = ColumnarFrame({"c": np.array(["a", "b", "a"])})
    test = ColumnarFrame({"c": np.array(["a", "zzz", "b"]),
                          "x": np.arange(3)})
    m = StringIndexer(inputCol="c", outputCol="ci",
                      handleInvalid="skip").fit(train)
    out = m.transform(test)
    assert len(out) == 2
    np.testing.assert_array_equal(out["x"], [0, 2])  # row 1 dropped
    out = m.setHandleInvalid("keep").transform(test)
    np.testing.assert_array_equal(out["ci"], [0, len(m.labels), 1])


def test_indexer_rejects_bad_policy_and_order():
    with pytest.raises(ValueError, match="handleInvalid"):
        StringIndexer(inputCol="c", outputCol="ci", handleInvalid="drop")
    with pytest.raises(ValueError, match="stringOrderType"):
        StringIndexer(inputCol="c", outputCol="ci",
                      stringOrderType="random")


def test_indexer_numeric_column_indexes_by_string_form():
    # pyspark casts non-string columns to string before indexing
    df = ColumnarFrame({"c": np.array([10, 2, 10, 3])})
    m = StringIndexer(inputCol="c", outputCol="ci").fit(df)
    assert m.labels == ["10", "2", "3"]


def test_indexer_model_roundtrip(tmp_path):
    df = ColumnarFrame({"c": np.array(["b", "a", "b"])})
    m = StringIndexer(inputCol="c", outputCol="ci",
                      handleInvalid="keep").fit(df)
    p = str(tmp_path / "idx")
    m.save(p)
    m2 = StringIndexerModel.load(p)
    assert m2.labels == m.labels
    np.testing.assert_array_equal(m2.transform(df)["ci"],
                                  m.transform(df)["ci"])
    assert m2.getOrDefault(m2.getParam("handleInvalid")) == "keep"


def test_index_to_string_inverse():
    df = ColumnarFrame({"c": np.array(["b", "a", "c", "b"])})
    m = StringIndexer(inputCol="c", outputCol="ci").fit(df)
    out = m.transform(df)
    inv = IndexToString(inputCol="ci", outputCol="back",
                        labels=m.labels).transform(out)
    np.testing.assert_array_equal(inv["back"], df["c"])


def test_index_to_string_bounds_check():
    t = IndexToString(inputCol="i", outputCol="s", labels=["a", "b"])
    with pytest.raises(ValueError, match="out of range"):
        t.transform(ColumnarFrame({"i": np.array([0, 5])}))


def test_index_to_string_roundtrip(tmp_path):
    t = IndexToString(inputCol="i", outputCol="s", labels=["a", "b"])
    p = str(tmp_path / "i2s")
    t.save(p)
    t2 = IndexToString.load(p)
    assert t2.labels == ["a", "b"]
    out = t2.transform(ColumnarFrame({"i": np.array([1, 0])}))
    np.testing.assert_array_equal(out["s"], ["b", "a"])


def test_indexer_model_rejects_bad_policy_everywhere():
    with pytest.raises(ValueError, match="handleInvalid"):
        StringIndexerModel(labels=["a"], handleInvalid="drop")
    with pytest.raises(ValueError, match="handleInvalid"):
        StringIndexerModel.from_labels(["a"], handleInvalid="eror")


def test_pipeline_fit_skips_transform_after_last_estimator(rng):
    """A stage after the last estimator must not be driven during fit —
    in particular the fitted model must not score the training set."""
    calls = []

    class SpyTransformer:
        def transform(self, df):
            calls.append(len(df))
            return df

    df = _string_ratings(rng, n_users=20, n_items=12)
    pipe = Pipeline(stages=[
        StringIndexer(inputCol="userName", outputCol="user"),
        StringIndexer(inputCol="itemName", outputCol="item"),
        ALS(userCol="user", itemCol="item", ratingCol="rating",
            rank=3, maxIter=2, regParam=0.005, seed=1),
        SpyTransformer(),
    ])
    pipe.fit(df)
    assert calls == []  # ALSModel.transform + spy both skipped in fit


def test_pipeline_save_rejects_foreign_stage(tmp_path):
    class Foreign:
        def transform(self, df):
            return df

        def _save_to(self, path):
            pass

    pipe = Pipeline(stages=[Foreign()])
    with pytest.raises(ValueError, match="outside tpu_als"):
        pipe.save(str(tmp_path / "f"))


# -- Pipeline --------------------------------------------------------------

def test_pipeline_string_ids_through_als(rng, tmp_path):
    """The canonical reference pipeline: index both id columns, fit ALS
    on the indices, predict on raw string ids end-to-end."""
    df = _string_ratings(rng)
    pipe = Pipeline(stages=[
        StringIndexer(inputCol="userName", outputCol="user",
                      handleInvalid="skip"),
        StringIndexer(inputCol="itemName", outputCol="item",
                      handleInvalid="skip"),
        ALS(userCol="user", itemCol="item", ratingCol="rating",
            rank=4, maxIter=6, regParam=0.005, seed=7),
    ])
    model = pipe.fit(df)
    assert isinstance(model, PipelineModel)
    out = model.transform(df)
    pred = out["prediction"]
    assert np.all(np.isfinite(pred))
    rmse = float(np.sqrt(np.mean((pred - df["rating"]) ** 2)))
    assert rmse < float(np.std(df["rating"]))  # beats trivial predictor

    # the fitted ALSModel is reachable for the recommend surface
    als_model = model.stages[-1]
    recs = als_model.recommendForAllUsers(3)
    assert len(recs) > 0

    # round-trip the whole fitted pipeline
    p = str(tmp_path / "pipe_model")
    model.save(p)
    loaded = PipelineModel.load(p)
    out2 = loaded.transform(df)
    np.testing.assert_allclose(out2["prediction"], pred, rtol=1e-6)


def test_pipeline_transformer_only_and_order():
    df = ColumnarFrame({"c": np.array(["b", "a", "b"])})
    idx_model = StringIndexer(inputCol="c", outputCol="ci").fit(df)
    pipe = Pipeline(stages=[
        idx_model,  # already-fitted transformer mixes with estimators
        IndexToString(inputCol="ci", outputCol="back",
                      labels=idx_model.labels),
    ])
    out = pipe.fit(df).transform(df)
    np.testing.assert_array_equal(out["back"], df["c"])


def test_pipeline_rejects_non_stage():
    with pytest.raises(TypeError, match="neither an estimator"):
        Pipeline(stages=[object()])


def test_unfitted_pipeline_roundtrip(tmp_path):
    pipe = Pipeline(stages=[
        StringIndexer(inputCol="userName", outputCol="user"),
        ALS(userCol="user", itemCol="item", rank=3, maxIter=2),
    ])
    p = str(tmp_path / "pipe")
    pipe.save(p)
    loaded = Pipeline.load(p)
    stages = loaded.getStages()
    assert isinstance(stages[0], StringIndexer)
    assert isinstance(stages[1], ALS)
    assert stages[1].getRank() == 3
    assert stages[0].getOrDefault(
        stages[0].getParam("outputCol")) == "user"


def test_pipeline_copy_routes_grid_params(rng):
    df = _string_ratings(rng, n_users=20, n_items=12)
    als = ALS(userCol="user", itemCol="item", ratingCol="rating",
              rank=3, maxIter=3, regParam=0.005, seed=1)
    pipe = Pipeline(stages=[
        StringIndexer(inputCol="userName", outputCol="user"),
        StringIndexer(inputCol="itemName", outputCol="item"),
        als,
    ])
    c = pipe.copy({als.rank: 5})
    assert c.getStages()[2].getRank() == 5
    assert pipe.getStages()[2].getRank() == 3  # original untouched

    # instance identity wins over class+name: each indexer's own param
    # drives only that stage, even though both stages share the class
    user_idx, item_idx = pipe.getStages()[0], pipe.getStages()[1]
    c2 = pipe.copy({user_idx.getParam("inputCol"): "renamed"})
    assert c2.getStages()[0].getOrDefault(
        c2.getStages()[0].getParam("inputCol")) == "renamed"
    assert c2.getStages()[1].getOrDefault(
        c2.getStages()[1].getParam("inputCol")) == "itemName"  # untouched

    # a DETACHED same-class param cannot pick between the two indexer
    # stages — refusing beats silently configuring both
    other = StringIndexer(inputCol="zz", outputCol="qq")
    with pytest.raises(ValueError, match="ambiguous"):
        pipe.copy({other.getParam("inputCol"): "nope"})
    with pytest.raises(ValueError, match="matches no pipeline stage"):
        ev = RegressionEvaluator()
        pipe.copy({ev.getParam("metricName"): "mae"})


@pytest.mark.slow
def test_crossvalidator_over_pipeline(rng):
    """CrossValidator(estimator=Pipeline) — the reference tuning idiom."""
    df = _string_ratings(rng, n_users=24, n_items=16, density=0.7)
    als = ALS(userCol="user", itemCol="item", ratingCol="rating",
              rank=3, maxIter=4, regParam=0.005, seed=3,
              coldStartStrategy="drop")
    pipe = Pipeline(stages=[
        StringIndexer(inputCol="userName", outputCol="user",
                      handleInvalid="skip"),
        StringIndexer(inputCol="itemName", outputCol="item",
                      handleInvalid="skip"),
        als,
    ])
    grid = ParamGridBuilder().addGrid(als.regParam, [0.005, 0.05]).build()
    cv = CrossValidator(estimator=pipe, estimatorParamMaps=grid,
                        evaluator=RegressionEvaluator(
                            metricName="rmse", labelCol="rating"),
                        numFolds=2, seed=11)
    cvm = cv.fit(df)
    assert len(cvm.avgMetrics) == 2
    assert np.all(np.isfinite(cvm.avgMetrics))
    out = cvm.transform(df)
    assert np.all(np.isfinite(out["prediction"]))


def test_crossvalidator_model_persistence_with_pipeline(rng, tmp_path):
    """CV over a Pipeline: the best model (a PipelineModel) must survive
    CrossValidatorModel save/load (tuning._save_tuned records the class)."""
    from tpu_als import CrossValidatorModel

    df = _string_ratings(rng, n_users=24, n_items=16, density=0.7)
    als = ALS(userCol="user", itemCol="item", ratingCol="rating",
              rank=3, maxIter=3, regParam=0.005, seed=3,
              coldStartStrategy="drop")
    pipe = Pipeline(stages=[
        StringIndexer(inputCol="userName", outputCol="user",
                      handleInvalid="skip"),
        StringIndexer(inputCol="itemName", outputCol="item",
                      handleInvalid="skip"),
        als,
    ])
    grid = ParamGridBuilder().addGrid(als.regParam, [0.005, 0.02]).build()
    cvm = CrossValidator(estimator=pipe, estimatorParamMaps=grid,
                         evaluator=RegressionEvaluator(
                             metricName="rmse", labelCol="rating"),
                         numFolds=2, seed=5).fit(df)
    p = str(tmp_path / "cvm")
    cvm.save(p)
    loaded = CrossValidatorModel.load(p)
    assert isinstance(loaded.bestModel, PipelineModel)
    a = cvm.transform(df)
    b = loaded.transform(df)
    np.testing.assert_allclose(np.asarray(b["prediction"]),
                               np.asarray(a["prediction"]), rtol=1e-6)


def test_pipeline_fitMultiple_snapshots_stage_state(rng):
    """The Estimator snapshot contract must hold THROUGH Pipeline.copy:
    mutating a stage after creating the iterator must not leak
    (advisor r4 — Pipeline.copy used to share unmutated stages)."""
    df = _string_ratings(rng, n_users=20, n_items=12)
    als = ALS(userCol="user", itemCol="item", ratingCol="rating",
              rank=3, maxIter=1, regParam=0.01, seed=0)
    pipe = Pipeline(stages=[
        StringIndexer(inputCol="userName", outputCol="user"),
        StringIndexer(inputCol="itemName", outputCol="item"),
        als,
    ])
    it = pipe.fitMultiple(df, [{}])
    als.setRank(9)
    _, model = next(it)
    assert model.stages[-1].rank == 3  # snapshot, not live state
