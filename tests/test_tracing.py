"""End-to-end causal tracing (tpu_als/obs/tracing.py + explain + the
propagation sites in serving/live/tenancy).

Four layers:

1. the context mechanics — deterministic ids, arming discipline,
   chaining semantics, schema validation at the emit site;
2. propagation through the real subsystems, happy path AND the fault
   matrix (shed, expired, torn publish, tenant batch failure, live
   poison-quarantine): every outcome leaves a COMPLETE linked span
   tree in the trail, refusals included;
3. the read side — ``observe explain`` reconstructs trees from the
   JSONL alone (jax-free, pinned by a poisoned-jax subprocess), the
   tail filters slice by tenant/trace, flight records carry the
   structural tenant + trace attribution;
4. the zero-overhead contract — disarmed tracing leaves the production
   step's jaxpr byte-identical (``tracing_disarmed`` in the contract
   registry).
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from tpu_als import obs
from tpu_als.obs import report, tracing
from tpu_als.obs import explain as explain_mod
from tpu_als.obs.trace import FlightRecorder
from tpu_als.resilience import faults
from tpu_als.serving import DeadlineExceeded, Overloaded, ServingEngine

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh():
    """Disarmed faults + tracing, fresh registry, counter reset to a
    known seed so span/trace ids in assertions are literal."""
    faults.clear()
    tracing.disable_tracing()
    tracing.reset_trace_ids(seed=0)
    reg = obs.reset()
    yield reg
    faults.clear()
    tracing.disable_tracing()


def _spans(reg):
    return [e for e in reg._events if e.get("type") == "trace_span"]


def _engine(rng, n=30, Ni=60, r=8, k=5, **kw):
    eng = ServingEngine(k=k, buckets=(8,), shortlist_k=16,
                        max_wait_s=0.0, **kw)
    U = rng.normal(size=(n, r)).astype(np.float32)
    V = rng.normal(size=(Ni, r)).astype(np.float32)
    eng.publish(U, V)
    return eng, U, V


def _drain_one(eng):
    batch = eng.batcher.next_batch(timeout=1.0)
    assert batch is not None
    eng.serve_batch(batch)
    return batch


# ---------------------------------------------------------------------------
# 1. context mechanics


def test_disarmed_is_the_default_and_mints_nothing(_fresh, rng):
    assert not tracing.tracing_armed()
    assert tracing.start_trace("serve.admit") is None
    assert tracing.record_span(None, "serve.queue") is None
    eng, _, _ = _engine(rng)
    t = eng.submit(0)
    _drain_one(eng)
    t.result(timeout=1.0)
    assert t.trace is None
    assert not _spans(_fresh)


def test_deterministic_ids_replay(_fresh):
    with tracing.traced():
        a = tracing.start_trace("serve.admit")
        b = tracing.record_span(a, "serve.queue")
        first = (a.trace_id, a.span_id, b.span_id)
        tracing.reset_trace_ids(seed=0)
        a2 = tracing.start_trace("serve.admit")
        b2 = tracing.record_span(a2, "serve.queue")
    assert (a2.trace_id, a2.span_id, b2.span_id) == first
    # a different seed produces a disjoint id namespace
    tracing.reset_trace_ids(seed=7)
    with tracing.traced():
        c = tracing.start_trace("serve.admit")
    assert c.trace_id.startswith("t07-")
    assert c.trace_id != a.trace_id


def test_chaining_links_parent_ids(_fresh):
    with tracing.traced():
        ctx = tracing.start_trace("serve.admit", tenant="a")
        child = tracing.record_span(ctx, "serve.queue", seconds=0.5)
    assert child.trace_id == ctx.trace_id
    assert child.parent_id == ctx.span_id
    assert child.tenant == "a"
    evs = _spans(_fresh)
    assert [e["name"] for e in evs] == ["serve.admit", "serve.queue"]
    assert evs[0]["parent_id"] is None
    assert evs[1]["parent_id"] == evs[0]["span_id"]
    assert all(e["tenant"] == "a" for e in evs)


def test_undeclared_span_name_and_status_raise(_fresh):
    with tracing.traced():
        with pytest.raises(KeyError, match="TRACE_SPANS"):
            tracing.start_trace("serve.bogus")
        ctx = tracing.start_trace("serve.admit")
        with pytest.raises(ValueError, match="undeclared status"):
            tracing.record_span(ctx, "serve.queue", status="meh")


def test_traced_scope_restores_prior_state(_fresh):
    assert not tracing.tracing_armed()
    with tracing.traced():
        assert tracing.tracing_armed()
        with tracing.traced():         # nested arming stays armed
            assert tracing.tracing_armed()
        assert tracing.tracing_armed()
    assert not tracing.tracing_armed()
    tracing.enable_tracing()
    with tracing.traced():
        pass
    assert tracing.tracing_armed()     # pre-armed state is restored


def test_env_flag_arms(monkeypatch, _fresh):
    monkeypatch.setenv("TPU_ALS_TRACE", "1")
    assert tracing.tracing_armed()
    assert tracing.start_trace("serve.admit") is not None
    monkeypatch.setenv("TPU_ALS_TRACE", "0")
    assert not tracing.tracing_armed()


# ---------------------------------------------------------------------------
# 2. propagation under the fault matrix


def test_serve_happy_path_full_chain(rng, _fresh):
    with tracing.traced():
        eng, _, _ = _engine(rng)
        t = eng.submit(0)
        _drain_one(eng)
        t.result(timeout=1.0)
    evs = _spans(_fresh)
    assert [e["name"] for e in evs] == \
        ["serve.admit", "serve.queue", "serve.score"]
    assert len({e["trace_id"] for e in evs}) == 1
    for parent, child in zip(evs, evs[1:]):
        assert child["parent_id"] == parent["span_id"]
    score = evs[-1]
    assert score["seconds"] is not None and score["path"] in \
        ("int8", "exact")


def test_serve_shed_is_traced(rng, _fresh):
    with tracing.traced():
        eng, _, _ = _engine(rng, max_queue=2)
        with pytest.raises(Overloaded):
            for _ in range(10):        # engine loop not running
                eng.submit(0)
    evs = _spans(_fresh)
    shed = [e for e in evs if e["status"] == "shed"]
    assert shed and shed[-1]["name"] == "serve.queue"
    # the shed queue hop chains off ITS request's admission span
    admit = {e["span_id"]: e for e in evs if e["name"] == "serve.admit"}
    assert shed[-1]["parent_id"] in admit
    fl = [e for e in _fresh._events if e["type"] == "flight_record"]
    assert fl[-1]["status"] == "shed"
    assert fl[-1]["trace_id"] == shed[-1]["trace_id"]


def test_serve_expired_is_traced(rng, _fresh):
    with tracing.traced():
        eng, _, _ = _engine(rng)
        t_dead = eng.submit(0, deadline_s=0.0)
        t_ok = eng.submit(1)
        time.sleep(0.01)
        _drain_one(eng)
        with pytest.raises(DeadlineExceeded):
            t_dead.result(timeout=1.0)
        t_ok.result(timeout=1.0)
    evs = _spans(_fresh)
    expired = [e for e in evs if e["name"] == "serve.expired"]
    assert len(expired) == 1 and expired[0]["status"] == "expired"
    # both requests still have complete trees: admit -> queue -> leaf
    by_trace = {}
    for e in evs:
        by_trace.setdefault(e["trace_id"], []).append(e["name"])
    assert sorted(tuple(v) for v in by_trace.values()) == sorted([
        ("serve.admit", "serve.queue", "serve.expired"),
        ("serve.admit", "serve.queue", "serve.score")])


def test_torn_publish_degraded_serve_is_traced(rng, _fresh):
    """A torn publish (fresh index dropped, stale one carried) forces
    the exact-score fallback; the request that rode the degraded path
    says so in its own span tree."""
    faults.install("serving.publish=corrupt@nth=2")
    with tracing.traced():
        eng, U, V = _engine(rng)
        eng.publish(U, V)              # torn: carries the stale index
        t = eng.submit(0)
        _drain_one(eng)
        t.result(timeout=1.0)
    score = [e for e in _spans(_fresh) if e["name"] == "serve.score"]
    assert score and score[-1]["path"] == "exact"
    assert _fresh.snapshot()["counters"]["serving.fallback_exact"] == 1


def test_serve_score_raise_failed_span(rng, _fresh):
    faults.install("serving.score=raise@nth=1")
    with tracing.traced():
        eng, _, _ = _engine(rng)
        eng.start()
        try:
            t = eng.submit(0)
            with pytest.raises(Exception):
                t.result(timeout=5.0)
        finally:
            eng.stop()
    failed = [e for e in _spans(_fresh) if e["status"] == "failed"]
    assert failed and failed[-1]["name"] == "serve.score"
    assert failed[-1]["error"]


def test_tenancy_round_links_scheduler_pick(rng, _fresh):
    from tpu_als.tenancy import MultiTenantEngine, TenantOverloaded

    with tracing.traced():
        mte = MultiTenantEngine()
        mte.add_tenant("a", rng.normal(size=(20, 4)).astype(np.float32),
                       rng.normal(size=(15, 4)).astype(np.float32))
        mte.warmup("a")
        with mte:
            mte.recommend("a", 2, timeout=10.0)
    evs = _spans(_fresh)
    assert [e["name"] for e in evs] == \
        ["serve.admit", "serve.queue", "tenancy.round", "serve.score"]
    rd = evs[2]
    assert rd["round"] == 1 and rd["batch_rows"] == 1
    assert all(e["tenant"] == "a" for e in evs)
    for parent, child in zip(evs, evs[1:]):
        assert child["parent_id"] == parent["span_id"]


def test_tenant_overloaded_shed_is_traced(rng, _fresh):
    from tpu_als.tenancy import (MultiTenantEngine, TenantOverloaded,
                                 TenantSpec)

    with tracing.traced():
        mte = MultiTenantEngine()
        mte.add_tenant(TenantSpec(name="b", max_queue=2),
                       rng.normal(size=(20, 4)).astype(np.float32),
                       rng.normal(size=(15, 4)).astype(np.float32))
        with pytest.raises(TenantOverloaded):
            for _ in range(10):        # scheduler not running
                mte.submit("b", 2)
    shed = [e for e in _spans(_fresh) if e["status"] == "shed"]
    assert shed and shed[-1]["tenant"] == "b"


def test_tenant_batch_failure_failed_spans(rng, _fresh):
    from tpu_als.tenancy import MultiTenantEngine

    faults.install("serving.score=raise@every=1")
    with tracing.traced():
        mte = MultiTenantEngine()
        tn = mte.add_tenant(
            "c", rng.normal(size=(20, 4)).astype(np.float32),
            rng.normal(size=(15, 4)).astype(np.float32))
        tk = mte.submit("c", 1)
        mte._drain_round()             # one synchronous scheduler round
        assert tk.done()
        tn.engine.flight.dump("degraded")   # surface the ring
    evs = _spans(_fresh)
    names = [e["name"] for e in evs]
    assert names == ["serve.admit", "serve.queue", "tenancy.round",
                     "serve.score"]
    assert evs[-1]["status"] == "failed"
    fl = [e for e in _fresh._events if e["type"] == "flight_record"]
    assert fl[-1]["status"] == "failed"
    assert fl[-1]["tenant"] == "c"                 # structural label
    assert fl[-1]["trace_id"] == evs[-1]["trace_id"]


def _live_stack(rng, **updater_kw):
    import tpu_als
    from tpu_als.io.movielens import synthetic_movielens
    from tpu_als.live import LiveUpdater
    from tpu_als.stream.microbatch import FoldInServer

    frame = synthetic_movielens(40, 30, 400, seed=1)
    model = tpu_als.ALS(rank=4, maxIter=2, seed=1).fit(frame)
    eng = ServingEngine(k=5)
    eng.publish(np.asarray(model._U), np.asarray(model._V))
    srv = FoldInServer(model)
    up = LiveUpdater(eng, srv, max_batch=8, max_wait_ms=5.0,
                     **updater_kw)
    uids = np.asarray(model._user_map.ids)
    iids = np.asarray(model._item_map.ids)
    return up, uids, iids


def test_live_chain_poison_quarantine_and_breach(rng, _fresh):
    """One good and one poisoned rating through the REAL update loop:
    the good event's tree runs admit -> queue -> foldin -> publish ->
    visible; the poisoned one ENDS at quarantine; the breach event
    names the worst trace and the publish links its trace ids."""
    with tracing.traced():
        up, uids, iids = _live_stack(rng, slo_s=1e-9)
        up.start()
        try:
            up.submit(int(uids[0]), int(iids[0]), 4.0)
            up.submit(int(uids[1]), int(iids[1]), float("nan"))
            deadline = time.perf_counter() + 15.0
            while up.queue_depth and time.perf_counter() < deadline:
                time.sleep(0.02)
            time.sleep(0.2)
        finally:
            up.stop()
    by_trace = {}
    for e in _spans(_fresh):
        by_trace.setdefault(e["trace_id"], []).append(e)
    chains = {t: [e["name"] for e in evs] for t, evs in by_trace.items()}
    full = [t for t, names in chains.items()
            if names == ["live.admit", "live.queue", "live.foldin",
                         "live.publish", "live.visible"]]
    poisoned = [t for t, names in chains.items()
                if names == ["live.admit", "live.queue",
                             "live.quarantine"]]
    assert len(full) == 1 and len(poisoned) == 1
    q = by_trace[poisoned[0]][-1]
    assert q["status"] == "quarantined"
    # every tree is parent-linked end to end
    for evs in by_trace.values():
        for parent, child in zip(evs, evs[1:]):
            assert child["parent_id"] == parent["span_id"]
    breach = [e for e in _fresh._events
              if e["type"] == "live_freshness_breach"]
    assert breach and breach[-1]["trace_id"] == full[0]
    pub = [e for e in _fresh._events if e["type"] == "serving_publish"
           and e.get("trace_ids")]
    assert pub and pub[-1]["trace_ids"] == [full[0]]
    fl = [e for e in _fresh._events if e["type"] == "flight_record"
          and e.get("trace_ids")]
    assert fl and full[0] in fl[-1]["trace_ids"]


def test_live_shed_is_traced(rng, _fresh):
    with tracing.traced():
        up, uids, iids = _live_stack(rng, max_queue=2)
        with pytest.raises(Overloaded):   # loop not running: queue fills
            for j in range(10):
                up.submit(int(uids[0]), int(iids[0]), 3.0)
    shed = [e for e in _spans(_fresh)
            if e["name"] == "live.admit" and e["status"] == "shed"]
    assert shed


# ---------------------------------------------------------------------------
# 3. the read side: explain, tail filters, flight labels


def _traced_breach_rundir(rng, tmp_path):
    """A finalized run dir whose trail carries a complete live chain
    and a freshness breach — the explain acceptance fixture."""
    run_dir = str(tmp_path / "run")
    obs.configure(os.path.join(run_dir, "obs"))
    try:
        tracing.reset_trace_ids(seed=0)
        with tracing.traced():
            up, uids, iids = _live_stack(rng, slo_s=1e-9)
            up.start()
            try:
                up.submit(int(uids[0]), int(iids[0]), 4.0)
                deadline = time.perf_counter() + 15.0
                while up.queue_depth and time.perf_counter() < deadline:
                    time.sleep(0.02)
                time.sleep(0.2)
            finally:
                up.stop()
        obs.finalize()
    finally:
        obs.deconfigure()
    return run_dir


def test_explain_reconstructs_breach_tree_from_jsonl(rng, tmp_path,
                                                     _fresh):
    run_dir = _traced_breach_rundir(rng, tmp_path)
    out = explain_mod.explain(run_dir, breach="last")
    assert out.startswith("breach: ") and "freshness_breach" in out
    for hop in ("live.admit", "live.queue", "live.foldin",
                "live.publish", "live.visible"):
        assert hop in out
    # indentation encodes the causal nesting: visible is the deepest
    lines = out.splitlines()
    depth = {ln.strip().lstrip("└─ ").split()[0]: len(ln) - len(ln.lstrip())
             for ln in lines if "live." in ln}
    assert depth["live.visible"] > depth["live.foldin"] \
        > depth["live.admit"]
    # the publish this trace rode is cross-referenced
    assert "serving_publish names this trace" in out
    # --trace renders the same tree; unknown ids are typed errors
    tid = next(ln.split()[1].rstrip(":") for ln in lines
               if ln.startswith("trace "))
    assert "live.visible" in explain_mod.explain(run_dir, trace=tid)
    with pytest.raises(ValueError, match="not in the trail"):
        explain_mod.explain(run_dir, trace="t99-ffffffff")
    # no selector: the per-trace index
    assert tid in explain_mod.explain(run_dir)


def test_explain_cli(rng, tmp_path, _fresh, capsys):
    from tpu_als.cli import main as cli_main

    run_dir = _traced_breach_rundir(rng, tmp_path)
    cli_main(["observe", "explain", run_dir, "--breach", "last"])
    out = capsys.readouterr().out
    assert "live.visible" in out and "breach" in out
    with pytest.raises(SystemExit):
        cli_main(["observe", "explain", str(tmp_path / "nope")])


def test_explain_is_jax_free(rng, tmp_path, _fresh):
    """The explain module must run standalone on a host with no jax —
    a breach is diagnosed from a copied run dir, not the serving host."""
    run_dir = _traced_breach_rundir(rng, tmp_path)
    poison = tmp_path / "poison"
    poison.mkdir()
    (poison / "jax.py").write_text(
        'raise ImportError("jax must not be imported by observe '
        'explain")\n')
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "tpu_als", "obs",
                                      "explain.py"),
         run_dir, "--breach", "last"],
        capture_output=True, text=True,
        env={**os.environ, "PYTHONPATH": str(poison)})
    assert p.returncode == 0, p.stdout + p.stderr
    assert "live.visible" in p.stdout


def test_explain_breach_on_breach_free_trail_is_typed(tmp_path):
    d = tmp_path / "obs"
    d.mkdir()
    (d / "events.jsonl").write_text(json.dumps(
        {"ts": 1, "type": "trace_span", "trace_id": "t1", "span_id": "a",
         "parent_id": None, "name": "serve.admit", "status": "ok",
         "seconds": None}) + "\n")
    with pytest.raises(ValueError, match="no breach-shaped"):
        explain_mod.explain(str(d), breach="last")


def test_tail_filters_tenant_and_trace(tmp_path, _fresh):
    d = tmp_path / "obs"
    d.mkdir()
    rows = [
        {"ts": 1, "type": "trace_span", "trace_id": "t1", "span_id": "a",
         "parent_id": None, "name": "serve.admit", "status": "ok",
         "seconds": None, "tenant": "x"},
        {"ts": 2, "type": "trace_span", "trace_id": "t2", "span_id": "b",
         "parent_id": None, "name": "serve.admit", "status": "ok",
         "seconds": None, "tenant": "y"},
        {"ts": 3, "type": "serving_publish", "seq": 4, "mode": "retag",
         "items": 9, "seconds": 0.1, "trace_ids": ["t1"]},
    ]
    with open(d / "events.jsonl", "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
    by_tenant = report.cmd_tail(str(d), tenant="x")
    assert "t1" in by_tenant and "t2" not in by_tenant
    by_trace = [json.loads(ln) for ln in
                report.cmd_tail(str(d), trace="t1").splitlines()]
    # trace filter matches trace_id AND trace_ids membership
    assert {e["type"] for e in by_trace} == \
        {"trace_span", "serving_publish"}
    assert all("t2" not in json.dumps(e) for e in by_trace)
    # filters compose with -n: last 1 of tenant x's events only
    assert len(report.cmd_tail(str(d), n=1, tenant="x").splitlines()) \
        == 1


def test_flight_recorder_structural_labels(_fresh):
    rec = FlightRecorder(capacity=4, span_keys=("a",),
                         labels={"tenant": "z"})
    rec.record("ok", {"a": 0.1}, trace_id="t1")
    rec.dump("slo_breach")
    evs = [e for e in _fresh._events if e["type"] == "flight_record"]
    assert evs and evs[-1]["tenant"] == "z"
    assert evs[-1]["trace_id"] == "t1"


def test_scenario_runner_arms_tracing_scoped(_fresh):
    from tpu_als.scenario.library import Phase, ScenarioSpec
    from tpu_als.scenario.runner import run_scenario

    seen = {}

    def probe(ctx):
        seen["armed"] = tracing.tracing_armed()
        seen["ctx"] = tracing.start_trace("serve.admit")

    spec = ScenarioSpec(name="t", doc="d", defaults={},
                        phases=(Phase("p", probe, "probe arming"),),
                        assertions=())
    assert not tracing.tracing_armed()
    result = run_scenario(spec, registry=_fresh)
    assert result["passed"]
    assert seen["armed"] and seen["ctx"] is not None
    assert not tracing.tracing_armed()     # restored after the run


def test_trace_vocabulary_static_checks():
    from tpu_als.analysis import vocab

    assert vocab.check_trace_vocabulary() == []
    assert vocab.check_tenant_vocabulary() == []


def test_vocab_flags_undeclared_span_literal(tmp_path):
    from tpu_als.analysis import vocab

    bad = tmp_path / "bad.py"
    bad.write_text(
        "from tpu_als.obs import tracing\n"
        'ctx = tracing.start_trace("serve.nonsense")\n'
        'tracing.record_span(ctx, "live.bogus", seconds=1.0)\n')
    msgs = [m for _, m in vocab.check_file(str(bad))]
    assert len(msgs) == 2
    assert all("TRACE_SPANS" in m for m in msgs)


# ---------------------------------------------------------------------------
# 4. zero overhead disarmed


def test_tracing_disarmed_step_jaxpr_byte_identical():
    from tpu_als.analysis import contracts

    result = contracts.verify("tracing_disarmed")
    assert result.ok, result.detail
