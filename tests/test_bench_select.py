"""bench.py's sweep-evidence auto-selection: the driver's end-of-round
capture must pick the fastest VALIDATED configuration the opportunistic
sweep measured, and never an unvalidated one."""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench


def _write(d, name, payload):
    with open(os.path.join(d, name + ".out"), "w") as f:
        f.write("some stderr-ish line\n")
        f.write(json.dumps(payload) + "\n")


def test_no_evidence_keeps_defaults(tmp_path):
    assert bench.best_measured_flags(str(tmp_path)) is None


def test_fastest_validated_wins(tmp_path):
    d = str(tmp_path)
    _write(d, "headline_f32", {"value": 0.75, "unit": "iters/sec"})
    _write(d, "headline_cg2", {"value": 2.4, "unit": "iters/sec"})
    _write(d, "headline_bf16", {"value": 0.9, "unit": "iters/sec"})
    _write(d, "rmse_cg2", {"value": 0.44, "unit": "rmse_stars"})
    assert bench.best_measured_flags(d) == {"cg_iters": 2}


def test_cg_winner_requires_quality_evidence(tmp_path):
    d = str(tmp_path)
    _write(d, "headline_f32", {"value": 0.75})
    _write(d, "headline_cg2", {"value": 2.4})
    # no rmse_cg2 at all -> keep defaults
    assert bench.best_measured_flags(d) is None
    # quality evidence exists but fails the gate -> keep defaults
    _write(d, "rmse_cg2", {"value": 0.9})
    assert bench.best_measured_flags(d) is None
    # passing quality unlocks the cg winner
    _write(d, "rmse_cg2", {"value": 0.43})
    assert bench.best_measured_flags(d) == {"cg_iters": 2}


def test_error_steps_are_ignored(tmp_path):
    d = str(tmp_path)
    _write(d, "headline_cg2", {"value": None, "error": "tunnel died"})
    _write(d, "headline_f32", {"value": 0.7})
    assert bench.best_measured_flags(d) == {}


def test_quality_neutral_winner_needs_no_gate(tmp_path):
    # wg15 changes padding only (masked rows) — numerics-identical, so
    # it is selectable without extra quality evidence
    d = str(tmp_path)
    _write(d, "headline_wg15", {"value": 1.1})
    assert bench.best_measured_flags(d) == {"width_growth": 1.5}


def test_configs_without_quality_evidence_never_selected(tmp_path):
    # a speed win without its matching quality step must NOT auto-select;
    # cg3/cg2_dense have no step at all and are never eligible
    d = str(tmp_path)
    _write(d, "headline_bf16_wg15", {"value": 9.9})
    _write(d, "headline_cg2_bf16", {"value": 9.8})
    _write(d, "headline_cg3", {"value": 9.9})
    _write(d, "headline_f32", {"value": 0.7})
    # the fastest eligible config lacks its quality step -> defaults
    # (no silent demotion to a slower validated one)
    assert bench.best_measured_flags(d) is None


def test_per_config_quality_steps_unlock_their_winner(tmp_path):
    d = str(tmp_path)
    _write(d, "headline_cg2_bf16", {"value": 9.8})
    _write(d, "headline_cg2", {"value": 2.4})
    _write(d, "rmse_cg2", {"value": 0.43})
    # the faster cg2_bf16 lacks ITS quality step -> whole selection
    # falls back to defaults (the winner is unvalidated, and silently
    # demoting to a slower validated config would misattribute)
    assert bench.best_measured_flags(d) is None
    _write(d, "rmse_cg2_bf16", {"value": 0.45})
    assert bench.best_measured_flags(d) == {
        "cg_iters": 2, "compute_dtype": "bfloat16"}


def test_provenance_static_fallback_when_no_sweep(tmp_path):
    # a dead-tunnel error JSON must still carry the committed
    # builder-measured record (VERDICT r3 #1)
    p = bench.builder_measured_provenance("headline", str(tmp_path))
    assert p["value"] == 0.8449
    assert p["source_log"] == "sweep_logs/headline_f32.out"
    assert "pallas_lanes" in p["resolved_config"]


def test_provenance_prefers_fresh_sweep_evidence(tmp_path):
    d = str(tmp_path)
    _write(d, "headline_cg2", {"value": 2.4, "unit": "iters/sec",
                               "vs_baseline": 144.0})
    _write(d, "rmse_cg2", {"value": 0.44, "unit": "rmse_stars"})
    p = bench.builder_measured_provenance("headline", d)
    assert p["value"] == 2.4 and "headline_cg2" in p["source_log"]


def test_provenance_headline_requires_quality_evidence(tmp_path):
    # an unvalidated numerics-changing sweep winner must not become the
    # advertised provenance number either (same bar as auto-selection)
    d = str(tmp_path)
    _write(d, "headline_bf16", {"value": 2.0, "unit": "iters/sec"})
    _write(d, "headline_f32", {"value": 0.8, "unit": "iters/sec"})
    p = bench.builder_measured_provenance("headline", d)
    assert p["value"] == 0.8  # bf16 lacks rmse_bf16 -> ineligible
    _write(d, "rmse_bf16", {"value": 0.44, "unit": "rmse_stars"})
    p = bench.builder_measured_provenance("headline", d)
    assert p["value"] == 2.0


def test_provenance_lower_is_better_for_rmse(tmp_path):
    d = str(tmp_path)
    _write(d, "rmse", {"value": 0.45, "unit": "rmse_stars"})
    _write(d, "rmse_cg2", {"value": 0.43, "unit": "rmse_stars"})
    p = bench.builder_measured_provenance("rmse", d)
    assert p["value"] == 0.43


def test_error_json_embeds_provenance():
    import argparse

    args = argparse.Namespace(mode="headline", rank=128, small=False)
    j = bench.error_json(args, "m", "u", "tunnel down")
    assert j["value"] is None
    lb = j["last_builder_measured"]
    assert lb is not None and lb["value"] is not None


def test_ml100k_mode_registered():
    # BASELINE config-1 row: the mode must exist in the CLI surface and
    # its sweep step must transport through provenance like the others
    import subprocess

    p = subprocess.run(
        [sys.executable, "bench.py", "--mode", "nonsense"],
        capture_output=True, text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert "ml100k" in p.stderr  # argparse lists valid choices


def test_ml100k_provenance_transports(tmp_path):
    d = str(tmp_path)
    _write(d, "ml100k", {"value": 2.1, "unit": "seconds_fit_wallclock"})
    p = bench.builder_measured_provenance("ml100k", d)
    assert p["value"] == 2.1


def test_serve_provenance_gates_bf16_on_overlap(tmp_path):
    d = str(tmp_path)
    _write(d, "serve", {"value": 50000.0, "unit": "users/sec"})
    # faster bf16 but below the overlap gate: f32 number must win
    _write(d, "serve_bf16", {"value": 90000.0, "unit": "users/sec",
                             "config": {"topk_overlap_vs_f32": 0.80}})
    p = bench.builder_measured_provenance("serve", d)
    assert p["value"] == 50000.0
    # at/above the gate the faster validated number carries
    _write(d, "serve_bf16", {"value": 90000.0, "unit": "users/sec",
                             "config": {"topk_overlap_vs_f32": 0.995}})
    p = bench.builder_measured_provenance("serve", d)
    assert p["value"] == 90000.0
    # overlap missing entirely -> never counted
    _write(d, "serve_bf16", {"value": 90000.0, "unit": "users/sec",
                             "config": {}})
    assert bench.builder_measured_provenance("serve", d)["value"] == 50000.0


def test_serve_gate_keys_on_evidence_not_filename(tmp_path):
    # a bf16 result landing in serve.out (re-run with --compute-dtype)
    # must face the same overlap gate as serve_bf16.out
    d = str(tmp_path)
    _write(d, "serve", {"value": 90000.0, "unit": "users/sec",
                        "config": {"compute_dtype": "bfloat16"}})
    # overlap-less bf16 evidence is gated OUT: provenance degrades to the
    # static builder-measured record, never to the unvalidated number
    prov = bench.builder_measured_provenance("serve", d)
    assert prov["value"] != 90000.0
    assert prov == bench._BUILDER_MEASURED["serve"]
    _write(d, "serve", {"value": 90000.0, "unit": "users/sec",
                        "config": {"compute_dtype": "bfloat16",
                                   "topk_overlap_vs_f32": 0.99}})
    assert bench.builder_measured_provenance("serve", d)["value"] == 90000.0


def _args(**kw):
    import argparse

    d = dict(ab="", ab_dir="", small=False)
    d.update(kw)
    return argparse.Namespace(**d)


def test_ab_specs_parse_known_and_reject_unknown():
    assert bench._ab_specs(_args()) == []
    specs = bench._ab_specs(_args(ab="exact,cg2,cg2_bf16"))
    assert [s for s, _ in specs] == ["exact", "cg2", "cg2_bf16"]
    assert specs[0][1] == {}
    assert specs[1][1] == {"cg_iters": 2}
    assert specs[2][1] == {"cg_iters": 2, "compute_dtype": "bfloat16"}
    try:
        bench._ab_specs(_args(ab="warp9"))
    except SystemExit:
        pass
    else:
        raise AssertionError("unknown spec must be rejected")


def test_ab_banks_into_canonical_logs(tmp_path):
    # the file the combined A/B writes is EXACTLY the file auto-selection
    # reads for that config — a variant banked by --ab is equivalent
    # evidence to a dedicated sweep step run
    res = {"value": 0.9, "unit": "iters/sec", "config": {}}
    bench._bank_variant("headline", "cg2", str(tmp_path), res, "m")
    assert bench._last_json(
        str(tmp_path / "headline_cg2.out"))["value"] == 0.9
    bench._bank_variant("rmse", "cg2", str(tmp_path),
                        {"value": 0.44, "config": {}}, "m")
    assert bench._last_json(str(tmp_path / "rmse_cg2.out"))["value"] == 0.44
    # exact maps to the canonical step names
    bench._bank_variant("headline", "exact", str(tmp_path), res, "m")
    assert bench._last_json(str(tmp_path / "headline_f32.out"))
    bench._bank_variant("rmse", "exact", str(tmp_path),
                        {"value": 0.43, "config": {}}, "m")
    assert bench._last_json(str(tmp_path / "rmse.out"))


def test_ab_never_banks_small_or_error_runs(tmp_path):
    bench._bank_variant("headline", "cg2", str(tmp_path),
                        {"value": 0.9, "config": {}}, "m", small=True)
    bench._bank_variant("headline", "cg3", str(tmp_path),
                        {"value": None, "config": {}}, "m")
    assert not (tmp_path / "headline_cg2.out").exists()
    assert not (tmp_path / "headline_cg3.out").exists()


def test_ab_banked_evidence_drives_auto_selection(tmp_path):
    # end-to-end contract: one combined A/B run's banked files are enough
    # for best_measured_flags to pick the validated winner
    _write(tmp_path, "headline_f32", {"value": 0.85})
    _write(tmp_path, "headline_cg2", {"value": 2.1, "banked_by":
                                      "headline --ab"})
    _write(tmp_path, "rmse_cg2", {"value": 0.44, "banked_by": "rmse --ab"})
    assert bench.best_measured_flags(str(tmp_path)) == {"cg_iters": 2}


def test_ab_retry_skips_banked_and_flags_partial_failure(tmp_path):
    import argparse

    # prior evidence: cg2 banked by an earlier (partial) A/B run
    _write(tmp_path, "headline_cg2", {"value": 2.0, "metric": "m",
                                      "banked_by": "headline --ab",
                                      "config": {"seconds_per_iter": 0.5}})
    calls = []

    def measure(overrides):
        calls.append(dict(overrides))
        if overrides.get("cg_iters") == 3:
            raise RuntimeError("tunnel died")
        return {"value": 1.0, "unit": "u",
                "config": {"seconds_per_iter": 1.0}}

    args = argparse.Namespace(ab="", ab_dir=str(tmp_path), small=False)
    specs = [("cg2", {"cg_iters": 2}), ("exact", {}),
             ("cg3", {"cg_iters": 3})]
    res = bench._run_ab(specs, measure, "headline", "m", args,
                        "seconds_per_iter")
    # cg2 skipped (banked), exact measured, cg3 failed -> error surfaces
    assert calls == [{}, {"cg_iters": 3}]
    assert res["config"]["ab"]["cg2"]["banked"] == "prior run"
    assert "cg3" in res["error"]
    # a --small line in the canonical log is NOT prior evidence
    _write(tmp_path, "headline_bf16", {"value": 9.9, "metric": "m_small",
                                       "banked_by": "headline --ab"})
    assert bench._already_banked("headline", "bf16", str(tmp_path)) is None


def test_ab_banking_requires_canonical_base_flags():
    import argparse

    args = argparse.Namespace(ab="cg2", ab_dir="sweep_logs", small=False,
                              cg_iters=0, cg_mode="matfree",
                              compute_dtype="bfloat16", width_growth=2.0,
                              solve_backend="auto", rank=128, iters=5,
                              iters_rmse=12, reg=0.02)
    try:
        bench._check_ab_bankable(args, "headline")
    except SystemExit as e:
        assert "compute_dtype" in str(e)
    else:
        raise AssertionError("off-default base flag must refuse banking")
    args.compute_dtype = "float32"
    bench._check_ab_bankable(args, "headline")   # canonical flags pass
    args.ab_dir = ""
    args.cg_iters = 2
    bench._check_ab_bankable(args, "headline")   # no banking -> no check


def test_ab_banking_guards_model_and_scale_flags():
    """A rank-64 or short-iteration run banked under a canonical name
    would read downstream as full-scale rank-128 evidence (advisor r4,
    medium): every model/scale flag the name doesn't encode must sit at
    the sweep's canonical value."""
    import argparse

    def mk(**kw):
        base = dict(ab="cg2", ab_dir="d", small=False, cg_iters=0,
                    cg_mode="matfree", compute_dtype="float32",
                    width_growth=2.0, solve_backend="auto", rank=128,
                    iters=5, iters_rmse=12, reg=0.02)
        base.update(kw)
        return argparse.Namespace(**base)

    for mode, bad in [("headline", {"rank": 64}),
                      ("headline", {"iters": 3}),
                      ("rmse", {"rank": 64}),
                      ("rmse", {"iters_rmse": 8}),
                      ("rmse", {"reg": 0.1})]:
        try:
            bench._check_ab_bankable(mk(**bad), mode)
        except SystemExit as e:
            (key,) = bad
            assert key in str(e)
        else:
            raise AssertionError(f"{mode} {bad} must refuse banking")
    # iters is headline-only: an rmse run may carry any --iters value
    bench._check_ab_bankable(mk(iters=3), "rmse")


def test_bank_variant_stamps_absolute_banked_at(tmp_path):
    bench._bank_variant("headline", "cg2", str(tmp_path),
                        {"value": 0.9, "config": {}}, "m")
    line = bench._last_json(str(tmp_path / "headline_cg2.out"))
    banked_at = line["banked_at"]
    # absolute ISO-8601 UTC instant, never a relative phrase
    import datetime as dt

    parsed = dt.datetime.fromisoformat(banked_at)
    assert parsed.tzinfo is not None
    assert "round" not in banked_at and "sweep" not in banked_at


def test_provenance_transports_banked_at_verbatim(tmp_path):
    """A number banked in one round and transported into a later round's
    provenance block must keep its ORIGINAL bank-time stamp (VERDICT r5
    weak #1: relative phrases like 'this round (sweep)' go stale)."""
    stamp = "2026-08-01T08:32:10+00:00"
    _write(tmp_path, "headline_cg2",
           {"value": 2.4, "unit": "iters/sec", "banked_at": stamp})
    _write(tmp_path, "rmse_cg2", {"value": 0.44, "unit": "rmse_stars"})
    p = bench.builder_measured_provenance("headline", str(tmp_path))
    assert p["measured_at"] == stamp
    assert p["banked_at"] == stamp
    assert "this round" not in json.dumps(p)


def test_provenance_mtime_fallback_is_labeled(tmp_path):
    # legacy banked lines (no banked_at) fall back to the log file's
    # mtime, explicitly labeled so it can't be mistaken for a bank stamp
    _write(tmp_path, "headline_cg2", {"value": 2.4, "unit": "iters/sec"})
    _write(tmp_path, "rmse_cg2", {"value": 0.44, "unit": "rmse_stars"})
    p = bench.builder_measured_provenance("headline", str(tmp_path))
    assert p["measured_at"].endswith("(sweep log mtime)")
    assert p["banked_at"] is None


def test_already_banked_rejects_config_mismatch(tmp_path):
    """A stale or mislabeled banked line (wrong rank or non-ML-25M
    shape) must not short-circuit a real retry (advisor r4, low)."""
    full = {"rank": 128, "users": 162541, "items": 59047}
    _write(tmp_path, "headline_cg2",
           {"value": 2.0, "metric": "m", "config": {**full, "rank": 64}})
    assert bench._already_banked("headline", "cg2", str(tmp_path)) is None
    _write(tmp_path, "headline_cg2",
           {"value": 2.0, "metric": "m",
            "config": {**full, "users": 6501, "items": 2361}})
    assert bench._already_banked("headline", "cg2", str(tmp_path)) is None
    _write(tmp_path, "headline_cg2",
           {"value": 2.0, "metric": "m", "config": full})
    got = bench._already_banked("headline", "cg2", str(tmp_path))
    assert got is not None and got["value"] == 2.0
    # a legacy line with no config fields cannot contradict -> accepted
    _write(tmp_path, "headline_cg3", {"value": 3.0, "metric": "m"})
    assert bench._already_banked(
        "headline", "cg3", str(tmp_path))["value"] == 3.0
    # rmse mode additionally pins its iteration count and reg: a short
    # 8-iter (or off-reg) line must not stand in for the 12-iter gate
    rcfg = {"rank": 128, "users": 162541, "items": 59047,
            "iters": 12, "reg_param": 0.02}
    for bad in ({"iters": 8}, {"reg_param": 0.1}):
        _write(tmp_path, "rmse_cg2",
               {"value": 0.44, "metric": "m", "config": {**rcfg, **bad}})
        assert bench._already_banked("rmse", "cg2", str(tmp_path)) is None
    _write(tmp_path, "rmse_cg2",
           {"value": 0.44, "metric": "m", "config": rcfg})
    assert bench._already_banked(
        "rmse", "cg2", str(tmp_path))["value"] == 0.44
