"""CLI + observability tests (CPU mesh)."""

import pytest
import json

import numpy as np

from tpu_als.cli import main as cli_main
from tpu_als.utils.observe import IterationLogger


@pytest.mark.slow
def test_cli_train_evaluate_recommend(tmp_path, capsys):
    model_dir = str(tmp_path / "m")
    cli_main(["train", "--data", "synthetic:200x80x4000", "--rank", "4",
              "--max-iter", "4", "--reg-param", "0.05",
              "--output", model_dir])
    out = capsys.readouterr().out.strip().splitlines()
    rmse = json.loads(out[-1])["holdout_rmse"]
    assert 0 < rmse < 2.0

    cli_main(["evaluate", "--model", model_dir,
              "--data", "synthetic:200x80x4000"])
    metrics = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert set(metrics) == {"rmse", "mae", "r2"}

    cli_main(["recommend", "--model", model_dir, "--limit", "2", "--k", "3"])
    lines = [json.loads(x) for x in
             capsys.readouterr().out.strip().splitlines()]
    assert len(lines) == 2
    assert len(lines[0]["items"]) == 3

    # subset recommend for users known to be in the model
    known = f'{lines[0]["user"]},{lines[1]["user"]}'
    cli_main(["recommend", "--model", model_dir, "--users", known,
              "--k", "3"])
    lines2 = [json.loads(x) for x in
              capsys.readouterr().out.strip().splitlines()]
    assert len(lines2) == 2


def test_cli_per_host_data_single_process_rejected():
    import pytest

    with pytest.raises(SystemExit, match="multi-process only"):
        cli_main(["train", "--data", "synthetic:50x20x500",
                  "--per-host-data"])


def test_cli_foldin_bench(tmp_path, capsys):
    model_dir = str(tmp_path / "m")
    cli_main(["train", "--data", "synthetic:100x50x2000", "--rank", "3",
              "--max-iter", "2", "--output", model_dir])
    capsys.readouterr()
    cli_main(["foldin-bench", "--model", model_dir, "--batches", "3",
              "--batch-size", "32"])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["metric"] == "foldin_p50_latency"
    assert np.isfinite(out["value"])


def test_iteration_logger(tmp_path, rng):
    from tpu_als.core.als import AlsConfig, train
    from tpu_als.core.ratings import build_csr_buckets
    from conftest import make_ratings

    u, i, r, _, _ = make_ratings(rng, 30, 20, rank=2, density=0.5)
    log_path = str(tmp_path / "train.jsonl")
    logger = IterationLogger(probe=(u, i, r), stream=None, path=log_path)
    cfg = AlsConfig(rank=2, max_iter=3, seed=0)
    train(build_csr_buckets(u, i, r, 30), build_csr_buckets(i, u, r, 20),
          cfg, callback=logger)
    logger.close()
    recs = [json.loads(x) for x in open(log_path)]
    assert len(recs) == 3
    assert recs[-1]["probe_rmse"] < recs[0]["probe_rmse"]
    assert all("seconds" in x for x in recs)


@pytest.mark.slow
def test_cli_tune(tmp_path, capsys):
    import json

    out_dir = str(tmp_path / "best")
    cli_main(["tune", "--data", "synthetic:150x60x3000",
              "--ranks", "2,4", "--reg-params", "0.01",
              "--max-iter", "3", "--folds", "2", "--output", out_dir])
    line = capsys.readouterr().out.strip().splitlines()[0]
    res = json.loads(line)
    assert res["best_rank"] in (2, 4)
    assert res["grid_size"] == 2
    assert len(res["avg_metrics"]) == 2

    from tpu_als.api.tuning import CrossValidatorModel

    loaded = CrossValidatorModel.load(out_dir)
    assert int(loaded.bestModel._params["rank"]) == res["best_rank"]


@pytest.mark.slow
def test_cli_train_profile_dir(tmp_path, capsys):
    prof = str(tmp_path / "prof")
    cli_main(["train", "--data", "synthetic:100x40x1500", "--rank", "3",
              "--max-iter", "2", "--profile-dir", prof])
    import os

    assert os.path.isdir(prof) and os.listdir(prof)  # trace files exist


def test_cli_recommend_with_foldin(tmp_path, capsys):
    """The full serving flow in ONE CLI command (VERDICT r3 #7): load a
    saved model -> FoldInServer folds a csv of new ratings -> top-k for
    the folded-in NEW user.  The new user duplicates an existing user's
    ratings, so their folded factor must score their own rated items
    higher than the catalog median (the fold-in ridge solve fits them)."""
    import numpy as np

    from tpu_als import ALSModel
    from tpu_als.io.movielens import synthetic_movielens

    model_dir = str(tmp_path / "m")
    cli_main(["train", "--data", "synthetic:200x80x4000", "--rank", "4",
              "--max-iter", "4", "--seed", "0", "--output", model_dir])
    capsys.readouterr()

    # new user id far outside training, rating real catalog items highly
    model = ALSModel.load(model_dir)
    item_ids = model._item_map.ids[:6]
    new_user = int(model._user_map.ids.max()) + 1000
    csv_path = tmp_path / "new_ratings.csv"
    lines = ["userId,movieId,rating,timestamp"]
    for it in item_ids:
        lines.append(f"{new_user},{int(it)},5.0,0")
    csv_path.write_text("\n".join(lines) + "\n")

    cli_main(["recommend", "--model", model_dir,
              "--foldin-data", f"csv:{csv_path}",
              "--users", str(new_user), "--k", "5"])
    out = [json.loads(ln)
           for ln in capsys.readouterr().out.strip().splitlines()]
    assert len(out) == 1 and out[0]["user"] == new_user
    items = out[0]["items"]
    assert len(items) == 5
    assert all(np.isfinite(s) for _, s in items)
    scores = [s for _, s in items]
    assert scores == sorted(scores, reverse=True)


def test_cli_recommend_with_item_foldin(tmp_path, capsys):
    """--foldin-items-data: a brand-new ITEM folded against fixed user
    factors surfaces in a known user's top-k when they are its best
    match (the symmetric serving direction)."""
    import numpy as np

    from tpu_als import ALSModel

    model_dir = str(tmp_path / "m")
    cli_main(["train", "--data", "synthetic:150x60x3000", "--rank", "4",
              "--max-iter", "4", "--seed", "0", "--output", model_dir])
    capsys.readouterr()

    model = ALSModel.load(model_dir)
    raters = model._user_map.ids[:8]
    new_item = 10 ** 6
    csv_path = tmp_path / "new_item.csv"
    lines = ["userId,movieId,rating,timestamp"]
    for u in raters:
        lines.append(f"{int(u)},{new_item},5.0,0")
    csv_path.write_text("\n".join(lines) + "\n")

    cli_main(["recommend", "--model", model_dir,
              "--foldin-items-data", f"csv:{csv_path}",
              "--users", str(int(raters[0])), "--k", "60"])
    out = [json.loads(ln)
           for ln in capsys.readouterr().out.strip().splitlines()]
    assert len(out) == 1
    items = [i for i, _ in out[0]["items"]]
    assert new_item in items  # the folded item is in the candidate set


@pytest.mark.slow
def test_cli_tune_alpha_grid(tmp_path, capsys):
    cli_main(["tune", "--data", "synthetic:100x40x2000",
              "--ranks", "3", "--reg-params", "0.02", "--implicit",
              "--alphas", "1.0,20.0", "--max-iter", "3", "--folds", "2"])
    line = json.loads(capsys.readouterr().out.strip().splitlines()[0])
    assert line["grid_size"] == 2
    assert line["best_alpha"] in (1.0, 20.0)


@pytest.mark.slow
def test_cli_evaluate_ranking_metrics(tmp_path, capsys):
    model_dir = str(tmp_path / "m")
    cli_main(["train", "--data", "synthetic:150x60x4000", "--rank", "6",
              "--max-iter", "5", "--seed", "0", "--output", model_dir])
    capsys.readouterr()
    cli_main(["evaluate", "--model", model_dir,
              "--data", "synthetic:150x60x4000", "--ranking-k", "5"])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    for key in ("rmse", "precision_at_5", "recall_at_5", "map",
                "ndcg_at_5", "ranking_users"):
        assert key in out, key
    assert 0.0 <= out["precision_at_5"] <= 1.0
    # evaluating ON the training data: a fitted model must rank its own
    # high-rated items far above the random floor (k/items ~ 0.08)
    assert out["recall_at_5"] > 0.05
    assert out["ranking_users"] > 0


@pytest.mark.slow
def test_cli_tt_train(tmp_path, capsys):
    out_dir = str(tmp_path / "towers")
    cli_main(["tt-train", "--data", "synthetic:300x100x8000",
              "--epochs", "2", "--embed-dim", "8", "--als-rank", "8",
              "--als-iters", "4", "--output", out_dir])
    line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert line["warm_start"] is True and line["saved"] == out_dir
    assert 0.0 <= line["filtered_recall_at_10"] <= 1.0

    from tpu_als.models.two_tower import load_two_tower, user_repr

    params, cfg, nU, nI = load_two_tower(out_dir)
    import numpy as np

    z = np.asarray(user_repr(params, np.arange(5)))
    assert z.shape == (5, cfg.out_dim) and np.isfinite(z).all()


def test_cli_evaluate_ranking_scores_cold_users_as_misses(tmp_path,
                                                          capsys):
    """A test split containing users the model never saw must count them
    as empty prediction lists (zero contribution), not silently drop
    them — dropping inflates every ranking metric (advisor r4)."""
    model_dir = str(tmp_path / "m")
    cli_main(["train", "--data", "synthetic:150x60x4000", "--rank", "6",
              "--max-iter", "5", "--seed", "0", "--output", model_dir])
    capsys.readouterr()
    # eval file = training interactions + positives for unknown users
    from tpu_als.io.movielens import synthetic_movielens

    frame = synthetic_movielens(150, 60, 4000, seed=0)
    csv_path = tmp_path / "eval.csv"
    lines = ["userId,movieId,rating,timestamp"]
    for u, i, r in zip(frame["user"], frame["item"], frame["rating"]):
        lines.append(f"{int(u)},{int(i)},{float(r)},0")
    n_cold = 7
    for cu in range(10 ** 6, 10 ** 6 + n_cold):  # ids absent from training
        lines.append(f"{cu},1,5.0,0")
    csv_path.write_text("\n".join(lines) + "\n")

    cli_main(["evaluate", "--model", model_dir,
              "--data", f"csv:{csv_path}", "--ranking-k", "5"])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["ranking_users_cold"] == n_cold
    # and the cold users are IN the averaged population
    cli_main(["evaluate", "--model", model_dir,
              "--data", "synthetic:150x60x4000", "--ranking-k", "5"])
    warm_only = json.loads(
        capsys.readouterr().out.strip().splitlines()[-1])
    assert out["ranking_users"] == warm_only["ranking_users"] + n_cold
    assert out["recall_at_5"] < warm_only["recall_at_5"]


def test_cli_tt_train_empty_holdout_emits_valid_json(capsys):
    """--holdout 0 leaves no test pairs; the metric must serialize as
    null, not the non-standard `NaN` token (advisor r4)."""
    cli_main(["tt-train", "--data", "synthetic:200x80x4000",
              "--epochs", "1", "--embed-dim", "8", "--cold",
              "--holdout", "0"])
    raw = capsys.readouterr().out.strip().splitlines()[-1]
    line = json.loads(raw)  # strict parse would fail on bare NaN
    assert "NaN" not in raw
    assert line["filtered_recall_at_10"] is None
    assert line["test_pairs"] == 0


def test_cli_recommend_titles_and_sharded(tmp_path, capsys):
    """--titles joins movie metadata into the output; --devices serves
    the all-users path through the sharded top-k (parallel/serve.py)."""
    model_dir = str(tmp_path / "m")
    cli_main(["train", "--data", "synthetic:120x50x3000", "--rank", "4",
              "--max-iter", "3", "--reg-param", "0.01",
              "--output", model_dir])
    capsys.readouterr()

    movies = tmp_path / "movies.csv"
    rows = ["movieId,title,genres"] + [
        f'{i},"Movie {i}, The ({1990 + i % 30})",Drama' for i in range(50)]
    movies.write_text("\n".join(rows) + "\n")

    cli_main(["recommend", "--model", model_dir, "--limit", "3",
              "--k", "4", "--titles", str(movies)])
    lines = [json.loads(x) for x in
             capsys.readouterr().out.strip().splitlines()]
    assert len(lines) == 3
    for ln in lines:
        assert len(ln["titles"]) == 4
        for (i, _), t in zip(ln["items"], ln["titles"]):
            assert t == f"Movie {i}, The ({1990 + i % 30})"

    # sharded serving must produce the same scores as single-device
    cli_main(["recommend", "--model", model_dir, "--limit", "3",
              "--k", "4"])
    single = [json.loads(x) for x in
              capsys.readouterr().out.strip().splitlines()]
    for strategy in ("all_gather", "ring"):
        cli_main(["recommend", "--model", model_dir, "--limit", "3",
                  "--k", "4", "--devices", "0",
                  "--gather-strategy", strategy, "--titles", str(movies)])
        sharded = [json.loads(x) for x in
                   capsys.readouterr().out.strip().splitlines()]
        assert len(sharded) == 3
        for a, b in zip(single, sharded):
            assert a["user"] == b["user"]
            sa = [s for _, s in a["items"]]
            sb = [s for _, s in b["items"]]
            np.testing.assert_allclose(sa, sb, rtol=1e-4, atol=1e-4)
            assert len(b["titles"]) == 4


def test_movies_metadata_formats(tmp_path):
    from tpu_als.io.movielens import load_movielens_movies

    (tmp_path / "u.item").write_text(
        "1|Toy Story (1995)|01-Jan-1995||http://x\n"
        "2|GoldenEye (1995)|01-Jan-1995||http://y\n", encoding="latin-1")
    f = load_movielens_movies(str(tmp_path / "u.item"))
    assert f["item"].tolist() == [1, 2]
    assert f["title"][0] == "Toy Story (1995)"

    (tmp_path / "movies.dat").write_text(
        "1::Toy Story (1995)::Animation\n2::Jumanji (1995)::Adventure\n",
        encoding="latin-1")
    f = load_movielens_movies(str(tmp_path / "movies.dat"))
    assert f["title"].tolist() == ["Toy Story (1995)", "Jumanji (1995)"]

    (tmp_path / "movies.csv").write_text(
        'movieId,title,genres\n1,"American President, The (1995)",Drama\n')
    f = load_movielens_movies(str(tmp_path / "movies.csv"))
    assert f["title"][0] == "American President, The (1995)"
    # directory form prefers movies.csv
    f2 = load_movielens_movies(str(tmp_path))
    assert f2["title"][0] == "American President, The (1995)"


def test_cli_recommend_users_with_devices_routes_sharded(tmp_path, capsys):
    """--users + --devices must serve the subset through the mesh (the
    catalog side is what outgrows one device), not silently ignore the
    sharding flags (advisor-style r4 finding)."""
    model_dir = str(tmp_path / "m")
    cli_main(["train", "--data", "synthetic:100x40x2500", "--rank", "4",
              "--max-iter", "2", "--reg-param", "0.01",
              "--output", model_dir])
    capsys.readouterr()
    cli_main(["recommend", "--model", model_dir, "--k", "3"])
    allu = {json.loads(x)["user"]: json.loads(x)["items"]
            for x in capsys.readouterr().out.strip().splitlines()}
    some = list(allu)[:2]
    cli_main(["recommend", "--model", model_dir, "--k", "3",
              "--users", ",".join(str(u) for u in some),
              "--devices", "0", "--gather-strategy", "ring"])
    lines = [json.loads(x) for x in
             capsys.readouterr().out.strip().splitlines()]
    assert {ln["user"] for ln in lines} == set(some)
    for ln in lines:
        np.testing.assert_allclose([s for _, s in ln["items"]],
                                   [s for _, s in allu[ln["user"]]],
                                   rtol=1e-4, atol=1e-4)


def test_cli_recommend_negative_devices_rejected(tmp_path, capsys):
    import pytest

    model_dir = str(tmp_path / "m")
    cli_main(["train", "--data", "synthetic:60x30x1200", "--rank", "3",
              "--max-iter", "1", "--output", model_dir])
    capsys.readouterr()
    with pytest.raises(SystemExit, match="--devices must be >= 0"):
        cli_main(["recommend", "--model", model_dir, "--devices", "-8"])


def test_movies_dat_utf8_titles(tmp_path):
    from tpu_als.io.movielens import load_movielens_movies

    # ml-10m style UTF-8 content must NOT be mojibaked by a latin-1 read
    (tmp_path / "movies.dat").write_bytes(
        "1::Les Misérables (1995)::Drama\n".encode("utf-8"))
    f = load_movielens_movies(str(tmp_path / "movies.dat"))
    assert f["title"][0] == "Les Misérables (1995)"
    # ml-1m style latin-1 still reads via the fallback
    (tmp_path / "movies.dat").write_bytes(
        "1::Am\xe9lie (2001)::Comedy\n".encode("latin-1"))
    f = load_movielens_movies(str(tmp_path / "movies.dat"))
    assert f["title"][0] == "Amélie (2001)"


def test_cli_recommend_too_many_devices_rejected(tmp_path, capsys):
    import pytest

    model_dir = str(tmp_path / "m")
    cli_main(["train", "--data", "synthetic:60x30x1200", "--rank", "3",
              "--max-iter", "1", "--output", model_dir])
    capsys.readouterr()
    with pytest.raises(ValueError, match="silently smaller mesh"):
        cli_main(["recommend", "--model", model_dir, "--devices", "99"])


def test_cli_evaluate_pipeline_model(tmp_path, capsys):
    """`evaluate --model` accepts a persisted PipelineModel: regression
    metrics flow through the whole pipeline; --ranking-k is refused with
    direction (it needs raw-id recommendForUserSubset)."""
    import pytest

    from tpu_als import ALS, ColumnarFrame, Pipeline, StringIndexer
    from tpu_als.io.movielens import synthetic_movielens

    raw = synthetic_movielens(150, 60, 5000, seed=4)
    # CLI data loaders emit integer user/item columns; index their
    # string forms so the saved pipeline maps them itself
    df = ColumnarFrame({"user": raw["user"], "item": raw["item"],
                        "rating": raw["rating"]})
    pipe = Pipeline(stages=[
        StringIndexer(inputCol="user", outputCol="u_idx",
                      handleInvalid="skip"),
        StringIndexer(inputCol="item", outputCol="i_idx",
                      handleInvalid="skip"),
        ALS(userCol="u_idx", itemCol="i_idx", ratingCol="rating",
            rank=4, maxIter=3, regParam=0.01, seed=0,
            coldStartStrategy="drop"),
    ])
    pm_dir = str(tmp_path / "pm")
    pipe.fit(df).save(pm_dir)

    data = tmp_path / "ratings.csv"
    rows = ["userId,movieId,rating,timestamp"] + [
        f"{u},{i},{r},0" for u, i, r in
        zip(raw["user"][:500], raw["item"][:500], raw["rating"][:500])]
    data.write_text("\n".join(rows) + "\n")

    cli_main(["evaluate", "--model", pm_dir, "--data", f"csv:{data}"])
    metrics = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert metrics["rmse"] is not None and metrics["rmse"] < 2.0

    with pytest.raises(SystemExit, match="ranking"):
        cli_main(["evaluate", "--model", pm_dir, "--data",
                  f"csv:{data}", "--ranking-k", "5"])


def test_cli_recommend_rejects_pipeline_save_with_direction(tmp_path,
                                                            capsys):
    import pytest

    from tpu_als import ALS, Pipeline, StringIndexer
    from tpu_als.io.movielens import synthetic_movielens

    raw = synthetic_movielens(100, 40, 2500, seed=5)
    pipe = Pipeline(stages=[
        StringIndexer(inputCol="user", outputCol="u", handleInvalid="skip"),
        ALS(userCol="u", itemCol="item", ratingCol="rating",
            rank=3, maxIter=1, seed=0),
    ])
    d = str(tmp_path / "pm")
    pipe.fit(raw).save(d)
    with pytest.raises(SystemExit, match="PipelineModel save"):
        cli_main(["recommend", "--model", d, "--k", "3"])


def test_cli_train_stream_spec(tmp_path, capsys):
    """Single-process `train --data stream:PATH`: string-id csv streams
    through the config-3 loader; the saved model carries the
    stream_labels sidecar mapping dense ids back to strings."""
    import numpy as np

    from tpu_als.cli import main

    rng = np.random.default_rng(3)
    csv = tmp_path / "s.csv"
    with open(csv, "w") as f:
        f.write("user_id,parent_asin,rating,timestamp\n")
        for k in range(1500):
            f.write(f"rev_{rng.integers(40):02d},"
                    f"B{rng.integers(25):03d},"
                    f"{rng.integers(1, 10) / 2.0},1600\n")
    out = tmp_path / "m"
    main(["train", "--data", f"stream:{csv}", "--rank", "4",
          "--max-iter", "3", "--reg-param", "0.02", "--seed", "0",
          "--output", str(out)])
    assert "holdout_rmse" in capsys.readouterr().out
    side = np.load(out / "stream_labels.npz")
    assert len(side["users"]) == 40 and len(side["items"]) == 25
    assert side["users"][0].item().decode().startswith("rev_")


def test_cli_evaluate_stream_uses_model_vocab(tmp_path, capsys):
    """evaluate --data stream: must densify in the MODEL's id space via
    the stream_labels sidecar — and drop ids the model never saw."""
    import numpy as np

    from tpu_als.cli import main

    rng = np.random.default_rng(5)
    train_csv = tmp_path / "tr.csv"
    with open(train_csv, "w") as f:
        f.write("user_id,parent_asin,rating,timestamp\n")
        for k in range(2000):
            f.write(f"rev_{rng.integers(30):02d},"
                    f"B{rng.integers(20):02d},"
                    f"{rng.integers(1, 10) / 2.0},1600\n")
    out = tmp_path / "m"
    main(["train", "--data", f"stream:{train_csv}", "--rank", "4",
          "--max-iter", "4", "--reg-param", "0.02", "--seed", "0",
          "--holdout", "0.0", "--output", str(out)])
    capsys.readouterr()

    # eval file: SUBSET of users (lexicographic positions differ from a
    # fresh vocab of this file) + one unknown user the model never saw
    ev_csv = tmp_path / "ev.csv"
    with open(ev_csv, "w") as f:
        f.write("user_id,parent_asin,rating,timestamp\n")
        for k in range(300):
            f.write(f"rev_{20 + (k % 10):02d},B{k % 20:02d},3.0,1600\n")
        f.write("rev_UNSEEN,B00,3.0,1600\n")
    main(["evaluate", "--model", str(out), "--data", f"stream:{ev_csv}"])
    text = capsys.readouterr()
    assert "rmse" in text.out
    # the unknown-id row was dropped with a notice, not mis-scored
    assert "dropped 1/301" in text.err

    # a model without the sidecar refuses stream eval data
    import shutil

    bare = tmp_path / "bare"
    shutil.copytree(out, bare)
    (bare / "stream_labels.npz").unlink()
    import pytest as _pytest

    with _pytest.raises(SystemExit, match="stream_labels"):
        main(["evaluate", "--model", str(bare),
              "--data", f"stream:{ev_csv}"])


def test_load_train_data_stream_host_policy(tmp_path):
    """stream: byte-range policy — a {proc} placeholder means per-host
    FILES (streamed whole); a shared file + per-host-data byte-splits;
    replicated streams whole."""
    import argparse

    import numpy as np

    from tpu_als.cli import _load_train_data

    shared = tmp_path / "all.csv"
    with open(shared, "w") as f:
        f.write("user_id,parent_asin,rating,timestamp\n")
        for k in range(400):
            f.write(f"u{k % 19:02d},B{k % 11:02d},2.5,1600\n")
    for p in range(2):
        part = tmp_path / f"part{p}.csv"
        with open(part, "w") as f:
            f.write("user_id,parent_asin,rating,timestamp\n")
            for k in range(100):
                f.write(f"u{k % 19:02d},B{k % 11:02d},2.5,1600\n")

    mk = lambda data, ph: argparse.Namespace(  # noqa: E731
        data=data, per_host_data=ph)
    # shared + per-host-data: byte-split -> halves sum to the whole
    n0 = len(_load_train_data(mk(f"stream:{shared}", True), 0, 2)[0])
    n1 = len(_load_train_data(mk(f"stream:{shared}", True), 1, 2)[0])
    assert n0 + n1 == 400 and 0 < n0 < 400
    # {proc} placeholder: per-host FILES, each streamed WHOLE even with
    # per-host-data (byte-splitting on top would drop half of each)
    spec = f"stream:{tmp_path}/part{{proc}}.csv"
    assert len(_load_train_data(mk(spec, True), 0, 2)[0]) == 100
    assert len(_load_train_data(mk(spec, True), 1, 2)[0]) == 100
    # replicated: every host streams the whole shared file
    assert len(_load_train_data(mk(f"stream:{shared}", False), 1, 2)[0]) == 400


def test_cli_recommend_stream_foldin_new_string_user(tmp_path, capsys):
    """The config-3 serving loop: stream-trained model + --foldin-data
    with a NEVER-SEEN string user id + --users by string — the new user
    gets a fresh dense id, is served, and the output maps both sides
    back to the original string ids."""
    import numpy as np

    from tpu_als.cli import main

    rng = np.random.default_rng(7)
    csv = tmp_path / "tr.csv"
    with open(csv, "w") as f:
        f.write("user_id,parent_asin,rating,timestamp\n")
        for k in range(1500):
            f.write(f"rev_{rng.integers(30):02d},"
                    f"B{rng.integers(20):02d},"
                    f"{rng.integers(1, 10) / 2.0},1600\n")
    out = tmp_path / "m"
    main(["train", "--data", f"stream:{csv}", "--rank", "4",
          "--max-iter", "3", "--reg-param", "0.02", "--seed", "0",
          "--holdout", "0.0", "--output", str(out)])
    capsys.readouterr()

    new = tmp_path / "new.csv"
    with open(new, "w") as f:
        f.write("user_id,parent_asin,rating,timestamp\n")
        f.write("rev_FRESH,B00,5.0,1600\n")
        f.write("rev_FRESH,B01,4.5,1600\n")
        f.write("rev_FRESH,UNKNOWN_ITEM,4.0,1600\n")  # dropped
    main(["recommend", "--model", str(out),
          "--foldin-data", f"stream:{new}",
          "--users", "rev_FRESH,rev_00", "--k", "3"])
    text = capsys.readouterr()
    assert "dropped 1/3" in text.err          # unknown item
    assert "1 new user ids" in text.err
    import json as _json

    rows = {r["user_id"]: r for r in
            (_json.loads(ln) for ln in text.out.splitlines()
             if ln.startswith("{"))}
    assert set(rows) == {"rev_FRESH", "rev_00"}
    fresh = rows["rev_FRESH"]
    assert fresh["user"] == 30                # dense id after the model
    assert all(isinstance(s, str) and s.startswith("B")
               for s in fresh["item_ids"])
    scores = [s for _, s in fresh["items"]]
    assert scores == sorted(scores, reverse=True)
    assert np.isfinite(scores).all()


def test_stream_foldin_ghost_user_gets_no_fresh_id(tmp_path, capsys):
    """A fold-in user whose EVERY row references unknown items must not
    receive a fresh dense id (it has no folded factor row to serve)."""
    import pytest as _pytest

    from tpu_als.cli import main

    csv = tmp_path / "tr.csv"
    with open(csv, "w") as f:
        f.write("user_id,parent_asin,rating,timestamp\n")
        for k in range(600):
            f.write(f"rev_{k % 20:02d},B{k % 15:02d},3.0,1600\n")
    out = tmp_path / "m"
    main(["train", "--data", f"stream:{csv}", "--rank", "3",
          "--max-iter", "2", "--reg-param", "0.02", "--seed", "0",
          "--holdout", "0.0", "--output", str(out)])
    capsys.readouterr()

    new = tmp_path / "ghost.csv"
    with open(new, "w") as f:
        f.write("user_id,parent_asin,rating,timestamp\n")
        f.write("rev_GHOST,NOPE1,4.0,1600\n")
        f.write("rev_GHOST,NOPE2,4.0,1600\n")
    with _pytest.raises(SystemExit, match="unknown user id 'rev_GHOST'"):
        main(["recommend", "--model", str(out),
              "--foldin-data", f"stream:{new}",
              "--users", "rev_GHOST", "--k", "3"])
    assert "new user ids" not in capsys.readouterr().err


def test_proc_placeholder_is_literal_single_process(tmp_path):
    """Single-process train must NOT expand {proc}: expanding to 0 would
    silently train on one split of N."""
    import pytest as _pytest

    from tpu_als.cli import main

    with _pytest.raises(FileNotFoundError):
        main(["train", "--data", f"csv:{tmp_path}/part-{{proc}}.csv",
              "--rank", "3", "--max-iter", "1"])


@pytest.mark.slow
def test_cli_tune_stream_saves_sidecar(tmp_path, capsys):
    from tpu_als.cli import main

    import numpy as np

    rng = np.random.default_rng(11)
    csv = tmp_path / "t.csv"
    with open(csv, "w") as f:
        f.write("user_id,parent_asin,rating,timestamp\n")
        for k in range(1200):
            f.write(f"rev_{rng.integers(25):02d},"
                    f"B{rng.integers(15):02d},"
                    f"{rng.integers(1, 10) / 2.0},1600\n")
    out = tmp_path / "cv"
    main(["tune", "--data", f"stream:{csv}", "--ranks", "2,4",
          "--reg-params", "0.05", "--folds", "2", "--max-iter", "2",
          "--seed", "0", "--output", str(out)])
    assert "best_rank" in capsys.readouterr().out
    side = np.load(out / "stream_labels.npz")
    assert len(side["users"]) == 25 and len(side["items"]) == 15
