"""The collective-traffic model (trainer.comm_bytes_per_iter — the CLI's
MB/device/iter line) validated against the bytes the TRACED STEP actually
moves, counted from its jaxpr (parallel.comm_audit).  A step change that
adds/removes/resizes a collective now fails here instead of silently
diverging from the reported number (VERDICT r3 weak #7)."""

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from tpu_als.core.als import AlsConfig, init_factors
from tpu_als.parallel.comm_audit import collective_bytes
from tpu_als.parallel.data import partition_balanced, shard_csr
from tpu_als.parallel.mesh import AXIS, make_mesh
from tpu_als.parallel.trainer import (
    comm_bytes_per_iter,
    make_a2a_step,
    make_ring_step,
    make_sharded_step,
    stacked_counts,
)

D = 8


def _problem(rng, nU=60, nI=40, nnz=900):
    u = rng.integers(0, nU, nnz)
    i = rng.integers(0, nI, nnz)
    r = np.abs(rng.normal(size=nnz)).astype(np.float32) + 0.1
    upart = partition_balanced(np.bincount(u, minlength=nU), D)
    ipart = partition_balanced(np.bincount(i, minlength=nI), D)
    return u, i, r, upart, ipart


def _factors(mesh, upart, ipart, rank):
    leading = NamedSharding(mesh, P(AXIS))
    key = jax.random.PRNGKey(0)
    ku, kv = jax.random.split(key)
    U = jax.device_put(
        jnp.zeros((upart.padded_rows, rank), jnp.float32), leading)
    V = jax.device_put(
        jnp.zeros((ipart.padded_rows, rank), jnp.float32), leading)
    return U, V, leading


def test_all_gather_model_matches_traced_bytes(rng):
    u, i, r, upart, ipart = _problem(rng)
    rank = 8
    cfg = AlsConfig(rank=rank, max_iter=1, reg_param=0.1,
                    implicit_prefs=True, alpha=4.0, seed=0)
    ush = shard_csr(upart, ipart, u, i, r, min_width=4)
    ish = shard_csr(ipart, upart, i, u, r, min_width=4)
    mesh = make_mesh(D)
    U, V, leading = _factors(mesh, upart, ipart, rank)
    ub = jax.device_put(ush.device_buckets(), leading)
    ib = jax.device_put(ish.device_buckets(), leading)
    step = make_sharded_step(mesh, ush, ish, cfg)
    traced, breakdown = collective_bytes(step, U, V, ub, ib, axis_size=D)
    model = comm_bytes_per_iter("all_gather", upart, ipart, rank,
                                user_container=ush, item_container=ish,
                                implicit=True)
    assert breakdown.get("all_gather") and breakdown.get("psum")
    assert traced == model, (traced, model, breakdown)


def test_ring_model_matches_traced_bytes_with_tiling(rng):
    from tpu_als.parallel.comm import shard_csr_grid

    u, i, r, upart, ipart = _problem(rng)
    rank = 8
    cfg = AlsConfig(rank=rank, max_iter=1, reg_param=0.1,
                    implicit_prefs=True, alpha=4.0, seed=0)
    # a small chunk budget forces ntiles > 1 so the audit must scale
    # the in-loop ppermutes by the scan trip count
    chunk = 512
    ugrid = shard_csr_grid(upart, ipart, u, i, r, min_width=4,
                           chunk_elems=chunk)
    igrid = shard_csr_grid(ipart, upart, i, u, r, min_width=4,
                           chunk_elems=chunk)
    mesh = make_mesh(D)
    U, V, leading = _factors(mesh, upart, ipart, rank)
    ub = jax.device_put(ugrid.device_buckets(), leading)
    ib = jax.device_put(igrid.device_buckets(), leading)
    uc = jax.device_put(
        jnp.asarray(stacked_counts(upart, u, r, positive_only=True)),
        leading)
    ic = jax.device_put(
        jnp.asarray(stacked_counts(ipart, i, r, positive_only=True)),
        leading)
    step = make_ring_step(mesh, ugrid, igrid, cfg)
    traced, breakdown = collective_bytes(step, U, V, ub, ib, uc, ic,
                                         axis_size=D)
    model = comm_bytes_per_iter("ring", upart, ipart, rank,
                                user_container=ugrid, item_container=igrid,
                                implicit=True)
    assert breakdown.get("ppermute") and breakdown.get("psum")
    assert traced == model, (traced, model, breakdown)


def test_ring_overlap_model_matches_traced_bytes_with_tiling(rng):
    from tpu_als.parallel.comm import shard_csr_grid

    u, i, r, upart, ipart = _problem(rng)
    rank = 8
    cfg = AlsConfig(rank=rank, max_iter=1, reg_param=0.1,
                    implicit_prefs=True, alpha=4.0, seed=0)
    chunk = 512
    ugrid = shard_csr_grid(upart, ipart, u, i, r, min_width=4,
                           chunk_elems=chunk)
    igrid = shard_csr_grid(ipart, upart, i, u, r, min_width=4,
                           chunk_elems=chunk)
    mesh = make_mesh(D)
    U, V, leading = _factors(mesh, upart, ipart, rank)
    ub = jax.device_put(ugrid.device_buckets(), leading)
    ib = jax.device_put(igrid.device_buckets(), leading)
    uc = jax.device_put(
        jnp.asarray(stacked_counts(upart, u, r, positive_only=True)),
        leading)
    ic = jax.device_put(
        jnp.asarray(stacked_counts(ipart, i, r, positive_only=True)),
        leading)
    step = make_ring_step(mesh, ugrid, igrid, cfg, overlap=True)
    traced, breakdown = collective_bytes(step, U, V, ub, ib, uc, ic,
                                         axis_size=D)
    # the double-buffered schedule prefetches shard k+1 while shard k
    # accumulates, but moves the SAME bytes in the SAME collectives as
    # the serial ring — the model is shared and must still match exactly
    model = comm_bytes_per_iter("ring_overlap", upart, ipart, rank,
                                user_container=ugrid, item_container=igrid,
                                implicit=True)
    assert model == comm_bytes_per_iter(
        "ring", upart, ipart, rank, user_container=ugrid,
        item_container=igrid, implicit=True)
    assert breakdown.get("ppermute") and breakdown.get("psum")
    assert traced == model, (traced, model, breakdown)


def test_chunked_gather_model_matches_traced_bytes(rng):
    from tpu_als.parallel.trainer import make_chunked_gather_step

    u, i, r, upart, ipart = _problem(rng)
    rank = 8
    cfg = AlsConfig(rank=rank, max_iter=1, reg_param=0.1,
                    implicit_prefs=True, alpha=4.0, seed=0)
    # chunk budget forces ntiles > 1 (scan-scaled gathers) and
    # n_blocks=3 leaves a ragged last block — the byte model must be
    # independent of BOTH because the column blocks partition the shard
    ush = shard_csr(upart, ipart, u, i, r, min_width=4, chunk_elems=512)
    ish = shard_csr(ipart, upart, i, u, r, min_width=4, chunk_elems=512)
    mesh = make_mesh(D)
    U, V, leading = _factors(mesh, upart, ipart, rank)
    ub = jax.device_put(ush.device_buckets(), leading)
    ib = jax.device_put(ish.device_buckets(), leading)
    step = make_chunked_gather_step(mesh, ush, ish, cfg, n_blocks=3)
    traced, breakdown = collective_bytes(step, U, V, ub, ib, axis_size=D)
    model = comm_bytes_per_iter("all_gather_chunked", upart, ipart, rank,
                                user_container=ush, item_container=ish,
                                implicit=True)
    assert breakdown.get("all_gather") and breakdown.get("psum")
    assert traced == model, (traced, model, breakdown)


def test_fused_ring_remote_dma_matches_model(rng):
    """solve_backend='gather_fused_ring': the inter-chip bytes are
    in-kernel remote DMAs — collective_bytes cannot see them (and must
    see NO ppermute/all_gather left in the step), remote_dma_bytes must
    count exactly comm_bytes_per_iter's gather_fused_ring closed form
    (perf.roofline.ring_remote_bytes per half-step)."""
    from tpu_als.parallel.comm import shard_csr_grid
    from tpu_als.parallel.comm_audit import remote_dma_bytes

    u, i, r, upart, ipart = _problem(rng)
    rank = 128  # real lane width — the payload model is r_pad-exact
    cfg = AlsConfig(rank=rank, max_iter=1, reg_param=0.1,
                    implicit_prefs=True, alpha=4.0, seed=0,
                    solve_backend="gather_fused_ring")
    ugrid = shard_csr_grid(upart, ipart, u, i, r, min_width=4)
    igrid = shard_csr_grid(ipart, upart, i, u, r, min_width=4)
    mesh = make_mesh(D)
    U, V, leading = _factors(mesh, upart, ipart, rank)
    ub = jax.device_put(ugrid.device_buckets(), leading)
    ib = jax.device_put(igrid.device_buckets(), leading)
    uc = jax.device_put(
        jnp.asarray(stacked_counts(upart, u, r, positive_only=True)),
        leading)
    ic = jax.device_put(
        jnp.asarray(stacked_counts(ipart, i, r, positive_only=True)),
        leading)
    step = make_ring_step(mesh, ugrid, igrid, cfg)
    traced, per_call = remote_dma_bytes(step, U, V, ub, ib, uc, ic)
    model = comm_bytes_per_iter("gather_fused_ring", upart, ipart, rank,
                                user_container=ugrid, item_container=igrid,
                                implicit=False)
    assert traced == model, (traced, model, per_call)
    # the rotation moved in-kernel: no XLA gather collectives remain,
    # only the replicated-YtY psum (implicit mode's base Gram term)
    _, breakdown = collective_bytes(step, U, V, ub, ib, uc, ic,
                                    axis_size=D)
    assert "ppermute" not in breakdown and "all_gather" not in breakdown
    psum_model = comm_bytes_per_iter(
        "gather_fused_ring", upart, ipart, rank, user_container=ugrid,
        item_container=igrid, implicit=True) - model
    assert breakdown.get("psum", 0) == psum_model, (breakdown, psum_model)


def test_a2a_model_matches_traced_bytes():
    from tpu_als.parallel.a2a import build_a2a

    # banded-sparse layout so the exchange plan is non-degenerate
    rng = np.random.default_rng(5)
    nU, nI = 24 * D, 48 * D
    nnz = 2 * nU
    u = rng.integers(0, nU, nnz)
    i = rng.integers(0, nI, nnz)
    r = np.abs(rng.normal(size=nnz)).astype(np.float32) + 0.1
    upart = partition_balanced(np.bincount(u, minlength=nU), D)
    ipart = partition_balanced(np.bincount(i, minlength=nI), D)
    ua = build_a2a(upart, ipart, u, i, r, min_width=4)
    ia = build_a2a(ipart, upart, i, u, r, min_width=4)
    assert not ua.degenerate and not ia.degenerate
    rank = 8
    cfg = AlsConfig(rank=rank, max_iter=1, reg_param=0.1,
                    implicit_prefs=True, alpha=4.0, seed=0)
    mesh = make_mesh(D)
    U, V, leading = _factors(mesh, upart, ipart, rank)
    ub = jax.device_put(ua.device_buckets(), leading)
    ib = jax.device_put(ia.device_buckets(), leading)
    us = jax.device_put(jnp.asarray(ua.send_idx), leading)
    is_ = jax.device_put(jnp.asarray(ia.send_idx), leading)
    step = make_a2a_step(mesh, ua, ia, cfg)
    traced, breakdown = collective_bytes(step, U, V, ub, ib, us, is_,
                                         axis_size=D)
    model = comm_bytes_per_iter("all_to_all", upart, ipart, rank,
                                user_container=ua, item_container=ia,
                                implicit=True)
    assert breakdown.get("all_to_all") and breakdown.get("psum")
    assert traced == model, (traced, model, breakdown)


def _shmap_psum_fn(mesh, branch_bytes_differ=False, while_pred=False):
    """Tiny shard_mapped programs exercising the audit's control-flow
    conventions (cond counted once / disagreeing branches rejected /
    collective in a while predicate rejected)."""
    from functools import partial

    from tpu_als.parallel.mesh import shard_map

    spec = P(AXIS)
    # check_vma=False: these programs put the psum inside cond/while, and
    # older jax's replication inference can't see through control flow —
    # the audit's own branch/predicate checks are what's under test here
    @partial(shard_map, mesh=mesh, in_specs=(spec,), out_specs=P(),
             check_vma=False)
    def equal_branches(x):
        return jax.lax.cond(
            x.sum() > 0,
            lambda v: jax.lax.psum(v.sum(), AXIS),
            lambda v: jax.lax.psum(v.sum() * 2.0, AXIS),
            x)

    @partial(shard_map, mesh=mesh, in_specs=(spec,), out_specs=P(),
             check_vma=False)
    def unequal_branches(x):
        return jax.lax.cond(
            x.sum() > 0,
            lambda v: jax.lax.psum(v[:2], AXIS).sum(),
            lambda v: jax.lax.psum(v.sum(), AXIS) * 0.0,
            x)

    @partial(shard_map, mesh=mesh, in_specs=(spec,), out_specs=P(),
             check_vma=False)
    def psum_in_while_pred(x):
        return jax.lax.while_loop(
            lambda s: jax.lax.psum(s.sum(), AXIS) > 1.0,
            lambda s: s * 0.5,
            x)

    if branch_bytes_differ:
        return unequal_branches
    if while_pred:
        return psum_in_while_pred
    return equal_branches


def test_cond_branches_counted_once():
    mesh = make_mesh(D)
    x = jnp.ones((D * 4,), jnp.float32)
    fn = _shmap_psum_fn(mesh)
    total, breakdown = collective_bytes(fn, x, axis_size=D)
    # one scalar f32 psum, counted ONCE (not per branch):
    # 2*(S-1)/S * 4 bytes
    assert total == int(2 * (D - 1) / D * 4)


def test_cond_disagreeing_branches_rejected():
    import pytest

    mesh = make_mesh(D)
    x = jnp.ones((D * 4,), jnp.float32)
    fn = _shmap_psum_fn(mesh, branch_bytes_differ=True)
    with pytest.raises(ValueError, match="branches"):
        collective_bytes(fn, x, axis_size=D)


def test_collective_in_while_predicate_rejected():
    import pytest

    mesh = make_mesh(D)
    x = jnp.ones((D * 4,), jnp.float32)
    fn = _shmap_psum_fn(mesh, while_pred=True)
    with pytest.raises(ValueError, match="while"):
        collective_bytes(fn, x, axis_size=D)
