"""Top-k serving kernel + fold-in correctness tests.

Fold-in oracle (SURVEY.md §4 item 2): a one-step fold-in must equal a full
half-step restricted to the touched rows.
"""

import numpy as np

import jax.numpy as jnp

from tpu_als.core.als import AlsConfig, train
from tpu_als.core.foldin import fold_in
from tpu_als.core.ratings import build_csr_buckets
from tpu_als.ops.topk import NEG_INF, chunked_topk_scores, topk_validity

from conftest import make_ratings


def test_topk_matches_full_sort(rng):
    n, Ni, r, k = 17, 103, 6, 5
    U = rng.normal(size=(n, r)).astype(np.float32)
    V = rng.normal(size=(Ni, r)).astype(np.float32)
    valid = np.ones(Ni, bool)
    valid[[3, 50]] = False
    s, idx = chunked_topk_scores(
        jnp.array(U), jnp.array(V), jnp.array(valid), k=k, item_chunk=16
    )
    s, idx = np.asarray(s), np.asarray(idx)
    full = U @ V.T
    full[:, ~valid] = -np.inf
    ref_idx = np.argsort(-full, axis=1)[:, :k]
    ref_s = np.take_along_axis(full, ref_idx, axis=1)
    np.testing.assert_allclose(s, ref_s, rtol=1e-4, atol=1e-4)
    # indices may tie-swap; compare via scores per position
    np.testing.assert_allclose(
        np.take_along_axis(full, idx, axis=1), ref_s, rtol=1e-4, atol=1e-4
    )
    assert not np.isin(idx, [3, 50]).any()


def test_topk_scores_sorted_desc(rng):
    U = rng.normal(size=(4, 3)).astype(np.float32)
    V = rng.normal(size=(33, 3)).astype(np.float32)
    s, _ = chunked_topk_scores(jnp.array(U), jnp.array(V), jnp.ones(33, bool), k=7)
    s = np.asarray(s)
    assert np.all(np.diff(s, axis=1) <= 1e-6)


def test_topk_validity_marks_sentinel_slots(rng):
    """Fewer valid items than k: the surplus slots carry the NEG_INF
    sentinel with meaningless indices — topk_validity is the contract
    callers trim by before surfacing recommendations."""
    U = rng.normal(size=(6, 4)).astype(np.float32)
    V = rng.normal(size=(30, 4)).astype(np.float32)
    valid = np.zeros(30, bool)
    valid[[2, 11, 29]] = True
    s, idx = chunked_topk_scores(jnp.array(U), jnp.array(V),
                                 jnp.array(valid), k=5, item_chunk=8)
    s, idx = np.asarray(s), np.asarray(idx)
    mask = topk_validity(s)
    np.testing.assert_array_equal(
        mask, np.tile([True] * 3 + [False] * 2, (6, 1)))
    np.testing.assert_array_equal(s[~mask],
                                  np.full(12, NEG_INF, np.float32))
    assert np.isin(idx[mask], [2, 11, 29]).all()


def test_topk_validity_all_false_item_valid(rng):
    """All-False validity (an empty catalog in disguise): every slot is
    a sentinel and the mask says so — no row leaks a real-looking score."""
    U = rng.normal(size=(3, 4)).astype(np.float32)
    V = rng.normal(size=(10, 4)).astype(np.float32)
    s, _ = chunked_topk_scores(jnp.array(U), jnp.array(V),
                               jnp.zeros(10, bool), k=4)
    s = np.asarray(s)
    assert not topk_validity(s).any()
    np.testing.assert_array_equal(s, np.full((3, 4), NEG_INF, np.float32))


def _padded_rows(u_sel, u, i, r, width):
    cols = np.zeros((len(u_sel), width), np.int32)
    vals = np.zeros((len(u_sel), width), np.float32)
    mask = np.zeros((len(u_sel), width), np.float32)
    for row, uu in enumerate(u_sel):
        sel = np.flatnonzero(u == uu)
        cols[row, : len(sel)] = i[sel]
        vals[row, : len(sel)] = r[sel]
        mask[row, : len(sel)] = 1.0
    return cols, vals, mask


def test_foldin_equals_half_step(rng):
    u, i, r, _, _ = make_ratings(rng, 40, 30, rank=3, density=0.4)
    cfg = AlsConfig(rank=3, max_iter=5, reg_param=0.1, seed=1)
    user_csr = build_csr_buckets(u, i, r, 40, min_width=4)
    item_csr = build_csr_buckets(i, u, r, 30, min_width=4)
    U, V = train(user_csr, item_csr, cfg)

    # fold-in for users {2, 7} with their existing ratings against fixed V
    # must reproduce what one more user half-step would give those rows.
    touched = np.array([2, 7])
    w = int(user_csr.counts[touched].max())
    cols, vals, mask = _padded_rows(touched, u, i, r, w)
    x = fold_in(V, jnp.array(cols), jnp.array(vals), jnp.array(mask), cfg.reg_param)

    from tpu_als.core.als import local_half_step
    import jax
    U_next = jax.jit(
        lambda Vf: local_half_step(
            Vf, jax.device_put(user_csr.device_buckets()), 40, cfg
        )
    )(V)
    np.testing.assert_allclose(
        np.asarray(x), np.asarray(U_next)[touched], rtol=1e-3, atol=1e-3
    )


def test_foldin_implicit_matches_half_step(rng):
    u, i, r, _, _ = make_ratings(rng, 30, 20, rank=2, density=0.5)
    r = np.abs(r) + 0.1
    cfg = AlsConfig(rank=2, max_iter=3, implicit_prefs=True, alpha=5.0, seed=4)
    user_csr = build_csr_buckets(u, i, r, 30, min_width=4)
    item_csr = build_csr_buckets(i, u, r, 20, min_width=4)
    U, V = train(user_csr, item_csr, cfg)

    touched = np.array([0, 9, 11])
    w = int(user_csr.counts[touched].max())
    cols, vals, mask = _padded_rows(touched, u, i, r, w)
    YtY = jnp.einsum("nr,ns->rs", V, V)
    x = fold_in(
        V, jnp.array(cols), jnp.array(vals), jnp.array(mask), cfg.reg_param,
        implicit_prefs=True, alpha=cfg.alpha, YtY=YtY,
    )
    from tpu_als.core.als import local_half_step
    import jax
    U_next = jax.jit(
        lambda Vf: local_half_step(
            Vf, jax.device_put(user_csr.device_buckets()), 30, cfg, YtY
        )
    )(V)
    np.testing.assert_allclose(
        np.asarray(x), np.asarray(U_next)[touched], rtol=1e-3, atol=1e-3
    )
