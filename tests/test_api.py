"""API conformance tests — SURVEY.md §4 item 6: param names/defaults/
validation exactly per §2.D, plus transform/coldStart/recommend/persistence
semantics of the reference surface.
"""

import numpy as np
import pytest

from tpu_als import ALS, ALSModel, ColumnarFrame, RegressionEvaluator
from tpu_als.utils.frame import as_frame

from conftest import make_ratings


EXPECTED_DEFAULTS = {
    "rank": 10, "maxIter": 10, "regParam": 0.1, "numUserBlocks": 10,
    "numItemBlocks": 10, "implicitPrefs": False, "alpha": 1.0,
    "userCol": "user", "itemCol": "item", "ratingCol": "rating",
    "predictionCol": "prediction", "nonnegative": False,
    "checkpointInterval": 10, "intermediateStorageLevel": "MEMORY_AND_DISK",
    "finalStorageLevel": "MEMORY_AND_DISK", "coldStartStrategy": "nan",
    "blockSize": 4096, "solver": "jax_tpu",
}


def small_frame(rng, nU=40, nI=30):
    u, i, r, _, _ = make_ratings(rng, nU, nI, rank=3, density=0.4)
    return ColumnarFrame({"user": u, "item": i, "rating": r})


def test_param_defaults_match_reference():
    als = ALS()
    for name, expected in EXPECTED_DEFAULTS.items():
        assert als.getOrDefault(als.getParam(name)) == expected, name


def test_param_setters_getters():
    als = ALS()
    als.setRank(32).setMaxIter(5).setRegParam(0.01).setImplicitPrefs(True)
    assert als.getRank() == 32
    assert als.getMaxIter() == 5
    assert als.getRegParam() == 0.01
    assert als.getImplicitPrefs() is True
    als2 = ALS(rank=7, alpha=40.0)
    assert als2.getRank() == 7 and als2.getAlpha() == 40.0


def test_param_validation():
    with pytest.raises(ValueError):
        ALS(rank=0).fit(ColumnarFrame({"user": np.array([0]),
                                       "item": np.array([0]),
                                       "rating": np.array([1.0])}))
    with pytest.raises(ValueError):
        ALS(coldStartStrategy="bogus").fit(
            ColumnarFrame({"user": np.array([0]), "item": np.array([0]),
                           "rating": np.array([1.0])}))
    with pytest.raises(TypeError):
        ALS(notAParam=3)
    with pytest.raises(ValueError):
        # non-integer id columns rejected (reference int-range restriction)
        ALS().fit(ColumnarFrame({"user": np.array([0.5]),
                                 "item": np.array([0]),
                                 "rating": np.array([1.0])}))


def test_copy_with_extra_grid_semantics():
    als = ALS(rank=5)
    c = als.copy({als.regParam: 0.9})
    assert c.getRegParam() == 0.9
    assert als.getRegParam() == 0.1  # original untouched
    assert c.getRank() == 5


def test_fit_transform_rmse(rng):
    frame = small_frame(rng)
    als = ALS(rank=4, maxIter=8, regParam=0.02, seed=3)
    model = als.fit(frame)
    out = model.transform(frame)
    assert "prediction" in out.columns
    ev = RegressionEvaluator(labelCol="rating")
    rmse = ev.evaluate(out)
    assert rmse < 0.3


def test_cold_start_nan_vs_drop(rng):
    frame = small_frame(rng)
    model = ALS(rank=3, maxIter=3, seed=0).fit(frame)
    unseen = ColumnarFrame({"user": np.array([10**6]),
                            "item": np.array([0])})
    p = model.transform(unseen)
    assert np.isnan(p["prediction"][0])
    model_drop = ALS(rank=3, maxIter=3, seed=0,
                     coldStartStrategy="drop").fit(frame)
    p2 = model_drop.transform(unseen)
    assert len(p2) == 0


def test_original_ids_roundtrip(rng):
    # non-contiguous original ids must round-trip through the model
    u = np.array([100, 100, 2000, 2000, 55])
    i = np.array([7, 9000, 7, 9000, 7])
    r = np.array([5.0, 1.0, 1.0, 5.0, 3.0], dtype=np.float32)
    frame = ColumnarFrame({"user": u, "item": i, "rating": r})
    model = ALS(rank=2, maxIter=5, regParam=0.01, seed=1).fit(frame)
    out = model.transform(frame)
    assert np.isfinite(out["prediction"]).all()
    uf = model.userFactors
    assert set(uf["id"].tolist()) == {55, 100, 2000}


def test_api_surface_conformance():
    """The full §2.D method/param surface exists by name — the parity
    contract SURVEY.md freezes (reference: pyspark.ml.recommendation +
    pyspark.mllib.recommendation method tables)."""
    from tpu_als.api import legacy

    est = ALS()
    for p in ("rank", "maxIter", "regParam", "numUserBlocks",
              "numItemBlocks", "implicitPrefs", "alpha", "userCol",
              "itemCol", "ratingCol", "predictionCol", "nonnegative",
              "checkpointInterval", "intermediateStorageLevel",
              "finalStorageLevel", "coldStartStrategy", "seed",
              "blockSize", "solver"):
        assert est.hasParam(p), p
        cap = p[0].upper() + p[1:]
        assert callable(getattr(est, f"get{cap}")), p
        assert callable(getattr(est, f"set{cap}")), p
    for m in ("fit", "setParams", "copy", "extractParamMap", "save",
              "load", "write"):
        assert callable(getattr(est, m)), m

    from tpu_als.api.estimator import ALSModel

    for m in ("transform", "predict", "recommendForAllUsers",
              "recommendForAllItems", "recommendForUserSubset",
              "recommendForItemSubset", "save", "load", "write"):
        assert callable(getattr(ALSModel, m)), m
    for prop in ("userFactors", "itemFactors"):
        assert isinstance(getattr(ALSModel, prop), property), prop
    # `rank` is a per-instance attribute; covered by the fit/save tests

    for m in ("train", "trainImplicit"):
        assert callable(getattr(legacy.ALS, m)), m
    for m in ("predict", "predictAll", "recommendProducts",
              "recommendUsers", "recommendProductsForUsers",
              "recommendUsersForProducts", "userFeatures",
              "productFeatures", "save", "load"):
        assert callable(getattr(legacy.MatrixFactorizationModel, m)), m
    assert legacy.Rating is not None


def test_transform_chunked_equals_single_call(rng, monkeypatch):
    """Frames above the scoring chunk stream in fixed-shape blocks (one
    jit specialization, padded tail); predictions must equal the
    single-call path bit-for-bit, cold rows included."""
    frame = small_frame(rng)
    model = ALS(rank=3, maxIter=3, seed=0).fit(frame)
    users = np.concatenate([np.asarray(frame["user"]),
                            np.array([10 ** 7])])  # one cold row
    items = np.concatenate([np.asarray(frame["item"]),
                            np.array([0])])
    big = ColumnarFrame({"user": users, "item": items})
    whole = np.asarray(model.transform(big)["prediction"])
    monkeypatch.setattr(type(model), "_TRANSFORM_CHUNK", 7)
    chunked = np.asarray(model.transform(big)["prediction"])
    np.testing.assert_array_equal(chunked, whole)
    assert np.isnan(chunked[-1])  # cold row survives chunking as NaN


def test_recommend_for_all_users(rng):
    frame = small_frame(rng)
    model = ALS(rank=3, maxIter=4, seed=2).fit(frame)
    recs = model.recommendForAllUsers(5)
    assert len(recs) == len(model.userFactors)
    first = recs["recommendations"][0]
    assert len(first) == 5
    scores = [s for _, s in first]
    assert scores == sorted(scores, reverse=True)
    item_ids = set(model.itemFactors["id"].tolist())
    assert all(iid in item_ids for iid, _ in first)


def test_recommend_subset(rng):
    frame = small_frame(rng)
    model = ALS(rank=3, maxIter=4, seed=2).fit(frame)
    users = np.unique(frame["user"])[:3]
    recs = model.recommendForUserSubset(
        ColumnarFrame({"user": users}), 4)
    assert len(recs) == 3
    assert set(recs["user"].tolist()) == set(users.tolist())
    # unseen users silently excluded (reference behavior)
    recs2 = model.recommendForUserSubset(
        ColumnarFrame({"user": np.array([users[0], 10**7])}), 4)
    assert len(recs2) == 1


def test_recommend_itemcol_named_rating_raises_clearly(rng):
    # itemCol='rating' would need two struct fields named 'rating' in the
    # recommendations dtype; np.dtype raises a bare "duplicate field
    # name" — the guard must surface the actual conflict (advisor r3)
    import pytest

    frame = small_frame(rng)
    ren = ColumnarFrame({"user": np.asarray(frame["user"]),
                         "rating": np.asarray(frame["item"]),
                         "score": np.asarray(frame["rating"])})
    model = ALS(rank=3, maxIter=2, seed=0, itemCol="rating",
                ratingCol="score").fit(ren)
    with pytest.raises(ValueError, match="itemCol='rating' collides"):
        model.recommendForAllUsers(3)


def test_model_save_load_roundtrip(rng, tmp_path):
    frame = small_frame(rng)
    model = ALS(rank=3, maxIter=3, seed=4).fit(frame)
    path = str(tmp_path / "als_model")
    model.save(path)
    loaded = ALSModel.load(path)
    out1 = model.transform(frame)
    out2 = loaded.transform(frame)
    np.testing.assert_allclose(out1["prediction"], out2["prediction"],
                               rtol=1e-6)
    assert loaded.rank == 3


def test_sharded_fit_via_mesh(rng):
    import jax

    from tpu_als.parallel.mesh import make_mesh

    frame = small_frame(rng)
    assert len(jax.devices()) == 8
    m1 = ALS(rank=3, maxIter=4, seed=5).fit(frame)
    m8 = ALS(rank=3, maxIter=4, seed=5, mesh=make_mesh(8)).fit(frame)
    o1 = m1.transform(frame)
    o8 = m8.transform(frame)
    np.testing.assert_allclose(o1["prediction"], o8["prediction"],
                               rtol=2e-3, atol=2e-3)


def test_checkpoint_written(rng, tmp_path):
    frame = small_frame(rng)
    als = ALS(rank=3, maxIter=4, seed=0, checkpointInterval=2,
              checkpointDir=str(tmp_path))
    als.fit(frame)
    from tpu_als.io.checkpoint import load_factors
    manifest, u_ids, U, i_ids, V = load_factors(
        str(tmp_path / "als_checkpoint"))
    assert manifest["iteration"] == 4
    assert U.shape[1] == 3


def test_frame_random_split(rng):
    frame = small_frame(rng, nU=100, nI=50)
    a, b = frame.randomSplit([0.8, 0.2], seed=42)
    assert len(a) + len(b) == len(frame)
    assert 0.6 < len(a) / len(frame) < 0.95
    a2, b2 = frame.randomSplit([0.8, 0.2], seed=42)
    np.testing.assert_array_equal(a["user"], a2["user"])


def test_as_frame_accepts_dict(rng):
    d = {"user": np.array([0, 1]), "item": np.array([0, 1]),
         "rating": np.array([1.0, 2.0], dtype=np.float32)}
    f = as_frame(d)
    assert f.columns == ["user", "item", "rating"]
    model = ALS(rank=2, maxIter=2).fit(d)  # plain dict accepted by fit
    assert model.rank == 2


def test_missing_rating_col_raises_and_empty_means_ones(rng):
    frame = ColumnarFrame({"user": np.array([0, 1]), "item": np.array([0, 1]),
                           "wrong_name": np.array([1.0, 2.0], np.float32)})
    with pytest.raises(ValueError, match="rating"):
        ALS(rank=2, maxIter=1).fit(frame)
    m = ALS(rank=2, maxIter=1, ratingCol="").fit(frame)  # unit ratings
    assert np.isfinite(m.transform(frame)["prediction"]).all()


def test_checkpoint_survives_swap_window(rng, tmp_path):
    import os
    from tpu_als.io.checkpoint import load_factors, save_factors

    path = str(tmp_path / "ck")
    ids = np.arange(3)
    save_factors(path, ids, np.ones((3, 2)), ids, np.ones((3, 2)),
                 iteration=1)
    # simulate a crash between the two renames: new never installed,
    # old still at path.old
    os.rename(path, path + ".old")
    manifest, *_ = load_factors(path)
    assert manifest["iteration"] == 1


@pytest.mark.parametrize("strategy", ["ring", "all_to_all"])
def test_sharded_fit_strategy_matches_all_gather(rng, strategy):
    """Estimator-level gatherStrategy plumbing: ring / all_to_all fits must
    reproduce the all_gather fit."""
    from tpu_als.parallel.mesh import make_mesh

    # sparse layout (4 random items/user over 256 entities) so the a2a
    # budget stays well below rows/shard on BOTH sides — the fallback must
    # NOT fire, or this would compare all_gather with itself.  (Arithmetic
    # strides resonate with partition_balanced's round-robin placement of
    # equal-count entities and degenerate; random draws do not.)
    gen = np.random.default_rng(11)
    nU = nI = 256
    u = np.repeat(np.arange(nU), 4)
    i = np.concatenate([gen.choice(nI, 4, replace=False)
                        for _ in range(nU)])
    r = gen.normal(size=len(u)).astype(np.float32)
    frame = {"user": u, "item": i, "rating": r}
    mesh = make_mesh(8)
    base = ALS(rank=4, maxIter=3, regParam=0.05, seed=0, mesh=mesh).fit(frame)
    import warnings

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        alt = ALS(rank=4, maxIter=3, regParam=0.05, seed=0, mesh=mesh,
                  gatherStrategy=strategy).fit(frame)
    assert not any("all_gather" in str(x.message) for x in w), \
        "test data degenerated; the strategy under test never ran"
    np.testing.assert_allclose(
        np.asarray(alt.transform(frame)["prediction"]),
        np.asarray(base.transform(frame)["prediction"]),
        rtol=5e-3, atol=5e-3)


def test_bad_gather_strategy_rejected():
    with pytest.raises(ValueError, match="gatherStrategy"):
        ALS(gatherStrategy="broadcast")


def test_writer_call_shape(rng, tmp_path):
    # pyspark parity: .write().save(path) raises on an existing path,
    # .write().overwrite().save(path) replaces it (VERDICT r1 missing #5)
    import pytest

    frame = small_frame(rng)
    model = ALS(rank=3, maxIter=2, seed=4).fit(frame)
    path = str(tmp_path / "m")
    model.write().save(path)
    with pytest.raises(IOError, match="already exists"):
        model.write().save(path)
    with pytest.raises(IOError, match="already exists"):
        model.save(path)  # save(path) == write().save(path)
    model.write().overwrite().save(path)
    assert ALSModel.load(path).rank == 3


def test_estimator_save_load_roundtrip(tmp_path):
    # the ALS estimator itself is writable/loadable (DefaultParamsWritable
    # parity, SURVEY.md §2.B11): explicitly-set params survive, defaults
    # stay defaults
    est = ALS(rank=7, regParam=0.25, implicitPrefs=True, alpha=12.0,
              coldStartStrategy="drop")
    path = str(tmp_path / "est")
    est.save(path)
    loaded = ALS.load(path)
    assert loaded.getRank() == 7
    assert loaded.getRegParam() == 0.25
    assert loaded.getImplicitPrefs() is True
    assert loaded.getAlpha() == 12.0
    assert loaded.getColdStartStrategy() == "drop"
    # maxIter was never set: must load as a default, not a set param
    assert not loaded.isSet(loaded.getParam("maxIter"))
    assert loaded.getMaxIter() == 10
    # same call-shape parity as the model
    import pytest

    with pytest.raises(IOError, match="already exists"):
        est.save(path)
    est.write().overwrite().save(path)


def test_failed_overwrite_preserves_old_save(rng, tmp_path, monkeypatch):
    # a _save_to failure mid-overwrite (ENOSPC, bug) must leave the old
    # save at `path` fully loadable — even across a RETRY of the failing
    # overwrite (code-review r2: the old move-aside scheme let a retry
    # rmtree the only good copy before failing again)
    import pytest

    frame = small_frame(rng)
    model = ALS(rank=3, maxIter=2, seed=4).fit(frame)
    path = str(tmp_path / "m")
    model.write().save(path)

    boom = RuntimeError("disk full")

    def failing_save_to(p):
        import os

        os.makedirs(p, exist_ok=True)  # leave partial contents behind
        raise boom

    monkeypatch.setattr(model, "_save_to", failing_save_to)
    for _ in range(2):  # the second attempt is the retry that used to lose
        with pytest.raises(RuntimeError, match="disk full"):
            model.write().overwrite().save(path)
        assert ALSModel.load(path).rank == 3  # old save intact

    monkeypatch.undo()
    model.write().overwrite().save(path)  # healthy retry still lands
    assert ALSModel.load(path).rank == 3

    # crash window between the two swap renames: path missing, old save
    # orphaned at .overwritten.tmp -> load and save must both recover it
    import os

    os.rename(path, path + ".overwritten.tmp")
    assert ALSModel.load(path).rank == 3  # load recovers the aside copy
    assert os.path.exists(path)


def test_overwrite_clears_stale_save_of_different_kind(rng, tmp_path):
    # overwriting a model save with an estimator save must not leave the
    # old model files loadable next to the new estimator.json
    import pytest

    frame = small_frame(rng)
    model = ALS(rank=3, maxIter=2, seed=4).fit(frame)
    p = str(tmp_path / "x")
    model.write().save(p)
    ALS(rank=5).write().overwrite().save(p)
    with pytest.raises(Exception):
        ALSModel.load(p)
    assert ALS.load(p).getRank() == 5


def test_fit_rejects_non_finite_ratings(rng):
    # a nan/inf rating would silently converge to nan factors through
    # the normal-equation sums — fit must fail fast with a count
    import pytest

    frame = small_frame(rng)
    r = np.asarray(frame["rating"], dtype=np.float32).copy()
    r[3] = np.nan
    r[7] = np.inf
    bad = ColumnarFrame({"user": np.asarray(frame["user"]),
                         "item": np.asarray(frame["item"]),
                         "rating": r})
    with pytest.raises(ValueError, match="2 non-finite"):
        ALS(rank=3, maxIter=2, seed=0).fit(bad)


def test_full_int64_ids_roundtrip_through_fit(rng):
    # the strict CSV parser carries ids beyond 2^53 exactly; the model
    # pipeline (remap -> fit -> factors -> recommend) must too
    base = (1 << 53) + 11
    u = np.array([base, base, base + 7, base + 7, base + 9] * 4,
                 dtype=np.int64)
    i = np.array([1, 2, 1, 3, 2] * 4, dtype=np.int64)
    r = rng.uniform(1, 5, len(u)).astype(np.float32)
    model = ALS(rank=2, maxIter=3, regParam=0.01, seed=0).fit(
        ColumnarFrame({"user": u, "item": i, "rating": r}))
    assert set(model.userFactors["id"].tolist()) == {base, base + 7,
                                                     base + 9}
    out = model.transform(ColumnarFrame({"user": u[:3], "item": i[:3]}))
    assert np.isfinite(out["prediction"]).all()
    recs = model.recommendForUserSubset(
        ColumnarFrame({"user": np.array([base], dtype=np.int64)}), 2)
    assert int(recs["user"][0]) == base


def test_model_param_setters(rng):
    """Reference ALSModel surface: serving-time knobs are settable on
    the fitted model (pyspark ALSModel.setPredictionCol etc.)."""
    import pytest

    frame = small_frame(rng)
    model = ALS(rank=3, maxIter=3, seed=0).fit(frame)
    model.setPredictionCol("score").setColdStartStrategy("drop")
    assert model.getPredictionCol() == "score"
    out = model.transform(ColumnarFrame({
        "user": np.array([10**6]), "item": np.array([0])}))
    assert "score" in out.columns and len(out) == 0  # dropped cold row
    with pytest.raises(ValueError):
        model.setColdStartStrategy("bogus")
    with pytest.raises(TypeError):
        model._set(rank=5)  # training-time params are not settable


def test_recommend_arrays_matches_frame_surface(rng):
    """recommend_arrays (the dense TPU-friendly serving surface) must
    produce the same ids/scores as recommendForAllUsers' struct column."""
    frame = small_frame(rng)
    model = ALS(rank=3, maxIter=4, seed=2).fit(frame)
    qids, ids, scores = model.recommend_arrays(4)
    recs = model.recommendForAllUsers(4)
    np.testing.assert_array_equal(qids, recs[recs.columns[0]])
    for row in range(len(qids)):
        got = [(int(i), float(s)) for i, s in
               zip(ids[row], scores[row])]
        want = [(int(i), float(s)) for i, s in
                recs["recommendations"][row]]
        assert got == want, row


def test_alpha_and_blocksize_validation():
    import pytest

    tiny = ColumnarFrame({"user": np.array([0]), "item": np.array([0]),
                          "rating": np.array([1.0], np.float32)})
    with pytest.raises(ValueError, match="alpha"):
        ALS(alpha=-1.0).fit(tiny)
    with pytest.raises(ValueError, match="blockSize"):
        ALS(blockSize=0).fit(tiny)


def test_fit_with_param_map_list_and_fitMultiple(rng):
    """Reference Estimator.fit(dataset, [pm...]) and fitMultiple
    overloads (python/pyspark/ml/base.py)."""
    u, i, r, _, _ = make_ratings(rng, 30, 20, 4, density=0.5)
    frame = ColumnarFrame({"user": u, "item": i, "rating": r})
    als = ALS(rank=3, maxIter=2, regParam=0.01, seed=0)
    maps = [{als.rank: 2}, {als.rank: 4}]
    models = als.fit(frame, maps)
    assert [m.rank for m in models] == [2, 4]
    assert als.getRank() == 3  # originals untouched

    pairs = list(als.fitMultiple(frame, maps))
    assert [i for i, _ in pairs] == [0, 1]
    assert [m.rank for _, m in pairs] == [2, 4]

    # single-dict overload still fits one model
    one = als.fit(frame, {als.rank: 5})
    assert one.rank == 5


def test_pipeline_fit_with_param_map_list(rng):
    from tpu_als import Pipeline

    u, i, r, _, _ = make_ratings(rng, 25, 15, 3, density=0.5)
    frame = ColumnarFrame({"user": u, "item": i, "rating": r})
    als = ALS(rank=3, maxIter=2, regParam=0.01, seed=0)
    pipe = Pipeline(stages=[als])
    models = pipe.fit(frame, [{als.rank: 2}, {als.rank: 4}])
    assert [m.stages[-1].rank for m in models] == [2, 4]


def test_fit_rejects_non_parammap_params(rng):
    u, i, r, _, _ = make_ratings(rng, 20, 12, 3, density=0.5)
    frame = ColumnarFrame({"user": u, "item": i, "rating": r})
    als = ALS(rank=3, maxIter=1)
    with pytest.raises(TypeError, match="param map"):
        als.fit(frame, als.rank)  # forgot the {param: value} wrapping


def test_fitMultiple_snapshots_estimator_state(rng):
    """Reference contract: fitMultiple fits against the estimator state
    AT CALL TIME — later mutations must not leak into pending fits."""
    u, i, r, _, _ = make_ratings(rng, 20, 12, 3, density=0.5)
    frame = ColumnarFrame({"user": u, "item": i, "rating": r})
    als = ALS(rank=3, maxIter=1, seed=0)
    it = als.fitMultiple(frame, [{}])
    als.setRank(9)  # mutate AFTER the iterator was created
    _, model = next(it)
    assert model.rank == 3  # snapshot, not live state


def test_low_reg_rank256_conditioning_warning(rng):
    """regParam below the measured f32 conditioning floor at rank>=256
    warns (docs/conditioning_rank256.md) — including regParam=0, the
    most ill-conditioned setting; normal configs stay silent."""
    import warnings

    import pytest

    from conftest import make_ratings

    from tpu_als import ALS, ColumnarFrame

    u, i, r, _, _ = make_ratings(rng, 40, 30, rank=3, density=0.4)
    frame = ColumnarFrame({"user": u, "item": i, "rating": r})
    for reg in (5e-5, 0.0):
        with pytest.warns(UserWarning, match="conditioning floor"):
            ALS(rank=256, maxIter=1, regParam=reg, seed=0).fit(frame)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        ALS(rank=256, maxIter=1, regParam=0.02, seed=0).fit(frame)
