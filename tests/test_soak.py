"""The production week (tpu_als/soak/): traffic model, chaos schedule,
orchestrator e2e, and the events-only verdict.

Four layers under test:

1. **traffic determinism** — the ISSUE's byte-for-byte pin: the same
   ``(seed, schedule)`` yields a byte-identical workload stream across
   a real process boundary, plus zipf/diurnal/catalog-growth/poison
   distribution sanity;
2. **chaos schedule mechanics** — construction-time validation (typo'd
   actions and fault specs fail the schedule, not minute three of the
   soak), scoped LIFO arming (including the scenario runner's new
   per-phase ``fault_spec``), and the default production-week placement;
3. **the soak itself** — a compressed in-process soak e2e asserting the
   verdict table AND its re-derivability from the dumped event list,
   plus the ``production-week`` scenario via the same code path the CLI
   takes;
4. **verdict standalone-ness** — ``verdict.py`` runs as a bare script
   against an events.jsonl with a POISONED ``jax`` on sys.path (any jax
   import explodes), proving the verdict needs nothing but the trail.

Plus the satellites that serve the soak: size-bounded obs rotation read
back transparently, ``filter_window`` slicing, and the soak vocabulary
pin (``analysis.vocab.check_soak_vocabulary``).
"""

import hashlib
import json
import os
import subprocess
import sys
import textwrap

import pytest

from tpu_als import obs, scenario
from tpu_als.obs import report
from tpu_als.resilience import faults
from tpu_als.scenario.spec import Phase, ScenarioSpec
from tpu_als.soak import chaos, traffic, verdict

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_VERDICT = os.path.join(_REPO, "tpu_als", "soak", "verdict.py")


@pytest.fixture(autouse=True)
def _fresh():
    faults.clear()
    reg = obs.reset()
    yield reg
    faults.clear()


def _small_cfg(**kw):
    base = dict(seed=23, windows=3, window_s=0.5,
                tenants=(("a", 3.0), ("b", 1.0)),
                base_qps=30.0, update_qps=20.0, catalog0=24,
                catalog_growth=4, n_users=32, poison_frac=0.1)
    base.update(kw)
    return traffic.TrafficConfig(**base)


# ---------------------------------------------------------------------------
# 1. traffic: the byte-for-byte determinism pin + distribution sanity


def test_traffic_stream_bytes_identical_across_processes():
    """Same (seed, schedule) -> byte-identical workload, across a REAL
    process boundary (the replay contract the soak's verdict leans on)."""
    cfg = _small_cfg()
    here = hashlib.sha256(traffic.stream_bytes(cfg)).hexdigest()
    prog = textwrap.dedent("""
        import hashlib, json, sys
        from tpu_als.soak import traffic
        cfg = traffic.TrafficConfig.from_dict(json.loads(sys.argv[1]))
        sys.stdout.write(
            hashlib.sha256(traffic.stream_bytes(cfg)).hexdigest())
    """)
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    p = subprocess.run(
        [sys.executable, "-c", prog, json.dumps(cfg.to_dict())],
        capture_output=True, text=True, env=env, check=True)
    assert p.stdout == here
    # and trivially stable within-process
    assert traffic.stream_bytes(cfg) == traffic.stream_bytes(cfg)


def test_traffic_stream_is_strict_json_with_null_poison():
    cfg = _small_cfg(poison_frac=0.5)
    lines = traffic.stream_bytes(cfg).decode().splitlines()
    assert lines
    poisoned = 0
    for line in lines:
        rec = json.loads(line)
        if rec["op"] == "rate" and rec["poison"]:
            assert rec["rating"] is None
            poisoned += 1
    assert poisoned > 0


def test_zipf_weights_monotone_and_normalized():
    w = traffic.zipf_weights(50, 1.1)
    assert w.shape == (50,)
    assert abs(float(w.sum()) - 1.0) < 1e-12
    assert all(w[i] > w[i + 1] for i in range(49))
    # heavier exponent -> more mass on the head
    assert traffic.zipf_weights(50, 2.0)[0] > w[0]


def test_diurnal_curve_peak_and_trough():
    cfg = _small_cfg(windows=4, day_windows=4, diurnal_amp=0.5)
    mults = [traffic.load_multiplier(cfg, w) for w in range(4)]
    assert mults[0] == pytest.approx(1.0)          # mean
    assert mults[1] == pytest.approx(1.5)          # peak
    assert mults[3] == pytest.approx(0.5)          # trough
    assert min(mults) >= 0.0


def test_catalog_growth_reaches_new_items():
    cfg = _small_cfg(windows=4, update_qps=200.0, poison_frac=0.0)
    for w in range(cfg.windows):
        ops = traffic.generate_window(cfg, w)
        items = [o["item"] for o in ops if o["op"] == "rate"]
        assert items and max(items) < traffic.catalog_size(cfg, w)
    # the last window's catalog really is reachable beyond window 0's
    late = [o["item"] for o in traffic.generate_window(cfg, 3)
            if o["op"] == "rate"]
    assert max(late) >= cfg.catalog0


def test_tenant_mix_follows_declared_weights():
    cfg = _small_cfg(base_qps=120.0, update_qps=80.0)
    totals = {"a": 0, "b": 0}
    for w in range(cfg.windows):
        counts = traffic.window_counts(cfg, w)
        for name in totals:
            totals[name] += counts[name]["serve"] + counts[name]["rate"]
    # a carries 3x b's weight; Poisson noise won't flip the ordering at
    # these volumes (and the draw is seeded anyway)
    assert totals["a"] > 2 * totals["b"]


def test_traffic_config_roundtrip_and_validation():
    cfg = _small_cfg()
    assert traffic.TrafficConfig.from_dict(cfg.to_dict()) == cfg
    with pytest.raises(ValueError, match="windows"):
        traffic.TrafficConfig(windows=0)
    with pytest.raises(ValueError, match="poison_frac"):
        traffic.TrafficConfig(poison_frac=1.5)
    with pytest.raises(ValueError, match="tenant"):
        traffic.TrafficConfig(tenants=())


# ---------------------------------------------------------------------------
# 2. chaos schedule: construction validation + scoped LIFO arming


def test_chaos_window_rejects_unknown_action_and_bad_spec():
    with pytest.raises(ValueError, match="unknown action"):
        chaos.ChaosWindow(1, "x", action="set_on_fire")
    with pytest.raises(faults.FaultSpecError):
        chaos.ChaosWindow(1, "x", fault_spec="not a spec !!")


def test_default_schedule_placement_and_cooldown():
    sched = chaos.default_schedule(8)
    names = {cw.name for cw in sched.windows}
    assert names == {"torn-publish", "poisoned-refit", "solver-rollback",
                     "tenant-churn", "preempt", "device-loss"}
    # warmup and cooldown windows stay clean
    assert all(1 <= cw.window <= 6 for cw in sched.windows)
    assert not sched.for_window(0) and not sched.for_window(7)
    # in-process mode drops the two CLI-child injections
    fast = chaos.default_schedule(5, subprocesses=False)
    assert {cw.name for cw in fast.windows} == {
        "torn-publish", "poisoned-refit", "solver-rollback",
        "tenant-churn"}
    assert sched.victims(1) == ("a",)
    for cw in sched.windows:
        assert cw.name in sched.describe()


def test_chaos_armed_is_scoped_and_overlays():
    faults.install("serve.gather=corrupt")
    sched = chaos.ChaosSchedule([
        chaos.ChaosWindow(2, "torn", fault_spec="serving.publish=corrupt",
                          action="torn_publish", victim="a")])
    d0 = faults.push_depth()
    with sched.armed(2):
        # overlay: the window's point is armed AND the base rule stays
        assert faults.armed("serving.publish")
        assert faults.armed("serve.gather")
        assert faults.push_depth() == d0 + 1
    assert not faults.armed("serving.publish")
    assert faults.armed("serve.gather")
    assert faults.push_depth() == d0
    with sched.armed(0):            # window with nothing scheduled
        assert faults.push_depth() == d0


def test_chaos_armed_pops_on_failure():
    sched = chaos.ChaosSchedule([
        chaos.ChaosWindow(1, "x", fault_spec="solve.gram=corrupt")])
    with pytest.raises(RuntimeError, match="boom"):
        with sched.armed(1):
            assert faults.armed("solve.gram")
            raise RuntimeError("boom")
    assert not faults.armed("solve.gram")
    assert faults.push_depth() == 0


def test_scenario_phase_scoped_fault_spec_lifo(_fresh):
    """The satellite the chaos scheduler rides on: a Phase's fault_spec
    is pushed just before its body and popped in a finally, overlaying
    the scenario-level spec without leaking into later phases."""
    seen = {}

    def armed_phase(ctx):
        seen["in_phase"] = faults.armed("solve.gram")
        seen["base_kept"] = faults.armed("serve.gather")
        seen["depth"] = faults.push_depth()

    def after_phase(ctx):
        seen["after"] = faults.armed("solve.gram")
        seen["base_still"] = faults.armed("serve.gather")

    spec = ScenarioSpec(
        name="tiny-phase-spec", doc="inline test spec",
        phases=(Phase("armed", armed_phase,
                      fault_spec="solve.gram=corrupt"),
                Phase("after", after_phase)),
        assertions=(), fault_spec="serve.gather=corrupt")
    scenario.run_scenario(spec)
    assert seen == {"in_phase": True, "base_kept": True, "depth": 2,
                    "after": False, "base_still": True}
    assert not faults.active()
    assert faults.push_depth() == 0


# ---------------------------------------------------------------------------
# 3. obs satellites: rotation read-back + window slicing


def test_rotation_and_rotated_trail_readback(tmp_path, monkeypatch, _fresh):
    monkeypatch.setenv("TPU_ALS_OBS_ROTATE_BYTES", "2000")
    reg = _fresh
    run = str(tmp_path / "run")
    reg.configure(run, config={"cmd": "soak-test"})
    total = 0
    for batch in range(3):
        for i in range(30):
            reg.emit("soak_window", window=total, offered=1, answered=1,
                     shed=0, errors=0)
            total += 1
        reg.finalize()
    reg.deconfigure()
    names = sorted(os.listdir(os.path.join(run)))
    rotated = [n for n in names if n.startswith("events.")
               and n.endswith(".jsonl") and n != "events.jsonl"]
    assert len(rotated) >= 2                      # e.g. events.000/001
    assert "events.jsonl" in names
    # readers walk rotations + live transparently, in emission order
    events = report.load_events(run)
    windows = [e["window"] for e in events if e["type"] == "soak_window"]
    assert windows == list(range(total))
    # the standalone verdict loader agrees byte for byte
    assert verdict.load_events(run) == events


def test_filter_window_slices_by_relative_seconds():
    events = [{"ts": 100.0 + t, "type": "soak_window", "window": t}
              for t in range(10)]
    assert report.filter_window(events) == events
    assert [e["window"] for e in report.filter_window(events, since=7)] \
        == [7, 8, 9]
    assert [e["window"]
            for e in report.filter_window(events, window="2:5")] == [2, 3, 4]
    assert [e["window"]
            for e in report.filter_window(events, window=":3")] == [0, 1, 2]
    assert [e["window"]
            for e in report.filter_window(events, window="8:")] == [8, 9]
    with pytest.raises(ValueError, match="A:B"):
        report.filter_window(events, window="5")


# ---------------------------------------------------------------------------
# 4. the verdict: pure-trail judging + standalone (poisoned-jax) runs


def _passing_trail():
    """A hand-written two-window trail that satisfies every check —
    the judge must need nothing beyond these records."""
    t = {"offered": 10, "answered": 10, "shed": 0, "errors": 0,
         "p99_ms": 40.0}
    victim = dict(t, errors=3, p99_ms=900.0)   # the targeted tenant
    return [
        {"type": "soak_start", "windows": 2, "window_s": 30.0,
         "tenants": 2, "seed": 17, "scheduled_injections": 1},
        {"type": "trace_span", "name": "live.visible", "seconds": 0.4},
        {"type": "trace_span", "name": "live.visible", "seconds": 0.6},
        {"type": "soak_window", "window": 0, "offered": 20,
         "answered": 20, "shed": 0, "errors": 0,
         "tenants": {"a": dict(t), "b": dict(t)}},
        {"type": "soak_injection", "window": 1, "action": "torn_publish",
         "fired": 1, "recovered": True, "victim": "a"},
        {"type": "soak_window", "window": 1, "offered": 20,
         "answered": 20, "shed": 0, "errors": 3,
         "tenants": {"a": victim, "b": dict(t)}},
    ]


def test_judge_passes_and_excuses_only_the_victim():
    result = verdict.judge(_passing_trail())
    assert result["passed"], result["checks"]
    assert result["windows"] == 2
    assert result["survived_minutes"] == 1.0
    # the victim's window-1 p99 (900ms) must NOT be the worst victim-free
    assert result["worst_window_p99_ms"] == 40.0
    assert result["freshness_p99_ms"] == 600.0
    assert result["injections"] == result["recoveries"] == 1


def test_judge_fails_on_victim_free_errors_and_missed_recovery():
    trail = _passing_trail()
    trail[-1]["tenants"]["b"]["errors"] = 1        # a bystander erred
    trail[4]["recovered"] = False                  # and no recovery
    result = verdict.judge(trail)
    assert not result["passed"]
    bad = {c["check"] for c in result["checks"] if not c["ok"]}
    assert bad == {"victim_free_errors", "injections_recovered"}


def test_judge_config_overrides_slo():
    result = verdict.judge(_passing_trail(), {"slo_ms": 10.0})
    assert not result["passed"]
    assert any(c["check"] == "serve_p99_victim_free" and not c["ok"]
               for c in result["checks"])


def test_verdict_standalone_with_poisoned_jax(tmp_path):
    """The acceptance pin: the verdict re-derives from events.jsonl with
    a POISONED jax on sys.path — any jax (or tpu_als) import would blow
    up the run, so passing proves the judge reads the trail alone."""
    poison = tmp_path / "poison"
    poison.mkdir()
    (poison / "jax.py").write_text(
        "raise ImportError('the verdict must not import jax')\n")
    (poison / "tpu_als.py").write_text(
        "raise ImportError('the verdict must not import tpu_als')\n")
    epath = tmp_path / "events.jsonl"
    epath.write_text("".join(json.dumps(e) + "\n"
                             for e in _passing_trail()))
    env = dict(os.environ)
    env["PYTHONPATH"] = str(poison)
    p = subprocess.run(
        [sys.executable, _VERDICT, str(epath), "--json"],
        capture_output=True, text=True, env=env, cwd=str(tmp_path))
    assert p.returncode == 0, p.stderr
    out = json.loads(p.stdout)
    assert out["passed"] is True and out["windows"] == 2
    # and the typed no-trail exit
    p2 = subprocess.run(
        [sys.executable, _VERDICT, str(tmp_path / "nowhere")],
        capture_output=True, text=True, env=env, cwd=str(tmp_path))
    assert p2.returncode == 2
    assert "no events.jsonl" in p2.stderr
    assert "Traceback" not in p2.stderr


def test_check_soak_vocabulary_clean():
    from tpu_als.analysis import vocab
    assert vocab.check_soak_vocabulary() == []


# ---------------------------------------------------------------------------
# 5. the soak itself: compressed e2e + the production-week scenario


def test_soak_e2e_inprocess_verdict_and_rederivability(tmp_path, _fresh):
    """The ISSUE's compressed soak e2e: a ~60s in-process production
    week (no CLI children) passes its verdict, and the SAME verdict
    re-derives from the dumped event list alone."""
    from tpu_als.soak import orchestrator

    cfg = traffic.TrafficConfig(
        seed=17, windows=5, window_s=1.0, base_qps=30.0,
        update_qps=15.0, catalog0=48, catalog_growth=6)
    # latency bounds widened for the shared-core tier-1 box (this test
    # runs at the tail of the full suite, where a GC pause can blow a
    # handful of requests past the default 1s p99); the tight default
    # SLOs are judged by test_production_week_scenario_passes below
    result = orchestrator.run_soak(
        cfg, subprocesses=False, workdir=str(tmp_path / "soak"),
        judge_config={"slo_ms": 5000.0, "freshness_slo_ms": 20000.0})
    assert result["passed"], result["checks"]
    assert result["windows"] == cfg.windows
    assert 0 < result["answered"] <= result["offered"]
    assert result["injections"] == result["recoveries"] == 4
    for inj in result["injection_records"]:
        assert inj["fired"] and inj["recovered"], inj
    # re-derive: dump the trail, reload it cold, judge again
    epath = tmp_path / "events.jsonl"
    epath.write_text("".join(json.dumps(e) + "\n"
                             for e in result["events"]))
    again = verdict.judge(verdict.load_events(str(epath)),
                          result["judge_config"])
    assert again["passed"] is True
    assert again["checks"] == result["checks"]
    assert again["survived_minutes"] == result["survived_minutes"]
    # nothing leaked: chaos disarmed, fleet stopped
    assert not faults.active()


def test_production_week_scenario_passes(_fresh):
    """ISSUE 19 acceptance: the composed scenario — soak + chaos + a
    subprocess re-derivation of the verdict — passes end to end on CPU
    at compressed timescale, via the same path `tpu_als scenario run
    production-week` takes."""
    reg = _fresh
    result = scenario.run_scenario(scenario.get_scenario("production-week"))
    assert result["passed"], result["assertions"]
    f = result["facts"]
    assert f["soak_passed"] is True
    assert f["all_injections_recovered"] is True
    assert f["victim_free_errors"] == 0
    assert f["rederive_exit"] == 0
    assert f["rederived_verdict_matches"] is True
    # the trail carries the soak vocabulary end to end
    assert reg.counter_value("soak.windows") >= 1
    assert any(e["type"] == "soak_verdict" for e in reg._events)
    assert sum(e["type"] == "soak_injection" for e in reg._events) == 6
