"""Batch-in-lanes Pallas SPD solver vs dense reference (interpret mode on
the CPU test mesh; the same kernel compiles for real on TPU — measured
2.2x the blocked kernel at rank 128 on v5e)."""

import numpy as np
import jax.numpy as jnp
import pytest

from tpu_als.ops.pallas_lanes import (
    LANES,
    available,
    spd_solve_lanes,
    supported_rank,
)
from tpu_als.ops.solve import solve_spd


def _spd_problem(rng, N, r, scale=1.0):
    M = rng.normal(size=(N, r, r)).astype(np.float32) * scale
    A = M @ M.transpose(0, 2, 1) + 0.5 * np.eye(r, dtype=np.float32)
    b = rng.normal(size=(N, r)).astype(np.float32)
    return jnp.asarray(A), jnp.asarray(b)


@pytest.mark.parametrize("N,r", [
    (5, 4),           # tiny everything, heavy batch padding
    (37, 10),         # the ALS default rank
    (LANES, 32),      # exactly one lane group
    (LANES + 9, 64),  # two groups, second mostly padding
    (40, 128),        # the benchmark rank
])
def test_matches_dense_solve(rng, N, r):
    A, b = _spd_problem(rng, N, r)
    x = np.asarray(spd_solve_lanes(A, b, interpret=True))
    ref = np.stack([np.linalg.solve(np.asarray(A)[k], np.asarray(b)[k])
                    for k in range(N)])
    denom = max(1.0, np.abs(ref).max())
    assert np.abs(x - ref).max() / denom < 5e-3


@pytest.mark.parametrize("panel", [1, 4, 8, 16])
def test_panel_widths_agree(rng, panel):
    # the panelized trailing update must reproduce the rank-1 recurrence
    # (same math, different blocking) at the benchmark rank
    N, r = LANES + 8, 128
    A, b = _spd_problem(rng, N, r, scale=1.0 / np.sqrt(r))
    x = np.asarray(spd_solve_lanes(A, b, panel=panel, interpret=True))
    ref = solve_spd(A, b, jnp.ones(N), backend="xla")
    np.testing.assert_allclose(x, np.asarray(ref), atol=1e-3, rtol=1e-2)


@pytest.mark.parametrize("panel,r", [(8, 128), (16, 128), (32, 128),
                                     (8, 24)])
def test_mxu_trailing_update_agrees(rng, panel, r):
    # the MXU rank-k trailing update (dot_general over the panel dim)
    # must reproduce the VPU sweep's math — same factorization, the
    # contraction moved to the matrix unit.  VPU-vs-XLA agreement per
    # panel is test_panel_widths_agree's pin; here MXU goes against the
    # XLA reference at every panel and against the VPU sweep once, on
    # the cheap sub-128 case (each interpret-mode compile is ~10s of
    # tier-1 budget, and the heavy VPU reruns re-prove a pinned fact)
    N = LANES + 8
    A, b = _spd_problem(rng, N, r, scale=1.0 / np.sqrt(r))
    x_mxu = np.asarray(spd_solve_lanes(A, b, panel=panel, mxu=True,
                                       interpret=True))
    ref = solve_spd(A, b, jnp.ones(N), backend="xla")
    np.testing.assert_allclose(x_mxu, np.asarray(ref), atol=1e-3,
                               rtol=1e-2)
    if r < 128:
        x_vpu = np.asarray(spd_solve_lanes(A, b, panel=panel, mxu=False,
                                           interpret=True))
        np.testing.assert_allclose(x_mxu, x_vpu, atol=1e-3, rtol=1e-2)


def test_selected_mxu_defaults_conservative():
    # no probe has validated the MXU variant off-TPU: dispatch must get
    # False (the VPU sweep), never an unvalidated kernel
    from tpu_als.ops.pallas_lanes import selected_mxu

    assert selected_mxu(128) is False


def test_panel_rounds_to_divisor(rng):
    # rank 24 pads to 24; DEFAULT_PANEL=8 divides it, but panel=16 must
    # round down to a divisor instead of tracing a ragged loop
    N, r = 12, 24
    A, b = _spd_problem(rng, N, r)
    x = np.asarray(spd_solve_lanes(A, b, panel=16, interpret=True))
    ref = np.stack([np.linalg.solve(np.asarray(A)[k], np.asarray(b)[k])
                    for k in range(N)])
    assert np.abs(x - ref).max() / max(1.0, np.abs(ref).max()) < 5e-3


def test_matches_solve_spd_contract(rng):
    # same prep as solve_spd: empty rows (count=0) -> identity A, zero b
    N, r = 24, 16
    A, b = _spd_problem(rng, N, r)
    count = np.ones(N, np.float32)
    count[::5] = 0.0
    b = jnp.asarray(np.where(count[:, None] > 0, np.asarray(b), 0.0))
    x_ref = solve_spd(A, b, jnp.asarray(count), backend="xla")
    eye = jnp.eye(r)
    Ap = jnp.where((count <= 0)[:, None, None], eye, A) + 1e-6 * eye
    x_lan = spd_solve_lanes(Ap, b, interpret=True)
    np.testing.assert_allclose(np.asarray(x_lan), np.asarray(x_ref),
                               atol=2e-4, rtol=2e-3)
    assert (np.asarray(x_lan)[::5] == 0).all()


def test_rank_gate():
    # the [r, r, 128] scratch exceeds VMEM above rank 128: the blocked
    # kernel owns that regime and available() must refuse without probing
    assert supported_rank(128)
    assert not supported_rank(136)
    assert available(256) is False


def test_solve_spd_lanes_backend_dispatch(rng, monkeypatch):
    # backend='lanes' must route to spd_solve_lanes (a refactor dropping
    # 'lanes' from the dispatch would otherwise only surface on TPU, at
    # trace time); unknown backends must raise
    from tpu_als.ops import pallas_lanes

    N, r = 16, 8
    A, b = _spd_problem(rng, N, r)
    count = jnp.ones((N,), jnp.float32)
    hits = []

    def fake(Ax, bx, panel=None, mxu=False, interpret=False):
        hits.append((Ax.shape, panel))
        return jnp.linalg.solve(Ax, bx[..., None])[..., 0]

    monkeypatch.setattr(pallas_lanes, "spd_solve_lanes", fake)
    x = solve_spd(A, b, count, backend="lanes")
    assert hits and hits[0][0] == (N, r, r)
    ref = solve_spd(A, b, count, backend="xla")
    np.testing.assert_allclose(np.asarray(x), np.asarray(ref),
                               atol=1e-4, rtol=1e-4)
    with pytest.raises(ValueError, match="unknown solve backend"):
        solve_spd(A, b, count, backend="warp")


class TestAvailableProbe:
    """Same standard as pallas_solve.available: wrong-but-finite output
    fails, crashes fail, correct output passes."""

    def _probe(self, monkeypatch, fake_kernel):
        from tpu_als.ops import pallas_lanes
        from tpu_als.utils import platform

        monkeypatch.setattr(platform, "on_tpu", lambda: True)
        monkeypatch.setattr(pallas_lanes, "_AVAILABLE", {})
        monkeypatch.setattr(pallas_lanes, "_PANEL", {})
        monkeypatch.setattr(pallas_lanes, "_MXU", {})
        monkeypatch.setattr(pallas_lanes, "spd_solve_lanes", fake_kernel)
        return pallas_lanes.available(32)

    def test_rejects_wrong_but_finite_kernel(self, monkeypatch):
        assert self._probe(
            monkeypatch,
            lambda A, b, panel=None, mxu=False, interpret=False: b,
        ) is False

    def test_rejects_crashing_kernel(self, monkeypatch):
        def boom(A, b, panel=None, mxu=False, interpret=False):
            raise RuntimeError("mosaic compile failure")

        assert self._probe(monkeypatch, boom) is False

    def test_accepts_correct_kernel(self, monkeypatch):
        from tpu_als.ops import pallas_lanes

        assert self._probe(
            monkeypatch,
            lambda A, b, panel=None, mxu=False, interpret=False:
            jnp.linalg.solve(A, b[..., None])[..., 0],
        ) is True
        # the probe ladder tries the MXU variant first; a kernel that
        # validates under it records the MXU selection for dispatch
        assert pallas_lanes.selected_mxu(32) is True

    def test_mxu_crash_falls_back_to_vpu(self, monkeypatch):
        # an MXU-only Mosaic failure must not disable the kernel: the
        # ladder degrades to the VPU sweep and records mxu=False
        from tpu_als.ops import pallas_lanes

        def picky(A, b, panel=None, mxu=False, interpret=False):
            if mxu:
                raise RuntimeError("mosaic compile failure")
            return jnp.linalg.solve(A, b[..., None])[..., 0]

        assert self._probe(monkeypatch, picky) is True
        assert pallas_lanes.selected_mxu(32) is False
