"""Distributed-without-a-pod tests — SURVEY.md §4 item 4: an 8-device forced
CPU mesh (the analog of the reference suite's ``local-cluster[...]`` masters)
must reproduce the single-device result to fp tolerance.
"""

import numpy as np
import pytest

import jax

from tpu_als.core.als import AlsConfig, train
from tpu_als.core.ratings import build_csr_buckets
from tpu_als.parallel.data import partition_balanced, shard_csr
from tpu_als.parallel.mesh import make_mesh
from tpu_als.parallel.trainer import train_sharded

from conftest import make_ratings


def _both(rng, cfg, num_users=50, num_items=35, implicit=False, n_dev=8):
    u, i, r, _, _ = make_ratings(rng, num_users, num_items, rank=3, density=0.4)
    if implicit:
        r = np.abs(r) * 4 + 0.1

    ucsr = build_csr_buckets(u, i, r, num_users, min_width=4)
    icsr = build_csr_buckets(i, u, r, num_items, min_width=4)
    U1, V1 = train(ucsr, icsr, cfg)

    mesh = make_mesh(n_dev)
    upart = partition_balanced(np.bincount(u, minlength=num_users), n_dev)
    ipart = partition_balanced(np.bincount(i, minlength=num_items), n_dev)
    ush = shard_csr(upart, ipart, u, i, r, min_width=4)
    ish = shard_csr(ipart, upart, i, u, r, min_width=4)
    Us, Vs = train_sharded(mesh, upart, ipart, ush, ish, cfg)
    # slot space -> entity space
    U8 = np.asarray(Us)[upart.slot]
    V8 = np.asarray(Vs)[ipart.slot]
    return (np.asarray(U1), np.asarray(V1)), (U8, V8)


@pytest.mark.parametrize("implicit", [False, True])
def test_sharded_equals_single_device(rng, implicit):
    assert len(jax.devices()) == 8, "conftest must force an 8-device CPU mesh"
    cfg = AlsConfig(rank=3, max_iter=4, reg_param=0.05,
                    implicit_prefs=implicit, alpha=8.0, seed=11)
    (U1, V1), (U8, V8) = _both(np.random.default_rng(1), cfg, implicit=implicit)
    np.testing.assert_allclose(U8, U1, rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(V8, V1, rtol=2e-3, atol=2e-3)


def test_sharded_nonnegative(rng):
    cfg = AlsConfig(rank=3, max_iter=3, reg_param=0.05, nonnegative=True, seed=2)
    (U1, V1), (U8, V8) = _both(np.random.default_rng(3), cfg)
    assert U8.min() >= -1e-5
    np.testing.assert_allclose(U8, U1, rtol=5e-3, atol=5e-3)


def test_partition_balance():
    rng = np.random.default_rng(0)
    # power-law counts
    counts = (rng.pareto(1.2, size=1000) * 10).astype(np.int64) + 1
    part = partition_balanced(counts, 8)
    loads = np.bincount(part.owner, weights=counts, minlength=8)
    avg = counts.sum() / 8
    assert loads.max() <= avg + counts.max()
    # slots are unique and in range
    slots = part.slot
    assert len(np.unique(slots)) == len(slots)
    assert slots.max() < part.padded_rows


def test_uneven_entity_count(rng):
    # num_users not divisible by device count; some devices get fewer rows
    cfg = AlsConfig(rank=2, max_iter=2, reg_param=0.1, seed=5)
    (U1, V1), (U8, V8) = _both(np.random.default_rng(7), cfg,
                               num_users=13, num_items=9, n_dev=8)
    np.testing.assert_allclose(U8, U1, rtol=2e-3, atol=2e-3)
