"""Distributed-without-a-pod tests — SURVEY.md §4 item 4: an 8-device forced
CPU mesh (the analog of the reference suite's ``local-cluster[...]`` masters)
must reproduce the single-device result to fp tolerance.
"""

import numpy as np
import pytest

import jax

from tpu_als.core.als import AlsConfig, train
from tpu_als.core.ratings import build_csr_buckets
from tpu_als.parallel.data import partition_balanced, shard_csr
from tpu_als.parallel.mesh import make_mesh
from tpu_als.parallel.trainer import train_sharded

from conftest import make_ratings


def _both(rng, cfg, num_users=50, num_items=35, implicit=False, n_dev=8,
          strategy="all_gather", gather_blocks=4):
    u, i, r, _, _ = make_ratings(rng, num_users, num_items, rank=3, density=0.4)
    if implicit:
        r = np.abs(r) * 4 + 0.1

    ucsr = build_csr_buckets(u, i, r, num_users, min_width=4)
    icsr = build_csr_buckets(i, u, r, num_items, min_width=4)
    U1, V1 = train(ucsr, icsr, cfg)

    mesh = make_mesh(n_dev)
    upart = partition_balanced(np.bincount(u, minlength=num_users), n_dev)
    ipart = partition_balanced(np.bincount(i, minlength=num_items), n_dev)
    if strategy in ("ring", "ring_overlap"):
        from tpu_als.parallel.comm import shard_csr_grid
        from tpu_als.parallel.trainer import stacked_counts

        ush = shard_csr_grid(upart, ipart, u, i, r, min_width=4)
        ish = shard_csr_grid(ipart, upart, i, u, r, min_width=4)
        rc = (stacked_counts(upart, u, r, positive_only=implicit),
              stacked_counts(ipart, i, r, positive_only=implicit))
        Us, Vs = train_sharded(mesh, upart, ipart, ush, ish, cfg,
                               strategy=strategy, ring_counts=rc)
    else:
        ush = shard_csr(upart, ipart, u, i, r, min_width=4)
        ish = shard_csr(ipart, upart, i, u, r, min_width=4)
        Us, Vs = train_sharded(mesh, upart, ipart, ush, ish, cfg,
                               strategy=strategy,
                               gather_blocks=gather_blocks)
    # slot space -> entity space
    U8 = np.asarray(Us)[upart.slot]
    V8 = np.asarray(Vs)[ipart.slot]
    return (np.asarray(U1), np.asarray(V1)), (U8, V8)


@pytest.mark.parametrize("implicit", [False, True])
def test_sharded_equals_single_device(rng, implicit):
    assert len(jax.devices()) == 8, "conftest must force an 8-device CPU mesh"
    cfg = AlsConfig(rank=3, max_iter=4, reg_param=0.05,
                    implicit_prefs=implicit, alpha=8.0, seed=11)
    (U1, V1), (U8, V8) = _both(np.random.default_rng(1), cfg, implicit=implicit)
    np.testing.assert_allclose(U8, U1, rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(V8, V1, rtol=2e-3, atol=2e-3)


@pytest.mark.parametrize("implicit", [False, True])
@pytest.mark.parametrize("strategy", ["ring_overlap", "all_gather_chunked"])
def test_overlap_variants_equal_single_device(rng, strategy, implicit):
    """Both overlapped schedules (double-buffered ring, column-blocked
    gather) are pure reorderings of the same math — they must reproduce
    the single-device result to the same tolerance as the base paths.
    gather_blocks=3 leaves a ragged last block on purpose."""
    cfg = AlsConfig(rank=3, max_iter=4, reg_param=0.05,
                    implicit_prefs=implicit, alpha=8.0, seed=11)
    (U1, V1), (U8, V8) = _both(np.random.default_rng(1), cfg,
                               implicit=implicit, strategy=strategy,
                               gather_blocks=3)
    np.testing.assert_allclose(U8, U1, rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(V8, V1, rtol=2e-3, atol=2e-3)


def test_sharded_nonnegative(rng):
    cfg = AlsConfig(rank=3, max_iter=3, reg_param=0.05, nonnegative=True, seed=2)
    (U1, V1), (U8, V8) = _both(np.random.default_rng(3), cfg)
    assert U8.min() >= -1e-5
    np.testing.assert_allclose(U8, U1, rtol=5e-3, atol=5e-3)


def test_partition_balance():
    rng = np.random.default_rng(0)
    # power-law counts
    counts = (rng.pareto(1.2, size=1000) * 10).astype(np.int64) + 1
    part = partition_balanced(counts, 8)
    loads = np.bincount(part.owner, weights=counts, minlength=8)
    avg = counts.sum() / 8
    assert loads.max() <= avg + counts.max()
    # slots are unique and in range
    slots = part.slot
    assert len(np.unique(slots)) == len(slots)
    assert slots.max() < part.padded_rows


def test_uneven_entity_count(rng):
    # num_users not divisible by device count; some devices get fewer rows
    cfg = AlsConfig(rank=2, max_iter=2, reg_param=0.1, seed=5)
    (U1, V1), (U8, V8) = _both(np.random.default_rng(7), cfg,
                               num_users=13, num_items=9, n_dev=8)
    np.testing.assert_allclose(U8, U1, rtol=2e-3, atol=2e-3)


def test_comm_bytes_per_iter_model(rng):
    """The traffic model (SURVEY §5.5 'gather bytes') against
    hand-computed values for every strategy."""
    from tpu_als.parallel.a2a import build_a2a
    from tpu_als.parallel.comm import shard_csr_grid
    from tpu_als.parallel.trainer import comm_bytes_per_iter

    nU = nI = 64
    D, r = 8, 16
    u = np.repeat(np.arange(nU), 2)
    i = (u * 7 + 3) % nI
    vals = np.ones(len(u), np.float32)
    upart = partition_balanced(np.bincount(u, minlength=nU), D)
    ipart = partition_balanced(np.bincount(i, minlength=nI), D)

    # all_gather: (D-1) * rows/shard * r * 4 per half-step, both sides
    ag = comm_bytes_per_iter("all_gather", upart, ipart, r)
    assert ag == 2 * (D - 1) * 8 * r * 4

    # implicit adds the psum(YtY) term on top of the same gathers
    agi = comm_bytes_per_iter("all_gather", upart, ipart, r,
                              implicit=True)
    assert agi == ag + 2 * 2 * (D - 1) * r * r * 4 // D

    # ring at 1 tile: D rotations per pass (no resident-shard discount,
    # the shard must return home) -> D/(D-1) x the all_gather bytes
    assert comm_bytes_per_iter("ring", upart, ipart, r) == \
        ag * D // (D - 1)
    # with containers: multiplied by the tile counts the grid implies
    ug = shard_csr_grid(upart, ipart, u, i, vals, min_width=4)
    ig = shard_csr_grid(ipart, upart, i, u, vals, min_width=4)
    ring = comm_bytes_per_iter("ring", upart, ipart, r,
                               user_container=ug, item_container=ig)
    assert ring >= ag * D // (D - 1)

    # ring_overlap: identical bytes to ring — double-buffering reorders
    # the schedule, it does not change what moves
    assert comm_bytes_per_iter("ring_overlap", upart, ipart, r,
                               user_container=ug, item_container=ig) == ring
    assert comm_bytes_per_iter("ring_overlap", upart, ipart, r) == \
        ag * D // (D - 1)

    # all_gather_chunked: same bytes as all_gather at 1 tile (the column
    # blocks partition the shard, so block count never changes bytes);
    # with containers it scales by the row-tile count since each tile
    # pass re-gathers its blocks
    assert comm_bytes_per_iter("all_gather_chunked", upart, ipart, r) == ag
    ush = shard_csr(upart, ipart, u, i, vals, min_width=4)
    ish = shard_csr(ipart, upart, i, u, vals, min_width=4)
    agc = comm_bytes_per_iter("all_gather_chunked", upart, ipart, r,
                              user_container=ush, item_container=ish)
    assert agc >= ag

    # a2a: 2*(D-1)*R*r*4 per half-step from the built plans
    ua = build_a2a(upart, ipart, u, i, vals, min_width=4)
    ia = build_a2a(ipart, upart, i, u, vals, min_width=4)
    a2a = comm_bytes_per_iter("all_to_all", upart, ipart, r,
                              user_container=ua, item_container=ia)
    assert a2a == 2 * (D - 1) * (ua.request_budget
                                 + ia.request_budget) * r * 4
    # (whether a2a undercuts the gather is a layout property —
    # tests/test_a2a.py pins the winning regime; here only the model)

    import pytest as _pytest

    with _pytest.raises(ValueError, match="A2aCsr"):
        comm_bytes_per_iter("all_to_all", upart, ipart, r)
