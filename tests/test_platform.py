"""probe_kernel scaffolding: trace-safety and failure caching."""

import warnings

import jax
import jax.numpy as jnp

from tpu_als.utils import platform


def test_probe_inside_jit_trace_degrades_without_caching(monkeypatch):
    """A probe firing while a training step is being TRACED (solve_spd's
    auto dispatch runs under jit) cannot execute — round-2 regression: its
    concrete arrays became tracers, block_until_ready raised, and False
    was CACHED, silently downgrading the whole process to the XLA path
    (the RMSE benchmark trained 40% slower than the headline run).  The
    contract now: degrade that one trace, cache nothing, warn — and every
    step builder prewarms probes eagerly so this never fires in the
    shipped call paths."""
    monkeypatch.setattr(platform, "on_tpu", lambda: True)
    cache = {}
    calls = []

    def probe():
        calls.append(1)
        return True

    @jax.jit
    def traced(y):
        ok = platform.probe_kernel(cache, "k", probe)
        return y * (1.0 if ok else 0.0)

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        out = traced(jnp.ones(3))
    assert any("inside a jit trace" in str(x.message) for x in w)
    assert cache == {}        # nothing cached from the in-trace request
    assert calls == []        # the probe body never ran under the trace
    assert float(out[0]) == 0.0  # that trace used the fallback path
    # a later EAGER call probes and caches normally
    assert platform.probe_kernel(cache, "k", probe) is True
    assert cache["k"] is True and calls == [1]


def test_transient_failure_not_cached_until_retries_exhausted(monkeypatch):
    monkeypatch.setattr(platform, "on_tpu", lambda: True)
    cache = {}
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 2:
            raise RuntimeError("backend UNAVAILABLE: tunnel dropped")
        return True

    import time as _time

    monkeypatch.setattr(_time, "sleep", lambda s: None)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        assert platform.probe_kernel(cache, "k", flaky) is True
    assert len(calls) == 2  # retried once, then succeeded and cached


def test_real_failure_cached_once(monkeypatch):
    monkeypatch.setattr(platform, "on_tpu", lambda: True)
    cache = {}
    calls = []

    def broken():
        calls.append(1)
        raise ValueError("Mosaic lowering rejected the kernel")

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        assert platform.probe_kernel(cache, "k", broken) is False
        assert platform.probe_kernel(cache, "k", broken) is False
    assert len(calls) == 1  # non-transient: no retry, cached
