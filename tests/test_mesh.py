"""Mesh helpers: slice-major device ordering for multi-slice (DCN)
deployments, and the DCN-boundary accounting the ring cost model uses."""

from dataclasses import dataclass

from tpu_als.parallel.mesh import (
    make_mesh,
    order_devices_slice_major,
    slice_boundaries,
)


@dataclass
class FakeDev:
    id: int
    slice_index: int = None


def test_single_slice_order_preserved():
    devs = [FakeDev(3), FakeDev(1), FakeDev(2)]
    assert order_devices_slice_major(devs) == devs
    assert slice_boundaries(devs) == []


def test_multi_slice_groups_contiguous():
    # interleaved arrival order, two slices of 3
    devs = [FakeDev(0, 0), FakeDev(3, 1), FakeDev(1, 0),
            FakeDev(4, 1), FakeDev(2, 0), FakeDev(5, 1)]
    out = order_devices_slice_major(devs)
    assert [d.slice_index for d in out] == [0, 0, 0, 1, 1, 1]
    assert [d.id for d in out] == [0, 1, 2, 3, 4, 5]
    assert slice_boundaries(devs) == [3]


def test_mixed_none_slice_index_sorts_first():
    devs = [FakeDev(0, 1), FakeDev(1, None), FakeDev(2, 0)]
    out = order_devices_slice_major(devs)
    assert [d.id for d in out] == [1, 2, 0]


def test_make_mesh_runs_on_cpu_devices():
    mesh = make_mesh(4)
    assert mesh.devices.size == 4


import pytest

# (n_slices, enumeration permutation, expected DCN boundaries)
_TOPOLOGIES = [
    (2, (0, 4, 1, 5, 2, 6, 3, 7), [4]),        # interleaved 2 x 4
    (4, (7, 2, 5, 0, 3, 6, 1, 4), [2, 4, 6]),  # shuffled 4 x 2
]


@pytest.mark.parametrize("n_slices,perm,bounds", _TOPOLOGIES)
def test_simulated_multi_slice_mesh_orders_and_bounds(n_slices, perm,
                                                      bounds):
    # CPU devices carry no slice_index; the simulated assignment drives
    # the SAME slice-major code path a pod deployment takes, pinning
    # device-order regrouping + one DCN boundary per slice seam
    import jax

    from tpu_als.parallel.mesh import simulated_slice_of

    pool = jax.devices()[:8]
    slice_of = simulated_slice_of(n_slices, pool)
    per = 8 // n_slices
    assert [slice_of(d) for d in sorted(pool, key=lambda d: d.id)] == \
        [k // per for k in range(8)]
    shuffled = [pool[k] for k in perm]
    mesh = make_mesh(devices=shuffled, slice_of=slice_of)
    order = [slice_of(d) for d in mesh.devices.flat]
    assert order == [k // per for k in range(8)], order
    assert slice_boundaries(list(mesh.devices.flat), slice_of) == bounds


@pytest.fixture(scope="module")
def _flat_baseline():
    """One flat-mesh training run shared by every topology case."""
    import numpy as np

    from tpu_als.core.als import AlsConfig
    from tpu_als.parallel.data import partition_balanced, shard_csr
    from tpu_als.parallel.trainer import train_sharded

    rng = np.random.default_rng(123)
    nU, nI, nnz, D = 40, 30, 500, 8
    u = rng.integers(0, nU, nnz)
    i = rng.integers(0, nI, nnz)
    r = np.abs(rng.normal(size=nnz)).astype(np.float32) + 0.1
    upart = partition_balanced(np.bincount(u, minlength=nU), D)
    ipart = partition_balanced(np.bincount(i, minlength=nI), D)
    ush = shard_csr(upart, ipart, u, i, r, min_width=4)
    ish = shard_csr(ipart, upart, i, u, r, min_width=4)
    cfg = AlsConfig(rank=4, max_iter=2, reg_param=0.05,
                    implicit_prefs=True, alpha=2.0, seed=0)
    U0, V0 = train_sharded(make_mesh(D), upart, ipart, ush, ish, cfg)
    import numpy as _np

    return (upart, ipart, ush, ish, cfg,
            _np.asarray(U0), _np.asarray(V0))


@pytest.mark.parametrize("n_slices,perm,bounds", _TOPOLOGIES)
def test_multi_slice_training_matches_flat_mesh(_flat_baseline, n_slices,
                                                perm, bounds):
    """Training over a mesh whose device order was regrouped through the
    slice-major path must equal the flat default mesh bit-for-layout:
    mesh position, not physical device identity, carries the semantics
    (SURVEY §5.8 'DCN across slices' — simulated; VERDICT r3 #5)."""
    import jax
    import numpy as np

    from tpu_als.parallel.mesh import simulated_slice_of
    from tpu_als.parallel.trainer import train_sharded

    upart, ipart, ush, ish, cfg, U0, V0 = _flat_baseline
    pool = jax.devices()[:8]
    mesh = make_mesh(devices=[pool[k] for k in perm],
                     slice_of=simulated_slice_of(n_slices, pool))
    U1, V1 = train_sharded(mesh, upart, ipart, ush, ish, cfg)
    np.testing.assert_allclose(np.asarray(U1), U0, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(V1), V0, rtol=1e-5, atol=1e-5)


def test_make_mesh_rejects_overask():
    import pytest

    from tpu_als.parallel.mesh import make_mesh

    with pytest.raises(ValueError, match="silently smaller mesh"):
        make_mesh(99)
