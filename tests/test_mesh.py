"""Mesh helpers: slice-major device ordering for multi-slice (DCN)
deployments, and the DCN-boundary accounting the ring cost model uses."""

from dataclasses import dataclass

from tpu_als.parallel.mesh import (
    make_mesh,
    order_devices_slice_major,
    slice_boundaries,
)


@dataclass
class FakeDev:
    id: int
    slice_index: int = None


def test_single_slice_order_preserved():
    devs = [FakeDev(3), FakeDev(1), FakeDev(2)]
    assert order_devices_slice_major(devs) == devs
    assert slice_boundaries(devs) == []


def test_multi_slice_groups_contiguous():
    # interleaved arrival order, two slices of 3
    devs = [FakeDev(0, 0), FakeDev(3, 1), FakeDev(1, 0),
            FakeDev(4, 1), FakeDev(2, 0), FakeDev(5, 1)]
    out = order_devices_slice_major(devs)
    assert [d.slice_index for d in out] == [0, 0, 0, 1, 1, 1]
    assert [d.id for d in out] == [0, 1, 2, 3, 4, 5]
    assert slice_boundaries(devs) == [3]


def test_mixed_none_slice_index_sorts_first():
    devs = [FakeDev(0, 1), FakeDev(1, None), FakeDev(2, 0)]
    out = order_devices_slice_major(devs)
    assert [d.id for d in out] == [1, 2, 0]


def test_make_mesh_runs_on_cpu_devices():
    mesh = make_mesh(4)
    assert mesh.devices.size == 4
