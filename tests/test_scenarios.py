"""Production-day scenario harness (tpu_als/scenario/).

Three layers under test:

1. the harness mechanics themselves — spec validation, ``$key`` bound
   resolution, delta-based counter/event judging, the obs trail
   (``scenario_start``/``scenario_phase``/``scenario_assert``/
   ``scenario_end``), fault-arming scope, LIFO cleanups — via tiny
   inline specs that never touch jax;
2. the five NAMED scenarios, each run end to end in-process (the same
   code path ``tpu_als scenario run`` takes) — including the
   preempt-under-serve acceptance property (bitwise resume while
   serving kept answering) and the subprocess-based pytest port of the
   chaos_smoke kill-and-resume flow;
3. the CLI error contract — unknown scenario names and unparseable
   ``TPU_ALS_FAULT_SPEC`` fail with one typed line and exit 2, never a
   traceback.

Plus the degraded-mode serving coverage ISSUE 6 asks for: the
``serve.degraded`` counter and ``serve_degraded`` event in ONE process,
with the shard loss injected through the fault harness.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

from tpu_als import obs, scenario
from tpu_als.resilience import faults
from tpu_als.scenario.spec import (
    Assertion,
    Phase,
    ScenarioSpec,
    evaluate_assertion,
    resolve_bound,
)

pytestmark = pytest.mark.scenario

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh():
    """Disarmed faults + a fresh registry per test (scenario runs judge
    counter DELTAS, but a clean slate keeps failures readable)."""
    faults.clear()
    reg = obs.reset()
    yield reg
    faults.clear()


def _cli(args, env_extra=None):
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.update(env_extra or {})
    return subprocess.run(
        [sys.executable, "-c",
         "import sys; from tpu_als.cli import main; main(sys.argv[1:])"]
        + args, capture_output=True, text=True, env=env)


# ---------------------------------------------------------------------------
# 1. harness mechanics (jax-free inline specs)


def test_registry_has_the_issue_scenarios():
    for name in ("traffic-spike", "preempt-under-serve", "torn-publish",
                 "cold-start", "preempt-resume", "flight-recorder",
                 "continuous-freshness"):
        assert scenario.get_scenario(name).name == name


def test_unknown_scenario_is_typed_and_lists_available():
    with pytest.raises(scenario.UnknownScenario) as ei:
        scenario.get_scenario("no-such")
    assert ei.value.name == "no-such"
    assert "traffic-spike" in str(ei.value)
    assert set(ei.value.available) == set(scenario.names())


def test_assertion_rejects_unknown_kind_and_op():
    with pytest.raises(ValueError, match="unknown kind"):
        Assertion("x", "vibes", value=1)
    with pytest.raises(ValueError, match="unknown op"):
        Assertion("x", "fact", op="~=", fact="f", value=1)


def test_resolve_bound_config_reference():
    assert resolve_bound("$slo_ms", {"slo_ms": 250.0}) == 250.0
    assert resolve_bound(42, {}) == 42
    with pytest.raises(scenario.ScenarioError, match="not set"):
        resolve_bound("$missing", {})


def _tiny_spec(phases, assertions, fault_spec=None, defaults=None):
    return ScenarioSpec(name="tiny", doc="inline test spec",
                        phases=tuple(phases),
                        assertions=tuple(assertions),
                        fault_spec=fault_spec,
                        defaults=defaults or {})


def test_run_scenario_obs_trail_and_delta_counters(_fresh):
    reg = _fresh
    # pre-scenario traffic: the delta baseline must exclude this
    reg.counter("serving.requests", 100)

    def work(ctx):
        ctx.registry.counter("serving.requests", 7)
        ctx.facts["answered"] = 7

    spec = _tiny_spec(
        [Phase("work", work)],
        [Assertion("delta_counted", "counter", metric="serving.requests",
                   op="==", value=7),
         Assertion("fact_bound", "fact", fact="answered", op=">=",
                   value="$floor")],
        defaults={"floor": 5})
    result = scenario.run_scenario(spec)
    assert result["passed"]
    types = [e["type"] for e in reg._events]
    assert types.count("scenario_start") == 1
    assert types.count("scenario_phase") == 1
    assert types.count("scenario_assert") == 2
    assert types.count("scenario_end") == 1
    end = [e for e in reg._events if e["type"] == "scenario_end"][-1]
    assert end["passed"] is True


def test_run_scenario_failed_assertion_fails_verdict():
    spec = _tiny_spec(
        [Phase("noop", lambda ctx: None)],
        [Assertion("missing_fact", "fact", fact="never_set", op="==",
                   value=1)])
    result = scenario.run_scenario(spec)
    assert not result["passed"]
    rec = result["assertions"][0]
    assert rec["error"] == "fact 'never_set' was never recorded"
    with pytest.raises(scenario.ScenarioFailed, match="missing_fact"):
        scenario.run_scenario(spec, raise_on_fail=True)


def test_run_scenario_phase_failure_is_typed_and_cleans_up(_fresh):
    reg = _fresh
    stopped = []

    def start(ctx):
        ctx.defer(lambda: stopped.append("a"))
        ctx.defer(lambda: stopped.append("b"))

    def boom(ctx):
        raise RuntimeError("shard on fire")

    spec = _tiny_spec([Phase("start", start), Phase("boom", boom)],
                      [Assertion("never", "fact", fact="x", value=1)],
                      fault_spec="serve.gather=raise")
    with pytest.raises(scenario.PhaseFailed, match="shard on fire"):
        scenario.run_scenario(spec)
    assert stopped == ["b", "a"]          # LIFO
    assert not faults.active()            # chaos never leaks out
    end = [e for e in reg._events if e["type"] == "scenario_end"][-1]
    assert end["passed"] is False and "shard on fire" in end["error"]


def test_run_scenario_restores_prior_fault_arming():
    faults.install("checkpoint.write=raise")
    spec = _tiny_spec(
        [Phase("check", lambda ctx: ctx.facts.__setitem__(
            "armed", faults.armed("serve.gather")))],
        [Assertion("scenario_chaos_armed", "fact", fact="armed",
                   op="==", value=True)],
        fault_spec="serve.gather=corrupt")
    assert scenario.run_scenario(spec)["passed"]
    # after the run: the scenario's arming is gone; with no env spec the
    # harness is fully disarmed (install_from_env semantics)
    assert not faults.armed("serve.gather")


def test_quantile_assertion_scales_to_ms(_fresh):
    reg = _fresh
    for v in (0.010, 0.020, 0.030):
        reg.histogram("serving.e2e_seconds", v)
    spec = _tiny_spec([Phase("noop", lambda ctx: None)],
                      [Assertion("p99_ms", "quantile",
                                 metric="serving.e2e_seconds", q=0.99,
                                 scale_ms=True, op="<=", value=50.0)])
    result = scenario.run_scenario(spec)
    assert result["passed"]
    assert 10.0 <= result["assertions"][0]["observed"] <= 50.0


def test_ratio_assertion_empty_denominator_is_zero():
    spec = _tiny_spec([Phase("noop", lambda ctx: None)],
                      [Assertion("shed_rate", "ratio",
                                 num="serving.shed",
                                 den=("serving.shed",
                                      "serving.requests"),
                                 op="<=", value=0.5)])
    result = scenario.run_scenario(spec)
    assert result["passed"]
    assert result["assertions"][0]["observed"] == 0.0


def test_bank_result_contract(tmp_path):
    spec = _tiny_spec([Phase("noop", lambda ctx: None)], [])
    result = scenario.run_scenario(spec)
    path = tmp_path / "BENCH_scenario_tiny.json"
    banked = scenario.bank_result(result, str(path))
    import json

    on_disk = json.loads(path.read_text())
    assert on_disk["metric"] == "scenario_tiny"
    assert on_disk["value"] == 1 and on_disk["unit"] == "pass"
    assert "+00:00" in on_disk["banked_at"]      # absolute UTC, not naive
    assert on_disk["platform"] == banked["platform"]


# ---------------------------------------------------------------------------
# 2. the named scenarios, end to end


def test_traffic_spike_scenario_passes():
    result = scenario.run_scenario(
        scenario.get_scenario("traffic-spike"),
        config={"base_s": 0.4, "spike_s": 0.6})
    assert result["passed"], result["assertions"]
    assert result["facts"]["hard_failures"] == 0


def test_torn_publish_scenario_passes(_fresh):
    reg = _fresh
    result = scenario.run_scenario(scenario.get_scenario("torn-publish"))
    assert result["passed"], result["assertions"]
    # the obs trail the ISSUE names: serve.degraded + serving_publish
    assert reg.counter_value("serve.degraded") >= 1
    assert any(e["type"] == "serve_degraded" for e in reg._events)
    assert sum(e["type"] == "serving_publish" for e in reg._events) >= 2


def test_cold_start_scenario_passes():
    result = scenario.run_scenario(scenario.get_scenario("cold-start"))
    assert result["passed"], result["assertions"]
    assert result["facts"]["new_user_served"] is True
    assert 0 < result["facts"]["freshness_ms"] <= 5000


def test_flight_recorder_scenario_passes(_fresh):
    """ISSUE 7 acceptance: forced SLO breaches leave flight_record
    events with full per-request span breakdowns (>= last 8 requests),
    asserted from the obs trail by the scenario's own assertions."""
    reg = _fresh
    result = scenario.run_scenario(scenario.get_scenario("flight-recorder"))
    assert result["passed"], result["assertions"]
    assert result["facts"]["complete_breach_records"] >= 8
    assert result["facts"]["hard_failures"] == 0
    records = [e for e in reg._events if e["type"] == "flight_record"]
    assert len(records) >= 8
    for r in records:
        assert r["trigger"] == "slo_breach"
        assert all(r["spans"][k] is not None for k in
                   ("admission", "queue_wait", "score", "respond"))


def test_continuous_freshness_scenario_passes(_fresh):
    """ISSUE 11 acceptance: a sustained rating stream under live serve
    load — freshness p99 under the SLO, zero torn publishes, every
    publish incremental (retag/delta/compact, never a full rebuild),
    and the poison quarantine counted exactly — all judged from the
    obs trail by the scenario's own assertions."""
    reg = _fresh
    result = scenario.run_scenario(
        scenario.get_scenario("continuous-freshness"))
    assert result["passed"], result["assertions"]
    f = result["facts"]
    assert f["all_incremental"] is True
    assert f["new_user_served"] is True
    assert f["hard_failures"] == 0
    # the trail carries the live vocabulary end to end
    assert reg.histogram_count("live.freshness_seconds") > 0
    assert any(e["type"] == "live_update" for e in reg._events)
    assert any(e["type"] == "ingest_quarantined"
               and e["path"] == "live" for e in reg._events)


def test_preempt_under_serve_acceptance():
    """The ISSUE's acceptance property: bitwise-equal factors vs an
    unpreempted run, while serving returned answers throughout (shed or
    degraded allowed, hard failures not)."""
    result = scenario.run_scenario(
        scenario.get_scenario("preempt-under-serve"))
    assert result["passed"], result["assertions"]
    f = result["facts"]
    assert f["resume_bitwise"] is True
    assert f["preempted"] is True
    assert f["served_during_train"] >= 1
    assert f["serve_hard_failures"] == 0


def test_preempt_resume_scenario_subprocess():
    """The pytest port of chaos_smoke stage 3: same scenario, same
    assertions (preempted CLI train exits 43; --resume auto discovers
    the checkpoint and saves a model), via real CLI subprocesses."""
    result = scenario.run_scenario(scenario.get_scenario("preempt-resume"))
    assert result["passed"], result["assertions"]
    f = result["facts"]
    assert f["preempt_exit_code"] == 43
    assert f["resume_exit_code"] == 0
    assert f["resume_discovered"] is True and f["model_saved"] is True


def test_device_loss_scenario_subprocess():
    """Elastic training acceptance (PR 18 tentpole): a device dies
    mid-fit, the run COMPLETES (exit 0, not a crash), the recovery tree
    (device_lost -> mesh_reformed -> elastic_resume) is re-derivable
    from events.jsonl alone, and the final factors are bitwise equal to
    a fresh shrunk-mesh fit resumed from the same checkpoint."""
    result = scenario.run_scenario(scenario.get_scenario("device-loss"))
    assert result["passed"], result["assertions"]
    f = result["facts"]
    assert f["elastic_exit_code"] == 0
    assert (f["device_lost_events"] == f["mesh_reformed_events"]
            == f["elastic_resume_events"] == 1)
    assert f["resume_from_checkpoint"] is True
    assert f["factors_bitwise_equal"] is True


# ---------------------------------------------------------------------------
# degraded-mode serving, single process (ISSUE 6 satellite)


def test_serve_degraded_counter_and_event_single_process(_fresh):
    from tpu_als.parallel import serve
    from tpu_als.parallel.mesh import make_mesh

    reg = _fresh
    serve.reset_last_good()
    rng = np.random.default_rng(0)
    U = rng.normal(size=(16, 8)).astype(np.float32)
    V = rng.normal(size=(24, 8)).astype(np.float32)
    mesh = make_mesh(8)
    # hit 1 clean (primes last-good), hit 2 a ServeShardLost via the
    # fault harness — all in THIS process
    faults.install("serve.gather=corrupt@nth=2")
    _, ix_good = serve.topk_sharded(U, V, 5, mesh)
    before = reg.counter_value("serve.degraded")
    _, ix, info = serve.topk_sharded(U, V, 5, mesh, return_info=True)
    assert info["degraded"] is True
    assert reg.counter_value("serve.degraded") == before + 1
    ev = [e for e in reg._events if e["type"] == "serve_degraded"]
    assert ev and "ServeShardLost" in ev[-1]["reason"]
    # degraded answers come from the last-good catalog == same catalog
    np.testing.assert_array_equal(ix, ix_good)


# ---------------------------------------------------------------------------
# 3. CLI error contract (typed, non-zero, no traceback)


def test_cli_unknown_scenario_exits_2_and_lists_names():
    p = _cli(["scenario", "run", "definitely-not-a-scenario"])
    assert p.returncode == 2
    assert "unknown scenario" in p.stderr
    for name in scenario.names():
        assert name in p.stderr
    assert "Traceback" not in p.stderr


@pytest.mark.parametrize("argv", [
    ["scenario", "run", "torn-publish"],
    ["serve-bench", "--users", "10", "--items", "20", "--rank", "4",
     "--duration", "0.1"],
])
def test_cli_rejects_unparseable_fault_spec(argv):
    p = _cli(argv, env_extra={"TPU_ALS_FAULT_SPEC": "not=a@spec="})
    assert p.returncode == 2
    assert "FaultSpecError" in p.stderr
    assert "TPU_ALS_FAULT_SPEC" in p.stderr
    assert "Traceback" not in p.stderr


def test_import_with_bad_env_spec_warns_and_disarms():
    """A library import (no CLI front door) must neither die with a
    traceback nor silently arm garbage: faults end up DISARMED with a
    RuntimeWarning pointing at the env var."""
    p = subprocess.run(
        [sys.executable, "-W", "always", "-c",
         "import sys; sys.path.insert(0, %r)\n"
         "from tpu_als.resilience import faults\n"
         "sys.exit(0 if not faults.active() else 3)" % _REPO],
        capture_output=True, text=True,
        env={**os.environ, "TPU_ALS_FAULT_SPEC": "garbage"})
    assert p.returncode == 0, p.stderr
    assert "IGNORED" in p.stderr and "RuntimeWarning" in p.stderr
