"""Rank-256 evidence on the CPU mesh (BASELINE config 3, VERDICT r2 #3).

Config 3 (Amazon-2023, ~570M ratings, rank 256, v5e-32) cannot run here,
so this file pins what CAN be checked without the pod:

- the per-device buffer arithmetic of each gather strategy at rank-256
  parameters — the documented HBM model must be reproduced by the actual
  built containers (shapes are exact at any entity count, so a scale
  model on the 8-device mesh verifies the formulas);
- end-to-end strategy equivalence AT rank 256 (tiny entity counts, full
  rank): the solve path, tiling arithmetic, and collectives all run at
  the production rank.

The single-chip rank-256 throughput proxy is ``scripts/rank256_proxy.py``
(queued in scripts/sweep_tpu.sh for the tunnel watcher).
"""

import warnings

import numpy as np

from tpu_als.core.als import AlsConfig
from tpu_als.core.ratings import trainer_chunk
from tpu_als.parallel.a2a import build_a2a
from tpu_als.parallel.comm import shard_csr_grid
from tpu_als.parallel.data import partition_balanced, shard_csr
from tpu_als.parallel.mesh import make_mesh
from tpu_als.parallel.trainer import stacked_counts, train_sharded

RANK = 256
MEM_ELEMS = 1 << 28  # 1 GiB of f32 — trainer_chunk's per-intermediate cap


def test_trainer_tile_bounds_accumulator_at_rank256():
    """At config-3 shard sizes (~1-2M solved rows/device) the row-tiled
    trainer must cap the [tile, r, r] accumulator at 1 GiB f32; the naive
    full-shard accumulator it replaces would be ~275 GB/device."""
    for nb in (1 << 20, 1 << 21):
        for w in (8, 64, 256, 1024):
            tile = trainer_chunk(nb, w, RANK, 1 << 19)
            assert tile * RANK * max(w, RANK) <= MEM_ELEMS
            assert nb % tile == 0  # tiles cover the shard exactly
    naive_bytes = (1 << 20) * RANK * RANK * 4
    assert naive_bytes > 250e9  # the blowup the tiling exists to avoid


def _sparse_layout(rng, D=8, per_user=2, users_per_dev=64, items_per_dev=64):
    nU, nI = users_per_dev * D, items_per_dev * D
    nnz = per_user * nU
    u = rng.integers(0, nU, nnz)
    i = rng.integers(0, nI, nnz)
    r = np.abs(rng.normal(size=nnz)).astype(np.float32) + 0.1
    upart = partition_balanced(np.bincount(u, minlength=nU), D)
    ipart = partition_balanced(np.bincount(i, minlength=nI), D)
    return u, i, r, upart, ipart


def test_ring_rank256_bytes_match_documented_model(rng):
    """Every term of parallel/comm.py's peak-HBM model, recomputed from
    the containers a rank-256 build actually produces."""
    D = 8
    u, i, r, upart, ipart = _sparse_layout(np.random.default_rng(5),
                                           D=D, per_user=6)
    grid = shard_csr_grid(upart, ipart, u, i, r, min_width=8)

    # term 1: the resident opposite factor shard — O(N_opposite/D · r)
    resident_bytes = ipart.rows_per_shard * RANK * 4
    assert resident_bytes == ipart.padded_rows // D * RANK * 4

    # term 2: one tile's accumulator — O(tile · r²), capped at 1 GiB
    for b in grid.buckets:
        S, nb, w = b.cols.shape[1], b.cols.shape[2], b.cols.shape[3]
        assert S == D  # full source axis: each device holds D grid cells
        tile = trainer_chunk(nb, w, RANK, grid.chunk_elems)
        assert tile * RANK * max(w, RANK) <= MEM_ELEMS
        # the full opposite table is NEVER a term: the ring holds one
        # shard (resident) + one in-flight permute buffer of equal size
        assert 2 * resident_bytes < ipart.padded_rows * RANK * 4 or D <= 2


def test_a2a_rank256_recv_table_below_gather(rng):
    """The a2a recv table [D·R, r] must beat all_gather's full opposite
    table at rank-256 parameters on the sparse layout (and the plan must
    be non-degenerate, i.e. the win is real, not the fallback)."""
    D = 8
    u, i, r, upart, ipart = _sparse_layout(np.random.default_rng(7), D=D)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        plan = build_a2a(upart, ipart, u, i, r, min_width=8)
    assert not plan.degenerate
    recv_bytes = D * plan.request_budget * RANK * 4
    gather_bytes = ipart.padded_rows * RANK * 4
    assert recv_bytes <= gather_bytes // 2


def test_all_strategies_agree_at_rank256(rng):
    """One full iteration of every gather strategy at rank 256 on the
    8-device mesh: the production rank exercises the real solve path
    (rank > 128 rides pallas_solve on chip, XLA here) and the tiling
    arithmetic; all three must agree."""
    D = 8
    local = np.random.default_rng(3)
    nU, nI, nnz = 48, 32, 500
    u = local.integers(0, nU, nnz)
    i = local.integers(0, nI, nnz)
    r = np.abs(local.normal(size=nnz)).astype(np.float32) + 0.1
    upart = partition_balanced(np.bincount(u, minlength=nU), D)
    ipart = partition_balanced(np.bincount(i, minlength=nI), D)
    cfg = AlsConfig(rank=RANK, max_iter=1, reg_param=0.1, seed=0)
    mesh = make_mesh(D)

    Ug, Vg = train_sharded(
        mesh, upart, ipart,
        shard_csr(upart, ipart, u, i, r, min_width=8),
        shard_csr(ipart, upart, i, u, r, min_width=8), cfg)

    rc = (stacked_counts(upart, u, r), stacked_counts(ipart, i, r))
    Ur, Vr = train_sharded(
        mesh, upart, ipart,
        shard_csr_grid(upart, ipart, u, i, r, min_width=8),
        shard_csr_grid(ipart, upart, i, u, r, min_width=8), cfg,
        strategy="ring", ring_counts=rc)
    np.testing.assert_allclose(np.asarray(Ur), np.asarray(Ug),
                               rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(np.asarray(Vr), np.asarray(Vg),
                               rtol=2e-3, atol=2e-3)

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")  # dense at this scale: a2a may pad
        ua = build_a2a(upart, ipart, u, i, r, min_width=8)
        ia = build_a2a(ipart, upart, i, u, r, min_width=8)
    Ua, Va = train_sharded(mesh, upart, ipart, ua, ia, cfg,
                           strategy="all_to_all")
    np.testing.assert_allclose(np.asarray(Ua), np.asarray(Ug),
                               rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(np.asarray(Va), np.asarray(Vg),
                               rtol=2e-3, atol=2e-3)


def test_sharded_serving_at_rank256(rng):
    """Config-3 serving evidence (SURVEY.md §5.7): top-k at rank 256 over
    the 8-device mesh, ring (catalog never materialized) == all_gather ==
    single device."""
    from tpu_als.ops.topk import chunked_topk_scores
    from tpu_als.parallel.serve import topk_sharded
    import jax.numpy as jnp

    U = rng.normal(size=(40, 256)).astype(np.float32)
    V = rng.normal(size=(100, 256)).astype(np.float32)
    ref_s, ref_i = chunked_topk_scores(
        jnp.asarray(U), jnp.asarray(V), jnp.ones(100, bool), k=10)
    for strategy in ("all_gather", "ring"):
        s, ix = topk_sharded(U, V, 10, make_mesh(8), strategy=strategy)
        np.testing.assert_allclose(s, np.asarray(ref_s), rtol=1e-5,
                                   atol=1e-5)
        np.testing.assert_array_equal(ix, np.asarray(ref_i))
