"""Solver unit tests vs numpy/scipy oracles — SURVEY.md §4 mapping item 2.

The reference suite tests CholeskySolver/NNLSSolver against exact rank-1
reconstructions and known QP solutions (ALSSuite / NNLSSuite); here the
batched solvers are checked against direct dense solves and scipy's nnls.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from tpu_als.ops.solve import (
    compute_yty,
    normal_eq_explicit,
    normal_eq_implicit,
    solve_nnls,
    solve_spd,
)


def dense_reference_explicit(Vg, vals, mask, reg):
    n, w, r = Vg.shape
    A = np.zeros((n, r, r))
    b = np.zeros((n, r))
    for u in range(n):
        cnt = 0
        for k in range(w):
            if mask[u, k] > 0:
                v = Vg[u, k]
                A[u] += np.outer(v, v)
                b[u] += vals[u, k] * v
                cnt += 1
        A[u] += reg * cnt * np.eye(r)
    return A, b


def test_normal_eq_explicit_matches_loop(rng):
    n, w, r = 7, 12, 5
    Vg = rng.normal(size=(n, w, r)).astype(np.float32)
    vals = rng.normal(size=(n, w)).astype(np.float32)
    mask = (rng.random((n, w)) < 0.7).astype(np.float32)
    A, b, count = normal_eq_explicit(jnp.array(Vg), jnp.array(vals), jnp.array(mask), 0.3)
    A_ref, b_ref = dense_reference_explicit(Vg, vals, mask, 0.3)
    np.testing.assert_allclose(np.asarray(A), A_ref, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(b), b_ref, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(count), mask.sum(-1))


def test_normal_eq_implicit_matches_loop(rng):
    n, w, r = 5, 9, 4
    alpha, reg = 2.0, 0.1
    Vg = rng.normal(size=(n, w, r)).astype(np.float32)
    vals = (rng.normal(size=(n, w)) * 2).astype(np.float32)
    mask = (rng.random((n, w)) < 0.8).astype(np.float32)
    Y = rng.normal(size=(20, r)).astype(np.float32)
    YtY = Y.T @ Y
    A, b, count = normal_eq_implicit(
        jnp.array(Vg), jnp.array(vals), jnp.array(mask), reg, alpha, jnp.array(YtY)
    )
    A_ref = np.zeros((n, r, r))
    b_ref = np.zeros((n, r))
    for u in range(n):
        cnt = 0
        for k in range(w):
            if mask[u, k] > 0:
                v = Vg[u, k]
                c = 1 + alpha * abs(vals[u, k])
                A_ref[u] += (c - 1) * np.outer(v, v)
                if vals[u, k] > 0:
                    b_ref[u] += c * v
                    cnt += 1  # reference's numExplicits: only positives
        A_ref[u] += YtY + reg * cnt * np.eye(r)
    np.testing.assert_allclose(np.asarray(A), A_ref, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(b), b_ref, rtol=1e-4, atol=1e-4)


def test_solve_spd_matches_numpy(rng):
    n, r = 16, 8
    M = rng.normal(size=(n, r, r)).astype(np.float32)
    A = M @ np.transpose(M, (0, 2, 1)) + 0.5 * np.eye(r, dtype=np.float32)
    b = rng.normal(size=(n, r)).astype(np.float32)
    count = np.ones(n, dtype=np.float32)
    x = np.asarray(solve_spd(jnp.array(A), jnp.array(b), jnp.array(count)))
    x_ref = np.stack([np.linalg.solve(A[k], b[k]) for k in range(n)])
    np.testing.assert_allclose(x, x_ref, rtol=1e-3, atol=1e-3)


def test_solve_spd_empty_rows_are_zero(rng):
    n, r = 4, 6
    A = np.zeros((n, r, r), dtype=np.float32)
    b = np.zeros((n, r), dtype=np.float32)
    count = np.zeros(n, dtype=np.float32)
    x = np.asarray(solve_spd(jnp.array(A), jnp.array(b), jnp.array(count)))
    assert np.all(np.isfinite(x))
    np.testing.assert_allclose(x, 0.0)


def test_solve_nnls_matches_scipy(rng):
    scipy_opt = pytest.importorskip("scipy.optimize")
    n, r = 6, 5
    M = rng.normal(size=(n, r, r)).astype(np.float32)
    A = M @ np.transpose(M, (0, 2, 1)) + 0.1 * np.eye(r, dtype=np.float32)
    b = rng.normal(size=(n, r)).astype(np.float32)
    count = np.ones(n, dtype=np.float32)
    x = np.asarray(
        solve_nnls(jnp.array(A), jnp.array(b), jnp.array(count), sweeps=400)
    )
    assert np.all(x >= -1e-6)
    for k in range(n):
        # scipy solves min ||Gz - h||; our problem min 1/2 zᵀAz - bᵀz with A=GᵀG, b=Gᵀh
        G = np.linalg.cholesky(A[k]).T
        h = np.linalg.solve(G.T, b[k])
        z_ref, _ = scipy_opt.nnls(G, h)
        np.testing.assert_allclose(x[k], z_ref, rtol=2e-2, atol=2e-2)


def test_compute_yty(rng):
    V = rng.normal(size=(30, 7)).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(compute_yty(jnp.array(V))), V.T @ V, rtol=1e-4, atol=1e-4
    )
