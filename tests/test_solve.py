"""Solver unit tests vs numpy/scipy oracles — SURVEY.md §4 mapping item 2.

The reference suite tests CholeskySolver/NNLSSolver against exact rank-1
reconstructions and known QP solutions (ALSSuite / NNLSSuite); here the
batched solvers are checked against direct dense solves and scipy's nnls.
"""

import functools

import numpy as np
import pytest

import jax.numpy as jnp

from tpu_als.ops.solve import (
    ADAPTIVE_JITTER_RUNGS,
    SolveUnstable,
    compute_yty,
    normal_eq_explicit,
    normal_eq_implicit,
    solve_nnls,
    solve_spd,
    solve_spd_checked,
)


def dense_reference_explicit(Vg, vals, mask, reg):
    n, w, r = Vg.shape
    A = np.zeros((n, r, r))
    b = np.zeros((n, r))
    for u in range(n):
        cnt = 0
        for k in range(w):
            if mask[u, k] > 0:
                v = Vg[u, k]
                A[u] += np.outer(v, v)
                b[u] += vals[u, k] * v
                cnt += 1
        A[u] += reg * cnt * np.eye(r)
    return A, b


def test_normal_eq_explicit_matches_loop(rng):
    n, w, r = 7, 12, 5
    Vg = rng.normal(size=(n, w, r)).astype(np.float32)
    vals = rng.normal(size=(n, w)).astype(np.float32)
    mask = (rng.random((n, w)) < 0.7).astype(np.float32)
    A, b, count = normal_eq_explicit(jnp.array(Vg), jnp.array(vals), jnp.array(mask), 0.3)
    A_ref, b_ref = dense_reference_explicit(Vg, vals, mask, 0.3)
    np.testing.assert_allclose(np.asarray(A), A_ref, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(b), b_ref, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(count), mask.sum(-1))


def test_normal_eq_implicit_matches_loop(rng):
    n, w, r = 5, 9, 4
    alpha, reg = 2.0, 0.1
    Vg = rng.normal(size=(n, w, r)).astype(np.float32)
    vals = (rng.normal(size=(n, w)) * 2).astype(np.float32)
    mask = (rng.random((n, w)) < 0.8).astype(np.float32)
    Y = rng.normal(size=(20, r)).astype(np.float32)
    YtY = Y.T @ Y
    A, b, count = normal_eq_implicit(
        jnp.array(Vg), jnp.array(vals), jnp.array(mask), reg, alpha, jnp.array(YtY)
    )
    A_ref = np.zeros((n, r, r))
    b_ref = np.zeros((n, r))
    for u in range(n):
        cnt = 0
        for k in range(w):
            if mask[u, k] > 0:
                v = Vg[u, k]
                c = 1 + alpha * abs(vals[u, k])
                A_ref[u] += (c - 1) * np.outer(v, v)
                if vals[u, k] > 0:
                    b_ref[u] += c * v
                    cnt += 1  # reference's numExplicits: only positives
        A_ref[u] += YtY + reg * cnt * np.eye(r)
    np.testing.assert_allclose(np.asarray(A), A_ref, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(b), b_ref, rtol=1e-4, atol=1e-4)


def test_solve_spd_matches_numpy(rng):
    n, r = 16, 8
    M = rng.normal(size=(n, r, r)).astype(np.float32)
    A = M @ np.transpose(M, (0, 2, 1)) + 0.5 * np.eye(r, dtype=np.float32)
    b = rng.normal(size=(n, r)).astype(np.float32)
    count = np.ones(n, dtype=np.float32)
    x = np.asarray(solve_spd(jnp.array(A), jnp.array(b), jnp.array(count)))
    x_ref = np.stack([np.linalg.solve(A[k], b[k]) for k in range(n)])
    np.testing.assert_allclose(x, x_ref, rtol=1e-3, atol=1e-3)


def test_solve_spd_empty_rows_are_zero(rng):
    n, r = 4, 6
    A = np.zeros((n, r, r), dtype=np.float32)
    b = np.zeros((n, r), dtype=np.float32)
    count = np.zeros(n, dtype=np.float32)
    x = np.asarray(solve_spd(jnp.array(A), jnp.array(b), jnp.array(count)))
    assert np.all(np.isfinite(x))
    np.testing.assert_allclose(x, 0.0)


def test_solve_nnls_matches_scipy(rng):
    scipy_opt = pytest.importorskip("scipy.optimize")
    n, r = 6, 5
    M = rng.normal(size=(n, r, r)).astype(np.float32)
    A = M @ np.transpose(M, (0, 2, 1)) + 0.1 * np.eye(r, dtype=np.float32)
    b = rng.normal(size=(n, r)).astype(np.float32)
    count = np.ones(n, dtype=np.float32)
    x = np.asarray(
        solve_nnls(jnp.array(A), jnp.array(b), jnp.array(count), sweeps=400)
    )
    assert np.all(x >= -1e-6)
    for k in range(n):
        # scipy solves min ||Gz - h||; our problem min 1/2 zᵀAz - bᵀz with A=GᵀG, b=Gᵀh
        G = np.linalg.cholesky(A[k]).T
        h = np.linalg.solve(G.T, b[k])
        z_ref, _ = scipy_opt.nnls(G, h)
        np.testing.assert_allclose(x[k], z_ref, rtol=2e-2, atol=2e-2)


def test_compute_yty(rng):
    V = rng.normal(size=(30, 7)).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(compute_yty(jnp.array(V))), V.T @ V, rtol=1e-4, atol=1e-4
    )


# ---------------------------------------------------------------------------
# adversarial solves (docs/resilience.md guardrails): the Gram batches a
# poisoned or degenerate shard actually produces — near-singular,
# rank-deficient, bf16, huge-magnitude — checked through the adaptive
# escalation ladder and across the solve backends.


def near_singular_batch(rng, n, r, k=1, eps=0.0):
    """Gram matrices of true rank ``k`` (< r) plus ``eps`` on the diagonal:
    the system a cold entity with a handful of collinear neighbors hands
    the solver."""
    G = rng.normal(size=(n, k, r)).astype(np.float32)
    A = np.einsum("nkr,nks->nrs", G, G) + eps * np.eye(r, dtype=np.float32)
    b = np.einsum("nkr,nk->nr", G, rng.normal(size=(n, k)).astype(np.float32))
    return A.astype(np.float32), b.astype(np.float32)


def heavy_rung_residual(A, x, b, count):
    """Relative residual against the heaviest-rung system — the contract
    the adaptive ladder guarantees (solve_spd docstring)."""
    r = A.shape[-1]
    eye = np.eye(r, dtype=np.float32)
    A0 = np.where((count <= 0)[:, None, None], eye, A)
    Ac = A0 + ADAPTIVE_JITTER_RUNGS[-1] * eye
    res = np.einsum("nrs,ns->nr", Ac, x) - b
    return np.linalg.norm(res, axis=-1) / (np.linalg.norm(b, axis=-1) + 1.0)


def _interpret_backends(monkeypatch, backend):
    """Route the Pallas kernels through interpret mode so the backend
    dispatch is exercised off-TPU (the test_pallas_lanes.py idiom)."""
    if backend == "lanes":
        from tpu_als.ops import pallas_lanes

        monkeypatch.setattr(
            pallas_lanes, "spd_solve_lanes",
            functools.partial(pallas_lanes.spd_solve_lanes, interpret=True))
    elif backend == "pallas":
        from tpu_als.ops import pallas_solve

        monkeypatch.setattr(
            pallas_solve, "spd_solve_pallas",
            functools.partial(pallas_solve.spd_solve_pallas, interpret=True))


@pytest.mark.parametrize("backend", ["xla", "lanes", "pallas"])
def test_adaptive_rescues_rank_deficient(rng, backend, monkeypatch):
    # true rank 1 << r and ZERO base jitter: the plain Cholesky breaks
    # down, the ladder's jitter rungs must save every row — on every
    # backend, because escalation sits above the dispatch
    _interpret_backends(monkeypatch, backend)
    n, r = 8, 8
    A, b = near_singular_batch(rng, n, r, k=1)
    count = np.ones(n, dtype=np.float32)
    x = np.asarray(solve_spd(jnp.array(A), jnp.array(b), jnp.array(count),
                             jitter=0.0, backend=backend, adaptive=True))
    assert np.all(np.isfinite(x))
    assert np.all(heavy_rung_residual(A, x, b, count) <= 1e-2)


def test_adaptive_rescues_near_singular(rng):
    # barely-above-singular (eps ~ f32 noise floor of the entries):
    # Cholesky may "succeed" with garbage — the residual check has to
    # catch that, not just NaNs
    n, r = 16, 8
    A, b = near_singular_batch(rng, n, r, k=2, eps=1e-7)
    count = np.ones(n, dtype=np.float32)
    x = np.asarray(solve_spd(jnp.array(A), jnp.array(b), jnp.array(count),
                             jitter=0.0, adaptive=True))
    assert np.all(np.isfinite(x))
    assert np.all(heavy_rung_residual(A, x, b, count) <= 1e-2)


def test_adaptive_is_identity_on_healthy_batch(rng):
    # well-conditioned batch: the lax.cond healthy branch returns the
    # plain solve's answer unchanged — adaptive mode must not perturb a
    # fit that never needed it
    n, r = 16, 8
    M = rng.normal(size=(n, r, r)).astype(np.float32)
    A = M @ np.transpose(M, (0, 2, 1)) + 0.5 * np.eye(r, dtype=np.float32)
    b = rng.normal(size=(n, r)).astype(np.float32)
    count = np.ones(n, dtype=np.float32)
    x_plain = np.asarray(solve_spd(jnp.array(A), jnp.array(b),
                                   jnp.array(count)))
    x_adapt = np.asarray(solve_spd(jnp.array(A), jnp.array(b),
                                   jnp.array(count), adaptive=True))
    np.testing.assert_array_equal(x_plain, x_adapt)


def test_solve_spd_bf16_inputs(rng):
    # bf16 Gram/bias (the gather-fused step's accumulation dtype under
    # mixed precision): the solve must stay finite and land within bf16's
    # ~3-decimal-digit precision of the f32 oracle
    n, r = 8, 8
    M = rng.normal(size=(n, r, r)).astype(np.float32)
    A = M @ np.transpose(M, (0, 2, 1)) + 2.0 * np.eye(r, dtype=np.float32)
    b = rng.normal(size=(n, r)).astype(np.float32)
    count = np.ones(n, dtype=np.float32)
    x = np.asarray(
        solve_spd(jnp.array(A, dtype=jnp.bfloat16),
                  jnp.array(b, dtype=jnp.bfloat16),
                  jnp.array(count)).astype(jnp.float32))
    assert np.all(np.isfinite(x))
    x_ref = np.stack([np.linalg.solve(A[k], b[k]) for k in range(n)])
    np.testing.assert_allclose(x, x_ref, rtol=0.15, atol=0.15)


def test_solve_spd_huge_magnitude_ratings(rng):
    # ratings at the RATING_ABS_MAX quarantine boundary (1e6): b scales
    # by 1e6, A entries by up to ~1e2 rating-independent — solutions must
    # stay finite and scale linearly, no f32 overflow in the residual path
    n, r = 8, 6
    M = rng.normal(size=(n, r, r)).astype(np.float32)
    A = M @ np.transpose(M, (0, 2, 1)) + 0.5 * np.eye(r, dtype=np.float32)
    b = rng.normal(size=(n, r)).astype(np.float32) * 1e6
    count = np.ones(n, dtype=np.float32)
    x = np.asarray(solve_spd(jnp.array(A), jnp.array(b), jnp.array(count),
                             adaptive=True))
    assert np.all(np.isfinite(x))
    x_ref = np.stack([np.linalg.solve(A[k], b[k]) for k in range(n)])
    np.testing.assert_allclose(x, x_ref, rtol=1e-3, atol=1e-3 * 1e6)


def test_solve_spd_checked_passes_healthy(rng):
    n, r = 8, 6
    M = rng.normal(size=(n, r, r)).astype(np.float32)
    A = M @ np.transpose(M, (0, 2, 1)) + 0.5 * np.eye(r, dtype=np.float32)
    b = rng.normal(size=(n, r)).astype(np.float32)
    count = np.ones(n, dtype=np.float32)
    x = np.asarray(solve_spd_checked(jnp.array(A), jnp.array(b),
                                     jnp.array(count)))
    x_ref = np.stack([np.linalg.solve(A[k], b[k]) for k in range(n)])
    np.testing.assert_allclose(x, x_ref, rtol=1e-3, atol=1e-3)


def test_solve_spd_checked_raises_on_unsalvageable(rng):
    # a NaN-poisoned Gram with count > 0 defeats every rung (jitter can't
    # fix non-finite entries, CG propagates them): the typed SolveUnstable
    # must fire with the bad-row count
    n, r = 6, 5
    M = rng.normal(size=(n, r, r)).astype(np.float32)
    A = M @ np.transpose(M, (0, 2, 1)) + 0.5 * np.eye(r, dtype=np.float32)
    A[2] = np.nan
    b = rng.normal(size=(n, r)).astype(np.float32)
    count = np.ones(n, dtype=np.float32)
    with pytest.raises(SolveUnstable) as ei:
        solve_spd_checked(jnp.array(A), jnp.array(b), jnp.array(count))
    assert ei.value.bad_rows == 1
    assert ei.value.total_rows == n
