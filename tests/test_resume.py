"""Failure detection / recovery (SURVEY.md §5.3): crash mid-training, then
resume from the checkpoint and converge to the same factors as an
uninterrupted run.

The reference stack bounds recovery cost via ``checkpointInterval`` RDD
lineage cuts; here ALS is a fixed-point iteration so recovery is
restart-from-factors, which must be *exact* — each iteration is a
deterministic function of (U, V, ratings).
"""

import os
import subprocess
import sys

import numpy as np
import pytest

from tests.conftest import make_ratings

import tpu_als
from tpu_als.io.checkpoint import load_factors

_CRASH_SCRIPT = """
import os, sys
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import tpu_als

data = np.load(sys.argv[1])
frame = {{"user": data["u"], "item": data["i"], "rating": data["r"]}}

def die(iteration, U, V):
    if iteration == 4:
        os._exit(42)  # simulated hard crash: no cleanup, no atexit

als = tpu_als.ALS(rank=4, maxIter=8, regParam=0.01, seed=3,
                  checkpointDir=sys.argv[2], checkpointInterval=3,
                  fitCallback=die)
als.fit(frame)
"""


@pytest.fixture
def dataset(rng):
    u, i, r, _, _ = make_ratings(rng, num_users=50, num_items=30, rank=4)
    return u, i, r


@pytest.mark.slow
def test_crash_then_resume_matches_uninterrupted(dataset, tmp_path):
    u, i, r = dataset
    frame = {"user": u, "item": i, "rating": r}

    # uninterrupted reference run
    full = tpu_als.ALS(rank=4, maxIter=8, regParam=0.01, seed=3).fit(frame)

    # crashing run: dies at iteration 4, checkpoint written at iteration 3
    npz = tmp_path / "data.npz"
    np.savez(npz, u=u, i=i, r=r)
    script = _CRASH_SCRIPT.format(
        repo=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    proc = subprocess.run(
        [sys.executable, "-c", script, str(npz), str(tmp_path)],
        capture_output=True, text=True,
        env={**os.environ,
             "XLA_FLAGS": "--xla_force_host_platform_device_count=1"})
    assert proc.returncode == 42, proc.stderr

    ckpt = str(tmp_path / "als_checkpoint")
    manifest, *_ = load_factors(ckpt)
    assert manifest["iteration"] == 3

    # resume: loads iteration-3 factors, runs the remaining 5 iterations
    resumed = tpu_als.ALS(rank=4, maxIter=8, regParam=0.01, seed=3,
                          resumeFrom=ckpt).fit(frame)

    np.testing.assert_allclose(resumed._U, full._U, atol=1e-5)
    np.testing.assert_allclose(resumed._V, full._V, atol=1e-5)


def test_resume_rejects_mismatched_rank(dataset, tmp_path):
    u, i, r = dataset
    frame = {"user": u, "item": i, "rating": r}
    tpu_als.ALS(rank=4, maxIter=2, regParam=0.01, seed=0,
                checkpointDir=str(tmp_path), checkpointInterval=1).fit(frame)
    ckpt = str(tmp_path / "als_checkpoint")
    with pytest.raises(ValueError, match="rank"):
        tpu_als.ALS(rank=6, maxIter=4, resumeFrom=ckpt).fit(frame)


def test_resume_rejects_mismatched_ids(dataset, tmp_path):
    u, i, r = dataset
    frame = {"user": u, "item": i, "rating": r}
    tpu_als.ALS(rank=4, maxIter=2, regParam=0.01, seed=0,
                checkpointDir=str(tmp_path), checkpointInterval=1).fit(frame)
    ckpt = str(tmp_path / "als_checkpoint")
    with pytest.raises(ValueError, match="id maps"):
        tpu_als.ALS(rank=4, maxIter=4, resumeFrom=ckpt).fit(
            {"user": u + 1000, "item": i, "rating": r})


def test_resume_rejects_mismatched_solver_params(dataset, tmp_path):
    u, i, r = dataset
    frame = {"user": u, "item": i, "rating": r}
    tpu_als.ALS(rank=4, maxIter=2, regParam=0.01, seed=0,
                checkpointDir=str(tmp_path), checkpointInterval=1).fit(frame)
    ckpt = str(tmp_path / "als_checkpoint")
    with pytest.raises(ValueError, match="regParam"):
        tpu_als.ALS(rank=4, maxIter=4, regParam=0.1,
                    resumeFrom=ckpt).fit(frame)


def _cli(args, env=None):
    return subprocess.run(
        [sys.executable, "-c",
         "import sys; from tpu_als.cli import main; main(sys.argv[1:])"]
        + args,
        capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu", **(env or {})})


def test_cli_preempt_then_resume_auto_is_bitwise_exact(tmp_path):
    """Graceful preemption end to end: the train CLI stops at an
    iteration boundary with the distinct exit code, and ``--resume
    auto`` discovers the checkpoint and produces factors BITWISE equal
    to an uninterrupted run — resume is restart-from-factors of a
    deterministic fixed-point iteration, so anything weaker than
    ``np.array_equal`` would hide a real divergence.

    Uses the deterministic ``TPU_ALS_PREEMPT_AT`` knob (a real SIGTERM
    races a fast CPU fit; the signal plumbing itself is covered by
    tests/test_resilience.py)."""
    from tpu_als.resilience.preempt import EXIT_PREEMPTED

    base = ["train", "--data", "synthetic:80x40x1500", "--rank", "4",
            "--max-iter", "6", "--reg-param", "0.05", "--seed", "7"]
    ckdir, out_full, out_res = (str(tmp_path / d)
                                for d in ("ck", "full", "resumed"))

    p = _cli(base + ["--output", out_full])
    assert p.returncode == 0, p.stderr

    # "preempted" at the iteration-3 boundary: checkpoint, exit 43
    p = _cli(base + ["--checkpoint-dir", ckdir,
                     "--checkpoint-interval", "100"],
             env={"TPU_ALS_PREEMPT_AT": "3"})
    assert p.returncode == EXIT_PREEMPTED, (p.returncode, p.stderr)
    assert "preempted" in p.stderr
    manifest, *_ = load_factors(os.path.join(ckdir, "als_checkpoint"))
    assert manifest["iteration"] == 3

    # resume discovers the checkpoint and finishes iterations 4..6
    p = _cli(base + ["--checkpoint-dir", ckdir, "--resume", "auto",
                     "--output", out_res])
    assert p.returncode == 0, p.stderr
    assert "resuming from" in p.stderr

    for side in ("user_factors.npz", "item_factors.npz"):
        full = np.load(os.path.join(out_full, side))
        res = np.load(os.path.join(out_res, side))
        assert np.array_equal(full["factors"], res["factors"]), side
        assert np.array_equal(full["ids"], res["ids"]), side


def test_cli_resume_auto_quarantines_corrupt_and_uses_old(tmp_path):
    """Disk corruption in the crash window: the primary checkpoint
    generation is torn with only the ``.old`` generation complete, so
    ``--resume auto`` must quarantine the primary to ``.corrupt/``
    (forensics, out of the next save's way), fall back to ``.old``, and
    still converge to factors BITWISE equal to an uninterrupted run —
    the ``.old`` swap contract driven end to end through the real CLI."""
    import shutil

    from tpu_als.resilience.preempt import EXIT_PREEMPTED

    base = ["train", "--data", "synthetic:80x40x1500", "--rank", "4",
            "--max-iter", "6", "--reg-param", "0.05", "--seed", "7"]
    ckdir, ck2, out_full, out_res = (str(tmp_path / d)
                                     for d in ("ck", "ck2", "full",
                                               "resumed"))

    p = _cli(base + ["--output", out_full])
    assert p.returncode == 0, p.stderr

    # preempted at iteration 4: the primary generation
    p = _cli(base + ["--checkpoint-dir", ckdir,
                     "--checkpoint-interval", "100"],
             env={"TPU_ALS_PREEMPT_AT": "4"})
    assert p.returncode == EXIT_PREEMPTED, (p.returncode, p.stderr)
    primary = os.path.join(ckdir, "als_checkpoint")
    assert load_factors(primary)[0]["iteration"] == 4

    # reconstruct the crash-window state: a complete iteration-2 .old
    # generation next to the (about to be torn) iteration-4 primary.
    # ALS iterations are max_iter-independent, so a finished maxIter=2
    # run's checkpoint IS the iteration-2 interval generation.
    prefix = list(base)
    prefix[prefix.index("--max-iter") + 1] = "2"
    p = _cli(prefix + ["--checkpoint-dir", ck2,
                       "--checkpoint-interval", "2"])
    assert p.returncode == 0, p.stderr
    shutil.move(os.path.join(ck2, "als_checkpoint"), primary + ".old")
    assert load_factors(primary + ".old")[0]["iteration"] == 2

    # tear the primary: truncate a manifest-listed factor file
    fp = os.path.join(primary, "user_factors.npz")
    raw = open(fp, "rb").read()
    with open(fp, "wb") as f:
        f.write(raw[:len(raw) // 2])

    p = _cli(base + ["--checkpoint-dir", ckdir, "--resume", "auto",
                     "--output", out_res])
    assert p.returncode == 0, p.stderr
    assert "resuming from" in p.stderr

    # the torn generation was preserved for forensics, not deleted
    qdir = os.path.join(ckdir, ".corrupt")
    assert os.path.isdir(qdir) and os.listdir(qdir)
    assert not os.path.exists(primary)

    # iterations 3..6 from the .old generation: bitwise vs uninterrupted
    for side in ("user_factors.npz", "item_factors.npz"):
        full = np.load(os.path.join(out_full, side))
        res = np.load(os.path.join(out_res, side))
        assert np.array_equal(full["factors"], res["factors"]), side
        assert np.array_equal(full["ids"], res["ids"]), side


@pytest.mark.slow
def test_cli_real_sigterm_checkpoints_and_exits_43(tmp_path):
    """A REAL SIGTERM mid-fit (not the deterministic knob): the guard
    finishes the in-flight iteration, checkpoints, and exits 43.  maxIter
    is set far beyond what the timeout allows so the run is always
    mid-fit when the signal lands."""
    import signal
    import time

    from tpu_als.resilience.preempt import EXIT_PREEMPTED

    ckdir = str(tmp_path / "ck")
    proc = subprocess.Popen(
        [sys.executable, "-c",
         "import sys; from tpu_als.cli import main; main(sys.argv[1:])",
         "train", "--data", "synthetic:80x40x1500", "--rank", "4",
         "--max-iter", "100000", "--reg-param", "0.05", "--seed", "7",
         "--checkpoint-dir", ckdir, "--checkpoint-interval", "100000"],
        stderr=subprocess.PIPE, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    try:
        # wait until the fit is actually running before signaling
        for line in proc.stderr:
            if "training on" in line:
                break
        time.sleep(3)                      # let compilation+iters start
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=180)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert rc == EXIT_PREEMPTED, rc
    manifest, *_ = load_factors(os.path.join(ckdir, "als_checkpoint"))
    assert manifest["iteration"] >= 1


def test_cli_resume_auto_fresh_dir_starts_from_scratch(tmp_path):
    """--resume auto with nothing on disk must start fresh (exit 0),
    not fail — the orchestrator reruns the same command after ANY
    preemption, including one that never reached a checkpoint."""
    p = _cli(["train", "--data", "synthetic:40x20x400", "--rank", "3",
              "--max-iter", "2", "--checkpoint-dir",
              str(tmp_path / "empty"), "--resume", "auto"])
    assert p.returncode == 0, p.stderr
    assert "starting from scratch" in p.stderr


def test_truncated_checkpoint_raises_not_garbage(rng, tmp_path):
    """A torn factor file (partial write, disk corruption) must raise at
    load — the npz zip container CRC/structure check is the integrity
    layer — never return silently-corrupt factors to resume from."""
    import pytest

    from tpu_als.io.checkpoint import load_factors, save_factors

    path = str(tmp_path / "ck")
    ids = np.arange(10)
    F = rng.normal(size=(10, 3)).astype(np.float32)
    save_factors(path, ids, F, ids, F, params={}, iteration=1)
    # sanity: loads fine
    load_factors(path)
    # truncate one factor file to half its bytes
    fp = os.path.join(path, "user_factors.npz")
    raw = open(fp, "rb").read()
    with open(fp, "wb") as f:
        f.write(raw[:len(raw) // 2])
    with pytest.raises(Exception) as ei:
        load_factors(path)
    assert not isinstance(ei.value, AssertionError)
