"""Evaluator + tuning tests — RankingMetrics vs hand-computed values (the
reference's RankingMetricsSuite protocol, SURVEY.md §4) and grid/CV drivers.
"""

import numpy as np
import pytest

from tpu_als import (
    ALS,
    ColumnarFrame,
    CrossValidator,
    ParamGridBuilder,
    RegressionEvaluator,
    TrainValidationSplit,
)
from tpu_als.api.evaluation import RankingEvaluator, RankingMetrics

from conftest import make_ratings


def test_regression_evaluator_metrics():
    frame = ColumnarFrame({
        "prediction": np.array([1.0, 2.0, 3.0, np.nan]),
        "label": np.array([1.5, 2.0, 2.0, 9.0]),
    })
    ev = RegressionEvaluator(labelCol="label")
    # NaN prediction row excluded
    np.testing.assert_allclose(ev.evaluate(frame),
                               np.sqrt((0.25 + 0 + 1.0) / 3))
    assert ev.evaluate(frame, {ev.getParam("metricName"): "mae"}) == pytest.approx(
        (0.5 + 0 + 1.0) / 3)
    mse = ev.copy({ev.getParam("metricName"): "mse"}).evaluate(frame)
    assert mse == pytest.approx((0.25 + 0 + 1.0) / 3)
    r2 = ev.copy({ev.getParam("metricName"): "r2"}).evaluate(frame)
    label = np.array([1.5, 2.0, 2.0])
    ss_tot = ((label - label.mean()) ** 2).sum()
    assert r2 == pytest.approx(1 - 1.25 / ss_tot)
    assert not ev.isLargerBetter()


def test_ranking_metrics_hand_computed():
    # one query: predicted [1,2,3], relevant {1,3}
    m = RankingMetrics([([1, 2, 3], [1, 3])])
    assert m.precisionAt(1) == 1.0
    assert m.precisionAt(2) == 0.5
    assert m.precisionAt(3) == pytest.approx(2 / 3)
    assert m.recallAt(2) == 0.5
    # AP = (1/1 + 2/3)/2
    assert m.meanAveragePrecision == pytest.approx((1 + 2 / 3) / 2)
    # NDCG@2: DCG = 1/log2(2); IDCG = 1/log2(2)+1/log2(3)
    expected = (1 / np.log2(2)) / (1 / np.log2(2) + 1 / np.log2(3))
    assert m.ndcgAt(2) == pytest.approx(expected)
    # empty relevant set contributes 0
    m2 = RankingMetrics([([1, 2], []), ([1, 2], [1])])
    assert m2.precisionAt(1) == pytest.approx(0.5)


def test_ranking_evaluator():
    frame = ColumnarFrame({
        "prediction": np.array([[1, 2, 3], [4, 5, 6]], dtype=object),
        "label": np.array([[1, 3], [9]], dtype=object),
    })
    ev = RankingEvaluator(metricName="precisionAtK", k=2)
    assert ev.evaluate(frame) == pytest.approx((0.5 + 0.0) / 2)
    assert ev.isLargerBetter()


def test_param_grid_builder():
    als = ALS()
    grid = (ParamGridBuilder()
            .addGrid(als.rank, [2, 4])
            .addGrid(als.regParam, [0.01, 0.1])
            .build())
    assert len(grid) == 4
    assert {m[als.rank] for m in grid} == {2, 4}


def test_cross_validator_picks_sane_rank(rng):
    u, i, r, _, _ = make_ratings(rng, 60, 40, rank=3, density=0.5, noise=0.02)
    frame = ColumnarFrame({"user": u, "item": i, "rating": r})
    als = ALS(maxIter=5, seed=0)
    grid = (ParamGridBuilder()
            .addGrid(als.rank, [1, 4])
            .addGrid(als.regParam, [0.02])
            .build())
    ev = RegressionEvaluator(labelCol="rating")
    cv = CrossValidator(estimator=als, estimatorParamMaps=grid,
                        evaluator=ev, numFolds=2, seed=7)
    cvm = cv.fit(frame)
    assert len(cvm.avgMetrics) == 2
    # rank=4 must beat rank=1 on rank-3 ground truth
    assert cvm.avgMetrics[1] < cvm.avgMetrics[0]
    out = cvm.transform(frame)
    assert "prediction" in out.columns


def test_train_validation_split(rng):
    u, i, r, _, _ = make_ratings(rng, 50, 30, rank=2, density=0.5)
    frame = ColumnarFrame({"user": u, "item": i, "rating": r})
    als = ALS(maxIter=4, seed=0)
    grid = ParamGridBuilder().addGrid(als.regParam, [0.01, 5.0]).build()
    ev = RegressionEvaluator(labelCol="rating")
    tvs = TrainValidationSplit(estimator=als, estimatorParamMaps=grid,
                               evaluator=ev, trainRatio=0.75, seed=1)
    model = tvs.fit(frame)
    assert len(model.validationMetrics) == 2
    # absurd regularization must lose
    assert model.validationMetrics[0] < model.validationMetrics[1]


def test_legacy_mllib_api(rng):
    from tpu_als.api.legacy import ALS as LegacyALS, Rating

    u, i, r, _, _ = make_ratings(rng, 30, 20, rank=2, density=0.5)
    ratings = [Rating(int(a), int(b), float(c)) for a, b, c in zip(u, i, r)]
    model = LegacyALS.train(ratings, rank=3, iterations=5, lambda_=0.01, seed=0)
    p = model.predict(int(u[0]), int(i[0]))
    assert np.isfinite(p)
    preds = model.predictAll([(int(u[0]), int(i[0])), (int(u[1]), int(i[1]))])
    assert len(preds) == 2 and isinstance(preds[0], Rating)
    recs = model.recommendProducts(int(u[0]), 5)
    assert len(recs) == 5
    assert all(rec.user == int(u[0]) for rec in recs)
    scores = [rec.rating for rec in recs]
    assert scores == sorted(scores, reverse=True)
    uf = model.userFeatures()
    assert len(uf[0][1]) == 3
    # implicit variant
    model2 = LegacyALS.trainImplicit(ratings, rank=2, iterations=3, alpha=10.0)
    assert np.isfinite(model2.predict(int(u[0]), int(i[0])))


def test_legacy_bulk_recommenders(rng):
    """The bulk legacy surface (recommendProductsForUsers /
    recommendUsersForProducts / recommendUsers) — iterates the structured
    recommendations column exactly like the reference's RDD-of-Rating
    shape (SURVEY.md §2.B2/§2.B6)."""
    from tpu_als.api.legacy import ALS as LegacyALS, Rating

    u, i, r, _, _ = make_ratings(rng, 25, 15, rank=2, density=0.5)
    ratings = [Rating(int(a), int(b), float(c)) for a, b, c in zip(u, i, r)]
    model = LegacyALS.train(ratings, rank=3, iterations=4, seed=0)

    per_user = dict(model.recommendProductsForUsers(4))
    assert set(per_user) == {int(x) for x in np.unique(u)}
    for uid, rs in per_user.items():
        assert len(rs) == 4
        assert all(isinstance(x, Rating) and x.user == uid for x in rs)
        scores = [x.rating for x in rs]
        assert scores == sorted(scores, reverse=True)

    per_item = dict(model.recommendUsersForProducts(3))
    assert set(per_item) == {int(x) for x in np.unique(i)}
    for pid, rs in per_item.items():
        assert len(rs) == 3
        assert all(x.product == pid for x in rs)
        scores = [x.rating for x in rs]
        assert scores == sorted(scores, reverse=True)

    ru = model.recommendUsers(int(i[0]), 5)
    assert len(ru) == 5 and all(x.product == int(i[0]) for x in ru)
    ru_scores = [x.rating for x in ru]
    assert ru_scores == sorted(ru_scores, reverse=True)
    # both bulk views must agree with the subset call for a sample user
    uid = int(u[0])
    direct = model.recommendProducts(uid, 4)
    assert [x.product for x in per_user[uid]] == [x.product for x in direct]


def test_legacy_save_load(rng, tmp_path):
    from tpu_als.api.legacy import ALS as LegacyALS, MatrixFactorizationModel, Rating

    u, i, r, _, _ = make_ratings(rng, 20, 15, rank=2, density=0.5)
    ratings = [Rating(int(a), int(b), float(c)) for a, b, c in zip(u, i, r)]
    model = LegacyALS.train(ratings, rank=2, iterations=3, seed=0)
    path = str(tmp_path / "mf_model")
    model.save(path)
    loaded = MatrixFactorizationModel.load(path)
    assert loaded.predict(int(u[0]), int(i[0])) == pytest.approx(
        model.predict(int(u[0]), int(i[0])), rel=1e-5)


def test_tuned_model_save_load(rng, tmp_path):
    """CrossValidatorModel / TrainValidationSplitModel persistence — the
    reference's tuning models are MLWritable (SURVEY.md §2.B12/§2.B11)."""
    from tpu_als.api.tuning import (
        CrossValidatorModel,
        TrainValidationSplit,
        TrainValidationSplitModel,
    )

    u, i, r, _, _ = make_ratings(np.random.default_rng(11), 40, 30,
                                 rank=2, density=0.4)
    frame = {"user": u, "item": i, "rating": r}
    est = ALS(rank=2, maxIter=2, regParam=0.05, seed=0)
    ev = RegressionEvaluator(labelCol="rating")
    grid = ParamGridBuilder().addGrid(est.getParam("rank"), [2, 3]).build()
    tvs = TrainValidationSplit(estimator=est, estimatorParamMaps=grid,
                               evaluator=ev, trainRatio=0.8, seed=0)
    model = tvs.fit(frame)
    p = tmp_path / "tvs"
    model.save(str(p))
    back = TrainValidationSplitModel.load(str(p))
    assert back.validationMetrics == model.validationMetrics
    np.testing.assert_allclose(
        np.asarray(back.bestModel.transform(frame)["prediction"]),
        np.asarray(model.transform(frame)["prediction"]), rtol=1e-6)

    cvm = CrossValidatorModel(model.bestModel, [0.5, 0.4], [[0.5], [0.4]])
    p2 = tmp_path / "cv"
    cvm.save(str(p2))
    back2 = CrossValidatorModel.load(str(p2))
    assert back2.avgMetrics == [0.5, 0.4]
    assert back2.foldMetrics == [[0.5], [0.4]]


def test_tuned_load_rejects_foreign_model_class(tmp_path):
    # tuning.json from an untrusted directory must not drive arbitrary
    # imports (ADVICE r1): only tpu_als.* classes are loadable
    import json

    import pytest

    from tpu_als.api.tuning import TrainValidationSplitModel

    p = tmp_path / "evil"
    p.mkdir()
    (p / "tuning.json").write_text(json.dumps(
        {"kind": "tvs", "validationMetrics": [],
         "modelClass": "os.path.join"}))
    with pytest.raises(ValueError, match="refusing to load"):
        TrainValidationSplitModel.load(str(p))


@pytest.mark.slow
def test_cv_respects_larger_is_better(rng):
    """With an isLargerBetter metric (r2), CV must pick the HIGHEST
    score — an argmin over r2 would select the worst model and this
    direction bug would be invisible to every rmse-based test."""
    from tpu_als import ALS, ColumnarFrame, RegressionEvaluator
    from tpu_als.api.tuning import CrossValidator, ParamGridBuilder

    u, i, r, _, _ = make_ratings(rng, 60, 40, rank=3, density=0.5)
    frame = ColumnarFrame({"user": u, "item": i, "rating": r})
    als = ALS(maxIter=6, regParam=0.01, seed=0, coldStartStrategy="drop")
    grid = ParamGridBuilder().addGrid(als.rank, [1, 6]).build()
    ev = RegressionEvaluator(labelCol="rating", metricName="r2")
    assert ev.isLargerBetter()
    cv = CrossValidator(estimator=als, estimatorParamMaps=grid,
                        evaluator=ev, numFolds=2, seed=0)
    model = cv.fit(frame)
    assert model.avgMetrics[1] > model.avgMetrics[0]  # rank 6 wins on r2
    assert model.bestModel._params["rank"] == 6


def test_regression_metrics_legacy_surface():
    """mllib.evaluation.RegressionMetrics parity (SURVEY.md §2.B7):
    five metric properties vs hand-computed values."""
    from tpu_als import RegressionMetrics

    pred = np.array([2.0, 1.0, 3.0, 4.0])
    obs = np.array([2.5, 0.5, 3.0, 5.0])
    m = RegressionMetrics(zip(pred, obs))
    res = pred - obs
    assert np.isclose(m.meanSquaredError, np.mean(res ** 2))
    assert np.isclose(m.rootMeanSquaredError, np.sqrt(np.mean(res ** 2)))
    assert np.isclose(m.meanAbsoluteError, np.mean(np.abs(res)))
    ss_res = np.sum(res ** 2)
    ss_tot = np.sum((obs - obs.mean()) ** 2)
    assert np.isclose(m.r2, 1 - ss_res / ss_tot)
    # Spark semantics: SSreg/n = E[(pred - mean(obs))^2], always >= 0
    assert np.isclose(m.explainedVariance,
                      np.mean((pred - obs.mean()) ** 2))
    # agreement with the DataFrame-era evaluator on the same pairs
    from tpu_als import RegressionEvaluator

    ev = RegressionEvaluator(metricName="rmse", labelCol="label")
    rmse = ev.evaluate({"prediction": pred, "label": obs})
    assert np.isclose(m.rootMeanSquaredError, rmse)

    import pytest

    with pytest.raises(ValueError, match="at least one"):
        RegressionMetrics([])
