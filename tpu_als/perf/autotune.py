"""Measured-timing autotuner for the fused-solve kernel family.

Closes the half of ROADMAP item 4 the planner left open: the plan cache
banks probe *verdicts* (faster/slower booleans) but every knob governing
the measured-vs-floor gap — ``panel``, the ``_tiles_solve`` VMEM budget,
``max_wc``, the DMA ``pump`` depth, the factor-table dtype — stayed a
hand-picked literal.  This module searches that small discrete space by
timing the REAL kernel (``ops.pallas_gather_ne.gather_solve``) min-of-k
at the plan key's shape class and returns the winner next to the
roofline model's closed-form prediction, so the planner
(``plan.planner.resolve_kernel_config``) can bank
``{config, measured_seconds, model_seconds, banked_at}`` into the
existing ``plan_*.json`` entries and thread the config through the
dispatch sites in place of the literals.

Search discipline: one-at-a-time from the hand-picked defaults — the
default config is timed FIRST, then each knob's alternatives with every
other knob held at its default, and the winner is the single measured
minimum with ties (and sub-noise wins) going to the EARLIER trial.
Because the default is trial 0, the tuned config is never slower than
the hand-picked constants on the very A/B that chose it, by
construction.  The enumeration order is deterministic (dict/tuple order
of ``SPACE``), so a deterministic timer makes the whole search
deterministic — the seed only feeds the instance generator.

Off-TPU the kernels run under ``interpret=True``: the timings still
rank configs by the work the interpreter simulates, but they are NOT
device measurements — the planner banks them with ``source:
"interpret"`` and never lets them override an on-chip verdict.

The re-plan loop: :func:`drifted` compares a banked measured/modeled
ratio against a fresh one; past the configurable band
(``TPU_ALS_TUNE_BAND``) the planner invalidates the entry so the next
armed resolve re-tunes instead of riding a stale config.  The
``floor_audit`` contract (analysis/contracts.py) pins the committed
bank's ratios to the same band so the roofline gap can never silently
reopen in CI.
"""

from __future__ import annotations

import os
import time

from tpu_als import obs

# the discrete search space; every value is a feasible kernel knob at
# rank <= 512 except where _tiles_solve raises TileBudgetError (the
# search skips infeasible combos instead of banking them)
SPACE = {
    "panel": (8, 16, 32),
    "vmem_budget": (1 << 16, 1 << 17, 1 << 18, 1 << 19),
    "max_wc": (128, 256, 512),
    "depth": (2, 4, 8),
    "dtype": ("float32", "bfloat16"),
}

# the hand-picked historical constants — the untuned/off fallback, and
# trial 0 of every search.  depth 8 IS the substrate default
# (ring_buffer.dma_slots == min(8, n_entries); every real tile has
# n_entries >= 64), and dtype float32 is the headline compute dtype.
DEFAULT_CONFIG = {
    "panel": 16,
    "vmem_budget": 1 << 17,
    "max_wc": 256,
    "depth": 8,
    "dtype": "float32",
}

TUNE_BAND_ENV = "TPU_ALS_TUNE_BAND"
DEFAULT_TUNE_BAND = 2.0


def tune_band(default=DEFAULT_TUNE_BAND):
    """The measured/modeled drift band (a multiplicative factor > 1);
    ``TPU_ALS_TUNE_BAND`` overrides."""
    raw = os.environ.get(TUNE_BAND_ENV, "")
    try:
        band = float(raw) if raw else float(default)
    except ValueError:
        band = float(default)
    return max(1.0 + 1e-9, band)


def drifted(banked_ratio, current_ratio, band=None):
    """True when a fresh measured/modeled ratio has left the banked
    ratio's band — the re-plan trigger (``observe regress --trend`` and
    the attribution gap table both reduce their evidence to this)."""
    band = tune_band() if band is None else float(band)
    if not banked_ratio or not current_ratio:
        return False
    rel = float(current_ratio) / float(banked_ratio)
    return rel > band or rel < 1.0 / band


def enumerate_configs(space=None):
    """Deterministic one-at-a-time trial list: the defaults first, then
    each knob's alternatives with the others held at default."""
    space = dict(SPACE if space is None else space)
    base = dict(DEFAULT_CONFIG)
    base.update({k: v[0] for k, v in space.items()
                 if k in base and base[k] not in v})
    trials = [dict(base)]
    for knob, values in space.items():
        if knob not in base:
            raise ValueError(f"unknown autotune knob {knob!r}; "
                             f"knobs: {sorted(DEFAULT_CONFIG)}")
        for v in values:
            if v == base[knob]:
                continue
            cfg = dict(base)
            cfg[knob] = v
            trials.append(cfg)
    return trials


def feasible(config, rank):
    """A config is feasible when the panel divides the padded rank and
    the VMEM budget keeps the row tile above the panel-efficiency knee
    (``_tiles_solve`` raising TileBudgetError is the infeasible case)."""
    from tpu_als.ops.pallas_gather_ne import TileBudgetError, _tiles_solve

    r_pad = max(128, -(-int(rank) // 128) * 128)
    if r_pad % int(config["panel"]):
        return False
    try:
        _tiles_solve(r_pad, 8, panel=int(config["panel"]),
                     max_wc=int(config["max_wc"]),
                     vmem_budget=int(config["vmem_budget"]))
    except TileBudgetError:
        return False
    return True


def model_seconds(config, rank, n, w):
    """The roofline closed-form prediction for one fused-solve call at
    this config's padded shapes — ``fused_solve_kernel_bytes`` over the
    v5e HBM stream, the same single source of truth the kernel's
    ``CostEstimate`` and the fused_solve_audit contract pin.  This is
    what the measured timing is banked NEXT TO, and what the
    ``floor_audit`` band is derived from."""
    import importlib

    rl = importlib.import_module("tpu_als.perf.roofline")
    from tpu_als.ops.pallas_gather_ne import _tiles_solve

    r_pad = max(128, -(-int(rank) // 128) * 128)
    w8 = -(-int(w) // 8) * 8
    tn, wc, w_pad = _tiles_solve(r_pad, w8, panel=int(config["panel"]),
                                 max_wc=int(config["max_wc"]),
                                 vmem_budget=int(config["vmem_budget"]))
    n_pad = -(-int(n) // tn) * tn
    db = 2 if "bfloat16" in str(config["dtype"]) else 4
    by = rl.fused_solve_kernel_bytes(n_pad * w_pad, n_pad, r_pad, db)
    return by / (rl.V5E_HBM_GBPS * 1e9)


def make_timer(rank, compute_dtype, *, n=256, w=64, k=3, seed=0,
               interpret=None):
    """Build the real-kernel timer: ``timer(config) -> min-of-k
    seconds`` for one ``gather_solve`` call on a representative
    (n, w) explicit instance at ``rank``.  Warm call first (compile
    excluded), then min of ``k`` fenced wall-clock reps — the
    ``faster_than_einsum`` probe's ``best(f)`` idiom.  ``interpret``
    defaults to "not on a TPU"."""
    import jax.numpy as jnp
    import numpy as np

    from tpu_als.ops.pallas_gather_ne import gather_fused_solve_explicit
    from tpu_als.utils import platform

    if interpret is None:
        interpret = not platform.on_tpu()
    rng = np.random.default_rng(int(seed))
    N = max(4 * n, 64)
    V32 = jnp.asarray(rng.normal(size=(N, rank)).astype(np.float32)
                      / np.sqrt(rank))
    cols = jnp.asarray(rng.integers(0, N, size=(n, w)).astype(np.int32))
    vals32 = jnp.asarray(rng.normal(size=(n, w)).astype(np.float32))
    mask32 = jnp.asarray((rng.random((n, w)) < 0.8).astype(np.float32))

    def timer(config):
        # the dtype knob IS the factor-table residency: the table and
        # the weight streams move in the config dtype end-to-end (the
        # kernel's reduce_precision ridge keeps the tail consistent)
        dt = jnp.dtype(str(config["dtype"]))
        V = V32.astype(dt)
        vals, mask = vals32.astype(dt), mask32.astype(dt)

        def run():
            return gather_fused_solve_explicit(
                V, cols, vals, mask, 0.1,
                panel=int(config["panel"]),
                max_wc=int(config["max_wc"]),
                vmem_budget=int(config["vmem_budget"]),
                depth=int(config["depth"]),
                interpret=interpret)

        platform.fence(run())  # compile + warm
        best = None
        for _ in range(max(1, int(k))):
            t0 = time.perf_counter()
            platform.fence(run())
            dt_s = time.perf_counter() - t0
            best = dt_s if best is None else min(best, dt_s)
        return best

    timer.interpret = bool(interpret)
    return timer


def tune(*, rank=128, compute_dtype="float32", space=None, budget_s=120.0,
         k=3, n=256, w=64, seed=0, timer=None, kernel="gather_solve"):
    """Run the one-at-a-time search and return the verdict dict the
    planner banks verbatim::

        {"config", "measured_seconds", "default_seconds",
         "model_seconds", "source", "trials", "tune_seconds"}

    ``timer(config) -> seconds`` is injectable (determinism tests, and
    the planner's interpret/device split rides ``timer.interpret``);
    the default is :func:`make_timer` on the real kernel.  The search
    stops early when ``budget_s`` is exhausted — the best config so far
    wins, and the defaults are always trial 0, so a tuned verdict is
    never slower than the hand-picked constants on its own A/B."""
    if timer is None:
        timer = make_timer(rank, compute_dtype, n=n, w=w, k=k, seed=seed)
    source = ("interpret" if getattr(timer, "interpret", True)
              else "device")
    trials = []
    best_cfg, best_s = None, None
    t_start = time.perf_counter()
    for config in enumerate_configs(space):
        if trials and budget_s is not None \
                and time.perf_counter() - t_start > float(budget_s):
            break
        if not feasible(config, rank):
            continue
        seconds = float(timer(config))
        obs.emit("tune_trial", kernel=kernel, config=dict(config),
                 seconds=seconds)
        trials.append({"config": dict(config), "seconds": seconds})
        if best_s is None or seconds < best_s:   # strict: ties keep the
            best_cfg, best_s = dict(config), seconds  # earlier trial
    if best_cfg is None:
        raise ValueError(f"no feasible config at rank {rank} in the "
                         f"given space")
    default_s = trials[0]["seconds"]
    return {
        "config": best_cfg,
        "measured_seconds": best_s,
        "default_seconds": default_s,
        "model_seconds": model_seconds(best_cfg, rank, n, w),
        "source": source,
        "trials": trials,
        "tune_seconds": time.perf_counter() - t_start,
        "shape": {"rank": int(rank), "n": int(n), "w": int(w),
                  "k": int(k), "seed": int(seed)},
    }
