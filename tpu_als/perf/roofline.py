"""Per-stage bytes/FLOPs roofline for one ALS iteration (ISSUE 2).

The question this module answers quantitatively: *how close is the
measured headline (1.184 s/iter on ML-25M rank-128 implicit, one v5e
core) to the memory-bound floor of the algorithm?*  Every prior perf
claim ended at "fastest variant tried"; the matfree-CG episode
(BASELINE.md round-5 resolution) showed why that is not enough — a
designed 10× lever lost on chip because nobody had priced its extra
passes over the gathered-factor HBM stream.

Model
-----
One full iteration = two half-steps (items solved against gathered user
factors, then vice versa).  Per half-step, with ``P`` padded ratings on
the solved side (``padding_waste × nnz``), ``n`` solved rows, ``N``
opposite rows, rank ``r`` and compute-dtype width ``db``:

- **gather_stream**: every padded entry reads one opposite factor row
  and writes it into the gathered layout (``2·P·r·db``), plus the
  cols/vals/mask rating stream (``12·P``).  This is THE co-dominant
  cost at rank 128 and the stream matfree CG fatally re-read.
- **normal_eq**: the einsum re-reads the gathered rows (``P·r·db``) and
  writes the ``[n, r, r]`` normal-equation tensor once (``n·r²·4``).
  FLOPs ``2·P·r² + 2·P·r`` (A then b).
- **solve**: reads A + b, writes x (``n·(r²+2·r)·4``).  FLOPs
  ``n·(2r³/3 + 4r²)`` — tiny on the MXU, but the batched Cholesky is a
  serial recurrence that runs on the VPU; the measured headline spends
  ~80% of the iteration here (BASELINE.md round-2 profile), far above
  this stage's floor.  The roofline makes that gap explicit instead of
  hiding it in a fudge factor.
- **scatter**: writes the solved rows back (``n·r·4``).
- **yty** (implicit feedback only): reads each factor table once and
  prices ``2·N·r²`` FLOPs per half-step.
- **collective** (sharded only): ICI bytes from
  :func:`tpu_als.parallel.trainer.comm_bytes_per_iter` — the SAME
  closed form the comm-audit tests pin to the traced jaxpr, so the
  roofline's comm stage is transitively traced-checked
  (tests/test_roofline.py cross-checks this equality directly).

Floor = Σ over stages of ``max(hbm_bytes/BW, flops/peak)`` (each stage
at its bandwidth: HBM for on-chip stages, ICI for the collective).  A
pure-HBM floor (Σ bytes / HBM BW) is reported alongside — that is the
"how fast could this possibly go without changing the algorithm"
number docs/roofline.md quotes next to the measured 1.184.
"""

from __future__ import annotations

from dataclasses import dataclass

# v5e public per-chip specs: 819 GB/s HBM BW, 197 bf16 TFLOP/s
# (f32 ~half).  ICI: 1600 Gbps aggregate per chip ≈ 200 GB/s.
V5E_HBM_GBPS = 819.0
V5E_ICI_GBPS = 200.0
V5E_BF16_PEAK_FLOPS = 197e12
V5E_F32_PEAK_FLOPS = 98.5e12

# THE headline config (BASELINE.md row 2): ML-25M, rank 128, implicit
# alpha=40, f32, single v5e core; padding_waste and the measured
# s/iter from sweep_logs/headline_f32.out (2026-07-31).
HEADLINE = dict(n_users=162_541, n_items=59_047, nnz=25_000_095,
                rank=128, dtype="float32", implicit=True,
                padding_waste=1.514, devices=1)
HEADLINE_MEASURED_S_PER_ITER = 1.184


def fused_ne_kernel_bytes(P, n, r, db):
    """HBM bytes the gather-fused NE kernel
    (tpu_als.ops.pallas_gather_ne) moves for one half-step over ``P``
    padded entries / ``n`` solved rows: each entry's factor row read ONCE
    straight into VMEM (never written back as a gathered intermediate),
    the cols (int32) + aw/bw weight streams, and the A/b outputs.

    THE single source of truth shared by the roofline's fused stage
    below, the kernel's ``pl.CostEstimate``, and the traced-jaxpr audit
    (tests/test_ne_audit.py extracts the estimate from the trace and pins
    it to this formula — the test_comm_audit.py pattern).
    """
    return int(P * r * db + P * (4 + 2 * db) + n * r * r * 4 + n * r * 4)


def fused_solve_kernel_bytes(P, n, r, db):
    """HBM bytes the whole-iteration fused kernel
    (tpu_als.ops.pallas_gather_ne.gather_solve) moves for one half-step:
    each entry's factor row read ONCE straight into VMEM, the cols (int32)
    + aw/bw/cw weight streams, and the solved ``x [n, r]`` output — the
    ``[n, r, r]`` normal-equation tensor never touches HBM (neither
    written NOR read back by a solver), which is this model's whole
    difference from :func:`fused_ne_kernel_bytes` + the solve stage.

    THE single source of truth shared by the roofline's fused-solve stage,
    the kernel's ``pl.CostEstimate``, and the fused_solve_audit contract
    (analysis/contracts.py) that pins the traced estimate to this formula.
    """
    return int(P * r * db + P * (4 + 3 * db) + n * r * 4)


def ring_remote_bytes(n_row_tiles, n_shards, per, r, db):
    """In-kernel remote-DMA payload of ONE ``gather_solve_ring`` call
    (tpu_als.ops.pallas_gather_ne): every row tile runs its own full ring
    pass, and each pass forwards the held ``[per, r]`` factor shard
    ``S - 1`` times — there is NO homecoming rotation (the XLA ring's
    S-th permute exists only to restore the shard for the next tile; the
    kernel re-streams from its immutable HBM copy instead, which is why
    the in-kernel ring moves (S-1)/S of the XLA ring's bytes per pass).

    THE single source of truth shared by the kernel's ``pl.CostEstimate``
    ring term, ``trainer.comm_bytes_per_iter('gather_fused_ring', …)``,
    and the extended ``comm_audit`` contract (analysis/contracts.py) that
    pins the traced remote-DMA payload × fire count to this formula.
    """
    return int(n_row_tiles * max(0, n_shards - 1) * per * r * db)


def fused_ring_kernel_bytes(P, n, r, db, ring_bytes):
    """HBM bytes of the fused-comm ring kernel
    (tpu_als.ops.pallas_gather_ne.gather_solve_ring): the whole-iteration
    fused model (:func:`fused_solve_kernel_bytes` — rows read once, weight
    streams, x out) plus the inter-chip ring payload
    (:func:`ring_remote_bytes`, counted once per transfer: the send's HBM
    read on this chip; the matching write lands on the neighbor)."""
    return fused_solve_kernel_bytes(P, n, r, db) + int(ring_bytes)


def serve_merge_remote_bytes(n_user_tiles, n_shards, tile_u, lanes=128):
    """In-kernel remote-DMA payload of ONE ``topk_merge_ring`` call
    (tpu_als.ops.pallas_topk): every user tile runs its own ring pass,
    and each pass forwards one packed ``[tile_u, 2·lanes]`` f32 candidate
    set (scores ++ bitcast ids) ``S - 1`` times — the set received each
    hop is what gets forwarded next, so after ``S - 1`` hops every device
    holds all ``S`` per-shard sets in VMEM and merges them locally.
    Note what is ABSENT from the form: the catalog (it never rotates; a
    query costs ``O(S · tile_u · lanes)`` wire bytes however large the
    sharded table is) and any per-shard ``[n, k]`` HBM list (the sets
    live only in the kernel's VMEM collect buffer).

    THE single source of truth shared by the kernel's
    ``pl.CostEstimate`` ring term, the per-query serving model
    (:func:`serve_query_bytes`, docs/roofline.md), and the
    ``serve_comm_audit`` contract (analysis/contracts.py) that pins the
    traced remote-DMA payload × fire count to this formula.
    """
    return int(n_user_tiles * max(0, n_shards - 1)
               * tile_u * 2 * lanes * 4)


def serve_query_bytes(n_queries, n_shards, ni, r, *, tile_u=256,
                      lanes=128, db=4):
    """Per-batch byte model of one fused sharded serving call, split by
    channel: ``hbm`` = each device streams its OWN catalog shard once
    (``ceil(Ni/S)·r·db``) plus the replicated query rows and the [n,
    LANES] result pair; ``ici`` = :func:`serve_merge_remote_bytes` over
    ``ceil(n/tile_u)`` user tiles.  Divide by ``n_queries`` for the
    per-query closed form docs/roofline.md quotes: the wire cost per
    query is independent of catalog size — the scaling property the
    sharded fabric exists for.
    """
    S = max(1, int(n_shards))
    ni_loc = -(-int(ni) // S)
    n_ut = -(-int(n_queries) // int(tile_u))
    hbm = int(n_queries * r * db + ni_loc * r * db
              + 2 * n_queries * lanes * 4)
    ici = serve_merge_remote_bytes(n_ut, S, tile_u, lanes)
    return {"hbm_bytes": hbm, "ici_bytes": ici,
            "hbm_per_query": hbm / max(1, n_queries),
            "ici_per_query": ici / max(1, n_queries)}


def einsum_ne_build_bytes(P, n, r, db, restream=1.0):
    """Modeled NE-build bytes of the UNFUSED path (gather_stream +
    normal_eq stages below, summed): the gather reads one factor row per
    padded entry and writes the [n, w, r] intermediate, the cols/vals/
    mask stream rides along, and the einsum re-reads the gathered rows
    and writes A.  The fused-vs-einsum byte-reduction claim
    (docs/roofline.md; pinned ≥40% at the headline config in
    tests/test_ne_audit.py) is this minus :func:`fused_ne_kernel_bytes`.
    """
    return int(restream * (2.0 * P * r * db) + 12.0 * P
               + P * r * db + n * r * r * 4.0)


def modeled_padding_waste(counts, min_width=8, chunk_elems=1 << 19,
                          growth=2.0):
    """padded_nnz / nnz for a degree distribution, derived from the SAME
    width-assignment + row-padding helpers the builder uses
    (tpu_als.core.ratings.entity_widths / padded_bucket_rows) — no bucket
    arrays are built, so this prices ML-25M-scale layouts instantly.
    Cross-checked against an actual ``build_csr_buckets`` run in
    tests/test_roofline.py (replaces the hardcoded 1.514 caller constant;
    the constant survives as an explicit override).
    """
    import numpy as np

    from tpu_als.core.ratings import entity_widths, padded_bucket_rows

    counts = np.asarray(counts, dtype=np.int64)
    nnz = int(counts.sum())
    rated = counts[counts > 0]
    if not nnz or not len(rated):
        return 1.0
    w = entity_widths(rated, min_width, growth)
    padded = 0
    for wv in sorted(set(w.tolist())):
        nb = int((w == wv).sum())
        padded += padded_bucket_rows(nb, int(wv), chunk_elems) * int(wv)
    return padded / nnz


@dataclass
class Stage:
    name: str
    bytes: float          # bytes moved through `bw` per iteration
    flops: float          # MXU-priced FLOPs per iteration
    bw: float             # bytes/sec of the stage's channel
    peak: float           # FLOP/s peak for the stage's dtype
    note: str = ""

    @property
    def byte_seconds(self):
        return self.bytes / self.bw if self.bw else 0.0

    @property
    def flop_seconds(self):
        return self.flops / self.peak if self.peak else 0.0

    @property
    def floor_seconds(self):
        return max(self.byte_seconds, self.flop_seconds)

    @property
    def bound(self):
        if not self.bytes and not self.flops:
            return "-"
        return "bytes" if self.byte_seconds >= self.flop_seconds \
            else "flops"


def _dtype_bytes(dtype):
    return {"float32": 4, "bfloat16": 2, "float16": 2}[str(dtype)]


def roofline(n_users, n_items, nnz, rank, *, dtype="float32",
             implicit=True, padding_waste=None, devices=1,
             strategy=None, tiles_user=1, tiles_item=1,
             comm_bytes=None, user_part=None, item_part=None,
             user_container=None, item_container=None,
             user_counts=None, item_counts=None,
             min_width=8, chunk_elems=1 << 19, width_growth=2.0,
             ne_path="einsum",
             hbm_gbps=V5E_HBM_GBPS, ici_gbps=V5E_ICI_GBPS,
             measured_s_per_iter=None):
    """Analytical per-stage roofline for one full ALS iteration.

    Parameterized by problem shape, ``dtype`` (compute dtype of the
    gather/NE stream), ``strategy`` + chunking (``tiles_user`` /
    ``tiles_item`` row-tile counts — the ring and chunked-gather
    strategies re-stream the opposite factors once per tile).

    ``ne_path``: 'einsum' prices the unfused build (gather_stream +
    normal_eq stages); 'gather_fused' prices the DMA-gather kernel
    (tpu_als.ops.pallas_gather_ne) — one fused stage reading each factor
    row ONCE and writing A/b, the :func:`fused_ne_kernel_bytes` model;
    'gather_fused_solve' prices the whole-iteration fusion — gather, Gram,
    ridge/YtY tail AND the Cholesky solve in one kernel writing only x,
    the :func:`fused_solve_kernel_bytes` model (the standalone solve
    stage folds into it).

    ``padding_waste``: explicit override; when None it is DERIVED from
    the per-entity degree arrays ``user_counts``/``item_counts`` via
    :func:`modeled_padding_waste` (the builder's own width assignment at
    ``min_width``/``chunk_elems``/``width_growth``), falling back to 1.0
    when no counts are given.

    Collective bytes: pass ``comm_bytes`` directly, or the built
    partitions/containers (``user_part``/``item_part`` +
    ``user_container``/``item_container``) to price them with the exact
    :func:`~tpu_als.parallel.trainer.comm_bytes_per_iter` closed form
    — the one the comm-audit tests pin to the traced jaxpr.

    Returns a plain dict (JSON-ready): per-stage accounting, the
    byte-only HBM floor, the per-stage roofline floor, and (when
    ``measured_s_per_iter`` is given) the measured-over-floor ratios.
    """
    D = max(1, int(devices))
    r = int(rank)
    db = _dtype_bytes(dtype)
    peak = V5E_F32_PEAK_FLOPS if db == 4 else V5E_BF16_PEAK_FLOPS
    hbm = hbm_gbps * 1e9
    ici = ici_gbps * 1e9
    if ne_path not in ("einsum", "gather_fused", "gather_fused_solve"):
        raise ValueError(f"unknown ne_path {ne_path!r} (expected "
                         "'einsum', 'gather_fused' or "
                         "'gather_fused_solve')")
    padding_waste_source = "explicit"
    if padding_waste is None:
        if user_counts is not None or item_counts is not None:
            sides = [c for c in (user_counts, item_counts) if c is not None]
            padding_waste = sum(
                modeled_padding_waste(c, min_width, chunk_elems,
                                      width_growth)
                for c in sides) / len(sides)
            padding_waste_source = "derived"
        else:
            padding_waste = 1.0
            padding_waste_source = "default"

    # per-device padded entries over BOTH half-steps; solved rows and
    # opposite-table rows per device
    P = 2.0 * float(padding_waste) * float(nnz) / D
    n = float(n_users + n_items) / D
    # the ring / chunked strategies re-stream the opposite factors once
    # per row tile; plain all_gather and a single-device run stream once
    restream = 1.0
    if strategy in ("ring", "ring_overlap", "all_gather_chunked"):
        restream = (float(tiles_user) + float(tiles_item)) / 2.0

    if ne_path == "gather_fused_solve":
        # the solve is fused INTO this stage (its flops ride along, its
        # A/b read-back bytes vanish) — no standalone solve stage below
        ne_stages = [Stage(
            "gather_fused_solve",
            bytes=(fused_solve_kernel_bytes(P, n, r, db)
                   + (restream - 1.0) * P * r * db),
            flops=(2.0 * P * r * r + 2.0 * P * r
                   + n * (2.0 * r ** 3 / 3.0 + 4.0 * r * r)),
            bw=hbm, peak=peak,
            note="whole-iteration fused kernel: factor rows read ONCE "
                 "into VMEM, Gram + ridge/YtY tail + Cholesky solve in "
                 "VMEM, only x written — A never in HBM "
                 "(ops/pallas_gather_ne.gather_solve)")]
    elif ne_path == "gather_fused":
        ne_stages = [Stage(
            "gather_fused_ne",
            bytes=(fused_ne_kernel_bytes(P, n, r, db)
                   + (restream - 1.0) * P * r * db),
            flops=2.0 * P * r * r + 2.0 * P * r,
            bw=hbm, peak=peak,
            note="DMA-gather kernel: factor rows read ONCE into VMEM, "
                 "A/b written — Vg never in HBM "
                 "(ops/pallas_gather_ne)")]
    else:
        ne_stages = [
            Stage("gather_stream",
                  bytes=restream * (2.0 * P * r * db) + 12.0 * P,
                  flops=0.0, bw=hbm, peak=peak,
                  note="opposite factor rows read+written per padded "
                       "entry + cols/vals/mask stream"),
            Stage("normal_eq",
                  bytes=P * r * db + n * r * r * 4.0,
                  flops=2.0 * P * r * r + 2.0 * P * r,
                  bw=hbm, peak=peak,
                  note="einsum re-reads gathered rows, writes [n,r,r] A"),
        ]
    stages = list(ne_stages)
    if ne_path != "gather_fused_solve":
        stages.append(Stage(
            "solve",
            bytes=n * (r * r + 2.0 * r) * 4.0,
            flops=n * (2.0 * r ** 3 / 3.0 + 4.0 * r * r),
            bw=hbm, peak=peak,
            note="reads A+b, writes x; VPU-serial Cholesky in "
                 "practice — see docs/roofline.md"))
    stages.append(Stage(
        "scatter",
        bytes=n * r * 4.0, flops=0.0, bw=hbm, peak=peak,
        note="solved rows written back"))
    if implicit:
        stages.append(Stage(
            "yty",
            bytes=2.0 * (float(n_users + n_items) / D) * r * 4.0,
            flops=2.0 * 2.0 * (float(n_users + n_items) / D) * r * r,
            bw=hbm, peak=peak,
            note="YtY precompute per half-step"))
    if comm_bytes is None and strategy is not None and D > 1:
        if user_part is not None and item_part is not None:
            from tpu_als.parallel.trainer import comm_bytes_per_iter

            comm_bytes = comm_bytes_per_iter(
                strategy, user_part, item_part, r,
                user_container=user_container,
                item_container=item_container, implicit=implicit)
        else:
            # closed-form estimate with balanced rows_per_shard =
            # ceil(n/D) — same formulas as trainer.comm_bytes_per_iter
            # (which is exact once containers exist; all_to_all needs
            # the built request budgets, so no estimate there)
            per_u = -(-int(n_users) // D)
            per_i = -(-int(n_items) // D)
            fb = 4 * r
            if strategy == "all_gather":
                comm_bytes = (D - 1) * (per_i + per_u) * fb
            elif strategy in ("ring", "ring_overlap"):
                comm_bytes = D * fb * (per_i * int(tiles_user)
                                       + per_u * int(tiles_item))
            elif strategy == "all_gather_chunked":
                comm_bytes = (D - 1) * fb * (per_i * int(tiles_user)
                                             + per_u * int(tiles_item))
            if comm_bytes is not None and implicit:
                comm_bytes += 2 * 2 * (D - 1) * r * r * 4 // D
    if comm_bytes:
        stages.append(Stage(
            "collective", bytes=float(comm_bytes), flops=0.0,
            bw=ici, peak=peak,
            note=f"{strategy} ICI traffic "
                 "(= trainer.comm_bytes_per_iter, traced-checked)"))

    hbm_bytes = sum(s.bytes for s in stages if s.bw == hbm)
    total_flops = sum(s.flops for s in stages)
    hbm_floor = hbm_bytes / hbm
    floor = sum(s.floor_seconds for s in stages)
    report = {
        "config": {
            "n_users": int(n_users), "n_items": int(n_items),
            "nnz": int(nnz), "rank": r, "dtype": str(dtype),
            "implicit": bool(implicit),
            "padding_waste": float(padding_waste),
            "padding_waste_source": padding_waste_source,
            "width_growth": float(width_growth),
            "ne_path": ne_path, "devices": D,
            "strategy": strategy,
            "tiles_user": int(tiles_user), "tiles_item": int(tiles_item),
            "hbm_gbps": float(hbm_gbps), "ici_gbps": float(ici_gbps),
        },
        "stages": [
            {"name": s.name, "bytes": int(s.bytes), "flops": int(s.flops),
             "byte_seconds": s.byte_seconds,
             "flop_seconds": s.flop_seconds,
             "floor_seconds": s.floor_seconds,
             "bound": s.bound, "note": s.note}
            for s in stages
        ],
        "hbm_bytes_per_iter": int(hbm_bytes),
        "comm_bytes_per_iter": int(comm_bytes or 0),
        "flops_per_iter": int(total_flops),
        "hbm_floor_s_per_iter": hbm_floor,
        "roofline_floor_s_per_iter": floor,
    }
    if measured_s_per_iter:
        report["measured_s_per_iter"] = float(measured_s_per_iter)
        report["measured_over_hbm_floor"] = (
            float(measured_s_per_iter) / hbm_floor if hbm_floor else None)
        report["measured_over_roofline_floor"] = (
            float(measured_s_per_iter) / floor if floor else None)
    return report


def headline_roofline(**overrides):
    """The roofline of BASELINE.md row 2 with its measured point.

    ``headline_roofline(ne_path='gather_fused')`` prices the same config
    on the DMA-gather kernel — the revised floor docs/roofline.md quotes.
    """
    return roofline(**{**HEADLINE, **overrides},
                    measured_s_per_iter=HEADLINE_MEASURED_S_PER_ITER)


def render(report):
    """Human-readable table for ``tpu_als observe roofline``."""
    c = report["config"]
    lines = [
        ("ALS iteration roofline — "
         f"{c['n_users']}x{c['n_items']} nnz={c['nnz']} rank={c['rank']} "
         f"{c['dtype']} {'implicit' if c['implicit'] else 'explicit'} "
         f"waste={c['padding_waste']:.3f}"
         f" ({c.get('padding_waste_source', 'explicit')})"
         f" ne={c.get('ne_path', 'einsum')} D={c['devices']}"
         + (f" strategy={c['strategy']}" if c["strategy"] else "")),
        f"(HBM {c['hbm_gbps']} GB/s, ICI {c['ici_gbps']} GB/s, v5e)",
        "",
        f"{'stage':<16}{'MB moved':>12}{'GFLOP':>10}"
        f"{'bytes ms':>10}{'flops ms':>10}{'bound':>7}",
    ]
    for s in report["stages"]:
        lines.append(
            f"{s['name']:<16}{s['bytes'] / 1e6:>12.1f}"
            f"{s['flops'] / 1e9:>10.1f}"
            f"{s['byte_seconds'] * 1e3:>10.2f}"
            f"{s['flop_seconds'] * 1e3:>10.2f}{s['bound']:>7}")
    lines += [
        "",
        f"HBM floor (all bytes / BW):    "
        f"{report['hbm_floor_s_per_iter']:.3f} s/iter",
        f"roofline floor (per-stage max): "
        f"{report['roofline_floor_s_per_iter']:.3f} s/iter",
    ]
    if "measured_s_per_iter" in report:
        lines += [
            f"measured:                       "
            f"{report['measured_s_per_iter']:.3f} s/iter  "
            f"({report['measured_over_hbm_floor']:.1f}x HBM floor, "
            f"{report['measured_over_roofline_floor']:.1f}x roofline)",
            "gap mechanism: the batched Cholesky runs on the VPU's "
            "serial recurrence, ~80% of the measured iteration "
            "(docs/roofline.md)",
        ]
    return "\n".join(lines)
