"""``tpu_als.perf`` — analytical performance models.

:mod:`tpu_als.perf.roofline` prices one ALS iteration stage by stage
(bytes moved vs FLOPs) and turns it into an HBM/compute floor in
seconds per iteration, so measured points land on a chart with a floor
instead of in a vacuum.  See docs/roofline.md.
"""

from tpu_als.perf.roofline import (  # noqa: F401
    HEADLINE,
    Stage,
    render,
    roofline,
)
