"""Stage attribution: measure where an ALS iteration's seconds GO.

``perf/roofline.py`` models what each stage of an iteration *should*
cost from bytes and FLOPs; this module measures what each stage
*actually* costs and joins the two into a gap table — the measured-probe
input format ROADMAP item 5's cost-model-driven planner consumes.

The production step (``core.als._step_jit``) is ONE jitted call — XLA
fuses across stage boundaries and the host sees a single opaque
dispatch, so it cannot be fence-timed from outside.  Attribution
therefore runs a DECOMPOSED twin of ``local_half_step``: the same
gather / normal-equation / solve / scatter (+ yty) computation split
into one jitted call per stage, each wrapped in an
``obs.trace.stage`` fence (``block_until_ready`` boundaries), with all
iteration-invariant prep (chunk reshapes, dtype casts of the rating
stream) hoisted to build time so the fences bracket real per-iteration
work.  Stage names match the roofline's exactly (``gather_stream``,
``normal_eq`` / ``gather_fused_ne`` / ``gather_fused_solve``,
``solve``, ``scatter``, ``yty``), so the join is by name.  On the
whole-iteration fused path the NE build and the solve are ONE kernel,
so they are fenced as the single ``gather_fused_solve`` stage — the
roofline models that stage the same way, so the gap column stays
meaningful.

The decomposed twin loses cross-stage fusion, so its wall clock is an
upper bound on the fused step's — ``measure_attributed`` times the real
fused step alongside and reports both.  The production ``train()`` loop
only ever reaches this module when ``obs.trace.stage_attribution_armed``
is true; disarmed, the fused step is byte-for-byte untouched (pinned by
an unchanged-jaxpr test).
"""

from __future__ import annotations

import functools
import time

import jax
import jax.numpy as jnp

from tpu_als.core.als import (
    AlsConfig,
    init_factors,
    make_step,
    resolve_solve_path,
)
from tpu_als.core.ratings import trainer_chunk
from tpu_als.obs import trace
from tpu_als.ops.solve import (
    DEFAULT_JITTER,
    compute_yty,
    normal_eq_explicit,
    normal_eq_implicit,
    solve_nnls,
    solve_spd,
)


class AttributionUnsupported(ValueError):
    """The resolved solve path has no decomposed twin (CG configs) —
    attribution covers the production exact paths."""


_gather = jax.jit(lambda V_comp, c: V_comp[c])
_yty = jax.jit(compute_yty)
_ne_explicit = jax.jit(normal_eq_explicit)
_ne_implicit = jax.jit(normal_eq_implicit)
_solve_spd = jax.jit(lambda A, b, count: solve_spd(A, b, count))


@functools.partial(jax.jit, donate_argnums=(0,))
def _scatter(out, rows, x):
    # padding rows carry index num_rows -> out of bounds -> dropped
    return out.at[rows].set(x, mode="drop", unique_indices=True)


def _bucket_plan(buckets, rank, cfg, chunk_elems, gather):
    """Iteration-invariant prep, hoisted out of the timed loop: the same
    chunk split ``local_half_step`` computes, pre-sliced into per-chunk
    device arrays with the rating stream pre-cast to compute dtype."""
    cdt = jnp.dtype(cfg.compute_dtype)
    plan = []
    for b in buckets:
        nb, w = b.cols.shape
        chunk = trainer_chunk(nb, w, rank, chunk_elems, fused_gather=gather)
        nchunks = nb // chunk
        cols = b.cols.reshape(nchunks, chunk, w)
        vals = b.vals.astype(cdt).reshape(nchunks, chunk, w)
        mask = b.mask.astype(cdt).reshape(nchunks, chunk, w)
        plan.append({
            "nb": nb, "rows": b.rows,
            "chunks": [(cols[k], vals[k], mask[k]) for k in range(nchunks)],
        })
    return plan


def make_attributed_step(user_buckets, item_buckets, num_users, num_items,
                         cfg: AlsConfig, user_chunk_elems=1 << 19,
                         item_chunk_elems=1 << 19, sink=None):
    """Build the decomposed fence-timed twin of ``core.als.make_step``.

    Same signature contract: returns ``step(U, V) -> (U, V)`` computing
    the identical iteration (item half then user half), but as per-stage
    jitted calls bracketed by ``obs.trace.stage`` fences.  Per-stage
    seconds land in ``train.stage_seconds{stage=...}`` and, when a
    ``sink`` dict is given, accumulate into it keyed by stage name.
    """
    resolved = resolve_solve_path(cfg, cfg.rank)
    path = resolved["resolved_solve_path"]
    gsolve = path == "gatherfused_solve"
    gather = path.startswith("gatherfused+")
    if cfg.cg_iters > 0:
        raise AttributionUnsupported(
            f"no decomposed twin for resolved solve path {path!r} "
            "(attribution covers the exact einsum / gather-fused paths)")
    gather_interpret = not resolved["on_tpu"]
    r = cfg.rank
    cdt = jnp.dtype(cfg.compute_dtype)
    reg = jnp.float32(cfg.reg_param)
    alpha = jnp.float32(cfg.alpha)

    if cfg.nonnegative:
        solve_fn = jax.jit(
            functools.partial(solve_nnls, sweeps=cfg.nnls_sweeps,
                              jitter=cfg.jitter))
    elif cfg.jitter == DEFAULT_JITTER:
        solve_fn = _solve_spd
    else:
        # non-default jitter (AlsConfig.jitter is the one knob): the twin
        # must solve the same regularized system as the production step
        solve_fn = jax.jit(
            functools.partial(solve_spd, jitter=cfg.jitter))

    item_plan = _bucket_plan(item_buckets, r, cfg, item_chunk_elems,
                             gather or gsolve)
    user_plan = _bucket_plan(user_buckets, r, cfg, user_chunk_elems,
                             gather or gsolve)

    def solve_fused(V_comp, c, v, m, YtY):
        from tpu_als.ops.pallas_gather_ne import (
            gather_fused_solve_explicit,
            gather_fused_solve_implicit,
        )

        # reg/alpha are STATIC on this path (the Pallas tail bakes them
        # in) — same as the production dispatch in local_half_step
        if cfg.implicit_prefs:
            return gather_fused_solve_implicit(
                V_comp, c, v, m, cfg.reg_param, cfg.alpha,
                YtY.astype(jnp.float32), jitter=cfg.jitter,
                interpret=gather_interpret)
        return gather_fused_solve_explicit(
            V_comp, c, v, m, cfg.reg_param, jitter=cfg.jitter,
            interpret=gather_interpret)

    def ne_fused(V_comp, c, v, m, YtY):
        from tpu_als.ops.pallas_gather_ne import (
            gather_normal_eq_explicit,
            gather_normal_eq_implicit,
        )

        if cfg.implicit_prefs:
            return gather_normal_eq_implicit(
                V_comp, c, v, m, reg, alpha, YtY.astype(jnp.float32),
                interpret=gather_interpret)
        return gather_normal_eq_explicit(
            V_comp, c, v, m, reg, interpret=gather_interpret)

    def half_step(V_full, plan, num_rows, YtY):
        with trace.stage("gather_stream", sink) as keep:
            V_comp = keep(V_full.astype(cdt))
        with trace.stage("scatter", sink) as keep:
            out = keep(jnp.zeros((num_rows, r), dtype=jnp.float32))
        for b in plan:
            xs = []
            for c, v, m in b["chunks"]:
                if gsolve:
                    # NE build + solve are one kernel here: one fence,
                    # one stage, joined to the roofline's
                    # gather_fused_solve stage by name
                    with trace.stage("gather_fused_solve", sink) as keep:
                        xs.append(keep(solve_fused(V_comp, c, v, m, YtY)))
                    continue
                if gather:
                    with trace.stage("gather_fused_ne", sink) as keep:
                        A, rhs, count = keep(ne_fused(V_comp, c, v, m, YtY))
                else:
                    with trace.stage("gather_stream", sink) as keep:
                        Vg = keep(_gather(V_comp, c))
                    with trace.stage("normal_eq", sink) as keep:
                        if cfg.implicit_prefs:
                            A, rhs, count = keep(_ne_implicit(
                                Vg, v, m, reg, alpha,
                                YtY.astype(jnp.float32)))
                        else:
                            A, rhs, count = keep(_ne_explicit(Vg, v, m, reg))
                with trace.stage("solve", sink) as keep:
                    xs.append(keep(solve_fn(A.astype(jnp.float32),
                                            rhs.astype(jnp.float32), count)))
            with trace.stage("scatter", sink) as keep:
                out = keep(_scatter(out, b["rows"],
                                    jnp.concatenate(xs, axis=0)
                                    .reshape(b["nb"], r)))
        return out

    def step(U, V):
        if cfg.implicit_prefs:
            with trace.stage("yty", sink) as keep:
                YtY_u = keep(_yty(U))
            V = half_step(U, item_plan, num_items, YtY_u)
            with trace.stage("yty", sink) as keep:
                YtY_v = keep(_yty(V))
            U = half_step(V, user_plan, num_users, YtY_v)
        else:
            V = half_step(U, item_plan, num_items, None)
            U = half_step(V, user_plan, num_users, None)
        return U, V

    return step


def measure_attributed(user_csr, item_csr, cfg: AlsConfig, iters=2,
                       warmup=1, compare_fused=True):
    """Run ``iters`` fence-timed attributed iterations (after ``warmup``
    un-timed ones to absorb compiles) and return per-stage seconds.

    Also times the PRODUCTION fused step on the same problem (same
    warmup discipline) so the report can state the attribution twin's
    overhead honestly.  Returns a dict with ``stage_seconds`` (per-iter,
    keyed by roofline stage name), ``wall_s_per_iter``, ``coverage``
    (sum of stages / wall — the ≥0.9 acceptance bound),
    ``unattributed_s_per_iter``, and ``fused_s_per_iter``.
    """
    num_users, num_items = user_csr.num_rows, item_csr.num_rows
    ub = jax.device_put(user_csr.device_buckets())
    ib = jax.device_put(item_csr.device_buckets())
    key = jax.random.PRNGKey(cfg.seed)
    ku, kv = jax.random.split(key)

    sink = {}
    with trace.stage_attribution():
        astep = make_attributed_step(
            ub, ib, num_users, num_items, cfg,
            user_csr.chunk_elems, item_csr.chunk_elems, sink=sink)
        U = init_factors(ku, num_users, cfg.rank)
        V = init_factors(kv, num_items, cfg.rank)
        for _ in range(warmup):
            U, V = astep(U, V)
        jax.block_until_ready((U, V))
        sink.clear()
        t0 = time.perf_counter()
        for _ in range(iters):
            U, V = astep(U, V)
        jax.block_until_ready((U, V))
        wall = (time.perf_counter() - t0) / iters

    stage_seconds = {k: v / iters for k, v in sink.items()}
    attributed = sum(stage_seconds.values())
    out = {
        "stage_seconds": stage_seconds,
        "wall_s_per_iter": wall,
        "sum_stage_s_per_iter": attributed,
        "coverage": attributed / wall if wall else 0.0,
        "unattributed_s_per_iter": wall - attributed,
        "resolved_solve_path": resolve_solve_path(
            cfg, cfg.rank)["resolved_solve_path"],
        "iters": int(iters), "warmup": int(warmup),
    }
    if compare_fused:
        step = make_step(ub, ib, num_users, num_items, cfg,
                         user_csr.chunk_elems, item_csr.chunk_elems)
        U = init_factors(ku, num_users, cfg.rank)
        V = init_factors(kv, num_items, cfg.rank)
        for _ in range(warmup):
            U, V = step(U, V)
        jax.block_until_ready((U, V))
        t0 = time.perf_counter()
        for _ in range(iters):
            U, V = step(U, V)
        jax.block_until_ready((U, V))
        out["fused_s_per_iter"] = (time.perf_counter() - t0) / iters
    return out


def attribution_report(measured, rl):
    """Join measured per-stage seconds against a ``roofline()`` report.

    One row per stage present in EITHER side (a modeled stage with no
    measurement — e.g. ``collective`` on one device — shows measured
    None; a measured stage the model lacks shows floor None), each with
    gap × (measured / modeled floor) and % of the measured iteration.
    """
    wall = measured["wall_s_per_iter"]
    stage_s = dict(measured["stage_seconds"])
    rows = []
    for s in rl["stages"]:
        m = stage_s.pop(s["name"], None)
        rows.append({
            "stage": s["name"], "measured_s": m,
            "floor_s": s["floor_seconds"], "bound": s["bound"],
            "gap_x": (m / s["floor_seconds"]
                      if m is not None and s["floor_seconds"] else None),
            "pct_of_iter": (100.0 * m / wall
                            if m is not None and wall else None),
        })
    for name, m in sorted(stage_s.items()):
        rows.append({"stage": name, "measured_s": m, "floor_s": None,
                     "bound": None, "gap_x": None,
                     "pct_of_iter": 100.0 * m / wall if wall else None})
    report = {
        "config": rl["config"],
        "rows": rows,
        "wall_s_per_iter": wall,
        "sum_stage_s_per_iter": measured["sum_stage_s_per_iter"],
        "unattributed_s_per_iter": measured["unattributed_s_per_iter"],
        "coverage": measured["coverage"],
        "roofline_floor_s_per_iter": rl["roofline_floor_s_per_iter"],
        "resolved_solve_path": measured["resolved_solve_path"],
        "iters": measured["iters"],
    }
    if "fused_s_per_iter" in measured:
        report["fused_s_per_iter"] = measured["fused_s_per_iter"]
        report["attribution_overhead_x"] = (
            wall / measured["fused_s_per_iter"]
            if measured["fused_s_per_iter"] else None)
    return report


def render_attribution(report):
    """Human-readable gap table for ``tpu_als observe attribution``."""
    c = report["config"]
    lines = [
        ("ALS stage attribution — measured vs modeled floor — "
         f"{c['n_users']}x{c['n_items']} nnz={c['nnz']} rank={c['rank']} "
         f"{c['dtype']} {'implicit' if c['implicit'] else 'explicit'} "
         f"waste={c['padding_waste']:.3f} "
         f"path={report['resolved_solve_path']}"),
        f"({report['iters']} fence-timed iterations, warm)",
        "",
        f"{'stage':<16}{'measured s':>12}{'floor s':>12}"
        f"{'gap x':>9}{'% iter':>8}",
    ]

    def num(v, fmt, width):
        return f"{v:>{width}{fmt}}" if v is not None else f"{'-':>{width}}"

    for row in report["rows"]:
        lines.append(
            f"{row['stage']:<16}"
            + num(row["measured_s"], ".5f", 12)
            + num(row["floor_s"], ".5f", 12)
            + num(row["gap_x"], ".1f", 9)
            + num(row["pct_of_iter"], ".1f", 8))
    cov = 100.0 * report["coverage"]
    lines += [
        f"{'sum of stages':<16}"
        f"{report['sum_stage_s_per_iter']:>12.5f}{'':>12}{'':>9}"
        f"{cov:>8.1f}",
        f"{'unattributed':<16}"
        f"{report['unattributed_s_per_iter']:>12.5f}{'':>12}{'':>9}"
        f"{100.0 - cov:>8.1f}",
        "",
        f"wall (attributed twin):  {report['wall_s_per_iter']:.5f} s/iter",
        f"roofline floor:          "
        f"{report['roofline_floor_s_per_iter']:.5f} s/iter",
    ]
    if report.get("fused_s_per_iter"):
        lines.append(
            f"production fused step:   {report['fused_s_per_iter']:.5f} "
            f"s/iter  (twin overhead "
            f"{report['attribution_overhead_x']:.2f}x; the fused step "
            "is the real speed, the twin is where the time goes)")
    return "\n".join(lines)
