"""Normal-equation traffic audit: pin the bytes the *traced build*
actually moves against the roofline model, straight from the jaxpr.

``perf.roofline`` carries two closed-form NE-build byte models
(``einsum_ne_build_bytes`` / ``fused_ne_kernel_bytes``).  This module
derives the auditable parts of both from the build functions' jaxprs —
the same validation style as ``parallel.comm_audit`` for collectives —
so the roofline's headline claim (the gather-fused kernel deletes the
``Vg`` round trip) is checked against what XLA is actually handed, not
against the model's own inputs:

- ``gather_out_bytes``: bytes written by ``gather`` equations (scaled by
  enclosing ``scan`` trip counts).  For the einsum path this is exactly
  the materialized ``Vg = V[cols]`` tensor, ``n·w·r·itemsize``; for the
  gather-fused path it must be **zero** — the factor rows stream through
  VMEM via in-kernel DMA and no HBM gather exists in the jaxpr.
- ``pallas_cost_bytes``: the ``bytes_accessed`` of every ``pallas_call``
  equation's embedded ``CostEstimate``.  The gather-fused kernel stamps
  its estimate from ``fused_ne_kernel_bytes`` at padded shapes, so a
  kernel/model divergence (e.g. a padding change that the model misses)
  fails a test instead of silently mis-reporting the roofline floor.

Elementwise traffic is deliberately NOT audited: XLA fuses it invisibly,
so the jaxpr carries no truth about it.  Gathers and kernel cost stamps
are discrete, unfusable facts — the strongest validation available
without an on-chip profiler trace.
"""

from __future__ import annotations

import numpy as np

import jax


def _aval_bytes(aval):
    return int(np.prod(aval.shape)) * np.dtype(aval.dtype).itemsize


def _walk(jaxpr, mult, visit):
    """Scan-scaled traversal shared by both counters.

    ``cond`` branches are rejected rather than guessed at (mirroring
    comm_audit's data-dependent-traffic rule); no NE builder uses one.
    ``pallas_call`` bodies are NOT descended into: everything inside the
    kernel touches VMEM refs (a body-level gather/cond moves no HBM), and
    the kernel's HBM traffic is exactly its cost stamp.
    """
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        visit(eqn, mult)
        if name == "pallas_call":
            continue
        if name == "scan":
            _walk(eqn.params["jaxpr"].jaxpr,
                  mult * int(eqn.params["length"]), visit)
        elif name == "cond":
            raise ValueError(
                "gather/pallas traffic inside cond is data-dependent "
                "and unauditable — no NE builder should branch")
        else:
            for p in ("jaxpr", "call_jaxpr"):
                inner = eqn.params.get(p) if eqn.params else None
                if inner is not None:
                    _walk(getattr(inner, "jaxpr", inner), mult, visit)


def gather_out_bytes(fn, *args):
    """Bytes written by every ``gather`` equation of one traced call.

    Returns ``(total_bytes, n_gathers)``.  The einsum NE path's row
    gather is its only large one, so at bucket shapes the total equals
    the materialized ``Vg`` exactly; small index-arithmetic gathers
    (none exist in the builders today) would show up in ``n_gathers``.
    """
    closed = jax.make_jaxpr(fn)(*args)
    total, count = 0, 0

    def visit(eqn, mult):
        nonlocal total, count
        if eqn.primitive.name == "gather":
            total += mult * sum(_aval_bytes(v.aval) for v in eqn.outvars)
            count += mult

    _walk(closed.jaxpr, 1, visit)
    return int(total), int(count)


def pallas_cost_bytes(fn, *args):
    """Sum of ``cost_estimate.bytes_accessed`` over every ``pallas_call``
    equation of one traced call, scan-scaled.

    Returns ``(total_bytes, n_calls)``.  Raises if a pallas_call carries
    no cost estimate — every kernel in this codebase that claims a
    roofline stage must stamp one, or the audit has nothing to pin.
    """
    closed = jax.make_jaxpr(fn)(*args)
    total, count = 0, 0

    def visit(eqn, mult):
        nonlocal total, count
        if eqn.primitive.name == "pallas_call":
            cost = eqn.params.get("cost_estimate")
            if cost is None or cost.bytes_accessed is None:
                raise ValueError(
                    f"pallas_call {eqn.params.get('name_and_src_info')} "
                    "has no bytes_accessed cost estimate to audit")
            total += mult * int(cost.bytes_accessed)
            count += mult

    _walk(closed.jaxpr, 1, visit)
    return int(total), int(count)
