"""Bench regression gate: judge the committed bench-series artifacts.

The repo banks one JSON artifact per sweep round (``BENCH_rNN.json``,
``MULTICHIP_rNN.json``) plus direct single-point banks
(``BENCH_serve_cpu.json``).  Nothing ever read them back — which is how
``BENCH_r05.json`` came to carry ``value: null`` after six silent probe
hangs.  This module is the reader: ``check()`` classifies every
artifact, reconstructs each series, and returns typed findings with a
typed exit code so sweeps and CI fail loudly instead of committing
nulls.

Exit codes (the max severity found wins):

- 0  OK — warnings at most (historical nulls, unparseable rounds)
- 1  REGRESSION — the latest effective value is worse than the best
     previous one beyond the noise band (direction from the unit:
     ``iters/sec`` up is good, ``ms``/``s`` down is good), the
     latest multichip round is failing, or (with ``trend=True``) the
     least-squares fit over the last ``trend_window`` rounds drifts in
     the worse direction beyond the band — the slow-slide case where
     every individual round passes but the series is sinking
- 2  NULL BANK — the LATEST round banked ``value: null`` with no
     same-round fallback, or a direct bank carries a null value
- 3  PROVENANCE — a direct bank is missing a timezone-aware
     ``banked_at`` stamp (the bench contract since PR 2)

Historical nulls are warnings, not errors: the series already happened
and the gate's job is to stop the NEXT null, not to make the committed
history unfixable (``--strict`` upgrades them).  A null round whose
wrapper carries a same-round ``last_builder_measured`` sweep fallback
(the PR 5 banking rule) counts as measured at that value.

Pure stdlib — ``scripts/bench_gate.sh`` and the ``observe regress`` CLI
run this without jax.
"""

from __future__ import annotations

import datetime
import glob
import json
import os
import re

EXIT_OK = 0
EXIT_REGRESSION = 1
EXIT_NULL_BANK = 2
EXIT_PROVENANCE = 3

# units where a larger number is a worse result
_LOWER_BETTER = ("ms", "s", "seconds", "sec", "s/iter", "seconds/iter")

_ROUND_RE = re.compile(r"^(?P<series>.+)_r(?P<n>\d+)\.json$")


def _finding(severity, code, where, message):
    return {"severity": severity, "code": code, "where": where,
            "message": message}


def _effective_value(payload):
    """The value a wrapper round actually measured: ``value``, else the
    same-round sweep fallback (``last_builder_measured.value``)."""
    if payload.get("value") is not None:
        return float(payload["value"]), "value"
    fb = payload.get("last_builder_measured") or {}
    if fb.get("value") is not None:
        return float(fb["value"]), "sweep_fallback"
    return None, None


def _tz_aware(stamp):
    try:
        dt = datetime.datetime.fromisoformat(
            str(stamp).replace("Z", "+00:00"))
    except ValueError:
        return False
    return dt.tzinfo is not None


def _trend_drift(window):
    """Least-squares slope over the series window, normalized to a
    fractional drift across it: ``slope * (npts - 1) / y-intercept``.
    A -0.04 means the fitted line loses 4% of its starting value over
    the window.  Fitting the LINE (not latest-vs-best) is the point:
    a single lucky latest round can sit inside the noise band of the
    best prior value while the fit still shows a sustained slide."""
    n = len(window)
    xbar = (n - 1) / 2.0
    ybar = sum(window) / n
    num = sum((i - xbar) * (y - ybar) for i, y in enumerate(window))
    den = sum((i - xbar) ** 2 for i in range(n))
    slope = num / den
    y0 = ybar - slope * xbar
    if y0 == 0:
        return 0.0
    return slope * (n - 1) / y0


def _check_trend(name, points, noise, trend_window, findings):
    """Direction-aware trend gate over the series tail.  Needs >= 3
    effective points (a 2-point 'trend' is just latest-vs-prior, which
    the plain gate already judges); drift toward the worse direction
    beyond the noise band is a REGRESSION even when the latest value
    alone survives the latest-vs-best check."""
    if len(points) < 3:
        return
    unit = points[-1][3] or ""
    lower_better = unit in _LOWER_BETTER
    window = [v for _, v, _, _ in points[-min(trend_window, len(points)):]]
    drift = _trend_drift(window)
    worse = drift > 0 if lower_better else drift < 0
    if worse and abs(drift) > noise:
        latest_n = points[-1][0]
        word = "rising" if lower_better else "falling"
        findings.append(_finding(
            "error", EXIT_REGRESSION, f"{name}_r{latest_n:02d}.json",
            f"series {name}: trend over the last {len(window)} rounds is "
            f"{word} {abs(drift):.1%} ({unit}), beyond the {noise:.0%} "
            "noise band — sustained drift even though the latest round "
            "alone may pass"))


def _check_bench_series(name, rounds, noise, strict, findings,
                        trend=False, trend_window=5):
    """``rounds``: sorted [(n, fname, doc)] of ``{n, rc, parsed}``
    wrappers.  Appends findings; returns nothing."""
    last_n = rounds[-1][0]
    points = []                     # (n, value, source, unit)
    for n, fname, doc in rounds:
        payload = doc.get("parsed")
        if payload is None:
            sev = "error" if strict else "warning"
            findings.append(_finding(
                sev, EXIT_NULL_BANK if strict else EXIT_OK, fname,
                f"round {n} banked no parseable bench payload "
                f"(rc={doc.get('rc')})"))
            continue
        value, source = _effective_value(payload)
        if value is None:
            latest = n == last_n
            sev = "error" if (latest or strict) else "warning"
            findings.append(_finding(
                sev, EXIT_NULL_BANK if sev == "error" else EXIT_OK, fname,
                f"round {n} banked value: null with no same-round "
                f"fallback ({payload.get('error') or 'no error recorded'})"
                + ("" if latest else " [historical]")))
            continue
        if source == "sweep_fallback":
            findings.append(_finding(
                "info", EXIT_OK, fname,
                f"round {n} value {value} recovered via "
                "last_builder_measured sweep fallback"))
        points.append((n, value, source, payload.get("unit")))

    if len(points) < 2:
        return
    unit = points[-1][3] or ""
    lower_better = unit in _LOWER_BETTER
    latest_n, latest, _, _ = points[-1]
    prior = [v for _, v, _, _ in points[:-1]]
    best = min(prior) if lower_better else max(prior)
    regressed = (latest > best * (1.0 + noise) if lower_better
                 else latest < best * (1.0 - noise))
    if regressed:
        direction = "above" if lower_better else "below"
        findings.append(_finding(
            "error", EXIT_REGRESSION, f"{name}_r{latest_n:02d}.json",
            f"series {name}: latest {latest} {unit} is {direction} the "
            f"best prior {best} {unit} beyond the {noise:.0%} noise band"))
    if trend:
        _check_trend(name, points, noise, trend_window, findings)


def _check_multichip_series(name, rounds, strict, findings):
    """Pass/fail rounds (``{n_devices, rc, ok, skipped}``): the latest
    must be passing; historical failures are warnings."""
    last_n = rounds[-1][0]
    for n, fname, doc in rounds:
        if doc.get("skipped"):
            continue
        if not doc.get("ok"):
            latest = n == last_n
            sev = "error" if (latest or strict) else "warning"
            findings.append(_finding(
                sev, EXIT_REGRESSION if sev == "error" else EXIT_OK, fname,
                f"round {n} multichip run failing (rc={doc.get('rc')})"
                + ("" if latest else " [historical]")))


def _check_direct_bank(fname, doc, findings):
    """Single-point bank (``{metric, value, unit, ..., banked_at}``)."""
    if doc.get("value") is None:
        findings.append(_finding(
            "error", EXIT_NULL_BANK, fname,
            f"direct bank {doc.get('metric')!r} carries value: null"))
    stamp = doc.get("banked_at")
    if stamp is None:
        findings.append(_finding(
            "error", EXIT_PROVENANCE, fname,
            f"direct bank {doc.get('metric')!r} is missing banked_at "
            "provenance"))
    elif not _tz_aware(stamp):
        findings.append(_finding(
            "error", EXIT_PROVENANCE, fname,
            f"direct bank {doc.get('metric')!r} banked_at={stamp!r} is "
            "not a timezone-aware ISO stamp"))


def check(root=".", noise=0.10, strict=False, files=None, trend=False,
          trend_window=5):
    """Gate every bench artifact under ``root`` (or the explicit
    ``files`` list).  Returns ``{"findings", "exit_code", "series",
    "checked"}`` — exit_code is the max error code found (0 when only
    warnings/info survive).  ``trend=True`` additionally fits the last
    ``trend_window`` effective points of each series and flags a
    sustained drift in the worse direction beyond the noise band — the
    gate that catches a slow decline the latest-vs-best check misses
    when each individual round stays inside the band (needs >= 3
    effective points; shorter series are plain-gated only)."""
    if files is None:
        files = sorted(glob.glob(os.path.join(root, "BENCH_*.json"))
                       + glob.glob(os.path.join(root, "MULTICHIP_*.json")))
    findings = []
    series = {}                     # name -> [(n, fname, doc)]
    checked = []
    for path in files:
        fname = os.path.basename(path)
        checked.append(fname)
        try:
            with open(path, encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            findings.append(_finding(
                "error", EXIT_NULL_BANK, fname,
                f"unreadable bench artifact: {e}"))
            continue
        m = _ROUND_RE.match(fname)
        if m and isinstance(doc, dict) and "rc" in doc:
            series.setdefault(m.group("series"), []).append(
                (int(m.group("n")), fname, doc))
        elif isinstance(doc, dict) and "metric" in doc and "value" in doc:
            _check_direct_bank(fname, doc, findings)
        else:
            findings.append(_finding(
                "warning", EXIT_OK, fname,
                "unrecognized bench artifact shape (neither a _rNN "
                "round wrapper nor a metric/value bank)"))

    for name, rounds in sorted(series.items()):
        rounds.sort()
        if any("parsed" in doc for _, _, doc in rounds):
            _check_bench_series(name, rounds, noise, strict, findings,
                                trend=trend, trend_window=trend_window)
        else:
            _check_multichip_series(name, rounds, strict, findings)

    exit_code = max(
        (f["code"] for f in findings if f["severity"] == "error"),
        default=EXIT_OK)
    return {
        "findings": findings,
        "exit_code": exit_code,
        "series": {name: [fname for _, fname, _ in rounds]
                   for name, rounds in sorted(series.items())},
        "checked": checked,
        "noise": float(noise),
        "strict": bool(strict),
        "trend": bool(trend),
        "trend_window": int(trend_window),
    }


def render(result):
    """Human-readable verdict for ``tpu_als observe regress``."""
    lines = [f"bench regression gate — {len(result['checked'])} "
             f"artifact(s), noise band {result['noise']:.0%}"
             + (" [strict]" if result["strict"] else "")
             + (f" [trend window {result['trend_window']}]"
                if result.get("trend") else "")]
    if not result["checked"]:
        lines.append("  (no BENCH_*/MULTICHIP_* artifacts found)")
    for f in result["findings"]:
        lines.append(f"  {f['severity'].upper():<8}{f['where']}: "
                     f"{f['message']}")
    if not result["findings"]:
        lines.append("  all clean")
    verdict = {EXIT_OK: "OK", EXIT_REGRESSION: "REGRESSION",
               EXIT_NULL_BANK: "NULL BANK",
               EXIT_PROVENANCE: "PROVENANCE"}[result["exit_code"]]
    lines.append(f"verdict: {verdict} (exit {result['exit_code']})")
    return "\n".join(lines)
