"""``tpu_als.obs`` — unified metrics/tracing for the whole stack.

Usage (the instrumented hot paths all go through the module-level
default registry, so library users get process-wide aggregation for
free):

    from tpu_als import obs

    with obs.span("train.fit"):
        ...
    obs.counter("ingest.rows", n)
    obs.histogram("serve.request_seconds", dt, strategy="ring")
    obs.gauge("train.comm_bytes_per_iter", b, strategy="ring")

    obs.configure(run_dir)      # start of a run (CLI does this)
    ...
    obs.finalize()              # drain events.jsonl / metrics.prom /
                                # run_manifest.json into run_dir

Everything is cheap in-memory bookkeeping until ``finalize``; a registry
that is never configured simply accumulates (bounded) in-memory state —
safe for library use and for the test suite.  See
docs/observability.md for the event schema and run-dir layout.
"""

from __future__ import annotations

from tpu_als.obs.metrics import BUCKET_BOUNDS, MetricsRegistry  # noqa: F401
from tpu_als.obs import schema  # noqa: F401

_default = MetricsRegistry()


def default_registry():
    return _default


def reset():
    """Replace the default registry with a fresh one (tests)."""
    global _default
    _default = MetricsRegistry()
    return _default


def counter(name, value=1, **labels):
    _default.counter(name, value, **labels)


def gauge(name, value, **labels):
    _default.gauge(name, value, **labels)


def histogram(name, value, **labels):
    _default.histogram(name, value, **labels)


def histogram_quantile(name, q, **labels):
    return _default.histogram_quantile(name, q, **labels)


def histogram_count(name, **labels):
    return _default.histogram_count(name, **labels)


def counter_value(name, **labels):
    return _default.counter_value(name, **labels)


def emit(etype, **fields):
    return _default.emit(etype, **fields)


def span(name, **labels):
    return _default.span(name, **labels)


def configure(run_dir, config=None, argv=None):
    _default.configure(run_dir, config=config, argv=argv)


def active():
    return _default.active()


def deconfigure():
    _default.deconfigure()


def update_manifest(**fields):
    _default.update_manifest(**fields)


def snapshot():
    return _default.snapshot()


def prometheus_text():
    return _default.prometheus_text()


def finalize():
    return _default.finalize()
