"""The declared observability vocabulary — every metric and event type.

The registry (tpu_als.obs.metrics) validates names against these tables at
call time, and ``scripts/check_obs_schema.py`` validates every *call site*
statically, so an undeclared name fails a tier-1 test instead of silently
minting a new time series nothing downstream knows how to read (the
Codahale-metrics discipline the reference stack gets from its fixed
MetricsSystem source names — SURVEY.md §5.5).

Adding a metric or event type = add a row here + a row in the matching
table of docs/observability.md.

Labels are vocabulary too: ``LABELS`` declares which label keys each
metric's writers may attach, and the registry rejects any other key at
call time — an ad-hoc label would mint a series dimension nothing
downstream (the Prometheus exposition, `observe summarize`, the bench
judges) knows how to aggregate.  The ``tenant`` label is the multi-
tenant attribution contract: every ``serving.*``/``live.*`` series
carries it (``TENANT_LABELED`` is derived, so adding a serving metric
without deciding its tenant story is impossible — the static check in
``analysis/vocab.py`` pins exactly that).
"""

from __future__ import annotations

# metric name -> (kind, unit, help text).  kind in {counter, gauge,
# histogram}; a name used with a different kind than declared raises.
METRICS = {
    "train.comm_bytes_per_iter": (
        "gauge", "bytes",
        "modeled per-device collective traffic of one ALS iteration "
        "(trainer.comm_bytes_per_iter, labeled by effective strategy)"),
    "train.gather_block_rows": (
        "gauge", "rows",
        "rows per column block of the chunked all_gather schedule "
        "(comm.gather_block_plan; bounds the resident gathered slice)"),
    "serve.request_seconds": (
        "histogram", "seconds",
        "wall-clock latency of one sharded top-k request "
        "(parallel.serve.topk_sharded), labeled by strategy"),
    "serve.requests": (
        "counter", "requests", "sharded top-k requests served"),
    "serve.rows": (
        "counter", "rows", "query rows scored by sharded top-k"),
    "ingest.rows": (
        "counter", "rows", "rating rows parsed by stream_ingest"),
    "ingest.bytes": (
        "counter", "bytes", "file bytes read by stream_ingest"),
    "ingest.stall_seconds": (
        "counter", "seconds",
        "time stream_ingest spent blocked in file reads (I/O stall, "
        "as opposed to parse/intern time)"),
    "foldin.update_seconds": (
        "histogram", "seconds",
        "FoldInServer micro-batch latency, labeled side=user|item"),
    "foldin.ratings": (
        "counter", "rows", "ratings folded in by FoldInServer"),
    "checkpoint.save_seconds": (
        "histogram", "seconds", "save_factors wall-clock duration"),
    "checkpoint.save_bytes": (
        "counter", "bytes", "bytes written by save_factors"),
    "checkpoint.load_seconds": (
        "histogram", "seconds", "load_factors wall-clock duration"),
    "checkpoint.load_bytes": (
        "counter", "bytes", "bytes read by load_factors"),
    "serve.degraded": (
        "counter", "requests",
        "top-k requests answered from last-good factors because the "
        "sharded gather failed (parallel.serve degraded mode)"),
    "serving.enqueue_seconds": (
        "histogram", "seconds",
        "time a request waited in the admission queue "
        "(serving.batcher: enqueue -> dequeue)"),
    "serving.score_seconds": (
        "histogram", "seconds",
        "device scoring time per serving micro-batch, labeled "
        "path=int8|exact"),
    "serving.e2e_seconds": (
        "histogram", "seconds",
        "end-to-end serving request latency (submit -> completion)"),
    "serving.batch_rows": (
        "histogram", "rows",
        "real (unpadded) requests per dequeued serving micro-batch — "
        "shows bucket fill under the offered load"),
    "serving.queue_depth": (
        "gauge", "requests",
        "admission-queue backlog sampled after each batch dequeue"),
    "serving.requests": (
        "counter", "requests", "requests admitted by the serving engine"),
    "serving.shed": (
        "counter", "requests",
        "requests refused at admission (queue at capacity; the typed "
        "Overloaded the caller sees)"),
    "serving.expired": (
        "counter", "requests",
        "requests whose deadline passed while queued (failed with "
        "DeadlineExceeded instead of being scored)"),
    "serving.fallback_exact": (
        "counter", "requests",
        "requests scored on the exact path because the int8 index was "
        "stale (publish without requantize, or injected staleness)"),
    "serving.publishes": (
        "counter", "publishes",
        "model generations atomically swapped into the serving engine"),
    "scenario.freshness_seconds": (
        "histogram", "seconds",
        "cold-start scenario: rating-arrival -> servable latency (fold-"
        "in + republish + first successful recommend for a NEW user)"),
    "train.rollbacks": (
        "counter", "rollbacks",
        "guardrail rollbacks: iterations retried from the last-good "
        "factor snapshot after a sentinel trip (resilience.guardrails, "
        "recover mode)"),
    "ingest.quarantined_rows": (
        "counter", "rows",
        "rating records routed to the quarantine sink by stream_ingest "
        "or the estimator's input scrub (malformed, non-finite, or "
        "out-of-range) instead of aborting the ingest"),
    "serving.publish_seconds": (
        "histogram", "seconds",
        "wall-clock cost of one model publish, labeled "
        "mode=full|retag|delta|compact|none — the O(touched)-vs-"
        "O(catalog) incremental-publish claim is measured here"),
    "live.freshness_seconds": (
        "histogram", "seconds",
        "rating-arrival -> servable: from the event entering the live "
        "updater's admission queue to its fold-in's publish seq being "
        "visible to the score path (tpu_als.live.updater)"),
    "live.batch_rows": (
        "histogram", "rows",
        "rating events per live-updater micro-batch (accumulation "
        "bounded by the planner's max_batch/max_wait_ms cadence)"),
    "live.shed": (
        "counter", "events",
        "rating events refused at the live updater's admission queue "
        "(queue at capacity; the typed Overloaded the producer sees)"),
    "live.queue_depth": (
        "gauge", "events",
        "live-updater admission backlog sampled after each micro-batch "
        "dequeue"),
    "foldin.batch_rows": (
        "histogram", "rows",
        "entities solved per FoldInServer micro-batch (the padded "
        "bucket is the next pow2 above this)"),
    "train.stage_seconds": (
        "histogram", "seconds",
        "fence-timed seconds of one attributed ALS stage (obs.trace."
        "stage), labeled stage=<perf.roofline stage name> so "
        "`observe attribution` can join measured time against the "
        "modeled floor"),
    "tenancy.tenants": (
        "gauge", "tenants",
        "models currently registered with the multi-tenant control "
        "plane (tpu_als.tenancy.registry)"),
    "tenancy.served_rows": (
        "counter", "rows",
        "requests completed per tenant by the fair-share scheduler "
        "(labeled tenant=<name>; the goodput series the fairness "
        "ratio is computed from)"),
    "tenancy.batch_errors": (
        "counter", "batches",
        "micro-batches whose scoring raised, failed in isolation "
        "(labeled tenant=<name>: the failing tenant's tickets erred, "
        "every other tenant kept serving)"),
    "train.reformations": (
        "counter", "reformations",
        "elastic mesh reformations: a mid-fit device loss was detected, "
        "the ring re-formed on the surviving mesh and training resumed "
        "from the last atomic checkpoint (resilience.elastic)"),
    "soak.windows": (
        "counter", "windows",
        "soak windows completed by the production-week orchestrator "
        "(tpu_als.soak.orchestrator)"),
    "soak.injections": (
        "counter", "injections",
        "chaos injections whose fault observably fired during a soak "
        "(the soak_injection event carries the evidence)"),
    "soak.recoveries": (
        "counter", "recoveries",
        "chaos injections that fired AND left recovery evidence in the "
        "trail before their window closed"),
    "soak.window_seconds": (
        "histogram", "seconds",
        "wall-clock duration of one soak window (traffic replay + "
        "chaos actions + joins; the schedule's window_s is the floor)"),
}

# metric name -> label keys its writers may attach.  Any key outside
# this row raises at call time (metrics.MetricsRegistry) and fails the
# static check (analysis/vocab.py) — labels are declared vocabulary,
# not free-form tags.  Metrics absent from this table take no labels.
LABELS = {
    "train.comm_bytes_per_iter": ("strategy",),
    "train.gather_block_rows": ("n_blocks", "side"),
    "train.stage_seconds": ("stage",),
    "serve.request_seconds": ("strategy",),
    "foldin.update_seconds": ("side",),
    "foldin.batch_rows": ("side",),
    "serving.enqueue_seconds": ("tenant",),
    "serving.score_seconds": ("path", "tenant"),
    "serving.e2e_seconds": ("tenant",),
    "serving.batch_rows": ("tenant",),
    "serving.queue_depth": ("tenant",),
    "serving.requests": ("tenant",),
    "serving.shed": ("tenant",),
    "serving.expired": ("tenant",),
    "serving.fallback_exact": ("tenant",),
    "serving.publishes": ("tenant",),
    "serving.publish_seconds": ("mode", "tenant"),
    "live.freshness_seconds": ("tenant",),
    "live.batch_rows": ("tenant",),
    "live.shed": ("tenant",),
    "live.queue_depth": ("tenant",),
    "tenancy.served_rows": ("tenant",),
    "tenancy.batch_errors": ("tenant",),
}

# every metric allowed to carry the multi-tenant attribution label —
# derived from LABELS so it can never drift from the table above; the
# analysis gate additionally pins that every serving.*/live.* metric
# appears here (a new serving series without a tenant story is a lint
# failure, the same way serving.publish_seconds' mode label is pinned)
TENANT_LABELED = tuple(sorted(
    n for n, keys in LABELS.items() if "tenant" in keys))

# -- causal-trace vocabulary (tpu_als/obs/tracing.py) ------------------------
#
# Every hop a request or rating event takes is one named span; the name
# is vocabulary exactly like a metric name — ``tracing.record_span`` and
# ``tracing.start_trace`` validate against this table at call time, and
# ``analysis/vocab.py`` validates every call-site literal statically.
# ``tpu_als observe explain`` renders the tree these spans encode.
TRACE_SPANS = (
    "serve.admit",        # request admitted at the serving front door
    "serve.queue",        # waited in the MicroBatcher admission queue
    "tenancy.round",      # drained by one fair-share scheduler round
    "serve.score",        # scored on device (path=int8|exact)
    "serve.expired",      # deadline passed while queued
    "live.admit",         # rating event admitted by the live updater
    "live.queue",         # waited in the live admission queue
    "live.quarantine",    # poisoned event dropped before the factors
    "live.foldin",        # folded into the touched factor rows
    "live.publish",       # rode an incremental publish_update
    "live.visible",       # its publish seq became score-path visible
    "elastic.detect",     # a failed step was classified (probe verdict)
    "elastic.reform",     # the mesh was rebuilt on the survivors
    "elastic.resume",     # training re-entered from the checkpoint
)

# per-span outcome vocabulary; "ok" is the happy path, everything else
# names the typed refusal/failure the span ended in (sheds and breaches
# are traced, never dropped)
TRACE_STATUSES = ("ok", "shed", "expired", "failed", "quarantined")

# the flight recorder's per-record span-key breakdowns (source of truth
# here, stdlib-only, so analysis/vocab.py can assert — jax-free — that
# they never collide with the record's structural fields or labels)
SERVE_SPAN_KEYS = ("admission", "queue_wait", "score", "rescore",
                   "respond")
LIVE_SPAN_KEYS = ("queue_wait", "quarantine", "foldin", "publish")

# field names every flight record (and its flight_record event) claims
# structurally — span keys and label keys must stay disjoint from these
FLIGHT_RESERVED = ("seq", "status", "spans", "e2e_seconds", "path",
                   "trigger", "ts", "type")

# event type -> (required fields beyond ts/type, help text).  Extra
# fields are allowed (events are self-describing JSON); missing required
# fields raise at emit time.
EVENTS = {
    "command": (
        ("cmd", "argv"),
        "one per CLI invocation: the subcommand and its argv"),
    "span": (
        ("name", "path", "seconds"),
        "one per closed span(): wall-clock duration; path is the "
        "'/'-joined stack of enclosing span names (the tree structure)"),
    "metric": (
        ("kind", "name", "value"),
        "a gauge set (gauges are point-in-time, so each set is an "
        "event; counters/histograms appear only in the final snapshot)"),
    "iteration": (
        ("iteration", "seconds", "total_seconds"),
        "one per training iteration observed by the CLI's "
        "IterationLogger (factor norms, optional probe_rmse)"),
    "ingest": (
        ("path", "rows", "bytes", "seconds", "stall_seconds"),
        "one per stream_ingest call: this host's parsed totals"),
    "checkpoint_save": (
        ("path", "seconds", "bytes"),
        "one per save_factors call"),
    "checkpoint_load": (
        ("path", "seconds", "bytes"),
        "one per load_factors call"),
    "bench_retry": (
        ("attempt", "attempts", "elapsed_seconds", "reason"),
        "one per failed bench.py backend probe attempt"),
    "retry_attempt": (
        ("what", "attempt", "attempts", "elapsed_seconds", "reason"),
        "one per failed attempt inside resilience.retry.retry_call "
        "(the call will be retried)"),
    "retry_exhausted": (
        ("what", "attempts", "reason"),
        "retry_call gave up: every attempt in the budget failed"),
    "fault_injected": (
        ("point", "mode", "hit"),
        "a resilience.faults fault point fired (chaos testing only; "
        "never emitted when TPU_ALS_FAULT_SPEC is unset)"),
    "serving_publish": (
        ("seq", "items", "quantized"),
        "one per ServingEngine.publish: the generation sequence number, "
        "catalog size, and whether an int8 index was built for it"),
    "serving_backend": (
        ("backend", "n_shards"),
        "one per ServingEngine, at first publish: the scoring backend "
        "the engine resolved (local / sharded / merge_ring), after the "
        "live-mesh probe for the in-kernel merge"),
    "serve_degraded": (
        ("strategy", "reason"),
        "a sharded top-k request fell back to last-good gathered "
        "factors after a gather failure"),
    "preempted": (
        ("iteration", "signum"),
        "training stopped at an iteration boundary after SIGTERM/"
        "SIGINT; a resumable checkpoint was written if a checkpoint "
        "dir is configured"),
    "checkpoint_quarantined": (
        ("path", "reason"),
        "load_factors moved a corrupt checkpoint generation aside to "
        ".corrupt/ (and fell back to .old when present)"),
    "guardrail_tripped": (
        ("iteration", "sentinel", "mode"),
        "a numerical-health sentinel fired at a training iteration "
        "boundary (resilience.guardrails; sentinel is one of "
        "nonfinite|norm_band|trend)"),
    "train_rollback": (
        ("iteration", "attempt", "sentinel", "reg_param"),
        "recover-mode guardrails restored the last-good factor "
        "snapshot (seeded perturbation + regularization bump) and are "
        "retrying the iteration"),
    "ingest_quarantined": (
        ("path", "rows", "reasons"),
        "one per ingest call that quarantined records: total rows "
        "routed to the sink and the per-reason breakdown "
        "(malformed/nonfinite/out_of_range); mirrors checkpoint's "
        ".corrupt/ convention"),
    "warning": (
        ("what", "reason"),
        "a degraded-but-continuing condition (e.g. profiler trace "
        "skipped because one is already active)"),
    "snapshot": (
        ("counters", "gauges", "histograms"),
        "final registry state, appended once by finalize() so the JSONL "
        "alone reconstructs every counter/gauge/histogram"),
    "scenario_start": (
        ("scenario", "phases"),
        "a scenario run began: its name, phase list, and effective "
        "config (tpu_als.scenario.runner)"),
    "scenario_phase": (
        ("scenario", "phase", "seconds"),
        "one scenario phase completed, with its wall-clock seconds"),
    "scenario_assert": (
        ("scenario", "check", "ok", "observed", "expected"),
        "one scenario assertion judged: observed value vs bound (the "
        "verdict is re-derivable from these events alone)"),
    "scenario_end": (
        ("scenario", "passed", "seconds"),
        "a scenario run finished (or aborted on a phase failure, with "
        "an extra 'error' field): the verdict and total seconds"),
    "bench_probe_exhausted": (
        ("attempts", "elapsed_seconds", "reason"),
        "bench.py gave up on the backend probe: every attempt in the "
        "retry/budget policy failed (the terminal record after the "
        "per-attempt bench_retry trail)"),
    "flight_record": (
        ("seq", "trigger", "status", "spans"),
        "one per-request trace dumped by the serving flight recorder "
        "on an SLO breach, shed, or degraded-mode answer: spans is the "
        "admission/queue_wait/score/rescore/respond breakdown in "
        "seconds (serving.engine.FlightRecorder)"),
    "attribution": (
        ("stages", "wall_s_per_iter", "coverage"),
        "one per `observe attribution` run: measured per-stage seconds "
        "joined against the roofline floor (the planner's measured-"
        "probe input format)"),
    "plan_resolved": (
        ("key", "component", "source", "resolved"),
        "the execution planner settled a plan component (solve path / "
        "top-k backend / gather strategy / serving buckets): the plan "
        "key, whether the verdict came from 'cache' or a fresh 'probe' "
        "walk, and the resolved value (tpu_als.plan.planner)"),
    "plan_probe": (
        ("kernel", "outcome", "seconds"),
        "one probe consultation spent by a COLD plan resolve (the "
        "per-kernel verdicts newly cached during the walk, plus one "
        "'walk:<component>' record for the walk itself); a warm-cache "
        "resolve emits none — the warm-start tests pin exactly that"),
    "plan_cache_hit": (
        ("key", "component", "path", "seeded"),
        "a plan component resolved from the persistent autotune cache: "
        "entry path and how many banked probe verdicts were seeded "
        "into the in-process registry (zero probe executions)"),
    "live_update": (
        ("seq", "events", "touched", "mode"),
        "one per live-updater micro-batch published: the resulting "
        "publish seq, rating events folded, catalog rows touched, and "
        "the publish mode (retag|delta|compact|full|none) "
        "(tpu_als.live.updater)"),
    "live_freshness_breach": (
        ("seq", "freshness_seconds", "slo_s"),
        "a live update's arrival->servable freshness exceeded the SLO; "
        "the updater's flight-recorder tail (queue_wait/quarantine/"
        "foldin/publish spans) is dumped alongside with "
        "trigger='freshness_breach'"),
    "tenant_registered": (
        ("tenant", "users", "items", "shape_class"),
        "one per TenantRegistry.register: the tenant's published table "
        "sizes and its planner shape-class (tenants sharing a "
        "shape-class share the plan-cache entry and, with equal "
        "rank/buckets, the compiled scoring executables)"),
    "tenant_removed": (
        ("tenant",),
        "a tenant was deregistered from the control plane; its engine "
        "was stopped and its device buffers released"),
    "trace_span": (
        ("trace_id", "span_id", "parent_id", "name", "status",
         "seconds"),
        "one causal-trace hop (tpu_als.obs.tracing): deterministic "
        "trace/span/parent ids link admission -> queue -> scheduler "
        "round -> score -> publish -> visible across serve/live/"
        "tenancy; `tpu_als observe explain` rebuilds the tree from "
        "these events alone (name in TRACE_SPANS, status in "
        "TRACE_STATUSES; seconds may be null for instantaneous hops)"),
    "device_lost": (
        ("iteration", "lost", "surviving"),
        "the elastic detector classified a failed collective/ring step "
        "as device loss: the health probe (bounded retry backoff) "
        "exhausted on the named logical device ids; 'surviving' is how "
        "many devices stay in the mesh (resilience.elastic)"),
    "mesh_reformed": (
        ("old_devices", "new_devices", "lost"),
        "the mesh was rebuilt from the surviving logical device ids and "
        "the shard plan / bucket schedule re-derived through the "
        "planner for the new device count (api.fitting elastic "
        "recovery)"),
    "elastic_resume": (
        ("iteration", "source", "devices"),
        "training re-entered the (shrunk) ring at an iteration "
        "boundary: from the last atomic checkpoint ('checkpoint', with "
        "its path in an extra field) or from the seed-deterministic "
        "init ('scratch' — the quarantined epoch is re-run in full)"),
    "plan_cache_miss": (
        ("key", "component", "reason"),
        "a plan component was not servable from the cache (reason: "
        "absent|component_absent|corrupt) — a probe walk follows and "
        "its verdict is banked; 'corrupt' means the entry file was "
        "quarantined to .corrupt/ first"),
    "plan_tuned": (
        ("key", "component", "source", "config", "measured_seconds",
         "model_seconds"),
        "the measured-timing autotuner banked a kernel config into the "
        "plan entry: the winning knobs (panel/vmem_budget/max_wc/depth/"
        "dtype), the min-of-k measured seconds next to the roofline "
        "closed-form prediction, and whether the timings came from the "
        "'device' or the CPU 'interpret' path — interpret verdicts "
        "never override an on-chip one (tpu_als.plan.planner)"),
    "tune_trial": (
        ("kernel", "config", "seconds"),
        "one autotune search trial: the kernel timed, the candidate "
        "config, and its min-of-k seconds (tpu_als.perf.autotune); a "
        "warm kernel-config resolve emits none — autotune_smoke pins "
        "exactly that"),
    "soak_start": (
        ("windows", "window_s", "tenants", "seed"),
        "a production-week soak began: the compressed timeline "
        "(windows x window_s seconds), the tenant mix, and the traffic "
        "seed; 'scheduled_injections' (extra field) is the chaos "
        "schedule's size — the verdict's injections_observed check "
        "compares against it (tpu_als.soak.orchestrator)"),
    "soak_window": (
        ("window", "offered", "answered", "shed", "errors"),
        "one soak window's serve outcome totals plus a 'tenants' extra "
        "field mapping tenant -> {offered, answered, shed, errors, "
        "p99_ms} — the verdict judges victim-free tenants from these "
        "per-window records alone"),
    "soak_injection": (
        ("window", "action", "fired", "recovered"),
        "one scheduled chaos injection's outcome: the window it landed "
        "in, the action performed, whether the fault observably fired, "
        "and whether its recovery evidence made it into the trail "
        "before the window closed; 'victim' and 'spec' ride as extra "
        "fields"),
    "soak_verdict": (
        ("passed", "survived_minutes", "checks"),
        "the soak's SLO verdict as judged from the trail (tpu_als/soak/"
        "verdict.py — stdlib-only, so the same verdict re-derives "
        "offline from events.jsonl alone)"),
}


def check_metric(name, kind):
    """Raise if ``name`` is undeclared or declared with another kind."""
    decl = METRICS.get(name)
    if decl is None:
        raise KeyError(
            f"metric {name!r} is not declared in tpu_als.obs.schema."
            "METRICS — declare it there (and in docs/observability.md) "
            "before emitting it")
    if decl[0] != kind:
        raise TypeError(
            f"metric {name!r} is declared as a {decl[0]}, used as a "
            f"{kind}")


def check_labels(name, labels):
    """Raise if a write attaches a label key ``name``'s LABELS row does
    not declare (no row = no labels).  Values are free; KEYS are the
    vocabulary — each declared key is one series dimension downstream
    readers aggregate over."""
    if not labels:
        return
    allowed = LABELS.get(name, ())
    unknown = sorted(k for k in labels if k not in allowed)
    if unknown:
        raise ValueError(
            f"metric {name!r} does not declare label key(s) {unknown} "
            f"(declared: {list(allowed)}) — add them to "
            "tpu_als.obs.schema.LABELS before writing the series")


def check_trace_span(name, status="ok"):
    """Raise if a causal-trace span names an undeclared hop or ends in
    an undeclared status — span names are vocabulary exactly like
    metric names (``observe explain`` renders only declared hops)."""
    if name not in TRACE_SPANS:
        raise KeyError(
            f"trace span {name!r} is not declared in tpu_als.obs."
            "schema.TRACE_SPANS — declare it there (and in "
            "docs/observability.md) before recording it")
    if status not in TRACE_STATUSES:
        raise ValueError(
            f"trace span {name!r} carries undeclared status {status!r} "
            f"(declared: {list(TRACE_STATUSES)})")


def check_event(etype, fields):
    """Raise if ``etype`` is undeclared or missing a required field."""
    decl = EVENTS.get(etype)
    if decl is None:
        raise KeyError(
            f"event type {etype!r} is not declared in tpu_als.obs."
            "schema.EVENTS — declare it there (and in "
            "docs/observability.md) before emitting it")
    missing = [f for f in decl[0] if f not in fields]
    if missing:
        raise ValueError(
            f"event {etype!r} is missing required field(s) {missing} "
            f"(declared: {list(decl[0])})")
