"""Run manifest: what produced this run dir — config, versions, git.

The reference stack records this in the Spark event log's
``SparkListenerEnvironmentUpdate`` / application properties; here it is
one JSON file next to the metrics, captured at ``obs.configure`` time
(cheap fields only) and completed at finalize (device info, which may
not exist until a backend initializes — probing it early could hang a
run on a flaky TPU tunnel, the exact failure bench.py guards against).
"""

from __future__ import annotations

import os
import platform
import subprocess
import sys
import time


def _git_describe():
    """``git describe --always --dirty`` of the source tree, or None —
    never raises (a deployed wheel has no .git)."""
    try:
        p = subprocess.run(
            ["git", "describe", "--always", "--dirty", "--tags"],
            cwd=os.path.dirname(os.path.abspath(__file__)),
            capture_output=True, text=True, timeout=5)
        if p.returncode == 0:
            return p.stdout.strip()
    except Exception:
        pass
    return None


def build_manifest(config=None, argv=None):
    import numpy as np

    import tpu_als

    man = {
        "started_at": round(time.time(), 6),
        "argv": list(argv) if argv is not None else sys.argv[1:],
        "config": dict(config or {}),
        "tpu_als_version": tpu_als.__version__,
        "python": sys.version.split()[0],
        "platform": platform.platform(),
        "numpy": np.__version__,
        "git": _git_describe(),
        "pid": os.getpid(),
    }
    jax = sys.modules.get("jax")
    if jax is not None:
        man["jax"] = getattr(jax, "__version__", None)
    return man


def late_device_info():
    """Device/mesh facts gathered at FINALIZE time, when the backend has
    already initialized (or never will): jax.devices() here cannot add a
    hang the run didn't already have."""
    info = {}
    jax = sys.modules.get("jax")
    if jax is None:
        return info
    info["jax"] = getattr(jax, "__version__", None)
    try:
        devs = jax.devices()
        info["device_count"] = len(devs)
        info["device_kind"] = devs[0].device_kind if devs else None
        info["process_count"] = jax.process_count()
    except Exception:
        pass
    return info
