"""Process-wide metrics registry + span tracing + JSONL/Prometheus sinks.

The TPU-native analog of the reference stack's SparkListener event bus +
Codahale MetricsSystem (SURVEY.md §5.1/§5.5): one in-process registry that
the instrumented hot paths (trainer, serve, ingest, checkpoint) write to
with plain dict/lock operations — no I/O, no jax imports — and that a run
drains to disk exactly once, at finalize:

- ``events.jsonl``   — append-only event log (spans, gauge sets, iteration
  records, a final ``snapshot`` of every counter/gauge/histogram),
- ``metrics.prom``   — Prometheus text exposition of the same registry,
- ``run_manifest.json`` — config / mesh / versions / git (obs.manifest).

Histograms use FIXED log-scale buckets (4 per decade, 1e-6..1e6 seconds
or bytes) so two runs' exposition files are always mergeable — the
Prometheus ``le`` contract.

``span(name)`` records wall-clock tree-structured spans (a thread-local
stack gives each event its ``path``) and applies ``jax.named_scope``
when jax is already imported, so host spans and device-trace scopes
share names (docs/observability.md's Perfetto walkthrough relies on
this).  Metric/event NAMES are validated against tpu_als.obs.schema at
call time; ``scripts/check_obs_schema.py`` validates call sites
statically.
"""

from __future__ import annotations

import bisect
import contextlib
import json
import os
import sys
import threading
import time

from tpu_als.obs import schema

# 4 buckets per decade over 1e-6 .. 1e6 (49 upper bounds; the 50th
# bucket is +Inf).  Fixed — never derived from data — so exposition
# files from different runs share the same `le` grid.
BUCKET_BOUNDS = tuple(10.0 ** (e / 4.0) for e in range(-24, 25))

# in-memory event cap: a registry that is never finalized (library use,
# the test suite) must not grow without bound; finalize() reports drops
_MAX_EVENTS = 100_000

# events.jsonl rotation bound (bytes).  When an incremental finalize
# would grow the file past this, the current file is renamed to the
# next events.NNN.jsonl and a fresh events.jsonl starts — long-horizon
# soaks finalize per chaos window, so one trail never grows unbounded.
# Env-overridable; 0 disables rotation.
ROTATE_ENV = "TPU_ALS_OBS_ROTATE_BYTES"
_ROTATE_BYTES = 8 << 20


def _rotate_bound():
    raw = os.environ.get(ROTATE_ENV)
    if raw is None:
        return _ROTATE_BYTES
    try:
        return max(0, int(raw))
    except ValueError:
        return _ROTATE_BYTES


def maybe_rotate(run_dir, bound=None):
    """Rotate ``<run_dir>/events.jsonl`` to ``events.NNN.jsonl`` when it
    has reached ``bound`` bytes.  Returns the rotated-to path or None.
    Readers (report/explain/verdict) list ``events.*.jsonl`` sorted and
    read them before the live file, so rotation is transparent."""
    if bound is None:
        bound = _rotate_bound()
    if not bound:
        return None
    live = os.path.join(run_dir, "events.jsonl")
    try:
        if os.path.getsize(live) < bound:
            return None
    except OSError:
        return None
    n = 0
    while True:
        cand = os.path.join(run_dir, f"events.{n:03d}.jsonl")
        if not os.path.exists(cand):
            break
        n += 1
    os.replace(live, cand)
    return cand


def _labels_key(labels):
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


def _render_labels(lkey):
    if not lkey:
        return ""
    return "{" + ",".join(f'{k}="{v}"' for k, v in lkey) + "}"


def _prom_name(name):
    return "tpu_als_" + name.replace(".", "_")


def _fmt(v):
    return f"{v:.10g}"


class _Hist:
    __slots__ = ("counts", "sum", "count", "min", "max")

    def __init__(self):
        self.counts = [0] * (len(BUCKET_BOUNDS) + 1)
        self.sum = 0.0
        self.count = 0
        self.min = float("inf")
        self.max = float("-inf")

    def observe(self, v):
        self.counts[bisect.bisect_left(BUCKET_BOUNDS, v)] += 1
        self.sum += v
        self.count += 1
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v

    def quantile(self, q):
        """Upper bucket bound at quantile ``q`` (0..1) — the standard
        bucketed estimate; the overflow bucket reports the observed max."""
        if self.count == 0:
            return float("nan")
        target = q * self.count
        acc = 0
        for i, c in enumerate(self.counts):
            acc += c
            # acc > 0 guards q=0: target is 0 there, and an empty prefix
            # must not report the first bucket's bound as the minimum
            if acc >= target and acc > 0:
                if i < len(BUCKET_BOUNDS):
                    return BUCKET_BOUNDS[i]
                return self.max
        return self.max

    def state(self):
        return {"count": self.count, "sum": self.sum,
                "min": self.min if self.count else None,
                "max": self.max if self.count else None,
                "p50": self.quantile(0.5) if self.count else None,
                "p95": self.quantile(0.95) if self.count else None}


class MetricsRegistry:
    """Counters + gauges + histograms + events + spans, one lock.

    Hot-path calls (counter/gauge/histogram/emit/span) do dict writes
    only; nothing touches the filesystem until :meth:`finalize`.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._counters = {}     # (name, labels_key) -> float
        self._gauges = {}       # (name, labels_key) -> float
        self._hists = {}        # (name, labels_key) -> _Hist
        self._events = []
        self._dropped = 0
        self._flushed = 0       # events already written to disk
        self._run_dir = None
        self._manifest = None
        self._local = threading.local()

    # -- instruments ---------------------------------------------------
    def counter(self, name, value=1, **labels):
        schema.check_metric(name, "counter")
        schema.check_labels(name, labels)
        key = (name, _labels_key(labels))
        with self._lock:
            self._counters[key] = self._counters.get(key, 0) + value

    def gauge(self, name, value, **labels):
        schema.check_metric(name, "gauge")
        schema.check_labels(name, labels)
        key = (name, _labels_key(labels))
        with self._lock:
            self._gauges[key] = value
        # gauges are point-in-time: each set is also an event, so the
        # JSONL alone carries the history (summarize reads these)
        self.emit("metric", kind="gauge", name=name, value=value,
                  labels=dict(labels))

    def histogram(self, name, value, **labels):
        schema.check_metric(name, "histogram")
        schema.check_labels(name, labels)
        key = (name, _labels_key(labels))
        with self._lock:
            h = self._hists.get(key)
            if h is None:
                h = self._hists[key] = _Hist()
            h.observe(float(value))

    def histogram_quantile(self, name, q, **labels):
        """Bucketed quantile estimate of a recorded histogram series
        (exact label match; NaN when the series has no observations).
        What ``serve-bench`` reads its p50/p99 from."""
        key = (name, _labels_key(labels))
        with self._lock:
            h = self._hists.get(key)
        return h.quantile(q) if h is not None else float("nan")

    def histogram_count(self, name, **labels):
        key = (name, _labels_key(labels))
        with self._lock:
            h = self._hists.get(key)
        return h.count if h is not None else 0

    def counter_value(self, name, **labels):
        key = (name, _labels_key(labels))
        with self._lock:
            return self._counters.get(key, 0)

    def emit(self, etype, **fields):
        """Append one event; returns the event dict (with its ts)."""
        schema.check_event(etype, fields)
        ev = {"ts": round(time.time(), 6), "type": etype, **fields}
        with self._lock:
            if len(self._events) >= _MAX_EVENTS:
                self._dropped += 1
            else:
                self._events.append(ev)
        return ev

    # -- span tracing --------------------------------------------------
    @contextlib.contextmanager
    def span(self, name, **labels):
        """Record a wall-clock span; nest for tree structure (the event's
        ``path`` is the '/'-joined stack).  Applies ``jax.named_scope``
        when jax is already imported so the device trace shares the name
        — but never imports jax itself (obs must stay importable in
        processes that keep jax out, e.g. bench.py's probe)."""
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        stack.append(name)
        path = "/".join(stack)
        scope = contextlib.nullcontext()
        jax = sys.modules.get("jax")
        if jax is not None:
            try:
                scope = jax.named_scope(name)
            except Exception:
                pass
        t0 = time.perf_counter()
        try:
            with scope:
                yield
        finally:
            dt = time.perf_counter() - t0
            stack.pop()
            self.emit("span", name=name, path=path,
                      seconds=round(dt, 6), **labels)

    # -- run lifecycle -------------------------------------------------
    def configure(self, run_dir, config=None, argv=None):
        """Point the registry at a run directory and capture the start-of-
        run manifest.  No files are written until :meth:`finalize` — the
        CLI's ``--output`` is atomically REPLACED by the model save
        (io.checkpoint.atomic_install), so anything written into it
        before that would be destroyed."""
        from tpu_als.obs.manifest import build_manifest

        with self._lock:
            self._run_dir = run_dir
            self._manifest = build_manifest(config=config, argv=argv)

    def active(self):
        return self._run_dir is not None

    def deconfigure(self):
        """Detach the run directory (accumulated state stays).  The CLI
        calls this after finalize so one process issuing several
        commands (the test suite, notebooks) never writes a later
        command's events into an earlier command's run dir."""
        with self._lock:
            self._run_dir = None
            self._manifest = None

    def update_manifest(self, **fields):
        with self._lock:
            if self._manifest is not None:
                self._manifest.update(fields)

    def snapshot(self):
        """Registry state as plain JSON-ready dicts."""
        with self._lock:
            return {
                "counters": {n + _render_labels(lk): v
                             for (n, lk), v in sorted(self._counters.items())},
                "gauges": {n + _render_labels(lk): v
                           for (n, lk), v in sorted(self._gauges.items())},
                "histograms": {n + _render_labels(lk): h.state()
                               for (n, lk), h in sorted(self._hists.items())},
            }

    def prometheus_text(self):
        """Prometheus text exposition of the whole registry (names
        prefixed ``tpu_als_``, dots -> underscores; counters get the
        conventional ``_total`` suffix)."""
        out = []
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            hists = {k: (list(h.counts), h.sum, h.count)
                     for k, h in self._hists.items()}
        by_name = {}
        for (n, lk), v in counters.items():
            by_name.setdefault((n, "counter"), []).append((lk, v))
        for (n, lk), v in gauges.items():
            by_name.setdefault((n, "gauge"), []).append((lk, v))
        for (n, lk), v in hists.items():
            by_name.setdefault((n, "histogram"), []).append((lk, v))
        for (n, kind), series in sorted(by_name.items()):
            pn = _prom_name(n)
            if kind == "counter":
                pn += "_total"
            decl = schema.METRICS.get(n)
            if decl is not None:
                out.append(f"# HELP {pn} {decl[2]}")
            out.append(f"# TYPE {pn} {kind}")
            for lk, v in sorted(series):
                if kind == "histogram":
                    counts, hsum, count = v
                    acc = 0
                    for bound, c in zip(BUCKET_BOUNDS, counts):
                        acc += c
                        lab = _render_labels(lk + (("le", _fmt(bound)),))
                        out.append(f"{pn}_bucket{lab} {acc}")
                    lab = _render_labels(lk + (("le", "+Inf"),))
                    out.append(f"{pn}_bucket{lab} {count}")
                    out.append(f"{pn}_sum{_render_labels(lk)} "
                               f"{_fmt(hsum)}")
                    out.append(f"{pn}_count{_render_labels(lk)} {count}")
                else:
                    out.append(f"{pn}{_render_labels(lk)} {_fmt(v)}")
        return "\n".join(out) + "\n"

    def finalize(self):
        """Drain the registry to the configured run dir: append new
        events to ``events.jsonl`` (with a final ``snapshot`` event),
        rewrite ``metrics.prom`` and ``run_manifest.json``.  Idempotent
        — a second call appends only events recorded since the first.
        A full ``events.jsonl`` (``TPU_ALS_OBS_ROTATE_BYTES``) rotates
        to ``events.NNN.jsonl`` first — see :func:`maybe_rotate`.
        Multi-process: only process 0 writes (peers share the dir)."""
        with self._lock:
            run_dir = self._run_dir
        if run_dir is None:
            return None
        jax = sys.modules.get("jax")
        if jax is not None:
            try:
                if jax.process_count() > 1 and jax.process_index() != 0:
                    return None
            except Exception:
                pass
        snap = self.snapshot()
        if self._dropped:
            snap["events_dropped"] = self._dropped
        self.emit("snapshot", **snap)
        os.makedirs(run_dir, exist_ok=True)
        with self._lock:
            pending = self._events[self._flushed:]
            self._flushed = len(self._events)
            manifest = dict(self._manifest or {})
        manifest["finished_at"] = round(time.time(), 6)
        from tpu_als.obs.manifest import late_device_info

        manifest.update(late_device_info())
        maybe_rotate(run_dir)
        with open(os.path.join(run_dir, "events.jsonl"), "a") as f:
            for ev in pending:
                f.write(json.dumps(ev) + "\n")
        with open(os.path.join(run_dir, "metrics.prom"), "w") as f:
            f.write(self.prometheus_text())
        with open(os.path.join(run_dir, "run_manifest.json"), "w") as f:
            json.dump(manifest, f, indent=2, sort_keys=True)
        return run_dir
