"""Reconstruct causal span trees from a run dir's JSONL trail.

``tpu_als observe explain [--trace ID | --breach last]`` — the read
side of ``tpu_als.obs.tracing``: every hop a request or rating event
took landed in ``events.jsonl`` as a ``trace_span`` event, and this
module rebuilds the admission -> queue -> scheduler round -> score ->
publish -> visible tree purely from those events.  No process state is
consulted — the same re-derivability discipline the scenario harness
enforces — so a breach is explainable from a run dir copied off the
serving host.

``--breach last`` starts from the trail's last breach-shaped event (a
``live_freshness_breach``, or a ``flight_record`` dumped with a breach
trigger) and renders the trace it names; ``--trace ID`` renders one
trace; no selector lists every trace with its hop count and outcome.

Pure stdlib, ZERO tpu_als imports: this file is runnable standalone
(``python tpu_als/obs/explain.py RUN_DIR``) on a host with no jax at
all — the bench_gate.sh discipline, pinned by a poisoned-jax test.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# flight_record triggers that mean "something breached" — the events
# --breach walks backwards over, alongside live_freshness_breach
BREACH_TRIGGERS = ("slo_breach", "freshness_breach")


def resolve_events_path(target):
    """Accept a run dir (``<output>``), its obs dir, or the events file
    itself (duplicated from report.py on purpose: this module must load
    with zero package imports)."""
    if os.path.isfile(target):
        return target
    for cand in (os.path.join(target, "obs", "events.jsonl"),
                 os.path.join(target, "events.jsonl")):
        if os.path.isfile(cand):
            return cand
    raise FileNotFoundError(
        f"no events.jsonl under {target!r} (expected <run>/obs/"
        "events.jsonl — was the command run with --output/--obs-dir?)")


def resolve_events_paths(target):
    """The full rotated trail in emission order (``events.NNN.jsonl``
    rotations sorted, then the live file) — duplicated from report.py
    on purpose, same zero-import discipline as above."""
    live = resolve_events_path(target)
    d = os.path.dirname(live)
    if os.path.basename(live) != "events.jsonl":
        return [live]
    rotated = sorted(
        f for f in os.listdir(d)
        if f.startswith("events.") and f.endswith(".jsonl")
        and f != "events.jsonl")
    return [os.path.join(d, f) for f in rotated] + [live]


def load_events(target):
    events = []
    for path in resolve_events_paths(target):
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    events.append(json.loads(line))
    return events


def build_traces(events):
    """Index the trail's ``trace_span`` events: trace_id -> spans in
    emission order (emission order IS causal order — ids are a process
    counter, never a clock)."""
    traces = {}
    for ev in events:
        if ev.get("type") == "trace_span" and ev.get("trace_id"):
            traces.setdefault(ev["trace_id"], []).append(ev)
    return traces


def publishes_for(events, trace_id):
    """The ``serving_publish`` events whose ``trace_ids`` name this
    trace — which published seq(s) this event's fold-in rode."""
    return [ev for ev in events
            if ev.get("type") == "serving_publish"
            and trace_id in (ev.get("trace_ids") or ())]


def find_breach(events):
    """The LAST breach-shaped event carrying a trace id, or None.
    Walks ``live_freshness_breach`` (trace_id of the worst event) and
    breach-triggered ``flight_record`` dumps (trace_id / trace_ids)."""
    for ev in reversed(events):
        t = ev.get("type")
        if t == "live_freshness_breach" and ev.get("trace_id"):
            return ev, ev["trace_id"]
        if t == "flight_record" \
                and ev.get("trigger") in BREACH_TRIGGERS:
            if ev.get("trace_id"):
                return ev, ev["trace_id"]
            ids = ev.get("trace_ids") or []
            if ids:
                return ev, ids[-1]
    return None


def _fmt_span(ev):
    parts = [ev.get("name", "?"), ev.get("status", "?")]
    secs = ev.get("seconds")
    if secs is not None:
        parts.append(f"{secs:.6f}s")
    for k in ("tenant", "path", "mode", "seq", "round", "batch_rows",
              "error"):
        if ev.get(k) is not None:
            parts.append(f"{k}={ev[k]}")
    return "  ".join(str(p) for p in parts)


def render_trace(trace_id, spans, publishes=()):
    """One trace as an indented causal tree (children under parents by
    ``parent_id``; orphans — a span whose parent predates the trail —
    surface as extra roots rather than vanishing)."""
    by_parent = {}
    by_id = {}
    for ev in spans:
        by_id[ev.get("span_id")] = ev
        by_parent.setdefault(ev.get("parent_id"), []).append(ev)
    roots = list(by_parent.get(None, []))
    roots += [ev for pid, evs in sorted(
        by_parent.items(), key=lambda kv: str(kv[0]))
        for ev in evs if pid is not None and pid not in by_id]
    statuses = [ev.get("status") for ev in spans]
    worst = next((s for s in ("failed", "shed", "expired", "quarantined")
                  if s in statuses), "ok")
    lines = [f"trace {trace_id}: {len(spans)} span(s), outcome {worst}"]

    def walk(ev, depth):
        pad = "  " + "   " * depth + ("└─ " if depth else "")
        lines.append(pad + _fmt_span(ev))
        for child in by_parent.get(ev.get("span_id"), []):
            walk(child, depth + 1)

    for root in roots:
        walk(root, 0)
    for pub in publishes:
        lines.append(
            f"  publish: seq={pub.get('seq')} mode={pub.get('mode')} "
            f"items={pub.get('items')} (serving_publish names this "
            "trace)")
    return "\n".join(lines)


def render_index(traces):
    lines = [f"{len(traces)} trace(s) in the trail "
             "(use --trace ID for one tree, --breach last for the "
             "latest breach):"]
    for tid in sorted(traces):
        spans = traces[tid]
        names = [ev.get("name") for ev in spans]
        statuses = {ev.get("status") for ev in spans}
        bad = sorted(statuses - {"ok"})
        lines.append(
            f"  {tid}: {len(spans)} span(s)  "
            f"{names[0]} -> {names[-1]}"
            + (f"  [{', '.join(bad)}]" if bad else ""))
    return "\n".join(lines)


def explain(target, trace=None, breach=None):
    """The command core: returns the rendered text (raises
    SystemExit-friendly ValueError/FileNotFoundError on bad input)."""
    events = load_events(target)
    traces = build_traces(events)
    if breach is not None:
        if breach != "last":
            raise ValueError(f"--breach takes 'last', got {breach!r}")
        hit = find_breach(events)
        if hit is None:
            raise ValueError(
                "no breach-shaped event carrying a trace id in the "
                "trail (live_freshness_breach, or a flight_record "
                f"with trigger in {'/'.join(BREACH_TRIGGERS)}) — "
                "was tracing armed (TPU_ALS_TRACE=1)?")
        ev, trace = hit
        head = (f"breach: {ev.get('type')}"
                + (f" trigger={ev['trigger']}" if ev.get("trigger")
                   else "")
                + (f" tenant={ev['tenant']}" if ev.get("tenant") else "")
                + (f" freshness={ev['freshness_seconds']:.4f}s "
                   f"slo={ev['slo_s']}s"
                   if ev.get("freshness_seconds") is not None else ""))
        body = explain_one(traces, events, trace)
        return head + "\n" + body
    if trace is not None:
        return explain_one(traces, events, trace)
    if not traces:
        return ("no trace_span events in the trail — was tracing armed "
                "(TPU_ALS_TRACE=1 / tracing.enable_tracing())?")
    return render_index(traces)


def explain_one(traces, events, trace_id):
    spans = traces.get(trace_id)
    if not spans:
        raise ValueError(
            f"trace {trace_id!r} not in the trail "
            f"({len(traces)} trace(s) present)")
    return render_trace(trace_id, spans,
                        publishes=publishes_for(events, trace_id))


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="explain",
        description="reconstruct causal span trees from a run dir's "
                    "trace_span trail (stdlib-only; jax-free)")
    ap.add_argument("run_dir", help="run dir / obs dir / events.jsonl")
    ap.add_argument("--trace", default=None, metavar="ID",
                    help="render one trace's tree")
    ap.add_argument("--breach", default=None, choices=("last",),
                    help="start from the trail's last breach event")
    args = ap.parse_args(argv)
    try:
        print(explain(args.run_dir, trace=args.trace,
                      breach=args.breach))
    except (FileNotFoundError, ValueError) as e:
        print(str(e), file=sys.stderr)
        return 1
    except BrokenPipeError:
        # `explain RUN | head` closing the pipe early is normal; point
        # stdout at devnull so the exit-time flush doesn't raise again
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
    return 0


if __name__ == "__main__":
    sys.exit(main())
