"""Causal trace-context propagation across serve -> live -> tenancy.

Dapper-style request tracing adapted to a JAX/XLA stack: the flight
recorder (PR 7) and the live updater's freshness spans each see one
subsystem, so a breach whose root cause lives across a boundary — a
rating stuck behind a slow fold-in, a request drained late by another
tenant's scheduler round — is invisible to both.  This module threads
ONE context through every hop instead:

- :func:`start_trace` mints a root span at an admission point (a serve
  request entering the engine, a rating event entering the live
  updater) and returns a :class:`TraceContext`;
- :func:`record_span` emits one child span and returns the NEW context,
  so call sites chain hops with a single assignment::

      t.trace = tracing.record_span(t.trace, "serve.queue",
                                    seconds=queue_wait)

- every span lands in the JSONL obs trail as a schema-registered
  ``trace_span`` event (name validated against ``schema.TRACE_SPANS``
  at call time AND statically by ``analysis/vocab.py``), so
  ``tpu_als observe explain`` reconstructs the admission -> queue ->
  scheduler round -> score -> publish -> visible tree purely from the
  trail — no process state, the scenario harness's re-derivability
  discipline.

Determinism: trace/span ids come from a lock-protected process counter
seeded by :func:`reset_trace_ids` — never wallclock or RNG (the TAL003
rule; the linter bans ``time.time()``/``uuid`` here and a seeded replay
must produce the same ids).  Device work is fence-timed by its callers
(``serving.score_seconds`` et al.) and the measured seconds ride the
span; this module never touches a device value.

Arming: tracing is OFF unless explicitly enabled (:func:`enable_
tracing`, the scoped :func:`traced` manager, or ``TPU_ALS_TRACE=1``).
Disarmed, :func:`start_trace` returns ``None`` and every propagation
site is a single ``is None`` check — nothing reaches the jitted paths,
and the production step's jaxpr stays byte-identical (the
``tracing_disarmed`` contract in ``analysis/contracts.py``, next to
``guardrails_disarmed``).  This module is stdlib + obs only; it must
stay importable without jax.
"""

from __future__ import annotations

import contextlib
import os
import threading

from tpu_als import obs
from tpu_als.obs import schema

__all__ = [
    "TraceContext", "enable_tracing", "disable_tracing",
    "tracing_armed", "traced", "reset_trace_ids", "start_trace",
    "record_span",
]

_ENV_FLAG = "TPU_ALS_TRACE"
_armed = False

_lock = threading.Lock()
_seed = 0
_next = 0


def enable_tracing():
    """Arm causal tracing for this process (scenario runs, tests, and
    the observe tooling arm it; production serving opts in)."""
    global _armed
    _armed = True


def disable_tracing():
    global _armed
    _armed = False


def tracing_armed():
    """True when tracing is on — explicitly or via the ``TPU_ALS_TRACE``
    env knob (any value but ''/'0')."""
    return _armed or os.environ.get(_ENV_FLAG, "0") not in ("", "0")


@contextlib.contextmanager
def traced():
    """Scoped arming (tests, the scenario runner, the disarmed-jaxpr
    contract)."""
    was = _armed
    enable_tracing()
    try:
        yield
    finally:
        if not was:
            disable_tracing()


def reset_trace_ids(seed=0):
    """Restart the deterministic id counter (tests; a seeded replay of
    the same admission order reproduces the same trace/span ids)."""
    global _seed, _next
    with _lock:
        _seed = int(seed)
        _next = 0


def _new_id(prefix):
    """One process-unique id: ``<prefix><seed:02x>-<counter:08x>``.
    A counter, not a clock or RNG — ids are causal order, replayable."""
    global _next
    with _lock:
        _next += 1
        return f"{prefix}{_seed:02x}-{_next:08x}"


class TraceContext:
    """The propagated half of one span: enough to emit a child.

    Immutable by convention; propagation replaces the whole context
    (``t.trace = record_span(t.trace, ...)``) so concurrent readers
    never see a half-updated hop.
    """

    __slots__ = ("trace_id", "span_id", "parent_id", "tenant")

    def __init__(self, trace_id, span_id, parent_id=None, tenant=None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.tenant = tenant

    def __repr__(self):
        return (f"TraceContext(trace_id={self.trace_id!r}, "
                f"span_id={self.span_id!r}, "
                f"parent_id={self.parent_id!r}, "
                f"tenant={self.tenant!r})")


def _emit(ctx, name, status, seconds, fields):
    schema.check_trace_span(name, status)
    extra = dict(fields)
    if ctx.tenant is not None:
        extra.setdefault("tenant", ctx.tenant)
    obs.emit("trace_span", trace_id=ctx.trace_id, span_id=ctx.span_id,
             parent_id=ctx.parent_id, name=name, status=status,
             seconds=seconds, **extra)


def start_trace(name, tenant=None, *, status="ok", seconds=None,
                **fields):
    """Mint a new trace at an admission point: emits the root span and
    returns its :class:`TraceContext` (``None`` when disarmed — the
    whole propagation chain no-ops off that None).

    ``name`` must be a declared ``schema.TRACE_SPANS`` hop; ``status``
    a declared ``TRACE_STATUSES`` outcome (a shed admission is a root
    span with ``status="shed"`` — refusals are traced, not dropped).
    """
    if not tracing_armed():
        return None
    ctx = TraceContext(_new_id("t"), _new_id("s"), parent_id=None,
                       tenant=tenant)
    _emit(ctx, name, status, seconds, fields)
    return ctx


def record_span(ctx, name, *, status="ok", seconds=None, **fields):
    """Emit one child span under ``ctx`` and return the NEW context
    (the child becomes the parent of the next hop).  No-ops — returning
    ``ctx`` unchanged — when ``ctx`` is None or tracing is disarmed, so
    call sites chain unconditionally."""
    if ctx is None or not tracing_armed():
        return ctx
    child = TraceContext(ctx.trace_id, _new_id("s"),
                         parent_id=ctx.span_id, tenant=ctx.tenant)
    _emit(child, name, status, seconds, fields)
    return child
