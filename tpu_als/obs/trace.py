"""Measurement-side tracing: fence-timed stage spans + flight recorder.

Two instruments that turn the passive obs registry into a profiler:

- ``stage(name)``: a context manager that times one ALS stage between
  ``block_until_ready`` fences and records the wall-clock into the
  ``train.stage_seconds{stage=name}`` histogram plus the span tree from
  PR 1.  Stage names match ``perf/roofline.py`` stage names exactly so
  ``tpu_als observe attribution`` can join measured seconds against the
  modeled floor.  Fencing is what makes the numbers mean anything: JAX
  dispatch is async, so without a fence the "gather time" is just the
  enqueue time of the gather.
- ``FlightRecorder``: a bounded ring of per-request span records for the
  serving engine.  Recording is always-on and cheap (a dict append under
  a lock); ``dump(trigger)`` emits the not-yet-dumped tail as
  schema-registered ``flight_record`` events, so an SLO breach leaves
  the last N request traces in the obs trail instead of vanishing into
  a p99 bucket.

Arming: the attributed training path is OFF unless explicitly enabled
(``enable_stage_attribution()`` or ``TPU_ALS_STAGE_ATTRIBUTION=1``).
When disarmed nothing here is ever reached from the hot path — the
fused jitted step is untouched (pinned by an unchanged-jaxpr test in
tests/test_attribution.py, the same discipline resilience.faults uses).

This module must stay importable without jax (bench.py-style callers);
jax is looked up via ``sys.modules`` only when fencing.
"""

from __future__ import annotations

import collections
import contextlib
import os
import sys
import threading
import time

from tpu_als import obs

_ENV_FLAG = "TPU_ALS_STAGE_ATTRIBUTION"
_armed = False


def enable_stage_attribution():
    """Arm the attributed (decomposed, fence-timed) training path."""
    global _armed
    _armed = True


def disable_stage_attribution():
    global _armed
    _armed = False


def stage_attribution_armed():
    """True when stage attribution is on — explicitly or via the
    ``TPU_ALS_STAGE_ATTRIBUTION`` env knob (any value but ''/'0')."""
    return _armed or os.environ.get(_ENV_FLAG, "0") not in ("", "0")


@contextlib.contextmanager
def stage_attribution():
    """Scoped arming for tests and the attribution CLI."""
    was = _armed
    enable_stage_attribution()
    try:
        yield
    finally:
        if not was:
            disable_stage_attribution()


def fence(x):
    """``jax.block_until_ready`` on any pytree, if jax is loaded;
    returns ``x`` either way (host values pass through untouched)."""
    jax = sys.modules.get("jax")
    if jax is not None:
        jax.block_until_ready(x)
    return x


@contextlib.contextmanager
def stage(name, sink=None):
    """Fence-timed stage span.

    Yields a ``keep(x)`` callable; the body passes every device output
    it wants attributed through it.  On exit the kept values are
    ``block_until_ready``'d, and the fence-to-fence wall clock lands in
    ``train.stage_seconds{stage=name}``, the obs span tree (span name
    ``attr.<name>``), and ``sink[name]`` when a dict is given (the
    attribution runner's per-iteration accumulator).
    """
    pending = []

    def keep(x):
        pending.append(x)
        return x

    # tal: disable=timer-brackets-span -- deliberate: the clock MUST
    # bracket the span enter/exit emissions.  The attribution coverage
    # contract (tests/test_attribution.py: stage sums >= 90% of the wall
    # iteration) attributes ALL armed-path time to stages; excluding the
    # two JSONL writes per stage leaves them unattributed and breaks the
    # bound on fast (CPU) iterations.
    t0 = time.perf_counter()
    with obs.span("attr." + name, stage=name):
        yield keep
        fence(pending)
    dt = time.perf_counter() - t0
    obs.histogram("train.stage_seconds", dt, stage=name)
    if sink is not None:
        sink[name] = sink.get(name, 0.0) + dt


# Per-request span breakdown every flight record carries.  rescore is
# None on the exact path (no int8 shortlist to refine).  The tuple's
# source of truth lives in the stdlib-only schema module so the jax-free
# static check (analysis/vocab.py) can pin it against FLIGHT_RESERVED.
SPAN_KEYS = obs.schema.SERVE_SPAN_KEYS


class FlightRecorder:
    """Bounded ring of per-request span records.

    ``record(...)`` is the always-on cheap path (called once per request
    outcome); ``dump(trigger)`` emits every not-yet-dumped record in the
    ring as a ``flight_record`` event.  A monotonic watermark guarantees
    each record is emitted at most once, so repeated triggers (every
    request breaching a tiny SLO) cost O(new records), not O(ring).

    ``span_keys`` names the breakdown each record carries — the serving
    request spans by default; the live updater records its own
    (queue_wait/quarantine/foldin/publish) through the same ring.

    ``labels`` is the recorder's STRUCTURAL attribution (e.g.
    ``tenant=<name>`` on a tenant-built engine's ring): stamped into
    every record at construction time rather than re-passed per call,
    so a new record site cannot forget the tenant and strand a dump
    event unattributable (the disjointness of label keys, span keys and
    the record's own fields is pinned by ``check_tenant_vocabulary``).
    """

    def __init__(self, capacity=64, span_keys=SPAN_KEYS, labels=None):
        self._ring = collections.deque(maxlen=int(capacity))
        self._lock = threading.Lock()
        self._span_keys = tuple(span_keys)
        self._labels = dict(labels) if labels else {}
        self._seq = 0
        self._dumped_seq = 0

    def record(self, status, spans, *, e2e_seconds=None, path=None,
               **extra):
        """Append one request trace. ``spans`` maps the recorder's span
        keys -> seconds (missing/None = not reached, e.g. a shed never
        queues)."""
        with self._lock:
            self._seq += 1
            rec = {"seq": self._seq, "status": status,
                   "spans": {k: spans.get(k) for k in self._span_keys},
                   "e2e_seconds": e2e_seconds, "path": path}
            rec.update(self._labels)
            rec.update(extra)
            self._ring.append(rec)
            return self._seq

    def dump(self, trigger):
        """Emit the not-yet-dumped tail as flight_record events; returns
        the number emitted."""
        with self._lock:
            recs = [dict(r) for r in self._ring
                    if r["seq"] > self._dumped_seq]
            self._dumped_seq = self._seq
        for r in recs:
            obs.emit("flight_record", trigger=trigger, **r)
        return len(recs)

    def __len__(self):
        with self._lock:
            return len(self._ring)
