"""Render a run dir's JSONL into a per-phase timing/throughput report.

The ``tpu_als observe`` subcommand (summarize / tail) — the CLI analog of
opening the reference stack's Spark UI stage timeline after a run.  Pure
stdlib: reads only what finalize() wrote (events.jsonl, run_manifest.json),
so it works on a run dir copied off the training host.
"""

from __future__ import annotations

import json
import os


def resolve_events_path(target):
    """Accept a run dir (``<output>``), its obs dir (``<output>/obs``),
    or the events file itself."""
    if os.path.isfile(target):
        return target
    for cand in (os.path.join(target, "obs", "events.jsonl"),
                 os.path.join(target, "events.jsonl")):
        if os.path.isfile(cand):
            return cand
    raise FileNotFoundError(
        f"no events.jsonl under {target!r} (expected <run>/obs/"
        "events.jsonl — was the command run with --output/--obs-dir?)")


def resolve_events_paths(target):
    """Every file of a possibly-rotated trail, in emission order: the
    ``events.NNN.jsonl`` rotations sorted numerically, then the live
    ``events.jsonl`` (obs.metrics.maybe_rotate writes them that way).
    A bare file target reads as a one-file trail."""
    live = resolve_events_path(target)
    d = os.path.dirname(live)
    base = os.path.basename(live)
    if base != "events.jsonl":
        return [live]
    rotated = sorted(
        f for f in os.listdir(d)
        if f.startswith("events.") and f.endswith(".jsonl")
        and f != "events.jsonl")
    return [os.path.join(d, f) for f in rotated] + [live]


def load_events(target):
    events = []
    for path in resolve_events_paths(target):
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    events.append(json.loads(line))
    return events


def filter_window(events, since=None, window=None):
    """Slice a trail by RELATIVE seconds from its first event's ts:
    ``since=S`` keeps events at/after t0+S; ``window="A:B"`` keeps
    ``t0+A <= ts < t0+B`` (either side of the colon may be empty).
    Soak trails are sliced per chaos window with exactly this."""
    if since is None and window is None:
        return events
    if not events:
        return events
    t0 = events[0].get("ts") or 0.0
    lo = hi = None
    if since is not None:
        lo = t0 + float(since)
    if window is not None:
        a, sep, b = str(window).partition(":")
        if not sep:
            raise ValueError(
                f"--window takes 'A:B' relative seconds, got {window!r}")
        if a.strip():
            wlo = t0 + float(a)
            lo = wlo if lo is None else max(lo, wlo)
        if b.strip():
            hi = t0 + float(b)
    return [ev for ev in events
            if (lo is None or (ev.get("ts") or 0.0) >= lo)
            and (hi is None or (ev.get("ts") or 0.0) < hi)]


def load_manifest(target):
    path = os.path.join(os.path.dirname(resolve_events_path(target)),
                        "run_manifest.json")
    if os.path.isfile(path):
        with open(path) as f:
            return json.load(f)
    return None


def summarize_events(events):
    """Aggregate an event list into the report dict ``render_summary``
    prints (also the ``observe summarize --json`` payload)."""
    spans = {}
    iterations = []
    gauges = {}
    warnings = []
    ingest = {"rows": 0, "bytes": 0, "seconds": 0.0, "stall_seconds": 0.0,
              "calls": 0}
    snapshot = None
    for ev in events:
        t = ev.get("type")
        if t == "span":
            s = spans.setdefault(ev["path"], {"count": 0, "total_seconds": 0.0,
                                              "max_seconds": 0.0})
            s["count"] += 1
            s["total_seconds"] += ev["seconds"]
            s["max_seconds"] = max(s["max_seconds"], ev["seconds"])
        elif t == "iteration":
            iterations.append(ev)
        elif t == "metric" and ev.get("kind") == "gauge":
            labels = ev.get("labels") or {}
            lab = ("{" + ",".join(f'{k}="{v}"' for k, v
                                  in sorted(labels.items())) + "}"
                   if labels else "")
            gauges[ev["name"] + lab] = ev["value"]
        elif t == "ingest":
            ingest["calls"] += 1
            for k in ("rows", "bytes", "seconds", "stall_seconds"):
                ingest[k] += ev.get(k, 0)
        elif t == "warning":
            warnings.append(ev)
        elif t == "snapshot":
            snapshot = ev
    for s in spans.values():
        s["total_seconds"] = round(s["total_seconds"], 6)
        # derived from the rounded total WITHOUT re-rounding: a 6-decimal
        # round of the mean breaks mean == total/count whenever the total
        # is an odd number of microseconds (sub-µs spans in tests)
        s["mean_seconds"] = s["total_seconds"] / s["count"]
    out = {"phases": spans, "iterations": iterations, "gauges": gauges,
           "warnings": warnings}
    if ingest["calls"]:
        ingest["rows_per_sec"] = round(
            ingest["rows"] / ingest["seconds"], 2) if ingest["seconds"] \
            else None
        out["ingest"] = ingest
    if snapshot is not None:
        out["counters"] = snapshot.get("counters", {})
        out["histograms"] = snapshot.get("histograms", {})
        # snapshot gauges cover anything set before the events we read
        for k, v in (snapshot.get("gauges") or {}).items():
            gauges.setdefault(k, v)
        serve = {k: v for k, v in out["histograms"].items()
                 if k.startswith("serve.request_seconds")}
        rows = sum(v for k, v in out["counters"].items()
                   if k.startswith("serve.rows"))
        secs = sum(v["sum"] for v in serve.values())
        reqs = sum(v["count"] for v in serve.values())
        if reqs:
            out["serve"] = {"requests": reqs, "rows": rows,
                            "seconds": round(secs, 6),
                            "rows_per_sec": (round(rows / secs, 2)
                                             if secs else None)}
    return out


def _fmt_secs(v):
    return f"{v:.4f}s" if v < 100 else f"{v:.1f}s"


def render_summary(summary, manifest=None):
    lines = []
    if manifest:
        head = "run: " + " ".join(manifest.get("argv") or [])
        git = manifest.get("git")
        lines.append(head.rstrip())
        lines.append(
            "  tpu_als " + str(manifest.get("tpu_als_version"))
            + (f" ({git})" if git else "")
            + f" | jax {manifest.get('jax')}"
            + f" | devices {manifest.get('device_count', '?')}"
            + f" ({manifest.get('device_kind', '?')})")
    phases = summary.get("phases") or {}
    if phases:
        lines.append("phases:")
        width = max(len(p) for p in phases)
        lines.append(f"  {'path':<{width}}  {'count':>5}  {'total':>10}"
                     f"  {'mean':>10}  {'max':>10}")
        for path in sorted(phases, key=lambda p: -phases[p]["total_seconds"]):
            s = phases[path]
            lines.append(
                f"  {path:<{width}}  {s['count']:>5}"
                f"  {_fmt_secs(s['total_seconds']):>10}"
                f"  {_fmt_secs(s['mean_seconds']):>10}"
                f"  {_fmt_secs(s['max_seconds']):>10}")
    iterations = summary.get("iterations") or []
    if iterations:
        lines.append("iterations:")
        lines.append(f"  {'it':>4}  {'seconds':>9}  {'total':>9}"
                     f"  {'probe_rmse':>10}  {'u_norm':>8}  {'v_norm':>8}")
        for ev in iterations:
            rmse = ev.get("probe_rmse")
            row = (f"  {ev['iteration']:>4}  {ev['seconds']:>9.4f}"
                   f"  {ev['total_seconds']:>9.4f}")
            row += (f"  {rmse:>10.4f}" if rmse is not None
                    else f"  {'-':>10}")
            row += (f"  {ev.get('u_norm', float('nan')):>8.4f}"
                    f"  {ev.get('v_norm', float('nan')):>8.4f}")
            lines.append(row)
    gauges = summary.get("gauges") or {}
    if gauges:
        lines.append("gauges:")
        for k in sorted(gauges):
            v = gauges[k]
            extra = ""
            if k.startswith("train.comm_bytes_per_iter"):
                extra = f"  ({v / 1e6:.3g} MB/device/iter)"
            lines.append(f"  {k} = {v}{extra}")
    counters = summary.get("counters") or {}
    if counters:
        lines.append("counters:")
        for k in sorted(counters):
            lines.append(f"  {k} = {counters[k]}")
    hists = summary.get("histograms") or {}
    if hists:
        lines.append("histograms:")
        for k in sorted(hists):
            h = hists[k]
            lines.append(
                f"  {k}: count={h['count']} sum={h['sum']:.6g}"
                f" p50={h['p50']:.3g} p95={h['p95']:.3g}"
                f" max={h['max']:.6g}")
    for key, label in (("ingest", "ingest"), ("serve", "serve")):
        blk = summary.get(key)
        if blk:
            rate = blk.get("rows_per_sec")
            lines.append(
                f"{label}: {blk['rows']:,} rows in {blk['seconds']:.4f}s"
                + (f" ({rate:,.0f} rows/sec)" if rate else ""))
    warnings = summary.get("warnings") or []
    for w in warnings:
        lines.append(f"warning: {w.get('what')}: {w.get('reason')}")
    if not lines:
        lines.append("(no events)")
    return "\n".join(lines)


def cmd_summarize(target, as_json=False, since=None, window=None):
    events = filter_window(load_events(target), since=since,
                           window=window)
    summary = summarize_events(events)
    manifest = load_manifest(target)
    if as_json:
        if manifest is not None:
            summary["manifest"] = manifest
        return json.dumps(summary, default=str)
    return render_summary(summary, manifest)


def cmd_tail(target, n=20, event=None, tenant=None, trace=None):
    """Last ``n`` raw events, optionally filtered by declared type
    (``event=``), by ``tenant=`` label, or by causal trace (``trace=``
    matches an event's ``trace_id`` or membership in its ``trace_ids``
    list, so publishes linked to the trace show up too).  All filters
    apply BEFORE the tail slice, so ``--event flight_record -n 8`` is
    the last 8 flight records, not whatever flight records happen to
    sit in the last 8 lines — and ``--tenant b -n 8`` is tenant b's
    last 8 events even if tenant a wrote the last thousand lines."""
    events = load_events(target)
    if event is not None:
        events = [ev for ev in events if ev.get("type") == event]
    if tenant is not None:
        events = [ev for ev in events if ev.get("tenant") == tenant]
    if trace is not None:
        events = [ev for ev in events
                  if ev.get("trace_id") == trace
                  or trace in (ev.get("trace_ids") or ())]
    return "\n".join(json.dumps(ev) for ev in events[-n:])
