"""The production-week driver: serve + fold-in + refit, under chaos.

``run_soak`` compresses a week of production into minutes: it builds a
small multi-tenant fleet (one ALS model per tenant, live fold-in
attached), replays the seeded :mod:`tpu_als.soak.traffic` workload
window by window, and — while traffic is in flight — performs the
:mod:`tpu_als.soak.chaos` schedule's injections with the matching fault
specs armed for exactly that window.  Every window closes with one
``soak_window`` event (per-tenant offered/answered/shed/errors/p99) and
one ``soak_injection`` event per scheduled injection (did the fault
observably fire, and is its recovery evidence in the trail).  The run
closes with a ``soak_verdict``.

The discipline that matters: the verdict is computed by
:func:`tpu_als.soak.verdict.judge` from the EVENT LIST ALONE — the
orchestrator hands it the same records ``events.jsonl`` holds, so
anyone holding a copied run dir re-derives the identical verdict
offline (``python tpu_als/soak/verdict.py RUN_DIR``).  When the obs
registry is configured, each window boundary also drains to disk
(``finalize`` is idempotent), which is what engages the trail's
size-bounded rotation on long soaks.

Recovery evidence per action (the chaos vocabulary):

- ``torn_publish``    — the corrupt publish fired, then a clean publish
  landed and the victim answered with finite scores;
- ``poisoned_refit``  — the refit's ingest quarantined the poisoned
  records and still published;
- ``solver_rollback`` — a ``guardrails=recover`` re-fit tripped the
  sentinel, rolled back (``train.rollbacks`` advanced), and published
  finite factors;
- ``tenant_churn``    — a short-lived tenant registered, answered, and
  was removed without touching the base fleet;
- ``preempt``         — a CLI train child exited ``EXIT_PREEMPTED`` and
  the same command with ``--resume auto`` completed;
- ``device_loss``     — an elastic train child lost a device, re-formed
  the mesh, resumed from checkpoint, and exited 0 (evidence read from
  the CHILD's own events.jsonl, then folded into the parent's
  ``soak_injection`` record so the parent trail stays self-contained).
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import tempfile
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from datetime import datetime, timezone

import numpy as np

from tpu_als.soak import chaos as chaos_mod
from tpu_als.soak import traffic as traffic_mod
from tpu_als.soak.verdict import DEFAULTS as JUDGE_DEFAULTS
from tpu_als.soak.verdict import judge, p99, render  # noqa: F401

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# the chaos children's training problem: small enough that a child fits
# inside a couple of windows on CPU, big enough to cross checkpoints
_CHILD_DATA = "synthetic:48x24x600"


def _cli_subprocess(args, env_extra=None):
    """A real tpu_als CLI child (preempt/device-loss need real exit
    statuses and their own fault env) — same contract as the scenario
    library's helper."""
    env = dict(os.environ)
    env.pop("TPU_ALS_PREEMPT_AT", None)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    if env_extra:
        env.update(env_extra)
    return subprocess.run(
        [sys.executable, "-c",
         "import sys; from tpu_als.cli import main; main(sys.argv[1:])"]
        + list(args),
        capture_output=True, text=True, env=env)


# ---------------------------------------------------------------------------
# fleet


def _build_fleet(cfg, *, rank, fit_iters, judge_cfg):
    """One small ALS model per tenant — IDENTICAL shapes across tenants
    (trained on the window-0 catalog), so the planner's shape-class
    compile sharing applies and window-0 traffic pays no jit.  Items
    beyond the trained catalog arrive later as NEW raw ids through the
    fold-in path (``fold_items``) — the catalog-growth contract under
    sustained load."""
    import tpu_als
    from tpu_als import plan as _plan
    from tpu_als.core.ratings import _next_pow2
    from tpu_als.io.movielens import synthetic_movielens
    from tpu_als.stream.microbatch import FoldInServer
    from tpu_als.tenancy import MultiTenantEngine, TenantSpec

    n_items = traffic_mod.catalog_size(cfg, 0)
    nnz = min(3 * cfg.n_users * n_items // 4, 1500)
    tplan = _plan.resolve_tenant_plan(rank=rank, n_users=cfg.n_users,
                                      n_items=n_items)
    cad = tplan["cadence"]
    max_batch = min(int(cad["max_batch"]), 32)
    max_wait_ms = min(float(cad["max_wait_ms"]), 25.0)
    eng = MultiTenantEngine()
    tenants = {}
    for idx, (name, weight) in enumerate(cfg.tenants):
        frame = synthetic_movielens(cfg.n_users, n_items, nnz,
                                    seed=cfg.seed + 101 * idx)
        model = tpu_als.ALS(rank=rank, maxIter=fit_iters, regParam=0.05,
                            seed=cfg.seed + idx).fit(frame)
        U, V = np.asarray(model._U), np.asarray(model._V)
        eng.add_tenant(
            TenantSpec(name=name, weight=weight, k=cfg.k,
                       buckets=tplan["buckets"], max_queue=256,
                       slo_s=judge_cfg["slo_ms"] / 1e3,
                       freshness_slo_s=judge_cfg["freshness_slo_ms"] / 1e3,
                       fold_items=True),
            U, V)
        srv = FoldInServer(model)
        # continuous-freshness startup discipline: every (rows, width)
        # shape the stream can produce compiles BEFORE traffic, both
        # fold directions, one table doubling of catalog headroom
        rows, m = [], max_batch
        while m >= 1:
            rows.append(_next_pow2(m))
            m //= 2
        srv.prewarm(rows=tuple(sorted(set(rows))), widths=(1, 2, 4),
                    sides=("user", "item"), growth=1)
        eng.attach_live(name, srv, max_batch=max_batch,
                        max_wait_ms=max_wait_ms, fold_items=True,
                        slo_s=judge_cfg["freshness_slo_ms"] / 1e3)
        item_ids = np.asarray(model._item_map.ids)
        tenants[name] = dict(
            model=model, U0=U, V0=V,
            user_ids=np.asarray(model._user_map.ids),
            item_ids=item_ids,
            dense_users=int(U.shape[0]),
            new_item_base=int(item_ids.astype(np.int64).max()) + 1000,
            base_u=np.asarray(frame["user"]),
            base_i=np.asarray(frame["item"]),
            base_r=np.asarray(frame["rating"], dtype=np.float64),
            clean=[],
        )
    eng.warmup()
    eng.start()
    return dict(eng=eng, tenants=tenants, plan=tplan, rank=rank,
                max_batch=max_batch, max_wait_ms=max_wait_ms)


# ---------------------------------------------------------------------------
# traffic replay


def _serve_one(fleet, op, stats, lock):
    from tpu_als.serving import DeadlineExceeded
    from tpu_als.tenancy import TenantOverloaded

    name = op["tenant"]
    t = fleet["tenants"][name]
    t_req = time.perf_counter()
    outcome = "answered"
    try:
        fleet["eng"].recommend(name, int(op["user"]) % t["dense_users"],
                               timeout=5.0)
    except TenantOverloaded:
        outcome = "shed"
    except DeadlineExceeded:
        outcome = "shed"
    except Exception:   # noqa: BLE001 — classified, judged by verdict
        outcome = "errors"
    ms = 1e3 * (time.perf_counter() - t_req)
    with lock:
        s = stats[name]
        s["offered"] += 1
        s[outcome] += 1
        if outcome == "answered":
            s["lat"].append(ms)


def _rate_one(fleet, op):
    """One rating arrival into the tenant's live pipeline.  Poisoned
    events materialize ``nan`` (the quarantine path); item indexes past
    the trained catalog become NEW raw ids (catalog growth via
    fold-in).  Clean events also accumulate as the tenant's refit
    corpus."""
    from tpu_als.serving import Overloaded

    t = fleet["tenants"][op["tenant"]]
    try:
        tn = fleet["eng"].tenant(op["tenant"])
    except Exception:   # noqa: BLE001 — tenant mid-churn
        return
    if tn.updater is None:
        return
    user_raw = int(t["user_ids"][int(op["user"]) % len(t["user_ids"])])
    idx = int(op["item"])
    if idx < len(t["item_ids"]):
        item_raw = int(t["item_ids"][idx])
    else:
        item_raw = t["new_item_base"] + idx
    rating = float("nan") if op["poison"] else float(op["rating"])
    try:
        tn.updater.submit(user_raw, item_raw, rating)
    except Overloaded:
        pass    # the updater already counted live.shed
    if not op["poison"]:
        clean = t["clean"]
        clean.append((user_raw, item_raw, float(op["rating"])))
        if len(clean) > 4000:
            del clean[:len(clean) - 4000]


def _replay(fleet, ops, stats, lock, pool):
    """Replay one window's ops on their scheduled offsets: serve ops go
    through the executor (client-side latency measured per request),
    rating arrivals submit inline (admission is non-blocking)."""
    t0 = time.perf_counter()
    futures = []
    for op in ops:
        delay = op["t"] - (time.perf_counter() - t0)
        if delay > 0:
            time.sleep(delay)
        if op["op"] == "serve":
            futures.append(pool.submit(_serve_one, fleet, op, stats,
                                       lock))
        else:
            _rate_one(fleet, op)
    for f in futures:
        f.result()   # workers classify, they never raise


# ---------------------------------------------------------------------------
# refit


def _refit(cfg, fleet, name, w, workdir):
    """Refit-and-republish one tenant from its accumulated clean
    ratings (plus the original corpus, so an early refit is never
    underdetermined): CSV -> ``stream_ingest`` (quarantine on) ->
    bucketed CSR -> ``guardrails=recover`` train -> scatter the solved
    rows back into the base-shaped tables by raw id -> atomic publish.
    Catalog-growth items (raw ids past the trained table) stay owned by
    the fold-in path and are skipped by the scatter."""
    from tpu_als import obs
    from tpu_als.core.als import AlsConfig, train
    from tpu_als.core.ratings import build_csr_buckets
    from tpu_als.io.stream import stream_ingest
    from tpu_als.resilience import guardrails

    t = fleet["tenants"][name]
    path = os.path.join(workdir, f"refit_{name}_w{w}.csv")
    with open(path, "w") as f:
        for uu, ii, rr in zip(t["base_u"], t["base_i"], t["base_r"]):
            f.write(f"{int(uu)},{int(ii)},{float(rr):.3f}\n")
        for uu, ii, rr in list(t["clean"]):
            f.write(f"{uu},{ii},{rr:.3f}\n")
    q0 = obs.counter_value("ingest.quarantined_rows")
    uo, io_, ro, ul, il = stream_ingest(path, quarantine=True)
    quarantined = int(obs.counter_value("ingest.quarantined_rows") - q0)
    ucsr = build_csr_buckets(uo, io_, ro, len(ul), min_width=4,
                             chunk_elems=1 << 12)
    icsr = build_csr_buckets(io_, uo, ro, len(il), min_width=4,
                             chunk_elems=1 << 12)
    with guardrails.scoped("recover"):
        U, V = train(ucsr, icsr,
                     AlsConfig(rank=fleet["rank"], max_iter=2,
                               reg_param=0.1, seed=cfg.seed + w))
    U, V = np.asarray(U), np.asarray(V)
    Ufull, Vfull = np.array(t["U0"]), np.array(t["V0"])
    umap = {int(x): j for j, x in
            enumerate(t["user_ids"].astype(np.int64))}
    imap = {int(x): j for j, x in
            enumerate(t["item_ids"].astype(np.int64))}
    for local, raw in enumerate(ul.astype(np.int64)):
        j = umap.get(int(raw))
        if j is not None:
            Ufull[j] = U[local]
    for local, raw in enumerate(il.astype(np.int64)):
        j = imap.get(int(raw))
        if j is not None:
            Vfull[j] = V[local]
    fleet["eng"].publish(name, Ufull, Vfull)
    return dict(published=True, quarantined=quarantined,
                rows=int(len(ro)))


# ---------------------------------------------------------------------------
# chaos action handlers — each returns recovery evidence (and `fired`
# when the injection has no parent-process fault spec to count hits on)


def _act_torn_publish(cfg, fleet, cw, w, workdir):
    t = fleet["tenants"][cw.victim]
    eng = fleet["eng"]
    eng.publish(cw.victim, t["U0"], t["V0"])   # armed: tags int8 stale
    eng.publish(cw.victim, t["U0"], t["V0"])   # the clean republish
    scores, _ = eng.recommend(cw.victim, 0, timeout=10.0)
    finite = bool(np.isfinite(np.asarray(scores)).all())
    return dict(recovered=finite)


def _act_poisoned_refit(cfg, fleet, cw, w, workdir):
    res = _refit(cfg, fleet, cw.victim, w, workdir)
    return dict(recovered=bool(res["published"]
                               and res["quarantined"] > 0), **res)


def _act_solver_rollback(cfg, fleet, cw, w, workdir):
    from tpu_als import obs
    from tpu_als.core.als import AlsConfig, train
    from tpu_als.core.ratings import build_csr_buckets
    from tpu_als.resilience import guardrails

    t = fleet["tenants"][cw.victim]
    nu, ni = t["U0"].shape[0], t["V0"].shape[0]
    rng = np.random.default_rng([cfg.seed, w, 77])
    u = rng.integers(0, nu, 600)
    i = rng.integers(0, ni, 600)
    r = rng.uniform(0.5, 5.0, 600).astype(np.float32)
    ucsr = build_csr_buckets(u, i, r, nu, min_width=4,
                             chunk_elems=1 << 12)
    icsr = build_csr_buckets(i, u, r, ni, min_width=4,
                             chunk_elems=1 << 12)
    rb0 = obs.counter_value("train.rollbacks")
    with guardrails.scoped("recover"):
        U, V = train(ucsr, icsr,
                     AlsConfig(rank=fleet["rank"], max_iter=4,
                               reg_param=0.1, seed=cfg.seed))
    rolled = int(obs.counter_value("train.rollbacks") - rb0)
    finite = bool(np.isfinite(np.asarray(U)).all()
                  and np.isfinite(np.asarray(V)).all())
    fleet["eng"].publish(cw.victim, np.asarray(U), np.asarray(V))
    return dict(recovered=bool(rolled > 0 and finite),
                rollbacks=rolled)


def _act_tenant_churn(cfg, fleet, cw, w, workdir):
    from tpu_als.tenancy import TenantSpec

    eng = fleet["eng"]
    shape = next(iter(fleet["tenants"].values()))
    rng = np.random.default_rng([cfg.seed, w, 55])
    U = rng.normal(size=shape["U0"].shape).astype(np.float32)
    V = rng.normal(size=shape["V0"].shape).astype(np.float32)
    name = f"churn{w}"
    eng.add_tenant(TenantSpec(name=name, k=cfg.k), U, V)
    served = False
    try:
        eng.warmup(name)
        _, idx = eng.recommend(name, 0, timeout=10.0)
        served = len(np.asarray(idx)) > 0
    finally:
        eng.remove_tenant(name)
    return dict(fired=True, recovered=served)


def _act_preempt(cfg, fleet, cw, w, workdir):
    from tpu_als.resilience.preempt import EXIT_PREEMPTED

    d = os.path.join(workdir, f"preempt_w{w}")
    base = ["train", "--data", _CHILD_DATA, "--rank", "4",
            "--max-iter", "5", "--reg-param", "0.05",
            "--seed", str(cfg.seed),
            "--checkpoint-dir", os.path.join(d, "ck")]
    p1 = _cli_subprocess(base, env_extra={
        "TPU_ALS_PREEMPT_AT": "2", "JAX_PLATFORMS": "cpu"})
    out = os.path.join(d, "model")
    p2 = _cli_subprocess(base + ["--resume", "auto", "--output", out],
                         env_extra={"JAX_PLATFORMS": "cpu"})
    return dict(fired=p1.returncode == EXIT_PREEMPTED,
                recovered=bool(
                    p2.returncode == 0
                    and os.path.isfile(os.path.join(out,
                                                    "manifest.json"))),
                preempt_exit=p1.returncode, resume_exit=p2.returncode)


def _act_device_loss(cfg, fleet, cw, w, workdir):
    d = os.path.join(workdir, f"device_loss_w{w}")
    obsdir = os.path.join(d, "obs")
    p = _cli_subprocess(
        ["train", "--data", _CHILD_DATA, "--rank", "4",
         "--reg-param", "0.05", "--seed", str(cfg.seed),
         "--devices", "3", "--elastic", "--max-iter", "4",
         "--checkpoint-dir", os.path.join(d, "ck"),
         "--checkpoint-interval", "1",
         "--output", os.path.join(d, "model"), "--obs-dir", obsdir],
        env_extra={
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
            "TPU_ALS_FAULT_SPEC": "mesh.device_lost=corrupt@nth=2",
        })
    by = {}
    epath = os.path.join(obsdir, "events.jsonl")
    if os.path.isfile(epath):
        with open(epath) as f:
            for line in f:
                line = line.strip()
                if line:
                    e = json.loads(line)
                    by[e["type"]] = by.get(e["type"], 0) + 1
    child = {k: by.get(k, 0) for k in
             ("device_lost", "mesh_reformed", "elastic_resume")}
    return dict(fired=child["device_lost"] >= 1,
                recovered=bool(p.returncode == 0
                               and child["mesh_reformed"] >= 1
                               and child["elastic_resume"] >= 1),
                exit=p.returncode, child_events=child)


_HANDLERS = {
    "torn_publish": _act_torn_publish,
    "poisoned_refit": _act_poisoned_refit,
    "solver_rollback": _act_solver_rollback,
    "tenant_churn": _act_tenant_churn,
    "preempt": _act_preempt,
    "device_loss": _act_device_loss,
}


def _run_action(cfg, fleet, cw, w, workdir, outcomes):
    try:
        outcomes[cw.name] = _HANDLERS[cw.action](cfg, fleet, cw, w,
                                                 workdir)
    except Exception as e:   # noqa: BLE001 — a dead action is a failed
        # recovery, judged by the verdict, never a crashed soak
        outcomes[cw.name] = dict(
            recovered=False, error=f"{type(e).__name__}: {e}")


def _run_refit(cfg, fleet, name, w, workdir, outcomes):
    """The PERIODIC refit (no chaos attached) — same pipeline as the
    poisoned one, but its success is just published-or-not."""
    try:
        outcomes["periodic-refit"] = _refit(cfg, fleet, name, w,
                                            workdir)
    except Exception as e:   # noqa: BLE001 — reported, never fatal
        outcomes["periodic-refit"] = dict(
            published=False, error=f"{type(e).__name__}: {e}")


# ---------------------------------------------------------------------------
# the window loop


def _refit_due(injections, w, refit_every):
    if any(cw.action == "poisoned_refit" for cw in injections):
        return False    # the chaos refit IS this window's refit
    return bool(refit_every) and w > 0 \
        and w % refit_every == refit_every - 1


def _run_window(cfg, schedule, fleet, w, workdir, refit_every, pool):
    from tpu_als import obs
    from tpu_als.resilience import faults

    injections = schedule.for_window(w)
    stats = {name: {"offered": 0, "answered": 0, "shed": 0,
                    "errors": 0, "lat": []}
             for name in fleet["tenants"]}
    lock = threading.Lock()
    outcomes = {}
    refit_name = cfg.tenants[0][0]
    t0 = time.perf_counter()
    irecs = []
    with schedule.armed(w):
        # hit baselines AFTER arming: push_spec installs fresh rules,
        # and hits() reads the armed table (popped specs vanish)
        points = sorted({p for cw in injections if cw.fault_spec
                         for p in faults.parse_spec(cw.fault_spec)})
        hits0 = {p: faults.hits(p)[1] for p in points}  # tal: disable=unregistered-name -- points come from parse_spec of construction-validated chaos specs
        threads = []
        for cw in injections:
            if cw.action:
                th = threading.Thread(
                    target=_run_action,
                    args=(cfg, fleet, cw, w, workdir, outcomes),
                    name=f"soak-{cw.name}", daemon=True)
                th.start()
                threads.append(th)
        if _refit_due(injections, w, refit_every):
            th = threading.Thread(
                target=_run_refit, args=(cfg, fleet, refit_name, w,
                                         workdir, outcomes),
                name="soak-refit", daemon=True)
            th.start()
            threads.append(th)
        _replay(fleet, traffic_mod.generate_window(cfg, w), stats,
                lock, pool)
        deadline = time.perf_counter() + 300.0
        for th in threads:
            th.join(max(0.1, deadline - time.perf_counter()))
        # injection verdicts, while the armed table still exists
        for cw in injections:
            out = dict(outcomes.get(cw.name, {}))
            if cw.fault_spec:
                pts = sorted(faults.parse_spec(cw.fault_spec))
                fired = any(faults.hits(p)[1] > hits0[p] for p in pts)  # tal: disable=unregistered-name -- same parse_spec-validated points as the baseline above
            else:
                fired = bool(out.pop("fired", False))
            out.pop("fired", None)
            recovered = bool(out.pop("recovered", False)) \
                if cw.action else fired
            irecs.append({"window": w, "name": cw.name,
                          "action": cw.action, "victim": cw.victim,
                          "spec": cw.fault_spec, "fired": bool(fired),
                          "recovered": bool(fired and recovered),
                          "detail": out})
    seconds = round(time.perf_counter() - t0, 3)

    tstats = {}
    totals = {"offered": 0, "answered": 0, "shed": 0, "errors": 0}
    for name, s in stats.items():
        q = p99(s["lat"])
        tstats[name] = {"offered": s["offered"],
                        "answered": s["answered"], "shed": s["shed"],
                        "errors": s["errors"],
                        "p99_ms": round(q, 3) if q is not None else None}
        for k in totals:
            totals[k] += s[k]
    wrec = {"window": w, "seconds": seconds, "tenants": tstats,
            **totals}
    if "periodic-refit" in outcomes:
        wrec["refit"] = outcomes["periodic-refit"]
    obs.emit("soak_window", **wrec)
    obs.counter("soak.windows")
    obs.histogram("soak.window_seconds", seconds)
    for rec in irecs:
        obs.emit("soak_injection", **rec)
        if rec["fired"]:
            obs.counter("soak.injections")
        if rec["recovered"]:
            obs.counter("soak.recoveries")
    return wrec, irecs


def _drain(fleet, timeout_s=30.0):
    """Wait for every tenant's live queue to empty, then one cadence
    tick more, so queued events' ``live.visible`` spans land before the
    verdict reads freshness."""
    deadline = time.perf_counter() + timeout_s
    for name in fleet["tenants"]:
        try:
            tn = fleet["eng"].tenant(name)
        except Exception:   # noqa: BLE001
            continue
        if tn.updater is None:
            continue
        while tn.updater.queue_depth and time.perf_counter() < deadline:
            time.sleep(0.02)
    time.sleep(2.5 * fleet["max_wait_ms"] / 1e3)


# ---------------------------------------------------------------------------
# entry points


def run_soak(cfg=None, schedule=None, *, rank=8, fit_iters=2,
             refit_every=3, subprocesses=True, judge_config=None,
             workdir=None):
    """The whole production week.  Returns the verdict dict (see
    :func:`tpu_als.soak.verdict.judge`) plus ``window_records``,
    ``injection_records``, ``config`` and ``wall_seconds``."""
    from tpu_als import obs
    from tpu_als.obs import tracing

    cfg = cfg if cfg is not None else traffic_mod.TrafficConfig()
    if schedule is None:
        schedule = chaos_mod.default_schedule(
            cfg.windows, victim=cfg.tenants[0][0],
            subprocesses=subprocesses)
    jcfg = dict(JUDGE_DEFAULTS)
    if judge_config:
        jcfg.update({k: v for k, v in judge_config.items()
                     if k in jcfg and v is not None})
    reg = obs.default_registry()
    own_wd = workdir is None
    if own_wd:
        workdir = tempfile.mkdtemp(prefix="tpu_als_soak_")
    else:
        os.makedirs(workdir, exist_ok=True)
    was_traced = tracing.tracing_armed()
    tracing.enable_tracing()   # freshness verdict reads live.visible
    ev_start = len(reg._events)
    t_soak = time.perf_counter()
    obs.emit("soak_start", windows=cfg.windows, window_s=cfg.window_s,
             tenants=[[n, wt] for n, wt in cfg.tenants], seed=cfg.seed,
             scheduled_injections=len(schedule))
    window_records, injection_records = [], []
    fleet = _build_fleet(cfg, rank=rank, fit_iters=fit_iters,
                         judge_cfg=jcfg)
    pool = ThreadPoolExecutor(max_workers=8,
                              thread_name_prefix="soak-serve")
    try:
        for w in range(cfg.windows):
            wrec, irecs = _run_window(cfg, schedule, fleet, w, workdir,
                                      refit_every, pool)
            window_records.append(wrec)
            injection_records.extend(irecs)
            if reg.active():
                reg.finalize()   # drains the trail — and engages the
                # size-bounded events.jsonl rotation on long soaks
        _drain(fleet)
    finally:
        pool.shutdown(wait=False)
        try:
            fleet["eng"].stop()
        except Exception:   # noqa: BLE001 — verdict still owed
            pass
        if not was_traced:
            tracing.disable_tracing()
        if own_wd:
            shutil.rmtree(workdir, ignore_errors=True)
    events = [dict(e) for e in reg._events[ev_start:]]
    result = judge(events, jcfg)
    obs.emit("soak_verdict", passed=result["passed"],
             survived_minutes=result["survived_minutes"],
             checks=result["checks"])
    result["events"] = events
    result["window_records"] = window_records
    result["injection_records"] = injection_records
    result["config"] = cfg.to_dict()
    result["judge_config"] = jcfg
    result["wall_seconds"] = round(time.perf_counter() - t_soak, 3)
    return result


def bank_result(result, path):
    """Bank the soak verdict for ``observe regress --trend``: the
    survived-minutes headline (unit 'minutes' is higher-is-better under
    the gate's unit table) plus the SLO extras."""
    rec = {
        "metric": "soak_survived_minutes",
        "value": result["survived_minutes"],
        "unit": "minutes",
        "passed": result["passed"],
        "windows": result["windows"],
        "worst_window_p99_ms": result["worst_window_p99_ms"],
        "freshness_p99_ms": result["freshness_p99_ms"],
        "fairness_ratio": result["fairness_ratio"],
        "shed_rate": result["shed_rate"],
        "injections": result["injections"],
        "recoveries": result["recoveries"],
        "config": result["config"],
        "banked_by": "tpu_als soak",
        "banked_at": datetime.now(timezone.utc).isoformat(
            timespec="seconds"),
    }
    with open(path, "w") as f:
        json.dump(rec, f, indent=2, sort_keys=False)
        f.write("\n")
    return rec
