"""Seeded synthetic workload model: the soak's "millions of users".

One deterministic generator emits BOTH sides of the production load —
serve queries and rating-arrival events — window by window:

- **zipfian item popularity**: item ranks are drawn with weight
  ``1/(rank+1)^s`` over a catalog that GROWS per window
  (``catalog_growth`` items join every window, so late windows rate
  items the trained model has never seen — the fold-in path's catalog-
  growth contract under sustained load);
- **diurnal load**: the per-window rate is the base rate scaled by
  ``1 + amp * sin(2π·w / day_windows)`` — a compressed day, so a soak
  of a few minutes sweeps a peak and a trough;
- **per-tenant request mixes**: each tenant's share of both streams is
  its declared weight over the weight total (the fairness verdict
  judges answered-per-offered across tenants, so the mix is the
  fairness test's ground truth);
- **poison**: each rating event is independently poisoned with
  probability ``poison_frac`` (its rating arrives as ``None`` — the
  orchestrator materializes ``nan`` at submit time, exercising the
  quarantine path; ``None`` rather than ``nan`` keeps the canonical
  byte stream strict JSON).

Determinism contract: every draw comes from ``np.random.default_rng(
[seed, window])`` in a FIXED order (serve counts/times/users per tenant
in declared order, then rating counts/times/users/items/values/poison),
so ``generate_window(cfg, w)`` is a pure function of ``(config, w)``
and :func:`stream_bytes` is byte-identical across processes and
platforms (numpy's PCG64 is specified).  The determinism test pins
exactly that, cross-process.

TAL003 note: no wall-clock RNG anywhere in this module — seeds are
config, never ``time``.
"""

from __future__ import annotations

import json
import math
from dataclasses import asdict, dataclass

import numpy as np


@dataclass(frozen=True)
class TrafficConfig:
    """The whole workload model, one frozen value.  ``(seed, schedule)``
    — where schedule is every other field — replays byte-for-byte."""

    seed: int = 17
    # (name, weight) per tenant, declared order = draw order
    tenants: tuple = (("a", 3.0), ("b", 1.0))
    windows: int = 8
    window_s: float = 3.0        # compressed wall seconds per window
    day_windows: int = 4         # diurnal period, in windows
    base_qps: float = 40.0       # serve queries/sec at the diurnal mean
    diurnal_amp: float = 0.5     # 0..1 swing around the mean
    update_qps: float = 25.0     # rating events/sec at the diurnal mean
    zipf_s: float = 1.1          # popularity exponent
    catalog0: int = 48           # items in the catalog at window 0
    catalog_growth: int = 6      # items joining per window
    n_users: int = 64
    poison_frac: float = 0.02
    k: int = 5                   # top-k per serve query

    def __post_init__(self):
        if self.windows < 1 or self.window_s <= 0:
            raise ValueError("windows >= 1 and window_s > 0 required")
        if not self.tenants:
            raise ValueError("at least one tenant required")
        if not 0.0 <= self.poison_frac <= 1.0:
            raise ValueError("poison_frac must be in [0, 1]")
        if self.day_windows < 1:
            raise ValueError("day_windows >= 1 required")

    def to_dict(self):
        d = asdict(self)
        d["tenants"] = [list(t) for t in self.tenants]
        return d

    @classmethod
    def from_dict(cls, d):
        d = dict(d)
        d["tenants"] = tuple((str(n), float(w)) for n, w in d["tenants"])
        return cls(**d)


def load_multiplier(cfg, w):
    """The diurnal curve at window ``w``: 1 ± amp over a compressed day
    of ``day_windows`` windows (clamped non-negative)."""
    phase = 2.0 * math.pi * (w % cfg.day_windows) / cfg.day_windows
    return max(0.0, 1.0 + cfg.diurnal_amp * math.sin(phase))


def catalog_size(cfg, w):
    """Items sampleable at window ``w`` — the growing catalog."""
    return cfg.catalog0 + cfg.catalog_growth * w


def max_catalog(cfg):
    return catalog_size(cfg, cfg.windows - 1)


def zipf_weights(n, s):
    """Normalized ``1/(rank+1)^s`` over ``n`` items (rank 0 is the
    most popular)."""
    w = 1.0 / np.power(np.arange(1, n + 1, dtype=np.float64), s)
    return w / w.sum()


def generate_window(cfg, w):
    """Every op of window ``w``, time-ordered.  Serve ops::

        {"op": "serve", "t": <offset s>, "tenant": str, "user": int,
         "k": int}

    Rating ops::

        {"op": "rate", "t": <offset s>, "tenant": str, "user": int,
         "item": int, "rating": float | None, "poison": bool}

    ``item`` indexes the zipf-ranked catalog of THIS window (late
    windows reach items earlier windows could not).  ``rating`` is
    ``None`` iff ``poison`` — the submitter turns it into ``nan``.
    """
    if not 0 <= w < cfg.windows:
        raise ValueError(f"window {w} outside 0..{cfg.windows - 1}")
    rng = np.random.default_rng([int(cfg.seed), int(w)])
    mult = load_multiplier(cfg, w)
    total_weight = sum(wt for _, wt in cfg.tenants)
    n_items = catalog_size(cfg, w)
    zw = zipf_weights(n_items, cfg.zipf_s)
    ops = []
    # draw order is the determinism contract — serve side first,
    # tenants in declared order, then the rating side the same way
    for name, weight in cfg.tenants:
        lam = cfg.base_qps * mult * cfg.window_s * weight / total_weight
        n = int(rng.poisson(lam))
        times = np.sort(rng.uniform(0.0, cfg.window_s, n))
        users = rng.integers(0, cfg.n_users, n)
        for j in range(n):
            ops.append({"op": "serve", "t": round(float(times[j]), 6),
                        "tenant": name, "user": int(users[j]),
                        "k": cfg.k})
    for name, weight in cfg.tenants:
        lam = cfg.update_qps * mult * cfg.window_s * weight / total_weight
        n = int(rng.poisson(lam))
        times = np.sort(rng.uniform(0.0, cfg.window_s, n))
        users = rng.integers(0, cfg.n_users, n)
        items = rng.choice(n_items, size=n, p=zw)
        ratings = np.round(rng.uniform(1.0, 5.0, n), 3)
        poison = rng.random(n) < cfg.poison_frac
        for j in range(n):
            p = bool(poison[j])
            ops.append({"op": "rate", "t": round(float(times[j]), 6),
                        "tenant": name, "user": int(users[j]),
                        "item": int(items[j]),
                        "rating": None if p else float(ratings[j]),
                        "poison": p})
    # stable total order: time, then kind, then tenant (ties are rare
    # but the byte-replay contract cannot tolerate ambiguity)
    ops.sort(key=lambda o: (o["t"], o["op"], o["tenant"],
                            o.get("user", -1), o.get("item", -1)))
    return ops


def stream(cfg):
    """Yield ``(window, ops)`` for every window in order."""
    for w in range(cfg.windows):
        yield w, generate_window(cfg, w)


def stream_bytes(cfg):
    """The whole workload as canonical JSON-lines bytes — the object the
    byte-for-byte replay pin compares across processes.  Strict JSON
    (``allow_nan=False``): poisoned ratings are ``null``."""
    out = []
    for w, ops in stream(cfg):
        for op in ops:
            rec = {"window": w, **op}
            out.append(json.dumps(rec, sort_keys=True,
                                  separators=(",", ":"),
                                  allow_nan=False))
    return ("\n".join(out) + "\n").encode()


def window_counts(cfg, w):
    """Offered-load summary of one window without materializing ops:
    {tenant: {"serve": n, "rate": n}} — convenience for tests/docs."""
    ops = generate_window(cfg, w)
    out = {name: {"serve": 0, "rate": 0} for name, _ in cfg.tenants}
    for op in ops:
        out[op["tenant"]]["serve" if op["op"] == "serve" else "rate"] += 1
    return out
