"""The production-week soak subsystem (ROADMAP item 5).

Four pieces, composed by :func:`tpu_als.soak.orchestrator.run_soak`:

- ``traffic``      — the fully seeded synthetic workload model (zipfian
  catalog with growth, diurnal load at compressed timescale, per-tenant
  mixes, poisoned rating arrivals), replayable byte-for-byte from
  ``(seed, schedule)``.
- ``chaos``        — the declarative chaos schedule: every existing
  fault point sequenced onto the soak timeline, armed per-window
  through ``faults.push_spec`` with LIFO restore.
- ``orchestrator`` — drives multi-tenant serve + per-tenant live
  fold-in + periodic refit concurrently under the traffic model, one
  ``soak_window`` / ``soak_injection`` event per window.
- ``verdict``      — stdlib-only SLO judge, re-derivable from
  events.jsonl alone (the ``observe explain`` discipline).

See docs/soak.md for the knobs, the chaos grammar, and the verdict
semantics.
"""

from tpu_als.soak.traffic import TrafficConfig  # noqa: F401
from tpu_als.soak.chaos import ChaosSchedule, ChaosWindow  # noqa: F401
from tpu_als.soak.orchestrator import run_soak  # noqa: F401
