"""The soak verdict: SLOs judged from the obs trail ALONE.

``judge(events, config)`` consumes nothing but a list of event dicts —
the same records ``events.jsonl`` holds — and returns the full verdict:
serve p99 (worst window, victim-free tenants), freshness p99 (from the
``live.visible`` trace spans, whose ``seconds`` field IS the per-event
arrival→servable freshness), fairness ratio, shed rate, zero errors on
victim-free tenants, and every scheduled chaos injection observed AND
recovered.  Because the inputs are events only, the verdict is
re-derivable offline from a run dir copied off the host — the
``observe explain`` discipline, pinned by a poisoned-jax test that
loads this file standalone.

Pure stdlib, ZERO tpu_als imports: runnable as
``python tpu_als/soak/verdict.py RUN_DIR``.  The trail loader reads
rotated ``events.NNN.jsonl`` files before the live one (duplicated
from report.py on purpose — same reason explain.py duplicates it).
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys

# the judge's SLO knobs; config overrides per key.  slo_ms is generous
# for CPU tier-1 (chaos children compete for the same cores); on chip
# the CLI/scenario pass production bounds instead.
DEFAULTS = {
    "slo_ms": 1000.0,            # serve p99, victim-free tenants
    "freshness_slo_ms": 5623.5,  # arrival->servable p99 (bucket rung)
    "fairness_max": 3.0,         # max/min answered-rate across tenants
    "shed_max": 0.5,             # shed / offered, whole soak
}


def resolve_events_path(target):
    if os.path.isfile(target):
        return target
    for cand in (os.path.join(target, "obs", "events.jsonl"),
                 os.path.join(target, "events.jsonl")):
        if os.path.isfile(cand):
            return cand
    raise FileNotFoundError(
        f"no events.jsonl under {target!r} (expected <run>/obs/"
        "events.jsonl — was the command run with --output/--obs-dir?)")


def resolve_events_paths(target):
    live = resolve_events_path(target)
    d = os.path.dirname(live)
    if os.path.basename(live) != "events.jsonl":
        return [live]
    rotated = sorted(
        f for f in os.listdir(d)
        if f.startswith("events.") and f.endswith(".jsonl")
        and f != "events.jsonl")
    return [os.path.join(d, f) for f in rotated] + [live]


def load_events(target):
    events = []
    for path in resolve_events_paths(target):
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    events.append(json.loads(line))
    return events


def p99(values):
    """Nearest-rank p99 of a plain list (None when empty)."""
    if not values:
        return None
    vs = sorted(values)
    return vs[max(0, math.ceil(0.99 * len(vs)) - 1)]


def _check(name, observed, op, expected, doc=""):
    ops = {"<=": lambda a, b: a <= b, ">=": lambda a, b: a >= b,
           "==": lambda a, b: a == b}
    ok = observed is not None and bool(ops[op](observed, expected))
    rec = {"check": name, "ok": ok, "observed": observed, "op": op,
           "expected": expected}
    if doc:
        rec["doc"] = doc
    return rec


def judge(events, config=None):
    """The verdict, from events alone.  Returns::

        {"passed": bool, "checks": [...], "survived_minutes": float,
         "worst_window_p99_ms", "freshness_p99_ms", "fairness_ratio",
         "shed_rate", "injections", "recoveries", "windows"}
    """
    cfg = dict(DEFAULTS)
    if config:
        cfg.update({k: v for k, v in config.items()
                    if k in DEFAULTS and v is not None})
    start = next((e for e in events if e.get("type") == "soak_start"),
                 None)
    windows = [e for e in events if e.get("type") == "soak_window"]
    injections = [e for e in events if e.get("type") == "soak_injection"]
    victims_by_window = {}
    for inj in injections:
        if inj.get("victim"):
            victims_by_window.setdefault(inj["window"], set()).add(
                inj["victim"])

    # serve p99: worst window over VICTIM-FREE tenants (a tenant a chaos
    # window targets may legitimately degrade; everyone else must hold)
    worst_p99 = None
    offered = answered = shed = 0
    victim_free_errors = 0
    per_tenant = {}     # tenant -> [answered, offered], victim-free only
    for wev in windows:
        w = wev.get("window")
        victims = victims_by_window.get(w, set())
        offered += wev.get("offered", 0)
        answered += wev.get("answered", 0)
        shed += wev.get("shed", 0)
        for name, t in (wev.get("tenants") or {}).items():
            if name in victims:
                continue
            victim_free_errors += t.get("errors", 0)
            q = t.get("p99_ms")
            if q is not None and (worst_p99 is None or q > worst_p99):
                worst_p99 = q
            acc = per_tenant.setdefault(name, [0, 0])
            acc[0] += t.get("answered", 0)
            acc[1] += t.get("offered", 0)

    # freshness: the live.visible span's seconds IS the per-event
    # arrival->servable freshness (tpu_als.live.updater's contract)
    fresh = [e.get("seconds") for e in events
             if e.get("type") == "trace_span"
             and e.get("name") == "live.visible"
             and e.get("seconds") is not None]
    fresh_p99_ms = (round(1e3 * p99(fresh), 3) if fresh else None)

    rates = [a / o for a, o in per_tenant.values() if o]
    fairness = (round(max(rates) / min(rates), 4)
                if rates and min(rates) > 0 else None)
    shed_rate = round(shed / offered, 4) if offered else 0.0

    recovered = sum(1 for i in injections
                    if i.get("fired") and i.get("recovered"))
    scheduled = (start or {}).get("scheduled_injections",
                                  len(injections))

    checks = [
        _check("windows_completed", len(windows), "==",
               (start or {}).get("windows", len(windows)),
               "every scheduled window ran and reported"),
        _check("serve_p99_victim_free", worst_p99, "<=", cfg["slo_ms"],
               "worst window p99 over tenants no chaos targeted"),
        _check("freshness_p99", fresh_p99_ms, "<=",
               cfg["freshness_slo_ms"],
               "arrival->servable p99 from live.visible spans"),
        _check("fairness_ratio", fairness, "<=", cfg["fairness_max"],
               "max/min answered-per-offered across victim-free "
               "tenant-windows"),
        _check("shed_rate", shed_rate, "<=", cfg["shed_max"],
               "shedding is the valve, not the norm"),
        _check("victim_free_errors", victim_free_errors, "==", 0,
               "tenants no chaos window targeted never erred"),
        _check("injections_observed", len(injections), "==", scheduled,
               "every scheduled chaos injection left a soak_injection "
               "record"),
        _check("injections_recovered", recovered, "==", scheduled,
               "every injection fired AND its recovery evidence is in "
               "the trail"),
    ]
    window_s = (start or {}).get("window_s", 0.0)
    result = {
        "passed": all(c["ok"] for c in checks),
        "checks": checks,
        "windows": len(windows),
        "survived_minutes": round(len(windows) * window_s / 60.0, 3),
        "worst_window_p99_ms": worst_p99,
        "freshness_p99_ms": fresh_p99_ms,
        "freshness_samples": len(fresh),
        "fairness_ratio": fairness,
        "shed_rate": shed_rate,
        "offered": offered,
        "answered": answered,
        "injections": len(injections),
        "recoveries": recovered,
    }
    return result


def render(result):
    """The human verdict table (the CLI's stdout)."""
    lines = [f"soak: {'PASS' if result['passed'] else 'FAIL'}  "
             f"({result['windows']} windows, "
             f"{result['survived_minutes']} survived-minutes, "
             f"{result['answered']}/{result['offered']} answered)"]
    for c in result["checks"]:
        mark = "ok  " if c["ok"] else "FAIL"
        lines.append(f"  {mark} {c['check']:<24} "
                     f"{c['observed']} {c['op']} {c['expected']}")
    return "\n".join(lines)


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="verdict",
        description="re-derive the soak verdict from a run dir's "
                    "events.jsonl alone (stdlib-only; jax-free)")
    ap.add_argument("run_dir", help="run dir / obs dir / events.jsonl")
    ap.add_argument("--json", dest="as_json", action="store_true")
    for key, dv in DEFAULTS.items():
        ap.add_argument("--" + key.replace("_", "-"), dest=key,
                        type=float, default=None,
                        help=f"override (default {dv})")
    args = ap.parse_args(argv)
    try:
        events = load_events(args.run_dir)
    except FileNotFoundError as e:
        print(str(e), file=sys.stderr)
        return 2
    result = judge(events, {k: getattr(args, k) for k in DEFAULTS})
    print(json.dumps(result) if args.as_json else render(result))
    return 0 if result["passed"] else 1


if __name__ == "__main__":
    sys.exit(main())
