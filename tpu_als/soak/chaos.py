"""Declarative chaos schedule: every fault point, on the soak timeline.

A :class:`ChaosSchedule` sequences the repo's whole chaos vocabulary —
``serving.publish`` torn publishes, ``ingest.record`` stream poison,
``solve.gram`` solver blowups, ``mesh.device_lost`` device loss,
SIGTERM preemption, tenant register/remove — onto soak windows.  Each
:class:`ChaosWindow` names the window it lands in, an optional
``TPU_ALS_FAULT_SPEC`` grammar string armed for exactly that window
(``faults.push_spec`` overlay, popped in a ``finally`` — the same LIFO
restore discipline the scenario runner uses for per-phase specs), and
an ``action`` the orchestrator performs while the spec is armed.

Actions are the vocabulary of things a fault spec alone cannot do:

==================  ========================================================
``torn_publish``    republish the victim's factors while ``serving.publish``
                    corrupt is armed (the int8 index tags stale; recovery is
                    the next clean publish)
``poisoned_refit``  the window's periodic refit ingests its accumulated
                    ratings through ``stream_ingest`` with ``ingest.record``
                    armed — recovery is quarantine-and-complete
``solver_rollback`` a guardrails=recover re-fit with ``solve.gram`` corrupt
                    armed — recovery is sentinel-trip → rollback → publish
``tenant_churn``    register a short-lived tenant under load, serve it,
                    remove it (publish-before-visible under chaos)
``preempt``         a CLI train child gets SIGTERM'd at an iteration
                    boundary (exit 43) and ``--resume auto`` completes
``device_loss``     a CLI ``--elastic`` train child loses a device
                    (``mesh.device_lost`` in the CHILD's env), re-forms the
                    mesh and completes
==================  ========================================================

Every fault spec is validated at construction (``faults.parse_spec``) —
a typo fails the schedule, not minute three of the soak.
"""

from __future__ import annotations

import contextlib
from dataclasses import dataclass

from tpu_als.resilience import faults

ACTIONS = ("torn_publish", "poisoned_refit", "solver_rollback",
           "tenant_churn", "preempt", "device_loss")


@dataclass(frozen=True)
class ChaosWindow:
    """One scheduled injection: which window, what to arm, what to do,
    and which tenant takes the hit (``victim=None`` = nobody — the
    verdict's victim-free-tenants-stay-clean check keys on this)."""

    window: int
    name: str
    fault_spec: str = None
    action: str = None
    victim: str = None
    doc: str = ""

    def __post_init__(self):
        if self.action is not None and self.action not in ACTIONS:
            raise ValueError(
                f"chaos window {self.name!r}: unknown action "
                f"{self.action!r} (known: {ACTIONS})")
        if self.fault_spec:
            faults.parse_spec(self.fault_spec)   # fail at construction


class ChaosSchedule:
    """An immutable window → injections map with scoped arming."""

    def __init__(self, windows=()):
        self.windows = tuple(windows)
        self._by_window = {}
        for cw in self.windows:
            self._by_window.setdefault(cw.window, []).append(cw)

    def __len__(self):
        return len(self.windows)

    def for_window(self, w):
        """The injections scheduled in window ``w`` (possibly empty)."""
        return tuple(self._by_window.get(w, ()))

    def victims(self, w):
        """Tenant names any window-``w`` injection targets."""
        return tuple(sorted({cw.victim for cw in self.for_window(w)
                             if cw.victim}))

    @contextlib.contextmanager
    def armed(self, w):
        """Push every window-``w`` fault spec (overlay over whatever is
        already armed), yield, pop them LIFO — failures included."""
        pushed = 0
        try:
            for cw in self.for_window(w):
                if cw.fault_spec:
                    faults.push_spec(cw.fault_spec)
                    pushed += 1
            yield
        finally:
            while pushed:
                faults.pop_spec()
                pushed -= 1

    def describe(self):
        """One line per injection — what `tpu_als soak --plan` prints."""
        lines = []
        for cw in sorted(self.windows, key=lambda c: (c.window, c.name)):
            bits = [f"window {cw.window}: {cw.name}"]
            if cw.action:
                bits.append(f"action={cw.action}")
            if cw.fault_spec:
                bits.append(f"spec={cw.fault_spec!r}")
            if cw.victim:
                bits.append(f"victim={cw.victim}")
            lines.append("  ".join(bits))
        return "\n".join(lines)


def default_schedule(windows, victim="a", subprocesses=True):
    """The production-week placement, scaled to ``windows``: window 0
    stays clean (warmup), the chaos vocabulary lands in order across
    the middle windows, and the last window stays clean (cooldown —
    the verdict's recovery evidence must fit inside the timeline).
    ``subprocesses=False`` drops the two CLI-child injections (preempt,
    device_loss) for fast in-process runs."""
    seq = [
        ChaosWindow(0, "torn-publish", victim=victim,
                    fault_spec="serving.publish=corrupt@once",
                    action="torn_publish",
                    doc="republish tags the victim's int8 index stale; "
                        "requests degrade to the exact path until the "
                        "clean republish"),
        ChaosWindow(0, "poisoned-refit", victim=victim,
                    fault_spec="ingest.record=corrupt@every=5",
                    action="poisoned_refit",
                    doc="the periodic refit's ingest is poisoned every "
                        "5th record; quarantine routes them aside and "
                        "the refit completes"),
        ChaosWindow(0, "solver-rollback", victim=victim,
                    fault_spec="solve.gram=corrupt@nth=2",
                    action="solver_rollback",
                    doc="a guardrails=recover re-fit hits a blown Gram "
                        "solve; sentinel trips, rolls back, publishes"),
        ChaosWindow(0, "tenant-churn", action="tenant_churn",
                    doc="a short-lived tenant registers, serves, and is "
                        "removed while the fleet is under load"),
    ]
    if subprocesses:
        seq.append(ChaosWindow(
            0, "preempt", victim=victim, action="preempt",
            doc="a CLI train child is preempted at an iteration "
                "boundary (exit 43); --resume auto completes"))
        seq.append(ChaosWindow(
            0, "device-loss", victim=victim, action="device_loss",
            doc="an elastic train child loses a device mid-fit; the "
                "ring re-forms on the survivors and the fit completes"))
    # place them across windows 1..windows-2, round-robin if the
    # timeline is shorter than the vocabulary
    slots = max(1, windows - 2)
    placed = []
    for i, cw in enumerate(seq):
        w = 1 + (i % slots)
        placed.append(ChaosWindow(w, cw.name, cw.fault_spec, cw.action,
                                  cw.victim, cw.doc))
    return ChaosSchedule(placed)
