"""Scenario execution: arm chaos, run phases, judge assertions, bank.

The runner is the integration layer ROADMAP item 4 asks for: it takes a
:class:`~tpu_als.scenario.spec.ScenarioSpec` and produces one verdict,
leaving a complete obs trail behind —

- ``scenario_start``  once, with the phase list and effective config,
- ``scenario_phase``  per phase, with its wall-clock seconds,
- ``scenario_assert`` per assertion, with observed vs expected,
- ``scenario_end``    once, with the verdict and total seconds

— so ``tpu_als observe tail`` on a scenario run dir reads as the
production day's story, and the assertions are *re-derivable* from the
events alone.

Fault arming is scoped and STACKED: the spec's ``fault_spec`` is pushed
(``faults.push_spec``) before phase 1, each phase's own ``fault_spec``
is pushed as an overlay around just that phase, and every push is
popped LIFO afterwards, failures included — so chaos windows can re-arm
mid-scenario (the soak chaos schedule) and a failing scenario never
leaks rules into the next one or the enclosing process.  Causal tracing
(``obs.tracing``) is armed over the same window with the same restore
discipline, so every scenario's trail carries complete ``trace_span``
trees (``observe explain`` on a scenario run dir) without flipping the
process-wide default.

``bank_result`` writes ``BENCH_scenario_<name>.json`` with the same
``banked_at`` UTC-provenance contract bench.py and serve-bench use, so
a scenario run on chip is a bankable artifact, not just a green line.
"""

from __future__ import annotations

import shutil
import tempfile

from tpu_als.obs import tracing
from tpu_als.resilience import faults
from tpu_als.scenario.spec import (
    PhaseFailed,
    RunContext,
    ScenarioFailed,
    evaluate_assertion,
    now,
)


def run_scenario(spec, config=None, registry=None, workdir=None,
                 raise_on_fail=False):
    """Run one scenario end to end; returns the result dict.

    ``config`` overrides the spec's defaults per key (CLI flags land
    here).  ``registry`` defaults to the process-wide obs registry.
    ``raise_on_fail=True`` turns a failed verdict into a typed
    :class:`ScenarioFailed` (the CLI prefers checking ``result
    ["passed"]`` so it can print the table first).

    The result dict::

        {"scenario", "passed", "seconds",
         "phases": [{"phase", "seconds"}, ...],
         "assertions": [{"check", "kind", "ok", "observed",
                         "expected", "op"}, ...],
         "config": {...}}
    """
    if registry is None:
        from tpu_als import obs

        registry = obs.default_registry()
    cfg = dict(spec.defaults)
    if config:
        cfg.update({k: v for k, v in config.items() if v is not None})

    own_workdir = workdir is None
    if own_workdir:
        workdir = tempfile.mkdtemp(prefix=f"tpu_als_scenario_{spec.name}_")
    ctx = RunContext(spec, cfg, workdir, registry)

    # counters/events are judged as deltas from here (spec.py docstring)
    baseline = {}
    for a in spec.assertions:
        for name in filter(None, (a.metric, a.num) + tuple(a.den)):
            if a.kind in ("counter", "ratio"):
                baseline[name] = registry.counter_value(name)
    events_start = len(registry._events)

    registry.emit("scenario_start", scenario=spec.name,
                  phases=[p.name for p in spec.phases], config=cfg)
    t_start = now()
    phase_records = []
    tracing_was = tracing.tracing_armed()
    pushed = 0
    try:
        tracing.enable_tracing()
        if spec.fault_spec:
            faults.push_spec(spec.fault_spec)
            pushed += 1
        for phase in spec.phases:
            t0 = now()
            # phase-scoped chaos window: push as an overlay over the
            # scenario-level spec, pop in the finally — LIFO restore,
            # so a failing phase never leaks its rules forward
            if phase.fault_spec:
                faults.push_spec(phase.fault_spec)
                pushed += 1
            try:
                phase.run(ctx)
            except Exception as e:   # noqa: BLE001 — typed + obs-visible
                err = PhaseFailed(spec.name, phase.name, e)
                registry.emit("scenario_end", scenario=spec.name,
                              passed=False, seconds=now() - t_start,
                              error=str(err))
                raise err from e
            finally:
                if phase.fault_spec:
                    faults.pop_spec()
                    pushed -= 1
            phase_records.append(
                {"phase": phase.name, "seconds": round(now() - t0, 4)})
            registry.emit("scenario_phase", scenario=spec.name,
                          phase=phase.name,
                          seconds=phase_records[-1]["seconds"])
    finally:
        # restore the pre-scenario fault state (the env spec, if any)
        # BEFORE teardown so engine drains don't hit armed points
        while pushed:
            faults.pop_spec()
            pushed -= 1
        for e in ctx.run_cleanups():
            registry.emit("warning", what="scenario.cleanup",
                          reason=f"{type(e).__name__}: {e}")
        # disarm AFTER the drains so in-flight tickets finish their
        # trees; restore-only (an operator-armed process stays armed)
        if not tracing_was:
            tracing.disable_tracing()
        if own_workdir:
            shutil.rmtree(workdir, ignore_errors=True)

    assertions = [
        evaluate_assertion(a, ctx, baseline, events_start)
        for a in spec.assertions
    ]
    for rec in assertions:
        registry.emit("scenario_assert", scenario=spec.name, **rec)
    failed = [rec for rec in assertions if not rec["ok"]]
    passed = not failed
    total = round(now() - t_start, 4)
    registry.emit("scenario_end", scenario=spec.name, passed=passed,
                  seconds=total)
    result = {"scenario": spec.name, "passed": passed, "seconds": total,
              "phases": phase_records, "assertions": assertions,
              "facts": dict(ctx.facts), "config": cfg}
    if raise_on_fail and not passed:
        raise ScenarioFailed(spec.name, failed)
    return result


def bank_result(result, path):
    """Write the scenario result as a BENCH-contract JSON artifact:
    ``metric``/``value`` headline plus the full phase/assertion record,
    stamped with absolute-UTC ``banked_at`` provenance (never a
    relative phrase) and the platform it ran on."""
    import datetime as _dt
    import json

    import jax

    banked = {
        "metric": f"scenario_{result['scenario']}",
        "value": 1 if result["passed"] else 0,
        "unit": "pass",
        **result,
        "platform": jax.default_backend(),
        "banked_by": "tpu_als scenario run",
        "banked_at": _dt.datetime.now(
            _dt.timezone.utc).isoformat(timespec="seconds"),
    }
    with open(path, "w") as f:
        json.dump(banked, f, indent=2, default=str)
        f.write("\n")
    return banked


def render_result(result):
    """Human-readable verdict table (the CLI's stdout companion to the
    machine-readable JSON line)."""
    lines = [f"scenario {result['scenario']}: "
             f"{'PASS' if result['passed'] else 'FAIL'} "
             f"({result['seconds']:.2f}s)"]
    for p in result["phases"]:
        lines.append(f"  phase {p['phase']:<24} {p['seconds']:>8.3f}s")
    for a in result["assertions"]:
        mark = "ok  " if a["ok"] else "FAIL"
        detail = f"{a['observed']} {a['op']} {a['expected']}"
        if a.get("error"):
            detail += f"  [{a['error']}]"
        lines.append(f"  {mark} {a['check']:<28} {detail}")
    return "\n".join(lines)
