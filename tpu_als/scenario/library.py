"""The named production-day scenarios.

Every scenario here composes primitives that already exist and are
individually tested — the fault harness (``resilience/faults.py``), the
preemption guard (``resilience/preempt.py``), the serving engine
(``serving/engine.py``), the fold-in server (``stream/microbatch.py``),
sharded degraded serving (``parallel/serve.py``) and checkpoint resume —
into one assertable run each:

``traffic-spike``        10× load step against the serving engine;
                         shed-rate bounded, p99 under the SLO.
``preempt-under-serve``  train + serve in ONE process, SIGTERM lands
                         mid-train; answers keep flowing, resume is
                         bitwise vs an unpreempted run.
``torn-publish``         a corrupt publish tags the int8 index stale and
                         a sharded gather loses a shard; both degrade
                         (exact-path fallback, last-good catalog) with
                         the full obs trail.
``cold-start``           sparse data → fit → new users fold in mid-serve;
                         rating-arrival → servable freshness is bounded.
``preempt-resume``       the chaos_smoke kill-and-resume flow: CLI train
                         preempted at an iteration boundary exits 43,
                         ``--resume auto`` finishes cleanly.
``continuous-freshness`` sustained rating-event stream (new users/items
                         + poison) folds in and publishes incrementally
                         under serve load; freshness p99 ≤ SLO, zero
                         torn publishes, quarantine from the trail.
``flight-recorder``      every request breaches a microsecond SLO; the
                         engine's flight recorder dumps per-request span
                         breakdowns as ``flight_record`` events.
``tenant-isolation``     the multi-tenant fault matrix lands on tenant A
                         (torn publish, poisoned stream, rollback, 10×
                         spike) while tenant B's top-k stays bitwise
                         equal to its solo run, in SLO, zero shed.
``device-loss``          elastic training: a device dies mid-fit, the
                         ring re-forms on the survivors and resumes from
                         the last atomic checkpoint; the final factors
                         are bitwise equal to a fresh shrunk-mesh fit
                         resumed from the same checkpoint.
``production-week``      the soak subsystem end-to-end: zipfian/diurnal
                         traffic drives multi-tenant serve + live
                         fold-in + periodic refit while the chaos
                         schedule lands every injection; the SLO verdict
                         passes AND re-derives identically from the
                         dumped events alone (stdlib verdict.py child).

All run on CPU in seconds (they are tier-1 tests via
tests/test_scenarios.py) and bank ``BENCH_scenario_<name>.json`` on
chip.  Phase bodies import jax lazily so ``scenario list`` and the CLI
error paths stay instant.
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
import time

import numpy as np

from tpu_als.scenario.spec import Assertion, Phase, ScenarioSpec

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


# ---------------------------------------------------------------------------
# shared machinery


class _LoadDriver:
    """Background request driver: submits user-id requests at a fixed
    rate and resolves each ticket, classifying the outcome.  ``shed``
    (Overloaded) and ``expired`` (DeadlineExceeded) are acceptable
    degradations under the scenarios' contracts; anything else is a
    ``hard_failures`` — the bucket the assertions pin to zero."""

    def __init__(self, engine, n_users, rate_hz=100.0, timeout_s=5.0,
                 seed=0):
        self.engine = engine
        self.n_users = n_users
        self.rate_hz = rate_hz
        self.timeout_s = timeout_s
        self.answered = 0
        self.shed = 0
        self.expired = 0
        self.hard_failures = 0
        self._rng = np.random.default_rng(seed)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run,
                                        name="scenario-load", daemon=True)

    def _run(self):
        from tpu_als.serving import DeadlineExceeded, Overloaded

        period = 1.0 / self.rate_hz
        while not self._stop.is_set():
            uid = int(self._rng.integers(0, self.n_users))
            try:
                self.engine.recommend(uid, timeout=self.timeout_s)
                self.answered += 1
            except Overloaded:
                self.shed += 1
            except DeadlineExceeded:
                self.expired += 1
            except Exception:   # noqa: BLE001 — the judged bucket
                self.hard_failures += 1
            self._stop.wait(period)

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        self._thread.join(max(2 * self.timeout_s, 5.0))


def _submit_open_loop(engine, U, qps, duration_s, rng, counts):
    """Open-loop submit at ``qps`` for ``duration_s`` (arrivals follow
    the clock, not completions — serve-bench's honest load model), then
    resolve every admitted ticket.  Mutates ``counts`` in place."""
    from tpu_als.serving import DeadlineExceeded, Overloaded

    n_req = max(1, int(qps * duration_s))
    uids = rng.integers(0, U.shape[0], n_req)
    tickets = []
    t0 = time.perf_counter()
    for j in range(n_req):
        delay = (t0 + j / qps) - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        try:
            tickets.append(engine.submit(int(uids[j])))
        except Overloaded:
            counts["shed"] += 1
    for t in tickets:
        try:
            t.result(timeout=10.0)
            counts["answered"] += 1
        except DeadlineExceeded:
            counts["expired"] += 1
        except Exception:   # noqa: BLE001
            counts["hard_failures"] += 1


def _cli_subprocess(args, env_extra=None):
    """Run the tpu_als CLI in a child process (the preempt scenarios
    need a real exit status).  The repo root rides PYTHONPATH so the
    child resolves the same checkout the parent runs from."""
    env = dict(os.environ)
    env.pop("TPU_ALS_PREEMPT_AT", None)   # only explicit knobs apply
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    if env_extra:
        env.update(env_extra)
    return subprocess.run(
        [sys.executable, "-c",
         "import sys; from tpu_als.cli import main; main(sys.argv[1:])"]
        + list(args),
        capture_output=True, text=True, env=env)


# ---------------------------------------------------------------------------
# traffic-spike


def _spike_publish(ctx):
    from tpu_als.serving import ServingEngine

    c = ctx.config
    rng = np.random.default_rng(c["seed"])
    U = rng.normal(size=(c["users"], c["rank"])).astype(np.float32)
    V = rng.normal(size=(c["items"], c["rank"])).astype(np.float32)
    engine = ServingEngine(k=c["k"], max_queue=c["max_queue"],
                           max_wait_s=c["max_wait_ms"] / 1e3)
    engine.publish(U, V)
    engine.warmup()
    engine.start()
    ctx.defer(engine.stop)
    ctx.state.update(engine=engine, U=U,
                     rng=rng, counts={"answered": 0, "shed": 0,
                                      "expired": 0, "hard_failures": 0})


def _spike_baseline(ctx):
    c, s = ctx.config, ctx.state
    _submit_open_loop(s["engine"], s["U"], c["base_qps"], c["base_s"],
                      s["rng"], s["counts"])


def _spike_spike(ctx):
    c, s = ctx.config, ctx.state
    _submit_open_loop(s["engine"], s["U"],
                      c["base_qps"] * c["spike_mult"], c["spike_s"],
                      s["rng"], s["counts"])
    ctx.facts.update(s["counts"])


def _traffic_spike():
    return ScenarioSpec(
        name="traffic-spike",
        doc="10x open-loop load step against the serving engine: "
            "shed-rate stays bounded, e2e p99 stays under --slo-ms, "
            "and nothing fails hard.",
        defaults=dict(seed=0, users=400, items=2000, rank=16, k=10,
                      max_queue=64, max_wait_ms=2.0,
                      base_qps=40.0, spike_mult=10, base_s=1.0,
                      spike_s=1.5, slo_ms=250.0),
        phases=(
            Phase("publish-and-warmup", _spike_publish,
                  "synthetic factors published, every bucket compiled"),
            Phase("baseline-load", _spike_baseline,
                  "open-loop base_qps for base_s"),
            Phase("spike-load", _spike_spike,
                  "base_qps x spike_mult for spike_s"),
        ),
        assertions=(
            Assertion("e2e_p99_under_slo", "quantile",
                      metric="serving.e2e_seconds", q=0.99,
                      scale_ms=True, op="<=", value="$slo_ms",
                      doc="tail latency through the spike"),
            Assertion("shed_rate_bounded", "ratio",
                      num="serving.shed",
                      den=("serving.shed", "serving.requests"),
                      op="<=", value=0.5,
                      doc="shedding is the valve, not the norm"),
            Assertion("answered_floor", "fact", fact="answered",
                      op=">=", value=50,
                      doc="the spike was actually served, not just shed"),
            Assertion("no_hard_failures", "fact", fact="hard_failures",
                      op="==", value=0),
        ),
    )


# ---------------------------------------------------------------------------
# preempt-under-serve


def _pus_fit_reference(ctx):
    import tpu_als
    from tpu_als.io.movielens import synthetic_movielens

    c = ctx.config
    frame = synthetic_movielens(c["users"], c["items"], c["nnz"],
                                seed=c["seed"])
    ref = tpu_als.ALS(rank=c["rank"], maxIter=c["iters"],
                      regParam=c["reg"], seed=c["seed"]).fit(frame)
    ctx.state.update(frame=frame, ref=ref)


def _pus_serve_start(ctx):
    from tpu_als.serving import ServingEngine

    ref = ctx.state["ref"]
    engine = ServingEngine(k=5)
    engine.publish(np.asarray(ref._U), np.asarray(ref._V))
    engine.warmup()
    engine.start()
    ctx.defer(engine.stop)
    driver = _LoadDriver(engine, n_users=ref._U.shape[0],
                         rate_hz=ctx.config["serve_hz"]).start()
    ctx.defer(driver.stop)
    ctx.state.update(engine=engine, driver=driver)


def _pus_train_preempt(ctx):
    import signal

    import tpu_als
    from tpu_als.resilience import preempt

    c = ctx.config
    ckdir = os.path.join(ctx.workdir, "ck")
    driver = ctx.state["driver"]
    answered_before = driver.answered

    def send_sigterm(iteration, U, V):
        if iteration == c["preempt_at"]:
            # prove answers flow WHILE the trainer is mid-fit before
            # pulling the plug: warm jit caches make these iterations
            # millisecond-fast on CPU, so polling the driver here is
            # the deterministic form of "serving continued during
            # training" (not a race against iteration wall-clock)
            deadline = time.monotonic() + 30.0
            while (driver.answered <= answered_before
                   and time.monotonic() < deadline):
                time.sleep(0.005)
            g = preempt.installed()
            if g is not None and g._installed:
                signal.raise_signal(signal.SIGTERM)
            elif g is not None:
                # non-main-thread harness (guard degrades to the env
                # knob): trigger programmatically instead of letting the
                # raw signal kill the process
                g.trigger(signal.SIGTERM)

    als = tpu_als.ALS(rank=c["rank"], maxIter=c["iters"],
                      regParam=c["reg"], seed=c["seed"],
                      checkpointDir=ckdir, checkpointInterval=100,
                      fitCallback=send_sigterm)
    preempted_at = None
    try:
        with preempt.PreemptionGuard():
            als.fit(ctx.state["frame"])
    except preempt.Preempted as p:
        preempted_at = p.iteration
        ctx.state["ckpt"] = p.checkpoint_path
    ctx.facts["preempted"] = preempted_at is not None
    ctx.facts["preempt_iteration"] = preempted_at
    ctx.facts["served_during_train"] = driver.answered - answered_before


def _pus_resume(ctx):
    import tpu_als

    c = ctx.config
    resumed = tpu_als.ALS(rank=c["rank"], maxIter=c["iters"],
                          regParam=c["reg"], seed=c["seed"],
                          resumeFrom=ctx.state["ckpt"],
                          ).fit(ctx.state["frame"])
    ref = ctx.state["ref"]
    ctx.facts["resume_bitwise"] = bool(
        np.array_equal(np.asarray(resumed._U), np.asarray(ref._U))
        and np.array_equal(np.asarray(resumed._V), np.asarray(ref._V)))


def _pus_serve_stop(ctx):
    driver = ctx.state["driver"]
    driver.stop()
    ctx.facts["serve_answered"] = driver.answered
    ctx.facts["serve_hard_failures"] = driver.hard_failures
    ctx.facts["serve_shed"] = driver.shed + driver.expired


def _preempt_under_serve():
    return ScenarioSpec(
        name="preempt-under-serve",
        doc="train and serve share one process; SIGTERM lands mid-train. "
            "Serving keeps answering throughout (shed/degraded allowed, "
            "hard failures not) and the resumed factors are BITWISE "
            "equal to an unpreempted run.",
        defaults=dict(seed=7, users=80, items=40, nnz=1500, rank=4,
                      iters=6, reg=0.05, preempt_at=3, serve_hz=100.0),
        phases=(
            Phase("fit-reference", _pus_fit_reference,
                  "the unpreempted run the resume must match bitwise"),
            Phase("serve-start", _pus_serve_start,
                  "publish yesterday's model, start the load driver"),
            Phase("train-preempt", _pus_train_preempt,
                  "refit under a PreemptionGuard; SIGTERM at preempt_at"),
            Phase("resume", _pus_resume,
                  "warm-start from the preemption checkpoint"),
            Phase("serve-stop", _pus_serve_stop,
                  "drain the driver, collect the serving verdict"),
        ),
        assertions=(
            Assertion("preempted_at_boundary", "fact", fact="preempted",
                      op="==", value=True),
            Assertion("preempted_event", "event", event="preempted",
                      op=">=", value=1),
            Assertion("resume_bitwise", "fact", fact="resume_bitwise",
                      op="==", value=True,
                      doc="restart-from-factors of a deterministic "
                          "fixed point — anything weaker hides "
                          "divergence"),
            Assertion("served_through_preemption", "fact",
                      fact="served_during_train", op=">=", value=1),
            Assertion("no_hard_failures", "fact",
                      fact="serve_hard_failures", op="==", value=0),
        ),
    )


# ---------------------------------------------------------------------------
# torn-publish


def _torn_publish_good(ctx):
    from tpu_als.serving import ServingEngine

    c = ctx.config
    rng = np.random.default_rng(c["seed"])
    U = rng.normal(size=(c["users"], c["rank"])).astype(np.float32)
    V = rng.normal(size=(c["items"], c["rank"])).astype(np.float32)
    engine = ServingEngine(k=c["k"], shortlist_k=c["shortlist_k"])
    engine.publish(U, V)           # serving.publish hit 1: clean
    engine.warmup()
    engine.start()
    ctx.defer(engine.stop)
    engine.recommend(0, timeout=10.0)   # int8 path sanity
    ctx.state.update(engine=engine, U=U, rng=rng)


def _torn_publish_torn(ctx):
    import jax.numpy as jnp

    from tpu_als.ops.topk import chunked_topk_scores

    c = ctx.config
    engine, U, rng = (ctx.state[k] for k in ("engine", "U", "rng"))
    V2 = rng.normal(size=(c["items"], c["rank"])).astype(np.float32)
    engine.publish(U, V2)          # serving.publish hit 2: torn (stale)
    s, ix = engine.recommend(1, timeout=10.0)
    ref_s, ref_ix = chunked_topk_scores(
        jnp.asarray(U[1:2]), jnp.asarray(V2),
        jnp.ones(c["items"], bool), c["k"],
        item_chunk=min(8192, c["items"]))
    # indices bitwise; scores allclose only — the engine scores a PADDED
    # batch, so the matmul reduction order differs from the 1-row
    # reference in the low-order bits
    ctx.facts["exact_path_match"] = bool(
        np.array_equal(ix, np.asarray(ref_ix)[0])
        and np.allclose(s, np.asarray(ref_s)[0], rtol=1e-5, atol=1e-6))
    ctx.state["V2"] = V2


def _torn_sharded_degrade(ctx):
    from tpu_als.parallel import serve
    from tpu_als.parallel.mesh import make_mesh

    U, V2 = ctx.state["U"], ctx.state["V2"]
    mesh = make_mesh()
    serve.topk_sharded(U, V2, 5, mesh)       # serve.gather hit 1: clean,
    #                                          primes the last-good catalog
    _, _, info = serve.topk_sharded(U, V2, 5, mesh,
                                    return_info=True)   # hit 2: shard lost
    ctx.facts["sharded_degraded"] = bool(info["degraded"])


def _torn_publish():
    return ScenarioSpec(
        name="torn-publish",
        doc="a publish is torn by fault injection (the new int8 index is "
            "tagged stale) and a sharded gather loses a shard: serving "
            "falls back to the exact path / the last-good catalog, and "
            "the serve.degraded + serving_publish obs trail is emitted.",
        fault_spec=("serving.publish=corrupt@nth=2;"
                    "serve.gather=corrupt@nth=2"),
        defaults=dict(seed=0, users=64, items=300, rank=16, k=10,
                      shortlist_k=64),
        phases=(
            Phase("publish-good", _torn_publish_good,
                  "generation 1: quantized index, int8 path serves"),
            Phase("torn-publish", _torn_publish_torn,
                  "generation 2 is torn; requests take the exact path"),
            Phase("sharded-degrade", _torn_sharded_degrade,
                  "a sharded gather fails; last-good catalog answers"),
        ),
        assertions=(
            Assertion("exact_fallback_counted", "counter",
                      metric="serving.fallback_exact", op=">=", value=1),
            Assertion("publish_trail", "event", event="serving_publish",
                      op=">=", value=2),
            Assertion("exact_path_match", "fact",
                      fact="exact_path_match", op="==", value=True,
                      doc="the stale-index fallback serves the exact "
                          "kernel's answer, bitwise"),
            Assertion("sharded_degraded", "fact",
                      fact="sharded_degraded", op="==", value=True),
            Assertion("degraded_counted", "counter",
                      metric="serve.degraded", op=">=", value=1),
            Assertion("degraded_event", "event", event="serve_degraded",
                      op=">=", value=1),
        ),
    )


# ---------------------------------------------------------------------------
# cold-start


def _cold_fit(ctx):
    import tpu_als
    from tpu_als.io.movielens import synthetic_movielens

    c = ctx.config
    frame = synthetic_movielens(c["users"], c["items"], c["nnz"],
                                seed=c["seed"])
    model = tpu_als.ALS(rank=c["rank"], maxIter=c["iters"],
                        regParam=0.05, seed=c["seed"]).fit(frame)
    ctx.state["model"] = model


def _cold_serve_start(ctx):
    from tpu_als.serving import ServingEngine
    from tpu_als.stream.microbatch import FoldInServer
    from tpu_als.core.ratings import _next_pow2

    c = ctx.config
    model = ctx.state["model"]
    engine = ServingEngine(k=c["k"])
    engine.publish(np.asarray(model._U), np.asarray(model._V))
    engine.warmup()
    engine.start()
    ctx.defer(engine.stop)
    engine.recommend(0, timeout=10.0)   # pre-fold-in serving sanity
    srv = FoldInServer(model)
    # production startup discipline: the fold-in kernel shapes the new-
    # user batch will need are compiled BEFORE traffic arrives, so the
    # measured freshness window is fold-in + republish + serve, not jit
    srv.prewarm(rows=(_next_pow2(c["new_users"]),),
                widths=(_next_pow2(c["ratings_per"]),))
    ctx.state.update(engine=engine, srv=srv)


def _cold_foldin_serve(ctx):
    from tpu_als.utils.frame import ColumnarFrame

    c = ctx.config
    model, engine, srv = (ctx.state[k] for k in ("model", "engine", "srv"))
    rng = np.random.default_rng(c["seed"] + 1)
    base = int(np.asarray(model._user_map.ids).max()) + 1000
    new_raw = np.repeat(np.arange(base, base + c["new_users"]),
                        c["ratings_per"])
    items = rng.choice(np.asarray(model._item_map.ids),
                       size=len(new_raw))
    batch = ColumnarFrame({
        "user": new_raw, "item": items,
        "rating": rng.uniform(0.5, 5.0, len(new_raw)).astype(np.float32),
    })
    t_arrival = time.perf_counter()
    srv.update(batch)                                  # fold in
    engine.publish(np.asarray(model._U), np.asarray(model._V))
    new_dense = int(model._user_map.to_dense(
        np.array([base]))[0])
    s, ix = engine.recommend(new_dense, timeout=30.0)  # first servable
    freshness = time.perf_counter() - t_arrival

    from tpu_als import obs

    obs.histogram("scenario.freshness_seconds", freshness)
    ctx.facts["freshness_ms"] = round(freshness * 1e3, 3)
    ctx.facts["new_user_served"] = bool(
        len(s) == c["k"] and np.isfinite(np.asarray(s)).all())


def _cold_start():
    return ScenarioSpec(
        name="cold-start",
        doc="sparse synthetic data -> fit -> serve; NEW users arrive as a "
            "rating micro-batch mid-serve and must become servable "
            "(fold-in + republish) within the freshness bound.",
        defaults=dict(seed=11, users=48, items=32, nnz=600, rank=8,
                      iters=3, k=5, new_users=6, ratings_per=4,
                      freshness_slo_ms=5000.0),
        phases=(
            Phase("fit-base", _cold_fit,
                  "ALS on the sparse base dataset"),
            Phase("serve-start", _cold_serve_start,
                  "publish, warm the engine AND the fold-in shapes"),
            Phase("foldin-and-serve", _cold_foldin_serve,
                  "new users' ratings arrive; fold in, republish, serve"),
        ),
        assertions=(
            Assertion("freshness_under_bound", "fact",
                      fact="freshness_ms", op="<=",
                      value="$freshness_slo_ms",
                      doc="rating-arrival -> servable latency"),
            Assertion("freshness_recorded", "counter",
                      metric="foldin.ratings", op=">=", value=1),
            Assertion("new_user_served", "fact",
                      fact="new_user_served", op="==", value=True),
            Assertion("republished", "event", event="serving_publish",
                      op=">=", value=2),
        ),
    )


# ---------------------------------------------------------------------------
# preempt-resume (the chaos_smoke stage-3 flow, now with ONE
# implementation: the shell script and the pytest port both run this)


def _pr_preempt(ctx):
    from tpu_als.resilience.preempt import EXIT_PREEMPTED

    c = ctx.config
    ckdir = os.path.join(ctx.workdir, "ck")
    base = ["train", "--data", c["data"], "--rank", str(c["rank"]),
            "--max-iter", str(c["iters"]), "--reg-param", str(c["reg"]),
            "--seed", str(c["seed"]), "--checkpoint-dir", ckdir]
    ctx.state["base"] = base
    p = _cli_subprocess(
        base, env_extra={"TPU_ALS_PREEMPT_AT": str(c["preempt_at"])})
    ctx.facts["preempt_exit_code"] = p.returncode
    ctx.facts["preempt_exit_expected"] = EXIT_PREEMPTED
    ctx.state["preempt_stderr"] = p.stderr


def _pr_resume(ctx):
    out = os.path.join(ctx.workdir, "model")
    p = _cli_subprocess(ctx.state["base"]
                        + ["--resume", "auto", "--output", out])
    ctx.facts["resume_exit_code"] = p.returncode
    ctx.facts["resume_discovered"] = "resuming from" in p.stderr
    ctx.facts["model_saved"] = os.path.isfile(
        os.path.join(out, "manifest.json"))
    ctx.state["resume_stderr"] = p.stderr


def _preempt_resume():
    from tpu_als.resilience.preempt import EXIT_PREEMPTED

    return ScenarioSpec(
        name="preempt-resume",
        doc="the end-to-end kill-and-resume train: a CLI train preempted "
            "at an iteration boundary (deterministic TPU_ALS_PREEMPT_AT "
            "knob) exits 43 with a checkpoint on disk; the SAME command "
            "with --resume auto discovers it and finishes cleanly.",
        defaults=dict(data="synthetic:80x40x1500", rank=4, iters=6,
                      reg=0.05, seed=7, preempt_at=3),
        phases=(
            Phase("preempt", _pr_preempt,
                  "train killed at the preempt_at iteration boundary"),
            Phase("resume", _pr_resume,
                  "--resume auto discovers the checkpoint and finishes"),
        ),
        assertions=(
            Assertion("preempt_exit_43", "fact", fact="preempt_exit_code",
                      op="==", value=EXIT_PREEMPTED,
                      doc="the orchestrator-visible 'reschedule me' "
                          "status, distinct from failure"),
            Assertion("resume_exit_0", "fact", fact="resume_exit_code",
                      op="==", value=0),
            Assertion("resume_discovered_checkpoint", "fact",
                      fact="resume_discovered", op="==", value=True),
            Assertion("model_saved", "fact", fact="model_saved",
                      op="==", value=True),
        ),
    )


# ---------------------------------------------------------------------------
# flight-recorder


def _fr_publish(ctx):
    from tpu_als.serving import ServingEngine

    c = ctx.config
    rng = np.random.default_rng(c["seed"])
    U = rng.normal(size=(c["users"], c["rank"])).astype(np.float32)
    V = rng.normal(size=(c["items"], c["rank"])).astype(np.float32)
    # a microsecond SLO no real request can meet: every served batch is
    # a breach, so the recorder's dump path runs on ordinary traffic
    engine = ServingEngine(k=c["k"], slo_s=c["slo_us"] / 1e6)
    engine.publish(U, V)
    engine.warmup()
    engine.start()
    ctx.defer(engine.stop)
    ctx.state.update(engine=engine, U=U, rng=rng,
                     counts={"answered": 0, "shed": 0, "expired": 0,
                             "hard_failures": 0})


def _fr_load(ctx):
    c, s = ctx.config, ctx.state
    _submit_open_loop(s["engine"], s["U"], c["qps"], c["load_s"],
                      s["rng"], s["counts"])
    ctx.facts.update(s["counts"])


def _fr_collect(ctx):
    from tpu_als import obs
    from tpu_als.obs.trace import SPAN_KEYS

    reg = obs.default_registry()
    records = [e for e in reg._events
               if e.get("type") == "flight_record"]
    # the acceptance shape: an slo_breach dump whose record carries the
    # FULL per-request span breakdown (rescore stays None — it is fused
    # into the int8 top-k kernel and not separately fenceable)
    complete = [
        r for r in records
        if r.get("trigger") == "slo_breach" and r.get("status") == "ok"
        and set(r.get("spans") or ()) == set(SPAN_KEYS)
        and all(r["spans"][k] is not None
                for k in ("admission", "queue_wait", "score", "respond"))]
    ctx.facts["flight_records"] = len(records)
    ctx.facts["complete_breach_records"] = len(complete)


def _flight_recorder():
    return ScenarioSpec(
        name="flight-recorder",
        doc="force an SLO breach on every request (microsecond slo_us) "
            "and assert the serving flight recorder dumps full "
            "per-request span breakdowns as flight_record events.",
        defaults=dict(seed=0, users=200, items=800, rank=16, k=10,
                      slo_us=1.0, qps=200.0, load_s=0.1),
        phases=(
            Phase("publish-and-warmup", _fr_publish,
                  "synthetic factors behind a microsecond SLO"),
            Phase("load", _fr_load,
                  "open-loop traffic; every answer is a breach"),
            Phase("collect", _fr_collect,
                  "count dumped records, check span completeness"),
        ),
        assertions=(
            Assertion("flight_records_dumped", "event",
                      event="flight_record", op=">=", value=8,
                      doc="the last-N trace ring reached the obs trail"),
            Assertion("span_breakdown_complete", "fact",
                      fact="complete_breach_records", op=">=", value=8,
                      doc="each record carries admission/queue_wait/"
                          "score/respond timings"),
            Assertion("requests_served", "counter",
                      metric="serving.requests", op=">=", value=12),
            Assertion("no_hard_failures", "fact", fact="hard_failures",
                      op="==", value=0),
        ),
    )


# ---------------------------------------------------------------------------
# solver-divergence


def _sd_problem(c):
    from tpu_als.core.ratings import build_csr_buckets

    rng = np.random.default_rng(c["seed"])
    u = rng.integers(0, c["users"], c["nnz"])
    i = rng.integers(0, c["items"], c["nnz"])
    r = rng.uniform(0.5, 5.0, c["nnz"]).astype(np.float32)
    ucsr = build_csr_buckets(u, i, r, c["users"], min_width=4,
                             chunk_elems=1 << 12)
    icsr = build_csr_buckets(i, u, r, c["items"], min_width=4,
                             chunk_elems=1 << 12)
    return u, i, r, ucsr, icsr


def _fit_rmse(U, V, u, i, r):
    U, V = np.asarray(U), np.asarray(V)
    pred = np.einsum("nr,nr->n", U[u], V[i])
    return float(np.sqrt(np.mean((pred - r) ** 2)))


def _sd_divergent(ctx):
    from tpu_als.core.als import AlsConfig, train
    from tpu_als.resilience import guardrails

    c = ctx.config
    u, i, r, ucsr, icsr = _sd_problem(c)
    cfg = AlsConfig(rank=c["rank"], max_iter=c["iters"],
                    reg_param=c["reg"], seed=c["seed"])
    ctx.state.update(u=u, i=i, r=r, ucsr=ucsr, icsr=icsr, cfg=cfg)
    with guardrails.scoped("recover"):
        U, V = train(ucsr, icsr, cfg)
    ctx.facts["recovered_finite"] = bool(
        np.isfinite(np.asarray(U)).all()
        and np.isfinite(np.asarray(V)).all())
    ctx.facts["recovered_rmse"] = _fit_rmse(U, V, u, i, r)


def _sd_clean(ctx):
    from tpu_als.core.als import train

    s = ctx.state
    # the divergent phase consumed the nth=3 firing (nth schedules fire
    # exactly once), so the still-armed spec can never fire here
    U, V = train(s["ucsr"], s["icsr"], s["cfg"])
    clean = _fit_rmse(U, V, s["u"], s["i"], s["r"])
    ctx.facts["clean_rmse"] = clean
    ctx.facts["rmse_ratio"] = ctx.facts["recovered_rmse"] / clean


def _solver_divergence():
    return ScenarioSpec(
        name="solver-divergence",
        doc="a NaN poisoned into the factors mid-train (solve.gram "
            "corrupt at iteration 3) must trip the nonfinite sentinel, "
            "roll back to the last-good snapshot, and finish with final "
            "RMSE inside the clean-run band — the --guardrails recover "
            "contract (docs/resilience.md).",
        fault_spec="solve.gram=corrupt@nth=3",
        defaults=dict(seed=0, users=300, items=200, nnz=5000, rank=8,
                      iters=6, reg=0.1, rmse_band=1.2),
        phases=(
            Phase("divergent-fit", _sd_divergent,
                  "guardrails=recover train with the mid-train NaN"),
            Phase("clean-fit", _sd_clean,
                  "reference run, same config, fault already consumed"),
        ),
        assertions=(
            Assertion("sentinel_tripped", "event",
                      event="guardrail_tripped", op=">=", value=1,
                      doc="the nonfinite sentinel fired at the poisoned "
                          "iteration's boundary"),
            Assertion("rolled_back", "event", event="train_rollback",
                      op=">=", value=1),
            Assertion("rollback_counted", "counter",
                      metric="train.rollbacks", op=">=", value=1),
            Assertion("recovered_factors_finite", "fact",
                      fact="recovered_finite", op="==", value=True),
            Assertion("rmse_within_clean_band", "fact",
                      fact="rmse_ratio", op="<=", value="$rmse_band",
                      doc="recovered fit quality vs the clean reference"),
        ),
    )


# ---------------------------------------------------------------------------
# poisoned-stream


def _ps_write(ctx):
    c = ctx.config
    rng = np.random.default_rng(c["seed"])
    u = rng.integers(0, c["users"], c["rows"])
    i = rng.integers(0, c["items"], c["rows"])
    r = rng.uniform(0.5, 5.0, c["rows"]).astype(np.float32)
    path = os.path.join(ctx.workdir, "ratings.csv")
    with open(path, "wb") as f:
        for k in range(c["rows"]):
            f.write(f"u{u[k]},i{i[k]},{r[k]:.4f}\n".encode())
    ctx.state.update(path=path, u=u, i=i, r=r)


def _ps_ingest(ctx):
    from tpu_als import obs
    from tpu_als.io.stream import stream_ingest
    from tpu_als.resilience import faults

    c0 = obs.counter_value("ingest.quarantined_rows")
    uo, io_, ro, ul, il = stream_ingest(ctx.state["path"],
                                        quarantine=True)
    quarantined = obs.counter_value("ingest.quarantined_rows") - c0
    injected = faults.hits("ingest.record")[1]
    ctx.state.update(uo=uo, io=io_, ro=ro, ul=ul, il=il)
    ctx.facts["injected_records"] = int(injected)
    ctx.facts["quarantined_rows"] = int(quarantined)
    ctx.facts["quarantined_equals_injected"] = \
        int(quarantined) == int(injected)
    ctx.facts["rows_out"] = int(len(ro))
    ctx.facts["survivors_finite"] = bool(np.isfinite(ro).all())


def _ps_fit(ctx):
    from tpu_als.core.als import AlsConfig, train
    from tpu_als.core.ratings import build_csr_buckets

    c, s = ctx.config, ctx.state
    cfg = AlsConfig(rank=c["rank"], max_iter=c["iters"],
                    reg_param=c["reg"], seed=c["seed"])

    def fit_rmse(u, i, r, nu, ni):
        ucsr = build_csr_buckets(u, i, r, nu, min_width=4,
                                 chunk_elems=1 << 12)
        icsr = build_csr_buckets(i, u, r, ni, min_width=4,
                                 chunk_elems=1 << 12)
        U, V = train(ucsr, icsr, cfg)
        return _fit_rmse(U, V, u, i, r)

    # survivors: the ~99% that passed quarantine, in local dense ids
    survivor = fit_rmse(s["uo"], s["io"], s["ro"],
                        len(s["ul"]), len(s["il"]))
    # reference: the full clean arrays the csv was synthesized from
    clean = fit_rmse(s["u"], s["i"], s["r"], c["users"], c["items"])
    ctx.facts["survivor_rmse"] = survivor
    ctx.facts["clean_rmse"] = clean
    ctx.facts["rmse_ratio"] = survivor / clean


def _poisoned_stream():
    return ScenarioSpec(
        name="poisoned-stream",
        doc="a ~1%-poisoned rating stream (ingest.record corrupt every "
            "100 records) must quarantine EVERY bad record — sink + "
            "counter == injected count, exactly — while the surviving "
            "99% fit to the clean run's quality (docs/resilience.md "
            "quarantine).",
        fault_spec="ingest.record=corrupt@every=100",
        defaults=dict(seed=0, users=120, items=80, rows=4000, rank=8,
                      iters=5, reg=0.1, rmse_band=1.1),
        phases=(
            Phase("write-stream", _ps_write,
                  "synthesize the rating csv"),
            Phase("poisoned-ingest", _ps_ingest,
                  "stream_ingest with quarantine on; the armed fault "
                  "point poisons the scheduled records pre-parse"),
            Phase("fit-survivors", _ps_fit,
                  "train on the surviving rows vs the clean reference"),
        ),
        assertions=(
            Assertion("poison_injected", "fact", fact="injected_records",
                      op=">=", value=20,
                      doc="the chaos schedule actually fired (~1% of "
                          "the stream)"),
            Assertion("all_poison_quarantined", "fact",
                      fact="quarantined_equals_injected", op="==",
                      value=True,
                      doc="quarantine counter == injected count"),
            Assertion("quarantine_counted", "counter",
                      metric="ingest.quarantined_rows", op=">=", value=1),
            Assertion("quarantine_event", "event",
                      event="ingest_quarantined", op=">=", value=1),
            Assertion("survivors_finite", "fact", fact="survivors_finite",
                      op="==", value=True),
            Assertion("fit_quality_unchanged", "fact", fact="rmse_ratio",
                      op="<=", value="$rmse_band"),
        ),
    )


# ---------------------------------------------------------------------------
# continuous-freshness


def _cf_start(ctx):
    import tpu_als
    from tpu_als.core.ratings import _next_pow2
    from tpu_als.io.movielens import synthetic_movielens
    from tpu_als.live import LiveUpdater
    from tpu_als.serving import ServingEngine
    from tpu_als.stream.microbatch import FoldInServer

    c = ctx.config
    frame = synthetic_movielens(c["users"], c["items"], c["nnz"],
                                seed=c["seed"])
    model = tpu_als.ALS(rank=c["rank"], maxIter=c["iters"],
                        regParam=0.05, seed=c["seed"]).fit(frame)
    engine = ServingEngine(k=c["k"])
    engine.publish(np.asarray(model._U), np.asarray(model._V))
    engine.warmup()
    engine.start()
    ctx.defer(engine.stop)
    srv = FoldInServer(model)
    # the cold-start discipline scaled up: every (rows, width) shape the
    # sustained stream can produce compiles BEFORE traffic, so measured
    # freshness is fold-in + publish, never jit.  Both fold directions
    # (fold_items streams touch the item side too), widths up to 4
    # (history merge accretes ratings per entity across batches), and
    # one table doubling of headroom (appended users push the fixed-U
    # pad past its pow2 mid-stream otherwise).
    rows, m = [], c["max_batch"]
    while m >= 1:
        rows.append(_next_pow2(m))
        m //= 2
    srv.prewarm(rows=tuple(sorted(set(rows))), widths=(1, 2, 4),
                sides=("user", "item"), growth=1)
    updater = LiveUpdater(
        engine, srv, max_batch=c["max_batch"],
        max_wait_ms=c["max_wait_ms"], fold_items=True,
        slo_s=c["freshness_slo_ms"] / 1e3)
    updater.start()
    ctx.defer(updater.stop)           # LIFO: updater stops before engine
    ctx.state.update(model=model, engine=engine, srv=srv,
                     updater=updater,
                     base_items=engine.published_index.n_items)


def _cf_stream(ctx):
    from tpu_als.serving import Overloaded

    c, s = ctx.config, ctx.state
    model, updater = s["model"], s["updater"]
    rng = np.random.default_rng(c["seed"] + 1)
    driver = _LoadDriver(s["engine"],
                         n_users=np.asarray(model._U).shape[0],
                         rate_hz=c["serve_qps"], seed=c["seed"])
    driver.start()
    user_ids = np.asarray(model._user_map.ids)
    item_ids = np.asarray(model._item_map.ids)
    new_user_base = int(user_ids.max()) + 1000
    new_item_base = int(item_ids.max()) + 1000
    n_events = max(1, int(c["update_qps"] * c["stream_s"]))
    # schedule the poison deterministically inside the stream
    poison_at = set(np.linspace(1, n_events - 1, int(c["poison_events"]),
                                dtype=int).tolist())
    shed = 0
    first_new_user = None
    t0 = time.perf_counter()
    for j in range(n_events):
        delay = (t0 + j / c["update_qps"]) - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        if j in poison_at:
            ev = (int(rng.choice(user_ids)), int(rng.choice(item_ids)),
                  float("nan"))
        elif j % 11 == 3:   # a NEW user joins the service
            ev = (new_user_base + j, int(rng.choice(item_ids)),
                  float(rng.uniform(0.5, 5.0)))
        elif j % 17 == 5:   # a NEW item enters the catalog
            ev = (int(rng.choice(user_ids)), new_item_base + j,
                  float(rng.uniform(0.5, 5.0)))
        else:               # known user rates a known item
            ev = (int(rng.choice(user_ids)), int(rng.choice(item_ids)),
                  float(rng.uniform(0.5, 5.0)))
        try:
            updater.submit(*ev)
            if (first_new_user is None and j not in poison_at
                    and j % 11 == 3):
                first_new_user = ev[0]
        except Overloaded:
            shed += 1
    # drain: every admitted event must reach a publish before judging
    deadline = time.perf_counter() + 30.0
    while updater.queue_depth and time.perf_counter() < deadline:
        time.sleep(0.02)
    time.sleep(2.5 * c["max_wait_ms"] / 1e3)   # the in-flight batch
    driver.stop()
    ctx.facts.update(events=n_events, update_shed=shed,
                     answered=driver.answered,
                     hard_failures=driver.hard_failures)
    ctx.state["new_user_raw"] = first_new_user


def _cf_collect(ctx):
    from tpu_als import obs

    s = ctx.state
    reg = obs.default_registry()
    updates = [e for e in reg._events if e.get("type") == "live_update"]
    ctx.facts["live_updates"] = len(updates)
    # zero torn publishes, structurally: every live publish after the
    # bootstrap one is incremental (retag/delta/compact) — a "full"
    # mode here would mean the pipeline lost its index and silently
    # paid O(catalog)
    ctx.facts["all_incremental"] = bool(updates) and all(
        e.get("mode") in ("retag", "delta", "compact") for e in updates)
    # the fold-ins are servable: a user who EXISTS only via the stream
    # answers from the published tables
    nur = s.get("new_user_raw")
    new_dense = (-1 if nur is None else
                 int(s["model"]._user_map.to_dense(np.array([nur]))[0]))
    ctx.facts["new_user_known"] = new_dense >= 0
    if new_dense >= 0:
        sc, _ = s["engine"].recommend(new_dense, timeout=10.0)
        ctx.facts["new_user_served"] = bool(
            np.isfinite(np.asarray(sc)).all())
    else:
        ctx.facts["new_user_served"] = False
    idx = s["engine"].published_index
    ctx.facts["catalog_grew"] = bool(
        idx is not None and idx.n_items > s["base_items"])
    # explainability is itself an assertion: at least one admitted
    # rating event must have a COMPLETE causal trail in the obs events
    # — admit -> queue -> foldin -> publish -> visible — the exact
    # spans `observe explain` rebuilds a breach from (docs/
    # observability.md).  Judged from reg._events, like everything else.
    full_chain = {"live.admit", "live.queue", "live.foldin",
                  "live.publish", "live.visible"}
    names_by_trace = {}
    for e in reg._events:
        if e.get("type") == "trace_span" and e.get("trace_id"):
            names_by_trace.setdefault(e["trace_id"], set()).add(
                e.get("name"))
    ctx.facts["explainable_traces"] = sum(
        1 for names in names_by_trace.values()
        if full_chain <= names)


def _continuous_freshness():
    return ScenarioSpec(
        name="continuous-freshness",
        doc="the live pipeline end to end: a sustained rating-event "
            "stream (new users, new items, poisoned events) folds in "
            "and publishes INCREMENTALLY under concurrent serve load; "
            "freshness p99 holds the SLO, every publish after bootstrap "
            "is retag/delta/compact (zero torn publishes, zero "
            "O(catalog) rebuilds), and the poison count is re-derivable "
            "from the obs trail alone.",
        defaults=dict(seed=13, users=64, items=48, nnz=800, rank=8,
                      iters=3, k=5, serve_qps=60.0, update_qps=150.0,
                      stream_s=1.2, max_batch=32, max_wait_ms=25.0,
                      poison_events=3,
                      # Judged against an obs-histogram QUANTILE, which
                      # reports bucket upper bounds on the x10^0.25 grid
                      # (... 3162, 5623, 10000 ms) — an SLO between
                      # rungs is unimplementable (5000 silently meant
                      # 3162).  Sit on the rung: p99 bucket <= 5623 ms.
                      freshness_slo_ms=5623.5),
        phases=(
            Phase("fit-and-start", _cf_start,
                  "fit, publish, warm serve + fold-in shapes, start "
                  "the live updater"),
            Phase("stream-under-serve", _cf_stream,
                  "sustained update stream with poison, against live "
                  "request load; drain before judging"),
            Phase("collect", _cf_collect,
                  "freshness, publish modes, and servability from the "
                  "obs trail"),
        ),
        assertions=(
            Assertion("freshness_p99_under_slo", "quantile",
                      metric="live.freshness_seconds", q=0.99,
                      scale_ms=True, op="<=", value="$freshness_slo_ms",
                      doc="rating-arrival -> servable p99 vs the SLO"),
            Assertion("zero_torn_publishes", "counter",
                      metric="serving.fallback_exact", op="==", value=0,
                      doc="no request ever saw a stale index"),
            Assertion("all_publishes_incremental", "fact",
                      fact="all_incremental", op="==", value=True),
            Assertion("poison_quarantined_exactly", "counter",
                      metric="ingest.quarantined_rows", op="==",
                      value="$poison_events",
                      doc="quarantine count == injected poison, from "
                          "the counter alone"),
            Assertion("quarantine_event", "event",
                      event="ingest_quarantined", op=">=", value=1),
            Assertion("live_updates_flowed", "event", event="live_update",
                      op=">=", value=2),
            Assertion("stream_new_user_served", "fact",
                      fact="new_user_served", op="==", value=True),
            Assertion("catalog_grew", "fact", fact="catalog_grew",
                      op="==", value=True,
                      doc="new items appended via the delta segment"),
            Assertion("no_hard_failures", "fact", fact="hard_failures",
                      op="==", value=0),
            Assertion("traces_explainable", "fact",
                      fact="explainable_traces", op=">=", value=1,
                      doc="at least one rating event's full causal "
                          "trail (admit->queue->foldin->publish->"
                          "visible) is reconstructible from the obs "
                          "events alone"),
        ),
    )


# ---------------------------------------------------------------------------
# tenant-isolation


def _ti_solo(ctx):
    """Tenant B alone: publish its factors into a solo engine and serve
    the seeded query set synchronously — the bitwise reference the
    multi-tenant run must reproduce under a fault storm on A."""
    from tpu_als import plan as _plan
    from tpu_als.serving import ServingEngine

    c = ctx.config
    rng = np.random.default_rng(c["seed"])
    Ub = rng.normal(size=(c["users"], c["rank"])).astype(np.float32)
    Vb = rng.normal(size=(c["items"], c["rank"])).astype(np.float32)
    uids = np.random.default_rng(c["seed"] + 1).integers(
        0, c["users"], c["n_queries"])
    # the same planner resolution the registry applies to tenant B —
    # bitwise equality needs the same bucket ladder, hence the same
    # padded shapes and compiled executables
    tplan = _plan.resolve_tenant_plan(rank=c["rank"],
                                      n_users=c["users"],
                                      n_items=c["items"])
    solo = ServingEngine(k=c["k"], buckets=tplan["buckets"])
    solo.publish(Ub, Vb)
    solo.warmup()
    results = []
    for uid in uids:
        # one ticket per batch, drained synchronously — the multi-tenant
        # driver blocks per request, so its batches are 1-row too and
        # the compiled (bucket=1) path is byte-identical across runs
        t = solo.submit(int(uid))
        solo.serve_batch(solo.batcher.next_batch(timeout=0))
        s, ix = t.result(timeout=10.0)
        results.append((np.asarray(s).copy(), np.asarray(ix).copy()))
    solo.stop()
    ctx.state.update(Ub=Ub, Vb=Vb, uids=uids, solo_results=results)


def _ti_start(ctx):
    """Two tenants behind one front door: A with the full live stack
    (its own model, fold-in, updater) and a deliberately small admission
    queue; B with the SAME factors the solo run served."""
    import tpu_als
    from tpu_als import obs
    from tpu_als.io.movielens import synthetic_movielens
    from tpu_als.stream.microbatch import FoldInServer
    from tpu_als.tenancy import MultiTenantEngine, TenantSpec

    c = ctx.config
    frame = synthetic_movielens(c["a_users"], c["a_items"], c["a_nnz"],
                                seed=c["seed"] + 2)
    model = tpu_als.ALS(rank=c["rank"], maxIter=2, regParam=0.05,
                        seed=c["seed"]).fit(frame)
    eng = MultiTenantEngine()
    eng.add_tenant(
        TenantSpec(name="a", max_queue=c["a_max_queue"]),
        np.asarray(model._U), np.asarray(model._V))
    eng.add_tenant(TenantSpec(name="b", k=c["k"]), ctx.state["Ub"],
                   ctx.state["Vb"])
    eng.warmup()
    srv = FoldInServer(model)
    eng.attach_live("a", srv, max_batch=16, max_wait_ms=10.0)
    eng.start()
    ctx.defer(eng.stop)
    # per-tenant baselines: the facts judge DELTAS over this scenario,
    # not whatever the registry accumulated before it
    ctx.state.update(
        eng=eng, model=model,
        base=dict(
            b_shed=obs.counter_value("serving.shed", tenant="b"),
            a_shed=obs.counter_value("serving.shed", tenant="a"),
            a_exact=obs.counter_value("serving.fallback_exact",
                                      tenant="a")))


def _ti_storm(ctx):
    """The storm, aimed at A only, while B's seeded queries run: a 10×
    spike past A's queue budget, a torn publish into A's seq-space, NaN
    poison into A's live stream, and a guardrails=recover re-fit with a
    mid-train corrupt — every fault armed in-phase and cleared, so only
    A's lifecycle can observe it."""
    from tpu_als.core.als import AlsConfig, train
    from tpu_als.core.ratings import build_csr_buckets
    from tpu_als.resilience import faults, guardrails
    from tpu_als.tenancy import TenantOverloaded

    c, s = ctx.config, ctx.state
    eng, model = s["eng"], s["model"]
    b_results, b_errors = [], []

    def drive_b():
        t0 = time.perf_counter()
        for j, uid in enumerate(s["uids"]):
            delay = (t0 + j / c["b_qps"]) - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            try:
                sc, ix = eng.recommend("b", int(uid), timeout=10.0)
                b_results.append((np.asarray(sc).copy(),
                                  np.asarray(ix).copy()))
            except Exception as e:   # noqa: BLE001 — the judged bucket
                b_errors.append(type(e).__name__)

    driver = threading.Thread(target=drive_b, name="scenario-tenant-b",
                              daemon=True)
    driver.start()

    # 1. traffic spike vs A's small queue: its typed shed, nobody else's
    spike_shed = 0
    tickets = []
    for _ in range(c["spike_submits"]):
        try:
            tickets.append(eng.submit("a", 0))
        except TenantOverloaded as e:
            assert e.tenant == "a"
            spike_shed += 1

    # 2. torn publish into A's seq-space: the corrupt tags A's int8
    # index stale; A's next requests degrade to the exact path
    faults.install("serving.publish=corrupt@once")
    try:
        eng.publish("a", np.asarray(model._U), np.asarray(model._V))
    finally:
        faults.clear()
    for uid in (0, 1, 2):
        # A's queue may still be draining the spike backlog; backing
        # off on ITS typed shed is exactly the client contract
        for _ in range(500):
            try:
                eng.recommend("a", uid, timeout=10.0)
                break
            except TenantOverloaded:
                time.sleep(0.01)

    # 3. poison A's live stream (quarantined, attributed to A) plus a
    # few clean events so A's pipeline demonstrably still publishes
    updater = eng.tenant("a").updater
    rngA = np.random.default_rng(c["seed"] + 3)
    user_ids = np.asarray(model._user_map.ids)
    item_ids = np.asarray(model._item_map.ids)
    for _ in range(c["poison_events"]):
        updater.submit(int(rngA.choice(user_ids)),
                       int(rngA.choice(item_ids)), float("nan"))
    for _ in range(c["good_events"]):
        updater.submit(int(rngA.choice(user_ids)),
                       int(rngA.choice(item_ids)),
                       float(rngA.uniform(0.5, 5.0)))

    # 4. guardrails=recover re-fit for A with a mid-train corrupt: the
    # sentinel trips, rolls back, and the recovered factors publish
    # into A's seq-space
    u = rngA.integers(0, c["a_users"], c["a_nnz"])
    i = rngA.integers(0, c["a_items"], c["a_nnz"])
    r = rngA.uniform(0.5, 5.0, c["a_nnz"]).astype(np.float32)
    ucsr = build_csr_buckets(u, i, r, c["a_users"], min_width=4,
                             chunk_elems=1 << 12)
    icsr = build_csr_buckets(i, u, r, c["a_items"], min_width=4,
                             chunk_elems=1 << 12)
    faults.install("solve.gram=corrupt@nth=2")
    try:
        with guardrails.scoped("recover"):
            Ua2, Va2 = train(ucsr, icsr,
                             AlsConfig(rank=c["rank"], max_iter=4,
                                       reg_param=0.1, seed=c["seed"]))
    finally:
        faults.clear()
    eng.publish("a", np.asarray(Ua2), np.asarray(Va2))

    # drain: A's spike tickets resolve or expire, A's live queue
    # empties, B's driver finishes its query list
    for t in tickets:
        try:
            t.result(timeout=10.0)
        except Exception:   # noqa: BLE001 — A's outcomes judged via obs
            pass
    deadline = time.perf_counter() + 30.0
    while updater.queue_depth and time.perf_counter() < deadline:
        time.sleep(0.02)
    driver.join(60.0)
    ctx.state.update(b_results=b_results)
    ctx.facts.update(a_spike_shed=spike_shed,
                     b_hard_failures=len(b_errors))


def _ti_churn(ctx):
    """Tenant churn under load: register/remove a short-lived tenant C
    through the live front door while B keeps serving.  The registry's
    publish-before-visible discipline is watched from a snapshot
    thread — no snapshot may ever expose a tenant without a published
    generation — and C must be servable the instant it IS visible."""
    from tpu_als.tenancy import TenantSpec

    c, s = ctx.config, ctx.state
    eng = s["eng"]
    rng = np.random.default_rng(c["seed"] + 7)
    Uc = rng.normal(size=(16, c["rank"])).astype(np.float32)
    Vc = rng.normal(size=(24, c["rank"])).astype(np.float32)
    unpublished, stop = [], threading.Event()

    def snapshotter():
        while not stop.is_set():
            for t in eng.registry.tenants():
                if t.engine.published_seq < 1:
                    unpublished.append(t.name)

    watcher = threading.Thread(target=snapshotter,
                               name="scenario-churn-watch", daemon=True)
    watcher.start()
    b_errors = 0
    try:
        for _ in range(c["churn_cycles"]):
            eng.add_tenant(TenantSpec(name="c", k=c["k"]), Uc, Vc)
            # servable the instant it is visible: its FIRST generation
            # was published before the registry ever listed it
            eng.recommend("c", 0, timeout=10.0)
            for uid in s["uids"][:3]:
                try:
                    eng.recommend("b", int(uid), timeout=10.0)
                except Exception:   # noqa: BLE001 — the judged bucket
                    b_errors += 1
            eng.remove_tenant("c")
    finally:
        stop.set()
        watcher.join(5.0)
    ctx.facts.update(churn_unpublished_snapshots=len(unpublished),
                     churn_b_errors=b_errors,
                     churn_final_tenants=len(eng.registry))


def _ti_judge(ctx):
    """The isolation verdict, from B's answers and the labeled trail:
    B bitwise vs solo, B's tail and shed in budget, A's storm evidence
    attributed to A."""
    from tpu_als import obs

    s, base = ctx.state, ctx.state["base"]
    solo, multi = s["solo_results"], s["b_results"]
    ok = len(solo) == len(multi)
    for (ss, si), (ms, mi) in zip(solo, multi):
        ok = ok and bool(np.array_equal(ss, ms)
                         and np.array_equal(si, mi))
    ctx.facts["b_topk_bitwise"] = ok
    p99 = obs.histogram_quantile("serving.e2e_seconds", 0.99,
                                 tenant="b")
    ctx.facts["b_p99_ms"] = (1e3 * float(p99)
                             if p99 == p99 else float("inf"))
    ctx.facts["b_shed"] = int(
        obs.counter_value("serving.shed", tenant="b") - base["b_shed"])
    ctx.facts["a_shed"] = int(
        obs.counter_value("serving.shed", tenant="a") - base["a_shed"])
    ctx.facts["a_fallback_exact"] = int(
        obs.counter_value("serving.fallback_exact", tenant="a")
        - base["a_exact"])
    events = obs.default_registry()._events
    ctx.facts["a_quarantine_attributed"] = bool(any(
        e.get("type") == "ingest_quarantined" and e.get("tenant") == "a"
        for e in events))
    ctx.facts["a_live_published"] = bool(any(
        e.get("type") == "live_update" and e.get("tenant") == "a"
        for e in events))


def _tenant_isolation():
    return ScenarioSpec(
        name="tenant-isolation",
        doc="the multi-tenant fault matrix: a torn publish, a poisoned "
            "live stream, a guardrail-rollback re-fit and a 10× spike "
            "all land on tenant A while tenant B serves its seeded "
            "queries — B's top-k stays BITWISE equal to its solo run, "
            "its p99/shed hold the SLO, and every piece of A's storm is "
            "attributed to A in the labeled obs trail (docs/tenancy.md).",
        defaults=dict(seed=21, users=64, items=96, rank=8, k=5,
                      n_queries=40, b_qps=80.0, b_slo_ms=500.0,
                      a_users=48, a_items=36, a_nnz=600,
                      a_max_queue=8, spike_submits=64,
                      poison_events=3, good_events=8, churn_cycles=5),
        phases=(
            Phase("solo-baseline", _ti_solo,
                  "tenant B alone: the bitwise reference answers"),
            Phase("multi-tenant-start", _ti_start,
                  "register A (full live stack, small queue) and B "
                  "(the solo factors) behind one front door"),
            Phase("fault-storm", _ti_storm,
                  "spike + torn publish + poison + rollback, all on A, "
                  "under B's query load; drain before judging"),
            Phase("tenant-churn", _ti_churn,
                  "register/remove tenant C while B serves: no "
                  "snapshot ever exposes an unpublished tenant"),
            Phase("judge", _ti_judge,
                  "B bitwise + SLO, A's evidence from the labeled "
                  "trail"),
        ),
        assertions=(
            Assertion("b_topk_bitwise", "fact", fact="b_topk_bitwise",
                      op="==", value=True,
                      doc="B's answers under A's storm == B's solo "
                          "answers, bit for bit"),
            Assertion("b_p99_under_slo", "fact", fact="b_p99_ms",
                      op="<=", value="$b_slo_ms"),
            Assertion("b_zero_shed", "fact", fact="b_shed",
                      op="==", value=0,
                      doc="A's overload never consumed B's queue "
                          "budget"),
            Assertion("b_no_hard_failures", "fact",
                      fact="b_hard_failures", op="==", value=0),
            Assertion("a_spike_shed", "fact", fact="a_spike_shed",
                      op=">=", value=1,
                      doc="the spike DID overflow A's small queue "
                          "(typed TenantOverloaded naming A)"),
            Assertion("a_degraded_exact", "fact",
                      fact="a_fallback_exact", op=">=", value=1,
                      doc="A's torn publish degraded A to the exact "
                          "path"),
            Assertion("a_quarantine_attributed", "fact",
                      fact="a_quarantine_attributed", op="==",
                      value=True,
                      doc="the poison's quarantine event carries "
                          "tenant=a"),
            Assertion("a_live_recovered", "fact",
                      fact="a_live_published", op="==", value=True,
                      doc="A's live pipeline still published after the "
                          "poison"),
            Assertion("churn_publish_before_visible", "fact",
                      fact="churn_unpublished_snapshots", op="==",
                      value=0,
                      doc="no registry snapshot during churn exposed a "
                          "tenant without a published generation"),
            Assertion("churn_b_undisturbed", "fact",
                      fact="churn_b_errors", op="==", value=0,
                      doc="B served through every register/remove "
                          "cycle of C"),
            Assertion("churn_no_leak", "fact",
                      fact="churn_final_tenants", op="==", value=2,
                      doc="every churned C was fully torn down"),
            Assertion("quarantine_event", "event",
                      event="ingest_quarantined", op=">=", value=1),
            Assertion("sentinel_tripped", "event",
                      event="guardrail_tripped", op=">=", value=1),
            Assertion("rolled_back", "event", event="train_rollback",
                      op=">=", value=1),
        ),
    )


# ---------------------------------------------------------------------------
# device-loss (elastic mesh training: loss -> reform -> resume, bitwise)


def _dl_env(c):
    """The forced-multi-device CPU environment every phase's CLI child
    runs under (the elastic protocol needs a real mesh to shrink)."""
    return {
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": ("--xla_force_host_platform_device_count="
                      f"{c['host_devices']}"),
    }


def _dl_train_args(c):
    return ["train", "--data", c["data"], "--rank", str(c["rank"]),
            "--reg-param", str(c["reg"]), "--seed", str(c["seed"])]


def _dl_elastic(ctx):
    import json

    c = ctx.config
    ckdir = os.path.join(ctx.workdir, "ck")
    out = os.path.join(ctx.workdir, "elastic_model")
    obsdir = os.path.join(ctx.workdir, "elastic_obs")
    env = dict(_dl_env(c))
    # deterministic loss: the nth traversal of the detector's fault
    # point kills the victim device (corrupt mode = a dead peer the
    # health probe confirms)
    env["TPU_ALS_FAULT_SPEC"] = \
        f"mesh.device_lost=corrupt@nth={c['lose_at']}"
    p = _cli_subprocess(
        _dl_train_args(c)
        + ["--devices", str(c["devices"]), "--elastic",
           "--max-iter", str(c["iters"]),
           "--checkpoint-dir", ckdir, "--checkpoint-interval", "1",
           "--output", out, "--obs-dir", obsdir],
        env_extra=env)
    ctx.facts["elastic_exit_code"] = p.returncode
    ctx.state["elastic_stderr"] = p.stderr
    by = {}
    epath = os.path.join(obsdir, "events.jsonl")
    if os.path.isfile(epath):
        with open(epath) as f:
            for line in f:
                e = json.loads(line)
                by.setdefault(e["type"], []).append(e)
    # the recovery tree must be re-derivable from events.jsonl alone
    ctx.facts["device_lost_events"] = len(by.get("device_lost", ()))
    ctx.facts["mesh_reformed_events"] = len(by.get("mesh_reformed", ()))
    ctx.facts["elastic_resume_events"] = len(
        by.get("elastic_resume", ()))
    res = (by.get("elastic_resume") or [{}])[0]
    ctx.facts["resume_from_checkpoint"] = res.get("source") == "checkpoint"
    ctx.state["resume_iteration"] = int(res.get("iteration") or 0)


def _dl_reference(ctx):
    """The recovery's ground truth, built WITHOUT any fault: the same
    fit stopped at the elastic run's resume iteration reproduces the
    checkpoint it recovered from (ALS iterations are max_iter-
    independent), then a FRESH fit on the shrunk mesh resumes from it."""
    c = ctx.config
    env = _dl_env(c)
    refck = os.path.join(ctx.workdir, "refck")
    out = os.path.join(ctx.workdir, "reference_model")
    it = ctx.state["resume_iteration"]
    survivors = c["devices"] - 1   # corrupt mode kills ONE device
    args = _dl_train_args(c)
    p = _cli_subprocess(
        args + ["--devices", str(c["devices"]), "--max-iter", str(it),
                "--checkpoint-dir", refck, "--checkpoint-interval", "1"],
        env_extra=env)
    ctx.facts["reference_prefix_exit"] = p.returncode
    p = _cli_subprocess(
        args + ["--devices", str(survivors),
                "--max-iter", str(c["iters"]),
                "--resume", os.path.join(refck, "als_checkpoint"),
                "--output", out],
        env_extra=env)
    ctx.facts["reference_exit_code"] = p.returncode
    ctx.state["reference_stderr"] = p.stderr


def _dl_judge(ctx):
    a = os.path.join(ctx.workdir, "elastic_model")
    b = os.path.join(ctx.workdir, "reference_model")
    eq = True
    for side in ("user_factors.npz", "item_factors.npz"):
        pa, pb = os.path.join(a, side), os.path.join(b, side)
        if not (os.path.isfile(pa) and os.path.isfile(pb)):
            eq = False
            break
        fa, fb = np.load(pa), np.load(pb)
        eq = (eq and np.array_equal(fa["factors"], fb["factors"])
              and np.array_equal(fa["ids"], fb["ids"]))
    ctx.facts["factors_bitwise_equal"] = bool(eq)


def _device_loss():
    return ScenarioSpec(
        name="device-loss",
        doc="elastic mesh training: a device dies mid-fit (injected "
            "mesh.device_lost), the health probe confirms a dead peer, "
            "the ring re-forms on the surviving mesh and training "
            "resumes from the last atomic checkpoint; the run completes "
            "and the final factors are BITWISE equal to a fresh "
            "shrunk-mesh fit resumed from the same checkpoint.",
        defaults=dict(data="synthetic:80x40x1500", rank=4, iters=5,
                      reg=0.05, seed=7, devices=4, host_devices=8,
                      lose_at=3),
        phases=(
            Phase("elastic-train", _dl_elastic,
                  "device dies at iteration $lose_at; the fit recovers "
                  "and completes"),
            Phase("reference", _dl_reference,
                  "fault-free shrunk-mesh fit resumed from the same "
                  "checkpoint"),
            Phase("judge", _dl_judge,
                  "bitwise-compare the two models' factor tables"),
        ),
        assertions=(
            Assertion("elastic_exit_0", "fact",
                      fact="elastic_exit_code", op="==", value=0,
                      doc="device loss is a rescheduling event, not a "
                          "crash"),
            Assertion("one_device_lost_event", "fact",
                      fact="device_lost_events", op="==", value=1),
            Assertion("one_mesh_reformed_event", "fact",
                      fact="mesh_reformed_events", op="==", value=1),
            Assertion("one_elastic_resume_event", "fact",
                      fact="elastic_resume_events", op="==", value=1),
            Assertion("resumed_from_checkpoint", "fact",
                      fact="resume_from_checkpoint", op="==", value=True),
            Assertion("reference_exit_0", "fact",
                      fact="reference_exit_code", op="==", value=0),
            Assertion("factors_bitwise_equal", "fact",
                      fact="factors_bitwise_equal", op="==", value=True,
                      doc="recovery is restart-from-factors of a "
                          "deterministic iteration — anything weaker "
                          "than array_equal would hide divergence"),
        ),
    )


# ---------------------------------------------------------------------------
# production-week


def _pw_soak(ctx):
    from tpu_als import obs
    from tpu_als.soak.orchestrator import run_soak
    from tpu_als.soak.traffic import TrafficConfig

    c = ctx.config
    cfg = TrafficConfig(seed=c["seed"], windows=c["windows"],
                        window_s=c["window_s"], base_qps=c["base_qps"],
                        update_qps=c["update_qps"])
    reg = obs.default_registry()
    ev0 = len(reg._events)
    res = run_soak(cfg, rank=c["rank"], refit_every=c["refit_every"],
                   subprocesses=bool(c["subprocesses"]),
                   workdir=os.path.join(ctx.workdir, "soak"),
                   judge_config={"slo_ms": c["slo_ms"],
                                 "freshness_slo_ms":
                                     c["freshness_slo_ms"]})
    # the exact event slice the soak produced — what the judge phase
    # dumps and re-derives the verdict from
    ctx.state["events"] = [dict(e) for e in reg._events[ev0:]]
    ctx.state["result"] = res
    ctx.facts["soak_passed"] = res["passed"]
    ctx.facts["windows_complete"] = res["windows"] == c["windows"]
    ctx.facts["scheduled_injections"] = res["injections"]
    ctx.facts["all_injections_recovered"] = (
        res["injections"] > 0
        and res["recoveries"] == res["injections"])
    ctx.facts["victim_free_errors"] = next(
        chk["observed"] for chk in res["checks"]
        if chk["check"] == "victim_free_errors")
    ctx.facts["answered"] = res["answered"]


def _pw_rederive(ctx):
    """The re-derivability pin, in-scenario: dump the soak's event
    slice to a jsonl file and have the STANDALONE stdlib judge
    (``tpu_als/soak/verdict.py`` run as a plain-python child, no
    tpu_als import, no jax) reproduce the identical verdict."""
    import json

    epath = os.path.join(ctx.workdir, "events.jsonl")
    with open(epath, "w") as f:
        for e in ctx.state["events"]:
            f.write(json.dumps(e) + "\n")
    vpath = os.path.join(_REPO, "tpu_als", "soak", "verdict.py")
    c = ctx.config
    p = subprocess.run(
        [sys.executable, vpath, epath, "--json",
         "--slo-ms", str(c["slo_ms"]),
         "--freshness-slo-ms", str(c["freshness_slo_ms"])],
        capture_output=True, text=True)
    ctx.facts["rederive_exit"] = p.returncode
    rederived = json.loads(p.stdout) if p.stdout.strip() else {}
    res = ctx.state["result"]
    ctx.facts["rederived_verdict_matches"] = (
        rederived.get("passed") == res["passed"]
        and rederived.get("checks") == res["checks"]
        and rederived.get("survived_minutes") == res["survived_minutes"])


def _production_week():
    return ScenarioSpec(
        name="production-week",
        doc="the soak subsystem end-to-end at compressed timescale: "
            "seeded zipfian/diurnal traffic drives two tenants' serve "
            "+ live fold-in + periodic refit while the default chaos "
            "schedule lands every injection (torn publish, poisoned "
            "refit, solver rollback, tenant churn, preemption, device "
            "loss); the SLO verdict must pass, and a standalone "
            "stdlib verdict.py child must re-derive the IDENTICAL "
            "verdict from the dumped events alone.",
        # latency bounds are the COMPRESSED-timescale tier-1 ones: the
        # CI box is often one shared core and the chaos children (CLI
        # preempt/device-loss trains, refits) compete with the serve
        # pool for it, so p99s run 2-3x what an idle box shows.  The
        # structural checks (recovery, fairness, shed, victim-free
        # errors) keep the verdict's teeth; `tpu_als soak` defaults to
        # the tighter production bounds (soak/verdict.py DEFAULTS).
        defaults=dict(seed=17, windows=8, window_s=1.5, base_qps=25.0,
                      update_qps=12.0, rank=8, refit_every=3,
                      subprocesses=True, slo_ms=2500.0,
                      freshness_slo_ms=10000.0),
        phases=(
            Phase("soak", _pw_soak,
                  "$windows windows of traffic under the full chaos "
                  "schedule"),
            Phase("judge", _pw_rederive,
                  "stdlib verdict.py child re-derives the verdict from "
                  "events alone"),
        ),
        assertions=(
            Assertion("soak_passed", "fact", fact="soak_passed",
                      op="==", value=True,
                      doc="every SLO check green: serve p99, freshness "
                          "p99, fairness, shed rate, zero victim-free "
                          "errors, all injections observed+recovered"),
            Assertion("windows_complete", "fact",
                      fact="windows_complete", op="==", value=True),
            Assertion("all_injections_recovered", "fact",
                      fact="all_injections_recovered", op="==",
                      value=True,
                      doc="every scheduled injection fired AND left "
                          "recovery evidence in the trail"),
            Assertion("victim_free_errors_zero", "fact",
                      fact="victim_free_errors", op="==", value=0),
            Assertion("rederive_exit_0", "fact", fact="rederive_exit",
                      op="==", value=0,
                      doc="the standalone judge exits 0 = verdict "
                          "passes offline too"),
            Assertion("rederived_verdict_matches", "fact",
                      fact="rederived_verdict_matches", op="==",
                      value=True,
                      doc="byte-identical checks: the verdict is a "
                          "pure function of the trail"),
        ),
    )


# ---------------------------------------------------------------------------
# registry

_BUILDERS = (
    _traffic_spike,
    _preempt_under_serve,
    _torn_publish,
    _cold_start,
    _preempt_resume,
    _flight_recorder,
    _solver_divergence,
    _poisoned_stream,
    _continuous_freshness,
    _tenant_isolation,
    _device_loss,
    _production_week,
)

SCENARIOS = {s.name: s for s in (b() for b in _BUILDERS)}


def names():
    return tuple(SCENARIOS)


def get_scenario(name):
    """The spec for ``name``; raises the typed :class:`UnknownScenario`
    (listing what IS available) on a miss."""
    from tpu_als.scenario.spec import UnknownScenario

    try:
        return SCENARIOS[name]
    except KeyError:
        raise UnknownScenario(name, names()) from None
