"""Production-day scenario harness: composed chaos with hard assertions.

The robustness primitives (fault injection, preemption, degraded
serving, fold-in, checkpoint resume) are each proven in isolation;
this package composes them into named, scripted end-to-end scenarios —
``tpu_als scenario run <name>`` — whose pass/fail verdicts are
evaluated from the obs metrics/events the run emits.  See
docs/scenarios.md.
"""

from tpu_als.scenario.library import SCENARIOS, get_scenario, names
from tpu_als.scenario.runner import bank_result, render_result, run_scenario
from tpu_als.scenario.spec import (
    Assertion,
    Phase,
    PhaseFailed,
    RunContext,
    ScenarioError,
    ScenarioFailed,
    ScenarioSpec,
    UnknownScenario,
)

__all__ = [
    "Assertion",
    "Phase",
    "PhaseFailed",
    "RunContext",
    "SCENARIOS",
    "ScenarioError",
    "ScenarioFailed",
    "ScenarioSpec",
    "UnknownScenario",
    "bank_result",
    "get_scenario",
    "names",
    "render_result",
    "run_scenario",
]
