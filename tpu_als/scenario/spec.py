"""Declarative scenario specs: phases, fault arming, assertions.

A scenario is a scripted "production day" slice — train, serve, stream,
and chaos composed into one runnable unit with HARD assertions.  The
pieces it composes all exist elsewhere (``resilience/faults.py`` specs,
``resilience/preempt.py``, ``serving/engine.py``, ``stream/microbatch.
py``, checkpoint resume); what this module adds is the *contract*: a
named spec that says which phases run, which fault rules are armed for
the whole run, and which assertions — evaluated from the obs
metrics/events the run emitted — decide pass/fail.

The assertion vocabulary is deliberately small and data-driven (see
docs/scenarios.md for the full table):

==============  =============================================================
``quantile``    ``histogram_quantile(metric, q)`` compared against a bound
                (``scale_ms=True`` converts the seconds histogram to ms so
                the bound can be an SLO in milliseconds)
``counter``     the DELTA of a counter since the scenario started
``ratio``       delta(num) / sum(delta(d) for d in den) — shed rate etc.;
                an empty denominator evaluates as 0 (nothing attempted =
                nothing shed)
``event``       count of events of a type emitted since the scenario started
``fact``        a value a phase recorded into ``ctx.facts`` (exit codes,
                bitwise-equality booleans, measured freshness seconds)
==============  =============================================================

Bounds may be literals or ``"$key"`` references into the scenario's
config (so ``tpu_als scenario run traffic-spike --slo-ms 80`` rebinds
the assertion without editing the spec).  Operators: ``<= >= == < > !=``.

Deliberately jax-free: specs and their evaluation logic import nothing
heavy, so ``scenario list`` and the CLI's error paths stay instant.
"""

from __future__ import annotations

import operator
import time
from dataclasses import dataclass, field

OPS = {
    "<=": operator.le,
    ">=": operator.ge,
    "==": operator.eq,
    "!=": operator.ne,
    "<": operator.lt,
    ">": operator.gt,
}

ASSERTION_KINDS = ("quantile", "counter", "ratio", "event", "fact")


class ScenarioError(RuntimeError):
    """Base class for scenario-harness failures."""


class UnknownScenario(ScenarioError):
    """``run``/``get_scenario`` was asked for a name nobody registered.

    Carries ``available`` so every surface (CLI, smoke scripts, tests)
    can list what IS runnable instead of a bare KeyError."""

    def __init__(self, name, available):
        self.name = name
        self.available = tuple(available)
        super().__init__(
            f"unknown scenario {name!r} (available: "
            f"{', '.join(self.available)})")


class PhaseFailed(ScenarioError):
    """A phase body raised — the scenario cannot reach its assertions.
    Distinct from assertion failure: this is harness breakage, not a
    judged robustness property."""

    def __init__(self, scenario, phase, error):
        self.scenario = scenario
        self.phase = phase
        self.error = error
        super().__init__(
            f"scenario {scenario!r} phase {phase!r} failed: "
            f"{type(error).__name__}: {error}")


class ScenarioFailed(ScenarioError):
    """One or more assertions did not hold; ``failed`` lists them."""

    def __init__(self, scenario, failed):
        self.scenario = scenario
        self.failed = list(failed)
        names = ", ".join(a["check"] for a in self.failed)
        super().__init__(
            f"scenario {scenario!r} failed {len(self.failed)} "
            f"assertion(s): {names}")


@dataclass(frozen=True)
class Phase:
    """One named step of a scenario.  ``run`` receives the RunContext;
    anything it must hand later phases goes in ``ctx.state`` (arrays,
    engines), anything an assertion judges goes in ``ctx.facts``
    (JSON-serializable scalars only).

    ``fault_spec`` arms a PHASE-scoped chaos window: the runner pushes
    it (``faults.push_spec``, overlaying the scenario-level spec) just
    before ``run`` and pops it in a ``finally`` — so a chaos window can
    re-arm mid-scenario without leaking rules into later phases or the
    enclosing process."""

    name: str
    run: object          # callable(ctx) -> None
    doc: str = ""
    fault_spec: str = None


@dataclass(frozen=True)
class Assertion:
    """One declarative check, evaluated after every phase has run.

    ``kind`` selects the evaluator; the remaining fields parameterize
    it (see the module docstring's vocabulary table).  ``value`` is the
    bound — a literal, or a ``"$key"`` reference into the run config.
    """

    check: str                 # stable name, reported in scenario_assert
    kind: str                  # one of ASSERTION_KINDS
    op: str = "<="
    value: object = None       # bound (literal or "$config_key")
    metric: str = None         # quantile/counter: metric name
    q: float = None            # quantile: which quantile
    scale_ms: bool = False     # quantile: seconds histogram vs ms bound
    num: str = None            # ratio: numerator counter
    den: tuple = ()            # ratio: denominator counters (summed)
    event: str = None          # event: event type
    fact: str = None           # fact: ctx.facts key
    doc: str = ""

    def __post_init__(self):
        if self.kind not in ASSERTION_KINDS:
            raise ValueError(
                f"assertion {self.check!r}: unknown kind {self.kind!r} "
                f"(known: {ASSERTION_KINDS})")
        if self.op not in OPS:
            raise ValueError(
                f"assertion {self.check!r}: unknown op {self.op!r} "
                f"(known: {tuple(OPS)})")


@dataclass(frozen=True)
class ScenarioSpec:
    """A complete scenario: identity + chaos arming + phases + judgments.

    ``fault_spec`` is a ``TPU_ALS_FAULT_SPEC`` grammar string the runner
    pushes before phase 1 and pops after the last phase (phases may
    push their own overlays — see :class:`Phase`) — the scenario's
    whole chaos schedule is visible here, declaratively, not buried in
    phase bodies.  ``defaults`` seed the run config; CLI
    flags / ``run_scenario(config=...)`` override per key.
    """

    name: str
    doc: str
    phases: tuple          # tuple[Phase, ...]
    assertions: tuple      # tuple[Assertion, ...]
    fault_spec: str = None
    defaults: dict = field(default_factory=dict)


class RunContext:
    """Everything a phase can see: config, a scratch dir, the shared
    facts/state dicts, and a LIFO cleanup stack (engines started in one
    phase are stopped by the runner even when a later phase fails)."""

    def __init__(self, spec, config, workdir, registry):
        self.spec = spec
        self.config = config
        self.workdir = workdir
        self.registry = registry
        self.facts = {}       # JSON scalars: what assertions judge
        self.state = {}       # arrays/objects handed between phases
        self._cleanups = []

    def defer(self, fn):
        """Register cleanup (engine.stop, thread joins) to run LIFO
        after the last phase, failures included."""
        self._cleanups.append(fn)

    def run_cleanups(self):
        errors = []
        while self._cleanups:
            fn = self._cleanups.pop()
            try:
                fn()
            except Exception as e:   # noqa: BLE001 — best-effort teardown
                errors.append(e)
        return errors


def resolve_bound(value, config):
    """A ``"$key"`` bound reads the run config; literals pass through."""
    if isinstance(value, str) and value.startswith("$"):
        key = value[1:]
        if key not in config:
            raise ScenarioError(
                f"assertion bound {value!r} references a config key "
                f"that is not set (have: {sorted(config)})")
        return config[key]
    return value


def evaluate_assertion(a, ctx, baseline_counters, events_start):
    """Evaluate one assertion against the registry state accumulated
    since the scenario started.  Returns a JSON-ready record:
    ``{"check", "kind", "ok", "observed", "expected", "op"}``.

    Counters/events are judged as deltas from the scenario-start
    baseline so a scenario composes with an already-instrumented
    process (the CLI run dir, a test that served traffic earlier).
    """
    reg = ctx.registry
    bound = resolve_bound(a.value, ctx.config)
    observed = None
    ok = False
    try:
        if a.kind == "quantile":
            observed = reg.histogram_quantile(a.metric, a.q)
            if a.scale_ms:
                observed = observed * 1e3
        elif a.kind == "counter":
            observed = (reg.counter_value(a.metric)
                        - baseline_counters.get(a.metric, 0))
        elif a.kind == "ratio":
            num = (reg.counter_value(a.num)
                   - baseline_counters.get(a.num, 0))
            den = sum(reg.counter_value(d) - baseline_counters.get(d, 0)
                      for d in a.den)
            observed = (num / den) if den else 0.0
        elif a.kind == "event":
            observed = sum(
                1 for e in reg._events[events_start:]
                if e.get("type") == a.event)
        elif a.kind == "fact":
            if a.fact not in ctx.facts:
                return {"check": a.check, "kind": a.kind, "ok": False,
                        "observed": None, "expected": bound, "op": a.op,
                        "error": f"fact {a.fact!r} was never recorded"}
            observed = ctx.facts[a.fact]
        ok = bool(OPS[a.op](observed, bound))
    except ScenarioError:
        raise
    except Exception as e:   # noqa: BLE001 — a broken check must FAIL, loudly
        return {"check": a.check, "kind": a.kind, "ok": False,
                "observed": observed, "expected": bound, "op": a.op,
                "error": f"{type(e).__name__}: {e}"}
    if isinstance(observed, float):
        observed = round(observed, 6)
    return {"check": a.check, "kind": a.kind, "ok": ok,
            "observed": observed, "expected": bound, "op": a.op}


def now():
    return time.perf_counter()
